# tpu-slo-toolkit build/test/gate entry points.
# Role parity with the reference Makefile (build/test/schema-validate/
# correlation-gate/m5 targets), re-keyed to the Python+C++ toolchain.

PY ?= python
ARTIFACTS ?= artifacts

.PHONY: all test test-fast native ebpf lint lint-changed \
	racecheck-smoke jitcheck-smoke schema-validate \
	correlation-gate fault-smoke replay-smoke ebpf-smoke bench \
	bench-smoke bench-columnar-smoke bench-columnar-full \
	chaos-smoke chaos-demo chaos-telemetry-smoke \
	chaos-telemetry-sweep crash-smoke crash-sweep \
	live-chaos-smoke live-chaos-sweep obs-smoke \
	burn-smoke burn-sweep fleet-smoke fleet-sweep \
	federation-smoke federation-sweep \
	global-smoke global-sweep \
	peer-smoke peer-sweep \
	remediation-smoke remediation-sweep \
	frontdoor-smoke frontdoor-bench \
	router-smoke router-bench \
	deviceplane-smoke deviceplane-sweep \
	profiler-smoke profiler-sweep \
	metrics-drift m5-candidate m5-gate helm-lint dashboards clean

all: native test

# ---- build ------------------------------------------------------------

native:
	$(MAKE) -C native

ebpf:
	./ebpf/gen.sh

# Frontend verification of every probe against -target bpf via the
# libclang wheel (works without a clang driver; see tools/ docstring).
ebpf-check:
	$(PY) tools/ebpf_frontend_check.py --write

# ---- test -------------------------------------------------------------

test: native
	$(PY) -m pytest tests/ -q

# Wall-time-gated full suite: the suite's cost compounded 145 -> 864
# -> 1330 -> 1435 s across rounds 1-4 (1 CPU); this budget stops the
# creep at the source.  Round 5's compile-sharing work (serving-matrix
# dedup in the dryrun test, memoized shard_map/jit builders, jitted
# test decode loops, shared compile keys across heavy tests) reversed
# the curve: measured clean 1294 s @ 715 tests (r4: 1435 s @ 699).
# Budget = measured + noise margin on a 1-CPU box (+~100 s for the
# late-round on-chip-session rehearsal guard); ratchets DOWN as more
# sharing lands (target: 1000).  Override for slow runners:
#   make test-timed TEST_BUDGET_S=1800
TEST_BUDGET_S ?= 1450
test-timed: native
	@start=$$(date +%s); \
	$(PY) -m pytest tests/ -q || exit 1; \
	end=$$(date +%s); wall=$$((end - start)); \
	echo "suite wall: $${wall}s (budget $(TEST_BUDGET_S)s)"; \
	if [ $$wall -gt $(TEST_BUDGET_S) ]; then \
		echo "FAIL: suite exceeded the wall-time budget — trim or"; \
		echo "share compiles before adding more (see CHANGELOG 0.5.0)"; \
		exit 1; \
	fi

# Sub-2-minute gate on one CPU: skips the compile-heavy model/serving
# modules (marked slow); full coverage stays in `make test`.
test-fast: native
	$(PY) -m pytest tests/ -q -x -m "not slow"

# tpulint v2 (tpuslo/analysis/): contract-aware semantic rules (schema/
# config/metrics drift, lock discipline, hot-path purity, exception
# accounting) + the TPL00x style tier.  Zero-delta against the committed
# .tpulint-baseline.json; see docs/static-analysis.md.
lint:
	$(PY) -m compileall -q tpuslo demo tests tools bench.py __graft_entry__.py
	$(PY) -m tpuslo.analysis

# Fast pre-commit loop: file-level rules scoped to git-changed .py files
# (repo-contract rules still run — they are cross-file by nature).
lint-changed:
	$(PY) -m tpuslo.analysis --changed

# Dynamic lock-order race detector over the threaded suites (delivery /
# runtime / obs) plus its own seeded AB/BA inversion test.  The conftest
# wraps threading.Lock/RLock when TPUSLO_RACECHECK=1 and fails the
# session on any cross-thread order inversion or sleep-under-lock.
# (Suite list: tpuslo/analysis/racecheck.py SMOKE_SUITES.)
racecheck-smoke:
	TPUSLO_RACECHECK=1 $(PY) -m tpuslo m5gate --racecheck-smoke

# Dynamic retrace/host-sync auditor over the serving lanes (speculative
# decode + its own planted-churn tests).  The conftest hooks jax
# compile events when TPUSLO_JITAUDIT=1; the serving loops self-declare
# their post-warmup steady sections, and the session fails if a
# steady-state decode loop ever triggers an XLA backend compile.
# (Suite list: tpuslo/analysis/jitaudit.py SMOKE_SUITES.)
jitcheck-smoke:
	TPUSLO_JITAUDIT=1 $(PY) -m tpuslo m5gate --jitcheck-smoke

# ---- gates (mirror the reference CI steps) ----------------------------

schema-validate:
	$(PY) -m tpuslo schemavalidate

correlation-gate:
	$(PY) -m tpuslo correlationeval --min-precision 0.90 --min-recall 0.85

fault-smoke:
	mkdir -p $(ARTIFACTS)/smoke
	$(PY) -m tpuslo faultinject --scenario dns_latency --count 5 \
		--output $(ARTIFACTS)/smoke/raw_samples.jsonl
	$(PY) -m tpuslo collector --input $(ARTIFACTS)/smoke/raw_samples.jsonl \
		--output jsonl --jsonl-path $(ARTIFACTS)/smoke/slo_events.jsonl
	@test -s $(ARTIFACTS)/smoke/slo_events.jsonl && echo "fault-smoke: OK"

replay-smoke:
	mkdir -p $(ARTIFACTS)/replay
	$(PY) -m tpuslo faultreplay --scenario tpu_mixed_multi --count 10 \
		--output $(ARTIFACTS)/replay/replay.jsonl
	$(PY) -m tpuslo attributor --input $(ARTIFACTS)/replay/replay.jsonl \
		--output $(ARTIFACTS)/replay/attributions.jsonl \
		--summary $(ARTIFACTS)/replay/summary.json \
		--confusion $(ARTIFACTS)/replay/confusion.csv
	@test -s $(ARTIFACTS)/replay/attributions.jsonl && echo "replay-smoke: OK"

ebpf-smoke:
	./scripts/ebpf-smoke.sh

# ---- benchmark + release gates ---------------------------------------

bench:
	$(PY) bench.py

# Seconds-scale spine check: bench_pipeline on a small sample count,
# asserting nonzero throughput and that the fast-path validator (not
# per-event jsonschema) is actually engaged.
bench-smoke:
	$(PY) -m pytest tests/test_bench_smoke.py -q

# Columnar spine smoke (ISSUE 8): row-vs-columnar parity at every
# stage plus result-shape checks on a toy batch — fast, runs in
# m5-gate.  The gate-scale run (columnar >= 1M events/s, matcher
# >= 10x the row path; bench.py hard-fails below the floors) is the
# slow-marked bench-columnar-full.
bench-columnar-smoke:
	$(PY) -m pytest tests/test_bench_columnar.py tests/test_columnar_parity.py \
		-q -m 'not slow'

bench-columnar-full:
	$(PY) -m pytest tests/test_bench_columnar.py -q

# Fault-injection suite: real agent loop vs a scripted flaky OTLP sink
# (refuse/5xx/4xx/hang), proving zero-loss spool+replay and breaker
# recovery.  chaos tests are also marked slow, so the tier-1
# `-m 'not slow'` lane never runs them implicitly.
chaos-smoke:
	$(PY) -m pytest tests/ -q -m chaos

# Source-side telemetry chaos (PR 2 broke the sink; this breaks the
# SOURCE): seeded low-intensity chaos sweep through the ingest gate —
# skew correction, dedup, quarantine, watermark — under the same
# `chaos` pytest marker (also slow, so tier-1 never runs it
# implicitly).  See docs/runbooks/telemetry-quality.md.
chaos-telemetry-smoke:
	$(PY) -m pytest tests/test_chaos_telemetry.py -q -m chaos

# Full chaos-sweep release gate: macro-F1 vs chaos intensity, ingest
# gate on vs off; fails unless degradation is graceful (moderate chaos
# within 5% of the clean baseline, gated strictly above ungated).
chaos-telemetry-sweep:
	mkdir -p $(ARTIFACTS)/chaos-telemetry
	$(PY) -m tpuslo m5gate --chaos-sweep \
		--summary-json $(ARTIFACTS)/chaos-telemetry/sweep.json \
		--summary-md $(ARTIFACTS)/chaos-telemetry/sweep.md

# Crash chaos (PR 2 broke the sink, PR 3 broke the source; this kills
# the AGENT): one seeded kill -9 / restart cycle proving no torn line
# replays, no cycle is lost, no webhook alert duplicates, and the
# restart resumes warm from the state snapshot.  Same chaos pytest
# marker (also slow, so tier-1 never runs it implicitly).
crash-smoke:
	$(PY) -m pytest tests/test_crash_runtime.py -q -m chaos

# Live deployment-plane chaos (ISSUE 17): the fast 2-process lane —
# a real agent shipping over a real livenet socket to a real cluster
# fleetagg, agent killed -9 mid-window, supervised restart resuming
# from the seq journal with zero lost/dup incidents and measured
# cadence coarsening.  Same chaos pytest marker (slow, never in
# tier-1 implicitly).
live-chaos-smoke:
	$(PY) -m pytest tests/test_live_procs.py -q -m chaos

# Full live deployment-plane release gate: the whole supervised tree
# (agent -> cluster -> region sockets + the front door), kill -9 of
# every role mid-window plus one socket partition; zero lost/dup
# incidents, warm resume, cadence coarsening at pressure >= 1, and a
# live demote_tenant flipping the admission order — minutes, not in
# the default m5-gate chain.
live-chaos-sweep:
	mkdir -p $(ARTIFACTS)/live-chaos
	$(PY) -m tpuslo m5gate --live-chaos-sweep \
		--live-chaos-root $(ARTIFACTS)/live-chaos \
		--summary-json $(ARTIFACTS)/live-chaos/sweep.json \
		--summary-md $(ARTIFACTS)/live-chaos/sweep.md

# Self-observability smoke: tracer span trees + tail sampling + OTLP
# trace payloads, the metrics HTTP server (/metrics //healthz //readyz),
# the agent --trace e2e path, and the metrics drift gate.
obs-smoke:
	$(PY) -m pytest tests/test_obs_tracer.py tests/test_metrics_server.py \
		tests/test_agent_trace.py -q
	$(PY) tools/metrics_drift_check.py

# Every AgentMetrics series must be referenced by a dashboard or a doc;
# orphans fail (see tools/metrics_drift_check.py).
metrics-drift:
	$(PY) tools/metrics_drift_check.py

# Error-budget / burn-rate engine smoke: window math, alert state
# machine, snapshot round trips, loadgen --slo-out offline replay, and
# the hot-path lint assertion (sloengine stays TPL120/121-clean).
burn-smoke:
	$(PY) -m pytest tests/test_sloengine.py tests/test_burn_sweep.py -q

# Full burn-scenario release gate: seeded traffic shapes (steady /
# fast-burn / slow-burn / latency regression / flapping /
# tenant-isolated / kill-restart) replayed through the engine;
# fails on any missed, spurious, late, or duplicated alert
# (see docs/runbooks/error-budget.md).
burn-sweep:
	mkdir -p $(ARTIFACTS)/burn
	$(PY) -m tpuslo m5gate --burn-sweep \
		--summary-json $(ARTIFACTS)/burn/sweep.json \
		--summary-md $(ARTIFACTS)/burn/sweep.md

# Auto-remediation smoke: policy matching (cooldown / rate-limit /
# budget edges), every action's apply/rollback round trip, verifier
# confirm/rollback/hysteresis, engine export/restore parity, ownership
# precedence vs the supervisor hold-down, and provenance completeness.
remediation-smoke:
	$(PY) -m pytest tests/test_remediation.py -q -m 'not slow'

# Full auto-remediation release gate: seeded fault scenarios through
# observe -> attribute -> remediate -> verify; fails on any action
# against a healthy/low-confidence target, a verify that neither
# confirms nor rolls back within the window budget, a storm that
# escapes the dampers, a duplicate action across the mid-sweep kill,
# or an action missing from the provenance chain
# (see docs/runbooks/auto-remediation.md).
remediation-sweep:
	mkdir -p $(ARTIFACTS)/remediation
	$(PY) -m tpuslo m5gate --remediation-sweep \
		--remediation-provenance-dir $(ARTIFACTS)/remediation \
		--summary-json $(ARTIFACTS)/remediation/sweep.json \
		--summary-md $(ARTIFACTS)/remediation/sweep.md

# Serving front-door smoke: per-slot stream parity vs the per-stream
# speculative engine, admission/preemption/shed edges, prefix-aware
# placement, burn-state demotion, and snapshot round trips — seconds,
# runs in m5-gate.
frontdoor-smoke:
	$(PY) -m pytest tests/test_frontdoor.py -q -m 'not slow'

# Full front-door release gate (slow): loadgen-driven bursty
# multi-tenant traffic through the FrontDoorEngine must beat the same
# streams served sequentially through the per-stream SpeculativeEngine
# by >= 2x on goodput AND tokens/s, with zero steady-state recompiles
# (jitaudit), host syncs/token under the serving ceiling, and
# burn-aware admission observable (see docs/runbooks/serving-slo.md).
frontdoor-bench:
	mkdir -p $(ARTIFACTS)/frontdoor
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} $(PY) -m tpuslo m5gate --frontdoor-bench \
		--summary-json $(ARTIFACTS)/frontdoor/bench.json \
		--summary-md $(ARTIFACTS)/frontdoor/bench.md

# Serving scale-out smoke: paged-vs-dense park/resume parity, router
# placement policy (bounded-load affinity, burn steering, p2c), the
# engine-kill drain/adopt path, loadgen prefix groups, and the
# front-door Prometheus bridge — seconds, runs in m5-gate.
router-smoke:
	$(PY) -m pytest tests/test_router.py -q -m 'not slow'

# Full serving scale-out release gate (slow): SLO-aware routing over
# N replicated paged-KV front doors in a virtual-time harness —
# aggregate goodput >= 0.8xN of one engine, bounded-load prefix
# affinity beats random placement on TTFT p99, zero steady-state
# recompiles per engine, and a mid-run engine kill loses zero
# requests (see docs/runbooks/serving-scaleout.md).
router-bench:
	mkdir -p $(ARTIFACTS)/router
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} $(PY) -m tpuslo m5gate --router-bench \
		--summary-json $(ARTIFACTS)/router/bench.json \
		--summary-md $(ARTIFACTS)/router/bench.md

# Device-plane smoke: ledger bucket-sum/tier parity over seeded
# synthetic-xprof traces, breakdown reason classes, roofline verdicts,
# dispatch-ledger + front-door tracing — seconds, runs in m5-gate.
deviceplane-smoke:
	$(PY) -m pytest tests/test_deviceplane.py -q -m 'not slow'

# Full device-plane release gate: the seeded synthetic-xprof lane
# through the per-launch ledger (buckets sum to total device time,
# substantive join >= 0.9, unexplained <= 0.1), roofline verdicts on
# every serving-path attribution, and the calibrated heldout suite
# with the preemption/noisy-neighbor domains at >= 0.96 macro-F1
# (see docs/runbooks/device-plane.md).
deviceplane-sweep:
	mkdir -p $(ARTIFACTS)/deviceplane
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} $(PY) -m tpuslo m5gate --deviceplane-sweep \
		--summary-json $(ARTIFACTS)/deviceplane/sweep.json \
		--summary-md $(ARTIFACTS)/deviceplane/sweep.md

# Continuous-profiler smoke: overhead governor (forced-slow degrade,
# headroom re-engage, eviction windows never dropped), per-window
# ledger parity vs one spliced full capture, probe-payload contracts,
# and state round trips — seconds, runs in m5-gate.
profiler-smoke:
	$(PY) -m pytest tests/test_profiler.py -q -m 'not slow'

# Full continuous-profiler release gate: seeded capture windows under
# the measured-overhead budget (EMA <= 3% of cycle budget), governor
# degrade/force/re-engage evidence, per-window substantive join
# >= 0.9 with the raw rate reported alongside, window/full-capture
# bucket parity, and the injected preemption window attributed to
# tpu_preemption (see docs/runbooks/continuous-profiling.md).
profiler-sweep:
	mkdir -p $(ARTIFACTS)/profiler
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} $(PY) -m tpuslo m5gate --profiler-sweep \
		--summary-json $(ARTIFACTS)/profiler/sweep.json \
		--summary-md $(ARTIFACTS)/profiler/sweep.md

# Fleet observability-plane smoke: wire contract round trips, hash-ring
# placement, rollup merge invariants (no cross-tenant/cross-domain),
# aggregator seq-dedup + failover absorb, and a small seeded simulator
# run — seconds, runs in m5-gate.
fleet-smoke:
	$(PY) -m pytest tests/test_fleet.py -q -m 'not slow'

# Full fleet-sweep release gate (slow): 1k simulated nodes over 4
# aggregator shards — aggregate columnar ingest >= 5M events/s,
# exactly one incident per injected fleet fault at the correct blast
# radius under chaos, and a mid-sweep aggregator kill with zero lost
# or duplicated incidents (see docs/runbooks/fleet-rollup.md).
fleet-sweep:
	mkdir -p $(ARTIFACTS)/fleet
	$(PY) -m tpuslo m5gate --fleet-sweep \
		--summary-json $(ARTIFACTS)/fleet/sweep.json \
		--summary-md $(ARTIFACTS)/fleet/sweep.md

# Federation-plane smoke: region wire envelope round trips,
# backpressure hysteresis + sampler invariants (fault evidence never
# sampled), online ring rebalancing under seeded churn, cross-cluster
# rollup identity, region failover, and the fleetagg/sloctl federation
# CLIs — seconds, runs in m5-gate.
federation-smoke:
	$(PY) -m pytest tests/test_federation.py -q -m 'not slow'

# Full federation-sweep release gate (slow): 10k simulated nodes over
# a two-level aggregator tree — aggregate ingest >= the 5M events/s
# single-level floor, exactly one region incident per injected fault
# (cross-cluster identity) under continuous node churn + rolling shard
# restarts, a mid-sweep region-aggregator kill with zero lost or
# duplicated incidents, and graceful degradation (counted by level,
# bounded staleness) under forced ingest saturation
# (see docs/runbooks/federation.md).
federation-sweep:
	mkdir -p $(ARTIFACTS)/federation
	$(PY) -m tpuslo m5gate --federation-sweep \
		--summary-json $(ARTIFACTS)/federation/sweep.json \
		--summary-md $(ARTIFACTS)/federation/sweep.md

# Global-tier smoke: gap-tolerant cursor, global wire round trips,
# cross-region rollup identity, partition-aware emission + registry
# merge, WAN link/proxy chaos, and the fleetagg/sloctl global CLIs —
# seconds, runs in m5-gate.
global-smoke:
	$(PY) -m pytest tests/test_global_tier.py -q -m 'not slow'

# Full global-tier release gate: the WAN-chaos lanes (cross-region
# identity under latency + one-way ack loss, the hour-dark rejoin
# with zero lost/dup pages and bounded replay, the split-brain
# registry-merge heal) plus the 100k-node (10 regions x 10k) ingest
# floor through the three-tier fold
# (see docs/runbooks/multi-region.md).
global-sweep:
	mkdir -p $(ARTIFACTS)/global
	$(PY) -m tpuslo m5gate --global-sweep \
		--summary-json $(ARTIFACTS)/global/sweep.json \
		--summary-md $(ARTIFACTS)/global/sweep.md

# Peer-mesh smoke: gossip lattice fold, bully election + epoch fence,
# commit-then-page outbox, deferred re-stamp, livemesh sockets and the
# fleetagg --peer CLIs — seconds, runs in m5-gate.
peer-smoke:
	$(PY) -m pytest tests/test_global_peer.py -q -m 'not slow'

# Full peer-mesh release gate: the symmetric-root chaos lanes (leader's
# whole peering domain dark mid-sweep -> bounded-round election, zero
# lost/dup pages; split-brain where BOTH sides elect healing by gossip
# alone; a deposed root returning from an hour dark fenced at its stale
# epoch) plus the 100k-node ingest floor
# (see docs/runbooks/multi-region.md).
peer-sweep:
	mkdir -p $(ARTIFACTS)/peer
	$(PY) -m tpuslo m5gate --peer-sweep \
		--summary-json $(ARTIFACTS)/peer/sweep.json \
		--summary-md $(ARTIFACTS)/peer/sweep.md

# Full crash-sweep release gate: seeds x kill points of SIGKILL/restart
# audits (see docs/evidence/crash-sweep.md + docs/runbooks/crash-recovery.md).
crash-sweep:
	mkdir -p $(ARTIFACTS)/crash
	$(PY) -m tpuslo m5gate --crash-sweep \
		--crash-root $(ARTIFACTS)/crash \
		--summary-json $(ARTIFACTS)/crash/sweep.json \
		--summary-md $(ARTIFACTS)/crash/sweep.md

# Watchable version of the same story: collector dies mid-run, the
# agent spools, the breaker trips, recovery replays the outage window
# (see the delivery[...] summary lines + docs/runbooks/degraded-delivery.md).
chaos-demo:
	mkdir -p $(ARTIFACTS)/chaos-spool
	$(PY) -m tpuslo agent --config config/chaos-demo.yaml \
		--scenario tpu_mixed --count 25 \
		--interval-s 0.1 --event-kind both \
		--chaos-sink 'ok:6,refuse:8,ok' \
		--spool-dir $(ARTIFACTS)/chaos-spool \
		--capability-mode tpu_full --metrics-port 0 \
		--max-overhead-pct 1000

# Build the m5 candidate tree: 7 scenarios x 3 reruns of benchmark
# bundles (reference Makefile m5-candidate-rebuild).
M5_SCENARIOS ?= dns_latency network_partition cpu_throttle ici_drop \
	hbm_pressure xla_recompile_storm tpu_mixed_multi
M5_RUNS ?= 1 2 3

m5-candidate:
	@for s in $(M5_SCENARIOS); do \
	  inj=$$s; [ $$s = tpu_mixed_multi ] && inj=tpu_mixed; \
	  for r in $(M5_RUNS); do \
	    out=$(ARTIFACTS)/m5/$$s/run$$r; mkdir -p $$out; \
	    $(PY) -m tpuslo faultinject --scenario $$inj --count 30 \
	        --start 2026-01-0$${r}T00:00:00Z \
	        --output $$out/raw_samples.jsonl || exit 1; \
	    $(PY) -m tpuslo benchgen --scenario $$s --count 30 \
	        --output-dir $$out --node bench-node-$$r || exit 1; \
	  done; \
	done
	@echo "m5-candidate: artifacts under $(ARTIFACTS)/m5"

# Release candidates fail on new lint findings, lock-order races,
# steady-state decode recompiles, burn-alert contract violations,
# row-vs-columnar divergence, a broken fleet plane, a federation tree
# that loses evidence under churn or saturation, a remediation loop
# that acts imprecisely, a serving front door that loses to
# per-stream serving, or a router tier that loses requests or
# scaling across an engine kill, before the statistical gates even
# run (ISSUEs 6 + 7 + 8 + 9 + 10 + 11 + 12 + 15 + 16 + 20).
m5-gate: lint racecheck-smoke jitcheck-smoke burn-smoke burn-sweep \
		bench-columnar-smoke fleet-smoke fleet-sweep \
		federation-smoke federation-sweep \
		global-smoke global-sweep \
		peer-smoke peer-sweep \
		remediation-smoke remediation-sweep \
		frontdoor-smoke frontdoor-bench \
		router-smoke router-bench \
		deviceplane-smoke deviceplane-sweep \
		profiler-smoke profiler-sweep \
		crash-smoke live-chaos-smoke
	$(PY) -m tpuslo m5gate --candidate-root $(ARTIFACTS)/m5 \
		--scenarios "$(shell echo $(M5_SCENARIOS) | tr ' ' ',')" \
		--summary-json $(ARTIFACTS)/m5/gate.json \
		--summary-md $(ARTIFACTS)/m5/gate.md

# ---- misc -------------------------------------------------------------

helm-lint:
	helm lint charts/tpu-slo-agent

dashboards:
	cd dashboards && $(PY) generate.py

clean:
	$(MAKE) -C native clean
	rm -rf $(ARTIFACTS) ebpf/build
