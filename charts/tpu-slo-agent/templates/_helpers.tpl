{{- define "tpu-slo-agent.name" -}}
{{- default .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "tpu-slo-agent.labels" -}}
app.kubernetes.io/name: {{ include "tpu-slo-agent.name" . }}
app.kubernetes.io/part-of: tpu-slo-toolkit
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version }}
{{- end -}}

{{- define "tpu-slo-agent.serviceAccountName" -}}
{{- if .Values.serviceAccount.create -}}
{{- default (include "tpu-slo-agent.name" .) .Values.serviceAccount.name -}}
{{- else -}}
{{- default "default" .Values.serviceAccount.name -}}
{{- end -}}
{{- end -}}
