#!/usr/bin/env bash
set -euo pipefail
kind delete cluster --name "${CLUSTER:-tpuslo}"
