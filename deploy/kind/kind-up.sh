#!/usr/bin/env bash
# Bring up the 3-node kind cluster and deploy the full stack in
# min-capability (synthetic) mode.
set -euo pipefail
cd "$(dirname "$0")"

CLUSTER="${CLUSTER:-tpuslo}"

if ! command -v kind >/dev/null; then
    echo "kind-up: kind not installed" >&2
    exit 2
fi

if ! kind get clusters | grep -qx "$CLUSTER"; then
    kind create cluster --name "$CLUSTER" --config kind-config.yaml
fi

kubectl apply -k ../k8s/min-capability/
kubectl apply -k ../observability/
echo "kind-up: cluster '$CLUSTER' ready; agent in min-capability mode"
