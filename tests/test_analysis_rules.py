"""tpulint v2 semantic rules: positive + negative fixtures per family.

Each rule family (schema drift, config drift, metrics drift, lock
discipline, hot-path purity, exception discipline, style tier) gets at
least one fixture that provokes the finding and one that stays clean.
Repo-contract rules are also run against the real tree (they must be
clean — the analyzer self-hosts) and against in-memory mutated sources
anchored to the real contracts, so the fixtures cannot drift from the
schemas they check.
"""

from __future__ import annotations

import ast
import textwrap
from pathlib import Path

from tpuslo.analysis import FileContext, RepoContext, run_analysis
from tpuslo.analysis.rules_contracts import (
    ColumnarDtypeDriftRule,
    ConfigDriftRule,
    FleetWireDriftRule,
    MetricsDriftRule,
    SchemaDriftRule,
)
from tpuslo.analysis.rules_except import ExceptionDisciplineRule
from tpuslo.analysis.rules_hotpath import HotPathPurityRule
from tpuslo.analysis.rules_locks import LockDisciplineRule
from tpuslo.analysis.rules_style import StyleRules

REPO = Path(__file__).resolve().parent.parent
TYPES_REL = "tpuslo/schema/types.py"
CFG_REL = "tpuslo/config/toolkitcfg.py"


def _ctx(rel: str, source: str) -> FileContext:
    return FileContext(REPO / rel, rel, textwrap.dedent(source))


def _mutated_repo(rel: str, transform) -> RepoContext:
    """RepoContext over the real repo with one file's source rewritten
    in memory — contract JSONs stay the committed ones."""
    source = (REPO / rel).read_text(encoding="utf-8")
    return RepoContext(REPO, [FileContext(REPO / rel, rel, transform(source))])


class TestStyleTier:
    def test_codes_fire_on_fixture(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            textwrap.dedent(
                """
                import os
                def f(x=[]):
                    return x == None
                try:
                    pass
                except:
                    pass
                """
            )
        )
        result = run_analysis(
            tmp_path, paths=["mod.py"], rules=[StyleRules()]
        )
        codes = {f.code for f in result.findings}
        assert {"TPL001", "TPL003", "TPL004", "TPL006"} <= codes

    def test_clean_module(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "import os\n\n\ndef f(x=None):\n    return x is None or os.name\n"
        )
        result = run_analysis(
            tmp_path, paths=["mod.py"], rules=[StyleRules()]
        )
        assert result.findings == []


class TestSchemaDrift:
    def test_real_tree_is_clean(self):
        repo = RepoContext(
            REPO,
            [
                FileContext(
                    REPO / TYPES_REL,
                    TYPES_REL,
                    (REPO / TYPES_REL).read_text(encoding="utf-8"),
                )
            ],
        )
        assert list(SchemaDriftRule().check_repo(repo)) == []

    def test_dropped_field_is_both_direction_drift(self):
        """Deleting ProbeEventV1.ts_unix_nano must flag the orphaned
        contract property (contract->dataclass direction)."""
        repo = _mutated_repo(
            TYPES_REL, lambda s: s.replace("    ts_unix_nano: int\n", "", 1)
        )
        findings = list(SchemaDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL101"
            and "'ts_unix_nano'" in f.message
            and "ProbeEventV1" in f.message
            for f in findings
        )

    def test_extra_field_is_dataclass_to_contract_drift(self):
        repo = _mutated_repo(
            TYPES_REL,
            lambda s: s.replace(
                "    unit: str\n",
                "    unit: str\n    totally_new_field: str = \"\"\n",
                1,
            ),
        )
        findings = list(SchemaDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL101" and "totally_new_field" in f.message
            for f in findings
        )

    def test_type_mismatch_detected(self):
        repo = _mutated_repo(
            TYPES_REL, lambda s: s.replace("    pid: int\n", "    pid: str\n", 1)
        )
        findings = list(SchemaDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL101" and "pid" in f.message and "incompatible"
            in f.message
            for f in findings
        )

    def test_conditional_required_emission_is_tpl102(self):
        """Re-introduce the pre-PR slo_impact drift: required by the
        contract, emitted only when set."""

        def transform(source: str) -> str:
            source = source.replace(
                "    slo_impact: SLOImpact\n",
                "    slo_impact: SLOImpact | None = None\n",
            )
            return source.replace(
                '            "slo_impact": self.slo_impact.to_dict(),\n'
                "        }\n",
                "        }\n"
                "        if self.slo_impact is not None:\n"
                '            out["slo_impact"] = self.slo_impact.to_dict()\n',
                1,
            )

        repo = _mutated_repo(TYPES_REL, transform)
        findings = list(SchemaDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL102" and "slo_impact" in f.message
            for f in findings
        )


COLUMNAR_REL = "tpuslo/columnar/schema.py"


def _columnar_repo(
    columnar_transform=None, types_transform=None
) -> RepoContext:
    """Both TPL103 anchors in context, one (or both) mutated in memory."""
    contexts = []
    for rel, transform in (
        (COLUMNAR_REL, columnar_transform),
        (TYPES_REL, types_transform),
    ):
        source = (REPO / rel).read_text(encoding="utf-8")
        if transform is not None:
            source = transform(source)
        contexts.append(FileContext(REPO / rel, rel, source))
    return RepoContext(REPO, contexts)


class TestColumnarDtypeDrift:
    def test_real_tree_is_clean(self):
        assert list(
            ColumnarDtypeDriftRule().check_repo(_columnar_repo())
        ) == []

    def test_new_dataclass_field_without_column_flagged(self):
        repo = _columnar_repo(
            types_transform=lambda s: s.replace(
                "    tid: int\n",
                '    tid: int\n    brand_new: str = ""\n',
                1,
            )
        )
        findings = list(ColumnarDtypeDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL103" and "brand_new" in f.message
            and "no entry" in f.message
            for f in findings
        )

    def test_stale_mapping_flagged(self):
        repo = _columnar_repo(
            types_transform=lambda s: s.replace(
                "    span_id: str = \"\"\n", "", 1
            )
        )
        findings = list(ColumnarDtypeDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL103" and "span_id" in f.message
            and "stale" in f.message
            for f in findings
        )

    def test_mapped_column_missing_from_dtype_flagged(self):
        repo = _columnar_repo(
            columnar_transform=lambda s: s.replace(
                '    ("span_id", "i4"),\n', "", 1
            )
        )
        findings = list(ColumnarDtypeDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL103" and "'span_id'" in f.message
            and "missing from _DTYPE_FIELDS" in f.message
            for f in findings
        )

    def test_unmapped_dtype_column_flagged(self):
        repo = _columnar_repo(
            columnar_transform=lambda s: s.replace(
                '    ("span_id", "i4"),\n',
                '    ("span_id", "i4"),\n    ("mystery_col", "i4"),\n',
                1,
            )
        )
        findings = list(ColumnarDtypeDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL103" and "mystery_col" in f.message
            and "unmapped" in f.message
            for f in findings
        )

    def test_non_literal_declarations_flagged(self):
        repo = _columnar_repo(
            columnar_transform=lambda s: s.replace(
                "_DTYPE_FIELDS: tuple[tuple[str, str], ...] = (",
                "_DTYPE_FIELDS: tuple[tuple[str, str], ...] = tuple(x for x in (",
                1,
            ).replace(
                '    ("tpu_module_name", "i4"),\n)',
                '    ("tpu_module_name", "i4"),\n))',
                1,
            )
        )
        findings = list(ColumnarDtypeDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL103" and "pure" in f.message for f in findings
        )


FLEET_WIRE_REL = "tpuslo/fleet/wire.py"


def _fleet_repo(
    wire_transform=None, columnar_transform=None, types_transform=None
) -> RepoContext:
    """All three TPL104 anchors in context, any mutated in memory."""
    contexts = []
    for rel, transform in (
        (FLEET_WIRE_REL, wire_transform),
        (COLUMNAR_REL, columnar_transform),
        (TYPES_REL, types_transform),
    ):
        source = (REPO / rel).read_text(encoding="utf-8")
        if transform is not None:
            source = transform(source)
        contexts.append(FileContext(REPO / rel, rel, source))
    return RepoContext(REPO, contexts)


class TestFleetWireDrift:
    def test_real_tree_is_clean(self):
        assert list(
            FleetWireDriftRule().check_repo(_fleet_repo())
        ) == []

    def test_dropped_wire_column_flagged(self):
        """Mutation test: remove one shipped column — the aggregator
        would silently reconstruct batches without span identity."""
        repo = _fleet_repo(
            wire_transform=lambda s: s.replace('    "span_id",\n', "", 1)
        )
        findings = list(FleetWireDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL104" and "'span_id'" in f.message
            and "missing from WIRE_EVENT_COLUMNS" in f.message
            for f in findings
        )

    def test_unknown_wire_column_flagged(self):
        repo = _fleet_repo(
            wire_transform=lambda s: s.replace(
                '    "span_id",\n',
                '    "span_id",\n    "mystery_wire_col",\n',
                1,
            )
        )
        findings = list(FleetWireDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL104" and "mystery_wire_col" in f.message
            and "not a PROBE_EVENT_DTYPE column" in f.message
            for f in findings
        )

    def test_duplicate_wire_column_flagged(self):
        repo = _fleet_repo(
            wire_transform=lambda s: s.replace(
                '    "span_id",\n', '    "span_id",\n    "span_id",\n', 1
            )
        )
        findings = list(FleetWireDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL104" and "listed twice" in f.message
            for f in findings
        )

    def test_new_dtype_column_must_ship(self):
        """A columnar-schema extension the wire contract misses is a
        finding in BOTH directions (dtype side + field-derivation
        side when mapped)."""
        repo = _fleet_repo(
            columnar_transform=lambda s: s.replace(
                '    ("span_id", "i4"),\n',
                '    ("span_id", "i4"),\n    ("new_col", "i4"),\n',
                1,
            ).replace(
                '    "span_id": ("span_id",),\n',
                '    "span_id": ("span_id", "new_col"),\n',
                1,
            )
        )
        findings = list(FleetWireDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL104" and "'new_col'" in f.message
            and "missing from WIRE_EVENT_COLUMNS" in f.message
            for f in findings
        )
        assert any(
            f.code == "TPL104" and "does not ship" in f.message
            for f in findings
        )

    def test_non_literal_declaration_flagged(self):
        repo = _fleet_repo(
            wire_transform=lambda s: s.replace(
                "WIRE_EVENT_COLUMNS: tuple[str, ...] = (",
                "WIRE_EVENT_COLUMNS: tuple[str, ...] = tuple(x for x in (",
                1,
            ).replace(
                '    "tpu_module_name",\n)', '    "tpu_module_name",\n))', 1
            )
        )
        findings = list(FleetWireDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL104" and "pure" in f.message for f in findings
        )


class TestConfigDrift:
    def test_real_tree_is_clean(self):
        repo = RepoContext(
            REPO,
            [
                FileContext(
                    REPO / CFG_REL,
                    CFG_REL,
                    (REPO / CFG_REL).read_text(encoding="utf-8"),
                )
            ],
        )
        assert list(ConfigDriftRule().check_repo(repo)) == []

    def test_dropped_dataclass_field_flags_schema_key(self):
        repo = _mutated_repo(
            CFG_REL,
            lambda s: s.replace("    burst_limit: int = 20000\n", "", 1),
        )
        findings = list(ConfigDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL140" and "sampling.burst_limit" in f.message
            for f in findings
        )

    def test_unvalidated_new_field_flagged(self):
        repo = _mutated_repo(
            CFG_REL,
            lambda s: s.replace(
                "    burst_limit: int = 20000\n",
                "    burst_limit: int = 20000\n    new_knob: int = 1\n",
                1,
            ),
        )
        findings = list(ConfigDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL140"
            and "sampling.new_knob" in f.message
            and "schema" in f.message
            for f in findings
        )

    def test_key_not_read_by_loader_flagged(self):
        repo = _mutated_repo(
            CFG_REL,
            lambda s: s.replace('"burst_limit": int', '"burst_limitx": int', 1),
        )
        findings = list(ConfigDriftRule().check_repo(repo))
        assert any(
            f.code == "TPL140"
            and "sampling.burst_limit" in f.message
            and "merge" in f.message
            for f in findings
        )


class TestMetricsDrift:
    def test_orphan_series_flagged(self, tmp_path):
        reg = tmp_path / "tpuslo" / "metrics"
        reg.mkdir(parents=True)
        (reg / "registry.py").write_text(
            'NAME = "llm_slo_agent_totally_orphaned_total"\n'
        )
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "x.md").write_text("nothing relevant\n")
        (tmp_path / "dashboards").mkdir()
        (tmp_path / "dashboards" / "generate.py").write_text("panels = []\n")
        result = run_analysis(
            tmp_path,
            paths=["tpuslo"],
            rules=[MetricsDriftRule()],
        )
        assert [f.code for f in result.findings] == ["TPL150"]
        assert "totally_orphaned" in result.findings[0].message

    def test_referenced_series_clean(self, tmp_path):
        reg = tmp_path / "tpuslo" / "metrics"
        reg.mkdir(parents=True)
        (reg / "registry.py").write_text(
            'NAME = "llm_slo_agent_referenced_total"\n'
        )
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "x.md").write_text(
            "llm_slo_agent_referenced_total is charted\n"
        )
        result = run_analysis(
            tmp_path, paths=["tpuslo"], rules=[MetricsDriftRule()]
        )
        assert result.findings == []

    def test_real_tree_is_clean(self):
        repo = RepoContext(REPO, [])
        assert list(MetricsDriftRule().check_repo(repo)) == []


_LOCK_FIXTURE_UNGUARDED = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def safe_inc(self):
            with self._lock:
                self.count += 1

        def racy_inc(self):
            self.count += 1
"""

_LOCK_FIXTURE_CLEAN = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def inc(self):
            with self._lock:
                self.count += 1

        def _drain_locked(self):
            self.count = 0

        def read(self):
            with self._lock:
                return self.count
"""

_LOCK_FIXTURE_DEADLOCK = """
    import threading

    class TwoLocks:
        def __init__(self):
            self._la = threading.Lock()
            self._lb = threading.Lock()

        def forward(self):
            with self._la:
                with self._lb:
                    pass

        def backward(self):
            with self._lb:
                with self._la:
                    pass
"""

_LOCK_FIXTURE_SELF_DEADLOCK = """
    import threading

    class Reentry:
        def __init__(self):
            self._lock = threading.Lock()

        def outer(self):
            with self._lock:
                self.inner()

        def inner(self):
            with self._lock:
                pass
"""

_LOCK_FIXTURE_CROSS_CLASS = """
    import threading

    class Inner:
        def __init__(self):
            self._ilock = threading.Lock()
            self._peer = Outer()

        def poke(self):
            with self._ilock:
                self._peer.touch()

    class Outer:
        def __init__(self):
            self._olock = threading.Lock()
            self._inner = Inner()

        def drive(self):
            with self._olock:
                self._inner.poke()

        def touch(self):
            with self._olock:
                pass
"""


def _lock_findings(source: str) -> list:
    ctx = _ctx("tpuslo/fixture_mod.py", source)
    return list(LockDisciplineRule().check_repo(RepoContext(REPO, [ctx])))


class TestLockDiscipline:
    def test_unguarded_write_flagged(self):
        findings = _lock_findings(_LOCK_FIXTURE_UNGUARDED)
        assert len(findings) == 1
        f = findings[0]
        assert f.code == "TPL110"
        assert "Counter.count" in f.message

    def test_guarded_and_locked_convention_clean(self):
        assert _lock_findings(_LOCK_FIXTURE_CLEAN) == []

    def test_init_writes_exempt(self):
        # The clean fixture writes count in __init__ without the lock.
        assert _lock_findings(_LOCK_FIXTURE_CLEAN) == []

    def test_synthetic_ab_ba_cycle_flagged(self):
        findings = _lock_findings(_LOCK_FIXTURE_DEADLOCK)
        cycles = [f for f in findings if f.code == "TPL111"]
        assert cycles, findings
        assert "TwoLocks._la" in cycles[0].message
        assert "TwoLocks._lb" in cycles[0].message

    def test_self_reacquire_through_call_is_deadlock(self):
        findings = _lock_findings(_LOCK_FIXTURE_SELF_DEADLOCK)
        assert any(
            f.code == "TPL111" and "re-acquired" in f.message
            for f in findings
        )

    def test_cross_class_cycle_flagged(self):
        findings = _lock_findings(_LOCK_FIXTURE_CROSS_CLASS)
        assert any(
            f.code == "TPL111"
            and "Outer._olock" in f.message
            and "Inner._ilock" in f.message
            for f in findings
        ), findings

    def test_out_of_scope_paths_ignored(self):
        ctx = _ctx("tests/fixture_mod.py", _LOCK_FIXTURE_UNGUARDED)
        rule = LockDisciplineRule()
        assert list(rule.check_repo(RepoContext(REPO, [ctx]))) == []

    def test_real_tree_is_clean(self):
        files = [
            FileContext(p, p.relative_to(REPO).as_posix(),
                        p.read_text(encoding="utf-8"))
            for p in sorted((REPO / "tpuslo").rglob("*.py"))
        ]
        findings = list(
            LockDisciplineRule().check_repo(RepoContext(REPO, files))
        )
        assert findings == [], "\n".join(f.render() for f in findings)


class TestHotPathPurity:
    def test_real_manifest_is_clean(self):
        files = [
            FileContext(p, p.relative_to(REPO).as_posix(),
                        p.read_text(encoding="utf-8"))
            for p in sorted((REPO / "tpuslo").rglob("*.py"))
        ]
        findings = list(
            HotPathPurityRule().check_repo(RepoContext(REPO, files))
        )
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_forbidden_call_in_hot_function_flagged(self):
        rel = "tpuslo/correlation/matcher.py"
        source = (
            "import json\n"
            "def match_batch(spans, signals, window_ms=0):\n"
            "    json.dumps(spans)\n"
            "    return []\n"
        )
        repo = RepoContext(REPO, [FileContext(REPO / rel, rel, source)])
        findings = [
            f
            for f in HotPathPurityRule().check_repo(repo)
            if f.code == "TPL120" and f.path == rel
        ]
        assert any("json.dumps" in f.message for f in findings)

    def test_renamed_manifest_entry_flagged(self):
        rel = "tpuslo/correlation/matcher.py"
        source = "def renamed():\n    pass\n"
        repo = RepoContext(REPO, [FileContext(REPO / rel, rel, source)])
        findings = list(HotPathPurityRule().check_repo(repo))
        assert any(
            "match_batch" in f.message and "manifest" in f.message
            for f in findings
        )

    def test_unslotted_hot_dataclass_flagged(self):
        rel = "tpuslo/obs/tracer.py"
        source = (
            "from dataclasses import dataclass\n"
            "@dataclass\n"
            "class Span:\n"
            "    name: str = ''\n"
        )
        repo = RepoContext(REPO, [FileContext(REPO / rel, rel, source)])
        findings = [
            f
            for f in HotPathPurityRule().check_repo(repo)
            if f.code == "TPL121" and f.path == rel
        ]
        assert any("Span" in f.message for f in findings)

    def test_dunder_slots_in_body_satisfies(self):
        rel = "tpuslo/obs/tracer.py"
        source = (
            "from dataclasses import dataclass\n"
            "@dataclass\n"
            "class Span:\n"
            "    __slots__ = ('name',)\n"
            "    name: str = ''\n"
        )
        repo = RepoContext(REPO, [FileContext(REPO / rel, rel, source)])
        assert not [
            f
            for f in HotPathPurityRule().check_repo(repo)
            if f.code == "TPL121" and f.path == rel
        ]


class TestExceptionDiscipline:
    def _findings(self, rel: str, body: str) -> list:
        return list(
            ExceptionDisciplineRule().check_file(_ctx(rel, body))
        )

    def test_silent_pass_flagged(self):
        findings = self._findings(
            "tpuslo/delivery/fixture.py",
            """
            def emit():
                try:
                    send()
                except Exception:
                    pass
            """,
        )
        assert [f.code for f in findings] == ["TPL130"]

    def test_silent_return_flagged(self):
        findings = self._findings(
            "tpuslo/obs/fixture.py",
            """
            def emit():
                try:
                    send()
                except Exception:
                    return None
            """,
        )
        assert [f.code for f in findings] == ["TPL130"]

    def test_counter_increment_satisfies(self):
        findings = self._findings(
            "tpuslo/delivery/fixture.py",
            """
            def emit(stats):
                try:
                    send()
                except Exception:
                    stats["errors"] += 1
            """,
        )
        assert findings == []

    def test_reraise_satisfies(self):
        findings = self._findings(
            "tpuslo/runtime/fixture.py",
            """
            def emit():
                try:
                    send()
                except Exception:
                    raise
            """,
        )
        assert findings == []

    def test_narrow_type_exempt(self):
        findings = self._findings(
            "tpuslo/delivery/fixture.py",
            """
            def emit():
                try:
                    send()
                except OSError:
                    pass
            """,
        )
        assert findings == []

    def test_non_agent_plane_exempt(self):
        findings = self._findings(
            "tpuslo/models/fixture.py",
            """
            def emit():
                try:
                    send()
                except Exception:
                    pass
            """,
        )
        assert findings == []

    def test_agent_plane_tree_is_clean(self):
        files = [
            FileContext(p, p.relative_to(REPO).as_posix(),
                        p.read_text(encoding="utf-8"))
            for p in sorted((REPO / "tpuslo").rglob("*.py"))
        ]
        rule = ExceptionDisciplineRule()
        findings = [f for ctx in files for f in rule.check_file(ctx)]
        assert findings == [], "\n".join(f.render() for f in findings)


class TestHotpathManifestIntegrity:
    def test_manifest_entries_resolve_in_real_tree(self):
        """Every manifest entry must point at a real function/class —
        guards against silent staleness after refactors."""
        from tpuslo.analysis.hotpaths import HOT_DATACLASSES, HOT_FUNCTIONS

        for rel, qualname in HOT_FUNCTIONS:
            tree = ast.parse((REPO / rel).read_text(encoding="utf-8"))
            names = set()
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    names.add(node.name)
                elif isinstance(node, ast.ClassDef):
                    names.update(
                        f"{node.name}.{sub.name}"
                        for sub in node.body
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        )
                    )
            assert qualname in names, f"{rel}:{qualname} missing"
        for rel, clsname in HOT_DATACLASSES:
            tree = ast.parse((REPO / rel).read_text(encoding="utf-8"))
            assert any(
                isinstance(n, ast.ClassDef) and n.name == clsname
                for n in ast.walk(tree)
            ), f"{rel}:{clsname} missing"
