"""Graceful drain: bounded shutdown steps + the SIGTERM contract.

The subprocess test is the satellite regression for "SIGTERM behaves
exactly like KeyboardInterrupt": a real agent process receiving
SIGTERM must exit 0 through the drain path with a final snapshot on
disk — the Kubernetes pod-termination story, end to end.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

from tpuslo.runtime import DrainController, install_drain_handler
from tpuslo.runtime.drain import (
    DRAIN_CLEAN,
    DRAIN_DEADLINE_EXCEEDED,
    DRAIN_STEP_ERROR,
    DrainSignal,
)


class FakeClock:
    def __init__(self, now: float = 0.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


class TestDrainController:
    def test_clean_drain_runs_every_step_in_order(self):
        clock = FakeClock()
        drain = DrainController("test", deadline_s=10.0, clock=clock)
        ran = []
        drain.step("a", lambda budget: ran.append(("a", budget)) or True)
        drain.step("b", lambda budget: ran.append(("b", budget)) or True)
        report = drain.finish()
        assert report.outcome == DRAIN_CLEAN
        assert [name for name, _ in ran] == ["a", "b"]
        assert all(budget == 10.0 for _, budget in ran)
        assert all(step.ok for step in report.steps)

    def test_slow_step_eats_only_its_own_budget(self):
        clock = FakeClock()
        drain = DrainController("test", deadline_s=10.0, clock=clock)

        def slow(budget):
            clock.advance(8.0)
            return True

        drain.step("slow", slow)
        budgets = []
        drain.step("next", lambda budget: budgets.append(budget) or True)
        report = drain.finish()
        assert budgets == [2.0]  # deadline is shared, not per-step
        assert report.outcome == DRAIN_CLEAN

    def test_exhausted_deadline_still_runs_steps_with_zero_budget(self):
        """Late steps (spill to spool, final snapshot) must run even
        after an earlier flush overran — with budget 0, so they take
        their immediate loss-free fallback instead of waiting."""
        clock = FakeClock()
        drain = DrainController("test", deadline_s=1.0, clock=clock)
        drain.step("eats-it", lambda budget: clock.advance(2.0) or True)
        ran = []
        drain.step("starved", lambda budget: ran.append(budget) or True)
        report = drain.finish()
        assert ran == [0.0]
        assert report.outcome == DRAIN_DEADLINE_EXCEEDED
        assert report.steps[-1].ok  # it ran and succeeded at budget 0

    def test_raising_step_is_recorded_and_drain_continues(self):
        drain = DrainController("test", deadline_s=10.0, clock=FakeClock())

        def explode(budget):
            raise RuntimeError("boom")

        ran = []
        drain.step("explodes", explode)
        drain.step("after", lambda budget: ran.append(1) or True)
        report = drain.finish()
        assert ran == [1]
        assert report.outcome == DRAIN_STEP_ERROR
        assert "boom" in report.steps[0].detail

    def test_none_return_counts_as_success(self):
        drain = DrainController("test", deadline_s=10.0, clock=FakeClock())
        drain.step("returns-none", lambda budget: None)
        assert drain.finish().outcome == DRAIN_CLEAN

    def test_summary_is_greppable(self):
        drain = DrainController("sigterm", deadline_s=5.0, clock=FakeClock())
        drain.step("flush", lambda budget: True)
        summary = drain.finish().summary()
        assert "reason=sigterm" in summary
        assert "outcome=clean" in summary
        assert "flush=ok" in summary


class TestInstallDrainHandler:
    def test_handler_raises_drain_signal_and_restores(self):
        previous = signal.getsignal(signal.SIGTERM)
        restore = install_drain_handler()
        try:
            try:
                os.kill(os.getpid(), signal.SIGTERM)
                time.sleep(1.0)  # signal delivery is asynchronous
                raise AssertionError("DrainSignal not raised")
            except DrainSignal as caught:
                assert caught.signum == signal.SIGTERM
        finally:
            restore()
        assert signal.getsignal(signal.SIGTERM) is previous

    def test_off_main_thread_install_is_a_noop(self):
        outcome = {}

        def worker():
            restore = install_drain_handler()
            restore()
            outcome["ok"] = True

        thread = threading.Thread(target=worker)
        thread.start()
        thread.join()
        assert outcome == {"ok": True}


class TestAgentSigterm:
    """Satellite regression: SIGTERM == KeyboardInterrupt, via drain."""

    def test_sigterm_exits_zero_with_final_snapshot(self, tmp_path):
        out = tmp_path / "events.jsonl"
        state_dir = tmp_path / "state"
        cmd = [
            sys.executable, "-m", "tpuslo", "agent",
            "--scenario", "dns_latency",
            "--count", "0",  # run forever; only the signal stops it
            "--interval-s", "0.05",
            "--event-kind", "both",
            "--output", "jsonl",
            "--jsonl-path", str(out),
            "--capability-mode", "bcc_degraded",
            "--metrics-port", "0",
            "--max-overhead-pct", "1000",
            "--state-dir", str(state_dir),
            "--snapshot-interval-s", "0",
        ]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True,
        )
        try:
            # Wait until the loop is demonstrably running (the signal
            # handler installs just before the loop starts).
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline:
                if out.exists() and out.stat().st_size > 0:
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("agent never started emitting")
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        stderr = proc.stderr.read()
        assert rc == 0, stderr

        # The drain ran, attributed to the signal.
        drain_lines = [l for l in stderr.splitlines() if "drain:" in l]
        assert drain_lines, stderr
        assert "reason=signal_15" in drain_lines[0]
        assert "final_snapshot=ok" in drain_lines[0]

        # And the final snapshot is on disk, complete and current.
        snapshot = json.loads(
            (state_dir / "agent-state.json").read_text()
        )
        progress = snapshot["components"]["progress"]
        emitted_cycles = {
            json.loads(line).get("trace_id")
            for line in out.read_text().splitlines()
            if line.strip()
        }
        # The signal may land mid-cycle: the cycle being written when
        # it arrived is durable in the output but not yet in progress
        # (it will be re-emitted on restart — at-least-once).
        assert len(emitted_cycles) > 0
        assert progress["next_cycle"] >= len(emitted_cycles) - 1
        assert progress["next_cycle"] >= 1
