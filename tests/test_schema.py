"""Contract-layer tests. Reference test model: pkg/schema/validator_test.go."""

from datetime import datetime, timezone

import pytest

from tpuslo import schema

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)


def make_slo_event(**overrides):
    ev = schema.SLOEvent(
        event_id="req-0001-ttft_ms",
        timestamp=TS,
        cluster="tpu-cluster",
        namespace="llm",
        workload="rag-service",
        service="rag-service",
        request_id="req-0001",
        sli_name="ttft_ms",
        sli_value=340.0,
        unit="ms",
        status="ok",
        trace_id="trace-0001",
        labels={"source": "synthetic"},
    )
    for k, v in overrides.items():
        setattr(ev, k, v)
    return ev


def make_probe_event(**overrides):
    ev = schema.ProbeEventV1(
        ts_unix_nano=int(TS.timestamp() * 1e9),
        signal="dns_latency_ms",
        node="tpu-vm-0",
        namespace="llm",
        pod="rag-service-abc",
        container="rag",
        pid=1234,
        tid=1234,
        value=12.0,
        unit="ms",
        status="ok",
        conn_tuple=schema.ConnTuple("10.0.0.10", "10.0.0.53", 42424, 53, "udp"),
    )
    for k, v in overrides.items():
        setattr(ev, k, v)
    return ev


class TestSLOEvent:
    def test_valid_event_passes_contract(self):
        schema.validate(make_slo_event().to_dict(), schema.SCHEMA_SLO_EVENT)

    def test_timestamp_rfc3339_z_suffix(self):
        payload = make_slo_event().to_dict()
        assert payload["timestamp"] == "2026-07-29T12:00:00Z"

    def test_bad_status_rejected(self):
        payload = make_slo_event(status="exploded").to_dict()
        with pytest.raises(schema.SchemaValidationError):
            schema.validate(payload, schema.SCHEMA_SLO_EVENT)

    def test_bad_sli_name_rejected(self):
        payload = make_slo_event(sli_name="nonsense_sli").to_dict()
        with pytest.raises(schema.SchemaValidationError):
            schema.validate(payload, schema.SCHEMA_SLO_EVENT)

    def test_missing_required_field_rejected(self):
        payload = make_slo_event().to_dict()
        del payload["cluster"]
        with pytest.raises(schema.SchemaValidationError):
            schema.validate(payload, schema.SCHEMA_SLO_EVENT)

    def test_empty_trace_id_omitted(self):
        payload = make_slo_event(trace_id="").to_dict()
        assert "trace_id" not in payload
        schema.validate(payload, schema.SCHEMA_SLO_EVENT)


class TestProbeEvent:
    def test_valid_probe_passes_contract(self):
        schema.validate(make_probe_event().to_dict(), schema.SCHEMA_PROBE_EVENT)

    def test_tpu_block_round_trips(self):
        ev = make_probe_event(
            signal="xla_compile_ms",
            conn_tuple=None,
            tpu=schema.TPURef(
                chip="accel0",
                slice_id="v5e-8-slice0",
                host_index=0,
                program_id="jit_train_step",
                launch_id=17,
                module_name="jit_train_step.17",
            ),
        )
        payload = ev.to_dict()
        assert payload["tpu"]["chip"] == "accel0"
        assert payload["tpu"]["launch_id"] == 17
        assert "ici_link" not in payload["tpu"]
        schema.validate(payload, schema.SCHEMA_PROBE_EVENT)

    def test_errno_and_confidence_serialised(self):
        ev = make_probe_event(errno=110, confidence=0.9)
        payload = ev.to_dict()
        assert payload["errno"] == 110
        assert payload["confidence"] == 0.9
        schema.validate(payload, schema.SCHEMA_PROBE_EVENT)

    def test_conn_tuple_key_is_canonical(self):
        tup = schema.ConnTuple("1.2.3.4", "5.6.7.8", 1111, 443, "tcp")
        assert tup.key() == "tcp:1.2.3.4:1111->5.6.7.8:443"

    def test_invalid_status_rejected(self):
        payload = make_probe_event(status="breach").to_dict()
        with pytest.raises(schema.SchemaValidationError):
            schema.validate(payload, schema.SCHEMA_PROBE_EVENT)

    def test_negative_port_rejected(self):
        payload = make_probe_event(
            conn_tuple=schema.ConnTuple("1.2.3.4", "5.6.7.8", -1, 443, "tcp")
        ).to_dict()
        with pytest.raises(schema.SchemaValidationError):
            schema.validate(payload, schema.SCHEMA_PROBE_EVENT)


class TestIncidentAttribution:
    def make(self, domain="network_dns"):
        return schema.IncidentAttribution(
            incident_id="inc-0001",
            timestamp=TS,
            cluster="tpu-cluster",
            service="rag-service",
            predicted_fault_domain=domain,
            confidence=0.92,
            evidence=[
                schema.Evidence("dns_latency_ms", 220.0, "ebpf"),
                schema.Evidence("fault_label", "dns_latency", "application"),
            ],
            slo_impact=schema.SLOImpact("ttft_ms", 2.4, 30),
            trace_ids=["trace-0001"],
            request_ids=["req-0001"],
            fault_hypotheses=[
                schema.FaultHypothesis("network_dns", 0.92, ["dns_latency_ms"]),
                schema.FaultHypothesis("network_egress", 0.05, []),
            ],
        )

    def test_valid_attribution_passes_contract(self):
        schema.validate(self.make().to_dict(), schema.SCHEMA_INCIDENT_ATTRIBUTION)

    @pytest.mark.parametrize(
        "domain", ["tpu_ici", "tpu_hbm", "xla_compile", "host_offload"]
    )
    def test_tpu_fault_domains_accepted(self, domain):
        schema.validate(
            self.make(domain=domain).to_dict(), schema.SCHEMA_INCIDENT_ATTRIBUTION
        )

    def test_unknown_domain_rejected(self):
        payload = self.make(domain="gpu_meltdown").to_dict()
        with pytest.raises(schema.SchemaValidationError):
            schema.validate(payload, schema.SCHEMA_INCIDENT_ATTRIBUTION)

    def test_confidence_out_of_range_rejected(self):
        bad = self.make()
        bad.confidence = 1.7
        with pytest.raises(schema.SchemaValidationError):
            schema.validate(bad.to_dict(), schema.SCHEMA_INCIDENT_ATTRIBUTION)

    def test_libtpu_evidence_source_accepted(self):
        att = self.make(domain="tpu_hbm")
        att.evidence = [schema.Evidence("hbm_alloc_stall_ms", 45.0, "libtpu")]
        schema.validate(att.to_dict(), schema.SCHEMA_INCIDENT_ATTRIBUTION)


class TestSchemaCompilation:
    def test_all_schemas_compile(self):
        for name in schema.ALL_SCHEMAS:
            assert schema.load_schema(name)["$schema"]

    def test_is_valid_nonraising(self):
        assert not schema.is_valid({}, schema.SCHEMA_SLO_EVENT)


class TestTimestamps:
    def test_parse_round_trip(self):
        assert schema.parse_rfc3339(schema.rfc3339(TS)) == TS

    def test_naive_datetime_treated_as_utc(self):
        naive = datetime(2026, 7, 29, 12, 0, 0)
        assert schema.rfc3339(naive) == "2026-07-29T12:00:00Z"
