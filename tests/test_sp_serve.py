"""Sequence-parallel serving prefill: ring attention fills the cache.

Parity target is :func:`tpuslo.models.llama.prefill` — same logits,
same cache, so decode continues on the ordinary path after a prefill
that was sharded over the sp mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpuslo.models.llama import (
    LlamaConfig,
    decode_step,
    init_kv_cache,
    init_params,
    prefill,
)
from tpuslo.models.sp_serve import sp_prefill, sp_prefill_into_cache

pytestmark = pytest.mark.slow


def _cfg(max_seq_len: int = 64) -> LlamaConfig:
    # f32 + GQA (4 heads over 2 KV heads): the ring path must get the
    # n_rep repeat right, and f32 keeps parity tolerances tight.
    return LlamaConfig(
        vocab_size=256, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=64, max_seq_len=max_seq_len, rope_theta=10000.0,
        dtype=jnp.float32,
    )


def _mesh(n: int = 4) -> Mesh:
    return Mesh(np.array(jax.devices("cpu")[:n]), ("sp",))


def test_sp_prefill_matches_dense_logits_and_kv():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    S = 32
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 255)

    dense_logits, dense_cache = prefill(
        params, tokens, init_kv_cache(cfg, 2), cfg
    )
    sp_logits, ks, vs = sp_prefill(params, tokens, cfg, _mesh())

    assert float(jnp.max(jnp.abs(sp_logits - dense_logits))) < 1e-3
    # Cache leaves: dense layout (L, B, S_max, KV, HD); compare the
    # written S positions.
    assert (
        float(jnp.max(jnp.abs(ks - dense_cache["k"][:, :, :S]))) < 1e-3
    )
    assert (
        float(jnp.max(jnp.abs(vs - dense_cache["v"][:, :, :S]))) < 1e-3
    )


def test_sp_prefill_padded_prompt_true_length():
    """Padded to an sp-aligned bucket: the last REAL position's logits
    come back even though it sits in an interior shard."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    S, true = 32, 17  # position 16 lives in shard 2 of 4 (8 per shard)
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, true), 0, 255)
    tokens = jnp.pad(ids, ((0, 0), (0, S - true)))

    dense_logits, _ = prefill(
        params, tokens, init_kv_cache(cfg, 1), cfg,
        true_length=jnp.asarray(true, jnp.int32),
    )
    sp_logits, _, _ = sp_prefill(
        params, tokens, cfg, _mesh(),
        true_length=jnp.asarray(true, jnp.int32),
    )
    assert float(jnp.max(jnp.abs(sp_logits - dense_logits))) < 1e-3


def test_sp_prefill_rejects_misaligned_length():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 30), jnp.int32)  # 30 % 4 != 0
    with pytest.raises(ValueError, match="not divisible"):
        sp_prefill(params, tokens, cfg, _mesh())


def test_sp_prefill_rejects_out_of_range_true_length():
    """An out-of-range true_length would psum a zero hidden state into
    plausible-looking logits; the API must refuse instead."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 32), jnp.int32)
    for bad in (0, 33):
        with pytest.raises(ValueError, match="outside"):
            sp_prefill(
                params, tokens, cfg, _mesh(),
                true_length=jnp.asarray(bad, jnp.int32),
            )


def test_sp_prefill_into_cache_then_decode_matches_dense():
    """The handoff contract: sharded prefill -> dense cache -> ordinary
    decode_step continues with logits matching the all-dense path."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    S = 32
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, S), 0, 255)

    dense_logits, dense_cache = prefill(
        params, tokens, init_kv_cache(cfg, 1), cfg
    )
    sp_logits, sp_cache = sp_prefill_into_cache(
        params, tokens, init_kv_cache(cfg, 1), cfg, _mesh()
    )
    assert int(sp_cache["length"]) == S

    tok_d = jnp.argmax(dense_logits, -1).astype(jnp.int32)
    tok_s = jnp.argmax(sp_logits, -1).astype(jnp.int32)
    assert jnp.array_equal(tok_d, tok_s) or (
        float(
            jnp.diff(jnp.sort(dense_logits[0].astype(jnp.float32))[-2:])[0]
        )
        < 0.15
    )
    # Teacher-force the same token through both caches: per-step decode
    # logits must stay within tolerance for several steps.
    for _ in range(4):
        d_logits, dense_cache = decode_step(params, tok_d, dense_cache, cfg)
        s_logits, sp_cache = decode_step(params, tok_d, sp_cache, cfg)
        assert float(jnp.max(jnp.abs(d_logits - s_logits))) < 1e-3
        tok_d = jnp.argmax(d_logits, -1).astype(jnp.int32)


def test_engine_ingest_prompt_sp_matches_dense_ingest():
    """Engine-level handoff: a long prompt ingested over the sp mesh
    yields the same logits/length as the chunked single-device path,
    and the ordinary decode loop continues from the installed cache."""
    from tpuslo.models.serve import ServeEngine

    cfg = _cfg(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(cfg=cfg, params=params, prefill_buckets=(32,))
    prompt = "long sequence-parallel prompt " * 3  # 90 ids > one bucket

    d_logits, d_cache, d_len = engine.ingest_prompt(prompt)
    s_logits, s_cache, s_len = engine.ingest_prompt_sp(prompt, _mesh())
    assert s_len == d_len
    assert float(jnp.max(jnp.abs(s_logits - d_logits))) < 1e-3

    tok = jnp.argmax(d_logits, -1).astype(jnp.int32)
    for _ in range(3):
        dl, d_cache = decode_step(params, tok, d_cache, cfg)
        sl, s_cache = decode_step(params, tok, s_cache, cfg)
        assert float(jnp.max(jnp.abs(dl - sl))) < 1e-3
        tok = jnp.argmax(dl, -1).astype(jnp.int32)


def test_engine_ingest_prompt_sp_guards():
    from tpuslo.models.serve import ServeEngine

    cfg = _cfg(max_seq_len=64)
    engine = ServeEngine(
        cfg=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        kv_dtype="int8", prefill_buckets=(32,),
    )
    with pytest.raises(ValueError, match="single-device bf16"):
        engine.ingest_prompt_sp("p", _mesh())

    # max_seq_len=67: aligned capacity is 64, but encode_bytes caps the
    # prompt at 65 ids — longer than any sp-aligned cache fit.
    odd = _cfg(max_seq_len=67)
    bf16 = ServeEngine(
        cfg=odd, params=init_params(jax.random.PRNGKey(0), odd),
        prefill_buckets=(32,),
    )
    with pytest.raises(ValueError, match="cannot hold"):
        bf16.ingest_prompt_sp("x" * 70, _mesh())


def test_sp_prefill_two_device_axis():
    """Axis sizes other than 4 (the ring rotation count changes)."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 48), 0, 255)
    dense_logits, _ = prefill(params, tokens, init_kv_cache(cfg, 1), cfg)
    sp_logits, _, _ = sp_prefill(params, tokens, cfg, _mesh(2))
    assert float(jnp.max(jnp.abs(sp_logits - dense_logits))) < 1e-3
