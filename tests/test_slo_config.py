"""SLO math + config loader tests. Reference: pkg/slo, pkg/toolkitcfg tests."""

from datetime import datetime, timedelta, timezone

import pytest

from tpuslo import slo
from tpuslo.config import default_config, load_config

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)


class TestSLOMath:
    def test_ttft(self):
        assert slo.ttft_ms(TS, TS + timedelta(milliseconds=250)) == 250.0

    def test_ttft_order_enforced(self):
        with pytest.raises(ValueError):
            slo.ttft_ms(TS, TS - timedelta(seconds=1))
        with pytest.raises(ValueError):
            slo.ttft_ms(None, TS)

    def test_tokens_per_second(self):
        tps = slo.tokens_per_second(TS, TS + timedelta(seconds=2), 50)
        assert tps == 25.0

    def test_tokens_zero_window_returns_count(self):
        assert slo.tokens_per_second(TS, TS, 7) == 7.0

    def test_tokens_validation(self):
        with pytest.raises(ValueError):
            slo.tokens_per_second(TS, TS, 0)

    def test_calculate_snapshot(self):
        timing = slo.Timing(
            request_start=TS,
            first_token_at=TS + timedelta(milliseconds=300),
            last_token_at=TS + timedelta(milliseconds=1300),
            token_count=40,
        )
        snap = slo.calculate(timing, slo.RetrievalBreakdown(10, 20, 5))
        assert snap.ttft_ms == 300.0
        assert snap.tokens_per_s == 40.0
        assert slo.total_retrieval_ms(snap.retrieval) == 35.0

    def test_quantile_interpolation(self):
        values = [10.0, 20.0, 30.0, 40.0]
        assert slo.quantile(values, 0.5) == 25.0
        assert slo.quantile(values, 0.0) == 10.0
        assert slo.quantile(values, 1.0) == 40.0
        assert slo.quantile([], 0.5) == 0.0
        assert slo.quantile([5.0], 0.95) == 5.0

    def test_aggregate(self):
        snaps = [
            slo.Snapshot(ttft_ms=float(v), tokens_per_s=float(100 - v))
            for v in (100, 200, 300, 400, 500)
        ]
        agg = slo.aggregate(snaps)
        assert agg.ttft_p50 == 300.0
        assert agg.ttft_p95 == pytest.approx(480.0)
        # negative throughputs are clamped to zero before aggregation
        assert agg.tokens_per_s_p50 == 0.0
        assert slo.aggregate([]) == slo.Percentiles()

    def test_aggregate_clamps_negatives(self):
        agg = slo.aggregate([slo.Snapshot(ttft_ms=-5.0, tokens_per_s=-1.0)])
        assert agg.ttft_p50 == 0.0


class TestToolkitConfig:
    def test_defaults_validate_contract(self):
        cfg = default_config()
        assert cfg.safety.max_overhead_pct == 3.0
        assert "xla_compile_ms" in cfg.signal_set
        assert cfg.correlation.window_ms == 2000

    def test_load_overrides_and_normalizes(self, tmp_path):
        path = tmp_path / "toolkit.yaml"
        path.write_text(
            """
apiVersion: toolkit.tpuslo.dev/v1alpha1
kind: ToolkitConfig
signal_set: [dns_latency_ms, xla_compile_ms]
sampling:
  events_per_second_limit: 500
  burst_limit: 0
correlation:
  window_ms: 1000
safety:
  max_overhead_pct: 2.5
webhook:
  enabled: true
  url: http://hooks.example/incident
  format: pagerduty
tpu:
  slice_id: v5e-8-s0
"""
        )
        cfg = load_config(str(path))
        assert cfg.signal_set == ["dns_latency_ms", "xla_compile_ms"]
        assert cfg.sampling.events_per_second_limit == 500
        assert cfg.sampling.burst_limit == 20000  # zero -> default
        assert cfg.correlation.window_ms == 1000
        assert cfg.safety.max_overhead_pct == 2.5
        assert cfg.webhook.enabled and cfg.webhook.format == "pagerduty"
        assert cfg.tpu.slice_id == "v5e-8-s0"

    def test_load_rejects_bad_schema(self, tmp_path):
        path = tmp_path / "bad.yaml"
        path.write_text("correlation:\n  window_ms: -5\n")
        with pytest.raises(Exception):
            load_config(str(path))

    def test_load_rejects_non_mapping(self, tmp_path):
        path = tmp_path / "list.yaml"
        path.write_text("- a\n- b\n")
        with pytest.raises(ValueError):
            load_config(str(path))
