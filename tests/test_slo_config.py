"""SLO math + config loader tests. Reference: pkg/slo, pkg/toolkitcfg tests."""

from datetime import datetime, timedelta, timezone

import pytest

from tpuslo import slo
from tpuslo.config import default_config, load_config

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)


class TestSLOMath:
    def test_ttft(self):
        assert slo.ttft_ms(TS, TS + timedelta(milliseconds=250)) == 250.0

    def test_ttft_order_enforced(self):
        with pytest.raises(ValueError):
            slo.ttft_ms(TS, TS - timedelta(seconds=1))
        with pytest.raises(ValueError):
            slo.ttft_ms(None, TS)

    def test_tokens_per_second(self):
        tps = slo.tokens_per_second(TS, TS + timedelta(seconds=2), 50)
        assert tps == 25.0

    def test_tokens_zero_window_returns_count(self):
        assert slo.tokens_per_second(TS, TS, 7) == 7.0

    def test_tokens_validation(self):
        with pytest.raises(ValueError):
            slo.tokens_per_second(TS, TS, 0)

    def test_calculate_snapshot(self):
        timing = slo.Timing(
            request_start=TS,
            first_token_at=TS + timedelta(milliseconds=300),
            last_token_at=TS + timedelta(milliseconds=1300),
            token_count=40,
        )
        snap = slo.calculate(timing, slo.RetrievalBreakdown(10, 20, 5))
        assert snap.ttft_ms == 300.0
        assert snap.tokens_per_s == 40.0
        assert slo.total_retrieval_ms(snap.retrieval) == 35.0

    def test_quantile_interpolation(self):
        values = [10.0, 20.0, 30.0, 40.0]
        assert slo.quantile(values, 0.5) == 25.0
        assert slo.quantile(values, 0.0) == 10.0
        assert slo.quantile(values, 1.0) == 40.0
        assert slo.quantile([], 0.5) == 0.0
        assert slo.quantile([5.0], 0.95) == 5.0

    def test_aggregate(self):
        snaps = [
            slo.Snapshot(ttft_ms=float(v), tokens_per_s=float(100 - v))
            for v in (100, 200, 300, 400, 500)
        ]
        agg = slo.aggregate(snaps)
        assert agg.ttft_p50 == 300.0
        assert agg.ttft_p95 == pytest.approx(480.0)
        # negative throughputs are clamped to zero before aggregation
        assert agg.tokens_per_s_p50 == 0.0
        assert slo.aggregate([]) == slo.Percentiles()

    def test_aggregate_clamps_negatives(self):
        agg = slo.aggregate([slo.Snapshot(ttft_ms=-5.0, tokens_per_s=-1.0)])
        assert agg.ttft_p50 == 0.0

    def test_aggregate_is_total_on_empty(self):
        # No caller special-casing: every percentile reads zero.
        agg = slo.aggregate([])
        assert agg == slo.Percentiles()
        assert agg.ttft_p99 == 0.0
        assert agg.retrieval_p95_ms == 0.0

    def test_aggregate_single_snapshot_is_exact(self):
        snap = slo.Snapshot(
            ttft_ms=123.0,
            tokens_per_s=45.0,
            retrieval=slo.RetrievalBreakdown(5.0, 3.0, 2.0),
        )
        agg = slo.aggregate([snap])
        assert agg.ttft_p50 == agg.ttft_p95 == agg.ttft_p99 == 123.0
        assert agg.tokens_per_s_p50 == agg.tokens_per_s_p95 == 45.0
        assert agg.retrieval_p95_ms == 10.0

    def test_quantile_clamps_out_of_range_q(self):
        values = [1.0, 2.0, 3.0]
        assert slo.quantile(values, -0.5) == 1.0
        assert slo.quantile(values, 1.5) == 3.0

    def test_quantile_nan_free_under_ties(self):
        import math

        ties = [50.0] * 7
        for q in (0.0, 0.25, 0.5, 0.95, 1.0):
            value = slo.quantile(ties, q)
            assert value == 50.0
            assert not math.isnan(value)

    def test_quantile_drops_nan_inputs(self):
        import math

        poisoned = [10.0, float("nan"), 30.0]
        assert slo.quantile(poisoned, 0.5) == 20.0
        assert slo.quantile([float("nan")], 0.5) == 0.0
        agg = slo.aggregate(
            [
                slo.Snapshot(ttft_ms=float("nan"), tokens_per_s=1.0),
                slo.Snapshot(ttft_ms=100.0, tokens_per_s=1.0),
            ]
        )
        assert agg.ttft_p50 == 100.0
        assert not math.isnan(agg.ttft_p99)


class TestToolkitConfig:
    def test_defaults_validate_contract(self):
        cfg = default_config()
        assert cfg.safety.max_overhead_pct == 3.0
        assert "xla_compile_ms" in cfg.signal_set
        assert cfg.correlation.window_ms == 2000

    def test_load_overrides_and_normalizes(self, tmp_path):
        path = tmp_path / "toolkit.yaml"
        path.write_text(
            """
apiVersion: toolkit.tpuslo.dev/v1alpha1
kind: ToolkitConfig
signal_set: [dns_latency_ms, xla_compile_ms]
sampling:
  events_per_second_limit: 500
  burst_limit: 0
correlation:
  window_ms: 1000
safety:
  max_overhead_pct: 2.5
webhook:
  enabled: true
  url: http://hooks.example/incident
  format: pagerduty
tpu:
  slice_id: v5e-8-s0
"""
        )
        cfg = load_config(str(path))
        assert cfg.signal_set == ["dns_latency_ms", "xla_compile_ms"]
        assert cfg.sampling.events_per_second_limit == 500
        assert cfg.sampling.burst_limit == 20000  # zero -> default
        assert cfg.correlation.window_ms == 1000
        assert cfg.safety.max_overhead_pct == 2.5
        assert cfg.webhook.enabled and cfg.webhook.format == "pagerduty"
        assert cfg.tpu.slice_id == "v5e-8-s0"

    def test_slo_section_presence_implies_on(self, tmp_path):
        path = tmp_path / "toolkit.yaml"
        path.write_text(
            """
slo:
  availability_target: 0.995
  ttft_objective_ms: 600
  tenants:
    gold:
      availability_target: 0.999
      ttft_objective_ms: 400
"""
        )
        cfg = load_config(str(path))
        assert cfg.slo.enabled
        assert cfg.slo.availability_target == 0.995
        assert cfg.slo.ttft_objective_ms == 600.0
        assert cfg.slo.bucket_s == 10  # untouched default
        assert cfg.slo.tenants == {
            "gold": {
                "availability_target": 0.999,
                "ttft_objective_ms": 400.0,
            }
        }

    def test_slo_explicit_disable_wins(self, tmp_path):
        path = tmp_path / "toolkit.yaml"
        path.write_text("slo:\n  enabled: false\n")
        cfg = load_config(str(path))
        assert not cfg.slo.enabled

    def test_slo_absent_stays_off_and_defaults_validate(self, tmp_path):
        path = tmp_path / "toolkit.yaml"
        path.write_text("correlation:\n  window_ms: 1500\n")
        cfg = load_config(str(path))
        assert not cfg.slo.enabled
        assert cfg.slo.fast_burn_threshold == 14.4
        assert cfg.slo.slow_burn_threshold == 6.0
        # Round trip: the emitted dict revalidates against the contract.
        from tpuslo.schema import SCHEMA_TOOLKIT_CONFIG, validate

        validate(cfg.to_dict(), SCHEMA_TOOLKIT_CONFIG)

    def test_slo_rejects_bad_tenant_override_type(self, tmp_path):
        path = tmp_path / "toolkit.yaml"
        path.write_text(
            "slo:\n  tenants:\n    gold:\n      "
            "availability_target: not-a-number\n"
        )
        with pytest.raises(Exception):
            load_config(str(path))

    def test_load_rejects_bad_schema(self, tmp_path):
        path = tmp_path / "bad.yaml"
        path.write_text("correlation:\n  window_ms: -5\n")
        with pytest.raises(Exception):
            load_config(str(path))

    def test_load_rejects_non_mapping(self, tmp_path):
        path = tmp_path / "list.yaml"
        path.write_text("- a\n- b\n")
        with pytest.raises(ValueError):
            load_config(str(path))
