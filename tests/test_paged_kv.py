"""Paged KV cache: parity with dense serving, block accounting,
admission backpressure, and the capacity win the paging exists for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from tpuslo.models.llama import (
    init_params,
    kv_cache_bytes,
    llama_tiny,
)
from tpuslo.models.paged_kv import (
    PagedBatchingEngine,
    init_paged_pool,
    paged_pool_bytes,
)
from tpuslo.models.serve import ServeEngine


CFG = llama_tiny(max_seq_len=128)
PARAMS = init_params(jax.random.PRNGKey(0), CFG)


def _single_stream(prompt, n=8, kv_dtype="bf16"):
    eng = ServeEngine(cfg=CFG, params=PARAMS, kv_dtype=kv_dtype)
    return [e.token_id for e in eng.generate(prompt, max_new_tokens=n)]


def test_paged_matches_single_request_serving():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    prompts = ["hello world", "a much longer second prompt here", "third"]
    ids = [eng.submit(p, max_new_tokens=8) for p in prompts]
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt), prompt


def test_paged_generation_crosses_block_boundaries():
    """Prompt of 20 ids with block_size 16 spans two blocks; 24 new
    tokens cross two more boundaries — output must still match the
    dense engine exactly."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=1, block_size=16
    )
    prompt = "x" * 19  # + BOS = 20 ids
    rid = eng.submit(prompt, max_new_tokens=24)
    results = eng.run()
    assert results[rid] == _single_stream(prompt, n=24)


def test_paged_int8_compose():
    """int8 representation + paging stack: parity against the int8
    single-request engine (same quantized write discipline)."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, kv_dtype="int8"
    )
    prompts = ["alpha", "beta prompt"]
    ids = [eng.submit(p, max_new_tokens=8) for p in prompts]
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt, kv_dtype="int8")


def test_block_accounting_and_release():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, n_blocks=9
    )
    free0 = len(eng._free)
    assert free0 == 8
    ids = [eng.submit("abcd", max_new_tokens=8) for _ in range(2)]
    eng.step()
    stats = eng.stats()
    # Each request: 5 prompt ids + 8 new = 13 positions -> 1 block.
    assert stats["blocks_live"] == 2
    eng.run()
    assert len(eng._free) == free0
    assert set(eng.results) == set(ids)


def test_admission_backpressure_then_progress():
    """Pool with room for ~one request at a time: the second request
    must wait (not crash, not corrupt), then complete after release."""
    # 37 ids + 28 new = 65 positions -> 3 blocks of 32; pool has 4.
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=32, n_blocks=5
    )
    prompts = ["p" * 36, "q" * 36]
    ids = [eng.submit(p, max_new_tokens=28) for p in prompts]
    eng.step()
    assert eng.stats()["active_slots"] == 1  # second is capacity-blocked
    assert eng.stats()["queued"] == 1
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt, n=28)


def test_never_admittable_request_raises():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=1, block_size=16, n_blocks=3
    )
    eng.submit("z" * 40, max_new_tokens=30)  # needs 5 blocks, pool has 2
    with pytest.raises(ValueError, match="blocks"):
        eng.run()


def test_stale_page_table_cannot_corrupt_reallocated_blocks():
    """An empty slot keeps decode-writing every step (parked lane).
    After release, its page table must point at the null block —
    otherwise it writes through freed blocks that the allocator has
    handed to a later request, corrupting that request's visible KV."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=3, block_size=16, n_blocks=10
    )
    # A and B finish quickly and release their blocks; D keeps the
    # engine stepping (parked lanes keep writing) with no queue to
    # refill slots 0/1.
    eng.submit("aaaa", max_new_tokens=4)
    eng.submit("bbbb", max_new_tokens=4)
    d = eng.submit("d" * 30, max_new_tokens=40)
    for _ in range(8):
        eng.step()
    assert eng.stats()["active_slots"] == 1
    # C takes the freed blocks while slots 0/1 sit empty with whatever
    # page tables they were left with.
    prompt_c = "c" * 40  # 41 ids + 24 new -> 5 blocks, spans A+B's old ones
    c = eng.submit(prompt_c, max_new_tokens=24)
    results = eng.run()
    assert results[c] == _single_stream(prompt_c, n=24)
    assert results[d] == _single_stream("d" * 30, n=40)


def test_capacity_blocked_request_prefills_once():
    """A capacity-blocked request must not re-run its prompt prefill on
    every decode step while it waits (review finding: _admit ingested
    before the block-capacity check and threw the row away)."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=32, n_blocks=5
    )
    calls = {"n": 0}
    real_ingest = eng._ingest.ingest_prompt

    def counting_ingest(prompt, prefix=None):
        calls["n"] += 1
        return real_ingest(prompt, prefix)

    eng._ingest.ingest_prompt = counting_ingest
    ids = [eng.submit("p" * 36, max_new_tokens=28) for _ in range(2)]
    results = eng.run()
    assert set(results) == set(ids)
    assert calls["n"] == 2  # one prefill per request, ever


def test_cancel_releases_blocks():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    free0 = len(eng._free)
    rid = eng.submit("some prompt", max_new_tokens=20)
    eng.step()
    assert len(eng._free) < free0
    eng.cancel(rid)
    assert len(eng._free) == free0


def test_capacity_win_vs_dense_reservation():
    """The measurable claim: at HALF the dense KV HBM, the paged pool
    admits the same 8-slot workload (live usage, not reservation,
    bounds memory) — and int8 halves it again."""
    slots, bs = 8, 16
    dense = kv_cache_bytes(CFG, slots)
    # Pool sized at half the dense reservation:
    n_blocks = 1 + (slots * (CFG.max_seq_len // bs)) // 2
    paged = paged_pool_bytes(CFG, n_blocks, bs)
    assert paged <= dense * 0.52  # half + the reserved null block
    paged_int8 = paged_pool_bytes(CFG, n_blocks, bs, kv_dtype="int8")
    # ~3.1x on the tiny config (head_dim 16 makes scale rows pricey);
    # ~3.8x at head_dim 128 (see test_kv_bytes_capacity_gain).
    assert dense / paged_int8 > 3.0

    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=slots, block_size=bs,
        n_blocks=n_blocks,
    )
    prompts = [f"request number {i}" for i in range(slots)]
    ids = [eng.submit(p, max_new_tokens=8) for p in prompts]
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt)


def test_pool_structure():
    state = init_paged_pool(CFG, 9, 16, 4)
    assert state["k"].shape == (CFG.n_layers, 9, 16, CFG.n_kv_heads, CFG.head_dim)
    assert state["page_table"].shape == (4, CFG.max_seq_len // 16)
    assert state["length"].shape == (4,)
    q = init_paged_pool(CFG, 9, 16, 4, kv_dtype="int8")
    assert q["k"]["q"].dtype == jnp.int8

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


def test_paged_with_prefix_cache():
    """Prefix-cached admission into the paged pool: the ingest engine's
    snapshot + suffix path feeds block injection unchanged."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    prefix = "system: terse answers. "
    rid = eng.submit("what is ttft?", max_new_tokens=8, prefix=prefix)
    results = eng.run()
    single = ServeEngine(cfg=CFG, params=PARAMS)
    expect = [
        e.token_id
        for e in single.generate("what is ttft?", max_new_tokens=8, prefix=prefix)
    ]
    assert results[rid] == expect


def test_misaligned_block_size_rejected():
    """max_seq_len not a block multiple would make the last prompt
    block's dynamic_slice clamp and copy a SHIFTED window (silent KV
    corruption) — the engine must refuse the config up front."""
    with pytest.raises(ValueError, match="multiple"):
        PagedBatchingEngine(
            cfg=CFG, params=PARAMS, max_slots=2, block_size=24
        )


def test_block_size_beyond_max_seq_len_rejected():
    with pytest.raises(ValueError, match="exceeds"):
        PagedBatchingEngine(
            cfg=CFG, params=PARAMS, max_slots=2,
            block_size=CFG.max_seq_len * 2,
        )


def test_moe_paged_engine_fails_fast_on_bad_geometry(monkeypatch):
    """The MoE subclass builds its (expensive) ingest engine before
    ``super().__init__``; the geometry check must come first so a bad
    block size never reaches param init / jit setup.  Ordering is
    asserted directly: the ingest factory must not be called."""
    from tpuslo.models.mixtral import MoEPagedBatchingEngine, mixtral_tiny

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("ingest built before geometry validation")

    monkeypatch.setattr(MoEPagedBatchingEngine, "_make_ingest", boom)
    with pytest.raises(ValueError, match="multiple"):
        MoEPagedBatchingEngine(
            cfg=mixtral_tiny(max_seq_len=96), block_size=64
        )


def test_parked_lane_past_table_width_writes_only_null_block():
    """Parked (released) lanes keep decoding — the batch is fixed
    shape — and their lengths keep climbing.  Once length walks past
    the page-table width (MB * block_size positions; unreachable from
    the engine API, whose requests die at max_seq_len, but inevitable
    for a lane parked across many drained requests), the block lookup
    must clamp to the zeroed table entry so every KV write still lands
    in the masked null block 0 — never in a live block, on any backend,
    regardless of the gather's out-of-bounds semantics."""
    import numpy as np

    from tpuslo.models.paged_kv import init_paged_pool, paged_decode_step

    bs = 16
    mb = CFG.max_seq_len // bs  # page-table width (8)
    state = init_paged_pool(CFG, n_blocks=5, block_size=bs, slots=2)
    # Both lanes parked: zeroed page tables, length 0 — the steady
    # state after their requests released.  Run well past MB * bs.
    token = jnp.zeros((2,), jnp.int32)
    steps = mb * bs + 12
    step = jax.jit(
        lambda p, t, s: paged_decode_step(p, t, s, CFG, bs),
        donate_argnums=(2,),
    )
    for _ in range(steps):
        logits, state = step(PARAMS, token, state)
    assert int(state["length"][0]) == steps  # clamp, not a freeze
    assert jnp.isfinite(logits).all()
    # Every write of every step hit null block 0: blocks 1..4 are
    # untouched (init_paged_pool zero-fills the pool).
    k = np.asarray(state["k"])
    assert np.abs(k[:, 1:]).max() == 0.0
    assert np.abs(k[:, 0]).max() > 0.0  # the writes really happened


# -- shared prefix blocks (block-granular copy-on-write) ----------------


def _single_prefix_stream(prompt, prefix, n=8, kv_dtype="bf16"):
    eng = ServeEngine(cfg=CFG, params=PARAMS, kv_dtype=kv_dtype)
    return [
        e.token_id
        for e in eng.generate(prompt, max_new_tokens=n, prefix=prefix)
    ]


def test_shared_prefix_token_parity():
    """Concurrent requests naming the same prefix share its full pool
    blocks — and still produce exactly the single-request streams,
    interleaved with a plain (no-prefix) request."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=3, block_size=16
    )
    prefix = "system: answer tersely and truthfully. "  # BOS + 39 bytes = 40 ids: 2 full blocks
    suffixes = ["what is ttft?", "define mfu", "name one tpu signal"]
    ids = [eng.submit(s, max_new_tokens=8, prefix=prefix) for s in suffixes]
    plain = eng.submit("no prefix here", max_new_tokens=8)
    results = eng.run()
    for rid, s in zip(ids, suffixes):
        assert results[rid] == _single_prefix_stream(s, prefix), s
    assert results[plain] == _single_stream("no prefix here")
    stats = eng.stats()
    assert stats["shared_prefix_blocks"] == 40 // 16
    assert stats["shared_prefixes"] == 1
    assert stats["prefix_reuse_hits"] >= 2  # 2nd and 3rd reused the KV


def test_shared_prefix_capacity_win():
    """The point of sharing: a pool that fits only ONE unshared request
    runs TWO concurrently once the prefix blocks are shared."""
    prefix = "P" * 31  # BOS + 31 bytes = 32 ids -> 2 full blocks of 16
    kwargs = dict(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, n_blocks=5
    )
    # 32 prefix + 8 suffix + 8 new = 48 positions -> 3 blocks plain,
    # 1 private with sharing; the 4-block pool fits 3 + 1 shared but
    # not 3 + 3 unshared.
    unshared = PagedBatchingEngine(**kwargs, share_prefixes=False)
    for s in ("aaaaaaaa", "bbbbbbbb"):
        unshared.submit(s, max_new_tokens=8, prefix=prefix)
    unshared.step()
    assert unshared.stats()["active_slots"] == 1  # capacity-blocked

    shared = PagedBatchingEngine(**kwargs)
    ids = [
        shared.submit(s, max_new_tokens=8, prefix=prefix)
        for s in ("aaaaaaaa", "bbbbbbbb")
    ]
    shared.step()
    assert shared.stats()["active_slots"] == 2
    results = shared.run()
    for rid, s in zip(ids, ("aaaaaaaa", "bbbbbbbb")):
        assert results[rid] == _single_prefix_stream(s, prefix), s
    # Both engines finish with identical streams either way.
    assert unshared.run()[0] == results[ids[0]]


def test_shared_prefix_warm_reuse_and_eviction():
    """Completed requests leave the prefix blocks warm (refs 0, still
    allocated); the next same-prefix request reuses them without a
    copy; admission pressure evicts idle prefixes LRU-first."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, n_blocks=8
    )
    free0 = len(eng._free)  # 7
    prefix = "S" * 32  # BOS + 32 bytes = 33 ids: 2 full blocks
    eng.submit("first", max_new_tokens=4, prefix=prefix)
    eng.run()
    # Private blocks returned; the 2 shared blocks stay warm.
    assert len(eng._free) == free0 - 2
    entry = eng._shared_prefixes[prefix]
    assert entry.refs == 0 and entry.populated
    hits0 = eng.prefix_reuse_hits
    eng.submit("second", max_new_tokens=4, prefix=prefix)
    eng.run()
    assert eng.prefix_reuse_hits == hits0 + 1
    # A request that needs more blocks than remain free forces the
    # idle prefix out and succeeds.
    big = eng.submit("z" * 60, max_new_tokens=40)  # 61+40=101 -> 7 blocks
    results = eng.run()
    assert results[big] == _single_stream("z" * 60, n=40)
    assert prefix not in eng._shared_prefixes
    assert len(eng._free) == free0


def test_shared_prefix_never_evicted_while_referenced():
    """A prefix with live references is pinned: a too-big request
    blocks (backpressure) instead of evicting mapped blocks."""
    prefix = "Q" * 32
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, n_blocks=6
    )
    # 33 prefix + 8 suffix + 24 new = 65 positions -> 5 blocks:
    # 2 shared + 3 private; fills the whole 5-block pool.
    a = eng.submit("aaaaaaaa", max_new_tokens=24, prefix=prefix)
    eng.step()
    assert eng.stats()["active_slots"] == 1
    b = eng.submit("y" * 40, max_new_tokens=24)  # 41+24=65 -> 5 blocks > 1 free
    eng.step()
    assert prefix in eng._shared_prefixes  # pinned, not evicted
    assert eng.stats()["queued"] == 1
    results = eng.run()
    assert results[a] == _single_prefix_stream("aaaaaaaa", prefix, n=24)
    assert results[b] == _single_stream("y" * 40, n=24)


def test_shared_prefix_two_prefixes_isolated():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    p1, p2 = "alpha " * 6, "omega " * 6  # BOS + 36 bytes = 37 ids each: 2 full blocks
    r1 = eng.submit("one", max_new_tokens=8, prefix=p1)
    r2 = eng.submit("two", max_new_tokens=8, prefix=p2)
    results = eng.run()
    assert results[r1] == _single_prefix_stream("one", p1)
    assert results[r2] == _single_prefix_stream("two", p2)
    assert eng.stats()["shared_prefixes"] == 2


def test_shared_prefix_int8_compose():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16,
        kv_dtype="int8",
    )
    prefix = "system: terse. " * 3  # BOS + 45 bytes = 46 ids: 2 full blocks
    ids = [
        eng.submit(s, max_new_tokens=8, prefix=prefix)
        for s in ("left", "right")
    ]
    results = eng.run()
    for rid, s in zip(ids, ("left", "right")):
        assert results[rid] == _single_prefix_stream(
            s, prefix, kv_dtype="int8"
        ), s
    assert eng.prefix_reuse_hits == 1


def test_eviction_never_victimizes_the_prefix_being_admitted():
    """Review regression: with two warm idle prefixes filling the pool,
    admitting against one of them must evict the OTHER — not the very
    prefix being reused (which would discard warm KV and, before the
    fix, could leave admission blocked at zero active slots, silently
    dropping the request)."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, n_blocks=5
    )
    p, q = "P" * 15, "Q" * 15  # 16 ids each -> 1 full block
    eng.submit("aaaaaaaa", max_new_tokens=8, prefix=p)
    eng.run()
    eng.submit("bbbbbbbb", max_new_tokens=8, prefix=q)
    eng.run()
    # Both prefixes warm (1 block each), 2 of 4 pool blocks free.
    assert len(eng._free) == 2
    assert p in eng._shared_prefixes and q in eng._shared_prefixes
    hits0 = eng.prefix_reuse_hits
    # 16 prefix + 20 suffix + 28 new = 64 positions -> 4 blocks:
    # 1 shared + 3 private; private_need 3 > 2 free, so eviction must
    # run — and must pick q, not the p it is admitting against.
    rid = eng.submit("c" * 20, max_new_tokens=28, prefix=p)
    results = eng.run()
    assert results[rid] == _single_prefix_stream("c" * 20, p, n=28)
    assert eng.prefix_reuse_hits == hits0 + 1  # p's KV was NOT discarded
    assert p in eng._shared_prefixes and q not in eng._shared_prefixes


def test_never_admittable_raises_even_with_warm_share():
    """plain_need > pool is never admittable regardless of sharing —
    the shared blocks occupy the pool too.  Must raise, not hang or
    silently drop."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, n_blocks=5
    )
    prefix = "R" * 31  # 2 full blocks
    eng.submit("warm", max_new_tokens=4, prefix=prefix)
    eng.run()
    assert prefix in eng._shared_prefixes
    # 32 + 9 + 40 = 81 positions -> 6 blocks > the 4-block pool.
    eng.submit("overflow", max_new_tokens=40, prefix=prefix)
    with pytest.raises(ValueError, match="blocks"):
        eng.run()
