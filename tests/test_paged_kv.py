"""Paged KV cache: parity with dense serving, block accounting,
admission backpressure, and the capacity win the paging exists for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from tpuslo.models.llama import (
    init_params,
    kv_cache_bytes,
    llama_tiny,
)
from tpuslo.models.paged_kv import (
    PagedBatchingEngine,
    init_paged_pool,
    paged_pool_bytes,
)
from tpuslo.models.serve import ServeEngine


CFG = llama_tiny(max_seq_len=128)
PARAMS = init_params(jax.random.PRNGKey(0), CFG)


def _single_stream(prompt, n=8, kv_dtype="bf16"):
    eng = ServeEngine(cfg=CFG, params=PARAMS, kv_dtype=kv_dtype)
    return [e.token_id for e in eng.generate(prompt, max_new_tokens=n)]


def test_paged_matches_single_request_serving():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    prompts = ["hello world", "a much longer second prompt here", "third"]
    ids = [eng.submit(p, max_new_tokens=8) for p in prompts]
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt), prompt


def test_paged_generation_crosses_block_boundaries():
    """Prompt of 20 ids with block_size 16 spans two blocks; 24 new
    tokens cross two more boundaries — output must still match the
    dense engine exactly."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=1, block_size=16
    )
    prompt = "x" * 19  # + BOS = 20 ids
    rid = eng.submit(prompt, max_new_tokens=24)
    results = eng.run()
    assert results[rid] == _single_stream(prompt, n=24)


def test_paged_int8_compose():
    """int8 representation + paging stack: parity against the int8
    single-request engine (same quantized write discipline)."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, kv_dtype="int8"
    )
    prompts = ["alpha", "beta prompt"]
    ids = [eng.submit(p, max_new_tokens=8) for p in prompts]
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt, kv_dtype="int8")


def test_block_accounting_and_release():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16, n_blocks=9
    )
    free0 = len(eng._free)
    assert free0 == 8
    ids = [eng.submit("abcd", max_new_tokens=8) for _ in range(2)]
    eng.step()
    stats = eng.stats()
    # Each request: 5 prompt ids + 8 new = 13 positions -> 1 block.
    assert stats["blocks_live"] == 2
    eng.run()
    assert len(eng._free) == free0
    assert set(eng.results) == set(ids)


def test_admission_backpressure_then_progress():
    """Pool with room for ~one request at a time: the second request
    must wait (not crash, not corrupt), then complete after release."""
    # 37 ids + 28 new = 65 positions -> 3 blocks of 32; pool has 4.
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=32, n_blocks=5
    )
    prompts = ["p" * 36, "q" * 36]
    ids = [eng.submit(p, max_new_tokens=28) for p in prompts]
    eng.step()
    assert eng.stats()["active_slots"] == 1  # second is capacity-blocked
    assert eng.stats()["queued"] == 1
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt, n=28)


def test_never_admittable_request_raises():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=1, block_size=16, n_blocks=3
    )
    eng.submit("z" * 40, max_new_tokens=30)  # needs 5 blocks, pool has 2
    with pytest.raises(ValueError, match="blocks"):
        eng.run()


def test_stale_page_table_cannot_corrupt_reallocated_blocks():
    """An empty slot keeps decode-writing every step (parked lane).
    After release, its page table must point at the null block —
    otherwise it writes through freed blocks that the allocator has
    handed to a later request, corrupting that request's visible KV."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=3, block_size=16, n_blocks=10
    )
    # A and B finish quickly and release their blocks; D keeps the
    # engine stepping (parked lanes keep writing) with no queue to
    # refill slots 0/1.
    eng.submit("aaaa", max_new_tokens=4)
    eng.submit("bbbb", max_new_tokens=4)
    d = eng.submit("d" * 30, max_new_tokens=40)
    for _ in range(8):
        eng.step()
    assert eng.stats()["active_slots"] == 1
    # C takes the freed blocks while slots 0/1 sit empty with whatever
    # page tables they were left with.
    prompt_c = "c" * 40  # 41 ids + 24 new -> 5 blocks, spans A+B's old ones
    c = eng.submit(prompt_c, max_new_tokens=24)
    results = eng.run()
    assert results[c] == _single_stream(prompt_c, n=24)
    assert results[d] == _single_stream("d" * 30, n=40)


def test_capacity_blocked_request_prefills_once():
    """A capacity-blocked request must not re-run its prompt prefill on
    every decode step while it waits (review finding: _admit ingested
    before the block-capacity check and threw the row away)."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=32, n_blocks=5
    )
    calls = {"n": 0}
    real_ingest = eng._ingest.ingest_prompt

    def counting_ingest(prompt, prefix=None):
        calls["n"] += 1
        return real_ingest(prompt, prefix)

    eng._ingest.ingest_prompt = counting_ingest
    ids = [eng.submit("p" * 36, max_new_tokens=28) for _ in range(2)]
    results = eng.run()
    assert set(results) == set(ids)
    assert calls["n"] == 2  # one prefill per request, ever


def test_cancel_releases_blocks():
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    free0 = len(eng._free)
    rid = eng.submit("some prompt", max_new_tokens=20)
    eng.step()
    assert len(eng._free) < free0
    eng.cancel(rid)
    assert len(eng._free) == free0


def test_capacity_win_vs_dense_reservation():
    """The measurable claim: at HALF the dense KV HBM, the paged pool
    admits the same 8-slot workload (live usage, not reservation,
    bounds memory) — and int8 halves it again."""
    slots, bs = 8, 16
    dense = kv_cache_bytes(CFG, slots)
    # Pool sized at half the dense reservation:
    n_blocks = 1 + (slots * (CFG.max_seq_len // bs)) // 2
    paged = paged_pool_bytes(CFG, n_blocks, bs)
    assert paged <= dense * 0.52  # half + the reserved null block
    paged_int8 = paged_pool_bytes(CFG, n_blocks, bs, kv_dtype="int8")
    # ~3.1x on the tiny config (head_dim 16 makes scale rows pricey);
    # ~3.8x at head_dim 128 (see test_kv_bytes_capacity_gain).
    assert dense / paged_int8 > 3.0

    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=slots, block_size=bs,
        n_blocks=n_blocks,
    )
    prompts = [f"request number {i}" for i in range(slots)]
    ids = [eng.submit(p, max_new_tokens=8) for p in prompts]
    results = eng.run()
    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _single_stream(prompt)


def test_pool_structure():
    state = init_paged_pool(CFG, 9, 16, 4)
    assert state["k"].shape == (CFG.n_layers, 9, 16, CFG.n_kv_heads, CFG.head_dim)
    assert state["page_table"].shape == (4, CFG.max_seq_len // 16)
    assert state["length"].shape == (4,)
    q = init_paged_pool(CFG, 9, 16, 4, kv_dtype="int8")
    assert q["k"]["q"].dtype == jnp.int8

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


def test_paged_with_prefix_cache():
    """Prefix-cached admission into the paged pool: the ingest engine's
    snapshot + suffix path feeds block injection unchanged."""
    eng = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    prefix = "system: terse answers. "
    rid = eng.submit("what is ttft?", max_new_tokens=8, prefix=prefix)
    results = eng.run()
    single = ServeEngine(cfg=CFG, params=PARAMS)
    expect = [
        e.token_id
        for e in single.generate("what is ttft?", max_new_tokens=8, prefix=prefix)
    ]
    assert results[rid] == expect


def test_misaligned_block_size_rejected():
    """max_seq_len not a block multiple would make the last prompt
    block's dynamic_slice clamp and copy a SHIFTED window (silent KV
    corruption) — the engine must refuse the config up front."""
    with pytest.raises(ValueError, match="multiple"):
        PagedBatchingEngine(
            cfg=CFG, params=PARAMS, max_slots=2, block_size=24
        )


def test_block_size_beyond_max_seq_len_rejected():
    with pytest.raises(ValueError, match="exceeds"):
        PagedBatchingEngine(
            cfg=CFG, params=PARAMS, max_slots=2,
            block_size=CFG.max_seq_len * 2,
        )


def test_parked_lane_past_table_width_writes_only_null_block():
    """Parked (released) lanes keep decoding — the batch is fixed
    shape — and their lengths keep climbing.  Once length walks past
    the page-table width (MB * block_size positions; unreachable from
    the engine API, whose requests die at max_seq_len, but inevitable
    for a lane parked across many drained requests), the block lookup
    must clamp to the zeroed table entry so every KV write still lands
    in the masked null block 0 — never in a live block, on any backend,
    regardless of the gather's out-of-bounds semantics."""
    import numpy as np

    from tpuslo.models.paged_kv import init_paged_pool, paged_decode_step

    bs = 16
    mb = CFG.max_seq_len // bs  # page-table width (8)
    state = init_paged_pool(CFG, n_blocks=5, block_size=bs, slots=2)
    # Both lanes parked: zeroed page tables, length 0 — the steady
    # state after their requests released.  Run well past MB * bs.
    token = jnp.zeros((2,), jnp.int32)
    steps = mb * bs + 12
    step = jax.jit(
        lambda p, t, s: paged_decode_step(p, t, s, CFG, bs),
        donate_argnums=(2,),
    )
    for _ in range(steps):
        logits, state = step(PARAMS, token, state)
    assert int(state["length"][0]) == steps  # clamp, not a freeze
    assert jnp.isfinite(logits).all()
    # Every write of every step hit null block 0: blocks 1..4 are
    # untouched (init_paged_pool zero-fills the pool).
    k = np.asarray(state["k"])
    assert np.abs(k[:, 1:]).max() == 0.0
    assert np.abs(k[:, 0]).max() > 0.0  # the writes really happened
