"""Trainer: loss descent, checkpointed resume is bit-exact."""

import pytest
import numpy as np

from tpuslo.models.llama import llama_tiny
from tpuslo.models.trainer import TrainerConfig, train
from tpuslo.parallel.mesh import MeshPlan, make_mesh

CORPUS = [
    f"sample {i}: pack my box with five dozen liquor jugs" for i in range(60)
]


def _mesh():
    return make_mesh(MeshPlan(dp=2, fsdp=2, tp=2))


def test_train_descends():
    cfg = llama_tiny(max_seq_len=64)
    result = train(
        cfg, _mesh(), CORPUS, TrainerConfig(steps=5, batch=4, seq_len=32)
    )
    assert result["first_step"] == 0 and result["last_step"] == 5
    losses = result["losses"]
    assert len(losses) == 5
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_resume_matches_uninterrupted(tmp_path):
    cfg = llama_tiny(max_seq_len=64)
    tcfg = dict(batch=4, seq_len=32, seed=3)

    # Uninterrupted 6-step run.
    full = train(
        cfg, _mesh(), CORPUS, TrainerConfig(steps=6, **tcfg)
    )["losses"]

    # Interrupted: 3 steps with checkpointing, then resume to 6.
    ckpt_dir = str(tmp_path / "ckpts")
    first = train(
        cfg, _mesh(), CORPUS,
        TrainerConfig(steps=3, ckpt_every=3, **tcfg),
        checkpoint_dir=ckpt_dir,
    )
    assert first["last_step"] == 3
    second = train(
        cfg, _mesh(), CORPUS,
        TrainerConfig(steps=6, ckpt_every=3, **tcfg),
        checkpoint_dir=ckpt_dir,
    )
    assert second["first_step"] == 3 and second["last_step"] == 6

    resumed = first["losses"] + second["losses"]
    np.testing.assert_allclose(resumed, full, rtol=1e-5, atol=1e-6)

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


def test_multi_slice_plan_matches_single_slice_loss():
    """dcn=2 x (fsdp=2, tp=2) over 8 devices: params replicate across
    slices, the batch splits over dcn, and one train step produces the
    same loss as the single-slice dp=2 plan on the same global batch —
    the cross-slice gradient psum is the only DCN collective."""
    import jax
    import jax.numpy as jnp

    from tpuslo.models.llama import llama_tiny
    from tpuslo.models.train import build_sharded_train_step

    # Same cfg + batch avals as __graft_entry__.dryrun_multichip's
    # baseline and multi-slice cells: both plans' train-step compiles
    # are shared through the memoized builder with the dryrun test.
    cfg = llama_tiny(max_seq_len=64)
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (8, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    losses = []
    for plan in (
        MeshPlan(dp=2, fsdp=2, tp=2),
        MeshPlan(dcn=2, dp=1, fsdp=2, tp=2),
    ):
        mesh = make_mesh(plan)
        step, init = build_sharded_train_step(mesh, cfg)
        params, opt = init(rng)
        _, _, loss = step(params, opt, tokens, targets)
        losses.append(float(loss))
    assert abs(losses[0] - losses[1]) < 2e-2, losses


def test_moe_family_trains_and_resumes_bit_exact(tmp_path):
    """The trainer is family-agnostic through step_builder: the MoE
    dp x ep builder trains, checkpoints, and resumes to the identical
    loss curve (the same contract the llama path promises)."""
    import jax
    from jax.sharding import Mesh

    from tpuslo.models import mixtral

    cfg = mixtral.mixtral_tiny(max_seq_len=64)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "ep"))
    tcfg = dict(batch=4, seq_len=32, seed=3)
    kw = dict(step_builder=mixtral.build_moe_train_step)

    full = train(
        cfg, mesh, CORPUS, TrainerConfig(steps=4, **tcfg), **kw
    )["losses"]
    assert full[-1] < full[0]  # descends

    ckpt_dir = str(tmp_path / "moe-ckpts")
    first = train(
        cfg, mesh, CORPUS, TrainerConfig(steps=2, ckpt_every=2, **tcfg),
        checkpoint_dir=ckpt_dir, **kw,
    )
    second = train(
        cfg, mesh, CORPUS, TrainerConfig(steps=4, ckpt_every=2, **tcfg),
        checkpoint_dir=ckpt_dir, **kw,
    )
    assert second["first_step"] == 2 and second["last_step"] == 4
    np.testing.assert_allclose(
        first["losses"] + second["losses"], full, rtol=1e-5, atol=1e-6
    )
