"""Metrics HTTP server tests: /metrics //healthz //readyz status codes,
content types, concurrent scrapes, and the readiness aggregation —
previously the server shipped untested and /readyz returned an
unconditional 200."""

import threading
import urllib.error
import urllib.request

import pytest

from tpuslo.metrics import AgentMetrics, Readiness, start_metrics_server


@pytest.fixture
def server_env():
    metrics = AgentMetrics()
    readiness = Readiness()
    server = start_metrics_server(
        metrics, 0, host="127.0.0.1", readiness=readiness
    )
    port = server.server_address[1]
    yield metrics, readiness, f"http://127.0.0.1:{port}"
    server.shutdown()
    server.server_close()


def fetch(url: str):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as exc:
        return exc.code, dict(exc.headers), exc.read()


class TestEndpoints:
    def test_metrics_endpoint(self, server_env):
        metrics, _, base = server_env
        metrics.up.set(1)
        status, headers, body = fetch(base + "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert b"llm_slo_agent_up 1.0" in body
        assert b"llm_slo_agent_cycle_stage_ms" in body

    def test_healthz(self, server_env):
        _, _, base = server_env
        status, headers, body = fetch(base + "/healthz")
        assert status == 200
        assert body == b"ok\n"
        assert headers["Content-Type"].startswith("text/plain")

    def test_readyz_ok_with_no_checks(self, server_env):
        _, _, base = server_env
        status, _, body = fetch(base + "/readyz")
        assert status == 200
        assert body == b"ok\n"

    def test_readyz_without_readiness_object_stays_200(self):
        metrics = AgentMetrics()
        server = start_metrics_server(metrics, 0, host="127.0.0.1")
        try:
            port = server.server_address[1]
            status, _, body = fetch(f"http://127.0.0.1:{port}/readyz")
            assert status == 200 and body == b"ok\n"
        finally:
            server.shutdown()
            server.server_close()

    def test_unknown_path_404(self, server_env):
        _, _, base = server_env
        status, _, _ = fetch(base + "/nope")
        assert status == 404


class TestReadiness:
    def test_failing_check_returns_503_with_reason(self, server_env):
        _, readiness, base = server_env
        state = {"draining": True}
        readiness.add_check(
            "drain",
            lambda: (not state["draining"], "drain in progress"),
        )
        status, _, body = fetch(base + "/readyz")
        assert status == 503
        assert b"drain: drain in progress" in body
        # Recovery flips it back without restarting the server.
        state["draining"] = False
        status, _, body = fetch(base + "/readyz")
        assert status == 200 and body == b"ok\n"

    def test_multiple_failures_all_reported(self, server_env):
        _, readiness, base = server_env
        readiness.add_check("breakers", lambda: (False, "all open"))
        readiness.add_check("snapshot", lambda: (False, "stale (400s)"))
        status, _, body = fetch(base + "/readyz")
        assert status == 503
        assert b"breakers: all open" in body
        assert b"snapshot: stale (400s)" in body

    def test_raising_check_is_not_ready(self, server_env):
        _, readiness, base = server_env

        def broken():
            raise RuntimeError("boom")

        readiness.add_check("broken", broken)
        status, _, body = fetch(base + "/readyz")
        assert status == 503
        assert b"broken: check raised" in body

    def test_evaluate_directly(self):
        readiness = Readiness()
        assert readiness.evaluate() == (True, "ok")
        readiness.add_check("a", lambda: (True, "ok"))
        readiness.add_check("b", lambda: (False, "nope"))
        ready, reason = readiness.evaluate()
        assert not ready and reason == "b: nope"


class TestConcurrentScrapes:
    def test_parallel_scrapes_all_succeed(self, server_env):
        metrics, _, base = server_env
        metrics.up.set(1)
        results: list[int] = []
        errors: list[Exception] = []
        lock = threading.Lock()

        def scrape():
            try:
                for _ in range(5):
                    status, _, body = fetch(base + "/metrics")
                    with lock:
                        results.append(status)
                    assert b"llm_slo_agent_up" in body
            except Exception as exc:  # noqa: BLE001
                with lock:
                    errors.append(exc)

        threads = [threading.Thread(target=scrape) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        assert len(results) == 40
        assert set(results) == {200}


class TestStageQuantiles:
    def test_quantiles_from_histogram_buckets(self):
        metrics = AgentMetrics()
        # 100 observations at ~2ms, 1 at ~40ms: p50 in the (1, 2.5]
        # bucket, p99 well above it.
        for _ in range(100):
            metrics.cycle_stage_ms.labels(stage="generate").observe(2.0)
        metrics.cycle_stage_ms.labels(stage="generate").observe(40.0)
        est = metrics.stage_quantiles()
        assert "generate" in est
        gen = est["generate"]
        assert gen["count"] == 101
        assert 1.0 <= gen["p50"] <= 2.5
        assert gen["p99"] > gen["p50"]

    def test_empty_histograms_yield_nothing(self):
        assert AgentMetrics().stage_quantiles() == {}

    def test_mark_cycle_with_duration_feeds_cycle_histogram(self):
        metrics = AgentMetrics()
        metrics.mark_cycle(duration_ms=12.5)
        samples = {
            s.name: s.value
            for m in metrics.cycle_ms.collect()
            for s in m.samples
        }
        assert samples["llm_slo_agent_cycle_ms_count"] == 1
        assert samples["llm_slo_agent_cycle_ms_sum"] == 12.5


class TestSLOBurnSeries:
    """The burn engine's slo_* series: registration, observer bridge,
    and a real scrape carrying every series the error-budget dashboard
    references."""

    def test_slo_series_registered_on_agent_metrics(self):
        metrics = AgentMetrics()
        for attr in (
            "slo_request_outcomes",
            "slo_budget_remaining",
            "slo_burn_rate",
            "slo_alert_state",
            "slo_alert_transitions",
        ):
            assert hasattr(metrics, attr)

    def test_observer_bridges_engine_callbacks(self):
        metrics = AgentMetrics()
        observer = metrics.slo_observer()
        observer.outcome("gold", "ok")
        observer.outcome("gold", "ok")
        observer.outcome("gold", "error")
        observer.burn_rate("gold", "availability", "5m", 16.2)
        observer.budget_remaining("gold", "availability", 0.25)
        observer.alert_state("gold", "availability", 2)
        observer.transition("gold", "availability", "page")
        samples = {
            (s.name, tuple(sorted(s.labels.items()))): s.value
            for metric in metrics.registry.collect()
            for s in metric.samples
        }
        assert samples[(
            "llm_slo_agent_slo_request_outcomes_total",
            (("status", "ok"), ("tenant", "gold")),
        )] == 2
        assert samples[(
            "llm_slo_agent_slo_burn_rate",
            (("objective", "availability"), ("tenant", "gold"),
             ("window", "5m")),
        )] == 16.2
        assert samples[(
            "llm_slo_agent_slo_budget_remaining",
            (("objective", "availability"), ("tenant", "gold")),
        )] == 0.25
        assert samples[(
            "llm_slo_agent_slo_alert_state",
            (("objective", "availability"), ("tenant", "gold")),
        )] == 2
        assert samples[(
            "llm_slo_agent_slo_alert_transitions_total",
            (("objective", "availability"), ("severity", "page"),
             ("tenant", "gold")),
        )] == 1

    def test_scrape_exposes_burn_series(self, server_env):
        metrics, _, base = server_env
        from tpuslo.sloengine import (
            BurnEngine,
            EngineConfig,
            RequestOutcome,
        )

        engine = BurnEngine(
            EngineConfig(), observer=metrics.slo_observer()
        )
        t0 = 1_700_000_000
        for i in range(720):
            engine.record(
                RequestOutcome(
                    tenant="gold",
                    ts_unix_nano=(t0 + i * 5) * 1_000_000_000,
                    ttft_ms=100.0,
                    tpot_ms=30.0,
                    tokens=64,
                    status="error",
                )
            )
        engine.evaluate(t0 + 3600)
        status, _, body = fetch(base + "/metrics")
        assert status == 200
        text = body.decode()
        for series in (
            'llm_slo_agent_slo_request_outcomes_total{status="error",tenant="gold"}',
            'llm_slo_agent_slo_budget_remaining{objective="availability",tenant="gold"}',
            'llm_slo_agent_slo_burn_rate{objective="availability",tenant="gold",window="1h"}',
            'llm_slo_agent_slo_alert_state{objective="availability",tenant="gold"} 2.0',
            'llm_slo_agent_slo_alert_transitions_total{objective="availability",severity="page",tenant="gold"}',
        ):
            assert series in text, series
