"""tpulint v2 framework: suppressions, baseline, CLI, self-hosting.

Rule-specific fixtures live in tests/test_analysis_rules.py; this file
covers the machinery every rule rides on.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

from tpuslo.analysis import (
    Baseline,
    Finding,
    run_analysis,
)
from tpuslo.analysis.__main__ import main as lint_main
from tpuslo.analysis.rules_style import StyleRules

REPO = Path(__file__).resolve().parent.parent


def _write(tmp_path: Path, rel: str, source: str) -> Path:
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source, encoding="utf-8")
    return path


class TestSuppression:
    def test_inline_disable_suppresses_only_that_code(self, tmp_path):
        _write(
            tmp_path,
            "pkg/mod.py",
            "import os  # tpulint: disable=TPL001\n"
            "import sys\n",
        )
        result = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        assert [f.code for f in result.findings] == ["TPL001"]
        assert "sys" in result.findings[0].message
        assert result.suppressed == 1

    def test_disable_on_preceding_line(self, tmp_path):
        _write(
            tmp_path,
            "pkg/mod.py",
            "def f(x):\n"
            "    # tpulint: disable=TPL006\n"
            "    return x == None\n",
        )
        result = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        assert result.findings == []
        assert result.suppressed == 1

    def test_file_level_disable(self, tmp_path):
        _write(
            tmp_path,
            "pkg/mod.py",
            "# tpulint: disable-file=TPL001\n"
            "import os\n"
            "import sys\n",
        )
        result = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        assert result.findings == []
        assert result.suppressed == 2

    def test_unrelated_code_not_suppressed(self, tmp_path):
        _write(
            tmp_path,
            "pkg/mod.py",
            "import os  # tpulint: disable=TPL999\n",
        )
        result = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        assert [f.code for f in result.findings] == ["TPL001"]


class TestBaseline:
    def test_round_trip_zero_delta(self, tmp_path):
        """write-baseline then re-run: everything baselined, exit 0."""
        _write(tmp_path, "pkg/mod.py", "import os\nx = 1 == None\n")
        result = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        assert len(result.findings) == 2

        baseline_path = tmp_path / "baseline.json"
        Baseline.from_findings(result.findings).save(baseline_path)
        loaded = Baseline.load(baseline_path)
        new, baselined, stale = loaded.split(result.findings)
        assert new == []
        assert len(baselined) == 2
        assert stale == []
        # Every generated entry demands a justification.
        raw = json.loads(baseline_path.read_text())
        assert all(e["reason"] for e in raw["entries"])

    def test_new_finding_escapes_baseline(self, tmp_path):
        _write(tmp_path, "pkg/mod.py", "import os\n")
        first = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        baseline = Baseline.from_findings(first.findings)

        _write(tmp_path, "pkg/mod.py", "import os\nimport sys\n")
        second = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        new, baselined, stale = baseline.split(second.findings)
        assert [f.message for f in new] == ["unused import 'sys'"]
        assert len(baselined) == 1

    def test_stale_entries_reported(self, tmp_path):
        baseline = Baseline(
            entries=[
                {
                    "path": "pkg/gone.py",
                    "code": "TPL001",
                    "message": "unused import 'os'",
                    "reason": "historical",
                }
            ]
        )
        new, baselined, stale = baseline.split([])
        assert new == [] and baselined == []
        assert len(stale) == 1

    def test_line_shift_does_not_invalidate_baseline(self, tmp_path):
        _write(tmp_path, "pkg/mod.py", "import os\n")
        first = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        baseline = Baseline.from_findings(first.findings)
        # Same finding, three lines lower.
        _write(tmp_path, "pkg/mod.py", "'''doc'''\n\n\nimport os\n")
        second = run_analysis(tmp_path, paths=["pkg"], rules=[StyleRules()])
        new, baselined, _ = baseline.split(second.findings)
        assert new == []
        assert len(baselined) == 1


class TestCLI:
    def test_clean_tree_exits_zero(self, tmp_path, capsys):
        _write(tmp_path, "pkg/mod.py", "import os\n\nprint(os.name)\n")
        rc = lint_main(["--root", str(tmp_path), "pkg", "--no-baseline"])
        assert rc == 0

    def test_findings_exit_one_with_human_output(self, tmp_path, capsys):
        _write(tmp_path, "pkg/mod.py", "import os\n")
        rc = lint_main(["--root", str(tmp_path), "pkg", "--no-baseline"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "pkg/mod.py:1: TPL001" in out

    def test_json_output(self, tmp_path, capsys):
        _write(tmp_path, "pkg/mod.py", "import os\n")
        rc = lint_main(
            ["--root", str(tmp_path), "pkg", "--no-baseline", "--json"]
        )
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"][0]["code"] == "TPL001"
        assert payload["files_scanned"] == 1

    def test_write_baseline_then_gate_is_clean(self, tmp_path, capsys):
        _write(tmp_path, "pkg/mod.py", "import os\n")
        baseline = tmp_path / "bl.json"
        assert (
            lint_main(
                [
                    "--root", str(tmp_path), "pkg",
                    "--baseline", str(baseline), "--write-baseline",
                ]
            )
            == 0
        )
        assert (
            lint_main(
                ["--root", str(tmp_path), "pkg", "--baseline", str(baseline)]
            )
            == 0
        )

    def test_zero_files_scanned_fails_closed(self, tmp_path, capsys):
        """Running from a wrong root must not report a green gate."""
        rc = lint_main(["--root", str(tmp_path), "nonexistent-dir"])
        assert rc == 2
        assert "refusing" in capsys.readouterr().err

    def test_write_baseline_preserves_reasons(self, tmp_path, capsys):
        _write(tmp_path, "pkg/mod.py", "import os\n")
        baseline = tmp_path / "bl.json"
        lint_main(
            [
                "--root", str(tmp_path), "pkg",
                "--baseline", str(baseline), "--write-baseline",
            ]
        )
        raw = json.loads(baseline.read_text())
        raw["entries"][0]["reason"] = "vendored shim, import is the API"
        baseline.write_text(json.dumps(raw))
        lint_main(
            [
                "--root", str(tmp_path), "pkg",
                "--baseline", str(baseline), "--write-baseline",
            ]
        )
        raw = json.loads(baseline.read_text())
        assert raw["entries"][0]["reason"] == (
            "vendored shim, import is the API"
        )

    def test_list_rules_covers_semantic_families(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for code in (
            "TPL001", "TPL101", "TPL102", "TPL110", "TPL111",
            "TPL120", "TPL121", "TPL130", "TPL140", "TPL150",
        ):
            assert code in out

    def test_syntax_error_is_tpl000(self, tmp_path, capsys):
        _write(tmp_path, "pkg/mod.py", "def broken(:\n")
        rc = lint_main(["--root", str(tmp_path), "pkg", "--no-baseline"])
        assert rc == 1
        assert "TPL000" in capsys.readouterr().out


class TestScopedRuns:
    """Git-scoped runs (--changed) must still run repo-contract rules
    over their anchor files — a schema edit with no .py change in the
    diff cannot sneak past `make lint-changed`."""

    def test_contract_rules_run_with_scoped_file_set(self, tmp_path):
        from tpuslo.analysis.rules_contracts import SchemaDriftRule

        # Mirror the repo layout in tmp: contracts + a drifted types.py
        # (one ProbeEventV1 field removed), but scope the run to an
        # UNRELATED changed file.
        contracts_src = REPO / "tpuslo" / "schema" / "contracts"
        contracts_dst = tmp_path / "tpuslo" / "schema" / "contracts"
        import shutil

        shutil.copytree(contracts_src, contracts_dst)
        types_src = (REPO / "tpuslo" / "schema" / "types.py").read_text(
            encoding="utf-8"
        )
        _write(
            tmp_path,
            "tpuslo/schema/types.py",
            types_src.replace("    ts_unix_nano: int\n", "", 1),
        )
        unrelated = _write(tmp_path, "tpuslo/other.py", "X = 1\n")

        result = run_analysis(
            tmp_path, files=[unrelated], rules=[SchemaDriftRule()]
        )
        assert any(
            f.code == "TPL101" and "ts_unix_nano" in f.message
            for f in result.findings
        ), result.findings

    def test_anchor_file_suppressions_honored_in_scoped_run(self, tmp_path):
        from tpuslo.analysis.rules_contracts import MetricsDriftRule

        _write(
            tmp_path,
            "tpuslo/metrics/registry.py",
            '# tpulint: disable-file=TPL150\n'
            'NAME = "llm_slo_agent_never_documented_total"\n',
        )
        unrelated = _write(tmp_path, "tpuslo/other.py", "X = 1\n")
        result = run_analysis(
            tmp_path, files=[unrelated], rules=[MetricsDriftRule()]
        )
        assert result.findings == []
        assert result.suppressed == 1

    def test_missing_manifest_file_is_a_finding(self, tmp_path):
        """A deleted/renamed hot-path module must surface as a finding,
        not silently drop the protection.  (The manifest marker makes
        tmp_path count as the governed repo.)"""
        from tpuslo.analysis.rules_hotpath import HotPathPurityRule

        _write(tmp_path, "tpuslo/analysis/hotpaths.py", "# manifest\n")
        result = run_analysis(
            tmp_path,
            files=[_write(tmp_path, "tpuslo/other.py", "X = 1\n")],
            rules=[HotPathPurityRule()],
        )
        assert any(
            f.code == "TPL120" and "missing or unparseable" in f.message
            for f in result.findings
        ), result.findings


class TestSelfHost:
    def test_repo_is_clean_against_committed_baseline(self):
        """`make lint` parity: the committed tree has zero non-baselined
        findings — the analyzer gates the repo that contains it."""
        result = run_analysis(REPO)
        baseline = Baseline.load(REPO / ".tpulint-baseline.json")
        new, _, stale = baseline.split(result.findings)
        assert new == [], "\n".join(f.render() for f in new)
        assert stale == [], f"stale baseline entries: {stale}"

    def test_full_run_under_bench_budget(self):
        """The bench.py gate (< 30 s) with slack for a loaded CI box —
        the lint gate only stays mandatory while it stays cheap."""
        t0 = time.perf_counter()
        run_analysis(REPO)
        assert time.perf_counter() - t0 < 30.0

    def test_finding_render_and_fingerprint(self):
        f = Finding("a/b.py", 3, "TPL001", "unused import 'os'")
        assert f.render() == "a/b.py:3: TPL001 unused import 'os'"
        assert f.fingerprint() == ("a/b.py", "TPL001", "unused import 'os'")
