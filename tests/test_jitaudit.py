"""jitaudit: the dynamic half of the TPL160s trace-discipline family.

Three layers, mirroring test_racecheck.py's structure:

* registry units driven directly (sections, steady-state violation
  recording, counters) — provoked churn never touches the global
  install's registry;
* a deterministic planted shape-churning loop (a fresh ``jax.jit`` per
  chunk length — the literal BENCH_r05 defect) that the installed
  auditor must catch;
* the real serving lanes: a warmed SpeculativeEngine/ServeEngine pair
  re-run under the auditor must show ZERO steady-state compiles and
  per-function compile attribution for the fused round kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from tpuslo.analysis import jitaudit
from tpuslo.analysis.jitaudit import JitAuditRegistry


class TestRegistryUnits:
    def test_steady_backend_compile_is_violation(self):
        reg = JitAuditRegistry()
        with reg.steady("decode"):
            reg.on_compile("backend_compile", 12.0)
        assert len(reg.violations) == 1
        assert "decode" in reg.violations[0].render()
        assert reg.steady_compile_count() == 1

    def test_non_steady_compile_is_not_violation(self):
        reg = JitAuditRegistry()
        with reg.section("warmup"):
            reg.on_compile("backend_compile", 12.0)
        reg.on_compile("backend_compile", 5.0)  # outside any section
        assert reg.violations == []
        assert reg.compile_count() == 2
        assert reg.steady_compile_count() == 0

    def test_trace_in_steady_is_counted_not_violation(self):
        # A jaxpr retrace that hits the lowering cache costs host time
        # but no XLA compile; it is recorded for diagnostics only.
        reg = JitAuditRegistry()
        with reg.steady("decode"):
            reg.on_compile("trace", 1.0)
        assert reg.violations == []
        assert reg.compile_count("trace") == 1

    def test_sections_nest_and_attribute_innermost(self):
        reg = JitAuditRegistry()
        with reg.section("outer"):
            with reg.steady("inner"):
                reg.on_compile("backend_compile", 1.0)
                reg.on_host_read()
            reg.on_host_read()
        assert reg.violations[0].section == "inner"
        assert reg.host_reads == {"inner": 1, "outer": 1}

    def test_sections_are_thread_local(self):
        """A steady section opened by one thread must not claim (and
        fail on) another thread's legitimate first-hit compile."""
        import threading

        reg = JitAuditRegistry()
        entered = threading.Event()
        release = threading.Event()

        def other_thread():
            entered.wait(5.0)
            reg.on_compile("backend_compile", 30.0)  # first-hit, ok
            release.set()

        worker = threading.Thread(target=other_thread)
        worker.start()
        with reg.steady("decode"):
            entered.set()
            assert release.wait(5.0)
        worker.join(5.0)
        assert reg.violations == []
        assert reg.compile_count() == 1

    def test_host_sync_count_sums_reads_and_uploads(self):
        reg = JitAuditRegistry()
        reg.on_host_read()
        reg.on_upload()
        reg.on_upload()
        assert reg.host_sync_count() == 3

    def test_reset_clears_everything(self):
        reg = JitAuditRegistry()
        with reg.steady("s"):
            reg.on_compile("backend_compile", 1.0)
        reg.on_fn_compiles("f", 2)
        reg.reset()
        assert reg.violations == []
        assert reg.events == []
        assert reg.fn_compiles == {}

    def test_report_names_churning_functions(self):
        reg = JitAuditRegistry()
        reg.on_fn_compiles("spec_round", 7)
        reg.on_fn_compiles("decode_step", 1)
        assert "spec_round=7" in reg.report()

    def test_violations_capped(self):
        reg = JitAuditRegistry(max_violations=3)
        with reg.steady("s"):
            for _ in range(10):
                reg.on_compile("backend_compile", 1.0)
        assert len(reg.violations) == 3


@pytest.fixture
def installed_audit():
    """Install the global auditor for one test, preserving any
    violations recorded earlier in the session (the session gate must
    still see them) and uninstalling only if this fixture installed."""
    owned = not jitaudit.installed()
    if owned:
        jitaudit.install()
    reg = jitaudit.registry()
    prior_violations = list(reg.violations)
    prior_events = list(reg.events)
    prior_fn = dict(reg.fn_compiles)
    yield reg
    reg.violations[:] = prior_violations
    reg.events[:] = prior_events
    reg.fn_compiles.clear()
    reg.fn_compiles.update(prior_fn)
    if owned:
        jitaudit.uninstall()


class TestInstalledHooks:
    def test_planted_shape_churning_loop_is_caught(self, installed_audit):
        """The literal BENCH_r05 defect: a fresh jax.jit per chunk
        length inside a loop the code believes is steady-state."""
        reg = installed_audit
        before = len(reg.violations)
        with reg.steady("planted-churn"):
            for n in (3, 4, 5):
                step = jax.jit(lambda x: x * 2 + 1)
                step(jnp.ones((n,), jnp.float32)).block_until_ready()
        caught = reg.violations[before:]
        assert len(caught) >= 3
        assert all(v.section == "planted-churn" for v in caught)

    def test_cached_jit_steady_loop_is_clean(self, installed_audit):
        reg = installed_audit
        step = jax.jit(lambda x: x * 3 - 1)
        step(jnp.ones((4,), jnp.float32)).block_until_ready()  # warmup
        before = len(reg.violations)
        with reg.steady("cached-loop"):
            for _ in range(5):
                step(jnp.ones((4,), jnp.float32)).block_until_ready()
        assert reg.violations[before:] == []

    def test_per_function_compile_attribution(self, installed_audit):
        reg = installed_audit

        def churner(x):
            return x + 1

        fn = jax.jit(churner)
        fn(jnp.ones((2,), jnp.float32))
        fn(jnp.ones((3,), jnp.float32))  # second shape -> second compile
        assert reg.fn_compiles.get("TestInstalledHooks."
                                   "test_per_function_compile_attribution."
                                   "<locals>.churner", 0) >= 2

    def test_device_get_counts_as_host_read(self, installed_audit):
        reg = installed_audit
        x = jnp.ones((3,), jnp.float32)
        with reg.section("reads"):
            jax.device_get(x)
            jax.device_get(x)
        assert reg.host_reads.get("reads", 0) == 2

    def test_asarray_of_host_value_counts_as_upload(self, installed_audit):
        reg = installed_audit
        dev = jnp.ones((3,), jnp.float32)
        with reg.section("uploads"):
            jnp.asarray([1, 2, 3], jnp.int32)  # host list -> upload
            jnp.asarray(dev)  # already on device -> not an upload
        assert reg.uploads.get("uploads", 0) == 1

    def test_install_uninstall_roundtrip(self):
        if jitaudit.installed():
            pytest.skip("session-level audit active; roundtrip covered "
                        "by the standalone run")
        real_jit = jax.jit
        real_get = jax.device_get
        jitaudit.install()
        try:
            assert jax.jit is not real_jit
            assert jitaudit.installed()
        finally:
            jitaudit.uninstall()
        assert jax.jit is real_jit
        assert jax.device_get is real_get
        assert not jitaudit.installed()


@pytest.mark.slow
class TestServingLanes:
    """The auditor over the real engines: steady-state decode must not
    recompile after warmup (the dynamic validation of TPL161)."""

    def _engines(self):
        from tpuslo.models.llama import LlamaConfig, init_params
        from tpuslo.models.serve import ServeEngine
        from tpuslo.models.speculative import SpeculativeEngine

        # A cfg distinct from other suites' so the lru-cached kernels
        # are built UNDER the audit (per-function attribution needs
        # wrappers created post-install).
        cfg = LlamaConfig(
            vocab_size=256, dim=48, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=96, max_seq_len=96, rope_theta=10000.0,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        target = ServeEngine(cfg=cfg, params=params,
                             prefill_buckets=(16, 32))
        draft = ServeEngine(cfg=cfg, params=params,
                            prefill_buckets=(16, 32))
        return target, draft, SpeculativeEngine(target, draft, k=2)

    def test_spec_decode_steady_state_zero_recompiles(self, installed_audit):
        reg = installed_audit
        target, _draft, spec = self._engines()
        prompt = "steady state audit"
        # Warmup: every first-hit compile happens here.
        spec.generate(prompt, max_new_tokens=8, stop_at_eos=False)
        [e.token_id for e in target.generate(
            prompt, max_new_tokens=8, stop_at_eos=False)]

        before_v = len(reg.violations)
        before_steady = reg.steady_compile_count()
        spec_stream = spec.generate(
            prompt, max_new_tokens=16, stop_at_eos=False
        )
        plain_stream = [e.token_id for e in target.generate(
            prompt, max_new_tokens=16, stop_at_eos=False)]
        assert spec_stream == plain_stream  # exactness, as always
        assert reg.violations[before_v:] == []
        assert reg.steady_compile_count() == before_steady
        # The fused round kernel was built under the audit and is
        # attributed by name.
        assert any(
            "spec_round" in name for name in reg.fn_compiles
        ), reg.fn_compiles

    def test_spec_stream_reads_once_per_round(self, installed_audit):
        reg = installed_audit
        _target, _draft, spec = self._engines()
        prompt = "fused read budget"
        spec.generate(prompt, max_new_tokens=8, stop_at_eos=False)  # warm
        reads0 = sum(reg.host_reads.values())
        rounds0 = spec.rounds
        out = spec.generate(prompt, max_new_tokens=16, stop_at_eos=False)
        rounds = spec.rounds - rounds0
        reads = sum(reg.host_reads.values()) - reads0
        assert len(out) >= 8
        # One fused device_get per round (+1 tolerance for a tail
        # fallback read near the KV capacity edge).
        assert rounds >= 1
        assert reads <= rounds + 1, (reads, rounds)
