"""Global tier: multi-region peering with partition-tolerant incident
identity under WAN chaos.

The load-bearing invariants get direct coverage: the gap-tolerant
cursor accepts every seq exactly once at ANY arrival order (the
bounded replay budget makes out-of-order arrival the normal case, not
the exception); a partition can scope pages but never wedge the
healthy side's session closes; and two peers that paged the same
fault from opposite sides of a partition reconcile by emitted-window
registry merge — suppress, never re-page.  The live lane proves the
asymmetric-failure shape end to end: a one-way WAN partition where
frames arrive but acks vanish, so the sender replays envelopes the
receiver already holds and the seq dedup absorbs the storm.
"""

from __future__ import annotations

import json
import socket
import time

import numpy as np
import pytest

from tpuslo.chaos.wan import (
    DIR_BACKWARD,
    DIR_FORWARD,
    WAN_ACK_LOSS,
    WAN_DARK,
    WAN_HEAL,
    WAN_LATENCY,
    WanEvent,
    WanLink,
    WanProxy,
)
from tpuslo.federation.backpressure import LEVEL_SAMPLE
from tpuslo.federation.global_tier import (
    BLAST_GLOBAL,
    PAGE_SCOPE_MULTI,
    PAGE_SCOPE_PARTITION,
    PAGE_SCOPE_SINGLE,
    GapTolerantCursor,
    GlobalAggregator,
    GlobalIncident,
    GlobalRollup,
    classify_global_radius,
)
from tpuslo.federation.simulator import (
    GlobalSimulator,
    global_injection_plan,
    measure_global_ingest,
)
from tpuslo.federation.sweep import (
    run_global_sweep,
    score_global_incidents,
)
from tpuslo.federation.wire import (
    GLOBAL_WIRE_VERSION,
    GlobalWireError,
    decode_global_envelope,
    encode_global_envelope,
    global_envelope_json_line,
    parse_global_envelope_line,
)
from tpuslo.fleet.rollup import FleetIncident
from tpuslo.fleet.simulator import EPOCH_NS
from tpuslo.fleet.wire import (
    WireContractError,
    decode_shipment,
    encode_shipment,
)
from tpuslo.livenet import LiveListener, ReconnectingClient

GAP = 5_000_000_000


def _fleet(
    rid: str,
    namespace: str = "tenant-a",
    domain: str = "tpu_hbm",
    start: int = EPOCH_NS,
    end: int = EPOCH_NS + GAP,
    confidence: float = 0.9,
    blast_radius: str = "fleet",
) -> FleetIncident:
    return FleetIncident(
        incident_id=f"fleet-{rid}-{domain}-{start}",
        namespace=namespace,
        domain=domain,
        blast_radius=blast_radius,
        window_start_ns=start,
        window_end_ns=end,
        confidence=confidence,
        nodes=[f"{rid}-node-0"],
        slices=[f"{rid}-slice-0"],
        members=[],
        region=rid,
        clusters=["cluster-0"],
    )


def _env(
    rid: str,
    seq: int,
    incidents: list[FleetIncident] | None = None,
    clock: int = EPOCH_NS,
) -> dict:
    return encode_global_envelope(
        region=rid,
        seq=seq,
        incidents=incidents or [],
        watermark_ns=clock,
        head_ns=clock,
    )


def _keys(incidents: list[GlobalIncident]) -> list[str]:
    return sorted(
        f"{gi.namespace}/{gi.domain}/{gi.blast_radius}"
        for gi in incidents
    )


class TestGlobalWire:
    def test_round_trip(self):
        fi = _fleet("region-0")
        payload = encode_global_envelope(
            "region-0", 3, [fi],
            watermark_ns=EPOCH_NS, head_ns=EPOCH_NS + 1,
            pressure_level=2,
        )
        env = decode_global_envelope(payload)
        assert env.region == "region-0"
        assert env.seq == 3
        assert env.watermark_ns == EPOCH_NS
        assert env.head_ns == EPOCH_NS + 1
        assert env.pressure_level == 2
        assert [i.to_dict() for i in env.incidents] == [fi.to_dict()]

    def test_jsonl_round_trip(self):
        payload = _env("region-1", 0, [_fleet("region-1")])
        env = parse_global_envelope_line(
            global_envelope_json_line(payload)
        )
        assert env.region == "region-1"
        assert len(env.incidents) == 1

    def test_version_mismatch_refused(self):
        payload = _env("region-0", 0)
        payload["global_wire_version"] = GLOBAL_WIRE_VERSION + 1
        with pytest.raises(GlobalWireError, match="global wire version"):
            decode_global_envelope(payload)

    def test_missing_region_refused(self):
        payload = _env("region-0", 0)
        payload["region"] = ""
        with pytest.raises(GlobalWireError, match="region identity"):
            decode_global_envelope(payload)

    def test_bad_incident_entry_refused(self):
        payload = _env("region-0", 0)
        payload["incidents"] = ["not a dict"]
        with pytest.raises(GlobalWireError, match="bad incident"):
            decode_global_envelope(payload)


class TestGapTolerantCursor:
    def test_in_order_advances_watermark(self):
        cursor = GapTolerantCursor()
        assert [cursor.accept(i) for i in range(4)] == [True] * 4
        assert cursor.watermark == 3
        assert not cursor.accepted
        assert not cursor.accept(2)

    def test_out_of_order_exactly_once(self):
        # The replay-budget arrival shape: fresh seqs overtake backlog.
        cursor = GapTolerantCursor()
        order = [0, 3, 1, 4, 2, 5]
        assert [cursor.accept(s) for s in order] == [True] * 6
        assert cursor.watermark == 5
        assert not cursor.accepted  # gaps filled, set compacted
        assert [cursor.accept(s) for s in order] == [False] * 6

    def test_sparse_set_bounded_by_gaps(self):
        cursor = GapTolerantCursor()
        cursor.accept(0)
        cursor.accept(5)
        cursor.accept(7)
        assert cursor.watermark == 0
        assert cursor.accepted == {5, 7}

    def test_export_restore_mid_gap(self):
        cursor = GapTolerantCursor()
        cursor.accept(0)
        cursor.accept(2)
        restored = GapTolerantCursor()
        restored.restore_state(cursor.export_state())
        assert not restored.accept(2)  # still a duplicate
        assert restored.accept(1)  # gap fills, watermark compacts
        assert restored.watermark == 2


class TestGlobalRollup:
    def test_cross_region_fault_pages_once_at_global_radius(self):
        rollup = GlobalRollup(gap_ns=GAP)
        rollup.observe(
            [
                _fleet("region-0"),
                _fleet("region-1", start=EPOCH_NS + GAP // 2),
            ]
        )
        pages = rollup.flush()
        assert len(pages) == 1
        page = pages[0]
        assert page.blast_radius == BLAST_GLOBAL
        assert page.regions == ["region-0", "region-1"]
        assert len(page.members) == 2
        assert page.scope == PAGE_SCOPE_MULTI
        # Members carry the drill-down identity, not node evidence.
        assert {m["region"] for m in page.members} == {
            "region-0",
            "region-1",
        }

    def test_distinct_tenants_never_merge(self):
        rollup = GlobalRollup(gap_ns=GAP)
        rollup.observe(
            [
                _fleet("region-0", namespace="tenant-a"),
                _fleet("region-1", namespace="tenant-b"),
            ]
        )
        pages = rollup.flush()
        assert len(pages) == 2
        assert {p.namespace for p in pages} == {"tenant-a", "tenant-b"}
        assert all(p.blast_radius == "fleet" for p in pages)

    def test_single_region_page_keeps_member_radius(self):
        assert (
            classify_global_radius(
                [_fleet("region-0", blast_radius="slice")]
            )
            == "slice"
        )
        pages_scope = GlobalRollup(gap_ns=GAP)
        pages_scope.observe([_fleet("region-0")])
        page = pages_scope.flush()[0]
        assert page.scope == PAGE_SCOPE_SINGLE

    def test_emitted_window_suppresses_replayed_session(self):
        rollup = GlobalRollup(gap_ns=GAP)
        rollup.observe([_fleet("region-0")])
        assert len(rollup.flush()) == 1
        # Spool redelivery rebuilds the same session: suppressed.
        rollup.observe([_fleet("region-0")])
        assert rollup.flush() == []
        assert rollup.duplicates_suppressed == 1


class TestGlobalAggregator:
    def _agg(self, **overrides) -> GlobalAggregator:
        kwargs = dict(
            rollup_gap_ns=GAP, region_stale_after_ns=3 * GAP
        )
        kwargs.update(overrides)
        return GlobalAggregator(**kwargs)

    def test_gap_tolerant_seq_dedup(self):
        agg = self._agg()
        assert agg.ingest(_env("region-0", 0))
        assert agg.ingest(_env("region-0", 2))  # overtook seq 1
        assert not agg.ingest(_env("region-0", 2))  # WAN replay
        assert agg.ingest(_env("region-0", 1))  # backlog arrives late
        assert not agg.ingest(_env("region-0", 0))
        assert agg.duplicate_envelopes == 2
        state = agg.regions["region-0"]
        assert state.cursor.watermark == 2

    def test_partition_scopes_pages_without_wedging_session_close(self):
        agg = self._agg()
        rids = ["region-0", "region-1", "region-2"]
        for rid in rids:
            agg.ingest(_env(rid, 0, [], EPOCH_NS))
        # region-2 goes dark; the others keep shipping.  The fault on
        # region-0 must still page once region-2 ages out of the min.
        fault = _fleet("region-0", start=EPOCH_NS + GAP)
        for tick in range(1, 7):
            clock = EPOCH_NS + (1 + tick) * GAP
            agg.ingest(
                _env("region-0", tick, [fault] if tick == 1 else [], clock)
            )
            agg.ingest(_env("region-1", tick, [], clock))
        assert agg.unreachable_regions() == ("region-2",)
        # The session clock is the min over REACHABLE regions only.
        assert agg.watermark_ns() == EPOCH_NS + 7 * GAP
        pages = agg.pump()
        assert len(pages) == 1
        assert pages[0].partition_scoped
        assert pages[0].unreachable_regions == ["region-2"]
        assert pages[0].scope == PAGE_SCOPE_PARTITION

    def test_export_restore_preserves_dedup(self):
        agg = self._agg()
        agg.ingest(_env("region-0", 0, [_fleet("region-0")]))
        agg.ingest(_env("region-0", 2))
        restored = self._agg()
        restored.restore_state(agg.export_state())
        assert not restored.ingest(_env("region-0", 2))
        assert restored.ingest(_env("region-0", 1))
        assert restored.regions["region-0"].cursor.watermark == 2
        # The open session survived the failover too.
        assert restored.backlog_incidents() >= 1

    def test_merge_peer_suppresses_replayed_page(self):
        # Peer B paged a fault this side never saw (the partition cut
        # its region off).  After the heal handshake, B's registry
        # window must suppress the replayed session here.
        peer_b = self._agg(global_id="global-b")
        peer_b.ingest(_env("region-2", 0, [_fleet("region-2")]))
        assert len(peer_b.pump(flush=True)) == 1
        peer_a = self._agg(global_id="global-a")
        merged = peer_a.merge_peer(peer_b.export_state())
        assert merged == 1
        # r2's spool replays the same envelope into A post-heal.
        assert peer_a.ingest(_env("region-2", 0, [_fleet("region-2")]))
        assert peer_a.pump(flush=True) == []
        assert peer_a.rollup.duplicates_suppressed == 1

    def test_merge_peer_without_registry_is_noop(self):
        peer_a = self._agg()
        assert peer_a.merge_peer({}) == 0


class TestWanLink:
    def _spool(self, n: int) -> list[dict]:
        return [{"seq": i} for i in range(n)]

    def test_bounded_replay_plus_fresh_overtake(self):
        link = WanLink("region-0", replay_budget=3)
        picked = link.select_for_send(self._spool(10))
        assert [p["seq"] for p in picked] == [0, 1, 2, 9]

    def test_zero_budget_is_strict_oldest_first(self):
        link = WanLink("region-0", replay_budget=0)
        picked = link.select_for_send(self._spool(4))
        assert [p["seq"] for p in picked] == [0, 1, 2, 3]

    def test_acked_envelopes_skip_the_wire(self):
        link = WanLink("region-0", replay_budget=2)
        link.on_ack(0)
        link.on_ack(1)
        picked = link.select_for_send(self._spool(5))
        assert [p["seq"] for p in picked] == [2, 3, 4]

    def test_ack_watermark_compacts_contiguously(self):
        link = WanLink("region-0")
        link.on_ack(2)
        assert link.ack_watermark == -1  # gap below: no trim yet
        link.on_ack(0)
        link.on_ack(1)
        assert link.ack_watermark == 2
        assert link.acked(2) and not link.acked(3)

    def test_backward_down_loses_acks(self):
        link = WanLink("region-0")
        link.apply(WanEvent(0, "region-0", WAN_ACK_LOSS))
        link.on_ack(0)
        assert link.lost_acks == 1
        assert not link.acked(0)  # the envelope stays spooled

    def test_dark_drops_frames_and_in_flight(self):
        link = WanLink("region-0", latency_rounds=2)
        link.offer(0, [{"seq": 0}])
        assert link.in_flight_seqs() == {0}
        link.apply(WanEvent(1, "region-0", WAN_DARK))
        assert link.in_flight_seqs() == set()  # hard cut loses it
        link.offer(1, [{"seq": 1}])
        assert link.dropped_frames == 1
        link.apply(WanEvent(2, "region-0", WAN_HEAL))
        link.offer(2, [{"seq": 1}])
        assert link.due(3) == []  # still in flight (latency)
        assert [p["seq"] for p in link.due(4)] == [1]

    def test_latency_event_reshapes_the_link(self):
        link = WanLink("region-0")
        link.apply(
            WanEvent(0, "region-0", WAN_LATENCY, latency_rounds=3)
        )
        link.offer(0, [{"seq": 0}])
        assert link.due(2) == []
        assert [p["seq"] for p in link.due(3)] == [0]

    def test_unknown_action_refused(self):
        link = WanLink("region-0")
        with pytest.raises(ValueError, match="unknown wan action"):
            link.apply(WanEvent(0, "region-0", "flood"))


def _free_port() -> int:
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def _wait_until(cond, timeout_s: float = 5.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError("condition not met within timeout")


class TestReplayBudgetInterleaving:
    def test_fresh_seqs_overtake_backlog_under_budget(self, tmp_path):
        """The satellite-1 regression: with a replay budget set, each
        send round replays at most that many spooled frames and the
        FRESH frame still goes out live — so the receiver sees seqs
        interleaved out of order, and only a gap-tolerant cursor can
        absorb the stream exactly once."""
        port = _free_port()
        received = []
        client = ReconnectingClient(
            ("127.0.0.1", port),
            tmp_path / "spool",
            timeout_s=0.5,
            replay_budget=1,
        )
        try:
            for seq in range(3):  # upstream down: all three spool
                assert client.send({"seq": seq}) is False
            assert client.pending_spooled() == 3
            listener = LiveListener(
                received.append, port=port, pressure=lambda: 0
            )
            try:
                for seq in (3, 4, 5):
                    assert client.send({"seq": seq}) is True
                # One backlog frame per round, fresh overtaking.
                assert [p["seq"] for p in received] == [
                    0, 3, 1, 4, 2, 5,
                ]
                assert client.replayed_frames == 3
                assert client.pending_spooled() == 0
                # The strict high-water-mark dedup of the lower hops
                # would eat seqs 1 and 2 as stale; the global tier's
                # cursor accepts every seq exactly once.
                cursor = GapTolerantCursor()
                assert [
                    cursor.accept(p["seq"]) for p in received
                ] == [True] * 6
                assert cursor.watermark == 5
            finally:
                listener.close()
        finally:
            client.close()


class TestWanProxyOneWayPartition:
    def test_acks_vanish_frames_arrive_then_replay_dedups(
        self, tmp_path
    ):
        """The defining asymmetric failure: the backward path drops
        acks while frames still arrive, so the sender spools a frame
        the receiver already holds and replays it after the heal —
        the receiver's gap-tolerant dedup absorbs the duplicate."""
        received = []
        listener = LiveListener(received.append, pressure=lambda: 0)
        proxy = WanProxy((listener.host, listener.port))
        client = ReconnectingClient(
            (proxy.host, proxy.port),
            tmp_path / "spool",
            timeout_s=0.5,
            replay_budget=4,
        )
        try:
            assert client.send({"seq": 0}) is True
            # One-way partition: connections stay UP (neither side
            # agrees the link is dead), only acks vanish.
            proxy.partition(DIR_BACKWARD)
            assert client.send({"seq": 1}) is False  # no ack: spooled
            _wait_until(lambda: len(received) == 2)
            assert [p["seq"] for p in received] == [0, 1]
            assert client.pending_spooled() == 1
            _wait_until(
                lambda: proxy.dropped_bytes[DIR_BACKWARD] > 0
            )
            assert proxy.forwarded_bytes[DIR_FORWARD] > 0
            proxy.heal(DIR_BACKWARD)
            # The next send replays the spooled frame — a duplicate
            # the receiver already holds — then the fresh one.
            assert client.send({"seq": 2}) is True
            assert [p["seq"] for p in received] == [0, 1, 1, 2]
            assert client.replayed_frames == 1
            assert client.pending_spooled() == 0
            cursor = GapTolerantCursor()
            accepted = [cursor.accept(p["seq"]) for p in received]
            assert accepted == [True, True, False, True]
            assert cursor.watermark == 2
        finally:
            client.close()
            proxy.close()
            listener.close()


def _small_sim(**overrides) -> GlobalSimulator:
    kwargs = dict(
        regions=2,
        nodes_per_region=48,
        clusters_per_region=2,
        shards_per_cluster=2,
        seed=1337,
    )
    kwargs.update(overrides)
    return GlobalSimulator(**kwargs)


class TestGlobalSimulator:
    def test_baseline_identity_exact(self):
        sim = _small_sim()
        plan = global_injection_plan(sim.topology, sim.region_ids)
        run = sim.run(16, plan)
        matches, precision, recall = score_global_incidents(
            plan, run.incidents
        )
        assert precision == 1.0 and recall == 1.0
        cross = next(
            m for m in matches if m.expected_blast_radius == BLAST_GLOBAL
        )
        assert cross.matched_count == 1
        assert cross.matched_regions == ["region-0", "region-1"]

    def test_rank_stability_under_wan_degradation(self):
        """Region-tier attribution must not reshuffle just because
        the WAN between region and global degraded: link latency and
        an ack-loss window (the sender replays envelopes the receiver
        already holds) may delay pages, but the seq dedup means the
        fold sees each fleet page exactly once — so every confidence,
        and therefore the incident ranking, is bit-identical to the
        healthy-WAN baseline."""
        base_sim = _small_sim()
        plan = global_injection_plan(
            base_sim.topology, base_sim.region_ids
        )
        baseline = base_sim.run(16, plan)
        degraded_sim = _small_sim(wan_latency_rounds=1)
        degraded = degraded_sim.run(
            16,
            plan,
            wan_events=[
                WanEvent(4, "region-1", WAN_ACK_LOSS),
                WanEvent(8, "region-1", WAN_HEAL),
            ],
        )
        # Preconditions: the replay storm actually happened, and the
        # plane stayed at/below the adaptive-sampling tier.
        assert degraded.global_snapshot["duplicate_envelopes"] > 0
        assert (
            degraded.global_snapshot["pressure_level"] <= LEVEL_SAMPLE
        )

        def _ranked(incidents):
            return [
                (gi.namespace, gi.domain, round(gi.confidence, 4))
                for gi in sorted(
                    incidents,
                    key=lambda g: (
                        -g.confidence,
                        g.namespace,
                        g.domain,
                    ),
                )
            ]

        assert _ranked(degraded.incidents) == _ranked(
            baseline.incidents
        )

    def test_dark_rejoin_zero_lost_zero_duplicated(self):
        # Three regions so the dark one is NOT half of the
        # cross-region fault — a fault spanning the dark boundary is
        # a different contract (it pages partition_scoped and the
        # late half suppresses), and the sweep keeps them separate
        # the same way.
        dark_at, dark_rounds = 6, 12
        base_sim = _small_sim(regions=3, replay_budget=2)
        plan = global_injection_plan(
            base_sim.topology,
            base_sim.region_ids,
            dark_region="region-2",
            dark_round=dark_at,
        )
        rounds = dark_at + dark_rounds + 10
        baseline = base_sim.run(rounds, plan)
        dark_sim = _small_sim(regions=3, replay_budget=2)
        run = dark_sim.run(
            rounds,
            plan,
            wan_events=[
                WanEvent(dark_at, "region-2", WAN_DARK),
                WanEvent(
                    dark_at + dark_rounds, "region-2", WAN_HEAL
                ),
            ],
        )
        assert _keys(run.incidents) == _keys(baseline.incidents)
        heal = run.heal_stats["region-2"]
        assert heal["backlog_at_heal"] > 2  # the budget actually binds
        assert 0 <= heal["replay_rounds"] <= heal["backlog_at_heal"]
        assert heal["max_out_of_order"] > 0  # fresh overtook backlog
        # The healthy side paged WHILE the partition was open.
        dark_window_pages = [
            (r, iid)
            for r, iid, _ in run.emits
            if dark_at <= r < dark_at + dark_rounds
        ]
        assert dark_window_pages
        assert any(gi.partition_scoped for gi in run.incidents)


class TestGlobalIngest:
    def test_measure_global_ingest_small(self):
        m = measure_global_ingest(
            regions=2,
            nodes_per_region=64,
            clusters_per_region=2,
            shards_per_cluster=2,
            events_per_node=60,
        )
        assert m.nodes == 128
        assert m.regions == 2
        assert m.events_per_sec > 0
        assert len(m.per_region_events_per_sec) == 2
        assert m.slowest_region in m.per_region_events_per_sec
        assert m.global_fold_ms >= 0


class TestGlobalSweep:
    @pytest.mark.slow
    def test_sweep_passes_at_small_scale(self):
        report = run_global_sweep(
            regions=3,
            nodes_per_region=48,
            clusters_per_region=2,
            shards_per_cluster=2,
            dark_at_round=8,
            dark_rounds=24,
            measure_ingest_lane=False,
        )
        assert report.passed, report.failures
        # The ack-loss window actually exercised the at-least-once hop.
        assert report.wan["duplicate_envelopes"] > 0
        assert report.wan["lost_acks"] > 0
        assert report.dark["lost"] == []
        assert report.dark["duplicated"] == []
        assert report.dark["pages_during_dark"] > 0
        assert report.splitbrain["suppressed"] >= 2
        assert report.splitbrain["re_pages"] == 0

    @pytest.mark.slow
    def test_m5gate_global_cli_round_trip(self, tmp_path):
        from tpuslo.cli.m5gate import main as m5gate_main

        summary_json = tmp_path / "sweep.json"
        summary_md = tmp_path / "sweep.md"
        rc = m5gate_main(
            [
                "--global-sweep",
                "--global-regions", "3",
                "--global-nodes-per-region", "48",
                "--global-dark-duration-rounds", "24",
                "--global-no-ingest",
                "--summary-json", str(summary_json),
                "--summary-md", str(summary_md),
            ]
        )
        assert rc == 0
        report = json.loads(summary_json.read_text())
        assert report["passed"] is True
        md = summary_md.read_text()
        assert "Global-tier gate" in md
        assert "PASS" in md


class TestGlobalCLI:
    def _write_envelopes(self, path, payloads):
        path.write_text(
            "".join(global_envelope_json_line(p) for p in payloads)
        )

    def test_fleetagg_global_tier_folds_and_dedups(
        self, tmp_path, capsys
    ):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        g0 = tmp_path / "g0.jsonl"
        g1 = tmp_path / "g1.jsonl"
        clock = EPOCH_NS + 8 * GAP
        self._write_envelopes(
            g0,
            [
                _env("region-0", 0, [_fleet("region-0")], clock),
                _env("region-0", 0, [_fleet("region-0")], clock),
            ],
        )
        self._write_envelopes(
            g1, [_env("region-1", 0, [_fleet("region-1")], clock)]
        )
        incidents_out = tmp_path / "global.jsonl"
        state_out = tmp_path / "gstate.json"
        rc = fleetagg_main(
            [
                "--global-tier", str(g0), str(g1),
                "--incidents-out", str(incidents_out),
                "--state-out", str(state_out),
                "--json",
            ]
        )
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["incidents"] == 1
        assert summary["duplicate_envelopes"] == 1
        assert summary["regions"] == ["region-0", "region-1"]
        page = json.loads(incidents_out.read_text().strip())
        assert page["blast_radius"] == BLAST_GLOBAL
        assert page["regions"] == ["region-0", "region-1"]
        state = json.loads(state_out.read_text())
        assert state["global"]["rollup"]["emitted_windows"]

    def test_fleetagg_merge_peer_suppresses_replay(
        self, tmp_path, capsys
    ):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        envelope = _env(
            "region-2", 0, [_fleet("region-2")], EPOCH_NS + 8 * GAP
        )
        # Peer B pages the fault on its side of the partition...
        peer_log = tmp_path / "peer.jsonl"
        self._write_envelopes(peer_log, [envelope])
        peer_state = tmp_path / "peer-state.json"
        assert fleetagg_main(
            [
                "--global-tier", str(peer_log),
                "--state-out", str(peer_state),
                "--global-id", "global-b",
            ]
        ) == 0
        capsys.readouterr()
        # ...and after the heal, this side merges B's registry before
        # replaying the same spool: suppress, never re-page.
        replay_log = tmp_path / "replay.jsonl"
        self._write_envelopes(replay_log, [envelope])
        rc = fleetagg_main(
            [
                "--global-tier", str(replay_log),
                "--merge-peer", str(peer_state),
                "--json",
            ]
        )
        assert rc == 0
        out = capsys.readouterr()
        assert "merged 1 emitted windows" in out.err
        summary = json.loads(out.out)
        assert summary["incidents"] == 0
        assert summary["duplicates_suppressed"] == 1

    def test_fleetagg_global_flag_conflicts(self, capsys):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        rc = fleetagg_main(["x.jsonl", "--global-tier", "--region"])
        assert rc == 2
        assert "--global-tier" in capsys.readouterr().err
        rc = fleetagg_main(
            ["x.jsonl", "--global-tier", "--global-out", "g.jsonl"]
        )
        assert rc == 2
        assert "--global-out" in capsys.readouterr().err
        rc = fleetagg_main(
            ["x.jsonl", "--merge-peer", "peer.json"]
        )
        assert rc == 2
        assert "--merge-peer" in capsys.readouterr().err
        rc = fleetagg_main(["x.jsonl", "--global-out", "g.jsonl"])
        assert rc == 2
        assert "--region" in capsys.readouterr().err

    def test_sloctl_global_scope(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main as sloctl_main

        pages = [
            GlobalIncident(
                incident_id="global-tenant-b-tpu_hbm-1",
                namespace="tenant-b",
                domain="tpu_hbm",
                blast_radius=BLAST_GLOBAL,
                window_start_ns=EPOCH_NS,
                window_end_ns=EPOCH_NS + GAP,
                confidence=0.92,
                regions=["region-0", "region-1"],
                members=[
                    {"incident_id": "f0", "region": "region-0",
                     "clusters": ["cluster-0"]},
                    {"incident_id": "f1", "region": "region-1",
                     "clusters": ["cluster-2"]},
                ],
            ),
            GlobalIncident(
                incident_id="global-tenant-a-tpu_ici-2",
                namespace="tenant-a",
                domain="tpu_ici",
                blast_radius="slice",
                window_start_ns=EPOCH_NS + 2 * GAP,
                window_end_ns=EPOCH_NS + 3 * GAP,
                confidence=0.8,
                regions=["region-0"],
                members=[
                    {"incident_id": "f2", "region": "region-0",
                     "clusters": ["cluster-1"]},
                ],
                partition_scoped=True,
                unreachable_regions=["region-1"],
            ),
        ]
        path = tmp_path / "global.jsonl"
        path.write_text(
            "".join(
                json.dumps(g.to_dict()) + "\n" for g in pages
            )
        )
        rc = sloctl_main(
            ["fleet", "incidents", "--incidents", str(path), "--global"]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "REGIONS" in out and "SCOPE" in out
        assert "region-0,region-1" in out
        assert "multi_region" in out
        # A partition-scoped page names who was dark.
        assert "partition_scoped !region-1" in out
        assert "2 global incidents" in out
        # --radius global keeps only the cross-region page.
        sloctl_main(
            [
                "fleet", "incidents", "--incidents", str(path),
                "--global", "--radius", "global",
            ]
        )
        out = capsys.readouterr().out
        assert "tpu_hbm" in out and "tpu_ici" not in out
        # --cluster drills into member provenance; --json parity.
        sloctl_main(
            [
                "fleet", "incidents", "--incidents", str(path),
                "--global", "--cluster", "cluster-2", "--json",
            ]
        )
        rows = json.loads(capsys.readouterr().out)
        assert [r["incident_id"] for r in rows] == [
            "global-tenant-b-tpu_hbm-1"
        ]
        assert rows[0]["regions"] == ["region-0", "region-1"]


class TestShipmentBoundsRegression:
    """The 100k-node bottleneck fix: decode_shipment's string-column
    bounds check became a single unsigned-view max reduction.  The
    trick only works if a negative i4 code still trips it (viewed as
    u4 it lands >= 2**31) — pin that, or a corrupted shipment would
    IndexError deep inside the gate instead of failing the contract."""

    def _payload(self):
        from tpuslo.schema.types import ProbeEventV1
        from tpuslo.columnar.schema import from_rows

        events = [
            ProbeEventV1(
                ts_unix_nano=EPOCH_NS + i * 1_000_000,
                signal="dns_latency_ms",
                node="node-x",
                namespace="tenant-a",
                pod="node-x-pod-0",
                container="workload",
                pid=100 + i,
                tid=100 + i,
                value=float(i),
                unit="ms",
                status="ok",
            )
            for i in range(4)
        ]
        return encode_shipment(from_rows(events), "node-x", 0)

    def _corrupt(self, payload, code: int):
        col = np.frombuffer(
            payload["columns"]["node"], dtype=np.int32
        ).copy()
        col[0] = code
        payload["columns"]["node"] = col.tobytes()

    def test_negative_code_refused(self):
        payload = self._payload()
        self._corrupt(payload, -1)
        with pytest.raises(WireContractError, match="outside"):
            decode_shipment(payload)

    def test_code_past_pool_refused(self):
        payload = self._payload()
        self._corrupt(payload, len(payload["pool"]))
        with pytest.raises(WireContractError, match="outside"):
            decode_shipment(payload)

    def test_max_valid_code_accepted(self):
        payload = self._payload()
        self._corrupt(payload, len(payload["pool"]) - 1)
        shipment = decode_shipment(payload)
        assert shipment.events == 4
