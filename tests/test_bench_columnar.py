"""Columnar bench gates (``make bench-columnar-smoke``).

Fast lane: a small ``bench_pipeline`` run proving the measured path is
alive, parity holds at every stage, and the result shape carries both
row and columnar numbers side by side (floors are NOT enforced at toy
batch sizes — fixed numpy overheads would gate on noise).

Slow lane: the full gate-scale run; the floors the release gates on
(columnar ≥ 1M events/s, matcher ≥ 10x the row path) must hold or
``bench_pipeline`` itself hard-fails with SystemExit.
"""

import pytest

import bench


def test_bench_pipeline_smoke_shapes_and_parity():
    result = bench.bench_pipeline(sample_count=60, repeats=1)

    assert result["probe_events"] > 0
    assert result["row"]["probe_events_per_sec"] > 0
    assert result["columnar"]["probe_events_per_sec"] > 0
    assert result["row"]["serialize_events_per_sec"] > 0
    assert result["columnar"]["serialize_events_per_sec"] > 0
    assert result["columnar"]["matcher_pairs_per_sec"] > 0
    assert result["columnar"]["posterior_samples_per_sec"] > 0

    # Parity is asserted in-run (bench_pipeline raises on divergence);
    # the flags must also land in the artifact.
    assert result["parity"]["all"] is True
    for stage in ("generate", "gate_admitted", "matcher", "serialize"):
        assert result["parity"][stage] is True

    gates = result["columnar_gates"]
    assert gates["events_per_sec_floor"] == bench.COLUMNAR_EVENTS_PER_SEC_FLOOR
    assert gates["enforced"] is False  # toy batch: floors not binding


def test_digest_pipeline_is_compact_and_named():
    result = bench.bench_pipeline(sample_count=60, repeats=1)
    digest = bench._digest_pipeline(result)
    assert set(digest) >= {
        "row_events_per_sec",
        "columnar_events_per_sec",
        "columnar_matcher_speedup",
        "columnar_gates_met",
        "parity_ok",
    }
    assert digest["parity_ok"] is True


@pytest.mark.slow
def test_bench_pipeline_full_run_meets_columnar_floors():
    # bench_pipeline raises SystemExit itself if the floors regress;
    # asserting the flags keeps the failure readable either way.
    result = bench.bench_pipeline(sample_count=2000, repeats=4)
    # The matcher corpus must actually correlate at gate scale (a
    # time-anchor regression once measured the 10x floor on an
    # all-miss corpus where parity held vacuously).
    assert result["matcher_matches"] > 0
    gates = result["columnar_gates"]
    assert gates["enforced"] is True
    assert gates["events_gate_met"] is True
    assert gates["matcher_gate_met"] is True
    assert (
        result["columnar"]["probe_events_per_sec"]
        >= bench.COLUMNAR_EVENTS_PER_SEC_FLOOR
    )
    assert (
        result["columnar"]["matcher_speedup"]
        >= bench.COLUMNAR_MATCHER_SPEEDUP_FLOOR
    )
