"""Burn-engine tests: ring windows, budget math, alert state machine,
snapshot round trips, offline replay, config bridging, and the
hot-path purity assertion (sloengine stays TPL120/121-clean)."""

import json
import math
from pathlib import Path

import pytest

from tpuslo.cli import loadgen
from tpuslo.sloengine import (
    OBJECTIVES,
    SEVERITY_PAGE,
    SEVERITY_RESOLVE,
    SEVERITY_TICKET,
    STATE_FAST,
    STATE_OK,
    STATE_SLOW,
    AlertPolicy,
    BurnEngine,
    EngineConfig,
    RequestOutcome,
    TenantWindows,
    load_outcomes,
    replay_outcomes,
    state_level,
)
from tpuslo.sloengine.budget import (
    TenantTargets,
    budget_remaining_for,
    burn_rates_for,
    resolve_targets,
)
from tpuslo.sloengine.stream import BUDGET_WINDOW_INDEX, WINDOWS

REPO = Path(__file__).resolve().parent.parent

T0 = 1_700_000_000


def outcome(
    ts_s=T0, tenant="t", status="ok", ttft_ms=100.0, tpot_ms=30.0
):
    return RequestOutcome(
        tenant=tenant,
        ts_unix_nano=int(ts_s) * 1_000_000_000,
        ttft_ms=ttft_ms,
        tpot_ms=tpot_ms,
        tokens=64,
        status=status,
    )


class TestTenantWindows:
    def test_counts_land_in_every_window(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        tw.record(T0, (True,))
        tw.record(T0, (False,))
        for wi in range(len(WINDOWS)):
            assert tw.window_counts(wi, 0) == (1, 2)
        assert tw.window_counts(BUDGET_WINDOW_INDEX, 0) == (1, 2)

    def test_roll_forward_expires_short_windows_first(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        tw.record(T0, (False,))
        # 6 minutes later: outside 5m, inside 30m/1h/6h.
        tw.roll_to((T0 + 360) // 10)
        assert tw.window_counts(0, 0) == (0, 0)      # 5m
        assert tw.window_counts(1, 0) == (0, 1)      # 30m
        assert tw.window_counts(3, 0) == (0, 1)      # 6h

    def test_full_horizon_gap_resets_everything(self):
        tw = TenantWindows(n_objectives=2, bucket_s=10)
        tw.record(T0, (True, False))
        tw.record(T0 + 7 * 3600, (True, True))
        for wi in range(len(WINDOWS)):
            assert tw.window_counts(wi, 0) == (1, 1)
            assert tw.window_counts(wi, 1) == (1, 1)

    def test_late_events_join_still_covered_windows(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        tw.record(T0 + 600, (True,))
        # 8 minutes late: inside 30m+, outside 5m.
        tw.record(T0 + 120, (False,))
        assert tw.window_counts(0, 0) == (1, 1)      # 5m
        assert tw.window_counts(1, 0) == (1, 2)      # 30m

    def test_stale_events_dropped_and_counted(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        tw.record(T0 + 7 * 3600, (True,))
        assert not tw.record(T0, (True,))
        assert tw.dropped_stale == 1

    def test_sums_match_naive_recompute_under_churn(self):
        import random

        rng = random.Random(7)
        tw = TenantWindows(n_objectives=2, bucket_s=10)
        events = []
        ts = T0
        for _ in range(2000):
            ts += rng.randint(0, 40)
            goods = (rng.random() < 0.9, rng.random() < 0.7)
            if tw.record(ts, goods):
                events.append((ts, goods))
        head_bucket = tw.head_abs
        for wi, (_, seconds) in enumerate(WINDOWS):
            wb = min(tw.n_buckets, max(1, seconds // 10))
            lo = head_bucket - wb + 1
            for oi in range(2):
                good = sum(
                    1
                    for ts, goods in events
                    if lo <= ts // 10 <= head_bucket and goods[oi]
                )
                total = sum(
                    1
                    for ts, goods in events
                    if lo <= ts // 10 <= head_bucket
                )
                assert tw.window_counts(wi, oi) == (good, total)

    def test_export_restore_round_trip(self):
        tw = TenantWindows(n_objectives=3, bucket_s=10)
        for i in range(500):
            tw.record(T0 + i * 7, (i % 2 == 0, True, i % 5 != 0))
        clone = TenantWindows(n_objectives=3, bucket_s=10)
        assert clone.restore_state(tw.export_state())
        for wi in range(len(WINDOWS) + 1):
            for oi in range(3):
                assert clone.window_counts(wi, oi) == tw.window_counts(
                    wi, oi
                )

    def test_restore_rejects_shape_mismatch(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        other = TenantWindows(n_objectives=1, bucket_s=30)
        assert not other.restore_state(tw.export_state())
        assert not tw.restore_state({"bucket_s": 10})


class TestBudgetMath:
    def test_burn_rate_definition(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        for i in range(100):
            tw.record(T0 + i, (i >= 10,))  # 10% bad
        targets = TenantTargets(availability_target=0.99)
        burns = burn_rates_for(tw, 0, targets.error_budget("availability"))
        assert burns["5m"] == pytest.approx(10.0)

    def test_empty_windows_burn_zero_and_full_budget(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        burns = burn_rates_for(tw, 0, 0.01)
        assert all(rate == 0.0 for rate in burns.values())
        assert budget_remaining_for(tw, 0, 0.01) == 1.0

    def test_budget_remaining_clamps(self):
        tw = TenantWindows(n_objectives=1, bucket_s=10)
        for i in range(100):
            tw.record(T0 + i, (False,))  # 100% bad
        assert budget_remaining_for(tw, 0, 0.01) == 0.0

    def test_tenant_override_resolution(self):
        defaults = TenantTargets()
        overrides = {
            "gold": {"availability_target": 0.999, "bogus": 1.0},
            "broken": "not-a-dict",
        }
        gold = resolve_targets(defaults, overrides, "gold")
        assert gold.availability_target == 0.999
        assert gold.ttft_objective_ms == defaults.ttft_objective_ms
        assert (
            resolve_targets(defaults, overrides, "unknown")
            == defaults
        )

    def test_perfect_target_still_divides(self):
        targets = TenantTargets(availability_target=1.0)
        assert targets.error_budget("availability") > 0
        assert math.isfinite(1.0 / targets.error_budget("availability"))


class TestAlertPolicy:
    def fire(self, policy, burns, n=1, now=0.0):
        out = []
        for i in range(n):
            tr = policy.evaluate("t", "availability", burns, now + i)
            if tr is not None:
                out.append(tr)
        return out

    def test_fast_burn_needs_both_windows(self):
        policy = AlertPolicy()
        hot = {"5m": 20.0, "1h": 20.0, "30m": 0.0, "6h": 0.0}
        spike_only = {"5m": 20.0, "1h": 1.0, "30m": 0.0, "6h": 0.0}
        assert not self.fire(policy, spike_only)
        fired = self.fire(policy, hot)
        assert [t.severity for t in fired] == [SEVERITY_PAGE]
        assert fired[0].to_state == STATE_FAST

    def test_sustained_burn_is_one_transition(self):
        policy = AlertPolicy()
        hot = {"5m": 20.0, "1h": 20.0, "30m": 20.0, "6h": 20.0}
        fired = self.fire(policy, hot, n=50)
        assert len(fired) == 1

    def test_slow_burn_tickets_on_long_windows(self):
        policy = AlertPolicy()
        slow = {"5m": 8.0, "1h": 8.0, "30m": 8.0, "6h": 8.0}
        fired = self.fire(policy, slow)
        assert [t.severity for t in fired] == [SEVERITY_TICKET]
        assert policy.state_of("t", "availability") == STATE_SLOW

    def test_escalation_slow_to_fast_pages(self):
        policy = AlertPolicy()
        self.fire(policy, {"5m": 8.0, "1h": 8.0, "30m": 8.0, "6h": 8.0})
        fired = self.fire(
            policy, {"5m": 20.0, "1h": 20.0, "30m": 20.0, "6h": 20.0}
        )
        assert [t.severity for t in fired] == [SEVERITY_PAGE]

    def test_hysteresis_blocks_flapping_refire(self):
        policy = AlertPolicy(clear_cycles=3)
        hot = {"5m": 20.0, "1h": 20.0, "30m": 20.0, "6h": 20.0}
        near = {"5m": 10.0, "1h": 10.0, "30m": 10.0, "6h": 10.0}
        assert len(self.fire(policy, hot)) == 1
        # Oscillate around the threshold: burn never drops below the
        # clear line (14.4 * 0.5 = 7.2), so nothing re-fires.
        for _ in range(20):
            assert not self.fire(policy, near)
            assert not self.fire(policy, hot)
        assert policy.state_of("t", "availability") == STATE_FAST

    def test_clear_requires_sustained_quiet_then_resolves_once(self):
        policy = AlertPolicy(clear_cycles=3)
        hot = {"5m": 20.0, "1h": 20.0, "30m": 20.0, "6h": 20.0}
        calm = {"5m": 0.0, "1h": 0.0, "30m": 0.0, "6h": 0.0}
        self.fire(policy, hot)
        assert not self.fire(policy, calm)  # streak 1
        assert not self.fire(policy, calm)  # streak 2
        fired = self.fire(policy, calm)     # streak 3 -> resolve
        assert [t.severity for t in fired] == [SEVERITY_RESOLVE]
        assert fired[0].to_state == STATE_OK
        assert not self.fire(policy, calm, n=10)

    def test_interrupted_clear_streak_resets(self):
        policy = AlertPolicy(clear_cycles=3)
        hot = {"5m": 20.0, "1h": 20.0, "30m": 20.0, "6h": 20.0}
        calm = {"5m": 0.0, "1h": 0.0, "30m": 0.0, "6h": 0.0}
        self.fire(policy, hot)
        self.fire(policy, calm, n=2)
        self.fire(policy, hot)  # burn resumes: streak must reset
        assert not self.fire(policy, calm, n=2)
        assert policy.state_of("t", "availability") == STATE_FAST

    def test_state_round_trip(self):
        policy = AlertPolicy()
        hot = {"5m": 20.0, "1h": 20.0, "30m": 20.0, "6h": 20.0}
        self.fire(policy, hot)
        clone = AlertPolicy()
        clone.restore_state(policy.export_state())
        assert clone.state_of("t", "availability") == STATE_FAST
        assert clone.alerting_count() == 1

    def test_state_levels(self):
        assert state_level(STATE_OK) == 0
        assert state_level(STATE_SLOW) == 1
        assert state_level(STATE_FAST) == 2
        assert state_level("garbage") == 0


class TestBurnEngine:
    def burn_for(self, seconds, error_rate, t0=T0, engine=None):
        engine = engine or BurnEngine(EngineConfig())
        for i in range(seconds // 5):
            ts = t0 + i * 5
            bad = (i * 7919) % 100 < error_rate * 100
            engine.record(
                outcome(ts_s=ts, status="error" if bad else "ok")
            )
        return engine

    def test_latency_objectives_independent_of_availability(self):
        engine = BurnEngine(EngineConfig())
        for i in range(120):
            engine.record(
                outcome(ts_s=T0 + i * 5, ttft_ms=5000.0)
            )
        engine.evaluate(T0 + 600)
        states = {
            (s.objective): s.alert_state for s in engine.status()
        }
        assert states["ttft"] != STATE_OK
        assert states["availability"] == STATE_OK
        assert states["tpot"] == STATE_OK

    def test_error_counts_against_every_objective(self):
        engine = BurnEngine(EngineConfig())
        engine.record(outcome(status="error", ttft_ms=10.0, tpot_ms=1.0))
        for stat in engine.status():
            assert stat.sli["5m"] == 0.0

    def test_tenant_isolation(self):
        engine = BurnEngine(EngineConfig())
        for i in range(720):
            ts = T0 + i * 5
            engine.record(outcome(ts_s=ts, tenant="a", status="error"))
            engine.record(outcome(ts_s=ts, tenant="b"))
        transitions = engine.evaluate(T0 + 3600)
        assert transitions
        assert all(t.tenant == "a" for t in transitions)
        states = {
            (s.tenant, s.objective): s.alert_state
            for s in engine.status()
        }
        assert states[("b", "availability")] == STATE_OK
        assert states[("a", "availability")] == STATE_FAST

    def test_max_tenants_overflow_accounted(self):
        engine = BurnEngine(EngineConfig(max_tenants=2))
        assert engine.record(outcome(tenant="a"))
        assert engine.record(outcome(tenant="b"))
        assert not engine.record(outcome(tenant="c"))
        assert engine.dropped_overflow == 1

    def test_active_burns_and_max_burn(self):
        engine = self.burn_for(3600, 1.0)
        engine.evaluate(T0 + 3600)
        burns = engine.active_burns()
        assert any(
            b["tenant"] == "t"
            and b["objective"] == "availability"
            and b["state"] == STATE_FAST
            for b in burns
        )
        assert engine.max_active_burn() > 14.4

    def test_snapshot_restore_preserves_burn_state(self):
        engine = self.burn_for(3600, 0.5)
        engine.evaluate(T0 + 3600)
        state = json.loads(json.dumps(engine.export_state()))
        clone = BurnEngine(EngineConfig())
        clone.restore_state(state)
        assert [s.to_dict() for s in clone.status()] == [
            s.to_dict() for s in engine.status()
        ]
        # Continuing after restore behaves like never restarting.
        more = self.burn_for(600, 0.5, t0=T0 + 3600, engine=clone)
        reference = self.burn_for(600, 0.5, t0=T0 + 3600,
                                  engine=self.burn_for(3600, 0.5))
        reference.evaluate(T0 + 3600)
        assert [
            s.to_dict() for s in more.status()
        ] == [s.to_dict() for s in reference.status()]

    def test_roll_to_is_policy_free(self):
        # A display read (sloctl budget) rolls windows forward without
        # advancing clear streaks or firing transitions.
        engine = self.burn_for(3600, 1.0)
        engine.evaluate(T0 + 3600)
        assert engine.policy.state_of("t", "availability") == STATE_FAST
        before = engine.policy.export_state()
        fired = engine.transitions_fired
        # Hours of quiet: evaluate() would resolve; roll_to must not.
        engine.roll_to(T0 + 3600 + 7 * 3600)
        assert engine.policy.export_state() == before
        assert engine.transitions_fired == fired
        # ...but the windows really did advance.
        for stat in engine.status():
            assert stat.totals["6h"] == 0

    def test_max_active_burn_accepts_precomputed_list(self):
        engine = self.burn_for(3600, 1.0)
        engine.evaluate(T0 + 3600)
        burns = engine.active_burns()
        assert engine.max_active_burn(burns) == engine.max_active_burn()
        assert engine.max_active_burn([]) == 0.0

    def test_restore_rejects_bucket_mismatch(self):
        engine = self.burn_for(600, 0.5)
        clone = BurnEngine(EngineConfig(bucket_s=30))
        clone.restore_state(engine.export_state())
        assert clone.status() == []

    def test_engine_config_from_toolkit(self):
        from tpuslo.config import SLOConfig

        slo = SLOConfig(
            availability_target=0.999,
            tenants={"gold": {"ttft_objective_ms": 500.0}},
        )
        cfg = EngineConfig.from_toolkit(slo)
        assert cfg.availability_target == 0.999
        engine = BurnEngine(cfg)
        assert engine.tenant_targets("gold").ttft_objective_ms == 500.0
        assert engine.tenant_targets("other").ttft_objective_ms == 800.0

    def test_observer_receives_gauges_and_transitions(self):
        calls = []

        class Spy:
            def outcome(self, tenant, status):
                calls.append(("outcome", tenant, status))

            def burn_rate(self, tenant, objective, window, rate):
                calls.append(("burn", tenant, objective, window))

            def budget_remaining(self, tenant, objective, remaining):
                calls.append(("budget", tenant, objective))

            def alert_state(self, tenant, objective, level):
                calls.append(("state", tenant, objective, level))

            def transition(self, tenant, objective, severity):
                calls.append(("transition", tenant, objective, severity))

        engine = BurnEngine(EngineConfig(), observer=Spy())
        for i in range(720):
            engine.record(outcome(ts_s=T0 + i * 5, status="error"))
        engine.evaluate(T0 + 3600)
        kinds = {c[0] for c in calls}
        assert {"outcome", "burn", "budget", "state",
                "transition"} <= kinds
        windows = {
            c[3] for c in calls if c[0] == "burn"
        }
        assert windows == {label for label, _ in WINDOWS}

    def test_snapshot_counters(self):
        engine = self.burn_for(600, 1.0)
        engine.evaluate(T0 + 600)
        snap = engine.snapshot()
        assert snap["tenants"] == 1
        assert snap["recorded"] == 120
        assert snap["alerting"] >= 1


class TestOfflineReplay:
    def test_loadgen_round_trip_fast_burn_verdict(self, tmp_path):
        """loadgen --slo-out → engine → expected burn verdict."""
        out = tmp_path / "outcomes.jsonl"
        rc = loadgen.main(
            [
                "--rps", "1", "--duration-s", "3600",
                "--error-rate", "0.3", "--error-after-s", "1800",
                "--tenant", "gold",
                "--output", str(tmp_path / "trace.jsonl"),
                "--slo-out", str(out),
            ]
        )
        assert rc == 0
        engine = BurnEngine(EngineConfig())
        transitions = replay_outcomes(engine, load_outcomes(str(out)))
        severities = {
            (t.tenant, t.objective, t.severity) for t in transitions
        }
        assert ("gold", "availability", SEVERITY_PAGE) in severities
        states = {
            (s.tenant, s.objective): s.alert_state
            for s in engine.status()
        }
        assert states[("gold", "availability")] == STATE_FAST

    def test_loadgen_steady_stream_stays_quiet(self, tmp_path):
        out = tmp_path / "outcomes.jsonl"
        loadgen.main(
            [
                "--rps", "1", "--duration-s", "3600",
                "--output", str(tmp_path / "trace.jsonl"),
                "--slo-out", str(out),
            ]
        )
        engine = BurnEngine(EngineConfig())
        transitions = replay_outcomes(engine, load_outcomes(str(out)))
        assert transitions == []
        assert all(
            s.alert_state == STATE_OK for s in engine.status()
        )

    def test_load_outcomes_skips_torn_tail(self, tmp_path):
        path = tmp_path / "outcomes.jsonl"
        good = outcome().to_dict()
        path.write_text(json.dumps(good) + "\n" + '{"tenant": "x", tr')
        loaded = list(load_outcomes(str(path)))
        assert len(loaded) == 1
        assert loaded[0].tenant == "t"

    def test_outcome_dict_round_trip(self):
        original = outcome(status="error")
        clone = RequestOutcome.from_dict(
            json.loads(json.dumps(original.to_dict()))
        )
        assert clone == original


class TestHotPathPurity:
    def test_sloengine_hot_path_is_lint_clean(self):
        """The TPL120/121 manifest covers the engine's record path, and
        the rule reports nothing — the sweep gate depends on it."""
        from tpuslo.analysis import run_analysis
        from tpuslo.analysis.hotpaths import (
            HOT_DATACLASSES,
            HOT_FUNCTIONS,
        )
        from tpuslo.analysis.rules_hotpath import HotPathPurityRule

        assert (
            "tpuslo/sloengine/stream.py",
            "TenantWindows.record",
        ) in HOT_FUNCTIONS
        assert (
            "tpuslo/sloengine/engine.py",
            "BurnEngine.record",
        ) in HOT_FUNCTIONS
        assert (
            "tpuslo/sloengine/stream.py",
            "RequestOutcome",
        ) in HOT_DATACLASSES
        result = run_analysis(
            REPO,
            paths=["tpuslo/sloengine", "tpuslo/analysis/hotpaths.py"],
            rules=[HotPathPurityRule()],
        )
        offending = [
            f
            for f in result.findings
            if f.code in ("TPL120", "TPL121")
        ]
        assert offending == [], [f.render() for f in offending]

    def test_objectives_match_window_layout(self):
        engine = BurnEngine(EngineConfig())
        engine.record(outcome())
        assert len(OBJECTIVES) == 3
        assert {s.objective for s in engine.status()} == set(OBJECTIVES)
