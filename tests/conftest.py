"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` (see SURVEY.md §4 rebuild
translation: "kind becomes a CPU-only JAX substrate").
"""

import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
