"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` (see SURVEY.md §4 rebuild
translation: "kind becomes a CPU-only JAX substrate").

Note: the TPU-tunnel sitecustomize imports jax at interpreter start, so
environment variables alone are too late — the platform must be forced
via ``jax.config`` before the backend initialises.
"""

import os
import sys
from pathlib import Path

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# --- dynamic lock-order race checking (make racecheck-smoke) -------------
# TPUSLO_RACECHECK=1 wraps threading.Lock/RLock in order-tracking proxies
# (tpuslo/analysis/racecheck.py); the session fails if any cross-thread
# acquisition-order inversion or lock-held-across-sleep was recorded.
# Installed after the jax import so third-party import-time lock usage
# stays untracked — the toolkit's locks are created per-instance inside
# tests and are tracked either way.
_RACECHECK = os.environ.get("TPUSLO_RACECHECK", "") == "1"
if _RACECHECK:
    from tpuslo.analysis import racecheck as _racecheck

    _racecheck.install()

# --- dynamic retrace auditing (make jitcheck-smoke) -----------------------
# TPUSLO_JITAUDIT=1 hooks jax.monitoring compile events and wraps
# jax.jit/device_get/jnp.asarray (tpuslo/analysis/jitaudit.py); serving
# loops self-declare their post-warmup steady sections, and the session
# fails if a steady-state decode loop ever triggered a backend compile.
# Installed at conftest import so engines built inside tests get
# per-function compile tracking from birth.
_JITAUDIT = os.environ.get("TPUSLO_JITAUDIT", "") == "1"
if _JITAUDIT:
    from tpuslo.analysis import jitaudit as _jitaudit

    _jitaudit.install()

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _racecheck_gate():
    """Fail the session on recorded lock-order violations."""
    yield
    if _RACECHECK:
        reg = _racecheck.registry()
        if reg.violations:
            pytest.fail(
                f"racecheck recorded {len(reg.violations)} violation(s):\n"
                + reg.report(),
                pytrace=False,
            )


@pytest.fixture(scope="session", autouse=True)
def _jitaudit_gate():
    """Fail the session on steady-state recompiles (retrace churn)."""
    yield
    if _JITAUDIT:
        reg = _jitaudit.registry()
        if reg.violations:
            pytest.fail(
                f"jitaudit recorded {len(reg.violations)} steady-state "
                f"recompile(s):\n" + reg.report(),
                pytrace=False,
            )
