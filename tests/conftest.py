"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` (see SURVEY.md §4 rebuild
translation: "kind becomes a CPU-only JAX substrate").

Note: the TPU-tunnel sitecustomize imports jax at interpreter start, so
environment variables alone are too late — the platform must be forced
via ``jax.config`` before the backend initialises.
"""

import os
import sys
from pathlib import Path

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
