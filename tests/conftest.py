"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` (see SURVEY.md §4 rebuild
translation: "kind becomes a CPU-only JAX substrate").

Note: the TPU-tunnel sitecustomize imports jax at interpreter start, so
environment variables alone are too late — the platform must be forced
via ``jax.config`` before the backend initialises.
"""

import os
import sys
from pathlib import Path

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# --- dynamic lock-order race checking (make racecheck-smoke) -------------
# TPUSLO_RACECHECK=1 wraps threading.Lock/RLock in order-tracking proxies
# (tpuslo/analysis/racecheck.py); the session fails if any cross-thread
# acquisition-order inversion or lock-held-across-sleep was recorded.
# Installed after the jax import so third-party import-time lock usage
# stays untracked — the toolkit's locks are created per-instance inside
# tests and are tracked either way.
_RACECHECK = os.environ.get("TPUSLO_RACECHECK", "") == "1"
if _RACECHECK:
    from tpuslo.analysis import racecheck as _racecheck

    _racecheck.install()

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _racecheck_gate():
    """Fail the session on recorded lock-order violations."""
    yield
    if _RACECHECK:
        reg = _racecheck.registry()
        if reg.violations:
            pytest.fail(
                f"racecheck recorded {len(reg.violations)} violation(s):\n"
                + reg.report(),
                pytrace=False,
            )
