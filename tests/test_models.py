"""JAX Llama model family + parallelism tests (virtual CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpuslo.models import llama
from tpuslo.models.serve import EOS, ServeEngine, decode_bytes, encode_bytes
from tpuslo.models.train import build_sharded_train_step
from tpuslo.ops import ring_attention_sharded
from tpuslo.ops.ring_attention import reference_causal_attention
from tpuslo.parallel import MeshPlan, make_mesh, plan_for_devices

CFG = llama.llama_tiny(max_seq_len=64)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


class TestForward:
    def test_shapes_and_dtype(self, params):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = jax.jit(lambda p, t: llama.forward(p, t, CFG))(params, tokens)
        assert logits.shape == (2, 16, CFG.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, params):
        """Changing a future token must not affect earlier logits."""
        rng = jax.random.PRNGKey(1)
        tokens = jax.random.randint(rng, (1, 16), 0, CFG.vocab_size)
        mutated = tokens.at[0, 10].set((tokens[0, 10] + 1) % CFG.vocab_size)
        a = llama.forward(params, tokens, CFG, remat=False)
        b = llama.forward(params, mutated, CFG, remat=False)
        np.testing.assert_allclose(a[0, :10], b[0, :10], atol=1e-5)
        assert not np.allclose(a[0, 10:], b[0, 10:])

    def test_remat_matches_no_remat(self, params):
        tokens = jnp.arange(16, dtype=jnp.int32)[None, :] % CFG.vocab_size
        a = llama.forward(params, tokens, CFG, remat=True)
        b = llama.forward(params, tokens, CFG, remat=False)
        np.testing.assert_allclose(a, b, atol=1e-5)


class TestDecode:
    def test_prefill_matches_forward(self, params):
        rng = jax.random.PRNGKey(2)
        tokens = jax.random.randint(rng, (2, 12), 0, CFG.vocab_size)
        cache = llama.init_kv_cache(CFG, 2)
        last, cache = llama.prefill(params, tokens, cache, CFG)
        full = llama.forward(params, tokens, CFG, remat=False)
        np.testing.assert_allclose(last, full[:, -1, :], atol=1e-4)
        assert int(cache["length"]) == 12

    def test_decode_matches_forward(self, params):
        """Incremental decode logits == full forward at each position."""
        rng = jax.random.PRNGKey(3)
        tokens = jax.random.randint(rng, (1, 8), 0, CFG.vocab_size)
        cache = llama.init_kv_cache(CFG, 1)
        last, cache = llama.prefill(params, tokens, cache, CFG)

        next_tok = jnp.argmax(last, -1).astype(jnp.int32)
        seq = jnp.concatenate([tokens, next_tok[:, None]], axis=1)
        logits, cache = llama.decode_step(params, next_tok, cache, CFG)
        full = llama.forward(params, seq, CFG, remat=False)
        np.testing.assert_allclose(logits, full[:, -1, :], atol=1e-4)

    def test_gqa_head_counts(self):
        assert CFG.n_heads % CFG.n_kv_heads == 0

    def test_decode_chunk_matches_single_steps(self, params):
        """One scanned chunk == the same greedy per-token step sequence."""
        rng = jax.random.PRNGKey(7)
        tokens = jax.random.randint(rng, (1, 8), 0, CFG.vocab_size)

        cache = llama.init_kv_cache(CFG, 1)
        last, cache = llama.prefill(params, tokens, cache, CFG)
        tok = jnp.argmax(last, -1).astype(jnp.int32)
        chunk, chunk_last, chunk_cache = llama.decode_chunk(
            params, tok, cache, CFG, 6
        )

        cache = llama.init_kv_cache(CFG, 1)
        _, cache = llama.prefill(params, tokens, cache, CFG)
        singles = []
        step_tok = tok
        for _ in range(6):
            logits, cache = llama.decode_step(params, step_tok, cache, CFG)
            step_tok = jnp.argmax(logits, -1).astype(jnp.int32)
            singles.append(int(step_tok[0]))

        assert chunk.shape == (1, 6)
        assert [int(t) for t in chunk[0]] == singles
        assert int(chunk_last[0]) == singles[-1]
        assert int(chunk_cache["length"]) == int(cache["length"])

    def test_padded_prefill_matches_unpadded(self, params):
        """Bucket padding must not change logits or cache length."""
        rng = jax.random.PRNGKey(4)
        tokens = jax.random.randint(rng, (1, 10), 0, CFG.vocab_size)
        padded = jnp.pad(tokens, ((0, 0), (0, 22)))  # bucket 32

        cache_a = llama.init_kv_cache(CFG, 1)
        logits_a, cache_a = llama.prefill(params, tokens, cache_a, CFG)
        cache_b = llama.init_kv_cache(CFG, 1)
        logits_b, cache_b = llama.prefill(
            params, padded, cache_b, CFG, true_length=jnp.asarray(10)
        )
        np.testing.assert_allclose(logits_a, logits_b, atol=1e-4)
        assert int(cache_b["length"]) == 10

        # And decode from the padded cache matches full forward.
        next_tok = jnp.argmax(logits_b, -1).astype(jnp.int32)
        logits_c, _ = llama.decode_step(params, next_tok, cache_b, CFG)
        seq = jnp.concatenate([tokens, next_tok[:, None]], axis=1)
        full = llama.forward(params, seq, CFG, remat=False)
        np.testing.assert_allclose(logits_c, full[:, -1, :], atol=1e-4)


class TestShardedTraining:
    def test_train_step_on_8dev_mesh(self):
        plan = MeshPlan(dp=2, fsdp=2, tp=2)
        mesh = make_mesh(plan)
        step_fn, init_fn = build_sharded_train_step(mesh, CFG)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        rng = jax.random.PRNGKey(1)
        tokens = jax.random.randint(rng, (4, 32), 0, CFG.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        losses = []
        for _ in range(3):
            params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_param_shardings_cover_tree(self):
        from tpuslo.parallel.mesh import param_shardings

        mesh = make_mesh(MeshPlan(dp=1, fsdp=2, tp=4))
        shard = param_shardings(mesh)
        params = llama.init_params(jax.random.PRNGKey(0), CFG)
        # Same tree structure: tree_map must not raise.
        jax.tree.map(lambda a, b: None, shard, params)

    def test_plan_for_devices(self):
        assert plan_for_devices(8).n_devices == 8
        assert plan_for_devices(1) == MeshPlan(1, 1, 1)
        assert plan_for_devices(4).tp == 4

    def test_mesh_requires_enough_devices(self):
        with pytest.raises(ValueError):
            make_mesh(MeshPlan(dp=4, fsdp=4, tp=4))


class TestRingAttention:
    @pytest.mark.parametrize("n_dev", [2, 4, 8])
    def test_matches_reference(self, n_dev):
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("sp",))
        B, S, H, D = 2, 8 * n_dev, 2, 8
        q = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D), jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(3), (B, S, H, D), jnp.float32)
        ring = ring_attention_sharded(q, k, v, mesh)
        ref = reference_causal_attention(q, k, v)
        np.testing.assert_allclose(ring, ref, atol=1e-4)


class TestServeEngine:
    @pytest.fixture(scope="class")
    def engine(self):
        return ServeEngine(cfg=llama.llama_tiny(max_seq_len=128))

    def test_tokenizer_round_trip(self):
        ids = encode_bytes("hello tpu", 64)
        assert ids[0] == 256  # BOS
        assert decode_bytes(ids[1:]) == "hello tpu"

    def test_generate_deterministic(self, engine):
        a = [e.token_id for e in engine.generate("same prompt", max_new_tokens=6)]
        b = [e.token_id for e in engine.generate("same prompt", max_new_tokens=6)]
        assert a == b
        assert len(a) <= 6

    def test_first_event_has_ttft(self, engine):
        events = list(engine.generate("x", max_new_tokens=3))
        assert events[0].ttft_ms is not None and events[0].ttft_ms > 0
        assert all(e.ttft_ms is None for e in events[1:])

    def test_warmup_returns_ms(self, engine):
        assert engine.warmup() >= 0.0

    def test_oversize_prompt_truncates_to_largest_bucket(self):
        engine = ServeEngine(
            cfg=llama.llama_tiny(max_seq_len=128), prefill_buckets=(32,)
        )
        long_prompt = "x" * 500
        events = list(engine.generate(long_prompt, max_new_tokens=2))
        assert len(events) >= 1  # no crash, no unpadded odd-length compile

    def test_max_new_tokens_capped_to_cache_capacity(self):
        """Requests past KV capacity cap cleanly instead of clamping
        dynamic_update_slice writes onto the last cache slot."""
        engine = ServeEngine(
            cfg=llama.llama_tiny(max_seq_len=128), prefill_buckets=(32,)
        )
        events = list(
            engine.generate("hello", max_new_tokens=10_000, stop_at_eos=False)
        )
        assert 1 <= len(events) <= 128

    def test_no_dead_lookahead_dispatch(self):
        """Exactly one decode chunk is dispatched when one suffices."""
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=256))
        calls = 0
        orig = engine._decode_chunk

        def counting(*a, **k):
            nonlocal calls
            calls += 1
            return orig(*a, **k)

        engine._decode_chunk = counting
        chunk = engine.decode_chunk_size
        events = list(
            engine.generate("abc", max_new_tokens=chunk + 1, stop_at_eos=False)
        )
        assert len(events) == chunk + 1
        assert calls == 1

    def test_tiny_max_seq_len_falls_back_to_single_bucket(self):
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=16))
        assert engine.prefill_buckets == (16,)
        assert engine.warmup() >= 0.0

    def test_short_config_still_generates_requested_tokens(self):
        """Capacity is per-request (decode starts at the prompt's true
        length, not the bucket), so a single-bucket fallback config must
        not silently cap generation at one token."""
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=16))
        events = list(
            engine.generate("hi", max_new_tokens=8, stop_at_eos=False)
        )
        # prompt = BOS + 2 bytes = 3 ids; avail = 16-3-1 = 12; chunk = 7
        # -> cap 7 tokens of the 8 requested.
        assert len(events) == 7

    def test_budget_of_exactly_one_chunk_uses_chunk_path(self):
        engine = ServeEngine(
            cfg=llama.llama_tiny(max_seq_len=32), prefill_buckets=(16,)
        )
        # 15-byte prompt -> 16 ids (chunked ingestion no longer
        # truncates at the bucket); avail = 32-16-1 = 15 = chunk ->
        # chunked path, cap 15.
        long_events = list(
            engine.generate("x" * 15, max_new_tokens=64, stop_at_eos=False)
        )
        assert len(long_events) == 15
        assert engine._decode_one is None  # tail path never compiled

    def test_budget_below_one_chunk_falls_back_to_single_steps(self):
        """A prompt that leaves less than one chunk of KV budget must
        still serve the remaining slots (single-token tail path), not
        round the request down to the prefill token."""
        engine = ServeEngine(
            cfg=llama.llama_tiny(max_seq_len=32), prefill_buckets=(24,)
        )
        # chunk = min(64, (32-2)//2) = 15; 24-id prompt -> avail =
        # 32-24-1 = 7 < 15 -> tail path with cap 7.
        events = list(
            engine.generate("y" * 23, max_new_tokens=64, stop_at_eos=False)
        )
        assert len(events) == 7
        assert engine._decode_one is not None
        assert all(
            0 <= e.token_id < engine.cfg.vocab_size for e in events
        )
        # The tail compile is visible to compile telemetry.
        assert any(
            e.get("bucket") == "decode_tail" for e in engine.compile_events
        )

    def test_warmup_can_precompile_tail_path(self):
        engine = ServeEngine(
            cfg=llama.llama_tiny(max_seq_len=32), prefill_buckets=(24,)
        )
        engine.warmup(include_tail=True)
        assert engine._decode_one is not None

    def test_generate_batch_matches_single_request_rows(self):
        """Per-row cache lengths: each batched row must reproduce its
        single-request greedy decode (no cross-row contamination, no
        pad conditioning)."""
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=256))
        prompts = ["short", "a rather longer prompt with more bytes", "mid one"]
        batch_out = engine.generate_batch(
            prompts, max_new_tokens=12, stop_at_eos=False
        )
        for prompt, row in zip(prompts, batch_out):
            single = [
                e.token_id
                for e in engine.generate(
                    prompt, max_new_tokens=12, stop_at_eos=False
                )
            ]
            assert row == single

    def test_generate_batch_eos_trims_per_row(self):
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=128))
        out = engine.generate_batch(["a", "bb"], max_new_tokens=16)
        assert len(out) == 2
        for row in out:
            assert 1 <= len(row) <= 16
            if EOS in row:
                assert row[-1] == EOS and row.count(EOS) == 1

    def test_generate_batch_empty_and_padding(self):
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=128))
        assert engine.generate_batch([]) == []
        out = engine.generate_batch(
            ["x", "y", "z"], max_new_tokens=4, stop_at_eos=False
        )
        # 3 prompts pad to batch bucket 4 internally; only 3 returned.
        assert len(out) == 3
        assert all(len(row) == 4 for row in out)

    def test_generate_batch_splits_past_largest_bucket(self):
        """More prompts than the largest batch bucket must split into
        sub-batches, each row still matching its single-request decode
        (previously crashed: prefill traced n_real rows against a
        bucket-sized KV cache)."""
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=128))
        prompts = [f"prompt number {i}" for i in range(10)]
        out = engine.generate_batch(prompts, max_new_tokens=6, stop_at_eos=False)
        assert len(out) == 10
        for prompt, row in ((prompts[0], out[0]), (prompts[9], out[9])):
            single = [
                e.token_id
                for e in engine.generate(prompt, max_new_tokens=6, stop_at_eos=False)
            ]
            assert row == single

    def test_prompt_conditioning_not_poisoned_by_pads(self):
        """Different prompts shorter than the bucket must produce
        different first tokens conditioned on the real last byte."""
        engine = ServeEngine(cfg=llama.llama_tiny(max_seq_len=128))
        a = next(iter(engine.generate("aaaa", max_new_tokens=1))).token_id
        b = next(iter(engine.generate("zzzzzz", max_new_tokens=1))).token_id
        # With the pad bug both prompts produced the logits of pad
        # position 31 regardless of content; distinct prompts now give
        # (almost surely) distinct argmax under a random tiny model.
        assert isinstance(a, int) and isinstance(b, int)

    def test_eos_stops_generation(self, engine):
        # Force EOS by patching argmax path: use a prompt and cap; we
        # simply assert the stream never exceeds max_new_tokens and all
        # ids are within vocab.
        events = list(engine.generate("abc", max_new_tokens=10))
        assert len(events) <= 10
        assert all(0 <= e.token_id < engine.cfg.vocab_size for e in events)
        if any(e.token_id == EOS for e in events):
            assert events[-1].token_id == EOS


def test_optimizer_state_shardings_path_matching():
    """Same-shaped params with different shardings resolve by path."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpuslo.parallel.mesh import optimizer_state_shardings

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    shard_a = NamedSharding(mesh, P("tp", None))
    shard_b = NamedSharding(mesh, P(None, "tp"))
    p_shard = {"wa": shard_a, "wb": shard_b}  # identical shapes
    leaf = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    opt_abstract = (
        {"mu": {"wa": leaf, "wb": leaf}, "count": jax.ShapeDtypeStruct((), jnp.int32)},
    )
    opt_shard = optimizer_state_shardings(opt_abstract, p_shard, mesh)
    assert opt_shard[0]["mu"]["wa"] == shard_a
    assert opt_shard[0]["mu"]["wb"] == shard_b
    assert opt_shard[0]["count"] == NamedSharding(mesh, P())

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow
