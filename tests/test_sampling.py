"""Sampling: greedy equivalences, nucleus/top-k masking, reproducibility."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuslo.models.llama import (
    GREEDY,
    SamplingConfig,
    init_params,
    llama_tiny,
    sample_from_logits,
)
from tpuslo.models.serve import ServeEngine


def _logits():
    # Batch of 2, vocab 8: sharply peaked rows with known order.
    return jnp.asarray(
        [
            [10.0, 9.0, 8.0, 0.0, -1.0, -2.0, -3.0, -4.0],
            [0.0, 1.0, 2.0, 3.0, 12.0, 4.0, 5.0, 6.0],
        ],
        jnp.float32,
    )


def test_greedy_is_argmax_and_rng_free():
    out = sample_from_logits(_logits(), jax.random.PRNGKey(0), GREEDY)
    np.testing.assert_array_equal(np.asarray(out), [0, 4])
    out2 = sample_from_logits(_logits(), jax.random.PRNGKey(999), GREEDY)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_top_k_restricts_support():
    cfg = SamplingConfig(temperature=1.0, top_k=3)
    seen = set()
    for seed in range(64):
        out = sample_from_logits(_logits(), jax.random.PRNGKey(seed), cfg)
        seen.update(
            (row, int(tok)) for row, tok in enumerate(np.asarray(out))
        )
    assert {t for r, t in seen if r == 0} <= {0, 1, 2}
    assert {t for r, t in seen if r == 1} <= {4, 7, 6}


def test_top_p_tiny_equals_greedy():
    cfg = SamplingConfig(temperature=1.0, top_p=1e-6)
    out = sample_from_logits(_logits(), jax.random.PRNGKey(3), cfg)
    np.testing.assert_array_equal(np.asarray(out), [0, 4])


def test_top_k_one_equals_greedy_any_temperature():
    cfg = SamplingConfig(temperature=5.0, top_k=1)
    out = sample_from_logits(_logits(), jax.random.PRNGKey(7), cfg)
    np.testing.assert_array_equal(np.asarray(out), [0, 4])


def test_temperature_flattens_distribution():
    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0]], jnp.float32)
    counts_cold = np.zeros(4)
    counts_hot = np.zeros(4)
    for seed in range(200):
        cold = sample_from_logits(
            logits, jax.random.PRNGKey(seed), SamplingConfig(temperature=0.3)
        )
        hot = sample_from_logits(
            logits, jax.random.PRNGKey(seed), SamplingConfig(temperature=3.0)
        )
        counts_cold[int(cold[0])] += 1
        counts_hot[int(hot[0])] += 1
    # Cold concentrates on the mode far more than hot.
    assert counts_cold[0] > counts_hot[0]
    assert (counts_hot > 0).sum() >= 3  # hot spreads over most tokens


@pytest.mark.slow
class TestServeEngineSampling:
    def _engine(self):
        cfg = llama_tiny(max_seq_len=128)
        params = init_params(jax.random.PRNGKey(0), cfg)
        return ServeEngine(cfg=cfg, params=params)

    def test_default_is_greedy_unchanged(self):
        engine = self._engine()
        a = [e.token_id for e in engine.generate("g", 12, stop_at_eos=False)]
        b = [e.token_id for e in engine.generate("g", 12, stop_at_eos=False)]
        assert a == b

    def test_sampled_stream_reproducible_by_seed(self):
        engine = self._engine()
        cfg_s = SamplingConfig(temperature=1.0, top_k=50)
        kw = dict(max_new_tokens=16, stop_at_eos=False, sampling=cfg_s)
        a = [e.token_id for e in engine.generate("s", seed=1, **kw)]
        b = [e.token_id for e in engine.generate("s", seed=1, **kw)]
        c = [e.token_id for e in engine.generate("s", seed=2, **kw)]
        assert a == b
        assert len(a) == 16
        assert a != c  # overwhelmingly likely on a 16-token stream

    def test_zero_temperature_sampling_equals_greedy(self):
        engine = self._engine()
        greedy = [e.token_id for e in engine.generate("z", 12, stop_at_eos=False)]
        zero = [
            e.token_id
            for e in engine.generate(
                "z", 12, stop_at_eos=False,
                sampling=SamplingConfig(temperature=0.0), seed=5,
            )
        ]
        assert zero == greedy

    def test_bad_rng_requirement(self):
        from tpuslo.models.llama import decode_chunk, init_kv_cache

        cfg = llama_tiny(max_seq_len=64)
        params = init_params(jax.random.PRNGKey(0), cfg)
        cache = init_kv_cache(cfg, 1)
        cache["length"] = jnp.asarray(4, jnp.int32)
        with pytest.raises(ValueError, match="rng"):
            decode_chunk(
                params, jnp.zeros((1,), jnp.int32), cache, cfg, 4,
                sampling=SamplingConfig(temperature=1.0),
            )


class TestBatchedSampling:
    """generate_batch(sampling=...): reproducible at batch level,
    greedy default bit-unchanged."""

    def _engine(self):
        from tpuslo.models.llama import init_params, llama_tiny
        from tpuslo.models.serve import ServeEngine

        cfg = llama_tiny(max_seq_len=128)
        return ServeEngine(
            cfg=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
            prefill_buckets=(32, 64),
        )

    def test_batch_sampling_reproducible_and_seed_sensitive(self):
        from tpuslo.models.llama import SamplingConfig

        engine = self._engine()
        prompts = ["sample row one", "and row two"]
        cfg = SamplingConfig(temperature=0.9, top_k=50)
        a = engine.generate_batch(prompts, 12, stop_at_eos=False,
                                  sampling=cfg, seed=3)
        b = engine.generate_batch(prompts, 12, stop_at_eos=False,
                                  sampling=cfg, seed=3)
        c = engine.generate_batch(prompts, 12, stop_at_eos=False,
                                  sampling=cfg, seed=4)
        assert a == b
        assert a != c  # astronomically unlikely to collide at T=0.9

    def test_batch_greedy_default_unchanged(self):
        engine = self._engine()
        prompts = ["greedy row", "second greedy"]
        plain = engine.generate_batch(prompts, 10, stop_at_eos=False)
        for prompt, row in zip(prompts, plain):
            expect = [
                e.token_id
                for e in engine.generate(prompt, 10, stop_at_eos=False)
            ]
            assert row == expect

    def test_batch_rows_draw_independently(self):
        """Two rows with the SAME prompt must not emit identical
        stochastic streams (per-row draws from the shared key)."""
        from tpuslo.models.llama import SamplingConfig

        engine = self._engine()
        rows = engine.generate_batch(
            ["same prompt", "same prompt"], 16, stop_at_eos=False,
            sampling=SamplingConfig(temperature=1.2), seed=11,
        )
        assert rows[0] != rows[1]
