"""Serving scale-out: SLO-aware router over replicated front doors +
paged-KV slots (ISSUE 16).

Two exactness contracts on top of the PR 12 front door:

* **Paged == dense.**  A front door with ``paged=True`` emits exactly
  the token streams the dense-slot front door emits — through fresh
  admission, warm-prefix admission and a forced preempt/park/resume
  cycle — because decode runs the identical fused round; only the
  park/resume copies change representation.

* **The fleet == one engine.**  Any routing policy over replicated
  engines yields the same per-request streams as a single engine (and
  therefore the per-stream speculative reference), including across a
  mid-run engine kill whose drained slots resume on siblings.
"""

from __future__ import annotations

import pytest

from tpuslo.models.frontdoor import FrontDoorEngine, FrontDoorObserver
from tpuslo.models.llama import llama_tiny
from tpuslo.models.router import SLORouter, RouterDecision
from tpuslo.models.speculative import SpeculativeEngine
from tpuslo.sloengine.engine import BurnEngine


@pytest.fixture(scope="module")
def engines():
    cfg = llama_tiny(max_seq_len=128)
    from tpuslo.models.serve import ServeEngine

    target = ServeEngine(cfg=cfg, rng_seed=0)
    draft = ServeEngine(cfg=cfg, rng_seed=0)
    return target, draft


def spec_reference(engines, prompt, n, stop_at_eos=False, prefix=None):
    spec = SpeculativeEngine(engines[0], engines[1], k=3)
    return spec.generate(
        prompt, max_new_tokens=n, stop_at_eos=stop_at_eos, prefix=prefix
    )


def make_frontdoor(engines, paged=False, **kw):
    kw.setdefault("k", 3)
    kw.setdefault("max_slots", 2)
    kw.setdefault("rounds_per_step", 1)
    return FrontDoorEngine(*engines, paged=paged, block_size=32, **kw)


def make_fleet(engines, n, paged=True, **kw):
    return [make_frontdoor(engines, paged=paged, **kw) for _ in range(n)]


# ---- paged-vs-dense parity (satellite) ---------------------------------


class TestPagedParity:
    def test_paged_streams_match_dense(self, engines):
        prompts = [f"paged parity {i}" for i in range(6)]
        dense = make_frontdoor(engines, paged=False)
        paged = make_frontdoor(engines, paged=True)
        dense_ids = [
            dense.submit(p, max_new_tokens=10, stop_at_eos=False)
            for p in prompts
        ]
        paged_ids = [
            paged.submit(p, max_new_tokens=10, stop_at_eos=False)
            for p in prompts
        ]
        dense_out, paged_out = dense.run(), paged.run()
        for d_rid, p_rid in zip(dense_ids, paged_ids):
            assert dense_out[d_rid] == paged_out[p_rid]

    def test_paged_park_resume_cycle_bit_identical(self, engines):
        """A preemption exercises the block-granular park + resume:
        the resumed stream must continue exactly where it left off."""
        burn = BurnEngine()
        burn.demote_tenant("lowly")
        fd = make_frontdoor(engines, paged=True, burn_engine=burn)
        low_ids = [
            fd.submit(f"low paged stream {i}", tenant="lowly",
                      max_new_tokens=24, stop_at_eos=False)
            for i in range(2)
        ]
        for _ in range(2):
            fd.step()
        hi = fd.submit("high priority paged", tenant="vip",
                       max_new_tokens=8, stop_at_eos=False)
        results = fd.run()
        assert fd.paged_parks >= 1
        assert fd.paged_resumes >= 1
        assert fd.paged_fallback_parks == 0
        for i, rid in enumerate(low_ids):
            assert results[rid] == spec_reference(
                engines, f"low paged stream {i}", 24
            )
        assert results[hi] == spec_reference(
            engines, "high priority paged", 8
        )
        # every parked block returned to the free list
        stats = fd.stats()["paged"]
        assert stats["free_blocks"] == stats["pool_blocks"] - 1

    def test_paged_warm_prefix_admission(self, engines):
        prefix = "[system] paged prefix parity."
        fd = make_frontdoor(engines, paged=True)
        prompts = [f" q{i}?" for i in range(4)]
        ids = [
            fd.submit(p, max_new_tokens=8, stop_at_eos=False,
                      prefix=prefix)
            for p in prompts
        ]
        results = fd.run()
        for prompt, rid in zip(prompts, ids):
            assert results[rid] == spec_reference(
                engines, prompt, 8, prefix=prefix
            )

    def test_pool_exhaustion_falls_back_to_dense_park(self, engines):
        """A park pool too small for even one bucket must not fail the
        preemption — it falls back to the full-row snapshot."""
        burn = BurnEngine()
        burn.demote_tenant("lowly")
        fd = FrontDoorEngine(
            *engines, k=3, max_slots=2, rounds_per_step=1,
            paged=True, block_size=32, pool_blocks=1,
            burn_engine=burn,
        )
        lo = fd.submit("fallback stream one two three", tenant="lowly",
                       max_new_tokens=24, stop_at_eos=False)
        fd.submit("second low", tenant="lowly",
                  max_new_tokens=24, stop_at_eos=False)
        for _ in range(2):
            fd.step()
        fd.submit("vip arrival", tenant="vip",
                  max_new_tokens=8, stop_at_eos=False)
        results = fd.run()
        assert fd.paged_fallback_parks >= 1
        assert results[lo] == spec_reference(
            engines, "fallback stream one two three", 24
        )


# ---- routing policy ----------------------------------------------------


class TestRoutingPolicy:
    def test_affinity_routes_group_to_one_engine(self, engines):
        router = SLORouter(make_fleet(engines, 3), seed=0)
        homes = set()
        for i in range(6):
            gid = router.route(f" q{i}", max_new_tokens=4,
                               prefix="grp-00/sys")
            homes.add(router._placements[gid][0])
            router.run()  # drain: queues stay under the overflow bound
        assert len(homes) == 1  # one warm home, all requests follow it
        assert router.affinity_hits == 5  # all but the cold fill

    def test_hot_group_spills_past_overflow_bound(self, engines):
        """Bounded-load affinity: once the warm home's queue exceeds
        ``affinity_overflow × max_slots``, the group spills to a
        sibling and becomes warm there too (replication under
        pressure) instead of pinning its whole tail on one engine."""
        router = SLORouter(make_fleet(engines, 3), seed=0)
        gids = [
            router.route(f" hot{i}", max_new_tokens=4,
                         prefix="grp-00/sys")
            for i in range(6)  # no stepping: queues only grow
        ]
        homes = {router._placements[g][0] for g in gids}
        assert len(homes) > 1  # the overloaded home stopped attracting
        warm_on = [
            i for i in router.live_engines()
            if "grp-00/sys" in router._warm[i]
        ]
        assert len(warm_on) == len(homes)  # spillover 2-homed the group
        router.run()

    def test_distinct_groups_spread_by_load(self, engines):
        router = SLORouter(make_fleet(engines, 3), seed=0)
        for g in range(3):
            for i in range(2):
                router.route(f" q{g}-{i}", max_new_tokens=4,
                             prefix=f"grp-{g:02d}/sys")
        # second request of each group lands warm on its group's home
        warm = [d for d in router.decisions if d.warm_hit]
        assert len(warm) == 3
        # cold fills spread across the fleet instead of piling up
        cold_homes = {
            d.engine for d in router.decisions if not d.warm_hit
        }
        assert len(cold_homes) > 1
        router.run()

    def test_random_policy_never_counts_affinity(self, engines):
        router = SLORouter(
            make_fleet(engines, 3), policy="random", seed=3
        )
        for i in range(6):
            router.route(f" q{i}", max_new_tokens=4,
                         prefix="grp-00/sys")
        assert router.affinity_hits == 0
        router.run()

    def test_fleet_streams_match_single_engine(self, engines):
        prompts = [f"fleet parity {i}" for i in range(8)]
        single = make_frontdoor(engines)
        ref_ids = [
            single.submit(p, max_new_tokens=8, stop_at_eos=False)
            for p in prompts
        ]
        ref = single.run()
        router = SLORouter(make_fleet(engines, 3), seed=0)
        gids = [
            router.route(p, max_new_tokens=8, stop_at_eos=False)
            for p in prompts
        ]
        out = router.run()
        for rid, gid in zip(ref_ids, gids):
            assert out[gid] == ref[rid]

    def test_burning_tenant_steers_off_contended_engine(self, engines):
        burn = BurnEngine()
        fleet = make_fleet(engines, 2, max_slots=1)
        router = SLORouter(fleet, burn_engine=burn, seed=0)
        # Occupy the warm home's only slot (contended: full house,
        # but its queue is empty so affinity still holds for healthy
        # tenants — burn steering, not overflow, must do the work).
        router.route("occupy one", max_new_tokens=16,
                     stop_at_eos=False, prefix="hot/sys")
        contended = router._placements[0][0]
        router.step()

        class FakeBurn:
            def tenant_burn_state(self, tenant):
                return "fast_burn" if tenant == "burny" else "ok"

        router._burn = FakeBurn()
        gid = router.route("burning request", tenant="burny",
                           max_new_tokens=4, prefix="hot/sys")
        # Affinity says the contended engine; burn steering overrides.
        assert router._placements[gid][0] != contended
        router._burn = None
        # A healthy tenant keeps following affinity onto that engine.
        ok = router.route("healthy request", max_new_tokens=4,
                          prefix="hot/sys")
        assert router._placements[ok][0] == contended
        router.run()

    def test_shed_reconciliation_surfaces_global_ids(self, engines):
        fleet = [
            make_frontdoor(engines, max_slots=1, max_queue=1)
        ]
        router = SLORouter(fleet, seed=0)
        kept = router.route("first", max_new_tokens=12,
                            stop_at_eos=False)
        router.step()  # first occupies the slot
        router.route("second", max_new_tokens=4)  # fills the queue
        refused = router.route("third", max_new_tokens=4)
        assert refused is None
        assert router.shed  # global-scope shed record exists
        out = router.run()
        assert kept in out

    def test_decision_log_bounded_and_typed(self, engines):
        router = SLORouter(make_fleet(engines, 2), seed=0)
        router.route("decided", max_new_tokens=2)
        dec = router.decisions[-1]
        assert isinstance(dec, RouterDecision)
        assert dec.engine in (0, 1)
        assert RouterDecision.__slots__  # hot-path record stays slotted
        router.run()


# ---- rebalancing under failure -----------------------------------------


class TestEngineKill:
    def test_kill_loses_zero_requests_and_keeps_parity(self, engines):
        """Mixed plain + prefixed traffic across a kill: every stream
        matches the uninterrupted single-engine reference."""
        specs = [
            (f"kill parity {i}",
             f"grp-{i % 2:02d}/sys" if i % 3 else None)
            for i in range(9)
        ]
        single = make_frontdoor(engines)
        ref_ids = [
            single.submit(p, max_new_tokens=10, stop_at_eos=False,
                          prefix=g)
            for p, g in specs
        ]
        ref = single.run()
        router = SLORouter(make_fleet(engines, 3), seed=0)
        gids = [
            router.route(p, max_new_tokens=10, stop_at_eos=False,
                         prefix=g)
            for p, g in specs
        ]
        for _ in range(2):
            router.step()
        victim = router.live_engines()[0]
        moved = router.kill_engine(victim)
        assert victim not in router.live_engines()
        out = router.run()
        assert len(out) == len(specs)  # zero lost across the kill
        assert router.rebalanced == moved
        for rid, gid in zip(ref_ids, gids):
            assert out[gid] == ref[rid]

    def test_kill_mid_run_stream_parity_no_prefix(self, engines):
        prompts = [f"kill stream {i}" for i in range(8)]
        refs = {
            p: spec_reference(engines, p, 16) for p in prompts
        }
        router = SLORouter(make_fleet(engines, 3), seed=1)
        gids = {
            router.route(p, max_new_tokens=16, stop_at_eos=False): p
            for p in prompts
        }
        for _ in range(2):
            router.step()
        moved = router.kill_engine(1)
        out = router.run()
        assert len(out) == len(prompts)
        for gid, p in gids.items():
            assert out[gid] == refs[p], p
        assert moved >= 1  # the kill actually rebalanced live work

    def test_kill_rehomes_warm_groups(self, engines):
        router = SLORouter(make_fleet(engines, 3), seed=0)
        router.route("warm it", max_new_tokens=2, prefix="grp-07/sys")
        home = router._placements[0][0]
        router.run()
        router.kill_engine(home)
        assert any(
            "grp-07/sys" in router._warm[i]
            for i in router.live_engines()
        )
        gid = router.route("after kill", max_new_tokens=2,
                           prefix="grp-07/sys")
        assert router._placements[gid][0] in router.live_engines()
        router.run()

    def test_kill_last_engine_refuses_routing(self, engines):
        router = SLORouter(make_fleet(engines, 1), seed=0)
        router.kill_engine(0)
        with pytest.raises(RuntimeError):
            router.route("nowhere to go", max_new_tokens=2)


# ---- loadgen prefix groups (satellite) ---------------------------------


class TestLoadgenPrefixGroups:
    def test_weights_normalized_and_tenant_shifted(self):
        from tpuslo.cli.loadgen import prefix_group_weights

        for tenant_idx in range(4):
            w = prefix_group_weights(tenant_idx, 4)
            assert len(w) == 4
            assert abs(sum(w) - 1.0) < 1e-9
            # each tenant's heaviest group is its own shifted slot
            assert max(range(4), key=lambda g: w[g]) == tenant_idx % 4

    def test_invalid_group_count_raises(self):
        from tpuslo.cli.loadgen import prefix_group_weights

        with pytest.raises(ValueError):
            prefix_group_weights(0, 0)

    def test_synthesize_distributes_over_groups(self):
        from tpuslo.cli.loadgen import synthesize_requests

        reqs = synthesize_requests(
            rps=20.0, duration_s=20.0, seed=7, tenants=4,
            prefix_rate=1.0, prefix_groups=8,
        )
        groups = {r["prefix_group"] for r in reqs if r.get("prefix_group")}
        assert len(groups) == 8
        assert all(g.startswith("grp-") for g in groups)

    def test_single_group_keeps_legacy_per_tenant_prefix(self):
        from tpuslo.cli.loadgen import synthesize_requests

        reqs = synthesize_requests(
            rps=5.0, duration_s=10.0, seed=7, tenants=2,
            prefix_rate=1.0, prefix_groups=1,
        )
        for r in reqs:
            if r.get("prefix_group"):
                assert r["prefix_group"].endswith("/sys")
                assert not r["prefix_group"].startswith("grp-")


# ---- metrics bridge (satellite) ----------------------------------------


class TestFrontDoorMetricsBridge:
    def test_observer_contract_and_series(self, engines):
        prometheus_client = pytest.importorskip("prometheus_client")
        from tpuslo.metrics.registry import AgentMetrics

        metrics = AgentMetrics(
            registry=prometheus_client.CollectorRegistry()
        )
        obs = metrics.frontdoor_observer(engine="0")
        # full FrontDoorObserver surface, including the new resumed()
        for hook in ("admitted", "shed", "preempted", "resumed",
                     "completed"):
            assert hasattr(FrontDoorObserver, hook)
        burn = BurnEngine()
        burn.demote_tenant("lowly")
        fd = make_frontdoor(
            engines, paged=True, burn_engine=burn, observer=obs,
        )
        for i in range(2):
            fd.submit(f"metrics low {i}", tenant="lowly",
                      max_new_tokens=20, stop_at_eos=False)
        for _ in range(2):
            fd.step()
        fd.submit("metrics vip", tenant="vip", max_new_tokens=6,
                  stop_at_eos=False)
        fd.run()

        def value(metric, **labels):
            for family in metric.collect():
                for sample in family.samples:
                    if sample.name.endswith("_total") and all(
                        sample.labels.get(k) == v
                        for k, v in labels.items()
                    ):
                        return sample.value
            return 0.0

        assert value(metrics.frontdoor_admitted, tenant="lowly") >= 2
        assert value(
            metrics.frontdoor_preemptions, tenant="lowly"
        ) >= 1
        assert value(metrics.frontdoor_resumes, tenant="lowly") >= 1
        assert value(
            metrics.frontdoor_completed_tokens, tenant="vip"
        ) >= 6.0

    def test_shed_series_labelled_by_reason(self, engines):
        prometheus_client = pytest.importorskip("prometheus_client")
        from tpuslo.metrics.registry import AgentMetrics

        metrics = AgentMetrics(
            registry=prometheus_client.CollectorRegistry()
        )
        obs = metrics.frontdoor_observer(engine="1")
        fd = make_frontdoor(
            engines, max_slots=1, max_queue=1, observer=obs,
        )
        fd.submit("occupy", max_new_tokens=12, stop_at_eos=False)
        fd.step()
        fd.submit("queued", max_new_tokens=2)
        assert fd.submit("refused", max_new_tokens=2) is None
        found = False
        for family in metrics.frontdoor_shed.collect():
            for sample in family.samples:
                if (
                    sample.name.endswith("_total")
                    and sample.labels.get("reason") == "queue_full"
                    and sample.labels.get("engine") == "1"
                    and sample.value >= 1
                ):
                    found = True
        assert found
        fd.run()
