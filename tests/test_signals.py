"""Signal-layer tests. Reference model: pkg/signals/*_test.go."""

from datetime import datetime, timezone

import pytest

from tpuslo import collector, schema, signals

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)
META = signals.Metadata(
    node="tpu-vm-0",
    namespace="llm",
    pod="rag-service-abc",
    container="rag",
    pid=1234,
    tid=1234,
    tpu_chip="accel0",
    slice_id="v5e-8-slice0",
)


def make_sample(fault="baseline", idx=0):
    return collector.build_synthetic_sample(fault, idx, TS, collector.SampleMeta())


class TestConstants:
    def test_signal_counts(self):
        assert len(signals.CPU_SIGNALS) == 12
        assert len(signals.TPU_SIGNALS) == 11
        assert len(signals.ALL_SIGNALS) == 23

    def test_mode_signal_sets(self):
        assert len(signals.supported_signals_for_mode(signals.CAPABILITY_TPU_FULL)) == 23
        assert len(signals.supported_signals_for_mode(signals.CAPABILITY_CORE_FULL)) == 12
        assert signals.supported_signals_for_mode(signals.CAPABILITY_BCC_DEGRADED) == [
            "dns_latency_ms",
            "tcp_retransmits_total",
        ]

    def test_disable_order_covers_all_and_tpu_first(self):
        order = signals.disable_order()
        assert sorted(order) == sorted(signals.ALL_SIGNALS)
        # All TPU signals shed before any kernel probe.
        assert set(order[:11]) == set(signals.TPU_SIGNALS)

    def test_thresholds_and_units_complete(self):
        for name in signals.ALL_SIGNALS:
            assert name in signals.SIGNAL_THRESHOLDS
            assert name in signals.SIGNAL_UNITS


class TestGenerator:
    def test_tpu_full_emits_21_events(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL, enricher=None)
        events = gen.generate(make_sample(), META)
        assert len(events) == 21
        for event in events:
            schema.validate(event.to_dict(), schema.SCHEMA_PROBE_EVENT)

    def test_capability_filters_requested_signals(self):
        gen = signals.Generator(
            signals.CAPABILITY_BCC_DEGRADED,
            signal_set=["dns_latency_ms", "xla_compile_ms"],
        )
        assert gen.enabled_signals() == ["dns_latency_ms"]

    def test_ici_drop_elevates_ici_signals(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
        events = {e.signal: e for e in gen.generate(make_sample("ici_drop"), META)}
        assert events["ici_link_retries_total"].status == "error"
        assert events["ici_collective_latency_ms"].status == "error"
        assert events["dns_latency_ms"].status == "ok"

    def test_recompile_storm_elevates_compile_signal(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
        events = {e.signal: e for e in gen.generate(make_sample("xla_recompile_storm"), META)}
        assert events["xla_compile_ms"].status == "error"
        assert events["xla_compile_ms"].value == 3200
        assert events["runqueue_delay_ms"].status == "warning"

    def test_tpu_events_carry_accelerator_identity(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
        events = {e.signal: e for e in gen.generate(make_sample("hbm_pressure", idx=6), META)}
        hbm = events["hbm_alloc_stall_ms"]
        assert hbm.tpu is not None
        assert hbm.tpu.chip == "accel0"
        assert hbm.tpu.slice_id == "v5e-8-slice0"
        assert hbm.tpu.launch_id == 7  # collector-req-0007
        assert events["dns_latency_ms"].tpu is None

    def test_provider_throttle_sets_errno(self):
        gen = signals.Generator(signals.CAPABILITY_CORE_FULL)
        events = {e.signal: e for e in gen.generate(make_sample("provider_throttle"), META)}
        assert events["connect_latency_ms"].errno == 110
        assert events["dns_latency_ms"].errno is None

    def test_disable_highest_cost_order(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
        shed = gen.disable_highest_cost()
        assert shed == "device_idle_gap_ms"
        assert shed not in gen.enabled_signals()
        # Exhaust the full set.
        count = 1
        while gen.disable_highest_cost() is not None:
            count += 1
        assert count == 21
        assert gen.disable_highest_cost() is None
        assert gen.generate(make_sample(), META) == []

    def test_disable_specific_signal(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
        assert gen.disable("dns_latency_ms") is True
        assert gen.disable("dns_latency_ms") is False

    def test_restore_one_reverses_shed_order(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
        first = gen.disable_highest_cost()
        second = gen.disable_highest_cost()
        assert gen.shed_signals() == [first, second]
        # Reverse cost order: the cheapest still-shed probe returns
        # first, ramping cost back gradually.
        assert gen.restore_one() == second
        assert second in gen.enabled_signals()
        assert gen.restore_one() == first
        assert gen.restore_one() is None
        assert gen.shed_signals() == []

    def test_restore_skips_manually_disabled_signals(self):
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
        shed = gen.disable_highest_cost()
        gen.set_signals(["dns_latency_ms"])  # operator override
        # The override supersedes shed history: nothing to restore.
        assert gen.restore_one() is None
        assert shed not in gen.enabled_signals()

    def test_static_enricher_fills_blanks(self):
        enricher = signals.StaticMetadataEnricher(META)
        gen = signals.Generator(signals.CAPABILITY_TPU_FULL, enricher=enricher)
        events = gen.generate(make_sample(), signals.Metadata())
        assert events[0].node == "tpu-vm-0"
        assert events[0].pod == "rag-service-abc"


class TestMetadata:
    def test_parse_cgroup_identity(self):
        content = (
            "0::/kubepods.slice/kubepods-burstable.slice/"
            "pod8f2b9c1a-1111-2222-3333-444455556666/"
            "cri-containerd-0123456789abcdef0123456789abcdef.scope\n"
        )
        pod, container = signals.parse_cgroup_identity(content)
        assert pod == "8f2b9c1a-1111-2222-3333-444455556666"
        assert container == "0123456789abcdef0123456789abcdef"

    def test_proc_enricher_missing_pid_noop(self, tmp_path):
        enricher = signals.ProcMetadataEnricher(proc_root=str(tmp_path))
        meta = signals.Metadata(pid=99999)
        assert enricher.enrich(meta) == meta

    def test_proc_enricher_reads_cgroup(self, tmp_path):
        pid_dir = tmp_path / "4242"
        pid_dir.mkdir()
        (pid_dir / "cgroup").write_text(
            "0::/kubepods/podaabbccdd-0000-1111-2222-333344445555/"
            "0123456789abcdef0123456789abcdef\n"
        )
        enricher = signals.ProcMetadataEnricher(proc_root=str(tmp_path))
        out = enricher.enrich(signals.Metadata(pid=4242))
        assert out.pod == "aabbccdd-0000-1111-2222-333344445555"

    def test_tpu_enricher_env(self, tmp_path):
        (tmp_path / "accel0").touch()
        (tmp_path / "accel1").touch()
        enricher = signals.TPUMetadataEnricher(
            dev_glob=str(tmp_path / "accel*"),
            env={"TPU_WORKER_ID": "2", "MEGASCALE_SLICE_ID": "slice-7"},
        )
        out = enricher.enrich(signals.Metadata())
        assert out.tpu_chip == "accel0"
        assert out.slice_id == "slice-7"
        assert out.host_index == 2
        assert enricher.discover_chips() == ["accel0", "accel1"]


class TestMode:
    def test_detect_no_btf_degraded(self, tmp_path):
        mode = signals.detect_capability_mode(
            btf_path=str(tmp_path / "missing"),
            accel_glob=str(tmp_path / "accel*"),
            env={},
        )
        assert mode == signals.CAPABILITY_BCC_DEGRADED

    def test_detect_btf_no_tpu_core_full(self, tmp_path):
        btf = tmp_path / "vmlinux"
        btf.touch()
        mode = signals.detect_capability_mode(
            btf_path=str(btf), accel_glob=str(tmp_path / "accel*"), env={}
        )
        assert mode == signals.CAPABILITY_CORE_FULL

    def test_detect_tpu_full(self, tmp_path):
        btf = tmp_path / "vmlinux"
        btf.touch()
        (tmp_path / "accel0").touch()
        mode = signals.detect_capability_mode(
            btf_path=str(btf), accel_glob=str(tmp_path / "accel*"), env={}
        )
        assert mode == signals.CAPABILITY_TPU_FULL

    def test_parse_explicit_mode(self):
        assert signals.parse_capability_mode("core_full") == "core_full"
        with pytest.raises(ValueError):
            signals.parse_capability_mode("quantum")
