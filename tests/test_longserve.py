"""Long-context sp serving: parity with the single-device engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpuslo.models.llama import (
    decode_step,
    init_kv_cache,
    init_params,
    llama_tiny,
    prefill,
)
from tpuslo.models.longserve import sp_generate, sp_prefill, sp_decode_step


def _mesh(sp: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:sp]), ("sp",))


def _cfg(max_seq_len=256):
    return llama_tiny(max_seq_len=max_seq_len)


def _ref_last_logits(params, tokens, cfg):
    cache = init_kv_cache(cfg, tokens.shape[0])
    logits, cache = prefill(params, tokens, cache, cfg)
    return logits, cache


@pytest.mark.parametrize("sp", [2, 4])
def test_sp_prefill_matches_plain(sp):
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)

    ref, _ = _ref_last_logits(params, tokens, cfg)
    got, cache = sp_prefill(params, tokens, cfg, _mesh(sp))
    err = float(jnp.max(jnp.abs(ref - got)))
    assert err < 5e-2, f"sp={sp} prefill logits error {err}"
    assert int(cache["tail_len"]) == 0
    assert cache["k_ctx"].shape[2] == 64


def test_sp_decode_matches_plain_chain():
    cfg = _cfg()
    sp = 4
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)

    # Reference: plain prefill + 6 decode steps.
    ref_logits, ref_cache = _ref_last_logits(params, tokens, cfg)
    ref_tokens = [jnp.argmax(ref_logits, -1).astype(jnp.int32)]
    for _ in range(5):
        logits, ref_cache = decode_step(params, ref_tokens[-1], ref_cache, cfg)
        ref_tokens.append(jnp.argmax(logits, -1).astype(jnp.int32))
    ref_seq = jnp.stack(ref_tokens, axis=1)

    got_seq = sp_generate(params, tokens, cfg, _mesh(sp), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ref_seq), np.asarray(got_seq))


def test_sp_decode_logits_close():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, cfg.vocab_size)
    mesh = _mesh(2)

    ref_logits, ref_cache = _ref_last_logits(params, tokens, cfg)
    tok = jnp.argmax(ref_logits, -1).astype(jnp.int32)
    ref_step, _ = decode_step(params, tok, ref_cache, cfg)

    sp_logits, sp_cache = sp_prefill(params, tokens, cfg, mesh)
    got_step, sp_cache = sp_decode_step(params, tok, sp_cache, cfg, mesh)
    assert int(sp_cache["tail_len"]) == 1
    err = float(jnp.max(jnp.abs(ref_step - got_step)))
    assert err < 5e-2, f"decode logits error {err}"


def test_sp_prefill_rejects_indivisible():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        sp_prefill(params, tokens, cfg, _mesh(4))


def test_sp_tail_budget_guard():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 32), jnp.int32)
    with pytest.raises(ValueError, match="tail_max"):
        sp_generate(params, tokens, cfg, _mesh(2), max_new_tokens=8, tail_max=8)


def test_sp_decode_tail_full_raises():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 32), jnp.int32)
    mesh = _mesh(2)
    _, cache = sp_prefill(params, tokens, cfg, mesh, tail_max=2)
    tok = jnp.zeros((1,), jnp.int32)
    _, cache = sp_decode_step(params, tok, cache, cfg, mesh)
    _, cache = sp_decode_step(params, tok, cache, cfg, mesh)
    with pytest.raises(ValueError, match="tail buffer full"):
        sp_decode_step(params, tok, cache, cfg, mesh)

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


def test_sp_int8_context_kv_structure_and_bytes():
    """int8 context: dict leaves, ~half the context HBM, tail bf16."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, 64), 0, cfg.vocab_size
    )
    _, cache = sp_prefill(params, tokens, cfg, _mesh(4), kv_dtype="int8")
    assert set(cache["k_ctx"].keys()) == {"q", "s"}
    assert cache["k_ctx"]["q"].dtype == jnp.int8
    assert cache["k_tail"].dtype == cfg.dtype  # tail stays bf16
    bf16_bytes = (
        np.prod(cache["k_ctx"]["q"].shape)
        * jnp.dtype(cfg.dtype).itemsize
    )
    int8_bytes = cache["k_ctx"]["q"].nbytes + cache["k_ctx"]["s"].nbytes
    assert int8_bytes < 0.8 * bf16_bytes


def test_sp_int8_context_decode_close_to_bf16():
    """Quantizing the frozen context must not meaningfully move the
    decode logits (per-row int8 scales: worst-case rounding is
    scale/2)."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (1, 32), 0, cfg.vocab_size
    )
    # mesh(2) at the (1, 32) shape: the bf16 prefill/decode compiles
    # are shared with test_sp_decode_logits_close (memoized builders),
    # so this test pays only for its int8 halves.  Ring size doesn't
    # affect the quantization-closeness property under test.
    mesh = _mesh(2)
    logits_bf, cache_bf = sp_prefill(params, tokens, cfg, mesh)
    logits_i8, cache_i8 = sp_prefill(
        params, tokens, cfg, mesh, kv_dtype="int8"
    )
    # Prefill logits are computed pre-quantization: identical paths.
    assert float(jnp.max(jnp.abs(logits_bf - logits_i8))) < 1e-5

    tok = jnp.argmax(logits_bf, -1).astype(jnp.int32)
    for _ in range(3):
        lb, cache_bf = sp_decode_step(params, tok, cache_bf, cfg, mesh)
        li, cache_i8 = sp_decode_step(params, tok, cache_i8, cfg, mesh)
        assert float(jnp.max(jnp.abs(lb - li))) < 0.25
        tok = jnp.argmax(lb, -1).astype(jnp.int32)


def test_sp_generate_int8_runs():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (1, 32), 0, cfg.vocab_size
    )
    out = sp_generate(
        params, tokens, cfg, _mesh(2), max_new_tokens=4, kv_dtype="int8"
    )
    assert out.shape == (1, 4)


def test_sp_prefill_rejects_unknown_kv_dtype():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 32), jnp.int32)
    with pytest.raises(ValueError):
        sp_prefill(params, tokens, cfg, _mesh(2), kv_dtype="fp8")
