"""ProbeSupervisor: heartbeats, restarts, backoff, flap shed, hold-down.

Includes the supervisor ↔ overhead-guard interplay contract: a signal
the supervisor shed for flapping must not be immediately restored by
``ShedRecoveryPolicy``-authorized ``restore_one`` calls, and the
restore order stays reverse-cost when the hold-down expires.
"""

from __future__ import annotations

from tpuslo.runtime import ProbeSupervisor, SupervisorConfig
from tpuslo.runtime.supervisor import (
    ACTION_FLAP_SHED,
    ACTION_RESTART_FAILED,
    ACTION_RESTARTED,
    REASON_FLAPPING,
)
from tpuslo.safety import ShedRecoveryPolicy
from tpuslo.safety.overhead_guard import OverheadResult


class FakeClock:
    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def make_supervisor(
    clock,
    restart_ok=True,
    heartbeat_timeout_s=10.0,
    flap_restarts=3,
    flap_window_s=100.0,
    flap_holddown_s=300.0,
):
    calls = {"restarts": [], "sheds": []}

    def restart(signal):
        calls["restarts"].append(signal)
        return restart_ok

    def shed(signal, reason):
        calls["sheds"].append((signal, reason))

    supervisor = ProbeSupervisor(
        config=SupervisorConfig(
            heartbeat_timeout_s=heartbeat_timeout_s,
            restart_backoff_base_s=1.0,
            restart_backoff_cap_s=60.0,
            flap_restarts=flap_restarts,
            flap_window_s=flap_window_s,
            flap_holddown_s=flap_holddown_s,
        ),
        restart=restart,
        shed=shed,
        clock=clock,
    )
    return supervisor, calls


class TestHeartbeat:
    def test_fresh_heartbeat_means_no_action(self):
        clock = FakeClock()
        supervisor, calls = make_supervisor(clock)
        supervisor.watch(["dns_latency_ms"])
        clock.advance(5.0)
        supervisor.beat("dns_latency_ms")
        clock.advance(5.0)
        assert supervisor.evaluate() == []
        assert calls["restarts"] == []
        assert supervisor.heartbeat_age_s("dns_latency_ms") == 5.0

    def test_beat_on_unwatched_signal_is_ignored(self):
        supervisor, _ = make_supervisor(FakeClock())
        supervisor.beat("never_watched")  # no raise
        assert supervisor.heartbeat_age_s("never_watched") == 0.0

    def test_dead_probe_is_restarted(self):
        clock = FakeClock()
        supervisor, calls = make_supervisor(clock)
        supervisor.watch(["dns_latency_ms", "tcp_retransmits_total"])
        supervisor.beat("dns_latency_ms")  # proven alive once
        clock.advance(11.0)
        supervisor.beat("tcp_retransmits_total")
        events = supervisor.evaluate()
        assert [e.action for e in events] == [ACTION_RESTARTED]
        assert calls["restarts"] == ["dns_latency_ms"]
        # A successful restart grants a fresh heartbeat window.
        assert supervisor.evaluate() == []

    def test_unproven_quiet_probe_is_never_restarted(self):
        """A signal that legitimately emits nothing (zero retransmits
        on a healthy network) must not be churned or flap-shed."""
        clock = FakeClock()
        supervisor, calls = make_supervisor(clock)
        supervisor.watch(["tcp_retransmits_total"])
        for _ in range(50):
            clock.advance(60.0)
            assert supervisor.evaluate() == []
        assert calls["restarts"] == []
        assert calls["sheds"] == []


class TestBackoff:
    def test_failed_restarts_back_off_exponentially(self):
        clock = FakeClock()
        supervisor, calls = make_supervisor(clock, restart_ok=False)
        supervisor.watch(["dns_latency_ms"])
        supervisor.beat("dns_latency_ms")
        clock.advance(11.0)
        assert supervisor.evaluate()[0].action == ACTION_RESTART_FAILED
        assert supervisor.evaluate() == []  # inside 1s backoff
        clock.advance(1.0)
        assert supervisor.evaluate()[0].action == ACTION_RESTART_FAILED
        clock.advance(1.0)
        assert supervisor.evaluate() == []  # backoff doubled to 2s
        clock.advance(1.0)
        assert supervisor.evaluate()[0].action == ACTION_RESTART_FAILED
        assert len(calls["restarts"]) == 3
        assert supervisor.restarts_total == 3


class TestFlapShed:
    def test_k_restarts_in_window_sheds_with_reason(self):
        clock = FakeClock()
        supervisor, calls = make_supervisor(clock, restart_ok=True)
        supervisor.watch(["dns_latency_ms"])
        supervisor.beat("dns_latency_ms")
        # Probe "recovers" after each restart, then dies again: the
        # flap pattern a dead-probe counter alone cannot see.
        for _ in range(3):
            clock.advance(11.0)
            events = supervisor.evaluate()
            assert events and events[0].action == ACTION_RESTARTED
        clock.advance(11.0)
        events = supervisor.evaluate()
        assert [e.action for e in events] == [ACTION_FLAP_SHED]
        assert calls["sheds"] == [("dns_latency_ms", REASON_FLAPPING)]
        assert supervisor.shed_reasons == {
            "dns_latency_ms": REASON_FLAPPING
        }
        assert supervisor.flap_sheds_total == 1
        # Shed probes are no longer supervised (no restart storms).
        clock.advance(50.0)
        assert supervisor.evaluate() == []

    def test_old_restarts_age_out_of_the_window(self):
        clock = FakeClock()
        supervisor, calls = make_supervisor(
            clock, restart_ok=True, flap_window_s=30.0
        )
        supervisor.watch(["dns_latency_ms"])
        supervisor.beat("dns_latency_ms")
        for _ in range(6):
            clock.advance(40.0)  # each restart falls out of the window
            events = supervisor.evaluate()
            assert [e.action for e in events] == [ACTION_RESTARTED]
        assert calls["sheds"] == []


class TestHoldDown:
    def _flap_shed_signal(self, clock, supervisor):
        supervisor.watch(["dns_latency_ms"])
        supervisor.beat("dns_latency_ms")
        for _ in range(3):
            clock.advance(11.0)
            supervisor.evaluate()
        clock.advance(11.0)
        supervisor.evaluate()

    def test_may_restore_blocks_until_holddown_expires(self):
        clock = FakeClock()
        supervisor, _ = make_supervisor(clock, flap_holddown_s=300.0)
        self._flap_shed_signal(clock, supervisor)
        assert not supervisor.may_restore("dns_latency_ms")
        clock.advance(299.0)
        assert not supervisor.may_restore("dns_latency_ms")
        clock.advance(2.0)
        assert supervisor.may_restore("dns_latency_ms")
        assert supervisor.shed_reasons == {}  # hold-down cleared

    def test_unheld_signals_are_always_restorable(self):
        supervisor, _ = make_supervisor(FakeClock())
        assert supervisor.may_restore("anything")

    def test_note_restored_resumes_supervision(self):
        clock = FakeClock()
        supervisor, _ = make_supervisor(clock)
        self._flap_shed_signal(clock, supervisor)
        clock.advance(301.0)
        supervisor.note_restored("dns_latency_ms")
        assert "dns_latency_ms" in supervisor.snapshot()["watched"]

    def test_holddown_survives_snapshot_restore(self):
        clock = FakeClock()
        supervisor, _ = make_supervisor(clock, flap_holddown_s=300.0)
        self._flap_shed_signal(clock, supervisor)
        clock.advance(100.0)
        exported = supervisor.export_state()

        clock2 = FakeClock(90_000.0)  # a different monotonic epoch
        restored, _ = make_supervisor(clock2, flap_holddown_s=300.0)
        restored.restore_state(exported)
        assert not restored.may_restore("dns_latency_ms")
        assert restored.shed_reasons == {
            "dns_latency_ms": REASON_FLAPPING
        }
        clock2.advance(201.0)  # 100s already served before the crash
        assert restored.may_restore("dns_latency_ms")


class TestRecoveryPolicyInterplay:
    """Flap hold-down outranks the overhead-guard recovery streak."""

    @staticmethod
    def _under_budget() -> OverheadResult:
        return OverheadResult(
            valid=True, cpu_pct=0.5, budget_pct=3.0, over_budget=False
        )

    def test_flap_shed_is_not_restored_by_recovery_streak(self):
        """The agent-loop contract, end to end against a fake manager:

        guard-shed signals restore in reverse cost order as streaks
        authorize them, but a flap-shed signal parks the restore until
        its hold-down expires — and then restores last-shed-first.
        """
        clock = FakeClock()
        supervisor, _ = make_supervisor(clock, flap_holddown_s=300.0)

        # Fake ProbeManager shed machinery: shed order cheap→costly is
        # [syscall (guard), dns (flap), tcp (guard)]; restore pops the
        # tail (reverse cost order).
        shed_list = ["syscall_latency_ms"]

        def flap_shed(signal, reason):
            shed_list.append(signal)

        supervisor._shed = flap_shed
        supervisor.watch(["dns_latency_ms"])
        supervisor.beat("dns_latency_ms")
        for _ in range(3):
            clock.advance(11.0)
            supervisor.evaluate()
        clock.advance(11.0)
        supervisor.evaluate()
        shed_list.append("tcp_retransmits_total")  # later guard shed
        assert shed_list == [
            "syscall_latency_ms",
            "dns_latency_ms",
            "tcp_retransmits_total",
        ]

        recovery = ShedRecoveryPolicy(cycles=2)
        restored_order = []
        for _ in range(40):
            clock.advance(1.0)
            if not recovery.note(self._under_budget()):
                continue
            candidate = shed_list[-1] if shed_list else None
            if candidate is None:
                continue
            if not supervisor.may_restore(candidate):
                continue  # held down: the streak is spent, not the shed
            restored_order.append(shed_list.pop())
            supervisor.note_restored(candidate)

        # tcp restores immediately; dns is held (clock only advanced
        # ~40s into the 300s hold-down) and blocks syscall behind it —
        # restore order stays strictly reverse-cost, never reordered
        # around the hold.
        assert restored_order == ["tcp_retransmits_total"]
        assert shed_list == ["syscall_latency_ms", "dns_latency_ms"]

        # Hold-down expiry releases the rest, still reverse-cost.
        clock.advance(400.0)
        for _ in range(40):
            clock.advance(1.0)
            if not recovery.note(self._under_budget()):
                continue
            candidate = shed_list[-1] if shed_list else None
            if candidate is None:
                continue
            if not supervisor.may_restore(candidate):
                continue
            restored_order.append(shed_list.pop())
            supervisor.note_restored(candidate)
        assert restored_order == [
            "tcp_retransmits_total",
            "dns_latency_ms",
            "syscall_latency_ms",
        ]

    def test_recovery_streak_is_consumed_by_a_held_candidate(self):
        """A blocked restore does not bank the authorization."""
        clock = FakeClock()
        supervisor, _ = make_supervisor(clock, flap_holddown_s=300.0)
        supervisor.watch(["dns_latency_ms"])
        supervisor.beat("dns_latency_ms")
        for _ in range(3):
            clock.advance(11.0)
            supervisor.evaluate()
        clock.advance(11.0)
        supervisor.evaluate()

        recovery = ShedRecoveryPolicy(cycles=3)
        authorized = 0
        for _ in range(9):
            if recovery.note(self._under_budget()):
                authorized += 1
                assert not supervisor.may_restore("dns_latency_ms")
        # Three full streaks authorized; none restored; streak state
        # reset each time (no instant restore after expiry mid-streak).
        assert authorized == 3
        assert recovery.streak == 0
