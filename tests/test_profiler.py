"""Continuous device profiler (ISSUE 20): governor, parity, emission.

The load-bearing invariants:

* the overhead governor degrades the stride when measured capture cost
  (amortised over the stride) sustains above the 3% budget, re-engages
  the base stride on sustained headroom, and NEVER drops a window that
  carries an eviction notice — degradation trades frequency, not
  eviction evidence;
* per-window ledger bucket deltas sum exactly to one big
  ``build_ledger`` over the spliced capture (``concat_window_docs``)
  — the windowing itself must not create or destroy device time;
* every emitted probe payload is contract-valid against
  ``SCHEMA_PROBE_EVENT`` and carries the same values the attribution
  map sees (one source);
* both join rates ride every window, raw strictly below tiered on the
  seeded lane (the 0.556 lesson);
* export_state/restore_state round-trips the governor and window ring.
"""

from __future__ import annotations

import pytest

from tpuslo.deviceplane.ledger import build_ledger
from tpuslo.deviceplane.profiler import (
    MAX_OVERHEAD_PCT,
    MIN_WINDOW_SUBSTANTIVE_JOIN,
    ContinuousProfiler,
    ProfilerReport,
    concat_window_docs,
    run_profiler_sweep,
    seeded_cost_model,
)
from tpuslo.otel.xla_spans import parse_trace_events
from tpuslo.schema import SCHEMA_PROBE_EVENT, is_valid, validate
from tpuslo.signals import constants as sig


def make_profiler(**kw):
    defaults = dict(
        source="synthetic",
        seed=1337,
        cycle_budget_ms=1000.0,
        stride_cycles=2,
        grace_cycles=2,
        window_steps=6,
        history=64,
        node="test-host",
        pod="test-pod",
    )
    defaults.update(kw)
    return ContinuousProfiler(**defaults)


# ---- stride / capture cadence ------------------------------------------


class TestCadence:
    def test_tick_captures_on_stride(self):
        prof = make_profiler(stride_cycles=3)
        results = [prof.tick() for _ in range(9)]
        windows = [w for w in results if w is not None]
        assert len(windows) == 3
        assert [w.cycle for w in windows] == [3, 6, 9]
        assert [w.index for w in windows] == [0, 1, 2]

    def test_windows_are_deterministic_per_index(self):
        a = make_profiler(stride_cycles=1)
        b = make_profiler(stride_cycles=1)
        wa = [a.tick() for _ in range(4)]
        wb = [b.tick() for _ in range(4)]
        for x, y in zip(wa, wb):
            assert x.idle_gap_ms == y.idle_gap_ms
            assert x.window_ms == y.window_ms
            assert x.launches == y.launches

    def test_history_ring_trims(self):
        prof = make_profiler(stride_cycles=1, history=3)
        for _ in range(8):
            prof.tick()
        kept = prof.windows()
        assert len(kept) == 3
        assert [w.index for w in kept] == [5, 6, 7]


# ---- the overhead governor ---------------------------------------------


class TestGovernor:
    def test_forced_slow_capture_degrades_stride(self):
        # cost_fn pins the measured cost at 400ms: amortised over a
        # 2-cycle stride against a 1000ms cycle budget that is 20%,
        # far over the 3% budget -> stride must lengthen.
        prof = make_profiler(
            stride_cycles=2,
            grace_cycles=2,
            max_stride_cycles=16,
            cost_fn=lambda _ms: 400.0,
        )
        for _ in range(40):
            prof.tick()
            if prof.degraded:
                break
        assert prof.degraded
        assert prof.stride_cycles > prof.base_stride_cycles
        assert prof.degradations >= 1

    def test_stride_caps_at_max(self):
        prof = make_profiler(
            stride_cycles=2,
            grace_cycles=1,
            max_stride_cycles=8,
            cost_fn=lambda _ms: 900.0,
        )
        for _ in range(200):
            prof.tick()
        assert prof.stride_cycles == 8

    def test_sustained_headroom_reengages(self):
        cost = {"ms": 400.0}
        prof = make_profiler(
            stride_cycles=2,
            grace_cycles=2,
            max_stride_cycles=16,
            cost_fn=lambda _ms: cost["ms"],
        )
        for _ in range(40):
            prof.tick()
            if prof.degraded:
                break
        assert prof.degraded
        # Headroom restored: EMA decays below half budget over the
        # cool streak and the base stride re-engages.
        cost["ms"] = 1.0
        for _ in range(600):
            prof.tick()
            if not prof.degraded:
                break
        assert not prof.degraded
        assert prof.stride_cycles == prof.base_stride_cycles
        assert prof.reengagements >= 1

    def test_eviction_notice_forces_capture_while_degraded(self):
        # The invariant the whole governor defends: degradation trades
        # capture FREQUENCY, never an eviction-bearing window.
        prof = make_profiler(
            stride_cycles=2,
            grace_cycles=2,
            max_stride_cycles=16,
            cost_fn=lambda _ms: 400.0,
        )
        for _ in range(40):
            prof.tick()
            if prof.degraded:
                break
        assert prof.degraded
        prof.notice_eviction()
        window = prof.tick()
        assert window is not None
        assert window.forced is True
        assert window.eviction_events >= 1
        assert prof.windows_forced == 1
        assert prof.eviction_windows >= 1

    def test_eviction_notice_rides_next_stride_capture_when_due(self):
        prof = make_profiler(stride_cycles=1)
        prof.notice_eviction(2)
        window = prof.tick()
        # Capture was already due, so the notice rides rather than
        # forcing: not flagged forced, but the events still land.
        assert window is not None
        assert window.forced is False
        assert window.eviction_events == 2

    def test_overhead_ema_tracks_amortised_cost(self):
        prof = make_profiler(stride_cycles=4, cost_fn=lambda _ms: 40.0)
        for _ in range(4):
            prof.tick()
        # 40ms once per 4 cycles of 1000ms budget = 1% amortised.
        assert prof.overhead_ema_pct == pytest.approx(1.0)
        assert not prof.degraded


# ---- per-window / full-capture ledger parity ---------------------------


class TestLedgerParity:
    def test_window_buckets_sum_to_spliced_capture(self):
        # Orphan helpers stay out of this lane: in a spliced trace a
        # later window's head-of-trace orphans sit after earlier step
        # frames and the frame tier legitimately claims them.
        prof = make_profiler(stride_cycles=1, synthetic_orphan_helpers=0)
        docs, compile_lists = [], []
        per_window: dict[str, float] = {}
        total_us = 0.0
        for _ in range(5):
            w = prof.tick()
            doc, compiles = prof.window_trace_doc(w.index)
            docs.append(doc)
            compile_lists.append(compiles)
            ledger = build_ledger(
                parse_trace_events(doc, include_ops=True), compiles
            )
            for bucket, us in ledger.buckets_us.items():
                per_window[bucket] = per_window.get(bucket, 0.0) + us
            total_us += ledger.total_us
        spliced_doc, spliced_compiles = concat_window_docs(
            docs, compile_lists
        )
        full = build_ledger(
            parse_trace_events(spliced_doc, include_ops=True),
            spliced_compiles,
        )
        assert total_us == pytest.approx(full.total_us, abs=0.5)
        for bucket, us in full.buckets_us.items():
            assert per_window.get(bucket, 0.0) == pytest.approx(
                us, abs=0.5
            ), bucket

    def test_concat_preserves_event_count_and_order(self):
        prof = make_profiler(stride_cycles=1, synthetic_orphan_helpers=0)
        docs = []
        for _ in range(3):
            w = prof.tick()
            doc, _ = prof.window_trace_doc(w.index)
            docs.append(doc)
        spliced, _ = concat_window_docs(docs)
        xs = [e for e in spliced["traceEvents"] if e.get("ph") == "X"]
        n_source = sum(
            sum(1 for e in d["traceEvents"] if e.get("ph") == "X")
            for d in docs
        )
        assert len(xs) == n_source
        # The splice leaves no artificial inter-window seams: windows
        # abut exactly where the previous window's last span ended.
        firsts, lasts = [], []
        cursor = 0
        for d in docs:
            n = sum(1 for e in d["traceEvents"] if e.get("ph") == "X")
            chunk = xs[cursor:cursor + n]
            firsts.append(min(float(e["ts"]) for e in chunk))
            lasts.append(
                max(float(e["ts"]) + float(e.get("dur", 0)) for e in chunk)
            )
            cursor += n
        for prev_end, next_start in zip(lasts, firsts[1:]):
            assert next_start == pytest.approx(prev_end, abs=1e-6)


# ---- emission: contract validity and single-sourcing -------------------


class TestEmission:
    def test_probe_payloads_are_contract_valid(self):
        prof = make_profiler(
            stride_cycles=1,
            slice_id="v5e-8-slice0",
            host_index=1,
        )
        window = prof.tick()
        payloads = prof.probe_payloads(window)
        assert len(payloads) == 4
        for payload in payloads:
            assert is_valid(payload, SCHEMA_PROBE_EVENT)
            validate(payload, SCHEMA_PROBE_EVENT)
        assert {p["signal"] for p in payloads} == {
            sig.SIGNAL_DEVICE_IDLE_GAP_MS,
            sig.SIGNAL_DEVICE_EVICTION_EVENTS,
            sig.SIGNAL_DEVICE_UNEXPLAINED_SHARE,
            sig.SIGNAL_DEVICE_MFU_PCT,
        }
        by_sig = {p["signal"]: p for p in payloads}
        tpu = by_sig[sig.SIGNAL_DEVICE_IDLE_GAP_MS]["tpu"]
        assert tpu["chip"] == "accel0"
        assert tpu["slice_id"] == "v5e-8-slice0"
        assert tpu["host_index"] == 1

    def test_payloads_and_attribution_map_share_values(self):
        prof = make_profiler(stride_cycles=1)
        window = prof.tick()
        by_sig = {
            p["signal"]: p["value"] for p in prof.probe_payloads(window)
        }
        for name, value in prof.window_signal_values(window).items():
            assert by_sig[name] == pytest.approx(value, abs=1e-4)

    def test_both_join_rates_ride_every_window(self):
        prof = make_profiler(stride_cycles=1)
        for _ in range(4):
            window = prof.tick()
            assert 0.0 <= window.raw_join_rate <= 1.0
            # Seeded lane: helpers/warmups carry no exact identity, so
            # raw sits strictly below tiered — if they ever collapse
            # together the single-sourcing broke (the 0.556 lesson).
            assert window.raw_join_rate < window.substantive_join_rate
            assert (
                window.substantive_join_rate
                >= MIN_WINDOW_SUBSTANTIVE_JOIN
            )

    def test_preemption_window_carries_gap_and_eviction(self):
        prof = make_profiler(
            stride_cycles=1,
            synthetic_preempt_window=2,
            synthetic_preempt_gap_ms=250.0,
        )
        windows = [prof.tick() for _ in range(4)]
        hit = windows[2]
        assert hit.eviction_events == 1
        clean_max = max(
            w.idle_gap_ms for w in windows if w.eviction_events == 0
        )
        assert hit.idle_gap_ms > clean_max + 100.0

    def test_roofline_verdict_attaches_with_cost_model(self):
        step_bytes, step_flops, step_dur = seeded_cost_model()
        prof = make_profiler(
            stride_cycles=1,
            bytes_per_step=step_bytes,
            flops_per_step=step_flops,
            step_dur_us=step_dur,
        )
        window = prof.tick()
        assert window.verdict == "memory_bound"
        assert window.mfu_pct > 0.0
        block = prof.window_roofline(window.index)
        assert block["verdict"] == "memory_bound"
        assert block["achieved_gb_per_sec"] > 0.0

    def test_no_cost_model_means_no_invented_mfu(self):
        prof = make_profiler(stride_cycles=1)
        window = prof.tick()
        assert window.mfu_pct == -1.0
        assert window.verdict == ""
        assert prof.window_roofline(window.index) == {}
        # The emitted payload clamps to 0.0 (the schema floor), never
        # a made-up positive MFU.
        by_sig = {
            p["signal"]: p["value"] for p in prof.probe_payloads(window)
        }
        assert by_sig[sig.SIGNAL_DEVICE_MFU_PCT] == 0.0


# ---- state round-trip ---------------------------------------------------


class TestStateRoundTrip:
    def test_export_restore_round_trip(self):
        prof = make_profiler(
            stride_cycles=2,
            grace_cycles=2,
            max_stride_cycles=16,
            cost_fn=lambda _ms: 400.0,
        )
        for _ in range(40):
            prof.tick()
            if prof.degraded:
                break
        prof.notice_eviction()
        prof.tick()
        state = prof.export_state()

        fresh = make_profiler(
            stride_cycles=2, grace_cycles=2, max_stride_cycles=16
        )
        fresh.restore_state(state)
        assert fresh.stats() == prof.stats()
        assert fresh.export_state()["window_index"] == state["window_index"]
        restored = fresh.windows()
        assert [w.to_dict() for w in restored] == state["windows"]
        # The restored profiler resumes the stride where it left off.
        assert fresh.stride_cycles == prof.stride_cycles
        assert fresh.degraded == prof.degraded

    def test_restore_ignores_garbage(self):
        prof = make_profiler()
        prof.restore_state(None)
        prof.restore_state({"windows": [{"index": "bogus"}]})
        assert prof.windows() == []
        assert prof.stats()["cycle"] == 0


# ---- config / construction ---------------------------------------------


class TestConstruction:
    def test_unknown_source_rejected(self):
        with pytest.raises(ValueError):
            ContinuousProfiler(source="perfetto")

    def test_xprof_source_needs_log_dir_and_work(self):
        with pytest.raises(ValueError):
            ContinuousProfiler(source="xprof")
        with pytest.raises(ValueError):
            ContinuousProfiler(source="xprof", log_dir="/tmp/x")

    def test_seeded_cost_model_is_memory_bound_regime(self):
        step_bytes, step_flops, (lo, hi) = seeded_cost_model()
        assert step_bytes > 0 and step_flops > 0
        assert 0 < lo < hi


# ---- the seeded sweep gate ---------------------------------------------


class TestProfilerSweep:
    def test_sweep_passes_at_default_seed(self):
        report = run_profiler_sweep(seed=1337, cycles=12, parity_windows=3)
        assert report.passed, report.failures
        assert (
            report.overhead["overhead_ema_pct"] <= MAX_OVERHEAD_PCT
        )
        assert (
            report.joins["min_substantive_join_rate"]
            >= MIN_WINDOW_SUBSTANTIVE_JOIN
        )
        assert report.governor["degradations"] >= 1
        assert report.governor["reengagements"] >= 1
        assert report.governor["forced_capture_evictions"] >= 1
        assert report.parity["worst_bucket_drift_us"] <= 0.5
        assert report.preemption["top_domain"] == "tpu_preemption"

    def test_report_dict_shape(self):
        report = ProfilerReport(seed=7)
        assert report.passed
        report.failures.append("x")
        data = report.to_dict()
        assert data["passed"] is False
        assert set(data) == {
            "seed",
            "passed",
            "overhead",
            "governor",
            "joins",
            "parity",
            "preemption",
            "failures",
        }
