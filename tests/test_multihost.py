"""Multi-host slice correlation tests.

TPU-native addition (no reference counterpart): collective straggler
attribution across a pod slice — SURVEY.md §2.5, BASELINE.json config 4.
"""

import json


from tpuslo.correlation.multihost import (
    CAUSE_COMPUTE,
    CAUSE_ICI_LINK,
    SliceJoiner,
)
from tpuslo.faultreplay.slice_streams import synthesize_slice_streams


def ingest(joiner, streams):
    for stream in streams:
        joiner.add_all(stream)


class TestStragglerAttribution:
    def test_compute_straggler_detected(self):
        streams = synthesize_slice_streams(
            n_hosts=4, n_launches=6, straggler_host=2, straggler_delay_ms=50.0
        )
        joiner = SliceJoiner(expected_hosts=4)
        ingest(joiner, streams)
        incidents = joiner.incidents()
        assert len(incidents) == 6
        for inc in incidents:
            assert inc.straggler_host == 2
            assert inc.straggler_node == "host-2"
            assert inc.cause == CAUSE_COMPUTE
            assert inc.n_hosts == 4
            assert inc.skew_ms > 40.0
            assert inc.confidence >= 0.75

    def test_ici_link_cause_from_retry_evidence(self):
        streams = synthesize_slice_streams(
            n_hosts=4, straggler_host=1, ici_link=3, link_retries_per_launch=5.0
        )
        joiner = SliceJoiner(expected_hosts=4)
        ingest(joiner, streams)
        incidents = joiner.incidents()
        assert incidents
        for inc in incidents:
            assert inc.cause == CAUSE_ICI_LINK
            assert inc.ici_link == 3
            assert inc.link_retries >= 5.0
            # Link corroboration raises confidence above the compute case.
            assert inc.confidence > 0.85

    def test_healthy_slice_produces_no_incidents(self):
        streams = synthesize_slice_streams(straggler_delay_ms=0.0)
        joiner = SliceJoiner()
        ingest(joiner, streams)
        assert joiner.incidents() == []

    def test_small_absolute_skew_below_floor_ignored(self):
        # 50% relative skew but only 2ms absolute: below the 5ms floor.
        streams = synthesize_slice_streams(
            base_latency_ms=2.0, straggler_delay_ms=2.0
        )
        joiner = SliceJoiner()
        ingest(joiner, streams)
        assert joiner.incidents() == []

    def test_min_hosts_guards_partial_join(self):
        streams = synthesize_slice_streams(n_hosts=4, straggler_delay_ms=50.0)
        joiner = SliceJoiner()
        joiner.add_all(streams[0])  # only one host's stream has arrived
        assert joiner.incidents() == []

    def test_partial_coverage_lowers_confidence(self):
        streams = synthesize_slice_streams(
            n_hosts=4, straggler_host=0, straggler_delay_ms=50.0
        )
        full = SliceJoiner(expected_hosts=4)
        ingest(full, streams)
        partial = SliceJoiner(expected_hosts=4)
        ingest(partial, streams[:2])  # straggler + one punctual host
        f = full.incidents()[0]
        p = partial.incidents()[0]
        assert p.straggler_host == f.straggler_host == 0
        assert p.confidence < f.confidence

    def test_events_without_slice_identity_skipped(self):
        joiner = SliceJoiner()
        assert not joiner.add({"signal": "dns_latency_ms", "value": 5.0})
        assert not joiner.add(
            {"signal": "ici_collective_latency_ms", "value": 5.0, "tpu": {}}
        )
        assert joiner.skipped == 2 and joiner.ingested == 0

    def test_incident_dict_round_trips_json(self):
        streams = synthesize_slice_streams(straggler_delay_ms=50.0, ici_link=1)
        joiner = SliceJoiner(expected_hosts=4)
        ingest(joiner, streams)
        payload = json.loads(json.dumps(joiner.incidents()[0].to_dict()))
        assert payload["cause"] == CAUSE_ICI_LINK
        assert payload["ici_link"] == 1
        assert set(payload["host_latencies_ms"]) == {"0", "1", "2", "3"}

    def test_drain_reports_once_and_bounds_memory(self):
        streams = synthesize_slice_streams(
            n_hosts=4, n_launches=6, straggler_delay_ms=50.0, ici_link=1
        )
        joiner = SliceJoiner(expected_hosts=4)
        ingest(joiner, streams)
        first = joiner.drain()
        assert len(first) == 6
        assert joiner.drain() == []  # evicted: no duplicate reporting
        assert not joiner._groups
        # A fresh launch after the drain is still attributed.
        late = synthesize_slice_streams(
            n_hosts=4, n_launches=1, straggler_delay_ms=50.0,
            start_unix_nano=1_700_000_100_000_000_000,
        )
        ingest(joiner, late)
        assert len(joiner.drain()) == 1

    def test_drain_keeps_groups_awaiting_hosts(self):
        streams = synthesize_slice_streams(n_hosts=4, straggler_delay_ms=50.0)
        joiner = SliceJoiner(expected_hosts=4)
        joiner.add_all(streams[0])
        assert joiner.drain(min_hosts=2) == []
        assert joiner._groups  # kept for the late host streams
        for stream in streams[1:]:
            joiner.add_all(stream)
        assert joiner.drain(min_hosts=2)

    def test_drain_evicts_and_attributes_stale_groups_best_effort(self):
        """A dead host agent must not grow drain() memory without bound:
        groups stuck below expected_hosts age out past the pending
        horizon and are attributed from whoever reported."""
        streams = synthesize_slice_streams(
            n_hosts=4, n_launches=5, straggler_host=1, straggler_delay_ms=50.0
        )
        joiner = SliceJoiner(expected_hosts=4, pending_horizon_ns=10)
        # Host 3's agent "died": its stream never arrives.
        for stream in streams[:3]:
            joiner.add_all(stream)
        drained = joiner.drain()
        # Launches older than the horizon behind the newest observation
        # are evicted + attributed from 3 hosts; the newest launch stays
        # pending (host 3 could still report it).
        assert len(drained) == 4
        assert all(i.straggler_host == 1 and i.n_hosts == 3 for i in drained)
        assert len(joiner._groups) == 1
        full = SliceJoiner(expected_hosts=4)
        for stream in streams:
            full.add_all(stream)
        # Best-effort attribution carries less confidence than complete.
        assert drained[0].confidence < full.incidents()[0].confidence

    def test_drain_learns_slice_membership_when_expected_unset(self):
        """Without expected_hosts, completeness is the widest membership
        the slice has demonstrated — a partial arrival must not be
        evicted as 'complete' at min_hosts."""
        streams = synthesize_slice_streams(
            n_hosts=4, n_launches=2, straggler_host=3, straggler_delay_ms=50.0
        )
        joiner = SliceJoiner()  # expected_hosts unset
        # Launch 0 fully arrives first: membership of 4 is demonstrated.
        for stream in streams:
            joiner.add(stream[0])
        # Launch 1: only punctual hosts 0-1 have reported so far.
        joiner.add(streams[0][1])
        joiner.add(streams[1][1])
        drained = joiner.drain(min_hosts=2)
        assert len(drained) == 1 and drained[0].launch_id == 0
        assert len(joiner._groups) == 1  # launch 1 kept, not judged healthy
        # Stragglers' events land; next drain attributes launch 1 fully.
        joiner.add(streams[2][1])
        joiner.add(streams[3][1])
        second = joiner.drain(min_hosts=2)
        assert len(second) == 1
        assert second[0].launch_id == 1 and second[0].straggler_host == 3

    def test_drain_horizon_is_per_slice(self):
        """A lagging slice must not be force-evicted because another
        slice has newer observations."""
        fresh = synthesize_slice_streams(
            n_hosts=2, n_launches=1, straggler_delay_ms=0.0,
            slice_id="slice-fresh",
            start_unix_nano=2_000_000_000_000_000_000,
        )
        lagging = synthesize_slice_streams(
            n_hosts=4, n_launches=1, straggler_delay_ms=50.0,
            slice_id="slice-lag",
            start_unix_nano=1_000_000_000_000_000_000,
        )
        joiner = SliceJoiner(expected_hosts=4)
        for stream in fresh:
            joiner.add_all(stream)
        for stream in lagging[:3]:  # slice-lag still missing host 3
            joiner.add_all(stream)
        assert joiner.drain() == []  # not stale relative to its own slice
        assert any(
            g.slice_id == "slice-lag" for g in joiner._groups.values()
        )

    @staticmethod
    def _collective(launch, host, ts, value, slice_id="s"):
        return {
            "signal": "ici_collective_latency_ms",
            "node": f"host-{host}",
            "ts_unix_nano": ts,
            "value": value,
            "tpu": {
                "slice_id": slice_id,
                "host_index": host,
                "program_id": "prog",
                "launch_id": launch,
            },
        }

    @staticmethod
    def _retry(host, link, ts, value, slice_id="s"):
        return {
            "signal": "ici_link_retries_total",
            "node": f"host-{host}",
            "ts_unix_nano": ts,
            "value": value,
            "tpu": {
                "slice_id": slice_id,
                "host_index": host,
                "ici_link": link,
            },
        }

    def test_drain_retry_evidence_outlives_pending_groups(self):
        """Link-retry corroboration must survive as long as any group
        that may reference it is still pending: a stale group drained
        several calls after its retries arrived is still attributed to
        the ICI link, not misreported as compute_straggler."""
        joiner = SliceJoiner(
            expected_hosts=4, retry_window_ns=100, pending_horizon_ns=5_000
        )
        # Launch 1: host 1 is the straggler (shortest observed wall
        # time) and shows link retries right at its observation.
        joiner.add(self._collective(1, 0, ts=1_000, value=100.0))
        joiner.add(self._collective(1, 1, ts=1_040, value=10.0))
        joiner.add(self._retry(1, link=2, ts=1_040, value=5.0))
        # A later, unrelated retry advances the newest-retry clock; a
        # prune horizon of 2*retry_window would now drop the launch-1
        # evidence even though launch 1 is still pending.
        joiner.add(self._retry(0, link=0, ts=4_000, value=1.0))
        assert joiner.drain() == []  # launch 1 incomplete, not yet stale
        # Newer slice activity pushes launch 1 past the pending horizon.
        joiner.add(self._collective(2, 0, ts=9_000, value=10.0))
        drained = joiner.drain()
        assert len(drained) == 1
        assert drained[0].cause == CAUSE_ICI_LINK
        assert drained[0].straggler_host == 1
        assert drained[0].ici_link == 2

    def test_drain_counts_unattributable_single_host_groups(self):
        """A stale single-reporter group cannot be attributed (skew is
        relative); it must be evicted *visibly* via the counter."""
        joiner = SliceJoiner(expected_hosts=4, pending_horizon_ns=10)
        joiner.add(self._collective(1, 0, ts=100, value=10.0))
        joiner.add(self._collective(2, 0, ts=10_000, value=10.0))
        drained = joiner.drain()
        assert drained == []
        assert joiner.dropped_unattributable == 1
        assert len(joiner._groups) == 1  # the newest launch stays pending

    def test_incidents_ranked_by_confidence_then_skew(self):
        streams = synthesize_slice_streams(straggler_delay_ms=50.0)
        joiner = SliceJoiner(expected_hosts=4)
        ingest(joiner, streams)
        incidents = joiner.incidents()
        confs = [i.confidence for i in incidents]
        assert confs == sorted(confs, reverse=True)


class TestIngestRobustness:
    def test_incident_stability_under_shuffled_add_order(self):
        # Launch-group joins are exact identity, so the attributed
        # incident set must be invariant to arbitrary interleavings of
        # the per-host streams (the DaemonSet gives no ordering
        # guarantee whatsoever).
        import random

        streams = synthesize_slice_streams(
            n_hosts=4, n_launches=8, straggler_host=1,
            straggler_delay_ms=45.0, ici_link=3,
            link_retries_per_launch=4.0,
        )
        flat = [event for stream in streams for event in stream]
        reference = SliceJoiner(expected_hosts=4)
        reference.add_all(flat)
        expected = [i.to_dict() for i in reference.incidents()]
        assert expected, "scenario must attribute something"

        rng = random.Random(7)
        for _ in range(5):
            shuffled = list(flat)
            rng.shuffle(shuffled)
            joiner = SliceJoiner(expected_hosts=4)
            joiner.add_all(shuffled)
            assert [i.to_dict() for i in joiner.incidents()] == expected

    def test_skips_are_reason_classed(self):
        from tpuslo.correlation.multihost import (
            SKIP_BAD_FIELD_TYPE,
            SKIP_MISSING_LAUNCH_ID,
            SKIP_MISSING_SLICE_IDENTITY,
            SKIP_UNMATCHED_SIGNAL,
        )

        joiner = SliceJoiner()
        assert not joiner.add({"signal": "ici_collective_latency_ms"})
        assert not joiner.add(
            {
                "signal": "ici_collective_latency_ms",
                "tpu": {"slice_id": "s0", "host_index": 0},
            }
        )
        assert not joiner.add(
            {
                "signal": "dns_latency_ms",
                "tpu": {"slice_id": "s0", "host_index": 0},
            }
        )
        assert not joiner.add(
            {
                "signal": "ici_collective_latency_ms",
                "tpu": {"slice_id": "s0", "host_index": "corrupt"},
            }
        )
        assert joiner.skipped == 4
        assert joiner.skipped_by_reason == {
            SKIP_MISSING_SLICE_IDENTITY: 1,
            SKIP_MISSING_LAUNCH_ID: 1,
            SKIP_UNMATCHED_SIGNAL: 1,
            SKIP_BAD_FIELD_TYPE: 1,
        }

    def test_corrupt_value_does_not_abort_stream(self):
        joiner = SliceJoiner()
        bad = {
            "signal": "ici_collective_latency_ms",
            "value": {"nested": "dict"},
            "tpu": {
                "slice_id": "s0", "host_index": 0, "launch_id": 1,
                "program_id": "p",
            },
        }
        assert not joiner.add(bad)
        good = dict(bad, value=5.0)
        assert joiner.add(good)


class TestSliceCorrCLI:
    def test_end_to_end_jsonl(self, tmp_path, capsys):
        from tpuslo.cli.slicecorr import main

        streams = synthesize_slice_streams(
            n_hosts=4, straggler_host=3, straggler_delay_ms=60.0, ici_link=2
        )
        paths = []
        for host, stream in enumerate(streams):
            p = tmp_path / f"host{host}.jsonl"
            p.write_text(
                "".join(json.dumps(e) + "\n" for e in stream), encoding="utf-8"
            )
            paths.append(str(p))
        out = tmp_path / "incidents.jsonl"
        summary = tmp_path / "summary.json"
        rc = main(
            paths
            + [
                "--output",
                str(out),
                "--summary",
                str(summary),
                "--expected-hosts",
                "4",
            ]
        )
        assert rc == 0
        incidents = [
            json.loads(line) for line in out.read_text().splitlines() if line
        ]
        assert incidents and all(i["straggler_host"] == 3 for i in incidents)
        meta = json.loads(summary.read_text())
        assert meta["incidents"] == len(incidents)
        assert meta["by_cause"] == {"ici_link": len(incidents)}

    def test_xprof_dir_mode(self, tmp_path, capsys):
        """slicecorr --xprof-dir runs the whole xprof -> collective
        signals -> straggler pipeline from trace files on disk."""
        import gzip

        from tests.test_xla_spans import trace_doc_with_collectives
        from tpuslo.cli.slicecorr import main

        run = tmp_path / "plugins" / "profile" / "run1"
        run.mkdir(parents=True)
        for host, straggler in (("vm-0", False), ("vm-1", True), ("vm-2", False)):
            with gzip.open(run / f"{host}.trace.json.gz", "wt") as fh:
                json.dump(trace_doc_with_collectives(straggler=straggler), fh)
        out = tmp_path / "inc.jsonl"
        rc = main(
            [
                "--xprof-dir",
                str(tmp_path),
                "--slice-id",
                "s9",
                "--skew-floor-ms",
                "0.1",
                "--output",
                str(out),
            ]
        )
        assert rc == 0
        incidents = [
            json.loads(line) for line in out.read_text().splitlines() if line
        ]
        assert incidents
        # vm-1 sorts to host_index 1 and waited least: the straggler.
        assert all(i["straggler_host"] == 1 for i in incidents)
        assert all(i["slice_id"] == "s9" for i in incidents)

    def test_xprof_dir_without_traces_errors(self, tmp_path, capsys):
        from tpuslo.cli.slicecorr import main

        assert main(["--xprof-dir", str(tmp_path)]) == 2
        assert "no xprof profile runs" in capsys.readouterr().err

    def test_xprof_dir_and_jsonl_inputs_mutually_exclusive(self, tmp_path, capsys):
        from tpuslo.cli.slicecorr import main

        assert main(["some.jsonl", "--xprof-dir", str(tmp_path)]) == 2
        assert "mutually exclusive" in capsys.readouterr().err

    def test_stdin_dash_mixed_with_files(self, tmp_path, monkeypatch, capsys):
        import io

        from tpuslo.cli.slicecorr import main

        streams = synthesize_slice_streams(
            n_hosts=2, n_launches=2, straggler_host=0, straggler_delay_ms=50.0
        )
        p = tmp_path / "host0.jsonl"
        p.write_text(
            "".join(json.dumps(e) + "\n" for e in streams[0]), encoding="utf-8"
        )
        monkeypatch.setattr(
            "sys.stdin",
            io.StringIO("".join(json.dumps(e) + "\n" for e in streams[1])),
        )
        assert main([str(p), "-"]) == 0
        lines = [
            json.loads(l)
            for l in capsys.readouterr().out.splitlines()
            if l.strip()
        ]
        assert len(lines) == 2 and all(i["n_hosts"] == 2 for i in lines)
