"""Telemetry ingest gate: dedup, quarantine, skew correction, watermark.

Deterministic throughout — chaos comes from seeded ChaosStream
scenarios, so every assertion is against reproducible ground truth.
"""

from __future__ import annotations

import json
from datetime import datetime, timezone

from tpuslo.chaos.telemetry import ChaosScenario, ChaosStream
from tpuslo.correlation.matcher import (
    DEFAULT_ENRICHMENT_THRESHOLD,
    SpanRef,
)
from tpuslo.ingest import (
    ADMITTED,
    DUPLICATE,
    LATE,
    LATE_CONFIDENCE_CAP,
    QUARANTINED,
    ClockSkewEstimator,
    GateConfig,
    LateEvent,
    Quarantine,
    TelemetryGate,
    Watermark,
    rematch_late,
)

T0 = 1_700_000_000_000_000_000  # ns


def probe_event(i=0, host=0, signal="dns_latency_ms", ts=None, **over):
    event = dict(
        ts_unix_nano=T0 + i * 1_000_000_000 if ts is None else ts,
        signal=signal,
        node=f"host-{host}",
        namespace="llm",
        pod=f"rag-agent-{host}",
        container="rag",
        pid=1,
        tid=1,
        value=12.0,
        unit="ms",
        status="ok",
    )
    event.update(over)
    return event


def collective_event(launch, host, ts_offset_ns=0):
    return probe_event(
        i=launch,
        host=host,
        signal="ici_collective_latency_ms",
        ts=T0 + launch * 1_000_000_000 + ts_offset_ns,
        value=3.5,
        tpu={
            "slice_id": "slice-0",
            "host_index": host,
            "program_id": "prog",
            "launch_id": launch,
        },
    )


class TestValidationAndQuarantine:
    def test_reason_classes(self, tmp_path):
        gate = TelemetryGate(
            GateConfig(quarantine_dir=str(tmp_path / "q"))
        )
        assert gate.admit("not a dict")[0] == QUARANTINED
        missing = probe_event()
        del missing["status"]
        assert gate.admit(missing)[0] == QUARANTINED
        assert gate.admit(probe_event(value="garbled"))[0] == QUARANTINED
        assert gate.admit(probe_event(ts=-5))[0] == QUARANTINED
        assert gate.quarantined_by_reason == {
            "not_object": 1,
            "missing_field": 1,
            "bad_field_type": 2,
        }
        # Bodies land in the capped JSONL spool, reason attached.
        gate.close()
        records = []
        for seg in sorted((tmp_path / "q").glob("seg-*.jsonl")):
            for line in seg.read_text().splitlines():
                records.append(json.loads(line))
        assert len(records) == 4
        assert {r["reason"] for r in records} == {
            "not_object", "missing_field", "bad_field_type"
        }

    def test_schema_reject_class(self):
        gate = TelemetryGate()
        # Structurally typed but contract-violating: bad conn port.
        event = probe_event(
            conn_tuple={
                "src_ip": "1.2.3.4",
                "dst_ip": "5.6.7.8",
                "src_port": 99999,
                "dst_port": 443,
                "protocol": "tcp",
            }
        )
        assert gate.admit(event)[0] == QUARANTINED
        assert gate.quarantined_by_reason == {"schema_reject": 1}

    def test_valid_events_admitted_uncopied_fields(self):
        gate = TelemetryGate()
        event = probe_event()
        outcome, admitted = gate.admit(event)
        assert outcome == ADMITTED
        assert admitted == event

    def test_quarantine_size_cap_truncates(self, tmp_path):
        quarantine = Quarantine(
            tmp_path / "q", max_bytes=8192, max_age_s=0
        )
        for i in range(2000):
            quarantine.put(probe_event(i=i), "bad_field_type")
        assert quarantine.truncated > 0
        assert quarantine.pending_bytes() <= 8192 + 64 * 1024
        quarantine.close()


class TestDedup:
    def test_exact_duplicates_suppressed(self):
        gate = TelemetryGate()
        event = probe_event()
        assert gate.admit(event)[0] == ADMITTED
        assert gate.admit(dict(event))[0] == DUPLICATE
        assert gate.duplicates == 1

    def test_lru_window_is_bounded(self):
        gate = TelemetryGate(GateConfig(dedup_window=2))
        a, b, c = (probe_event(i=i, pid=i + 1) for i in range(3))
        gate.admit(a)
        gate.admit(b)
        gate.admit(c)  # evicts a's identity
        outcome, _ = gate.admit(dict(a))
        # a re-admitted (not flagged dup: its identity aged out) but it
        # is now behind the watermark -> late, never silently dropped.
        assert outcome in (ADMITTED, LATE)
        assert gate.duplicates == 0

    def test_chaos_duplication_ground_truth(self):
        events = [probe_event(i=i, pid=i + 1) for i in range(200)]
        chaos = ChaosStream(ChaosScenario(seed=11, dup_rate=0.1))
        gate = TelemetryGate()
        gate.admit_all(chaos.stream(events))
        assert chaos.duplicated > 0
        assert gate.duplicates == chaos.duplicated


class TestSkewCorrection:
    def test_recovers_injected_offsets(self):
        events = [
            collective_event(launch, host)
            for launch in range(20)
            for host in range(4)
        ]
        chaos = ChaosStream(ChaosScenario(seed=3, skew_ms=200))
        gate = TelemetryGate()
        batch = gate.admit_all(chaos.stream(events))
        offsets = gate.skew.offsets_ms()
        # Injected: host-1 +200, host-2 -150, host-3 +100 (fractioned).
        assert abs(offsets["host-1"] - 200) < 1
        assert abs(offsets["host-2"] + 150) < 1
        assert abs(offsets["host-3"] - 100) < 1
        # After warm-up every admitted event sits back on the true
        # clock.
        original = {
            (e["tpu"]["launch_id"], e["tpu"]["host_index"]): e[
                "ts_unix_nano"
            ]
            for e in events
        }
        residuals = [
            abs(
                e["ts_unix_nano"]
                - original[
                    (e["tpu"]["launch_id"], e["tpu"]["host_index"])
                ]
            )
            for e in batch.all_events()
            if e["tpu"]["launch_id"] >= 5  # past min_skew_samples
        ]
        assert max(residuals) == 0

    def test_under_evidenced_hosts_uncorrected(self):
        estimator = ClockSkewEstimator(min_samples=3)
        for launch in range(2):  # only two groups: below min_samples
            estimator.observe(collective_event(launch, 0))
            estimator.observe(
                collective_event(launch, 1, ts_offset_ns=50_000_000)
            )
        assert estimator.offset_ns("host-1") == 0

    def test_correction_applies_to_non_collective_events(self):
        gate = TelemetryGate()
        for launch in range(5):
            for host in range(2):
                gate.admit(
                    ChaosStream(
                        ChaosScenario(seed=1, skew_ms=100)
                    ).stream([collective_event(launch, host)]).__next__()
                )
        skewed_dns = probe_event(i=10, host=1)
        skewed_dns["ts_unix_nano"] += 100_000_000  # the host's skew
        outcome, corrected = gate.admit(skewed_dns)
        assert outcome == ADMITTED
        assert corrected["ts_unix_nano"] == probe_event(i=10)[
            "ts_unix_nano"
        ]


class TestWatermark:
    def test_bounded_out_of_order_admitted(self):
        wm = Watermark(lateness_ns=2_000_000_000)
        assert wm.admit(T0)
        assert wm.admit(T0 + 5_000_000_000)
        assert wm.admit(T0 + 4_000_000_000)  # 1s behind head: fine
        assert not wm.admit(T0)  # 5s behind: late
        assert wm.late == 1

    def test_gate_routes_late_with_lag(self):
        gate = TelemetryGate(GateConfig(watermark_lateness_ms=1000))
        gate.admit(probe_event(i=10))
        outcome, event = gate.admit(probe_event(i=0, pid=7))
        assert outcome == LATE
        assert event is not None
        batch = gate.admit_all([probe_event(i=11), probe_event(i=1, pid=9)])
        assert len(batch.admitted) == 1
        assert len(batch.late) == 1
        assert batch.late[0].lag_ns == 10_000_000_000


class TestRematchLate:
    def span(self, **kw):
        kw.setdefault(
            "timestamp",
            datetime.fromtimestamp(T0 / 1e9, tz=timezone.utc),
        )
        return SpanRef(**kw)

    def test_stale_event_capped_below_enrichment(self):
        # Trace ids match -> pairwise would say 1.0, but the event is
        # 30s behind the head: indistinguishable from id reuse.
        late = [
            LateEvent(
                probe_event(i=0, trace_id="t-1"), lag_ns=30_000_000_000
            )
        ]
        results = rematch_late(
            [self.span(trace_id="t-1")], late, window_ms=2000
        )
        assert results[0].decision.matched
        assert results[0].decision.confidence == LATE_CONFIDENCE_CAP
        assert (
            results[0].decision.confidence < DEFAULT_ENRICHMENT_THRESHOLD
        )

    def test_recheck_restores_full_confidence(self):
        # Barely late (lag within one window beyond the lateness
        # bound) and window-verified on the corrected timestamp: the
        # re-check passes.
        late = [
            LateEvent(
                probe_event(i=0, trace_id="t-1"), lag_ns=1_500_000_000
            )
        ]
        results = rematch_late(
            [self.span(trace_id="t-1")], late, window_ms=2000
        )
        assert results[0].decision.confidence == 1.0

    def test_recheck_reachable_at_default_config(self):
        # With ALL defaults (lateness == correlation window == 2 s) a
        # late event lags > 2 s by definition; the re-check bound must
        # sit beyond the lateness or full confidence could never be
        # restored.
        gate = TelemetryGate()
        gate.admit(probe_event(i=3))  # head at t0+3s
        outcome, _ = gate.admit(probe_event(i=0, trace_id="t-1"))
        assert outcome == LATE
        batch = gate.admit_all(
            [probe_event(i=0, pid=5, trace_id="t-1")]
        )
        assert len(batch.late) == 1
        assert batch.late[0].lag_ns == 3_000_000_000
        results = rematch_late([self.span(trace_id="t-1")], batch.late)
        assert results[0].decision.confidence == 1.0

    def test_missing_timestamp_late_event_capped(self):
        event = probe_event(i=0, trace_id="t-1")
        event["ts_unix_nano"] = 0
        late = [LateEvent(event, lag_ns=100)]
        results = rematch_late([self.span(trace_id="t-1")], late)
        assert results[0].decision.matched
        assert (
            results[0].decision.confidence < DEFAULT_ENRICHMENT_THRESHOLD
        )

    def test_never_enriches_without_recheck_under_chaos(self):
        # Property form of the acceptance bar: whatever a seeded chaos
        # stream makes late, nothing matched may reach the enrichment
        # threshold unless its lag passed the re-check bound.
        events = [
            probe_event(i=i, pid=i + 1, trace_id=f"t-{i}")
            for i in range(100)
        ]
        chaos = ChaosStream(
            ChaosScenario(seed=23, reorder_rate=0.3, reorder_depth=40)
        )
        gate = TelemetryGate(GateConfig(watermark_lateness_ms=500))
        batch = gate.admit_all(chaos.stream(events))
        assert batch.late, "scenario must actually produce late events"
        spans = [
            self.span(trace_id=f"t-{i}") for i in range(100)
        ]
        window_ms = 2000
        results = rematch_late(spans, batch.late, window_ms=window_ms)
        for result in results:
            if not result.decision.matched:
                continue
            if result.decision.confidence >= DEFAULT_ENRICHMENT_THRESHOLD:
                lag = batch.late[result.signal_index].lag_ns
                assert lag <= 2 * window_ms * 1_000_000


class TestGateAccounting:
    def test_snapshot_shape(self):
        gate = TelemetryGate()
        gate.admit_all([probe_event(i=i, pid=i + 1) for i in range(5)])
        snap = gate.snapshot()
        assert snap["admitted"] == 5
        for key in (
            "duplicates",
            "quarantined",
            "quarantined_by_reason",
            "late_admitted",
            "skew_corrected",
            "skew_offsets_ms",
            "watermark_ns",
        ):
            assert key in snap

    def test_prometheus_observer_bridge(self):
        from tpuslo.metrics import AgentMetrics

        metrics = AgentMetrics()
        gate = TelemetryGate(observer=metrics.ingest_observer())
        gate.admit(probe_event())
        gate.admit(probe_event())  # duplicate
        gate.admit("junk")

        def value(name, **labels):
            return metrics.registry.get_sample_value(name, labels or None)

        assert value("llm_slo_agent_ingest_admitted_events_total") == 1
        assert value("llm_slo_agent_ingest_duplicate_events_total") == 1
        assert (
            value(
                "llm_slo_agent_ingest_quarantined_events_total",
                reason="not_object",
            )
            == 1
        )

    def test_skew_gauge_updates_on_per_event_path(self):
        # The agent's ring loop calls admit() per event (never
        # admit_all): the clock-skew gauge must still track new
        # launch-group evidence.
        from tpuslo.metrics import AgentMetrics

        metrics = AgentMetrics()
        gate = TelemetryGate(observer=metrics.ingest_observer())
        chaos = ChaosStream(ChaosScenario(seed=1, skew_ms=100))
        events = [
            collective_event(launch, host)
            for launch in range(10)
            for host in range(2)
        ]
        for event in chaos.stream(events):
            gate.admit(event)
        gauge = metrics.registry.get_sample_value(
            "llm_slo_agent_ingest_clock_skew_ms", {"node": "host-1"}
        )
        assert gauge is not None
        assert abs(gauge - 100) < 1
