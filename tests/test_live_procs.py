"""Live deployment-plane chaos: the 2-process smoke lane (ISSUE 17).

One seeded kill -9 / supervised-restart cycle through the real socket
hop — a real ``agent --fleet-upstream tcp://…`` shipping into a real
``fleetagg --listen`` — from the ``tpuslo.chaos.procs`` harness.  The
full matrix (every kill target + the socket partition + the front
door's remediation flip) runs via ``m5gate --live-chaos-sweep`` /
``make live-chaos-sweep``.

The module-level tests are marked ``chaos`` (run via ``make
live-chaos-smoke``, an m5-gate prerequisite next to ``crash-smoke``)
and ``slow`` (real subprocesses, real sockets, wall-clock windows —
never in tier-1).  The helper classes in ``TestLaneHelpers`` need no
subprocess and stay tier-1.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from tpuslo.chaos.procs import (
    BlackholeProxy,
    LiveRunResult,
    LiveSweepReport,
    _frames_rejected,
    _member_keys,
    _parse_cadence,
    run_live_smoke,
)


@pytest.mark.chaos
@pytest.mark.slow
def test_agent_kill_resumes_through_socket(tmp_path):
    result = run_live_smoke(tmp_path / "live", seed=1337)
    assert result.passed, result.failures
    # The supervisor restarted the SIGKILLed agent and the restart
    # left grep-able evidence (a second upstream banner, a journal
    # seq strictly past the pre-kill cursor).
    assert result.restarts >= 1
    assert "agent" in result.restored_evidence
    # Content-based audits: the cluster ledger has incidents, none of
    # them duplicated across the kill, and redelivery never tore a
    # frame.
    assert result.cluster_incidents >= 1
    assert result.duplicate_incident_ids == 0
    assert result.frames_rejected == 0
    # The loop this PR closes: the cluster's acks carried pressure
    # >= 1 and the agent answered by merging shipments.
    assert result.cadence["max_level"] >= 1
    assert result.cadence["flushes"] < result.cadence["cycles"]


class TestLaneHelpers:
    """No-subprocess units of the lane's audit plumbing (tier-1)."""

    def test_parse_cadence_aggregates_incarnations(self):
        # One line per incarnation in the append-mode stderr; the
        # evidence is the sum (and the max level ANY incarnation
        # observed) — a short post-restart window at level 0 must not
        # erase the first window's coarsening.
        text = (
            "agent: fleet cadence: cycles=9 flushes=3 coarsened=6 "
            "max_level=2\n"
            "agent: fleet cadence: cycles=4 flushes=4 coarsened=0 "
            "max_level=0\n"
        )
        assert _parse_cadence(text) == {
            "cycles": 13,
            "flushes": 7,
            "coarsened": 6,
            "max_level": 2,
        }
        assert _parse_cadence("no cadence here") == {}

    def test_frames_rejected_sums_summaries(self):
        text = (
            "fleetagg: live cluster clu-live: 40 frames (2 rejected), "
            "5 incidents written (0 suppressed as dups)\n"
            "fleetagg: live cluster clu-live: 9 frames (1 rejected), "
            "1 incidents written (0 suppressed as dups)\n"
        )
        assert _frames_rejected(text) == 3
        assert _frames_rejected("") == 0

    def test_member_keys_fold_namespace_domain_node_pod(self):
        incidents = [
            {
                "namespace": "tenant-a",
                "domain": "tpu_hbm",
                "members": [
                    {"node": "n0", "pod": "p0"},
                    {"node": "n0", "pod": "p0"},  # dup folds
                    {"node": "n1", "pod": "p1"},
                ],
            },
            {"namespace": "tenant-b", "domain": "dns", "members": []},
        ]
        assert _member_keys(incidents) == {
            ("tenant-a", "tpu_hbm", "n0", "p0"),
            ("tenant-a", "tpu_hbm", "n1", "p1"),
        }

    def test_sweep_report_verdict(self):
        ok = LiveRunResult(target="agent", seed=1)
        bad = LiveRunResult(
            target="region", seed=2, failures=["lost 3 members"]
        )
        report = LiveSweepReport(runs=[ok, bad])
        assert not report.passed
        assert report.failures == ["region (seed 2): lost 3 members"]
        assert LiveSweepReport(runs=[ok]).passed
        # An empty sweep never passes: silence is not evidence.
        assert not LiveSweepReport().passed


class TestBlackholeProxy:
    def _echo_server(self):
        server = socket.socket()
        server.bind(("127.0.0.1", 0))
        server.listen(4)

        def serve():
            while True:
                try:
                    conn, _ = server.accept()
                except OSError:
                    return
                try:
                    while True:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        conn.sendall(chunk)
                except OSError:
                    pass
                finally:
                    conn.close()

        threading.Thread(target=serve, daemon=True).start()
        return server, server.getsockname()

    def test_forwards_both_ways_when_healthy(self):
        server, addr = self._echo_server()
        proxy = BlackholeProxy(addr)
        try:
            client = socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            )
            client.sendall(b"ping")
            assert client.recv(65536) == b"ping"
            client.close()
            # The echo proves both directions delivered, but each
            # pump thread counts AFTER its sendall — on one CPU the
            # main thread's recv can wake before the back pump gets
            # the GIL again, so poll instead of asserting instantly.
            deadline = time.monotonic() + 5.0
            while (
                proxy.forwarded_bytes < 8
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert proxy.forwarded_bytes >= 8  # 4 up + 4 back
            assert proxy.dropped_bytes == 0
        finally:
            proxy.close()
            server.close()

    def test_partition_tears_live_conns_and_drops_new_bytes(self):
        server, addr = self._echo_server()
        proxy = BlackholeProxy(addr)
        try:
            live = socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            )
            live.sendall(b"pre")
            assert live.recv(65536) == b"pre"
            proxy.partition()
            # The in-flight connection is torn down (a real partition
            # kills established TCP) …
            live.settimeout(5.0)
            assert live.recv(65536) == b""
            live.close()
            # … and a new connection is accepted but black-holed:
            # bytes are read and dropped, never forwarded, never
            # answered.
            holed = socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            )
            holed.sendall(b"into the void")
            holed.settimeout(1.0)
            got_reply = True
            try:
                got_reply = holed.recv(65536) != b""
            except socket.timeout:
                got_reply = False
            assert not got_reply
            holed.close()
            deadline_bytes = len(b"into the void")
            assert proxy.dropped_bytes >= deadline_bytes
        finally:
            proxy.close()
            server.close()

    def test_heal_restores_forwarding_for_new_conns(self):
        server, addr = self._echo_server()
        proxy = BlackholeProxy(addr)
        try:
            proxy.partition()
            proxy.heal()
            client = socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            )
            client.sendall(b"back")
            assert client.recv(65536) == b"back"
            client.close()
        finally:
            proxy.close()
            server.close()
