"""Attribution-engine tests.

Reference model: pkg/attribution/*_test.go, including the golden
multi-fault dataset gate (TestMultiFault / TestPartialAccuracy /
TestCoverageAccuracy in reference CI).
"""

from datetime import datetime, timezone
from pathlib import Path

import pytest

from tpuslo import attribution, faultreplay, schema
from tpuslo.signals.generator import profile_for_fault

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)
GOLDEN = Path(__file__).parent.parent / "tpuslo/attribution/testdata/multi_fault_samples.jsonl"

SINGLE_FAULTS = [
    "dns_latency",
    "cpu_throttle",
    "memory_pressure",
    "provider_throttle",
    "network_partition",
    "ici_drop",
    "hbm_pressure",
    "xla_recompile_storm",
    "host_offload_stall",
]


def make_sample(label, signals=None, **overrides):
    s = attribution.FaultSample(
        incident_id="inc-1",
        timestamp=TS,
        cluster="tpu-cluster",
        namespace="llm",
        service="rag-service",
        fault_label=label,
        confidence=0.9,
        burn_rate=2.0,
        window_minutes=5,
        request_id="req-1",
        trace_id="trace-1",
        signals=signals if signals is not None else profile_for_fault(label),
    )
    for k, v in overrides.items():
        setattr(s, k, v)
    return s


class TestMapper:
    @pytest.mark.parametrize(
        "label,domain",
        [
            ("dns_latency", "network_dns"),
            ("network_partition", "network_egress"),
            ("ici_drop", "tpu_ici"),
            ("hbm_pressure", "tpu_hbm"),
            ("xla_recompile_storm", "xla_compile"),
            ("host_offload_stall", "host_offload"),
            ("something_else", "unknown"),
        ],
    )
    def test_map_fault_label(self, label, domain):
        assert attribution.map_fault_label(label) == domain

    def test_rule_envelope_validates(self):
        att = attribution.build_attribution(make_sample("ici_drop", signals={}))
        schema.validate(att.to_dict(), schema.SCHEMA_INCIDENT_ATTRIBUTION)
        assert att.predicted_fault_domain == "tpu_ici"
        sources = {e.source for e in att.evidence}
        assert "accel_driver" in sources


class TestBayesian:
    @pytest.mark.parametrize("label", SINGLE_FAULTS)
    def test_single_fault_top1(self, label):
        att = attribution.BayesianAttributor()
        posteriors = att.attribute(profile_for_fault(label))
        assert posteriors[0].domain == attribution.map_fault_label(label)
        assert posteriors[0].posterior > 0.5

    def test_posteriors_normalized(self):
        att = attribution.BayesianAttributor()
        posteriors = att.attribute(profile_for_fault("hbm_pressure"))
        assert sum(p.posterior for p in posteriors) == pytest.approx(1.0)

    def test_no_elevated_signals_prefers_nothing_strongly(self):
        att = attribution.BayesianAttributor()
        posteriors = att.attribute(profile_for_fault("baseline"))
        # Healthy profile: no domain should claim high confidence except
        # via absence-likelihoods; unknown/clean domains float to top.
        assert posteriors[0].posterior < 0.9

    def test_evidence_lists_only_elevated_supporting_signals(self):
        att = attribution.BayesianAttributor()
        top = att.attribute(profile_for_fault("ici_drop"))[0]
        assert top.evidence == [
            "ici_collective_latency_ms",
            "ici_link_retries_total",
        ]

    def test_attribute_sample_without_signals_falls_back_to_rule(self):
        att = attribution.BayesianAttributor()
        out = att.attribute_sample(make_sample("dns_latency", signals={}))
        assert out.predicted_fault_domain == "network_dns"
        assert out.fault_hypotheses == []

    def test_attribute_sample_envelope_validates(self):
        att = attribution.BayesianAttributor()
        out = att.attribute_sample(make_sample("xla_recompile_storm"))
        schema.validate(out.to_dict(), schema.SCHEMA_INCIDENT_ATTRIBUTION)
        assert out.predicted_fault_domain == "xla_compile"

    def test_explaining_away_surfaces_secondary_fault(self):
        att = attribution.BayesianAttributor()
        merged = {}
        for label in ("hbm_pressure", "host_offload_stall"):
            for k, v in profile_for_fault(label).items():
                merged[k] = max(merged.get(k, 0.0), v)
        out = att.attribute_sample(make_sample("hbm_pressure", signals=merged))
        domains = {h.domain: h.posterior for h in out.fault_hypotheses}
        assert "tpu_hbm" in domains and "host_offload" in domains
        assert domains["tpu_hbm"] >= 0.05 and domains["host_offload"] >= 0.05

    def test_degraded_mode_dns_only_signals(self):
        full = profile_for_fault("dns_latency")
        subset = {
            k: full[k] for k in ("dns_latency_ms", "tcp_retransmits_total")
        }
        att = attribution.BayesianAttributor()
        posteriors = att.attribute(subset)
        assert posteriors[0].domain == "network_dns"

    def test_likelihood_table_covers_all_domains_and_signals(self):
        table = attribution.default_likelihoods()
        assert len(table) == 22
        for row in table.values():
            assert set(row) == set(attribution.ALL_DOMAINS)
            for p in row.values():
                assert 0.0 < p < 1.0


class TestBatchParity:
    """attribute_batch must be semantically identical to the scalar
    attribute_sample path it replaces in build_attributions."""

    def all_samples(self):
        samples = attribution.load_samples_jsonl(GOLDEN)
        for label in SINGLE_FAULTS:
            samples.append(make_sample(label))
        # Degenerate vectors: empty (rule fallback), unknown signal
        # names, all-healthy values, single-signal.
        samples.append(make_sample("dns_latency", signals={}))
        samples.append(make_sample("dns_latency", signals={"nope_ms": 9.9}))
        samples.append(
            make_sample("cpu_throttle", signals={"dns_latency_ms": 1.0})
        )
        samples.append(
            make_sample("hbm_pressure", signals={"hbm_alloc_stall_ms": 50.0})
        )
        return samples

    def test_batch_matches_scalar_exactly(self):
        attributor = attribution.BayesianAttributor()
        samples = self.all_samples()
        batch = attributor.attribute_batch(samples)
        scalar = [attributor.attribute_sample(s) for s in samples]
        assert len(batch) == len(scalar)
        for b, s in zip(batch, scalar):
            assert b.predicted_fault_domain == s.predicted_fault_domain
            assert b.confidence == pytest.approx(s.confidence, abs=1e-12)
            assert [h.domain for h in b.fault_hypotheses] == [
                h.domain for h in s.fault_hypotheses
            ]
            for hb, hs in zip(b.fault_hypotheses, s.fault_hypotheses):
                assert hb.posterior == pytest.approx(hs.posterior, abs=1e-12)
                assert hb.evidence == hs.evidence

    def test_batch_preserves_input_order(self):
        attributor = attribution.BayesianAttributor()
        samples = [
            make_sample("dns_latency", signals={}),  # rule fallback
            make_sample("ici_drop"),
            make_sample("cpu_throttle", signals={}),
            make_sample("hbm_pressure"),
        ]
        preds = attributor.attribute_batch(samples)
        assert len(preds) == 4
        assert preds[1].predicted_fault_domain == "tpu_ici"
        assert preds[3].predicted_fault_domain == "tpu_hbm"

    def test_batch_matches_scalar_with_incomplete_custom_table(self):
        """Missing domains in a custom likelihood row default to 0.5 as
        a likelihood factor but 0.0 for evidence/residual membership —
        the batch path must honor both defaults."""
        table = attribution.default_likelihoods()
        table["dns_latency_ms"] = {
            d: p
            for d, p in table["dns_latency_ms"].items()
            if d != "network_dns"
        }
        attributor = attribution.BayesianAttributor(likelihoods=table)
        samples = [
            make_sample("dns_latency"),
            make_sample("network_partition"),
        ]
        batch = attributor.attribute_batch(samples)
        scalar = [attributor.attribute_sample(s) for s in samples]
        for b, s in zip(batch, scalar):
            assert b.predicted_fault_domain == s.predicted_fault_domain
            assert [(h.domain, h.evidence) for h in b.fault_hypotheses] == [
                (h.domain, h.evidence) for h in s.fault_hypotheses
            ]

    def test_batch_parity_with_table_missing_a_thresholded_signal(self):
        """An elevated signal with no likelihood row contributes no
        factors but must still trigger the residual pass (scalar
        counts it as unexplained by every domain)."""
        table = attribution.default_likelihoods()
        del table["syscall_latency_ms"]
        attributor = attribution.BayesianAttributor(likelihoods=table)
        sample = make_sample(
            "network_partition",
            signals={
                "dns_latency_ms": 100.0,
                "tcp_retransmits_total": 6.0,
                "connect_latency_ms": 200.0,
                "syscall_latency_ms": 120.0,  # elevated, tableless
            },
        )
        b = attributor.attribute_batch([sample])[0]
        s = attributor.attribute_sample(sample)
        assert b.predicted_fault_domain == s.predicted_fault_domain
        assert [(h.domain, h.evidence) for h in b.fault_hypotheses] == [
            (h.domain, h.evidence) for h in s.fault_hypotheses
        ]
        for hb, hs in zip(b.fault_hypotheses, s.fault_hypotheses):
            assert hb.posterior == pytest.approx(hs.posterior, abs=1e-12)

    def test_batch_tracks_live_table_mutation(self):
        """The scalar path reads priors/likelihoods live; the batch
        path must not serve stale cached matrices."""
        attributor = attribution.BayesianAttributor()
        sample = make_sample("dns_latency")
        before = attributor.attribute_batch([sample])[0]
        attributor.likelihoods["dns_latency_ms"] = {
            d: 0.01 for d in attribution.ALL_DOMAINS
        }
        after = attributor.attribute_batch([sample])[0]
        scalar_after = attributor.attribute_sample(sample)
        assert after.confidence == pytest.approx(
            scalar_after.confidence, abs=1e-12
        )
        assert after.confidence != pytest.approx(before.confidence, abs=1e-6)

    def test_batch_empty(self):
        assert attribution.BayesianAttributor().attribute_batch([]) == []


class TestPipeline:
    def test_mode_dispatch(self):
        assert attribution.normalize_mode("RULE ") == "rule"
        assert attribution.normalize_mode("bayes") == "bayes"
        assert attribution.normalize_mode("whatever") == "bayes"

    def test_confusion_matrix_counts(self):
        samples = [make_sample("dns_latency"), make_sample("ici_drop")]
        preds = attribution.build_attributions(samples)
        matrix = attribution.build_confusion_matrix(samples, preds)
        assert matrix[("network_dns", "network_dns")] == 1
        assert matrix[("tpu_ici", "tpu_ici")] == 1

    def test_rule_mode(self):
        samples = [make_sample("dns_latency")]
        preds = attribution.build_attributions(samples, mode="rule")
        assert preds[0].fault_hypotheses == []
        assert attribution.accuracy(samples, preds) == 1.0


class TestGoldenDataset:
    @pytest.fixture(scope="class")
    def golden(self):
        samples = attribution.load_samples_jsonl(GOLDEN)
        preds = attribution.build_attributions(samples, mode="bayes")
        return samples, preds

    def test_dataset_size(self, golden):
        samples, _ = golden
        assert len(samples) >= 55

    def test_all_predictions_validate(self, golden):
        _, preds = golden
        for p in preds:
            schema.validate(p.to_dict(), schema.SCHEMA_INCIDENT_ATTRIBUTION)

    def test_single_fault_accuracy_gate(self, golden):
        samples, preds = golden
        singles = [
            (s, p)
            for s, p in zip(samples, preds)
            if not s.expected_domains
        ]
        acc = attribution.accuracy(*map(list, zip(*singles)))
        assert acc == 1.0

    def test_partial_accuracy_gate(self, golden):
        samples, preds = golden
        assert attribution.partial_accuracy(samples, preds) == 1.0

    def test_coverage_accuracy_gate(self, golden):
        samples, preds = golden
        assert attribution.coverage_accuracy(samples, preds) >= 0.85

    def test_macro_f1_beats_rebuild_target(self, golden):
        samples, preds = golden
        report = attribution.macro_f1(samples, preds)
        assert report.macro_f1 >= 0.85  # methodology target; rebuild gate is 0.70
        assert report.micro_accuracy >= 0.95

    def test_tpu_fault_f1(self, golden):
        samples, preds = golden
        pairs = [
            (s, p)
            for s, p in zip(samples, preds)
            if set(attribution.expected_domains_for(s))
            & set(attribution.TPU_DOMAINS)
        ]
        report = attribution.macro_f1(*map(list, zip(*pairs)))
        assert report.macro_f1 >= 0.70  # BASELINE.md rebuild target


class TestFaultReplay:
    def test_supported_scenarios(self):
        scen = faultreplay.supported_scenarios()
        for s in ("mixed", "mixed_multi", "tpu_mixed", "tpu_mixed_multi"):
            assert s in scen

    def test_deterministic(self):
        a = faultreplay.generate_fault_samples("tpu_mixed", 6, TS)
        b = faultreplay.generate_fault_samples("tpu_mixed", 6, TS)
        assert [s.to_dict() for s in a] == [s.to_dict() for s in b]

    def test_samples_carry_signal_vectors(self):
        samples = faultreplay.generate_fault_samples("hbm_pressure", 2, TS)
        assert samples[0].signals["hbm_alloc_stall_ms"] == 60

    def test_multi_fault_expected_domains(self):
        samples = faultreplay.generate_fault_samples("tpu_mixed_multi", 4, TS)
        assert samples[0].expected_domains == ["tpu_hbm", "host_offload"]
        assert samples[1].expected_domains == ["tpu_ici", "network_egress"]

    def test_unknown_scenario_raises(self):
        with pytest.raises(ValueError):
            faultreplay.generate_fault_samples("plasma_leak", 1, TS)

    def test_count_validation(self):
        with pytest.raises(ValueError):
            faultreplay.generate_fault_samples("mixed", 0, TS)


class TestIO:
    def test_round_trip(self, tmp_path):
        samples = faultreplay.generate_fault_samples("tpu_mixed_multi", 4, TS)
        path = tmp_path / "samples.jsonl"
        with open(path, "w") as f:
            attribution.dump_samples_jsonl(samples, f)
        loaded = attribution.load_samples_jsonl(path)
        assert [s.to_dict() for s in loaded] == [s.to_dict() for s in samples]

    def test_empty_file_raises(self, tmp_path):
        path = tmp_path / "empty.jsonl"
        path.write_text("\n\n")
        with pytest.raises(ValueError):
            attribution.load_samples_jsonl(path)

    def test_bad_line_raises_with_location(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        path.write_text('{"incident_id": "x"\n')
        with pytest.raises(ValueError, match="bad.jsonl:1"):
            attribution.load_samples_jsonl(path)


class TestDCNDomain:
    """Round-4 multi-slice fault domain: cross-slice DCN degradation
    must attribute to tpu_dcn and stay separable from its two nearest
    neighbours (ici_drop shares the collective symptom, network
    partition shares the retransmit symptom)."""

    def test_dcn_scenario_attributes_to_tpu_dcn(self):
        from datetime import datetime, timezone

        from tpuslo import attribution
        from tpuslo.faultreplay import generate_fault_samples

        start = datetime(2026, 1, 1, tzinfo=timezone.utc)
        for scenario, expect in (
            ("dcn_degradation", "tpu_dcn"),
            ("ici_drop", "tpu_ici"),
            ("network_partition", "network_egress"),
        ):
            samples = generate_fault_samples(scenario, 10, start)
            preds = attribution.build_attributions(samples, mode="bayes")
            domains = {p.predicted_fault_domain for p in preds}
            assert domains == {expect}, (scenario, domains)

    def test_dcn_evidence_names_the_transfer_signal(self):
        from datetime import datetime, timezone

        from tpuslo import attribution
        from tpuslo.faultreplay import generate_fault_samples

        start = datetime(2026, 1, 1, tzinfo=timezone.utc)
        samples = generate_fault_samples("dcn_degradation", 3, start)
        preds = attribution.build_attributions(samples, mode="bayes")
        for p in preds:
            assert any(
                e.signal == "dcn_transfer_latency_ms"
                and e.source == "megascale"
                for e in p.evidence
            ), p.evidence
