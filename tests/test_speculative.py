"""Speculative decoding: exactness vs plain greedy, acceptance stats."""

import jax
import jax.numpy as jnp
import pytest

from tpuslo.models.llama import (
    init_kv_cache,
    init_params,
    llama_tiny,
    prefill,
    decode_step,
    verify_chunk,
)
from tpuslo.models.serve import ServeEngine
from tpuslo.models.speculative import SpeculativeEngine


def _cfg():
    return llama_tiny(max_seq_len=256)


def _plain_greedy(engine: ServeEngine, prompt: str, n: int) -> list[int]:
    return [
        e.token_id
        for e in engine.generate(prompt, max_new_tokens=n, stop_at_eos=False)
    ]


def test_verify_chunk_matches_stepwise_decode():
    """Scoring K tokens in one pass == K sequential decode steps."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)

    logits0, cache_a = prefill(params, prompt, init_kv_cache(cfg, 1), cfg)
    _, cache_b = prefill(params, prompt, init_kv_cache(cfg, 1), cfg)
    chunk = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, cfg.vocab_size)

    # Reference: sequential decode steps.
    step_logits = []
    for i in range(4):
        logits, cache_a = decode_step(params, chunk[:, i], cache_a, cfg)
        step_logits.append(logits)
    ref = jnp.stack(step_logits, axis=1)  # (1, 4, V)

    got, cache_b = verify_chunk(params, chunk, cache_b, cfg)
    err = float(jnp.max(jnp.abs(ref - got)))
    assert err < 5e-2, f"verify_chunk diverges from stepwise decode: {err}"
    assert int(cache_b["length"]) == 8  # caller advances length


@pytest.mark.parametrize("k", [1, 3, 4])
def test_speculative_equals_plain_greedy_self_draft(k):
    """Draft == target: every proposal accepted, output identical."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    target = ServeEngine(cfg=cfg, params=params)
    draft = ServeEngine(cfg=cfg, params=params)
    spec = SpeculativeEngine(target, draft, k=k)

    want = _plain_greedy(ServeEngine(cfg=cfg, params=params), "speculate!", 24)
    got = spec.generate("speculate!", max_new_tokens=24, stop_at_eos=False)
    assert got == want
    assert spec.acceptance_rate > 0.9  # self-draft: near-total acceptance


def test_speculative_equals_plain_greedy_different_draft():
    """Weak draft (different seed): rejections happen, output STILL
    identical to the target-only stream — the exactness guarantee."""
    cfg = _cfg()
    t_params = init_params(jax.random.PRNGKey(0), cfg)
    d_params = init_params(jax.random.PRNGKey(99), cfg)
    target = ServeEngine(cfg=cfg, params=t_params)
    draft = ServeEngine(cfg=cfg, params=d_params)
    spec = SpeculativeEngine(target, draft, k=4)

    want = _plain_greedy(ServeEngine(cfg=cfg, params=t_params), "exactness", 24)
    got = spec.generate("exactness", max_new_tokens=24, stop_at_eos=False)
    assert got == want
    # An unrelated draft should see some rejections.
    assert spec.acceptance_rate < 1.0
    assert spec.rounds > 0


def test_speculative_respects_max_tokens():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    spec = SpeculativeEngine(
        ServeEngine(cfg=cfg, params=params),
        ServeEngine(cfg=cfg, params=params),
        k=4,
    )
    out = spec.generate("bounded", max_new_tokens=7, stop_at_eos=False)
    assert len(out) == 7


def test_bad_k_rejected():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(cfg=cfg, params=params)
    with pytest.raises(ValueError, match="k must be"):
        SpeculativeEngine(engine, engine, k=0)


def test_speculative_tail_matches_stepwise_near_capacity():
    """With fewer than k+1 free KV slots, the plain-decode tail keeps
    the output identical to the target-only greedy stream, including
    its chunk-rounded token budget."""
    cfg = llama_tiny(max_seq_len=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = "y" * 56  # 57 ids after BOS: 6-token budget, k+1 = 5

    ref_engine = ServeEngine(cfg=cfg, params=params)
    ref = _plain_greedy(ref_engine, prompt, 32)  # budget-clamped
    assert len(ref) == ref_engine.decode_cap_tokens(57)

    spec = SpeculativeEngine(
        ServeEngine(cfg=cfg, params=params),
        ServeEngine(cfg=cfg, params=params),
        k=4,
    )
    got = spec.generate(prompt, max_new_tokens=32, stop_at_eos=False)
    assert got == ref


def test_speculative_long_prompt_chunked_ingestion():
    """Prompts past the largest prefill bucket ride chunked ingestion
    in BOTH engines; exactness vs target-only greedy still holds."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft_params = init_params(jax.random.PRNGKey(7), cfg)
    target = ServeEngine(cfg=cfg, params=params, prefill_buckets=(32, 64))
    draft = ServeEngine(cfg=cfg, params=draft_params, prefill_buckets=(32, 64))
    spec = SpeculativeEngine(target, draft, k=3)

    prompt = "z" * 150  # 151 ids > largest bucket (64)
    plain = ServeEngine(cfg=cfg, params=params, prefill_buckets=(32, 64))
    want = _plain_greedy(plain, prompt, 16)
    got = spec.generate(prompt, max_new_tokens=16, stop_at_eos=False)
    assert got == want


def test_speculative_near_capacity_exact():
    """Reviewer repro: 61-id prompt in a 64-slot cache with k=4 must
    ingest fully (no k-dependent truncation) and match target-only
    greedy via the single-step tail."""
    cfg = llama_tiny(max_seq_len=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    target = ServeEngine(cfg=cfg, params=params)
    draft = ServeEngine(cfg=cfg, params=init_params(jax.random.PRNGKey(7), cfg))
    spec = SpeculativeEngine(target, draft, k=4)
    prompt = "y" * 60
    want = _plain_greedy(ServeEngine(cfg=cfg, params=params), prompt, 8)
    got = spec.generate(prompt, max_new_tokens=8, stop_at_eos=False)
    assert got == want

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


def test_speculative_with_tensor_parallel_target():
    """Speculative decoding composes with a tensor-parallel int8-KV
    target: the contract (output == the TARGET engine's own greedy
    stream) holds exactly, because both paths run the same sharded
    program."""
    import numpy as np
    from jax.sharding import Mesh

    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    target = ServeEngine(
        cfg=cfg, params=params, mesh=mesh, kv_dtype="int8"
    )
    draft = ServeEngine(cfg=cfg, params=params)  # same cfg: any pair is correct
    spec = SpeculativeEngine(target, draft, k=3)
    prompt = "speculative over tp"
    expect = _plain_greedy(target, prompt, 12)
    got = spec.generate(prompt, max_new_tokens=12, stop_at_eos=False)
    assert got == expect


class TestBatchedSpeculative:
    """generate_batch: per-row streams identical to target-only greedy,
    with per-row acceptance divergence riding vector-length verify."""

    def _engines(self, draft_seed=0):
        cfg = llama_tiny(max_seq_len=128)
        params = init_params(jax.random.PRNGKey(0), cfg)
        target = ServeEngine(cfg=cfg, params=params,
                             prefill_buckets=(32, 64))
        draft = ServeEngine(
            cfg=cfg, params=init_params(jax.random.PRNGKey(draft_seed), cfg),
            prefill_buckets=(32, 64),
        )
        return target, draft

    def test_batch_matches_target_only_greedy_per_row(self):
        target, draft = self._engines(draft_seed=7)  # weak draft: rejections
        spec = SpeculativeEngine(target, draft, k=3)
        prompts = ["first spec row", "a different second row",
                   "and a third one"]
        batch = spec.generate_batch(prompts, max_new_tokens=10,
                                    stop_at_eos=False)
        assert spec.acceptance_rate < 1.0  # unrelated draft: rejections
        for prompt, row in zip(prompts, batch):
            expect = [
                e.token_id
                for e in target.generate(prompt, max_new_tokens=10,
                                         stop_at_eos=False)
            ]
            assert row == expect, prompt

    def test_batch_matches_single_row_speculative(self):
        target, draft = self._engines(draft_seed=7)
        spec = SpeculativeEngine(target, draft, k=3)
        batch = spec.generate_batch(
            ["row with its own pace", "short"], max_new_tokens=8,
            stop_at_eos=False,
        )
        single = SpeculativeEngine(target, draft, k=3)
        for prompt, row in zip(["row with its own pace", "short"], batch):
            assert row == single.generate(prompt, max_new_tokens=8,
                                          stop_at_eos=False)

    def test_self_draft_batch_accepts_nearly_everything(self):
        target, _ = self._engines()
        draft = ServeEngine(cfg=target.cfg, params=target.params,
                            prefill_buckets=(32, 64))
        spec = SpeculativeEngine(target, draft, k=4)
        batch = spec.generate_batch(["same model drafts", "twice"],
                                    max_new_tokens=12, stop_at_eos=False)
        assert all(len(r) == 12 for r in batch)
        assert spec.acceptance_rate > 0.9

    def test_heterogeneous_lengths_near_capacity_no_truncation(self):
        """A long row hitting the speculative window limit must not
        truncate a short row's stream: guards range over ACTIVE rows
        and finished rows' frontiers freeze.  (Regression: start.max()
        over all rows ended the loops when the fastest/longest row ran
        out of window, returning a truncated prefix for slow rows.)"""
        target, _ = self._engines()
        draft = ServeEngine(cfg=target.cfg, params=target.params,
                            prefill_buckets=(32, 64))
        spec = SpeculativeEngine(target, draft, k=4)  # full accepts
        long_prompt = "x" * 99
        short_prompt = "short row"
        batch = spec.generate_batch(
            [long_prompt, short_prompt], max_new_tokens=24,
            stop_at_eos=False,
        )
        for prompt, row in zip([long_prompt, short_prompt], batch):
            expect = [
                e.token_id
                for e in target.generate(prompt, max_new_tokens=24,
                                         stop_at_eos=False)
            ]
            assert row == expect, (prompt[:12], len(row), len(expect))

    def test_batch_pads_to_buckets_and_returns_real_rows(self):
        """3 prompts pad to the 4-bucket (each shape compiles once);
        only the real rows come back, streams unaffected."""
        target, draft = self._engines(draft_seed=7)
        spec = SpeculativeEngine(target, draft, k=2)
        prompts = ["one", "two", "three"]
        batch = spec.generate_batch(prompts, max_new_tokens=5,
                                    stop_at_eos=False)
        assert len(batch) == 3
        for prompt, row in zip(prompts, batch):
            expect = [
                e.token_id
                for e in target.generate(prompt, max_new_tokens=5,
                                         stop_at_eos=False)
            ]
            assert row == expect


def test_stream_yields_first_token_before_full_generation():
    """stream() is a real generator: the first token arrives without
    decoding the rest (the demo backend's TTFT depends on it)."""
    cfg = llama_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    target = ServeEngine(cfg=cfg, params=params, prefill_buckets=(32,))
    draft = ServeEngine(cfg=cfg, params=params, prefill_buckets=(32,))
    spec = SpeculativeEngine(target, draft, k=3)
    gen = spec.stream("stream me", max_new_tokens=64, stop_at_eos=False)
    first = next(gen)
    assert isinstance(first, int)
    assert spec.emitted_tokens == 1  # nothing decoded past the prefill
    rest = list(gen)
    expect = [
        e.token_id
        for e in target.generate("stream me", max_new_tokens=64,
                                 stop_at_eos=False)
    ]
    assert [first] + rest == expect  # capacity-capped, same budget rule


def test_stream_with_prefix_matches_target_prefix_stream():
    """prefix= mirrors ServeEngine.generate(prefix=...)'s id-level
    truncation rules: identical stream, rejections and all."""
    cfg = llama_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    target = ServeEngine(cfg=cfg, params=params, prefill_buckets=(32, 64))
    draft = ServeEngine(
        cfg=cfg, params=init_params(jax.random.PRNGKey(7), cfg),
        prefill_buckets=(32, 64),
    )
    spec = SpeculativeEngine(target, draft, k=3)
    prefix = "shared system preamble for speculation"
    expect = [
        e.token_id
        for e in target.generate("user ask", max_new_tokens=10,
                                 stop_at_eos=False, prefix=prefix)
    ]
    got = spec.generate("user ask", max_new_tokens=10,
                        stop_at_eos=False, prefix=prefix)
    assert got == expect


def test_generate_batch_with_prefix_matches_target_prefix_streams():
    """Batched speculation under a shared system prompt: every row
    equals the target-only prefix stream (same shared truncation
    helper as the single-row path)."""
    cfg = llama_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    target = ServeEngine(cfg=cfg, params=params, prefill_buckets=(32, 64))
    draft = ServeEngine(
        cfg=cfg, params=init_params(jax.random.PRNGKey(7), cfg),
        prefill_buckets=(32, 64),
    )
    spec = SpeculativeEngine(target, draft, k=3)
    prefix = "shared batched preamble"
    prompts = ["first ask", "second ask"]
    batch = spec.generate_batch(prompts, max_new_tokens=8,
                                stop_at_eos=False, prefix=prefix)
    for prompt, row in zip(prompts, batch):
        expect = [
            e.token_id
            for e in target.generate(prompt, max_new_tokens=8,
                                     stop_at_eos=False, prefix=prefix)
        ]
        assert row == expect, prompt
