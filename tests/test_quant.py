"""int8 weight-only quantization: numerics, memory layout, serving."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from tpuslo.models.llama import (
    forward,
    init_params,
    init_params_quantized,
    llama_tiny,
    prefill,
    decode_step,
    init_kv_cache,
    quantize_params,
    quantized_bytes,
    param_count,
)
from tpuslo.models.serve import ServeEngine


def _cfg():
    return llama_tiny(max_seq_len=64)


def test_quantized_init_matches_two_step_path():
    cfg = _cfg()
    rng = jax.random.PRNGKey(3)
    two_step = quantize_params(init_params(rng, cfg))
    leafwise = init_params_quantized(rng, cfg)
    flat_a = jax.tree.leaves(two_step)
    flat_b = jax.tree.leaves(leafwise)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        assert a.shape == b.shape and a.dtype == b.dtype
        if a.dtype == jnp.int8:
            # Agreement to one quantization step: XLA may round
            # exact-.5 boundaries differently across fusion contexts.
            diff = np.abs(
                np.asarray(a).astype(np.int32) - np.asarray(b).astype(np.int32)
            )
            assert diff.max() <= 1
            assert (diff != 0).mean() < 1e-3
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quantized_forward_close_to_dense():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)

    dense = forward(params, tokens, cfg, remat=False)
    quant = forward(qparams, tokens, cfg, remat=False)

    rel = float(
        jnp.linalg.norm(dense - quant) / jnp.maximum(jnp.linalg.norm(dense), 1e-9)
    )
    assert rel < 0.05, f"relative logits error {rel}"


def test_quantized_prefill_decode_consistent_with_dense():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)

    logits_d, cache_d = prefill(params, tokens, init_kv_cache(cfg, 1), cfg)
    logits_q, cache_q = prefill(qparams, tokens, init_kv_cache(cfg, 1), cfg)
    tok_d = jnp.argmax(logits_d, -1).astype(jnp.int32)
    step_d, _ = decode_step(params, tok_d, cache_d, cfg)
    step_q, _ = decode_step(qparams, tok_d, cache_q, cfg)
    rel = float(
        jnp.linalg.norm(step_d - step_q)
        / jnp.maximum(jnp.linalg.norm(step_d), 1e-9)
    )
    assert rel < 0.05, f"decode-step relative error {rel}"


def test_quantized_serve_engine_generates():
    engine = ServeEngine(cfg=_cfg(), quantize=True)
    assert engine.quantized
    events = list(engine.generate("hello quant", max_new_tokens=6, stop_at_eos=False))
    assert len(events) == 6
    assert events[0].ttft_ms is not None
    rows = engine.generate_batch(["a", "bb"], max_new_tokens=4, stop_at_eos=False)
    assert [len(r) for r in rows] == [4, 4]


def test_quantized_bytes_accounting():
    cfg = _cfg()
    n = param_count(cfg)
    qb = quantized_bytes(cfg)
    assert n < qb < 1.1 * n  # int8 body + small fp32 scale/norm overhead


def test_int8_leaves_really_int8():
    cfg = _cfg()
    q = quantize_params(init_params(jax.random.PRNGKey(0), cfg))
    assert q["layers"]["w1"]["q"].dtype == jnp.int8
    assert q["embed"]["q"].dtype == jnp.int8
    assert q["output"]["q"].dtype == jnp.int8
    assert q["layers"]["attn_norm"].dtype == cfg.dtype

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow
