"""tpuslo.utils: atomic artifact writes + git provenance."""

from __future__ import annotations

import json
import os
import stat

from tpuslo.utils import git_short_sha, write_json_atomic, write_text_atomic


def test_write_text_atomic_creates_dirs_and_content(tmp_path):
    path = tmp_path / "nested" / "dir" / "artifact.txt"
    write_text_atomic(str(path), "hello\n")
    assert path.read_text() == "hello\n"


def test_write_json_atomic_roundtrip(tmp_path):
    path = tmp_path / "artifact.json"
    write_json_atomic(str(path), {"a": [1, 2], "b": "x"})
    assert json.loads(path.read_text()) == {"a": [1, 2], "b": "x"}


def test_atomic_write_replaces_not_truncates(tmp_path):
    """A failed dump must never leave a truncated artifact: the old
    content survives any tmp-file path, and a successful write fully
    replaces it."""
    path = tmp_path / "artifact.json"
    write_json_atomic(str(path), {"generation": 1})
    write_json_atomic(str(path), {"generation": 2})
    assert json.loads(path.read_text()) == {"generation": 2}
    # No stray temp files left behind.
    assert [p.name for p in tmp_path.iterdir()] == ["artifact.json"]


def test_failed_dump_preserves_previous_artifact(tmp_path):
    """The actual crash-safety property: a serialization failure
    mid-write leaves the previous good artifact intact (a plain
    truncating open() would have destroyed it)."""
    import pytest

    path = tmp_path / "artifact.json"
    write_json_atomic(str(path), {"generation": 1})
    with pytest.raises(TypeError):
        write_json_atomic(str(path), {"bad": object()})
    assert json.loads(path.read_text()) == {"generation": 1}
    assert [p.name for p in tmp_path.iterdir()] == ["artifact.json"]


def test_permissions_match_plain_open(tmp_path):
    """mkstemp defaults to 0600; the helper must honor the umask like
    plain open() so committed artifacts stay readable in containers
    that drop privileges."""
    atomic = tmp_path / "atomic.txt"
    plain = tmp_path / "plain.txt"
    write_text_atomic(str(atomic), "x")
    with open(plain, "w") as fh:
        fh.write("x")
    assert stat.S_IMODE(os.stat(atomic).st_mode) == stat.S_IMODE(
        os.stat(plain).st_mode
    )


def test_git_short_sha_in_repo_and_outside(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sha = git_short_sha(repo_root)
    assert sha != "unknown" and 6 <= len(sha) <= 16
    # Outside any repo: best-effort "unknown", never an exception.
    assert git_short_sha(str(tmp_path)) == "unknown"
