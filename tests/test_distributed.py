"""Real multi-process distributed collectives (the DCN-analog path).

N OS processes join one jax.distributed runtime (gloo CPU collectives)
and measure cross-process psum launches — the actual multi-host shape
the single-process virtual mesh cannot exercise.  The straggler test's
physics is real: the collective blocks the punctual hosts until the
delayed one arrives.
"""

from __future__ import annotations

import pytest

from tpuslo.parallel.distributed import run_distributed_probe

pytestmark = pytest.mark.slow  # two jax processes per test


def test_cross_process_collectives_measured():
    report = run_distributed_probe(n_processes=2, launches=3)
    assert report["errors"] == []
    assert report["events_measured"] == 6  # 3 launches x 2 hosts
    assert report["mechanism"] == "jax_distributed_gloo"
    # Healthy run: no straggler incidents (skew under the floor).
    assert report["incidents"] == []


def test_delayed_host_stalls_the_collective_and_is_attributed():
    report = run_distributed_probe(
        n_processes=2, launches=4, delay_ms=200.0, delayed_host=1
    )
    assert report["errors"] == []
    assert report["correct_attributions"] == 4
    assert report["top_confidence"] >= 0.7
    incident = report["incidents"][0]
    # REAL collective physics: the punctual host measured ~the delay
    # (it was blocked inside psum), the delayed host sailed through.
    lat = incident["host_latencies_ms"]
    assert lat["0"] > 150.0
    assert lat["1"] < 50.0


def test_icibench_multiprocess_cli(tmp_path):
    import json
    import subprocess
    import sys

    from tpuslo.schema import SCHEMA_PROBE_EVENT, validate

    out = tmp_path / "dist_events.jsonl"
    report_path = tmp_path / "dist_report.json"
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpuslo", "icibench",
            "--multiprocess", "2", "--reps", "2",
            "--delay-host", "0", "--delay-ms", "120",
            "--output", str(out), "--report", str(report_path),
        ],
        capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr
    # Same output contract as the single-process path: schema-valid
    # probe-event JSONL (4 = 2 launches x 2 hosts).
    events = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(events) == 4
    for event in events:
        validate(event, SCHEMA_PROBE_EVENT)
        assert event["signal"] == "ici_collective_latency_ms"
    report = json.loads(report_path.read_text())
    assert report["correct_attributions"] == 2
    assert "events" not in report  # summary only; events live in --output
    assert "cross-process events" in proc.stderr


def test_icibench_multiprocess_flag_validation(tmp_path):
    import subprocess
    import sys

    # Out-of-range delay host: exit 2, nothing written.
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpuslo", "icibench",
            "--multiprocess", "2", "--delay-host", "2",
            "--output", str(tmp_path / "x.jsonl"),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "out of range" in proc.stderr
    assert not (tmp_path / "x.jsonl").exists()
    # Invalid --ops still rejected in multiprocess mode.
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpuslo", "icibench",
            "--multiprocess", "2", "--ops", "bogus",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "unknown ops" in proc.stderr


def test_crashed_worker_kills_survivors_under_one_deadline(monkeypatch):
    """If one worker crashes, the survivors block forever inside the
    collective; the probe must kill them as soon as the crash is seen
    (one SHARED deadline), not stack N sequential timeouts."""
    import time
    import types

    from tpuslo.parallel import distributed as dist

    class FakeProc:
        def __init__(self, rc, out="", err="", exits_after=0.0):
            self._rc = rc
            self._out, self._err = out, err
            self._born = time.monotonic()
            self._exits_after = exits_after
            self.killed = False
            self.returncode = None

        def poll(self):
            if self.killed:
                self.returncode = -9
                return self.returncode
            if time.monotonic() - self._born >= self._exits_after:
                self.returncode = self._rc
                return self.returncode
            return None

        def kill(self):
            self.killed = True
            self.returncode = -9

        def communicate(self, timeout=None):
            return self._out, self._err

    crasher = FakeProc(rc=1, err="boom: gloo rendezvous failed",
                       exits_after=0.1)
    hung = FakeProc(rc=0, exits_after=3600.0)  # would block forever
    procs = iter([crasher, hung])
    fake_subprocess = types.SimpleNamespace(
        Popen=lambda *a, **k: next(procs), PIPE=-1
    )
    monkeypatch.setattr(dist, "subprocess", fake_subprocess)

    t0 = time.monotonic()
    report = dist.run_distributed_probe(n_processes=2, timeout_s=300.0)
    elapsed = time.monotonic() - t0

    assert elapsed < 10.0  # NOT 300s, and never N*300s
    assert hung.killed
    assert any("peer exited nonzero" in e for e in report["errors"])
    assert any("boom" in e for e in report["errors"])


def test_two_slice_probe_measures_dcn_component():
    """2 hosts as 2 slices: the global round crosses slices, so with a
    delayed host the punctual host's measured dcn_transfer component
    carries the stall while intra-slice rounds (single-host psum) stay
    clean — the dcn fault physiology, measured over a real IPC
    collective, not simulated."""
    report = run_distributed_probe(
        n_processes=2, launches=3, delay_ms=200.0, delayed_host=1,
        n_slices=2,
    )
    assert report["errors"] == []
    assert report["n_slices"] == 2
    assert report["dcn_events"] == 6  # 3 launches x 2 hosts
    dcn = [
        e for e in report["events"]
        if e["signal"] == "dcn_transfer_latency_ms"
    ]
    intra = [
        e for e in report["events"]
        if e["signal"] == "ici_collective_latency_ms"
    ]
    # Punctual host 0: the cross-slice round absorbed the delay.
    host0_dcn = [e["value"] for e in dcn if e["tpu"]["host_index"] == 0]
    assert max(host0_dcn) > 150.0
    # Intra rounds are slice-local (here: single host) — clean.
    assert all(e["value"] < 50.0 for e in intra)
    # Per-slice identity rides the events.
    slices = {e["tpu"]["slice_id"] for e in dcn}
    assert slices == {"dist-slice-0", "dist-slice-1"}
    # SliceJoiner attributes the delayed host over the cross-slice
    # group, names its slice, and blames the DCN path (no ICI link
    # evidence applies across slices).
    assert report["correct_attributions"] == 3
    incident = report["incidents"][0]
    assert incident["cause"] == "dcn_path"
    assert incident["straggler_slice"] == "dist-slice-1"


def test_icibench_rejects_misaligned_slices(tmp_path):
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tpuslo", "icibench",
         "--multiprocess", "2", "--n-slices", "3"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2
    assert "must divide" in proc.stderr


def test_four_host_two_slice_attribution_is_slice_level():
    """The review scenario: with 2 hosts per slice, the delayed host's
    intra-slice PEER absorbs the stall intra-slice, so every host of
    the delayed slice shows a near-zero dcn component.  The dcn verdict
    must therefore be slice-level (lowest mean component), and the
    per-host verdict comes from the intra-slice ICI group, which the
    right-sized min_hosts no longer suppresses."""
    report = run_distributed_probe(
        n_processes=4, launches=2, delay_ms=200.0, delayed_host=1,
        n_slices=2,
    )
    assert report["errors"] == []
    dcn_incidents = [
        i for i in report["incidents"] if i["cause"] == "dcn_path"
    ]
    assert dcn_incidents, report["incidents"]
    for i in dcn_incidents:
        assert i["straggler_slice"] == "dist-slice-0"  # host 1's slice
    intra_incidents = [
        i for i in report["incidents"]
        if i["cause"] != "dcn_path" and i["slice_id"] == "dist-slice-0"
    ]
    assert intra_incidents, report["incidents"]
    for i in intra_incidents:
        assert i["straggler_host"] == 1
