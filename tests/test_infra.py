"""Static sanity gates for the Terraform runner module.

The dev/CI container has no terraform binary (real validation is the
``terraform-validate`` job in ci.yml, which runs on GitHub's runners);
these checks catch the mechanical drift that survives until then:
undeclared/unused variables, unbalanced blocks, and a startup template
whose placeholders don't match what main.tf passes in.
"""

import re
from pathlib import Path

MODULE = Path(__file__).resolve().parent.parent / "infra" / "runner" / "gcp"


def _read(name: str) -> str:
    return (MODULE / name).read_text(encoding="utf-8")


def _strip_comments(text: str) -> str:
    return "\n".join(line.split("#", 1)[0] for line in text.splitlines())


class TestRunnerModule:
    def test_files_present(self):
        for name in ("main.tf", "variables.tf", "outputs.tf", "startup.sh.tftpl"):
            assert (MODULE / name).is_file(), name

    def test_braces_balanced(self):
        for name in ("main.tf", "variables.tf", "outputs.tf"):
            text = _strip_comments(_read(name))
            assert text.count("{") == text.count("}"), name

    def test_every_used_variable_is_declared(self):
        declared = set(
            re.findall(r'variable\s+"([a-z0-9_]+)"', _read("variables.tf"))
        )
        used = set(re.findall(r"var\.([a-z0-9_]+)", _read("main.tf")))
        assert used <= declared, f"undeclared: {used - declared}"

    def test_every_declared_variable_is_used(self):
        declared = set(
            re.findall(r'variable\s+"([a-z0-9_]+)"', _read("variables.tf"))
        )
        used = set(re.findall(r"var\.([a-z0-9_]+)", _read("main.tf")))
        assert declared <= used, f"dead variables: {declared - used}"

    def test_startup_template_placeholders_match_templatefile_args(self):
        # templatefile(...) { gh_repo = ..., gh_runner_token = ..., ... }
        main = _read("main.tf")
        call = re.search(
            r"templatefile\([^)]*startup\.sh\.tftpl[^{]*\{(.*?)\n\s*\}\)",
            main,
            re.S,
        )
        assert call, "templatefile call for startup.sh.tftpl not found"
        passed = set(re.findall(r"([a-z0-9_]+)\s*=", call.group(1)))
        template = _read("startup.sh.tftpl")
        # ${name} placeholders; $${...} would be literal-escaped.
        placeholders = {
            m
            for m in re.findall(r"(?<!\$)\$\{([a-z0-9_]+)\}", template)
            # Shell vars rendered at runtime are upper-case by
            # convention in this template; terraform placeholders are
            # lower-case.
            if m.islower()
        }
        assert placeholders <= passed, f"unfed placeholders: {placeholders - passed}"
        assert passed <= placeholders, f"unused template args: {passed - placeholders}"

    def test_runner_labels_cover_workflow_targets(self):
        """The labels the workflows schedule on must be provisioned."""
        default = re.search(
            r'variable\s+"runner_labels".*?default\s*=\s*\[(.*?)\]',
            _read("variables.tf"),
            re.S,
        )
        assert default
        labels = set(re.findall(r'"([^"]+)"', default.group(1)))
        assert {"self-hosted", "tpu-vm"} <= labels

    def test_sensitive_token_is_marked(self):
        block = re.search(
            r'variable\s+"gh_runner_token"\s*\{(.*?)\n\}',
            _read("variables.tf"),
            re.S,
        )
        assert block and "sensitive" in block.group(1)
