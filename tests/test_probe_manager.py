"""ProbeManager planning/shedding tests + symbol resolution tests.

Attachment itself needs privileges; planning, symbol resolution, the
manifest contract, and shed ordering are all testable unprivileged —
the same split the reference uses (probe_manager_test.go exercises the
lifecycle with nil links).
"""

from __future__ import annotations

import ctypes
import ctypes.util
from pathlib import Path

import pytest

from tpuslo.signals import constants as sig
from tpuslo.collector import symbols
from tpuslo.collector.probe_manager import (
    DEFAULT_MANIFEST,
    SIGNAL_IDS,
    ProbeManager,
    make_cookie,
)


def test_manifest_parses_and_covers_tpu_signals():
    import yaml

    with open(DEFAULT_MANIFEST, "r", encoding="utf-8") as fh:
        manifest = yaml.safe_load(fh)
    covered = set(manifest["signals"])
    assert covered == {
        "xla_compile_ms",
        "hbm_alloc_stall_ms",
        "ici_collective_latency_ms",
        "ici_link_retries_total",
        "host_offload_stall_ms",
        "dcn_transfer_latency_ms",
    }
    for spec in manifest["signals"].values():
        assert spec["kind"] in ("span", "counter", "kprobe_ioctl")
        assert spec["candidates"]


def test_cookie_encodes_signal_id():
    cookie = make_cookie(sig.SIGNAL_XLA_COMPILE_MS, "TpuCompiler_Compile")
    assert cookie >> 48 == SIGNAL_IDS[sig.SIGNAL_XLA_COMPILE_MS]
    # Fingerprint is stable.
    assert cookie == make_cookie(
        sig.SIGNAL_XLA_COMPILE_MS, "TpuCompiler_Compile"
    )
    assert cookie != make_cookie(sig.SIGNAL_XLA_COMPILE_MS, "OtherSymbol")


def test_elf_symbol_resolution_against_libc():
    libc = ctypes.util.find_library("c")
    assert libc is not None
    # find_library returns a soname; resolve to a real path.
    candidates = [
        p
        for base in ("/lib", "/usr/lib", "/lib/x86_64-linux-gnu",
                     "/usr/lib/x86_64-linux-gnu", "/lib/aarch64-linux-gnu")
        for p in Path(base).glob("libc.so.6")
        if p.exists()
    ]
    if not candidates:
        pytest.skip("libc.so.6 not found on disk")
    path = candidates[0]
    resolved = symbols.resolve_elf_symbol(str(path), ["getaddrinfo"])
    assert resolved is not None
    assert "getaddrinfo" in resolved.name.lower()
    assert resolved.file_offset > 0


def test_elf_resolution_pattern_priority():
    candidates = [
        p
        for base in ("/lib", "/usr/lib", "/lib/x86_64-linux-gnu",
                     "/usr/lib/x86_64-linux-gnu", "/lib/aarch64-linux-gnu")
        for p in Path(base).glob("libc.so.6")
        if p.exists()
    ]
    if not candidates:
        pytest.skip("libc.so.6 not found on disk")
    # First pattern that matches wins even if a later one also would.
    resolved = symbols.resolve_elf_symbol(
        str(candidates[0]), ["no_such_symbol_xyz", "malloc"]
    )
    assert resolved is not None
    assert "malloc" in resolved.name.lower()


def test_kernel_symbol_resolution(tmp_path):
    kallsyms = tmp_path / "kallsyms"
    kallsyms.write_text(
        "0000000000000000 t some_private_fn\n"
        "0000000000000000 T vfio_device_fops_unl_ioctl\n"
        "0000000000000000 D some_data\n"
    )
    hit = symbols.resolve_kernel_symbol(
        ["accel_ioctl", "vfio_device_fops_unl_ioctl"], str(kallsyms)
    )
    assert hit == "vfio_device_fops_unl_ioctl"
    miss = symbols.resolve_kernel_symbol(["nope"], str(kallsyms))
    assert miss is None


def test_plan_reports_missing_objects_and_symbols(tmp_path):
    pm = ProbeManager(obj_dir=tmp_path)  # empty: nothing built
    plans = {
        p.signal: p
        for p in pm.plan(list(sig.supported_signals_for_mode("tpu_full")))
    }
    assert len(plans) == len(sig.ALL_SIGNALS)
    # Kernel signals: object missing (not built in tmp dir).
    assert plans[sig.SIGNAL_DNS_LATENCY_MS].status == "no_object"
    # hbm utilization is a sampler, never a probe.
    assert plans[sig.SIGNAL_HBM_UTILIZATION_PCT].kind == "sampler"
    # Derived signals ride their parent.
    assert plans[sig.SIGNAL_CONNECT_ERRORS].kind == "none"
    assert "connect_latency_ms" in plans[sig.SIGNAL_CONNECT_ERRORS].detail
    # TPU signals: no libtpu on this host -> no_symbol (except ioctl,
    # which may or may not find a vfio symbol in kallsyms).
    assert plans[sig.SIGNAL_XLA_COMPILE_MS].status in ("no_symbol", "no_object")


def test_attach_all_reports_unavailable_without_privileges(tmp_path):
    pm = ProbeManager(obj_dir=tmp_path)
    report = pm.attach_all([sig.SIGNAL_DNS_LATENCY_MS])
    assert len(report.results) == 1
    result = report.results[0]
    # Either libbpf is missing (unavailable) or load fails unprivileged;
    # both are honest non-attached outcomes.
    assert not result.attached or result.status == "attached"
    payload = report.to_dict()
    assert "attached" in payload and "results" in payload


def test_shed_order_prefers_tpu_probes():
    order = sig.disable_order()
    tpu_positions = [order.index(s) for s in sig.TPU_SIGNALS]
    cpu_positions = [order.index(s) for s in sig.CPU_SIGNALS]
    assert max(tpu_positions) < min(cpu_positions)


class _TrippedGuard:
    def evaluate(self):
        from tpuslo.safety import OverheadResult

        return OverheadResult(
            cpu_pct=9.0, budget_pct=3.0, over_budget=True, valid=True
        )


def test_check_overhead_sheds_in_cost_order(tmp_path):
    pm = ProbeManager(obj_dir=tmp_path, guard=_TrippedGuard())
    # Simulate two attached signals without touching libbpf.
    pm._attached = {
        sig.SIGNAL_DNS_LATENCY_MS: "h1",
        sig.SIGNAL_ICI_COLLECTIVE_MS: "h2",
    }
    shed = pm.check_overhead()
    assert shed == sig.SIGNAL_ICI_COLLECTIVE_MS  # TPU probe goes first
    assert sig.SIGNAL_DNS_LATENCY_MS in pm.attached_signals
    assert pm.shed_signals == [sig.SIGNAL_ICI_COLLECTIVE_MS]


def test_restore_one_reattaches_last_shed(tmp_path, monkeypatch):
    from tpuslo.collector.probe_manager import AttachReport, AttachResult

    pm = ProbeManager(obj_dir=tmp_path, guard=_TrippedGuard())
    pm._attached = {
        sig.SIGNAL_DNS_LATENCY_MS: "h1",
        sig.SIGNAL_ICI_COLLECTIVE_MS: "h2",
        sig.SIGNAL_XLA_COMPILE_MS: "h3",
    }
    assert pm.check_overhead() == sig.SIGNAL_ICI_COLLECTIVE_MS
    assert pm.check_overhead() == sig.SIGNAL_XLA_COMPILE_MS
    assert pm.shed_signals == [
        sig.SIGNAL_ICI_COLLECTIVE_MS, sig.SIGNAL_XLA_COMPILE_MS,
    ]

    # Stub the native attach: restore re-plans exactly the popped
    # signal and succeeds.
    def fake_attach_all(signal_names):
        report = AttachReport()
        for name in signal_names:
            pm._attached[name] = f"restored:{name}"
            report.results.append(
                AttachResult(signal=name, attached=True, status="attached")
            )
        return report

    monkeypatch.setattr(pm, "attach_all", fake_attach_all)
    # Reverse cost order: the last-shed (cheapest) probe comes back first.
    assert pm.restore_one() == sig.SIGNAL_XLA_COMPILE_MS
    assert pm.shed_signals == [sig.SIGNAL_ICI_COLLECTIVE_MS]
    assert pm.restore_one() == sig.SIGNAL_ICI_COLLECTIVE_MS
    assert pm.restore_one() is None


def test_restore_one_keeps_signal_on_failed_reattach(tmp_path, monkeypatch):
    from tpuslo.collector.probe_manager import AttachReport, AttachResult

    pm = ProbeManager(obj_dir=tmp_path, guard=_TrippedGuard())
    pm._attached = {sig.SIGNAL_ICI_COLLECTIVE_MS: "h2"}
    pm.check_overhead()

    def failing_attach_all(signal_names):
        report = AttachReport()
        report.results.append(
            AttachResult(
                signal=signal_names[0], attached=False, status="no_symbol",
            )
        )
        return report

    monkeypatch.setattr(pm, "attach_all", failing_attach_all)
    # libtpu vanished: the signal stays shed for a later retry instead
    # of being forgotten.
    assert pm.restore_one() is None
    assert pm.shed_signals == [sig.SIGNAL_ICI_COLLECTIVE_MS]
