"""Deploy-layer contract tests: manifests parse, reference each other
consistently, and keep the privileged/min-capability split honest."""

from __future__ import annotations

from pathlib import Path

import yaml

REPO = Path(__file__).resolve().parent.parent


def _load_all(path: Path) -> list[dict]:
    with open(path, "r", encoding="utf-8") as fh:
        return [d for d in yaml.safe_load_all(fh) if d]


def _all_yaml_files() -> list[Path]:
    out = []
    for root in ("deploy", "config"):
        out.extend(sorted((REPO / root).rglob("*.yaml")))
    return out


def test_every_manifest_parses():
    files = _all_yaml_files()
    assert len(files) >= 12
    for path in files:
        docs = _load_all(path)
        assert docs, f"{path} is empty"


def test_daemonset_mounts_tpu_surface():
    (ds,) = _load_all(REPO / "deploy/k8s/daemonset.yaml")
    spec = ds["spec"]["template"]["spec"]
    assert spec["hostPID"] is True
    container = spec["containers"][0]
    caps = container["securityContext"]["capabilities"]["add"]
    assert "BPF" in caps
    mounts = {m["name"]: m for m in container["volumeMounts"]}
    assert "dev-accel" in mounts           # /dev/accel* probe surface
    assert "libtpu" in mounts              # uprobe target ELF
    assert mounts["sys"]["readOnly"] is True
    volumes = {v["name"] for v in spec["volumes"]}
    assert {"bpffs", "modules", "config"} <= volumes


def test_min_capability_overlay_drops_privileges():
    (patch,) = _load_all(
        REPO / "deploy/k8s/min-capability/daemonset-patch.yaml"
    )
    spec = patch["spec"]["template"]["spec"]
    assert spec["hostPID"] is False
    sc = spec["containers"][0]["securityContext"]
    assert sc["privileged"] is False
    assert sc["capabilities"]["drop"] == ["ALL"]
    (cm,) = _load_all(
        REPO / "deploy/k8s/min-capability/configmap-patch.yaml"
    )
    assert cm["data"]["AGENT_PROBE_SOURCE"] == "synthetic"
    degraded_cfg = yaml.safe_load(cm["data"]["toolkit.yaml"])
    assert degraded_cfg["signal_set"] == [
        "dns_latency_ms", "tcp_retransmits_total",
    ]


def test_default_config_matches_loader_schema():
    from tpuslo.config import toolkitcfg

    cfg = toolkitcfg.load_config(str(REPO / "config/toolkit.yaml"))
    assert cfg.safety.max_overhead_pct == 3.0
    assert "xla_compile_ms" in cfg.signal_set
    assert len(cfg.signal_set) == 16


def test_alert_rules_cover_tpu_fault_domains():
    docs = _load_all(REPO / "deploy/observability/prometheus-alerts.yaml")
    rules_yaml = yaml.safe_load(docs[0]["data"]["tpuslo-alerts.yaml"])
    alerts = [
        r["alert"]
        for group in rules_yaml["groups"]
        for r in group["rules"]
    ]
    assert len(alerts) >= 8
    domains = {
        r["labels"].get("fault_domain")
        for group in rules_yaml["groups"]
        for r in group["rules"]
        if "fault_domain" in r.get("labels", {})
    }
    assert {"network_dns", "tpu_ici", "tpu_hbm"} <= domains


def test_helm_values_parse_and_mirror_defaults():
    values = yaml.safe_load(
        (REPO / "charts/tpu-slo-agent/values.yaml").read_text()
    )
    assert values["agent"]["probeSource"] == "ring"
    assert len(values["config"]["signalSet"]) == 16
    assert values["config"]["maxOverheadPct"] == 3.0


def test_helm_test_hook_references_resolve():
    """No helm binary in this image; statically check the chart test
    hook only uses helpers that _helpers.tpl defines, targets the
    Service name templates/service.yaml actually renders, and greps a
    metric the agent registry actually exports."""
    import re

    chart = REPO / "charts/tpu-slo-agent"
    hook = (chart / "templates/tests/test-connection.yaml").read_text()
    helpers = (chart / "templates/_helpers.tpl").read_text()
    defined = set(re.findall(r'define\s+"([^"]+)"', helpers))
    used = set(re.findall(r'include\s+"([^"]+)"', hook))
    assert used <= defined, f"undefined helpers: {used - defined}"
    # Service is <name>-metrics (templates/service.yaml).
    assert '-metrics:' in hook
    assert '"helm.sh/hook": test' in hook
    metric = re.search(r"grep -q \"\^(\w+)", hook).group(1)
    registry = (REPO / "tpuslo/metrics/registry.py").read_text()
    assert metric in registry, f"hook greps unknown metric {metric}"
    assert (chart / ".helmignore").is_file()


def test_rag_demo_manifests():
    """Demo workload ships deployable manifests (reference
    demo/rag-service/k8s)."""
    k8s = REPO / "demo/rag_service/k8s"
    (dep,) = _load_all(k8s / "deployment.yaml")
    (svc,) = _load_all(k8s / "service.yaml")
    (kus,) = _load_all(k8s / "kustomization.yaml")
    assert dep["kind"] == "Deployment"
    container = dep["spec"]["template"]["spec"]["containers"][0]
    port = container["ports"][0]["containerPort"]
    assert port == 18080
    assert svc["spec"]["ports"][0]["targetPort"] == "http"
    assert dep["spec"]["selector"]["matchLabels"] == svc["spec"]["selector"]
    assert set(kus["resources"]) == {"deployment.yaml", "service.yaml"}
    # backend choices in the manifest must exist in the server CLI
    server = (REPO / "demo/rag_service/server.py").read_text()
    backend = next(
        e["value"] for e in container["env"] if e["name"] == "LLM_BACKEND"
    )
    assert f'"{backend}"' in server
    assert (REPO / "demo/rag_service/Dockerfile").is_file()


def test_observability_metric_names_resolve():
    """Every metric the dashboards/alerts query must be declared by the
    agent registry or the demo service — this drifted once (dashboards
    queried llm_slo_agent_hbm_utilization_pct; the registry exports
    llm_tpu_agent_hbm_utilization_pct)."""
    import re

    scanned = [
        REPO / "dashboards/generate.py",
        REPO / "deploy/observability/prometheus-alerts.yaml",
        *sorted((REPO / "test/incident-lab/scenarios").glob("*.yaml")),
    ]
    queries = "".join(p.read_text() for p in scanned)
    declared = (
        (REPO / "tpuslo/metrics/registry.py").read_text()
        + (REPO / "demo/rag_service/service.py").read_text()
    )
    referenced = set(re.findall(r"llm_[a-z0-9_]+", queries))
    assert len(referenced) >= 8
    for name in sorted(referenced):
        base = re.sub(r"_(bucket|count|sum)$", "", name)
        candidates = {base, re.sub(r"_total$", "", base)}
        assert any(c in declared for c in candidates), (
            f"dashboard/alert references undeclared metric {name}"
        )


def test_agent_args_exist_in_cli():
    """Every --flag the DaemonSets pass must exist in the agent parser
    (an env/values knob pointing at a removed flag crashlooms)."""
    import re

    from tpuslo.cli.agent import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    sources = [
        (REPO / "deploy/k8s/daemonset.yaml").read_text(),
        (REPO / "charts/tpu-slo-agent/templates/daemonset.yaml").read_text(),
    ]
    for text in sources:
        for flag in re.findall(r"(--[a-z][a-z0-9-]*)=", text):
            assert flag in known, f"daemonset passes unknown flag {flag}"
    # The kustomize daemonset's env indirections must be defined in the
    # configmap.
    ds = (REPO / "deploy/k8s/daemonset.yaml").read_text()
    cm = (REPO / "deploy/k8s/configmap.yaml").read_text()
    for var in re.findall(r"\$\((AGENT_[A-Z_]+)\)", ds):
        assert f"{var}:" in cm, f"daemonset references undefined env {var}"
