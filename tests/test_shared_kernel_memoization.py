"""The shared-kernel discipline: equal keys -> IDENTICAL callables.

Round 5 found that fresh closures per call (new function objects)
silently defeat jax's dispatch cache — every call re-traced and
re-compiled (sp serving recompiled the ring per prefill/step; the
suite paid hundreds of seconds).  These tests lock the fix: the
memoized builders must return the *same object* for equal-valued keys,
including meshes built fresh from the same devices (Mesh hashes by
value) and MoE mlp_fn hooks (a fresh lambda per call was the round's
sneakiest cache-killer).
"""

from __future__ import annotations

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from tpuslo.models.llama import llama_tiny

pytestmark = pytest.mark.slow  # builds touch jit machinery


def _fresh_mesh(n: int = 2, axis: str = "sp") -> Mesh:
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def test_sp_builders_share_across_fresh_equal_meshes():
    from tpuslo.models.longserve import (
        _sp_decode_fn,
        _sp_generate_step,
        _sp_prefill_fn,
    )

    cfg = llama_tiny(max_seq_len=256)
    a, b = _fresh_mesh(), _fresh_mesh()
    # jax interns Mesh instances (equal construction may return the
    # SAME object); either way the builders must key by value.
    assert a == b
    assert _sp_prefill_fn(cfg, a, "sp", "bf16", None) is _sp_prefill_fn(
        cfg, b, "sp", "bf16", None
    )
    assert _sp_decode_fn(cfg, a, "sp", None, False) is _sp_decode_fn(
        cfg, b, "sp", None, False
    )
    assert _sp_generate_step(cfg, a, "sp", None) is _sp_generate_step(
        cfg, b, "sp", None
    )


def test_ring_attention_builder_shares_across_fresh_meshes():
    from tpuslo.ops.ring_attention import _ring_fn

    assert _ring_fn(_fresh_mesh(), "sp") is _ring_fn(_fresh_mesh(), "sp")


def test_train_step_builders_share_across_equal_keys():
    from tpuslo.models.mixtral import build_moe_train_step, mixtral_tiny
    from tpuslo.models.train import build_sharded_train_step
    from tpuslo.parallel.mesh import MeshPlan, make_mesh

    cfg = llama_tiny(max_seq_len=64)
    step_a, init_a = build_sharded_train_step(
        make_mesh(MeshPlan(dp=2, fsdp=2, tp=2)), cfg
    )
    step_b, init_b = build_sharded_train_step(
        make_mesh(MeshPlan(dp=2, fsdp=2, tp=2)), cfg
    )
    assert step_a is step_b and init_a is init_b

    mcfg = mixtral_tiny(max_seq_len=64)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "ep"))
    mesh2 = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "ep"))
    ma, _ = build_moe_train_step(mesh, mcfg)
    mb, _ = build_moe_train_step(mesh2, mcfg)
    assert ma is mb


def test_moe_serving_mlp_fn_is_identity_stable():
    """The mlp_fn hook keys downstream jit caches by IDENTITY; a fresh
    lambda per call recompiles the whole serving path."""
    from tpuslo.models.mixtral import _serving_mlp_fn, mixtral_tiny

    cfg = mixtral_tiny(max_seq_len=64)
    assert _serving_mlp_fn(cfg) is _serving_mlp_fn(
        mixtral_tiny(max_seq_len=64)
    )


def test_engine_shared_kernels_are_single_caches():
    """decode_step's shared compile lives ONCE (serve.py): the batching
    and speculative engines must resolve to the same builder."""
    from tpuslo.models.batching import _shared_batch_step_fn
    from tpuslo.models.serve import _shared_decode_step_fn
    from tpuslo.models.speculative import (
        _shared_decode_step_fn as spec_step_fn,
    )

    assert _shared_batch_step_fn is _shared_decode_step_fn
    assert spec_step_fn is _shared_decode_step_fn
    cfg = llama_tiny(max_seq_len=256)
    assert _shared_decode_step_fn(cfg) is _shared_decode_step_fn(cfg)
