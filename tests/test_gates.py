"""Release-gate / benchmark / cdgate / prereq tests.

Reference model: pkg/releasegate/gate_test.go, pkg/cdgate/gate_test.go,
pkg/prereq/checker_test.go, pkg/benchmark/harness_test.go.
"""

import csv
import json
from datetime import datetime, timezone
from pathlib import Path

import pytest

from tpuslo import attribution, benchmark, cdgate, prereq, releasegate
from tpuslo.faultreplay import generate_fault_samples

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)


class TestStats:
    def test_mean_stddev_cv(self):
        values = [10.0, 12.0, 8.0, 10.0]
        assert releasegate.mean(values) == 10.0
        assert releasegate.coefficient_of_variance_pct([5.0, 5.0, 5.0]) == 0.0
        assert releasegate.stddev([1.0]) == 0.0

    def test_mann_whitney_identical_distributions(self):
        x = [float(v) for v in range(1, 31)]
        p = releasegate.mann_whitney_p_value(x, list(x))
        assert p > 0.9

    def test_mann_whitney_shifted_distributions(self):
        x = [float(v) for v in range(1, 31)]
        y = [float(v + 50) for v in range(1, 31)]
        p = releasegate.mann_whitney_p_value(x, y)
        assert p < 0.001

    def test_mann_whitney_empty(self):
        assert releasegate.mann_whitney_p_value([], [1.0]) == 1.0

    def test_cliffs_delta_bounds(self):
        assert releasegate.cliffs_delta([1, 2], [3, 4]) == -1.0
        assert releasegate.cliffs_delta([3, 4], [1, 2]) == 1.0
        assert releasegate.cliffs_delta([1, 2], [1, 2]) == 0.0
        assert releasegate.cliffs_delta([], [1]) == 0.0

    def test_bootstrap_deterministic(self):
        cand = [float(v) for v in range(100, 130)]
        base = [float(v) for v in range(100, 130)]
        a = releasegate.bootstrap_delta_ci(cand, base, 0.95, 200, seed=42)
        b = releasegate.bootstrap_delta_ci(cand, base, 0.95, 200, seed=42)
        assert a == b

    def test_bootstrap_detects_shift(self):
        cand = [float(v + 100) for v in range(30)]
        base = [float(v) for v in range(30)]
        low, high = releasegate.bootstrap_delta_ci(cand, base, 0.95, 500, seed=42)
        assert low > 0 and high >= low


def write_run(
    root: Path, scenario: str, run: str, ttft_shift: float = 0.0, cpu: float = 1.5
):
    run_dir = root / scenario / run
    run_dir.mkdir(parents=True)
    samples = generate_fault_samples(
        scenario if scenario in ("dns_latency", "hbm_pressure") else "dns_latency",
        40,
        TS,
    )
    with open(run_dir / "raw_samples.jsonl", "w") as f:
        for idx, s in enumerate(samples):
            from tpuslo.collector.synthetic import RawSample

            raw = RawSample(
                timestamp=s.timestamp,
                cluster="c",
                namespace="n",
                workload="w",
                service="s",
                node="tpu-vm-0",
                request_id=s.request_id,
                trace_id=s.trace_id,
                ttft_ms=800.0 + (idx % 7) * 10 + ttft_shift,
                request_latency_ms=1500.0,
                token_throughput_tps=18.0 + (idx % 3),
                error_rate=0.03,
                fault_label=s.fault_label,
            )
            f.write(json.dumps(raw.to_dict()) + "\n")
    with open(run_dir / "collector_overhead.csv", "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["node", "cpu_pct", "memory_mb"])
        writer.writerow(["tpu-vm-0", f"{cpu}", "110"])
        writer.writerow(["tpu-vm-1", f"{cpu + 0.3}", "115"])


@pytest.fixture
def artifact_tree(tmp_path):
    candidate = tmp_path / "candidate"
    baseline = tmp_path / "candidate" / "baseline"
    for run in ("run-1", "run-2", "run-3"):
        write_run(candidate, "dns_latency", run)
        write_run(baseline, "dns_latency", run)
    (baseline / "manifest.json").write_text(
        json.dumps({"source_ref": "v0.9", "source_commit": "abc123"})
    )
    return candidate


class TestReleaseGate:
    def scenarios(self):
        return ["dns_latency"]

    def test_all_gates_pass_on_clean_tree(self, artifact_tree):
        cfg = releasegate.Config(
            candidate_root=str(artifact_tree),
            scenarios=self.scenarios(),
            candidate_commit="def456",
        )
        summary = releasegate.evaluate(cfg)
        assert summary.passed, summary.failures
        assert summary.overhead.max_node_p95_pct <= 3.0
        assert summary.variance.scenarios[0].passed
        sig = summary.significance.scenarios[0]
        assert sig.minimum_samples_reached
        assert sig.passed

    def test_overhead_gate_fails_on_hot_node(self, tmp_path):
        candidate = tmp_path / "candidate"
        for run in ("run-1", "run-2", "run-3"):
            write_run(candidate, "dns_latency", run, cpu=4.5)
        cfg = releasegate.Config(
            candidate_root=str(candidate), scenarios=self.scenarios()
        )
        summary = releasegate.evaluate(cfg)
        assert not summary.overhead.passed
        assert "p95 overhead" in summary.overhead.failure_reason

    def test_variance_gate_fails_on_too_few_runs(self, tmp_path):
        candidate = tmp_path / "candidate"
        write_run(candidate, "dns_latency", "run-1")
        cfg = releasegate.Config(
            candidate_root=str(candidate), scenarios=self.scenarios()
        )
        summary = releasegate.evaluate(cfg)
        assert not summary.variance.passed
        assert "at least 3 runs" in summary.variance.scenarios[0].failure_reason

    def test_significance_regression_detected(self, tmp_path):
        candidate = tmp_path / "candidate"
        baseline = candidate / "baseline"
        for run in ("run-1", "run-2", "run-3"):
            write_run(candidate, "dns_latency", run, ttft_shift=120.0)
            write_run(baseline, "dns_latency", run)
        (baseline / "manifest.json").write_text(
            json.dumps({"source_ref": "v0.9", "source_commit": "abc123"})
        )
        cfg = releasegate.Config(
            candidate_root=str(candidate),
            scenarios=self.scenarios(),
            candidate_commit="def456",
        )
        summary = releasegate.evaluate(cfg)
        sig = summary.significance.scenarios[0]
        assert sig.ttft_regression_pct > 5.0
        assert sig.mann_whitney_p_value < 0.05
        assert not sig.passed
        assert not summary.passed

    def test_same_source_baseline_informational(self, artifact_tree):
        cfg = releasegate.Config(
            candidate_root=str(artifact_tree),
            scenarios=self.scenarios(),
            candidate_commit="abc123",  # matches manifest source_commit
        )
        summary = releasegate.evaluate(cfg)
        assert summary.baseline.same_source
        assert summary.significance.scenarios[0].informational_only

    def test_missing_required_manifest_fails(self, tmp_path):
        candidate = tmp_path / "candidate"
        for run in ("run-1", "run-2", "run-3"):
            write_run(candidate, "dns_latency", run)
        cfg = releasegate.Config(
            candidate_root=str(candidate),
            scenarios=self.scenarios(),
            require_baseline_manifest=True,
        )
        summary = releasegate.evaluate(cfg)
        assert not summary.baseline.passed

    def test_config_normalization_defaults(self):
        cfg = releasegate.Config().normalized()
        assert cfg.max_overhead_pct == 3.0
        assert cfg.bootstrap_seed == 42
        # defaults only include scenarios faultinject can actually produce
        assert "tpu_mixed" in cfg.scenarios
        assert "tpu_mixed_multi" not in cfg.scenarios


class TestBenchmarkHarness:
    def test_bundle_files_and_summary(self, tmp_path):
        opts = benchmark.Options(
            output_dir=str(tmp_path / "bundle"), scenario="tpu_mixed", count=24
        )
        bundle = benchmark.generate_artifacts(opts)
        for path in (
            bundle.predictions_csv,
            bundle.confusion_csv,
            bundle.overhead_csv,
            bundle.summary_json,
            bundle.report_md,
            bundle.provenance_json,
        ):
            assert Path(path).exists()
        assert bundle.summary["accuracy"] == 1.0
        assert bundle.summary["macro_f1"] >= 0.70
        provenance = json.loads(Path(bundle.provenance_json).read_text())
        assert provenance["seed"] == 42
        assert provenance["measured_overhead"] is True

    def test_bundle_from_input_jsonl(self, tmp_path):
        samples = generate_fault_samples("mixed", 10, TS)
        path = tmp_path / "input.jsonl"
        with open(path, "w") as f:
            attribution.dump_samples_jsonl(samples, f)
        opts = benchmark.Options(
            output_dir=str(tmp_path / "bundle"), input_samples=str(path)
        )
        bundle = benchmark.generate_artifacts(opts)
        assert bundle.summary["sample_count"] == 10

    def test_confusion_csv_well_formed(self, tmp_path):
        opts = benchmark.Options(output_dir=str(tmp_path), scenario="ici_drop", count=5)
        bundle = benchmark.generate_artifacts(opts)
        rows = list(csv.DictReader(open(bundle.confusion_csv)))
        assert rows[0]["actual"] == "tpu_ici"
        assert rows[0]["predicted"] == "tpu_ici"
        assert rows[0]["count"] == "5"


class FakeQuerier:
    def __init__(self, values):
        self.values = values

    def query(self, promql):
        if promql not in self.values:
            raise cdgate.QueryError("no data")
        value = self.values[promql]
        if isinstance(value, Exception):
            raise value
        return value


class TestCDGate:
    QUERIES = {"ttft_p95_ms": "q_ttft", "error_rate": "q_err", "burn_rate": "q_burn"}

    def test_gate_passes_under_thresholds(self):
        querier = FakeQuerier({"q_ttft": 420.0, "q_err": 0.01, "q_burn": 0.8})
        report = cdgate.evaluate_slo_gate(querier, queries=self.QUERIES)
        assert report.passed
        assert all(c.passed for c in report.checks)

    def test_gate_fails_on_breach(self):
        querier = FakeQuerier({"q_ttft": 1200.0, "q_err": 0.01, "q_burn": 0.8})
        report = cdgate.evaluate_slo_gate(querier, queries=self.QUERIES)
        assert not report.passed
        failed = [c for c in report.checks if not c.passed]
        assert failed[0].name == "ttft_p95_ms"

    def test_query_failure_counts(self):
        querier = FakeQuerier(
            {"q_ttft": cdgate.QueryError("boom"), "q_err": 0.01, "q_burn": 0.8}
        )
        report = cdgate.evaluate_slo_gate(querier, queries=self.QUERIES)
        assert not report.passed
        assert report.query_failures == 1


class TestPrereq:
    def test_parse_kernel_release(self):
        assert prereq.parse_kernel_release("6.18.5-fc-v18") == (6, 18)
        assert prereq.parse_kernel_release("5.15.0") == (5, 15)
        with pytest.raises(ValueError):
            prereq.parse_kernel_release("weird")

    def test_evaluate_blockers_and_warnings(self):
        snapshot = prereq.HostSnapshot(
            kernel_release="6.1.0",
            has_btf=True,
            is_root=True,
            bpftool="/usr/sbin/bpftool",
            clang="",
            accel_devices=["/dev/accel0"],
            libtpu_path="/usr/lib/libtpu.so",
            jax_available=True,
        )
        results = {r.name: r for r in prereq.evaluate(snapshot)}
        assert results["kernel_version"].passed
        assert results["btf_available"].passed
        assert results["accel_devices"].passed
        assert not results["clang"].passed
        assert results["clang"].severity == prereq.SEVERITY_WARNING

    def test_old_kernel_blocks(self):
        snapshot = prereq.HostSnapshot(kernel_release="4.19.0")
        results = {r.name: r for r in prereq.evaluate(snapshot)}
        assert not results["kernel_version"].passed
        assert results["kernel_version"].severity == prereq.SEVERITY_BLOCKER

    def test_collect_snapshot_runs(self):
        snapshot = prereq.collect_snapshot()
        assert snapshot.kernel_release
        assert snapshot.jax_available
