"""TPU-evidence persistence: serving_bench captures must survive a dead
tunnel into the driver-visible bench artifact.

The tunnel relay died before the driver's final capture in rounds 1-2,
so ``BENCH_r0{1,2}.json`` carried zero TPU serving numbers despite real
same-session measurements.  These tests pin the persistence contract:
a successful TPU run is written (atomically, with provenance) to a
committed artifact, and ``bench.py``'s fallback branch embeds that
artifact verbatim as ``serving_tpu_last_capture``.
"""

from __future__ import annotations

import json
import os

import pytest

from tpuslo.benchmark.serving_bench import (
    LATEST_CAPTURE_PATH,
    load_last_tpu_capture,
    persist_tpu_capture,
)


def test_persist_skips_non_tpu_results(tmp_path):
    path = str(tmp_path / "latest.json")
    assert not persist_tpu_capture({"backend": "cpu_fallback"}, path=path)
    assert not persist_tpu_capture({"backend": "unavailable"}, path=path)
    assert not os.path.exists(path)


def _complete_capture(**overrides):
    cap = {
        "backend": "tpu",
        "device_kind": "TPU v5 lite",
        "ttft_ms": 78.4,
        "decode_tokens_per_sec": 84.6,
        "mfu_prefill": 0.62,
        "xprof_launch_spans": 18,
    }
    cap.update(overrides)
    return cap


def test_persist_refuses_degraded_capture(tmp_path):
    """A run missing MFU or xprof evidence (flaky xprof, unknown chip)
    must not clobber the last complete committed capture."""
    path = str(tmp_path / "latest.json")
    assert persist_tpu_capture(_complete_capture(), path=path)
    assert not persist_tpu_capture(
        _complete_capture(xprof_launch_spans=None), path=path
    )
    degraded = _complete_capture()
    del degraded["mfu_prefill"]
    assert not persist_tpu_capture(degraded, path=path)
    artifact = load_last_tpu_capture(path=path)
    assert artifact["capture"]["xprof_launch_spans"] == 18


def test_persist_and_load_round_trip(tmp_path):
    path = str(tmp_path / "latest.json")
    result = _complete_capture()
    assert persist_tpu_capture(result, path=path)
    artifact = load_last_tpu_capture(path=path)
    assert artifact is not None
    assert artifact["capture"] == result
    prov = artifact["provenance"]
    assert prov["captured_at"]
    assert "serving_bench" in prov["capture_command"]
    assert "git_sha" in prov


def test_persist_overwrites_previous_capture(tmp_path):
    path = str(tmp_path / "latest.json")
    persist_tpu_capture(_complete_capture(ttft_ms=1.0), path=path)
    persist_tpu_capture(_complete_capture(ttft_ms=2.0), path=path)
    artifact = load_last_tpu_capture(path=path)
    assert artifact["capture"]["ttft_ms"] == 2.0


def test_load_missing_and_corrupt(tmp_path):
    assert load_last_tpu_capture(path=str(tmp_path / "absent.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert load_last_tpu_capture(path=str(bad)) is None
    # Valid JSON but wrong shape is rejected too.
    bad.write_text('["list"]')
    assert load_last_tpu_capture(path=str(bad)) is None


def test_committed_artifact_carries_tpu_evidence():
    """The repo always ships a last-known-good TPU capture with the
    fields the driver artifact needs (ttft / tok/s / MFU / xprof)."""
    artifact = load_last_tpu_capture()
    assert artifact is not None, LATEST_CAPTURE_PATH
    cap = artifact["capture"]
    assert cap["backend"] == "tpu"
    assert cap["device_kind"]
    assert cap["ttft_ms"] > 0
    assert cap["decode_tokens_per_sec"] > 0
    assert cap["mfu_prefill"] > 0
    assert cap["xprof_launch_spans"] > 0
    assert artifact["provenance"]["captured_at"]


def test_bench_fallback_embeds_last_capture():
    import bench

    result = {"backend": "cpu_fallback", "tpu_error": "relay dead"}
    bench._attach_last_tpu_capture(result)
    embedded = result.get("serving_tpu_last_capture")
    assert embedded is not None
    assert embedded["capture"]["backend"] == "tpu"
    assert embedded["provenance"]["captured_at"]


def test_committed_artifact_is_valid_json_file():
    with open(LATEST_CAPTURE_PATH) as fh:
        artifact = json.load(fh)
    assert set(artifact) == {"provenance", "capture"}


def test_dead_relay_short_circuits_probe_ladder(monkeypatch):
    """With every relay port closed, bench_serving must skip the
    ~15-minute probe/backoff ladder, fall back immediately, and still
    embed the last TPU capture."""
    import bench

    monkeypatch.setattr(bench, "_relay_known_dead", lambda: True)
    calls = {"probe": 0}

    def no_probe(timeout_s):
        calls["probe"] += 1
        return {"ok": False}

    monkeypatch.setattr(bench, "_probe_backend", no_probe)
    monkeypatch.setattr(
        bench, "_run_serving_subprocess",
        lambda args, timeout_s, env_extra=None: {"backend": "cpu"},
    )
    result = bench.bench_serving()
    assert calls["probe"] == 0  # ladder skipped entirely
    assert result["backend"] == "cpu_fallback"
    assert "relay" in result["tpu_error"]
    assert result["serving_tpu_last_capture"]["capture"]["backend"] == "tpu"


def test_failed_cpu_child_keeps_unavailable_backend(monkeypatch):
    """A timed-out CPU child must NOT be relabeled cpu_fallback — the
    artifact would claim CPU numbers that don't exist."""
    import bench

    monkeypatch.setattr(
        bench, "_run_serving_subprocess",
        lambda args, timeout_s, env_extra=None: {
            "backend": "unavailable", "error": "timed out",
        },
    )
    fallback = bench._cpu_fallback("relay dead")
    assert fallback["backend"] == "unavailable"
    assert fallback["tpu_error"] == "relay dead"


def test_relay_check_only_applies_to_tunneled_backend(monkeypatch):
    """Direct-attached TPU hosts (JAX_PLATFORMS unset/tpu) must never
    short-circuit on missing relay ports — their probe path works."""
    import bench

    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    assert bench._relay_known_dead() is False
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    assert bench._relay_known_dead() is False
    # Tunneled mode: the answer is a fast socket truth either way.
    import time

    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    t0 = time.perf_counter()
    value = bench._relay_known_dead()
    assert isinstance(value, bool)
    assert time.perf_counter() - t0 < 10.0


def test_additive_lane_retries_transient_errors_once():
    """A tunnel flap mid-lane (UNAVAILABLE) earns exactly one retry;
    the successful retry records what it recovered from (round 4 lost
    its only int8 TPU measurement to a one-shot lane)."""
    from tpuslo.benchmark import serving_bench as sb

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("UNAVAILABLE: TPU backend setup/compile error")
        return {"tokens_per_sec": 42.0}

    out = sb._additive_lane(flaky, retry_wait_s=0.0)
    assert len(calls) == 2
    assert out["tokens_per_sec"] == 42.0
    assert out["retried_after_transient"].startswith("UNAVAILABLE")


def test_additive_lane_structural_errors_do_not_retry():
    """Shape/lowering failures return immediately, and the error string
    keeps its actionable tail (ADVICE r4: a 160-char cap truncated the
    Mosaic tiling rule mid-sentence)."""
    from tpuslo.benchmark import serving_bench as sb

    calls = []
    rule = (
        "The Pallas TPU lowering currently requires that the last two "
        "dimensions of your block shape are divisible by 8 and 128 "
        "respectively, or be equal to the respective dimensions of the "
        "overall array. " + "details " * 40
    )

    def broken():
        calls.append(1)
        raise ValueError(rule)

    out = sb._additive_lane(broken, retry_wait_s=0.0)
    assert len(calls) == 1
    assert out["error"].endswith(("details ", "details"))  # tail intact


def test_additive_lane_double_transient_keeps_both_errors():
    from tpuslo.benchmark import serving_bench as sb

    def dead():
        raise RuntimeError("UNAVAILABLE: Socket closed")

    out = sb._additive_lane(dead, retry_wait_s=0.0)
    assert out["retried"] is True
    assert "UNAVAILABLE" in out["error"]
    assert "UNAVAILABLE" in out["first_error"]


def test_bandwidth_report_decode_lens():
    """The b8 decode number VERDICT r4 weak #5 complained about:
    268 tok/s on the 3.6B bf16 flagship is ~30% of the v5e HBM roof —
    the report must carry bytes/step and %-of-roof, not just MFU."""
    from tpuslo.benchmark import serving_bench as sb

    n_params = 3_606_752_256
    kv_b8 = 2 * 28 * 8 * 2048 * 8 * 128 * 2  # L*B*S*KV*HD, k+v, bf16
    step = sb.decode_step_hbm_bytes(n_params, kv_b8)
    assert step == n_params * 2.0 + kv_b8
    rep = sb.bandwidth_report(268.0, 8, step, sb.PEAK_HBM_BW["v5e"])
    expected = (268.0 / 8) * step / 819e9 * 100
    assert abs(rep["hbm_bw_pct"] - round(expected, 1)) < 0.11
    assert 20.0 < rep["hbm_bw_pct"] < 60.0  # the ~3x-headroom datum
    assert rep["peak_gb_per_sec"] == 819.0


def test_bandwidth_report_without_peak_is_bytes_only():
    from tpuslo.benchmark import serving_bench as sb

    rep = sb.bandwidth_report(100.0, 1, 1e9, None)
    assert rep["achieved_gb_per_sec"] == 100.0
    assert "hbm_bw_pct" not in rep


@pytest.mark.slow
def test_speculative_measured_lane_trains_and_measures():
    """The measured (not projected) speculative lane: trained weights,
    real acceptance accounting, greedy-parity streams.  Tiny step
    counts keep CI cheap; the bench uses deeper recipes."""
    from tpuslo.benchmark.serving_bench import _speculative_measured_lane

    # Cheap config pair: the target is the suite-wide llama_tiny (its
    # serve/train compiles are shared with dozens of other tests); the
    # draft is a 1-layer dim-32 config whose compiles are tiny.
    from tpuslo.models.llama import LlamaConfig, llama_tiny

    lane = _speculative_measured_lane(
        k=2, target_steps=6, draft_steps=6, n_tokens=6,
        target_cfg=llama_tiny(max_seq_len=256),
        draft_cfg=LlamaConfig(
            vocab_size=512, dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
            ffn_dim=64, max_seq_len=256, rope_theta=10000.0,
        ),
    )
    assert lane["parity_ok"] is True
    assert 0.0 <= lane["acceptance_rate"] <= 1.0
    assert lane["measured_speedup"] > 0
    assert lane["target"]["loss_last"] < lane["target"]["loss_first"]
    assert lane["draft"]["loss_last"] < lane["draft"]["loss_first"]
    assert lane["cost_ratio"] > 2


def test_speculative_measured_lane_default_configs_are_sound():
    """The bench's default config pair stays constructible and keeps
    the cost ratio speculation needs (the training itself is covered
    by the injected-config lane test + the real bench)."""
    import inspect

    from tpuslo.benchmark.serving_bench import _speculative_measured_lane
    from tpuslo.models.llama import param_count

    src = inspect.getsource(_speculative_measured_lane)
    # Reconstruct the defaults exactly as the lane builds them.
    from tpuslo.models.llama import LlamaConfig, llama_tiny

    target = LlamaConfig(
        vocab_size=512, dim=192, n_layers=4, n_heads=8, n_kv_heads=4,
        ffn_dim=384, max_seq_len=256, rope_theta=10000.0,
    )
    draft = llama_tiny(max_seq_len=256)
    assert "dim=192" in src  # drift guard: lane default matches this test
    assert target.dim % target.n_heads == 0
    assert target.n_heads % target.n_kv_heads == 0
    assert param_count(target) / param_count(draft) > 8


def test_checkpoint_sidecar_never_clobbers_main(tmp_path, monkeypatch):
    """Progressive persistence semantics: the mid-run checkpoint lives
    in a SIDECAR; a newer surviving sidecar wins at load time (fresh
    partial beats stale complete) but the main artifact's complete
    lanes are never physically overwritten by a partial."""
    import time as _time

    from tpuslo.benchmark import serving_bench as sb

    main_path = str(tmp_path / "latest.json")
    side_path = main_path + ".checkpoint"
    monkeypatch.setattr(sb, "LATEST_CAPTURE_PATH", main_path)
    monkeypatch.setattr(sb, "CHECKPOINT_CAPTURE_PATH", side_path)

    complete = _complete_capture()
    complete["moe"] = {"decode_tokens_per_sec": 100.0}
    assert persist_tpu_capture(complete, path=main_path)

    _time.sleep(1.1)  # captured_at has second resolution
    checkpoint = _complete_capture(ttft_ms=50.0)
    checkpoint["partial"] = "checkpoint before the moe/int8 lanes"
    assert persist_tpu_capture(checkpoint, path=side_path)

    # Newer sidecar wins, marker intact; main artifact untouched.
    loaded = sb.load_last_tpu_capture()
    assert loaded["capture"]["partial"]
    assert loaded["capture"]["ttft_ms"] == 50.0
    on_disk = sb.load_last_tpu_capture(path=main_path)
    assert on_disk["capture"]["moe"]["decode_tokens_per_sec"] == 100.0

    # A later COMPLETE run supersedes: final persisted + sidecar gone.
    _time.sleep(1.1)
    final = _complete_capture(ttft_ms=60.0)
    assert persist_tpu_capture(final, path=main_path)
    os.unlink(side_path)
    loaded = sb.load_last_tpu_capture()
    assert "partial" not in loaded["capture"]
    assert loaded["capture"]["ttft_ms"] == 60.0


def test_digest_carries_partial_marker():
    """bench.py's compact line must never present a checkpoint as a
    complete capture."""
    import bench

    artifact = {
        "provenance": {"captured_at": "2026-07-31", "git_sha": "abc"},
        "capture": _complete_capture(
            partial="checkpoint before the moe/int8 lanes"
        ),
    }
    digest = bench._digest_tpu_evidence(artifact)
    assert "partial" in digest
