"""Safety-governor tests. Reference model: pkg/safety/*_test.go."""

import pytest

from tpuslo import safety


class FakeSampler:
    def __init__(self, samples):
        self._samples = iter(samples)

    def sample(self):
        return next(self._samples)


class TestOverheadGuard:
    def test_first_evaluation_invalid(self):
        guard = safety.OverheadGuard(
            3.0,
            sampler=FakeSampler([safety.CPUSample(100, 10000)]),
            num_cpus=4,
        )
        result = guard.evaluate()
        assert not result.valid
        assert not result.over_budget

    def test_within_budget(self):
        guard = safety.OverheadGuard(
            3.0,
            sampler=FakeSampler(
                [safety.CPUSample(100, 10000), safety.CPUSample(102, 10400)]
            ),
            num_cpus=4,
        )
        guard.evaluate()
        result = guard.evaluate()
        assert result.valid
        # (2/400)*100*4 = 2.0%
        assert result.cpu_pct == pytest.approx(2.0)
        assert not result.over_budget

    def test_over_budget(self):
        guard = safety.OverheadGuard(
            3.0,
            sampler=FakeSampler(
                [safety.CPUSample(100, 10000), safety.CPUSample(120, 10400)]
            ),
            num_cpus=4,
        )
        guard.evaluate()
        result = guard.evaluate()
        assert result.cpu_pct == pytest.approx(20.0)
        assert result.over_budget

    def test_counter_reset_invalid(self):
        guard = safety.OverheadGuard(
            3.0,
            sampler=FakeSampler(
                [safety.CPUSample(100, 10000), safety.CPUSample(50, 9000)]
            ),
            num_cpus=4,
        )
        guard.evaluate()
        assert not guard.evaluate().valid

    def test_rejects_nonpositive_budget(self):
        with pytest.raises(ValueError):
            safety.OverheadGuard(0)

    def test_proc_sampler_reads_real_proc(self):
        # Sandboxed/virtualized environments either hide /proc entirely
        # or serve a stub whose machine counters are all zero (e.g.
        # `cpu  0 0 0 ...` in gVisor-style sandboxes).  Neither says
        # anything about the sampler — skip with the reason instead of
        # failing the suite on the environment.
        try:
            sample = safety.ProcCPUSampler().sample()
        except (OSError, ValueError) as exc:
            pytest.skip(f"proc interface unavailable in this sandbox: {exc}")
        if sample.total_ticks <= 0:
            pytest.skip(
                "proc interface is virtualized (machine tick counters "
                "in /proc/stat read zero); real-host behavior is "
                "covered by the FakeSampler tests"
            )
        assert sample.total_ticks > 0
        assert sample.proc_ticks >= 0


class TestShedRecoveryPolicy:
    @staticmethod
    def result(cpu_pct, budget_pct=3.0, valid=True):
        return safety.OverheadResult(
            cpu_pct=cpu_pct,
            budget_pct=budget_pct,
            over_budget=cpu_pct > budget_pct,
            valid=valid,
        )

    def test_restores_after_n_consecutive_under_budget_cycles(self):
        policy = safety.ShedRecoveryPolicy(cycles=3, headroom_factor=0.8)
        assert not policy.note(self.result(1.0))
        assert not policy.note(self.result(1.0))
        assert policy.note(self.result(1.0))
        # Streak restarts after each authorized restore (one-at-a-time ramp).
        assert policy.streak == 0
        assert not policy.note(self.result(1.0))

    def test_over_budget_resets_streak(self):
        policy = safety.ShedRecoveryPolicy(cycles=2)
        assert not policy.note(self.result(1.0))
        assert not policy.note(self.result(9.0))  # breach
        assert not policy.note(self.result(1.0))
        assert policy.note(self.result(1.0))

    def test_headroom_hysteresis_blocks_borderline_cycles(self):
        # 2.5% is under the 3% budget but above the 2.4% (0.8x) recovery
        # line: restoring there would flap straight back into shedding.
        policy = safety.ShedRecoveryPolicy(cycles=1, headroom_factor=0.8)
        assert not policy.note(self.result(2.5))
        assert policy.note(self.result(2.3))

    def test_invalid_samples_do_not_break_streak(self):
        policy = safety.ShedRecoveryPolicy(cycles=2)
        assert not policy.note(self.result(1.0))
        assert not policy.note(self.result(0.0, valid=False))
        assert policy.note(self.result(1.0))

    def test_rejects_bad_params(self):
        with pytest.raises(ValueError):
            safety.ShedRecoveryPolicy(cycles=0)
        with pytest.raises(ValueError):
            safety.ShedRecoveryPolicy(headroom_factor=1.5)


class TestRateLimiter:
    def test_burst_then_deny(self):
        now = [0.0]
        limiter = safety.RateLimiter(10, burst=5, clock=lambda: now[0])
        assert all(limiter.allow() for _ in range(5))
        assert not limiter.allow()

    def test_refill_over_time(self):
        now = [0.0]
        limiter = safety.RateLimiter(10, burst=5, clock=lambda: now[0])
        for _ in range(5):
            limiter.allow()
        now[0] = 0.25  # refills 2.5 tokens
        assert limiter.allow()
        assert limiter.allow()
        assert not limiter.allow()

    def test_capacity_capped(self):
        now = [0.0]
        limiter = safety.RateLimiter(10, burst=5, clock=lambda: now[0])
        now[0] = 100.0
        limiter.allow()
        assert limiter.tokens == pytest.approx(4.0)

    def test_rejects_zero_rate(self):
        with pytest.raises(ValueError):
            safety.RateLimiter(0)
