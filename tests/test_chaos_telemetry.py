"""Source-side telemetry chaos: ChaosStream determinism + the sweep.

The deterministic unit tests run in tier-1; the end-to-end chaos-sweep
smoke is marked ``chaos`` (run via ``make chaos-telemetry-smoke``) and
``slow`` like the delivery chaos suite.
"""

from __future__ import annotations

import pytest

from tpuslo.chaos.telemetry import ChaosScenario, ChaosStream
from tpuslo.schema import SCHEMA_PROBE_EVENT, is_valid


def probe_event(i=0, host=0):
    return dict(
        ts_unix_nano=1_700_000_000_000_000_000 + i * 1_000_000_000,
        signal="ici_collective_latency_ms",
        node=f"host-{host}",
        namespace="llm",
        pod=f"rag-agent-{host}",
        container="rag",
        pid=1,
        tid=1,
        value=3.5,
        unit="ms",
        status="ok",
        tpu={
            "slice_id": "slice-0",
            "host_index": host,
            "program_id": "prog",
            "launch_id": i,
        },
    )


def corpus(n=60, hosts=4):
    return [probe_event(i, h) for i in range(n) for h in range(hosts)]


class TestChaosStream:
    def test_same_seed_is_bit_identical(self):
        events = corpus()
        first = list(
            ChaosStream(ChaosScenario.at_intensity(1.0, seed=9)).stream(
                events
            )
        )
        second = list(
            ChaosStream(ChaosScenario.at_intensity(1.0, seed=9)).stream(
                events
            )
        )
        assert first == second

    def test_zero_intensity_is_identity(self):
        events = corpus(20)
        stream = ChaosStream(ChaosScenario.at_intensity(0.0))
        assert list(stream.stream(events)) == events
        assert stream.snapshot()["skewed"] == 0

    def test_never_mutates_source_events(self):
        events = corpus(20)
        backup = [dict(e, tpu=dict(e["tpu"])) for e in events]
        list(
            ChaosStream(ChaosScenario.at_intensity(2.0, seed=4)).stream(
                events
            )
        )
        assert events == backup

    def test_event_conservation(self):
        events = corpus()
        stream = ChaosStream(ChaosScenario.at_intensity(1.5, seed=21))
        out = list(stream.stream(events))
        snap = stream.snapshot()
        assert len(out) == len(events) - snap["dropped"] + snap[
            "duplicated"
        ]
        assert len(out) == snap["emitted"]

    def test_corruption_is_always_schema_breaking(self):
        events = corpus()
        stream = ChaosStream(
            ChaosScenario(seed=13, corrupt_rate=1.0)
        )
        out = list(stream.stream(events))
        assert stream.corrupted == len(events)
        assert all(not is_valid(e, SCHEMA_PROBE_EVENT) for e in out)

    def test_coordinator_clock_is_never_skewed(self):
        events = corpus()
        stream = ChaosStream(
            ChaosScenario(seed=2, skew_ms=300, drift_ms_per_s=5)
        )
        out = list(stream.stream(events))
        for event in out:
            if event["tpu"]["host_index"] == 0:
                launch = event["tpu"]["launch_id"]
                assert event["ts_unix_nano"] == probe_event(launch)[
                    "ts_unix_nano"
                ]

    def test_reordered_events_are_displaced_not_lost(self):
        events = corpus(30, hosts=1)
        stream = ChaosStream(
            ChaosScenario(seed=6, reorder_rate=0.5, reorder_depth=5)
        )
        out = list(stream.stream(events))
        assert stream.reordered > 0
        assert sorted(e["ts_unix_nano"] for e in out) == [
            e["ts_unix_nano"] for e in events
        ]
        assert [e["ts_unix_nano"] for e in out] != [
            e["ts_unix_nano"] for e in events
        ]


class TestSweepPlumbing:
    def test_reconstruction_recovers_clean_profiles(self):
        from datetime import datetime, timezone

        from tpuslo.attribution.pipeline import (
            reconstruct_samples,
            synthesize_probe_events,
        )
        from tpuslo.faultreplay import generate_fault_samples

        samples = generate_fault_samples(
            "ici_drop", 5, datetime(2026, 1, 1, tzinfo=timezone.utc)
        )
        events = synthesize_probe_events(samples)
        rebuilt = reconstruct_samples(samples, events)
        for sample, copy in zip(samples, rebuilt):
            assert copy.signals == sample.signals

    def test_sweep_report_gates_and_serializes(self):
        from tpuslo.attribution.pipeline import run_chaos_sweep

        report = run_chaos_sweep(
            scenario="tpu_mixed", count=24, intensities=(0.0, 1.0)
        )
        data = report.to_dict()
        assert data["baseline_macro_f1"] > 0.9
        assert len(data["points"]) == 2
        gated = data["points"][1]["gated_macro_f1"]
        ungated = data["points"][1]["ungated_macro_f1"]
        assert gated > ungated
        assert report.passed


@pytest.mark.chaos
@pytest.mark.slow
class TestChaosSweepSmoke:
    """`make chaos-telemetry-smoke`: the seeded sweep at low intensity."""

    def test_low_intensity_sweep_passes(self, tmp_path):
        from tpuslo.cli import m5gate

        summary = tmp_path / "sweep.json"
        rc = m5gate.main(
            [
                "--chaos-sweep",
                "--chaos-count", "40",
                "--chaos-intensities", "0,0.25,0.5,1",
                "--summary-json", str(summary),
                "--summary-md", str(tmp_path / "sweep.md"),
            ]
        )
        assert rc == 0
        import json

        data = json.loads(summary.read_text())
        assert data["passed"] is True
        by_intensity = {
            p["intensity"]: p for p in data["points"]
        }
        moderate = by_intensity[1.0]
        baseline = data["baseline_macro_f1"]
        # The acceptance bar, asserted from the artifact itself:
        # within 5% of baseline at moderate chaos, never worse than
        # ungated, strictly better wherever chaos degraded ungated.
        assert moderate["gated_macro_f1"] >= 0.95 * baseline
        degraded_somewhere = False
        for intensity, point in by_intensity.items():
            if intensity <= 0:
                continue
            assert point["gated_macro_f1"] >= point["ungated_macro_f1"]
            if point["ungated_macro_f1"] < 0.95 * baseline:
                degraded_somewhere = True
                assert (
                    point["gated_macro_f1"] > point["ungated_macro_f1"]
                )
        assert degraded_somewhere, "sweep never stressed the pipeline"
