"""MoE expert-parallelism: dense/sharded parity, drops, gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpuslo.ops.moe import (
    MoEConfig,
    init_moe_params,
    moe_mlp,
    moe_mlp_sharded,
    place_moe_params,
)


def _mesh(ep: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:ep]), ("ep",))


def _cfg(**kw) -> MoEConfig:
    defaults = dict(
        dim=32, ffn_dim=64, n_experts=8, top_k=2, capacity_factor=4.0
    )
    defaults.update(kw)
    return MoEConfig(**defaults)


def test_dense_moe_shape_and_finite():
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, cfg.dim), jnp.bfloat16)
    y = moe_mlp(params, x, cfg)
    assert y.shape == x.shape
    assert bool(jnp.all(jnp.isfinite(y.astype(jnp.float32))))


@pytest.mark.parametrize("ep", [2, 4, 8])
def test_sharded_matches_dense_when_nothing_drops(ep):
    # capacity_factor=n_experts/top_k guarantees zero drops in both the
    # dense (capacity over T) and sharded (capacity over T/ep) paths, so
    # the two must agree numerically.
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, cfg.dim), jnp.bfloat16)

    dense = moe_mlp(params, x, cfg)

    mesh = _mesh(ep)
    placed = place_moe_params(params, mesh)
    sharded = jax.jit(
        lambda p, t: moe_mlp_sharded(p, t, cfg, mesh)
    )(placed, x)

    err = float(
        jnp.max(jnp.abs(dense.astype(jnp.float32) - sharded.astype(jnp.float32)))
    )
    assert err < 2e-2, f"ep={ep} parity error {err}"


def test_capacity_drop_zeroes_token_output():
    # One-expert config with capacity 1: only the first token gets a
    # slot, every later token must come back exactly zero (residual
    # fallback semantics).
    cfg = _cfg(n_experts=1, top_k=1, capacity_factor=0.01)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, cfg.dim), jnp.bfloat16)
    assert cfg.capacity(8) == 1
    y = moe_mlp(params, x, cfg)
    tail = jnp.abs(y[1:].astype(jnp.float32))
    assert float(jnp.max(tail)) == 0.0
    assert float(jnp.max(jnp.abs(y[0].astype(jnp.float32)))) > 0.0


def test_sharded_grad_flows_to_local_experts():
    cfg = _cfg()
    mesh = _mesh(4)
    params = place_moe_params(
        init_moe_params(jax.random.PRNGKey(0), cfg), mesh
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (32, cfg.dim), jnp.bfloat16)

    def loss(p):
        y = moe_mlp_sharded(p, x, cfg, mesh)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    grads = jax.jit(jax.grad(loss))(params)
    g_norm = float(
        jnp.sqrt(
            sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grads)
            )
        )
    )
    assert np.isfinite(g_norm) and g_norm > 0.0


def test_indivisible_experts_rejected():
    cfg = _cfg(n_experts=6)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    mesh = _mesh(4)
    x = jnp.zeros((16, cfg.dim), jnp.bfloat16)
    with pytest.raises(ValueError, match="not divisible"):
        moe_mlp_sharded(params, x, cfg, mesh)


def test_load_balancing_loss_uniform_is_one():
    from tpuslo.ops.moe import load_balancing_loss

    T, E = 64, 8
    uniform = jnp.zeros((T, E), jnp.float32)
    # Uniform probs: P_e = 1/E; top-1 all land on expert 0 (argmax ties)
    # so f is concentrated — use slightly rotated logits so each token's
    # top-1 cycles through experts evenly.
    rotated = jax.nn.one_hot(jnp.arange(T) % E, E, dtype=jnp.float32) * 1e-4
    val = float(load_balancing_loss(uniform + rotated, E))
    assert abs(val - 1.0) < 1e-3

    # All mass on one expert: loss -> E (maximally imbalanced).
    hot = jax.nn.one_hot(jnp.zeros((T,), jnp.int32), E, dtype=jnp.float32) * 20
    val_hot = float(load_balancing_loss(hot, E))
    assert val_hot > 5.0


def test_moe_mlp_return_aux():
    from tpuslo.ops.moe import moe_mlp

    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (32, cfg.dim), jnp.bfloat16)
    y, aux = moe_mlp(params, x, cfg, return_aux=True)
    assert y.shape == x.shape
    assert float(aux) >= 1.0 - 1e-3  # lower bound at perfect balance

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow
