"""Active ICI collective prober: measured collectives + probe events."""

from __future__ import annotations

import json

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuslo.cli.common import validate_probe
from tpuslo.parallel.collectives import (
    CollectiveProbe,
    _collective_fn,
    bench_collectives,
    probes_to_events,
)


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("probe",))


def _sharded_ones(mesh, rows_per_dev=8, cols=8):
    n = mesh.shape["probe"]
    x = np.ones((n * rows_per_dev, cols), np.float32)
    return jax.device_put(x, NamedSharding(mesh, P("probe", None)))


@pytest.mark.slow
def test_collective_fns_compute_correctly():
    mesh = _mesh()
    n = mesh.shape["probe"]
    x = _sharded_ones(mesh)

    summed = _collective_fn("psum", mesh, "probe")(x)
    np.testing.assert_allclose(np.asarray(summed), n)

    gathered = _collective_fn("all_gather", mesh, "probe")(x)
    assert gathered.shape == (n * x.shape[0], x.shape[1])

    scattered = _collective_fn("reduce_scatter", mesh, "probe")(x)
    assert scattered.shape == (x.shape[0] // n, x.shape[1])
    np.testing.assert_allclose(np.asarray(scattered), n)

    permuted = _collective_fn("ppermute", mesh, "probe")(x)
    assert permuted.shape == x.shape

    with pytest.raises(ValueError, match="unknown collective"):
        _collective_fn("alltofoo", mesh, "probe")


@pytest.mark.slow
def test_bench_collectives_shapes_and_quantiles():
    probes = bench_collectives(
        mesh=_mesh(), payload_bytes=64 * 1024, reps=3
    )
    assert [p.op for p in probes] == [
        "psum", "all_gather", "reduce_scatter", "ppermute"
    ]
    for p in probes:
        assert p.n_devices == 8
        assert p.payload_bytes_per_device == 64 * 1024
        assert p.reps == 3
        assert 0 < p.min_ms <= p.p50_ms <= p.p95_ms
        assert p.to_dict()["op"] == p.op


@pytest.mark.slow
def test_probe_events_schema_and_identity():
    probes = [
        CollectiveProbe(
            op="psum", n_devices=8, payload_bytes_per_device=1024,
            reps=5, mean_ms=2.0, p50_ms=1.8, p95_ms=2.5, min_ms=1.5,
        ),
        CollectiveProbe(
            op="all_gather", n_devices=8, payload_bytes_per_device=1024,
            reps=5, mean_ms=40.0, p50_ms=38.0, p95_ms=45.0, min_ms=30.0,
        ),
    ]
    events = probes_to_events(probes, slice_id="slice-0", host_index=1)
    assert len(events) == 2
    for event in events:
        assert validate_probe(event)
        assert event.signal == "ici_collective_latency_ms"
        assert event.tpu.slice_id == "slice-0"
    assert events[0].tpu.module_name == "collective:psum"
    assert events[0].status == "ok"  # p95 2.5ms under the 10ms warning
    assert events[1].status == "error"  # p95 45ms over the 30ms error


@pytest.mark.slow
def test_icibench_cli_writes_jsonl(tmp_path):
    from tpuslo.cli.icibench import main

    out = tmp_path / "ici.jsonl"
    rc = main(
        [
            "--payload-kb", "64", "--reps", "2", "--ops", "psum,ppermute",
            "--output", str(out), "--slice-id", "slice-7",
        ]
    )
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 2
    assert {l["tpu"]["module_name"] for l in lines} == {
        "collective:psum", "collective:ppermute"
    }
    assert all(l["signal"] == "ici_collective_latency_ms" for l in lines)
    assert all(l["tpu"]["slice_id"] == "slice-7" for l in lines)


@pytest.mark.slow
def test_active_prober_interval_and_disable():
    from tpuslo.parallel.collectives import ActiveICIProber

    logs = []
    prober = ActiveICIProber(
        interval_s=100.0, payload_kb=16, reps=1, log=logs.append,
        slice_id="s0", host_index=0,
    )
    events = prober.maybe_probe(now_monotonic=10.0)
    assert len(events) == 4
    assert all(validate_probe(e) for e in events)
    # Not due again until interval elapses.
    assert prober.maybe_probe(now_monotonic=50.0) == []
    assert prober.maybe_probe(now_monotonic=111.0) != []

    # A failing probe disables the prober after one log line.
    broken = ActiveICIProber(interval_s=1.0, log=logs.append)

    def boom(**kw):
        raise RuntimeError("backend gone")

    import tpuslo.parallel.collectives as mod

    orig = mod.CollectiveSuite
    mod.CollectiveSuite = boom
    try:
        assert broken.maybe_probe(0.0) == []
        assert broken._disabled
        assert any("disabled" in line for line in logs)
        mod.CollectiveSuite = orig
        assert broken.maybe_probe(1000.0) == []  # stays off
    finally:
        mod.CollectiveSuite = orig


@pytest.mark.slow
def test_agent_emits_ici_probe_events(tmp_path):
    from tpuslo.cli.agent import main

    out = tmp_path / "agent.jsonl"
    rc = main(
        [
            "--scenario", "baseline", "--count", "2", "--interval-s", "0.01",
            "--output", "jsonl", "--jsonl-path", str(out),
            "--event-kind", "probe", "--metrics-port", "0",
            "--max-overhead-pct", "1000",
            "--ici-probe-interval-s", "3600",
            "--ici-probe-payload-kb", "16",
        ]
    )
    assert rc == 0
    events = [json.loads(l) for l in out.read_text().splitlines()]
    ici = [
        e for e in events
        if e.get("tpu", {}).get("program_id") == "icibench"
    ]
    # One probe round (4 collectives) on the first cycle only.
    assert len(ici) == 4
    assert {e["tpu"]["module_name"] for e in ici} == {
        "collective:psum", "collective:all_gather",
        "collective:reduce_scatter", "collective:ppermute",
    }


@pytest.mark.slow
def test_suite_reuses_compiled_programs():
    from tpuslo.parallel.collectives import ActiveICIProber, CollectiveSuite

    prober = ActiveICIProber(interval_s=0.0, payload_kb=16, reps=1)
    assert prober._suite is None
    prober.maybe_probe(0.0)
    suite = prober._suite
    assert isinstance(suite, CollectiveSuite)
    prober.maybe_probe(1.0)
    assert prober._suite is suite  # same compiled suite, no rebuild


def test_icibench_rejects_unknown_ops(capsys):
    from tpuslo.cli.icibench import main

    assert main(["--ops", "psumm"]) == 2
    assert "unknown ops" in capsys.readouterr().err
    assert main(["--ops", ""]) == 2


@pytest.mark.slow
def test_agent_warns_ici_probe_with_slo_kind(tmp_path, capsys):
    from tpuslo.cli.agent import main

    out = tmp_path / "slo.jsonl"
    rc = main(
        [
            "--scenario", "baseline", "--count", "1", "--interval-s", "0.01",
            "--output", "jsonl", "--jsonl-path", str(out),
            "--event-kind", "slo", "--metrics-port", "0",
            "--ici-probe-interval-s", "60",
        ]
    )
    assert rc == 0
    assert "--event-kind probe|both" in capsys.readouterr().err
    assert all(
        json.loads(l).get("kind") != "probe"
        for l in out.read_text().splitlines()
    )


def test_prober_timeout_disables_instead_of_stalling():
    """A wedged backend HANGS (no exception) in suite build/measure;
    the prober's worker-thread join(timeout) must disable it and
    return, not stall the agent emit loop (ADVICE r02 #1)."""
    import threading
    import time as _time

    from tpuslo.parallel.collectives import ActiveICIProber

    logs = []
    prober = ActiveICIProber(interval_s=1.0, log=logs.append, timeout_s=0.3)
    release = threading.Event()

    def hang():
        release.wait(30.0)  # simulates jax.devices() blocking forever

    prober._probe_once = hang
    t0 = _time.perf_counter()
    assert prober.maybe_probe(0.0) == []
    elapsed = _time.perf_counter() - t0
    release.set()
    assert elapsed < 5.0  # returned at the join timeout, not the hang
    assert prober._disabled
    assert any("hang" in line for line in logs)
    assert prober.maybe_probe(1000.0) == []  # stays off


def test_multi_slice_mesh_and_batch_layout():
    """Pure layout (no compile): dcn factors out first, axes order
    puts dcn outermost, and the batch splits over every data axis."""
    import pytest
    from jax.sharding import PartitionSpec as P

    from tpuslo.parallel.mesh import (
        batch_sharding,
        make_mesh,
        plan_for_devices,
    )

    plan = plan_for_devices(8, slices=2)
    assert (plan.dcn, plan.n_devices) == (2, 8)
    mesh = make_mesh(plan)
    assert mesh.axis_names == ("dcn", "dp", "fsdp", "tp")
    spec = batch_sharding(mesh).spec
    assert spec == P(("dcn", "dp", "fsdp"), None)

    with pytest.raises(ValueError, match="not divisible"):
        plan_for_devices(8, slices=3)
