"""Active ICI collective prober: measured collectives + probe events."""

from __future__ import annotations

import json

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuslo.cli.common import validate_probe
from tpuslo.parallel.collectives import (
    CollectiveProbe,
    _collective_fn,
    bench_collectives,
    probes_to_events,
)


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("probe",))


def _sharded_ones(mesh, rows_per_dev=8, cols=8):
    n = mesh.shape["probe"]
    x = np.ones((n * rows_per_dev, cols), np.float32)
    return jax.device_put(x, NamedSharding(mesh, P("probe", None)))


def test_collective_fns_compute_correctly():
    mesh = _mesh()
    n = mesh.shape["probe"]
    x = _sharded_ones(mesh)

    summed = _collective_fn("psum", mesh, "probe")(x)
    np.testing.assert_allclose(np.asarray(summed), n)

    gathered = _collective_fn("all_gather", mesh, "probe")(x)
    assert gathered.shape == (n * x.shape[0], x.shape[1])

    scattered = _collective_fn("reduce_scatter", mesh, "probe")(x)
    assert scattered.shape == (x.shape[0] // n, x.shape[1])
    np.testing.assert_allclose(np.asarray(scattered), n)

    permuted = _collective_fn("ppermute", mesh, "probe")(x)
    assert permuted.shape == x.shape

    with pytest.raises(ValueError, match="unknown collective"):
        _collective_fn("alltofoo", mesh, "probe")


def test_bench_collectives_shapes_and_quantiles():
    probes = bench_collectives(
        mesh=_mesh(), payload_bytes=64 * 1024, reps=3
    )
    assert [p.op for p in probes] == [
        "psum", "all_gather", "reduce_scatter", "ppermute"
    ]
    for p in probes:
        assert p.n_devices == 8
        assert p.payload_bytes_per_device == 64 * 1024
        assert p.reps == 3
        assert 0 < p.min_ms <= p.p50_ms <= p.p95_ms
        assert p.to_dict()["op"] == p.op


def test_probe_events_schema_and_identity():
    probes = [
        CollectiveProbe(
            op="psum", n_devices=8, payload_bytes_per_device=1024,
            reps=5, mean_ms=2.0, p50_ms=1.8, p95_ms=2.5, min_ms=1.5,
        ),
        CollectiveProbe(
            op="all_gather", n_devices=8, payload_bytes_per_device=1024,
            reps=5, mean_ms=40.0, p50_ms=38.0, p95_ms=45.0, min_ms=30.0,
        ),
    ]
    events = probes_to_events(probes, slice_id="slice-0", host_index=1)
    assert len(events) == 2
    for event in events:
        assert validate_probe(event)
        assert event.signal == "ici_collective_latency_ms"
        assert event.tpu.slice_id == "slice-0"
    assert events[0].tpu.module_name == "collective:psum"
    assert events[0].status == "ok"  # p95 2.5ms under the 10ms warning
    assert events[1].status == "error"  # p95 45ms over the 30ms error


def test_icibench_cli_writes_jsonl(tmp_path):
    from tpuslo.cli.icibench import main

    out = tmp_path / "ici.jsonl"
    rc = main(
        [
            "--payload-kb", "64", "--reps", "2", "--ops", "psum,ppermute",
            "--output", str(out), "--slice-id", "slice-7",
        ]
    )
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 2
    assert {l["tpu"]["module_name"] for l in lines} == {
        "collective:psum", "collective:ppermute"
    }
    assert all(l["signal"] == "ici_collective_latency_ms" for l in lines)
    assert all(l["tpu"]["slice_id"] == "slice-7" for l in lines)
