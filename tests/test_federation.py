"""Federation plane: region wire contract, backpressure/sampling
invariants, online ring rebalancing under churn, cross-cluster rollup
identity, region failover, the seeded simulator/sweep, and the
fleetagg/sloctl federation CLIs.

The two load-bearing invariants get adversarial coverage: the
adaptive sampler can structurally never touch a pod carrying fault
evidence (so saturation cannot drop or split an incident), and a
shard join/leave re-homes ONLY the moved arcs with in-flight window
handoff (so churn mid-window neither loses nor duplicates evidence).
"""

from __future__ import annotations

import json

import pytest

from tpuslo.columnar.schema import from_rows
from tpuslo.federation.backpressure import (
    LEVEL_AGGRESSIVE,
    LEVEL_COARSE,
    LEVEL_NONE,
    LEVEL_SAMPLE,
    AdaptiveSampler,
    PressureController,
)
from tpuslo.federation.cluster import ClusterAggregator
from tpuslo.federation.region import RegionAggregator
from tpuslo.federation.simulator import (
    FederationSimulator,
    FederationTopology,
    build_churn_plan,
    federation_injection_plan,
)
from tpuslo.federation.sweep import run_federation_sweep
from tpuslo.federation.wire import (
    REGION_WIRE_VERSION,
    RegionWireError,
    decode_region_envelope,
    encode_region_envelope,
    parse_region_envelope_line,
    region_envelope_json_line,
)
from tpuslo.fleet.aggregator import AggregatorShard
from tpuslo.fleet.ring import HashRing
from tpuslo.fleet.rollup import NodeIncident
from tpuslo.fleet.simulator import EPOCH_NS
from tpuslo.fleet.sweep import score_incidents
from tpuslo.fleet.wire import encode_shipment
from tpuslo.schema.types import ProbeEventV1


def _incident(
    node: str = "node-0001",
    cluster: str = "cluster-0",
    domain: str = "tpu_hbm",
    namespace: str = "tenant-b",
    ts: int = EPOCH_NS,
    confidence: float = 0.9,
    slice_id: str = "slice-000",
) -> NodeIncident:
    return NodeIncident(
        node=node,
        pod=f"{node}-pod-1",
        namespace=namespace,
        slice_id=slice_id,
        domain=domain,
        confidence=confidence,
        ts_unix_nano=ts,
        signals={"hbm_used_bytes": 1.5e10},
        cluster=cluster,
    )


def _status_batch(statuses: list[str], pods: list[str] | None = None):
    """One batch with given per-row statuses (pod defaults per row)."""
    pods = pods or [f"pod-{i}" for i in range(len(statuses))]
    rows = [
        ProbeEventV1(
            ts_unix_nano=EPOCH_NS + i * 1000,
            signal="runqueue_delay_ms",
            node="node-x",
            namespace="tenant-a",
            pod=pods[i],
            container="w",
            pid=1,
            tid=1,
            value=4.0,
            unit="ms",
            status=statuses[i],
        )
        for i in range(len(statuses))
    ]
    return from_rows(rows)


class TestRegionWire:
    def test_round_trip(self):
        incidents = [
            _incident(),
            _incident(node="node-0002", cluster="cluster-1"),
        ]
        payload = encode_region_envelope(
            "cluster-0",
            3,
            incidents,
            watermark_ns=EPOCH_NS + 5,
            head_ns=EPOCH_NS + 9,
            pressure_level=2,
            sampled_rows={2: 17},
        )
        env = decode_region_envelope(payload)
        assert env.cluster == "cluster-0"
        assert env.seq == 3
        assert env.watermark_ns == EPOCH_NS + 5
        assert env.head_ns == EPOCH_NS + 9
        assert env.pressure_level == 2
        assert env.sampled_rows == {"2": 17}
        assert env.incidents == incidents

    def test_jsonl_round_trip(self):
        payload = encode_region_envelope("cluster-0", 0, [_incident()])
        line = region_envelope_json_line(payload)
        env = parse_region_envelope_line(line)
        assert env.incidents[0].cluster == "cluster-0"
        assert env.incidents[0].signals == {"hbm_used_bytes": 1.5e10}

    def test_version_mismatch_refused(self):
        payload = encode_region_envelope("cluster-0", 0, [])
        payload["region_wire_version"] = REGION_WIRE_VERSION + 1
        with pytest.raises(RegionWireError, match="wire version"):
            decode_region_envelope(payload)

    def test_missing_cluster_refused(self):
        payload = encode_region_envelope("cluster-0", 0, [])
        payload["cluster"] = ""
        with pytest.raises(RegionWireError, match="cluster identity"):
            decode_region_envelope(payload)

    def test_bad_incident_entry_refused(self):
        payload = encode_region_envelope("cluster-0", 0, [_incident()])
        del payload["incidents"][0]["domain"]
        with pytest.raises(RegionWireError, match="bad incident"):
            decode_region_envelope(payload)

    def test_bad_header_and_incident_list_refused(self):
        payload = encode_region_envelope("cluster-0", 0, [])
        payload["seq"] = "not-a-seq"
        with pytest.raises(RegionWireError, match="bad envelope header"):
            decode_region_envelope(payload)
        payload = encode_region_envelope("cluster-0", 0, [])
        payload["incidents"] = "nope"
        with pytest.raises(RegionWireError, match="incidents list"):
            decode_region_envelope(payload)


class TestPressureController:
    def test_levels_rise_immediately(self):
        ctl = PressureController(100)
        assert ctl.observe(10) == LEVEL_NONE
        assert ctl.observe(55) == LEVEL_COARSE
        assert ctl.observe(80) == LEVEL_SAMPLE
        assert ctl.observe(95) == LEVEL_AGGRESSIVE
        assert ctl.observe(500) == LEVEL_AGGRESSIVE

    def test_release_needs_consecutive_cool_readings(self):
        ctl = PressureController(100, cool_observations=2)
        ctl.observe(95)
        assert ctl.level == LEVEL_AGGRESSIVE
        # One cool reading is not enough; an interleaved warm reading
        # resets the streak (hysteresis: the level cannot flap).
        assert ctl.observe(10) == LEVEL_AGGRESSIVE
        assert ctl.observe(80) == LEVEL_AGGRESSIVE
        assert ctl.observe(10) == LEVEL_AGGRESSIVE
        assert ctl.observe(10) == LEVEL_NONE

    def test_oscillation_around_threshold_does_not_release(self):
        ctl = PressureController(100, cool_observations=2)
        ctl.observe(95)
        # Just below the entry threshold but above the release margin:
        # stays degraded forever.
        for _ in range(10):
            assert ctl.observe(85) == LEVEL_AGGRESSIVE

    def test_degraded_observations_counted_by_level(self):
        ctl = PressureController(100)
        ctl.observe(55)
        ctl.observe(60)
        ctl.observe(95)
        assert ctl.observations_by_level == {
            LEVEL_COARSE: 2,
            LEVEL_AGGRESSIVE: 1,
        }

    def test_state_round_trip(self):
        ctl = PressureController(100)
        ctl.observe(95)
        ctl.observe(10)
        clone = PressureController(100)
        clone.restore_state(ctl.export_state())
        assert clone.level == ctl.level
        assert clone.observations_by_level == ctl.observations_by_level
        # The cool streak survives: one more cool reading releases.
        assert clone.observe(10) == LEVEL_NONE

    def test_bad_thresholds_refused(self):
        with pytest.raises(ValueError, match="ascending"):
            PressureController(100, raise_at=(0.9, 0.5, 0.7))
        with pytest.raises(ValueError, match="thresholds"):
            PressureController(100, raise_at=(0.5, 0.9))


class TestAdaptiveSampler:
    def test_no_sampling_below_sample_level(self):
        sampler = AdaptiveSampler()
        batch = _status_batch(["ok"] * 8)
        for level in (LEVEL_NONE, LEVEL_COARSE):
            result = sampler.sample_batch(batch, level)
            assert result.dropped_rows == 0
            assert result.batch.n == 8

    def test_non_ok_rows_never_sampled(self):
        sampler = AdaptiveSampler()
        batch = _status_batch(["warning"] * 4 + ["error"] * 4)
        result = sampler.sample_batch(batch, LEVEL_AGGRESSIVE)
        assert result.dropped_rows == 0
        assert result.batch.n == 8

    def test_fault_pod_rows_fully_protected(self):
        # One pod carries a single warning row among ok rows: EVERY
        # row of that pod survives aggressive sampling; only the
        # wholly-healthy pods' rows are candidates.
        statuses = ["ok", "warning", "ok", "ok"] + ["ok"] * 12
        pods = ["pod-hot"] * 4 + [f"pod-{i}" for i in range(12)]
        sampler = AdaptiveSampler()
        result = sampler.sample_batch(
            _status_batch(statuses, pods), LEVEL_AGGRESSIVE
        )
        kept = result.batch
        strings = kept.pool.strings
        kept_pods = [strings[c] for c in kept.columns["pod"]]
        assert kept_pods.count("pod-hot") == 4
        assert result.dropped_rows == 9  # 12 candidates, keep 1 in 4
        assert sampler.sampled_rows_by_level == {LEVEL_AGGRESSIVE: 9}
        assert sampler.sampled_batches_by_level == {LEVEL_AGGRESSIVE: 1}

    def test_stride_by_level(self):
        sampler = AdaptiveSampler()
        result = sampler.sample_batch(
            _status_batch(["ok"] * 16), LEVEL_SAMPLE
        )
        assert result.batch.n == 8  # 1 in 2 kept

    def test_phase_persists_across_batches(self):
        # A sparse stream of 1-row batches must still pass 1 in 4 rows
        # at the aggressive stride, not lose every row to the batch
        # boundary.
        sampler = AdaptiveSampler()
        kept = sum(
            sampler.sample_batch(
                _status_batch(["ok"]), LEVEL_AGGRESSIVE
            ).batch.n
            for _ in range(16)
        )
        assert kept == 4

    def test_state_round_trip(self):
        sampler = AdaptiveSampler()
        sampler.sample_batch(_status_batch(["ok"] * 5), LEVEL_AGGRESSIVE)
        clone = AdaptiveSampler()
        clone.restore_state(sampler.export_state())
        assert (
            clone.sampled_rows_by_level == sampler.sampled_rows_by_level
        )
        a = sampler.sample_batch(
            _status_batch(["ok"] * 7), LEVEL_AGGRESSIVE
        )
        b = clone.sample_batch(
            _status_batch(["ok"] * 7), LEVEL_AGGRESSIVE
        )
        assert a.batch.n == b.batch.n  # same phase → same keeps


class TestRingRebalance:
    def test_seeded_churn_only_moved_keys_rehome(self):
        """Satellite contract: under a seeded continuous join/leave
        churn schedule, rehome_plan reports exactly the keys whose
        owner changed — every other key keeps its owner — and
        cordoned arcs never appear as rebalancing targets."""
        import random

        rng = random.Random(4242)
        arcs = [
            (f"node-{i:04d}", f"slice-{i // 16:03d}") for i in range(256)
        ]
        ring = HashRing([f"agg-{i}" for i in range(4)])
        ring.cordon("node-0007", "slice-000")
        ring.cordon("node-0133", "slice-008")
        next_shard = 4
        pool = [f"agg-{i}" for i in range(4)]
        for _ in range(12):
            prior = ring.assignments(arcs)
            if rng.random() < 0.5 or len(pool) <= 2:
                shard = f"agg-{next_shard}"
                next_shard += 1
                ring.add_shard(shard)
                pool.append(shard)
            else:
                shard = pool.pop(rng.randrange(len(pool)))
                ring.remove_shard(shard)
            plan = ring.rehome_plan(arcs, prior)
            after = ring.assignments(arcs)
            # Exactly the moved keys: plan ∪ unchanged == all placed.
            for node, owner in after.items():
                if prior[node] != owner:
                    assert plan[node] == (prior[node], owner)
                else:
                    assert node not in plan
            # Cordoned arcs are never targets (never even placed).
            assert "node-0007" not in plan
            assert "node-0007" not in after
            assert "node-0133" not in plan
        # Sanity: churn actually moved keys at some point.
        assert ring.rebalances == 12

    def test_rehome_plan_fresh_joins_are_not_moves(self):
        ring = HashRing(["agg-0", "agg-1"])
        arcs = [("node-a", "s0"), ("node-b", "s0")]
        prior = ring.assignments([("node-a", "s0")])
        plan = ring.rehome_plan(arcs, prior)
        assert "node-b" not in plan  # placement, not a re-home


def _ship_events(shard_or_cluster, node: str, seq: int, values=None):
    """One shipment of warning-level evidence for ``node``."""
    values = values or [30.0, 31.0]
    rows = [
        ProbeEventV1(
            ts_unix_nano=EPOCH_NS + seq * 1_000_000_000 + i,
            signal="runqueue_delay_ms",
            node=node,
            namespace="tenant-a",
            pod=f"{node}-pod-0",
            container="w",
            pid=1,
            tid=1,
            value=v,
            unit="ms",
            status="warning",
        )
        for i, v in enumerate(values)
    ]
    payload = encode_shipment(from_rows(rows), node, seq, slice_id="s0")
    return shard_or_cluster.ingest(payload)


class TestClusterAggregator:
    def test_shard_handoff_moves_in_flight_windows(self):
        """A node moving mid-window carries its open accumulator
        groups: the window closes exactly once on exactly one shard
        (no lost evidence, no duplicate incidents)."""
        cluster = ClusterAggregator(
            "cluster-0", ["agg-0", "agg-1"], min_confidence=0.0
        )
        for i in range(8):
            _ship_events(cluster, f"node-{i:04d}", 0)
        open_before = sum(
            len(s._acc) for s in cluster.shards.values()
        )
        assert open_before == 0  # not drained yet (coalesce buffer)
        plan = cluster.add_shard("agg-2")
        moved_nodes = set(plan)
        # Every moved node's state (incl. in-flight windows, drained
        # by export_node) lives exactly once, on its new owner.
        for node, (old, new) in plan.items():
            assert node not in cluster.shards[old].nodes
            assert node in cluster.shards[new].nodes
        all_nodes = [
            n
            for s in cluster.shards.values()
            for n in s.nodes
        ]
        assert len(all_nodes) == len(set(all_nodes)) == 8
        incidents = [
            ni
            for s in cluster.shards.values()
            for ni in s.close_windows(flush=True)
        ]
        assert len(incidents) == 8  # one per node, none lost/duped
        assert cluster.churn_rebalances == {"shard_join": 1}
        if moved_nodes:
            assert {ni.node for ni in incidents} >= moved_nodes

    def test_graceful_remove_hands_every_arc_over(self):
        cluster = ClusterAggregator(
            "cluster-0", ["agg-0", "agg-1", "agg-2"], min_confidence=0.0
        )
        for i in range(12):
            _ship_events(cluster, f"node-{i:04d}", 0)
        victim_nodes = set(cluster.shards["agg-1"].nodes)
        moved = cluster.remove_shard("agg-1")
        assert set(moved) == victim_nodes
        assert "agg-1" not in cluster.shards
        incidents = [
            ni
            for s in cluster.shards.values()
            for ni in s.close_windows(flush=True)
        ]
        assert len(incidents) == 12

    def test_remove_unknown_shard_refused(self):
        cluster = ClusterAggregator("cluster-0", ["agg-0"])
        with pytest.raises(ValueError, match="unknown shard"):
            cluster.remove_shard("agg-9")

    def test_close_and_ship_stamps_cluster_and_seq(self):
        cluster = ClusterAggregator(
            "cluster-0", ["agg-0"], min_confidence=0.0
        )
        _ship_events(cluster, "node-0000", 0)
        first = cluster.close_and_ship(flush=True)
        second = cluster.close_and_ship(flush=True)
        assert first["seq"] == 0 and second["seq"] == 1
        env = decode_region_envelope(first)
        assert env.incidents, "flush should attribute the window"
        assert all(
            ni.cluster == "cluster-0" for ni in env.incidents
        )
        assert cluster.resend_since(-1) == [first, second]
        assert cluster.resend_since(0) == [second]

    def test_envelope_sampled_rows_is_per_envelope_delta(self):
        # The wire contract says "since the last envelope": a region
        # summing across envelopes must not overcount the cluster's
        # cumulative sampling history.
        cluster = ClusterAggregator(
            "cluster-0", ["agg-0"], min_confidence=0.0
        )
        cluster.set_upstream_pressure(LEVEL_AGGRESSIVE)
        cluster.sampler.sample_batch(
            _status_batch(["ok"] * 9), LEVEL_AGGRESSIVE
        )
        first = cluster.close_and_ship(flush=True)
        second = cluster.close_and_ship(flush=True)
        dropped = cluster.sampler.sampled_rows_by_level[
            LEVEL_AGGRESSIVE
        ]
        assert first["sampled_rows"] == {
            str(LEVEL_AGGRESSIVE): dropped
        }
        assert second["sampled_rows"] == {}  # nothing new since
        # The shipped cursor survives a snapshot round trip.
        clone = ClusterAggregator(
            "cluster-0", ["agg-0"], min_confidence=0.0
        )
        clone.restore_state(cluster.export_state())
        assert clone.close_and_ship(flush=True)["sampled_rows"] == {}

    def test_coarsen_responds_to_pressure(self):
        cluster = ClusterAggregator(
            "cluster-0", ["agg-0"], capacity_events=2
        )
        _ship_events(cluster, "node-0000", 0, values=[5.0, 6.0, 7.0])
        signal = cluster.observe_pressure()
        assert signal.level == LEVEL_AGGRESSIVE
        base = cluster._base_coalesce["agg-0"]
        assert (
            cluster.shards["agg-0"].coalesce_events
            == base << LEVEL_AGGRESSIVE
        )
        # Upstream pressure propagates into the effective level too.
        calm = ClusterAggregator("cluster-1", ["agg-0"])
        calm.set_upstream_pressure(LEVEL_SAMPLE)
        assert calm.effective_level() == LEVEL_SAMPLE

    def test_sampling_level_protects_fault_evidence_end_to_end(self):
        cluster = ClusterAggregator(
            "cluster-0",
            ["agg-0"],
            min_confidence=0.0,
            capacity_events=1,
        )
        cluster.observe_pressure()  # backlog 0; force via upstream
        cluster.set_upstream_pressure(LEVEL_AGGRESSIVE)
        # Mixed shipment: one pod with warning evidence + 8 healthy
        # pods.  Sampling drops only healthy-pod rows.
        rows = []
        for i in range(9):
            status = "warning" if i == 0 else "ok"
            rows.append(
                ProbeEventV1(
                    ts_unix_nano=EPOCH_NS + i,
                    signal="runqueue_delay_ms",
                    node="node-0000",
                    namespace="tenant-a",
                    pod=f"node-0000-pod-{i}",
                    container="w",
                    pid=1,
                    tid=1,
                    value=30.0 if i == 0 else 4.0,
                    unit="ms",
                    status=status,
                )
            )
        payload = encode_shipment(
            from_rows(rows), "node-0000", 0, slice_id="s0"
        )
        assert cluster.ingest(payload)
        assert cluster.sampler.sampled_rows_by_level[
            LEVEL_AGGRESSIVE
        ] == 6  # 8 healthy rows → keep 2
        shard = cluster.shards["agg-0"]
        shard._drain()
        acc_pods = {
            key[2]
            for groups in shard._acc.values()
            for key in groups
        }
        assert "node-0000-pod-0" in acc_pods  # evidence survived


class TestHealthyGroupSkip:
    def _fold_groups(self, shard: AggregatorShard):
        rows = [
            ProbeEventV1(
                ts_unix_nano=EPOCH_NS,
                signal="runqueue_delay_ms",
                node="node-h",
                namespace="tenant-a",
                pod="node-h-pod-0",
                container="w",
                pid=1,
                tid=1,
                value=4.0,
                unit="ms",
                status="ok",
            ),
            ProbeEventV1(
                ts_unix_nano=EPOCH_NS + 1,
                signal="runqueue_delay_ms",
                node="node-f",
                namespace="tenant-a",
                pod="node-f-pod-0",
                container="w",
                pid=1,
                tid=1,
                value=30.0,
                unit="ms",
                status="warning",
            ),
        ]
        batch = from_rows(rows)
        shard.ingest(encode_shipment(batch, "node-h", 0))
        return shard.close_windows(flush=True)

    def test_skip_healthy_groups_counts_and_keeps_evidence(self):
        shard = AggregatorShard(
            "agg-0", min_confidence=0.0, skip_healthy_groups=True
        )
        incidents = self._fold_groups(shard)
        assert shard.groups_skipped_healthy == 1
        assert [ni.node for ni in incidents] == ["node-f"]
        assert shard.snapshot()["groups_skipped_healthy"] == 1

    def test_default_off_attributes_everything(self):
        shard = AggregatorShard("agg-0", min_confidence=0.0)
        incidents = self._fold_groups(shard)
        assert shard.groups_skipped_healthy == 0
        assert {ni.node for ni in incidents} == {"node-h", "node-f"}


class TestRegionAggregator:
    def test_cross_cluster_identity_is_one_incident(self):
        region = RegionAggregator()
        region.ingest(
            encode_region_envelope(
                "cluster-0",
                0,
                [_incident(node="node-0001", cluster="cluster-0")],
                watermark_ns=EPOCH_NS + 60_000_000_000,
            )
        )
        region.ingest(
            encode_region_envelope(
                "cluster-1",
                0,
                [
                    _incident(
                        node="node-0070",
                        cluster="cluster-1",
                        ts=EPOCH_NS + 1_000_000_000,
                        slice_id="slice-001",
                    )
                ],
                watermark_ns=EPOCH_NS + 60_000_000_000,
            )
        )
        emitted = region.pump()
        assert len(emitted) == 1
        incident = emitted[0]
        assert incident.region == "region-0"
        assert incident.clusters == ["cluster-0", "cluster-1"]
        assert incident.blast_radius == "fleet"  # two slices
        member_clusters = {m["cluster"] for m in incident.members}
        assert member_clusters == {"cluster-0", "cluster-1"}

    def test_seq_dedup_per_cluster(self):
        region = RegionAggregator()
        payload = encode_region_envelope(
            "cluster-0", 0, [_incident()]
        )
        assert region.ingest(payload)
        assert not region.ingest(payload)  # replay
        assert region.duplicate_envelopes == 1
        assert region.ingested_incidents == 1

    def test_out_of_order_cluster_flushes_still_coalesce(self):
        # Cluster 1's envelope arrives first with a LATER timestamp;
        # cluster 0's straggler is EARLIER.  pump() time-sorts before
        # the rollup sees them, so they coalesce into one session.
        region = RegionAggregator()
        region.ingest(
            encode_region_envelope(
                "cluster-1",
                0,
                [
                    _incident(
                        node="node-0070",
                        cluster="cluster-1",
                        ts=EPOCH_NS + 3_000_000_000,
                    )
                ],
            )
        )
        region.ingest(
            encode_region_envelope(
                "cluster-0",
                0,
                [_incident(node="node-0001", cluster="cluster-0")],
            )
        )
        emitted = region.pump(flush=True)
        assert len(emitted) == 1

    def test_staleness_recorded_on_emission(self):
        region = RegionAggregator()
        region.ingest(
            encode_region_envelope(
                "cluster-0",
                0,
                [_incident()],
                watermark_ns=EPOCH_NS + 60_000_000_000,
                head_ns=EPOCH_NS + 12_000_000_000,
            )
        )
        region.pump()
        assert region.max_staleness_ms == pytest.approx(12_000.0)

    def test_state_round_trip_preserves_pending_and_cursors(self):
        region = RegionAggregator()
        region.ingest(
            encode_region_envelope("cluster-0", 4, [_incident()])
        )
        clone = RegionAggregator()
        clone.restore_state(region.export_state())
        assert clone.clusters["cluster-0"].seq == 4
        # Pending (buffered, un-pumped) incidents survive the restore.
        emitted = clone.pump(flush=True)
        assert len(emitted) == 1
        # And the emitted-window registry round-trips: re-building the
        # same group after another restore pages zero times.
        clone2 = RegionAggregator()
        clone2.restore_state(clone.export_state())
        clone2.ingest(
            encode_region_envelope("cluster-0", 5, [_incident()])
        )
        assert clone2.pump(flush=True) == []
        assert clone2.rollup.duplicates_suppressed == 1


class TestFederationTopologyAndPlan:
    def test_slices_stripe_across_clusters(self):
        topo = FederationTopology.for_nodes(10000, clusters=4)
        assert topo.cluster_index(0) == 0
        assert topo.cluster_index(topo.nodes_per_slice) == 1
        assert topo.cluster_index(2 * topo.nodes_per_slice) == 2
        seen = {
            topo.cluster_of_node(i)
            for i in range(0, topo.nodes, topo.nodes_per_slice)
        }
        assert len(seen) == 4

    def test_plan_fleet_scope_spans_clusters(self):
        topo = FederationTopology.for_nodes(400, clusters=4)
        plan = federation_injection_plan(topo)
        fleet = next(p for p in plan if p.scope == "fleet")
        clusters = {
            topo.cluster_of_node(node_i)
            for node_i, _ in fleet.affected(topo)
        }
        assert len(clusters) >= 2
        # Distinct (namespace, domain) ground truth throughout.
        pairs = [(p.namespace, p.domain) for p in plan]
        assert len(pairs) == len(set(pairs))

    def test_churn_plan_protects_fault_nodes(self):
        topo = FederationTopology.for_nodes(200, clusters=2)
        plan = federation_injection_plan(topo)
        protected = {
            node_i
            for injection in plan
            for node_i, _ in injection.affected(topo)
        }
        churn = build_churn_plan(
            topo, 16, plan, node_churn_per_round=3, seed=99
        )
        leaves = {
            e.node_i for e in churn if e.kind == "node_leave"
        }
        assert leaves and not (leaves & protected)
        joins = {e.node_i for e in churn if e.kind == "node_join"}
        assert joins and min(joins) >= topo.nodes
        restarts = [e for e in churn if e.kind == "shard_down"]
        assert restarts and all(
            any(
                u.kind == "shard_up"
                and u.shard_id == d.shard_id
                and u.round_i == d.round_i + 1
                for u in churn
            )
            for d in restarts
        )


class TestFederationSimulator:
    def test_churn_run_exact_dedup_cross_cluster(self):
        topo = FederationTopology.for_nodes(64, clusters=2)
        plan = federation_injection_plan(topo)
        churn = build_churn_plan(
            topo, 18, plan, node_churn_per_round=1, seed=7
        )
        sim = FederationSimulator(topo, shards_per_cluster=2, seed=7)
        result = sim.run(18, plan, churn=churn)
        _, precision, recall, _ = score_incidents(
            plan, result.incidents
        )
        assert precision == 1.0 and recall == 1.0
        fleet = [
            i for i in result.incidents if i.blast_radius == "fleet"
        ]
        assert fleet and len(fleet[0].clusters) >= 2
        assert result.churn["node_leave"] > 0
        assert result.churn["shard_down"] == 2
        assert sim.moved_keys > 0
        assert all(i.region == "region-0" for i in result.incidents)

    def test_region_kill_loses_and_duplicates_nothing(self, tmp_path):
        from tpuslo.runtime import AgentRuntime, StateStore

        topo = FederationTopology.for_nodes(64, clusters=2)
        plan = federation_injection_plan(topo)
        churn = build_churn_plan(
            topo, 18, plan, node_churn_per_round=1, seed=7
        )

        def keys(incidents):
            return sorted(
                f"{i.namespace}/{i.domain}/{i.blast_radius}"
                for i in incidents
            )

        baseline = FederationSimulator(
            topo, shards_per_cluster=2, seed=7
        ).run(18, plan, churn=churn)
        runtime = AgentRuntime(
            StateStore(str(tmp_path / "fed.json"), interval_s=0.0)
        )
        sim = FederationSimulator(topo, shards_per_cluster=2, seed=7)
        result = sim.run(
            18, plan, churn=churn, kill_region_at=9, runtime=runtime
        )
        assert result.failover["resent_envelopes"] > 0
        assert keys(result.incidents) == keys(baseline.incidents)

    def test_saturation_degrades_but_never_drops(self):
        topo = FederationTopology.for_nodes(64, clusters=2)
        plan = federation_injection_plan(topo)
        sim = FederationSimulator(
            topo,
            shards_per_cluster=2,
            seed=7,
            cluster_capacity_events=200,
            region_capacity_incidents=8,
        )
        result = sim.run(18, plan)
        _, precision, recall, _ = score_incidents(
            plan, result.incidents
        )
        assert precision == 1.0 and recall == 1.0
        assert result.max_level_seen >= LEVEL_SAMPLE
        assert sum(result.sampled_rows_by_level.values()) > 0
        assert result.pressure_observations_by_level
        assert result.max_staleness_ms < 30_000.0

    def test_throughput_lane_template_cloned(self):
        topo = FederationTopology.for_nodes(96, clusters=2)
        sim = FederationSimulator(topo, shards_per_cluster=2, seed=7)
        m = sim.measure_ingest(events_per_node=400)
        assert m.nodes == 96
        assert m.clusters == 2 and m.shards == 4
        assert m.total_events > 0
        assert m.admitted_events > 0
        assert m.events_per_sec > 0
        assert set(m.per_cluster_events_per_sec) == {
            "cluster-0",
            "cluster-1",
        }


class TestFederationSweep:
    def test_small_sweep_passes_all_phases(self):
        report = run_federation_sweep(
            nodes=48,
            clusters=2,
            shards_per_cluster=2,
            rounds=16,
            events_per_node=400,
            churn_per_round=1,
            min_ingest_events_per_sec=1.0,  # smoke scale: no floor
        )
        assert report.passed, report.failures
        assert report.precision == 1.0 and report.recall == 1.0
        assert report.cross_cluster_members >= 2
        assert report.failover.get("resent_envelopes", 0) >= 0
        assert not report.failover_lost
        assert not report.failover_duplicated
        assert report.saturation["max_level_seen"] >= LEVEL_SAMPLE
        assert report.saturation["precision"] == 1.0
        d = report.to_dict()
        assert d["passed"] is True
        assert json.loads(json.dumps(d)) == d

    def test_sweep_fails_loud_on_impossible_floor(self):
        report = run_federation_sweep(
            nodes=48,
            clusters=2,
            shards_per_cluster=2,
            rounds=14,
            events_per_node=400,
            churn_per_round=0,
            kill_region=False,
            saturate=False,
            min_ingest_events_per_sec=1e15,
        )
        assert not report.passed
        assert any("below the" in f for f in report.failures)

    @pytest.mark.slow
    def test_m5gate_federation_cli_round_trip(self, tmp_path):
        from tpuslo.cli.m5gate import main as m5gate_main

        summary_json = tmp_path / "sweep.json"
        summary_md = tmp_path / "sweep.md"
        rc = m5gate_main(
            [
                "--federation-sweep",
                "--federation-nodes", "48",
                "--federation-clusters", "2",
                "--federation-shards-per-cluster", "2",
                "--federation-rounds", "16",
                "--federation-events-per-node", "400",
                "--federation-churn-rate", "1",
                "--federation-min-ingest", "1",
                "--summary-json", str(summary_json),
                "--summary-md", str(summary_md),
            ]
        )
        assert rc == 0
        report = json.loads(summary_json.read_text())
        assert report["passed"] is True
        md = summary_md.read_text()
        assert "Federation-plane gate" in md
        assert "PASS" in md


class TestFederationCLIs:
    def _write_cluster_log(self, path, node, slice_id):
        from tpuslo.fleet.wire import ShipmentWriter
        from tpuslo.schema.types import TPURef
        from tpuslo.signals.constants import TPU_SIGNALS
        from tpuslo.signals.generator import (
            SIGNAL_UNITS,
            profile_for_fault,
            signal_status,
        )

        rows = []
        for k, (sig, val) in enumerate(
            sorted(profile_for_fault("hbm_pressure").items())
        ):
            rows.append(
                ProbeEventV1(
                    ts_unix_nano=EPOCH_NS + k * 1000,
                    signal=sig,
                    node=node,
                    namespace="tenant-b",
                    pod=f"{node}-pod-1",
                    container="w",
                    pid=1,
                    tid=1,
                    value=float(val),
                    unit=SIGNAL_UNITS.get(sig, "ms"),
                    status=signal_status(sig, float(val)),
                    tpu=TPURef(slice_id=slice_id, host_index=0)
                    if sig in TPU_SIGNALS
                    else None,
                )
            )
        writer = ShipmentWriter(str(path))
        writer.send(
            "fleet",
            [
                encode_shipment(
                    from_rows(rows),
                    node,
                    0,
                    transport="base64",
                    slice_id=slice_id,
                )
            ],
        )
        writer.close()

    def test_fleetagg_federation_tree_end_to_end(
        self, tmp_path, capsys
    ):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        c0 = tmp_path / "c0.jsonl"
        c1 = tmp_path / "c1.jsonl"
        self._write_cluster_log(c0, "node-0001", "slice-000")
        self._write_cluster_log(c1, "node-0070", "slice-001")
        r0 = tmp_path / "r0.jsonl"
        r1 = tmp_path / "r1.jsonl"
        s0 = tmp_path / "s0.json"
        assert fleetagg_main(
            [
                str(c0), "--cluster-id", "cluster-0",
                "--region-out", str(r0), "--state-out", str(s0),
            ]
        ) == 0
        assert fleetagg_main(
            [
                str(c1), "--cluster-id", "cluster-1",
                "--region-out", str(r1),
            ]
        ) == 0
        capsys.readouterr()
        incidents_out = tmp_path / "inc.jsonl"
        provenance_out = tmp_path / "prov.jsonl"
        region_state = tmp_path / "region.json"
        rc = fleetagg_main(
            [
                "--region", str(r0), str(r1),
                "--incidents-out", str(incidents_out),
                "--provenance-out", str(provenance_out),
                "--state-out", str(region_state),
                "--json",
            ]
        )
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["incidents"] == 1
        assert summary["clusters"] == ["cluster-0", "cluster-1"]
        incident = json.loads(
            incidents_out.read_text().strip()
        )
        assert incident["region"] == "region-0"
        assert incident["clusters"] == ["cluster-0", "cluster-1"]
        assert incident["blast_radius"] == "fleet"
        prov = json.loads(provenance_out.read_text().strip())
        assert prov["correlation"]["clusters"] == [
            "cluster-0",
            "cluster-1",
        ]
        # Cluster state snapshot carries the cluster identity.
        assert json.loads(s0.read_text())["cluster"] == "cluster-0"
        # Re-running the region against the SAME envelopes from its
        # saved state pages nothing twice (seq dedup).
        capsys.readouterr()
        rc = fleetagg_main(
            [
                "--region", str(r0), str(r1),
                "--restore-state", str(region_state),
                "--json",
            ]
        )
        assert rc == 0
        replay = json.loads(capsys.readouterr().out)
        assert replay["incidents"] == 0
        assert replay["duplicate_envelopes"] == 2

    def test_fleetagg_region_flag_conflicts(self, capsys):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        rc = fleetagg_main(
            ["x.jsonl", "--region", "--cluster-id", "c0"]
        )
        assert rc == 2
        assert "--region consumes" in capsys.readouterr().err

    def test_fleetagg_region_out_requires_cluster_id(self, capsys):
        # A fallback identity would collide across cluster runs at the
        # region (shared seq cursor drops one cluster's envelope).
        from tpuslo.cli.fleetagg import main as fleetagg_main

        rc = fleetagg_main(["x.jsonl", "--region-out", "r.jsonl"])
        assert rc == 2
        assert "requires --cluster-id" in capsys.readouterr().err

    def test_fleetagg_region_rejects_bad_envelopes(
        self, tmp_path, capsys
    ):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        bad = tmp_path / "bad.jsonl"
        bad.write_text(
            "not json\n"
            + json.dumps({"region_wire_version": 99, "cluster": "c"})
            + "\n"
        )
        rc = fleetagg_main(["--region", str(bad), "--json"])
        assert rc == 0
        out = capsys.readouterr()
        assert json.loads(out.out)["rejected_envelopes"] == 2
        assert "rejected" in out.err

    def test_sloctl_region_cluster_scopes(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main as sloctl_main
        from tpuslo.fleet.rollup import FleetIncident

        incidents = [
            FleetIncident(
                incident_id="fleet-tenant-a-tpu_hbm-1",
                namespace="tenant-a",
                domain="tpu_hbm",
                blast_radius="fleet",
                window_start_ns=EPOCH_NS,
                window_end_ns=EPOCH_NS + 1,
                confidence=0.9,
                nodes=["node-0001", "node-0070"],
                slices=["slice-000", "slice-001"],
                members=[],
                region="region-0",
                clusters=["cluster-0", "cluster-1"],
            ),
            FleetIncident(
                incident_id="fleet-tenant-b-tpu_ici-2",
                namespace="tenant-b",
                domain="tpu_ici",
                blast_radius="slice",
                window_start_ns=EPOCH_NS,
                window_end_ns=EPOCH_NS + 1,
                confidence=0.8,
                nodes=["node-0099"],
                slices=["slice-002"],
                members=[],
                region="region-1",
                clusters=["cluster-2"],
            ),
        ]
        path = tmp_path / "inc.jsonl"
        path.write_text(
            "".join(
                json.dumps(i.to_dict()) + "\n" for i in incidents
            )
        )
        rc = sloctl_main(
            ["fleet", "incidents", "--incidents", str(path)]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "REGION" in out and "CLUSTERS" in out
        assert "region-0" in out and "cluster-0,cluster-1" in out
        # --region scope.
        sloctl_main(
            [
                "fleet", "incidents", "--incidents", str(path),
                "--region", "region-1",
            ]
        )
        out = capsys.readouterr().out
        assert "tpu_ici" in out and "tpu_hbm" not in out
        # --cluster scope with --json parity.
        sloctl_main(
            [
                "fleet", "incidents", "--incidents", str(path),
                "--cluster", "cluster-1", "--json",
            ]
        )
        rows = json.loads(capsys.readouterr().out)
        assert [r["incident_id"] for r in rows] == [
            "fleet-tenant-a-tpu_hbm-1"
        ]
        assert rows[0]["region"] == "region-0"

    def test_sloctl_nodes_cluster_scope(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main as sloctl_main

        state = {
            "cluster": "cluster-0",
            "shards": {
                "agg-0": {
                    "nodes": {
                        "node-0001": {
                            "head_ns": EPOCH_NS,
                            "seq": 3,
                            "events": 21,
                            "slice_id": "slice-000",
                            "stale": False,
                        }
                    }
                }
            },
            "snapshots": {"agg-0": {"watermark_ns": 0}},
        }
        path = tmp_path / "state.json"
        path.write_text(json.dumps(state))
        rc = sloctl_main(["fleet", "nodes", "--state", str(path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "CLUSTER" in out and "cluster-0" in out
        # Matching filter keeps the row; a different cluster empties.
        sloctl_main(
            [
                "fleet", "nodes", "--state", str(path),
                "--cluster", "cluster-0", "--json",
            ]
        )
        rows = json.loads(capsys.readouterr().out)
        assert rows[0]["cluster"] == "cluster-0"
        sloctl_main(
            [
                "fleet", "nodes", "--state", str(path),
                "--cluster", "cluster-9",
            ]
        )
        assert "(no nodes)" in capsys.readouterr().out


class TestFederationMetricsBridge:
    def test_observer_series(self):
        from tpuslo.metrics.registry import AgentMetrics

        metrics = AgentMetrics()
        observer = metrics.federation_observer()
        observer.region_ingested("cluster-0", 5)
        observer.region_ingested("cluster-0", 3)
        observer.backpressure_level("region-0", 2)
        observer.sampled_rows(3, 17)
        observer.churn_rebalance("shard_join", 4)
        observer.incident_staleness_ms(1234.5)
        from prometheus_client import generate_latest

        scrape = generate_latest(metrics.registry).decode()
        assert (
            'llm_slo_fleet_federation_region_ingested_incidents_total'
            '{cluster="cluster-0"} 8.0' in scrape
        )
        assert (
            'llm_slo_fleet_federation_backpressure_level'
            '{source="region-0"} 2.0' in scrape
        )
        assert (
            'llm_slo_fleet_federation_sampled_rows_total'
            '{level="3"} 17.0' in scrape
        )
        assert (
            'llm_slo_fleet_federation_churn_rebalances_total'
            '{kind="shard_join"} 1.0' in scrape
        )
        assert (
            "llm_slo_fleet_federation_incident_staleness_ms_count 1.0"
            in scrape
        )

    def test_simulator_drives_observer(self):
        from tpuslo.metrics.registry import AgentMetrics

        metrics = AgentMetrics()
        topo = FederationTopology.for_nodes(48, clusters=2)
        plan = federation_injection_plan(topo)
        sim = FederationSimulator(
            topo,
            shards_per_cluster=2,
            seed=7,
            cluster_capacity_events=100,
            observer=metrics.federation_observer(),
        )
        churn = build_churn_plan(
            topo, 14, plan, node_churn_per_round=1, seed=7
        )
        sim.run(14, plan, churn=churn)
        from prometheus_client import generate_latest

        scrape = generate_latest(metrics.registry).decode()
        assert (
            "llm_slo_fleet_federation_region_ingested_incidents_total"
            in scrape
        )
        assert (
            'llm_slo_fleet_federation_churn_rebalances_total'
            '{kind="shard_leave"}' in scrape
        )
        assert (
            "llm_slo_fleet_federation_incident_staleness_ms_count"
            in scrape
        )
