"""Vector store demo component (reference placeholder: demo/vectordb)."""

from __future__ import annotations

import json
import urllib.request

import numpy as np

from demo.vectordb import VectorStore, embed_text, embed_texts
from demo.vectordb.store import _bucket


def test_embed_deterministic_and_normalized():
    a = embed_text("time to first token")
    b = embed_text("time to first token")
    np.testing.assert_array_equal(a, b)
    assert abs(float(np.linalg.norm(a)) - 1.0) < 1e-5
    assert embed_texts([]).shape == (0, 256)


def test_embed_distinguishes_topics():
    dns = embed_text("dns resolution latency for the retrieval query")
    hbm = embed_text("hbm allocation stalls and memory defragmentation")
    assert float(dns @ hbm) < 0.9


def test_bucket_rounding():
    assert _bucket(1) == 8
    assert _bucket(8) == 8
    assert _bucket(9) == 16
    assert _bucket(100) == 128


def test_search_ranks_matching_doc_first():
    store = VectorStore()
    store.add("dns", "DNS resolution latency adds to time to first token")
    store.add("hbm", "HBM pressure shows up as allocation stalls")
    store.add("ici", "ICI link retries slow down collectives in the slice")
    hits = store.search("what causes dns latency in retrieval", k=2)
    assert len(hits) == 2
    assert hits[0].doc_id == "dns"
    assert hits[0].score >= hits[1].score


def test_search_empty_store_and_k_clamping():
    store = VectorStore()
    assert store.search("anything") == []
    store.add("only", "a single document about tpu serving")
    hits = store.search("tpu serving", k=5)
    assert [h.doc_id for h in hits] == ["only"]


def test_search_batch_and_bucket_growth():
    store = VectorStore()
    store.add_many([(f"d{i}", f"document number {i} about topic {i}") for i in range(20)])
    assert len(store) == 20
    results = store.search_batch(["document number 7", "document number 13"], k=3)
    assert len(results) == 2
    assert results[0][0].doc_id == "d7"
    assert results[1][0].doc_id == "d13"


def test_from_corpus_fixture():
    store = VectorStore.from_corpus("demo/rag_service/fixtures/corpus.json")
    assert len(store) >= 10
    hits = store.search("time to first token latency", k=3)
    assert hits[0].doc_id == "doc-ttft"


def test_http_server_roundtrip():
    from demo.vectordb.server import serve

    store = VectorStore()
    store.add("doc-a", "tcp retransmits inflate network latency")
    server = serve(store, port=0, host="127.0.0.1")
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            assert json.load(resp)["docs"] == 1

        req = urllib.request.Request(
            f"{base}/add",
            data=json.dumps(
                {"id": "doc-b", "text": "tls handshake latency spikes"}
            ).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.load(resp)["docs"] == 2

        req = urllib.request.Request(
            f"{base}/search",
            data=json.dumps({"query": "tls handshake", "k": 1}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            payload = json.load(resp)
        assert payload["hits"][0]["id"] == "doc-b"
        assert payload["latency_ms"] >= 0

        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            metrics = resp.read().decode()
        assert "vectordb_searches_total" in metrics
    finally:
        server.shutdown()


def test_rag_service_with_vector_store():
    from demo.rag_service.service import RagService, StubBackend

    store = VectorStore.from_corpus("demo/rag_service/fixtures/corpus.json")
    service = RagService(
        backend=StubBackend(), sleep=lambda s: None, vector_store=store
    )
    events = list(service.chat("why is time to first token slow", "chat_short"))
    summary = events[-1]
    assert summary["type"] == "summary"
    assert summary["retrieval"]["doc_ids"][0] == "doc-ttft"
    # vectordb phase is measured, not the seeded sleep value
    assert summary["retrieval"]["vectordb_ms"] >= 0
    retr_span = next(
        s for s in service.recorder.recent() if s["name"] == "chat.retrieval"
    )
    assert "retrieval.doc_ids" in retr_span["attributes"]


def test_numpy_fallback_matches_jax_path(monkeypatch):
    """The demo image ships without jax; search must degrade to the
    numpy exact path with identical ranking."""
    store = VectorStore.from_corpus("demo/rag_service/fixtures/corpus.json")
    jax_hits = store.search("time to first token latency", k=3)

    def no_jax(*a, **k):
        raise ImportError("jax not installed")

    monkeypatch.setattr(VectorStore, "_search_jax", no_jax)
    np_hits = store.search("time to first token latency", k=3)
    assert [h.doc_id for h in np_hits] == [h.doc_id for h in jax_hits]
    np.testing.assert_allclose(
        [h.score for h in np_hits], [h.score for h in jax_hits], rtol=1e-5
    )


def test_add_after_search_invalidates_matrix_cache():
    store = VectorStore()
    store.add("a", "alpha document about tpu scheduling")
    assert store.search("tpu scheduling", k=1)[0].doc_id == "a"
    store.add("b", "beta document about dns resolution")
    assert store.search("dns resolution", k=1)[0].doc_id == "b"


def test_http_server_rejects_malformed_search():
    import urllib.error

    from demo.vectordb.server import serve

    store = VectorStore()
    store.add("doc-a", "a document")
    server = serve(store, port=0, host="127.0.0.1")
    port = server.server_address[1]
    try:
        for bad in (
            {"query": "x", "k": "abc"},
            {"query": 5},
            {"query": "x", "k": 0},
            {},
        ):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/search",
                data=json.dumps(bad).encode(),
                method="POST",
            )
            try:
                urllib.request.urlopen(req, timeout=5)
                raise AssertionError(f"{bad} should 400")
            except urllib.error.HTTPError as err:
                assert err.code == 400, bad
    finally:
        server.shutdown()


def test_http_server_rejects_malformed_add():
    import urllib.error

    from demo.vectordb.server import serve

    store = VectorStore()
    server = serve(store, port=0, host="127.0.0.1")
    port = server.server_address[1]
    try:
        for bad in ({"id": "x", "text": 123}, {"id": 5, "text": "t"}, {"id": "x"}):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/add",
                data=json.dumps(bad).encode(),
                method="POST",
            )
            try:
                urllib.request.urlopen(req, timeout=5)
                raise AssertionError(f"{bad} should 400")
            except urllib.error.HTTPError as err:
                assert err.code == 400, bad
        assert len(store) == 0
    finally:
        server.shutdown()
