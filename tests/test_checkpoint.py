"""Checkpoint/resume: roundtrips (dense, quantized, sharded), rotation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuslo.models.checkpoint import (
    TrainCheckpointer,
    abstract_like,
    restore_checkpoint,
    save_checkpoint,
)
from tpuslo.models.llama import init_params, llama_tiny, quantize_params


def _trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        assert la.dtype == lb.dtype


def test_save_restore_roundtrip(tmp_path):
    cfg = llama_tiny(max_seq_len=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = restore_checkpoint(path)
    _trees_equal(params, restored)


def test_overwrite_guard(tmp_path):
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, {"x": jnp.arange(4)})
    with pytest.raises(FileExistsError):
        save_checkpoint(path, {"x": jnp.arange(4)})
    save_checkpoint(path, {"x": jnp.arange(8)}, overwrite=True)
    assert restore_checkpoint(path)["x"].shape == (8,)


def test_quantized_tree_roundtrip(tmp_path):
    cfg = llama_tiny(max_seq_len=32)
    qparams = quantize_params(init_params(jax.random.PRNGKey(0), cfg))
    path = str(tmp_path / "q")
    save_checkpoint(path, qparams)
    restored = restore_checkpoint(path)
    assert restored["layers"]["w1"]["q"].dtype == jnp.int8
    _trees_equal(qparams, restored)


@pytest.mark.slow
def test_sharded_restore(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("tp",))
    sharding = NamedSharding(mesh, P(None, "tp"))
    x = jax.device_put(
        jnp.arange(64, dtype=jnp.float32).reshape(4, 16), sharding
    )
    tree = {"w": x}
    path = str(tmp_path / "sharded")
    save_checkpoint(path, tree)

    abstract = abstract_like(tree, {"w": sharding})
    restored = restore_checkpoint(path, abstract)
    assert restored["w"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(x))


@pytest.mark.slow
def test_train_checkpointer_rotation_and_resume(tmp_path):
    cfg = llama_tiny(max_seq_len=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    with TrainCheckpointer(str(tmp_path / "mgr"), max_to_keep=2) as ckpt:
        for step in (1, 2, 3):
            scaled = jax.tree.map(lambda w: w * step, params)
            ckpt.save(step, scaled, opt_state={"count": jnp.asarray(step)})
        ckpt._mgr.wait_until_finished()
        assert ckpt.latest_step() == 3
        restored = ckpt.restore()
        assert int(restored["opt_state"]["count"]) == 3
        _trees_equal(restored["params"], jax.tree.map(lambda w: w * 3, params))
        # keep-N rotation: step 1 evicted
        steps = sorted(ckpt._mgr.all_steps())
        assert steps == [2, 3]
