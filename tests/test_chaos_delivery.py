"""Chaos tests: zero-loss delivery through a scripted sink outage.

The fault-injection HTTP sink (tpuslo/delivery/faultsink.py) refuses /
5xxes / hangs mid-run while the real agent loop keeps emitting; the
delivery layer must spool the outage window, trip and recover the
breaker, replay on reconnect, and end the run with every generated
event either accepted by the sink or dead-lettered with a reason —
never silently dropped.

Marked ``chaos`` (run via ``make chaos-smoke``) and ``slow`` (kept out
of the tier-1 ``-m 'not slow'`` lane: these tests drive real sockets,
threads, and wall-clock backoff).
"""

from __future__ import annotations

import pytest

from tpuslo.delivery.faultsink import FaultInjectingHTTPServer
from tpuslo.metrics import AgentMetrics

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


CHAOS_CONFIG = """\
apiVersion: toolkit.tpuslo.dev/v1alpha1
kind: ToolkitConfig
signal_set: [dns_latency_ms, tcp_retransmits_total]
sampling: {events_per_second_limit: 10000, burst_limit: 20000}
correlation: {window_ms: 2000, enrichment_threshold: 0.7}
otlp: {endpoint: "http://unused-placeholder:4318/v1/logs"}
safety: {max_overhead_pct: 1000.0}
delivery:
  queue_max: 64
  max_attempts: 2
  base_delay_s: 0.005
  max_delay_s: 0.02
  breaker_failure_threshold: 3
  breaker_open_duration_s: 0.1
"""


def metric(metrics: AgentMetrics, name: str, **labels) -> float:
    value = metrics.registry.get_sample_value(name, labels or None)
    return 0.0 if value is None else value


def run_chaos_agent(tmp_path, server, cycles: int) -> AgentMetrics:
    from tpuslo.cli import agent

    cfg = tmp_path / "toolkit.yaml"
    cfg.write_text(CHAOS_CONFIG)
    metrics = AgentMetrics()
    rc = agent.main(
        [
            "--config", str(cfg),
            "--scenario", "dns_latency",
            "--count", str(cycles),
            "--interval-s", "0.05",
            "--event-kind", "both",
            "--output", "otlp",
            "--otlp-endpoint", server.endpoint,
            "--capability-mode", "bcc_degraded",
            "--spool-dir", str(tmp_path / "spool"),
            "--metrics-port", "0",
            "--max-overhead-pct", "1000",
        ],
        metrics=metrics,
    )
    assert rc == 0
    return metrics


def identity_sets(server):
    """Unique delivered identities: SLO event ids / probe (signal, ts)."""
    slo_ids = set()
    probe_ids = set()
    for record in server.accepted_log_records():
        attrs = {a["key"]: a["value"] for a in record["attributes"]}
        if "event.id" in attrs:
            slo_ids.add(attrs["event.id"]["stringValue"])
        elif "signal" in attrs:
            probe_ids.add(
                (attrs["signal"]["stringValue"], record["timeUnixNano"])
            )
    return slo_ids, probe_ids


class TestZeroLossAcrossOutage:
    def test_outage_window_is_spooled_and_replayed(self, tmp_path):
        cycles = 20
        # Healthy start, then the collector drops 8 consecutive
        # connections mid-run, then recovers.  The window is sized so
        # live sends + breaker probes consume it well before the run
        # ends, leaving time for in-run replay.
        server = FaultInjectingHTTPServer("ok:4,refuse:8,ok").start()
        try:
            metrics = run_chaos_agent(tmp_path, server, cycles)
            slo_ids, probe_ids = identity_sets(server)
            # Zero loss: every generated event was eventually accepted.
            # 12 cycles x 4 SLIs and x 2 probe signals (bcc_degraded).
            assert len(slo_ids) == cycles * 4
            assert len(probe_ids) == cycles * 2
            # Nothing was poisoned and nothing silently vanished.
            for sink in ("otlp-slo", "otlp-probe"):
                dead = metric(
                    metrics,
                    "llm_slo_agent_delivery_dead_letter_events_total",
                    sink=sink, reason="non_retryable",
                )
                assert dead == 0
            # The outage is visible in metrics: events spooled, then
            # replayed after recovery.
            spooled = sum(
                metric(
                    metrics,
                    "llm_slo_agent_delivery_spooled_events_total",
                    sink=s,
                )
                for s in ("otlp-slo", "otlp-probe")
            )
            replayed = sum(
                metric(
                    metrics,
                    "llm_slo_agent_delivery_replayed_events_total",
                    sink=s,
                )
                for s in ("otlp-slo", "otlp-probe")
            )
            assert spooled > 0
            # The whole window came back (>= because replay is
            # at-least-once: an aborted drain re-sends a segment tail).
            assert replayed >= spooled
            # Drop accounting stayed clean: spooling is not dropping.
            assert metric(
                metrics, "llm_slo_agent_events_dropped_total", reason="emit"
            ) == 0
        finally:
            server.stop()

    def test_breaker_lifecycle_visible_in_metrics(self, tmp_path):
        # A long enough outage must trip the breaker (open), probe it
        # (half-open), and close it again after recovery — all three
        # transitions land in the transitions counter.
        server = FaultInjectingHTTPServer("ok:2,5xx:8,ok").start()
        try:
            metrics = run_chaos_agent(tmp_path, server, 20)
            transitions = {
                state: sum(
                    metric(
                        metrics,
                        "llm_slo_agent_delivery_breaker_transitions_total",
                        sink=s, state=state,
                    )
                    for s in ("otlp-slo", "otlp-probe")
                )
                for state in ("open", "half_open", "closed")
            }
            assert transitions["open"] >= 1
            assert transitions["half_open"] >= 1
            assert transitions["closed"] >= 1
            # And the run ends healthy.
            for sink in ("otlp-slo", "otlp-probe"):
                assert metric(
                    metrics,
                    "llm_slo_agent_delivery_breaker_state",
                    sink=sink,
                ) == 0
        finally:
            server.stop()

    def test_poison_batches_dead_letter_with_reason(self, tmp_path):
        # A 4xx verdict is not an outage: the batch is recorded as a
        # dead letter immediately instead of being retried forever.
        server = FaultInjectingHTTPServer("4xx:4,ok").start()
        try:
            metrics = run_chaos_agent(tmp_path, server, 4)
            dead = sum(
                metric(
                    metrics,
                    "llm_slo_agent_delivery_dead_letter_events_total",
                    sink=s, reason="non_retryable",
                )
                for s in ("otlp-slo", "otlp-probe")
            )
            assert dead > 0
            dl_files = list((tmp_path / "spool").glob("*-dead-letter.jsonl"))
            assert dl_files
        finally:
            server.stop()


class TestChaosSinkFlag:
    def test_agent_chaos_sink_flag_runs_end_to_end(self, tmp_path, capsys):
        from tpuslo.cli import agent

        metrics = AgentMetrics()
        rc = agent.main(
            [
                "--scenario", "dns_latency",
                "--count", "3",
                "--interval-s", "0.02",
                "--event-kind", "slo",
                "--chaos-sink", "ok:1,5xx:2,ok",
                "--spool-dir", str(tmp_path / "spool"),
                "--capability-mode", "bcc_degraded",
                "--metrics-port", "0",
                "--max-overhead-pct", "1000",
            ],
            metrics=metrics,
        )
        assert rc == 0
        err = capsys.readouterr().err
        assert "chaos sink on http://127.0.0.1:" in err
        assert "delivery[otlp-slo]" in err  # shutdown summary printed
        assert metric(
            metrics,
            "llm_slo_agent_delivery_delivered_events_total",
            sink="otlp-slo",
        ) == 3 * 4
