"""Serving front door: batched spec rounds in slots + SLO-aware
admission (ISSUE 12).

The exactness contract everything else leans on: every request served
through the front door emits EXACTLY the stream the per-stream
:class:`SpeculativeEngine` (and therefore the target-only greedy
decoder) would emit — through batching, preemption/park-resume,
prefix-cache placement, and a restart-mid-serve snapshot round trip.
"""

from __future__ import annotations

import json

import pytest

from tpuslo.models.frontdoor import (
    DEMOTED_PRIORITY,
    FrontDoorEngine,
    FrontDoorObserver,
    SHED_BURNING,
    SHED_DISPLACED,
    SHED_QUEUE_FULL,
)
from tpuslo.models.llama import llama_tiny
from tpuslo.models.serve import EOS, ServeEngine
from tpuslo.models.speculative import SpeculativeEngine
from tpuslo.sloengine.engine import (
    BurnEngine,
    DEFAULT_ADMISSION_PRIORITY,
    DEMOTED_ADMISSION_PRIORITY,
)
from tpuslo.sloengine.stream import RequestOutcome


@pytest.fixture(scope="module")
def engines():
    cfg = llama_tiny(max_seq_len=128)
    target = ServeEngine(cfg=cfg, rng_seed=0)
    # Same seed => self-draft: acceptance 1.0, fast deterministic tests.
    draft = ServeEngine(cfg=cfg, rng_seed=0)
    return target, draft


@pytest.fixture(scope="module")
def real_draft_engines():
    cfg = llama_tiny(max_seq_len=128)
    target = ServeEngine(cfg=cfg, rng_seed=0)
    draft = ServeEngine(cfg=cfg, rng_seed=7)  # genuinely different model
    return target, draft


def spec_reference(engines, prompt, n, stop_at_eos=False, prefix=None):
    spec = SpeculativeEngine(engines[0], engines[1], k=3)
    return spec.generate(
        prompt, max_new_tokens=n, stop_at_eos=stop_at_eos, prefix=prefix
    )


def make_burning_engine(tenant: str, now_s: float = 10_000.0) -> BurnEngine:
    """A real BurnEngine with ``tenant`` in fast burn at ``now_s``."""
    burn = BurnEngine()
    for j in range(600):
        ts = now_s - 1500.0 + j * 2.5
        burn.record(
            RequestOutcome(
                tenant=tenant,
                ts_unix_nano=int(ts * 1e9),
                ttft_ms=50.0,
                tpot_ms=10.0,
                tokens=8,
                status="error" if j % 2 == 0 else "ok",
            )
        )
    burn.evaluate(now_s)
    assert burn.tenant_burn_state(tenant) == "fast_burn"
    return burn


# ---- exactness ---------------------------------------------------------


class TestStreamParity:
    def test_matches_per_stream_speculative(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        prompts = [f"hello world {i}" for i in range(5)]
        ids = [
            fd.submit(p, max_new_tokens=10, stop_at_eos=False)
            for p in prompts
        ]
        results = fd.run()
        for prompt, rid in zip(prompts, ids):
            assert results[rid] == spec_reference(engines, prompt, 10)

    @pytest.mark.parametrize("rounds_per_step", [1, 2, 3])
    def test_multi_round_dispatch_parity(self, engines, rounds_per_step):
        fd = FrontDoorEngine(
            *engines, k=3, max_slots=2, rounds_per_step=rounds_per_step
        )
        prompts = [f"multi round {i}" for i in range(5)]
        ids = [
            fd.submit(p, max_new_tokens=11, stop_at_eos=False)
            for p in prompts
        ]
        results = fd.run()
        for prompt, rid in zip(prompts, ids):
            assert results[rid] == spec_reference(engines, prompt, 11)

    def test_real_draft_pair_parity(self, real_draft_engines):
        """A draft that actually disagrees exercises partial-acceptance
        frontiers across slots."""
        fd = FrontDoorEngine(*real_draft_engines, k=3, max_slots=2)
        prompts = [f"disagreeing draft {i}" for i in range(4)]
        ids = [
            fd.submit(p, max_new_tokens=12, stop_at_eos=False)
            for p in prompts
        ]
        results = fd.run()
        assert fd.acceptance_rate < 1.0  # the pair really disagrees
        for prompt, rid in zip(prompts, ids):
            assert results[rid] == spec_reference(
                real_draft_engines, prompt, 12
            )

    def test_stop_at_eos_respected(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        ids = [
            fd.submit(f"eos probe {i}", max_new_tokens=16)
            for i in range(3)
        ]
        results = fd.run()
        for i, rid in enumerate(ids):
            ref = spec_reference(
                engines, f"eos probe {i}", 16, stop_at_eos=True
            )
            assert results[rid] == ref
            assert EOS not in results[rid][:-1]

    def test_mixed_budgets_and_more_requests_than_slots(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        budgets = [3, 9, 1, 14, 6, 2, 11]
        ids = [
            fd.submit(f"budget {i}", max_new_tokens=b, stop_at_eos=False)
            for i, b in enumerate(budgets)
        ]
        results = fd.run()
        for i, (rid, budget) in enumerate(zip(ids, budgets)):
            assert results[rid] == spec_reference(
                engines, f"budget {i}", budget
            )


# ---- admission policy --------------------------------------------------


class TestAdmissionPolicy:
    def test_burn_demotion_changes_admission_order(self, engines):
        """Satellite: a demoted tenant's queued request is passed over
        by later-arriving default-priority requests."""
        burn = BurnEngine()
        burn.demote_tenant("lowly")
        fd = FrontDoorEngine(
            *engines, k=3, max_slots=1, burn_engine=burn
        )
        order: list[str] = []

        class Obs(FrontDoorObserver):
            def admitted(self, tenant: str) -> None:
                order.append(tenant)

        fd._observer = Obs()
        fd.submit("first in line", tenant="lowly", max_new_tokens=4,
                  stop_at_eos=False)
        fd.submit("second in line", tenant="vip", max_new_tokens=4,
                  stop_at_eos=False)
        fd.submit("third in line", tenant="vip", max_new_tokens=4,
                  stop_at_eos=False)
        fd.run()
        assert order == ["vip", "vip", "lowly"]

    def test_fast_burn_state_deprioritizes_without_demotion(self, engines):
        tenant = "burny"
        burn = make_burning_engine(tenant)
        fd = FrontDoorEngine(*engines, burn_engine=burn)
        assert (
            burn.admission_priority(tenant) == DEFAULT_ADMISSION_PRIORITY
        )
        assert fd.effective_priority(tenant) == DEMOTED_PRIORITY
        assert (
            fd.effective_priority("healthy") == DEFAULT_ADMISSION_PRIORITY
        )

    def test_full_queue_sheds_by_reason(self, engines):
        """Satellite: every shed is counted under its reason."""
        burn = BurnEngine()
        burn.demote_tenant("lowly")
        fd = FrontDoorEngine(
            *engines, k=3, max_slots=1, max_queue=2, burn_engine=burn
        )
        keep = fd.submit("occupies the slot", max_new_tokens=30,
                         stop_at_eos=False)
        fd.step()  # admit into the slot; queue now empty
        fd.submit("queued 1", tenant="lowly", max_new_tokens=4)
        fd.submit("queued 2", tenant="lowly", max_new_tokens=4)
        # Queue full; an equal-or-lower arrival sheds itself...
        shed_low = fd.submit("refused", tenant="lowly", max_new_tokens=4)
        assert shed_low is None
        # ...while a higher-priority arrival displaces a queued one.
        kept_hi = fd.submit("displaces", tenant="vip", max_new_tokens=4)
        assert kept_hi is not None
        counts = fd.shed_by_reason
        assert counts[SHED_BURNING] == 1  # lowly refused while demoted
        assert counts[SHED_DISPLACED] == 1
        assert counts[SHED_QUEUE_FULL] == 0
        # A default-priority arrival against a default-priority queue
        # sheds under the plain reason.
        fd2 = FrontDoorEngine(*engines, k=3, max_slots=1, max_queue=1)
        fd2.submit("slot", max_new_tokens=30, stop_at_eos=False)
        fd2.step()
        fd2.submit("queued", max_new_tokens=4)
        assert fd2.submit("refused", max_new_tokens=4) is None
        assert fd2.shed_by_reason[SHED_QUEUE_FULL] == 1
        assert keep in fd.run()

    def test_shed_records_failed_outcome_for_shed_tenant(self, engines):
        burn = BurnEngine()
        burn.demote_tenant("lowly")
        fd = FrontDoorEngine(
            *engines, k=3, max_slots=1, max_queue=1, burn_engine=burn
        )
        fd.submit("slot", max_new_tokens=30, stop_at_eos=False)
        fd.step()
        fd.submit("queued", max_new_tokens=4)
        before = burn.recorded
        assert fd.submit("refused", tenant="lowly", max_new_tokens=4) is None
        assert burn.recorded == before + 1

    def test_preempted_slot_resumes_bit_identical(self, engines):
        """Satellite: park-and-resume parity vs an uncontended run."""
        burn = BurnEngine()
        burn.demote_tenant("lowly")
        fd = FrontDoorEngine(
            *engines, k=3, max_slots=2, burn_engine=burn
        )
        low_ids = [
            fd.submit(f"low stream {i}", tenant="lowly",
                      max_new_tokens=24, stop_at_eos=False)
            for i in range(2)
        ]
        for _ in range(2):
            fd.step()
        hi = fd.submit("high priority arrives", tenant="vip",
                       max_new_tokens=8, stop_at_eos=False)
        results = fd.run()
        assert fd.preemptions >= 1
        assert fd.resumes >= 1
        for i, rid in enumerate(low_ids):
            assert results[rid] == spec_reference(
                engines, f"low stream {i}", 24
            )
        assert results[hi] == spec_reference(
            engines, "high priority arrives", 8
        )

    def test_equal_priorities_never_preempt(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=1)
        fd.submit("long runner", max_new_tokens=20, stop_at_eos=False)
        fd.step()
        fd.submit("same priority", max_new_tokens=4, stop_at_eos=False)
        fd.run()
        assert fd.preemptions == 0


# ---- prefix-cache-aware placement --------------------------------------


class TestPrefixPlacement:
    PREFIX = "[system] You are a terse assistant."

    def test_prefix_streams_match_reference(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        prompts = [f" question {i}?" for i in range(4)]
        ids = [
            fd.submit(p, max_new_tokens=8, stop_at_eos=False,
                      prefix=self.PREFIX)
            for p in prompts
        ]
        results = fd.run()
        for prompt, rid in zip(prompts, ids):
            assert results[rid] == spec_reference(
                engines, prompt, 8, prefix=self.PREFIX
            )

    def test_warm_prefix_admission_is_faster(self, engines):
        """Satellite: the second same-prefix request reuses the KV
        snapshot — its admission (suffix-only prefill) must beat the
        cold one (full prefix build) by a wide margin."""
        import time

        target, draft = engines
        prefix = "[system] a fresh prefix never cached before this test."
        fd = FrontDoorEngine(target, draft, k=3, max_slots=1)
        assert not fd._prefix_warm(prefix)

        t0 = time.perf_counter()
        fd.submit(" cold?", max_new_tokens=2, stop_at_eos=False,
                  prefix=prefix)
        fd.run()
        cold_s = time.perf_counter() - t0
        assert fd._prefix_warm(prefix)

        best_warm_s = 1e30
        for i in range(3):
            t0 = time.perf_counter()
            fd.submit(f" warm {i}?", max_new_tokens=2,
                      stop_at_eos=False, prefix=prefix)
            fd.run()
            best_warm_s = min(best_warm_s, time.perf_counter() - t0)
        assert best_warm_s < cold_s

    def test_warm_prefix_requests_sort_together(self, engines):
        """Queue order batches snapshot-reusing requests at equal
        priority."""
        target, draft = engines
        fd = FrontDoorEngine(target, draft, k=3, max_slots=1)
        warm_prefix = "[system] warm group prefix."
        target.cache_prefix(warm_prefix)
        draft.cache_prefix(warm_prefix)
        order: list[int] = []

        class Obs(FrontDoorObserver):
            def admitted(self, tenant: str) -> None: ...

        fd.submit("occupy", max_new_tokens=6, stop_at_eos=False)
        fd.step()
        cold = fd.submit(" cold", max_new_tokens=2, stop_at_eos=False,
                         prefix="[system] cold group prefix.")
        warm = fd.submit(" warm", max_new_tokens=2, stop_at_eos=False,
                         prefix=warm_prefix)
        fd._queue.sort(key=fd._order_key)
        assert [r.request_id for r in fd._queue] == [warm, cold]
        fd.run()


# ---- burn-engine feedback ----------------------------------------------


class TestOutcomeFeedback:
    def test_completions_record_outcomes(self, engines):
        burn = BurnEngine()
        fd = FrontDoorEngine(*engines, k=3, max_slots=2,
                             burn_engine=burn)
        fd.submit("tenant a stream", tenant="a", max_new_tokens=6,
                  stop_at_eos=False)
        fd.submit("tenant b stream", tenant="b", max_new_tokens=6,
                  stop_at_eos=False)
        fd.run()
        assert burn.recorded == 2
        snapshot = burn.snapshot()
        assert snapshot["tenants"] == 2


# ---- lifecycle / telemetry ---------------------------------------------


class TestLifecycle:
    def test_bad_args_rejected(self, engines):
        with pytest.raises(ValueError):
            FrontDoorEngine(*engines, k=0)
        with pytest.raises(ValueError):
            FrontDoorEngine(*engines, max_slots=0)
        with pytest.raises(ValueError):
            FrontDoorEngine(*engines, max_queue=0)
        with pytest.raises(ValueError):
            FrontDoorEngine(*engines, rounds_per_step=0)

    def test_priority_scale_is_the_sloengine_scale(self):
        """Review regression: the front door must read the SAME
        constants the remediation surface writes — a local mirror
        would silently desync the fast-burn clamp from
        demote_tenant."""
        from tpuslo.models import frontdoor as fd_mod
        from tpuslo.sloengine import engine as slo_mod

        assert fd_mod.DEFAULT_PRIORITY is slo_mod.DEFAULT_ADMISSION_PRIORITY
        assert fd_mod.DEMOTED_PRIORITY is slo_mod.DEMOTED_ADMISSION_PRIORITY

    def test_cancel_completed_clears_both_result_surfaces(self, engines):
        """Review regression: cancelling a COMPLETED request must drop
        its timing record too — telemetry and results must agree."""
        fd = FrontDoorEngine(*engines, k=3, max_slots=1)
        rid = fd.submit("done then cancelled", max_new_tokens=6,
                        stop_at_eos=False)
        fd.run()
        assert rid in fd.request_timings()
        fd.cancel(rid)
        assert rid not in fd.results
        assert rid not in fd.request_timings()

    def test_partial_tokens_and_cancel(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=1)
        a = fd.submit("running stream", max_new_tokens=20,
                      stop_at_eos=False)
        b = fd.submit("queued stream", max_new_tokens=4,
                      stop_at_eos=False)
        fd.step()
        assert len(fd.partial_tokens(a)) >= 1
        assert fd.partial_tokens(b) == []
        assert fd.partial_tokens(999) is None
        fd.cancel(b)
        assert fd.partial_tokens(b) is None
        results = fd.run()
        assert b not in results
        assert a in results

    def test_stats_and_timings(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        rid = fd.submit("timed stream", max_new_tokens=8,
                        stop_at_eos=False)
        fd.run()
        stats = fd.stats()
        assert stats["completed"] == 1
        assert stats["acceptance_rate"] == 1.0  # self-draft
        assert stats["emitted_tokens"] == 8
        timings = fd.request_timings()
        record = timings[rid]
        assert record["ttft_s"] >= 0.0
        assert record["e2e_s"] >= record["ttft_s"]
        assert record["tpot_s"] > 0.0
        assert record["tenant"] == "default"

    def test_instant_complete_requests_never_hold_slots(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=1)
        ids = [
            fd.submit(f"instant {i}", max_new_tokens=1,
                      stop_at_eos=False)
            for i in range(3)
        ]
        results = fd.run()
        assert fd.rounds == 0  # nothing ever needed a decode round
        for i, rid in enumerate(ids):
            assert results[rid] == spec_reference(
                engines, f"instant {i}", 1
            )


# ---- snapshot / restore -------------------------------------------------


class TestSnapshotRestore:
    def test_export_restore_round_trip_json_safe(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        fd.submit("stream one", max_new_tokens=24, stop_at_eos=False)
        fd.submit("stream two", max_new_tokens=24, stop_at_eos=False)
        fd.submit("queued three", max_new_tokens=24, stop_at_eos=False)
        fd.step()
        state = json.loads(json.dumps(fd.export_state()))
        fd2 = FrontDoorEngine(*engines, k=3, max_slots=2)
        fd2.restore_state(state)
        assert len(fd2._queue) == 3  # 2 in-flight + 1 queued
        assert fd2._next_id == fd._next_id

    def test_restart_mid_serve_through_agent_runtime(
        self, engines, tmp_path
    ):
        """Satellite: kill mid-serve, restore via AgentRuntime, finish
        — per-request streams equal the uninterrupted reference."""
        from tpuslo.runtime.statestore import AgentRuntime, StateStore

        store = StateStore(tmp_path / "state.json", interval_s=0.0)
        runtime = AgentRuntime(store)
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        runtime.register(
            "frontdoor", fd.export_state, fd.restore_state
        )
        prompts = [f"restart stream {i}" for i in range(4)]
        ids = [
            fd.submit(p, max_new_tokens=18, stop_at_eos=False)
            for p in prompts
        ]
        for _ in range(2):
            fd.step()
        assert runtime.snapshot_now()
        del fd  # the "crash"

        runtime2 = AgentRuntime(StateStore(tmp_path / "state.json"))
        fd2 = FrontDoorEngine(*engines, k=3, max_slots=2)
        runtime2.register(
            "frontdoor", fd2.export_state, fd2.restore_state
        )
        assert runtime2.restore() == "restored"
        results = fd2.run()
        assert fd2.snapshot_resumes >= 1
        for prompt, rid in zip(prompts, ids):
            assert results[rid] == spec_reference(engines, prompt, 18)

    def test_restore_rejects_unknown_version(self, engines):
        fd = FrontDoorEngine(*engines, k=3, max_slots=2)
        fd.restore_state({"version": 99, "queue": [{"request_id": 1}]})
        assert fd._queue == []


# ---- remediation end-to-end (satellite 5) ------------------------------


@pytest.mark.slow
def test_hbm_attribution_demotes_tenant_in_live_admission(engines):
    """faultreplay → BayesianAttributor → remediation policy →
    demote_tenant action → the LIVE front-door admission order changes.

    The full PR 11 loop landing in the serving plane: nothing is
    scripted — the posterior comes from a real hbm_pressure fault
    profile, the policy gates on it plus real fast-burn state, the
    action mutates the real BurnEngine, and the front door (which
    consults that engine live) starts admitting the demoted tenant
    last.
    """
    from datetime import datetime, timezone

    from tpuslo.attribution.bayesian import BayesianAttributor
    from tpuslo.faultreplay.generator import generate_fault_samples
    from tpuslo.remediation.actions import ActionBindings
    from tpuslo.remediation.engine import RemediationEngine
    from tpuslo.remediation.policy import AttributionContext

    tenant = "burny"
    now_s = 10_000.0
    burn = make_burning_engine(tenant, now_s)
    fd = FrontDoorEngine(*engines, k=3, max_slots=1, burn_engine=burn)

    # Before remediation: fast burn already deprioritizes, but the
    # remediation surface itself is untouched.
    assert burn.admission_priority(tenant) == DEFAULT_ADMISSION_PRIORITY

    sample = generate_fault_samples(
        "hbm_pressure", 1,
        start=datetime.fromtimestamp(now_s, tz=timezone.utc),
    )[0]
    attribution = BayesianAttributor().attribute_sample(sample)
    assert attribution.predicted_fault_domain == "tpu_hbm"

    engine = RemediationEngine(bindings=ActionBindings(burn_engine=burn))
    record = engine.consider(
        AttributionContext(
            incident_id="inc-e2e-hbm",
            domain=attribution.predicted_fault_domain,
            confidence=attribution.confidence,
            burn_state=burn.tenant_burn_state(tenant),
            burn_rate=burn.max_active_burn(),
            tenant=tenant,
            at_s=now_s,
        ),
        now_s,
    )
    assert record is not None and record.phase == "verifying"
    assert burn.admission_priority(tenant) == DEMOTED_ADMISSION_PRIORITY

    # The LIVE scheduling change: the demoted tenant queued first still
    # serves last.
    order: list[str] = []

    class Obs(FrontDoorObserver):
        def admitted(self, t: str) -> None:
            order.append(t)

    fd._observer = Obs()
    fd.submit("demoted tenant request", tenant=tenant,
              max_new_tokens=3, stop_at_eos=False)
    fd.submit("healthy tenant request", tenant="healthy",
              max_new_tokens=3, stop_at_eos=False)
    fd.run()
    assert order == ["healthy", tenant]


# ---- loadgen traffic synthesis (satellite 1) ---------------------------


class TestLoadgenTraffic:
    def test_arrival_models_shape_offsets(self):
        from tpuslo.cli.loadgen import arrival_offsets_ms
        import random

        rng = random.Random(7)
        duration_ms = 10_000.0
        for arrival in ("steady", "burst", "ramp", "poisson"):
            offsets = arrival_offsets_ms(
                arrival, 200, 10.0, random.Random(7)
            )
            assert len(offsets) == 200
            assert offsets == sorted(offsets)
            assert all(o >= 0 for o in offsets)
        # burst packs each burst's traffic into the window head.
        burst = arrival_offsets_ms("burst", 200, 10.0, rng)
        in_heads = sum(
            1 for o in burst if (o % 2500.0) <= 0.2 * 2500.0 + 1e-6
        )
        assert in_heads == len(burst)
        with pytest.raises(ValueError):
            arrival_offsets_ms("warble", 10, 1.0, rng)

    def test_tenant_mix_weights(self):
        from tpuslo.cli.loadgen import parse_tenant_mix, synthesize_requests

        assert parse_tenant_mix("", 2) == [0.5, 0.5]
        weights = parse_tenant_mix("70,20,10", 3)
        assert abs(sum(weights) - 1.0) < 1e-9
        assert weights[0] > weights[1] > weights[2]
        # short lists pad with the last weight
        assert len(parse_tenant_mix("5", 4)) == 4
        with pytest.raises(ValueError):
            parse_tenant_mix("1,2,3", 2)
        with pytest.raises(ValueError):
            parse_tenant_mix("0,1", 2)
        # Review regressions: an empty entry must be a loud error, not
        # a silent drop that shifts later weights onto the wrong
        # tenants; an all-separator spec must not IndexError.
        with pytest.raises(ValueError):
            parse_tenant_mix("70,,10", 3)
        with pytest.raises(ValueError):
            parse_tenant_mix(",", 2)

        records = synthesize_requests(
            seed=3, rps=50, duration_s=4.0, tenants=3,
            tenant_mix="80,15,5", arrival="poisson",
        )
        counts: dict[str, int] = {}
        for r in records:
            counts[r["tenant"]] = counts.get(r["tenant"], 0) + 1
        assert counts["tenant-00"] > counts.get("tenant-02", 0)

    def test_prefix_rate_marks_groups(self):
        from tpuslo.cli.loadgen import synthesize_requests

        records = synthesize_requests(
            seed=5, rps=50, duration_s=4.0, tenants=2,
            prefix_rate=0.5,
        )
        marked = [r for r in records if "prefix_group" in r]
        assert 0 < len(marked) < len(records)
        for r in marked:
            assert r["prefix_group"] == f"{r['tenant']}/sys"
        # deterministic across calls
        again = synthesize_requests(
            seed=5, rps=50, duration_s=4.0, tenants=2,
            prefix_rate=0.5,
        )
        assert records == again

    def test_cli_flags_round_trip(self, tmp_path):
        from tpuslo.cli import loadgen

        out = tmp_path / "reqs.jsonl"
        rc = loadgen.main([
            "--arrival", "burst", "--tenants", "3",
            "--tenant-mix", "60,30,10", "--prefix-rate", "0.4",
            "--rps", "20", "--duration-s", "2",
            "--output", str(out),
        ])
        assert rc == 0
        records = [
            json.loads(line) for line in out.read_text().splitlines()
        ]
        assert len(records) == 40
        tenants = {r["tenant"] for r in records}
        assert tenants <= {"tenant-00", "tenant-01", "tenant-02"}
        assert any("prefix_group" in r for r in records)
