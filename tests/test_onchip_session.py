"""Rehearsal-mode regression for the on-chip e2e incident session.

``scripts/demo/e2e_onchip_session.py`` is the round-5 chip-window
deliverable (live serve + recompile storm -> ring -> agent -> matcher
-> attributor).  The tunnel can stay down for most of a session, so
the script must be runnable-at-a-moment's-notice; this test keeps the
whole plumbing green on the CPU backend (the xprof verdicts bind only
on a real backend — see the script's verdict table).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # spawns an agent + trains nothing, ~60s

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_rehearsal_passes_all_verdicts(tmp_path):
    proc = subprocess.run(
        [
            sys.executable, "scripts/demo/e2e_onchip_session.py",
            "--rehearse", "--out", str(tmp_path / "bundle"),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    session = json.loads(
        (tmp_path / "bundle" / "session.json").read_text()
    )
    assert session["pass"] is True
    assert session["rehearsal"] is True
    assert session["agent_compile_events"] >= 1
    attribution = json.loads(
        (tmp_path / "bundle" / "attribution.json").read_text()
    )
    assert attribution["predicted_domain"] == "xla_compile"
    assert attribution["from_agent_emitted_events"] is True
    readme = (tmp_path / "bundle" / "README.md").read_text()
    assert "REHEARSAL RUN" in readme  # a CPU bundle can't pose as evidence
