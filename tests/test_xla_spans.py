"""xprof span-source tests.

The trace-viewer document fixture mirrors what ``jax.profiler.trace``
emits on a TPU backend (device process with "XLA Modules"/"XLA Ops"
lanes; module events named ``<module>(<fingerprint>)`` with a
``run_id`` arg).  The CPU backend used in CI emits no device lanes, so
parsing is unit-tested against the fixture and ``capture`` is driven as
a smoke test only.
"""

import gzip
import json

import pytest

from tpuslo.correlation import SpanRef, SignalRef, match
from tpuslo.otel.xla_spans import (
    MODULES_LANE,
    OPS_LANE,
    capture,
    find_trace_files,
    load_latest_trace,
    load_latest_trace_by_host,
    load_trace_file,
    parse_trace_events,
)

ANCHOR_NS = 1_700_000_000_000_000_000


def trace_doc():
    return {
        "displayTimeUnit": "ns",
        "metadata": {"highres-ticks": True},
        "traceEvents": [
            {"ph": "M", "pid": 3, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 3, "tid": 2, "name": "thread_name",
             "args": {"name": "XLA Modules"}},
            {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "M", "pid": 701, "tid": 9, "name": "thread_name",
             "args": {"name": "python"}},
            # Two launches of the same program, one of another.
            {"ph": "X", "pid": 3, "tid": 2, "ts": 100.0, "dur": 5.0,
             "name": "jit_train_step(1111)", "args": {"run_id": "42"}},
            {"ph": "X", "pid": 3, "tid": 2, "ts": 300.0, "dur": 5.5,
             "name": "jit_train_step(1111)", "args": {"run_id": "43"}},
            {"ph": "X", "pid": 3, "tid": 2, "ts": 200.0, "dur": 1.0,
             "name": "jit_prefill(2222)", "args": {"run_id": "7"}},
            # Op-level event (excluded unless include_ops).
            {"ph": "X", "pid": 3, "tid": 3, "ts": 101.0, "dur": 4.0,
             "name": "fusion.1", "args": {"hlo_category": "fusion"}},
            # Host-side python event: never a span.
            {"ph": "X", "pid": 701, "tid": 9, "ts": 90.0, "dur": 50.0,
             "name": "PjitFunction(train_step)"},
        ],
    }


class TestParse:
    def test_module_spans_with_identity(self):
        spans = parse_trace_events(trace_doc())
        assert [s.launch_id for s in spans] == [42, 7, 43]  # time-sorted
        first = spans[0]
        assert first.module_name == "jit_train_step"
        assert first.program_id == "1111"
        assert first.lane == MODULES_LANE
        assert first.duration_us == 5.0

    def test_ops_included_on_request_only(self):
        assert all(
            s.lane == MODULES_LANE for s in parse_trace_events(trace_doc())
        )
        with_ops = parse_trace_events(trace_doc(), include_ops=True)
        ops = [s for s in with_ops if s.lane == OPS_LANE]
        assert len(ops) == 1 and ops[0].hlo_category == "fusion"

    def test_thread_metadata_without_args_is_skipped(self):
        doc = trace_doc()
        doc["traceEvents"].insert(
            0, {"ph": "M", "pid": 9, "tid": 9, "name": "thread_name"}
        )
        assert len(parse_trace_events(doc)) == 3  # parse survives

    def test_unparseable_module_name_keeps_raw_name(self):
        doc = trace_doc()
        doc["traceEvents"].append(
            {"ph": "X", "pid": 3, "tid": 2, "ts": 400.0, "dur": 1.0,
             "name": "weird-module", "args": {}}
        )
        span = [s for s in parse_trace_events(doc) if s.name == "weird-module"][0]
        assert span.module_name == "weird-module"
        assert span.program_id == "" and span.launch_id == -1

    def test_span_ref_feeds_xla_launch_tier(self):
        """The whole point: an xprof span joins a probe signal on the
        exact-identity xla_launch tier with no instrumentation."""
        span = parse_trace_events(trace_doc())[0]
        ref_dict = span.to_span_ref_dict(
            ANCHOR_NS, service="rag-demo", node="host-0"
        )
        span_ref = SpanRef.from_dict(ref_dict)
        signal = SignalRef.from_dict(
            {
                "signal": "ici_collective_latency_ms",
                "timestamp": ref_dict["timestamp"],
                "program_id": "1111",
                "launch_id": 42,
                "value": 3.0,
            }
        )
        decision = match(span_ref, signal)
        assert decision.matched and decision.tier == "xla_launch"
        assert decision.confidence == 0.95

    def test_anchor_offsets_timestamp_by_trace_us(self):
        spans = parse_trace_events(trace_doc())
        a = SpanRef.from_dict(spans[0].to_span_ref_dict(ANCHOR_NS))
        b = SpanRef.from_dict(spans[2].to_span_ref_dict(ANCHOR_NS))
        delta_ms = (b.timestamp - a.timestamp).total_seconds() * 1000.0
        assert delta_ms == pytest.approx(0.2, abs=1e-6)  # 300us - 100us


def trace_doc_with_collectives(base_latency_us=400.0, straggler=False):
    """Two module launches, each containing an all-reduce whose
    duration encodes the collective wait (punctual hosts wait longer)."""
    wait = 50.0 if straggler else base_latency_us
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
    ]
    for launch in range(2):
        t0 = 1000.0 * launch
        events.append(
            {"ph": "X", "pid": 3, "tid": 2, "ts": t0, "dur": 900.0,
             "name": "jit_train_step(777)", "args": {"run_id": str(launch)}}
        )
        events.append(  # sync all-reduce inside the module
            {"ph": "X", "pid": 3, "tid": 3, "ts": t0 + 10.0, "dur": wait,
             "name": "all-reduce.3", "args": {"hlo_category": "all-reduce"}}
        )
        events.append(  # async pair caught by name fallback
            {"ph": "X", "pid": 3, "tid": 3, "ts": t0 + 200.0, "dur": 20.0,
             "name": "all-gather-start.1", "args": {"hlo_category": "fusion"}}
        )
        events.append(  # non-collective op: never extracted
            {"ph": "X", "pid": 3, "tid": 3, "ts": t0 + 300.0, "dur": 99.0,
             "name": "fusion.7", "args": {"hlo_category": "fusion"}}
        )
    # A collective outside any module span: skipped.
    events.append(
        {"ph": "X", "pid": 3, "tid": 3, "ts": 5000.0, "dur": 11.0,
         "name": "all-reduce.9", "args": {"hlo_category": "all-reduce"}}
    )
    return events and {"traceEvents": events}


class TestCollectiveExtraction:
    def spans(self, straggler=False):
        from tpuslo.otel.xla_spans import parse_trace_events

        return parse_trace_events(
            trace_doc_with_collectives(straggler=straggler), include_ops=True
        )

    def test_per_launch_totals_with_identity(self):
        from tpuslo.otel.xla_spans import extract_collective_signals

        events = extract_collective_signals(
            self.spans(), ANCHOR_NS, node="host-0", slice_id="s0", host_index=0
        )
        assert len(events) == 2  # one per module launch
        for launch, ev in enumerate(events):
            assert ev["signal"] == "ici_collective_latency_ms"
            assert ev["tpu"]["launch_id"] == launch
            assert ev["tpu"]["program_id"] == "777"
            assert ev["value"] == pytest.approx(0.42)  # (400+20)us in ms
            assert ev["tpu"]["slice_id"] == "s0"

    def test_events_validate_against_probe_schema(self):
        from tpuslo import schema
        from tpuslo.otel.xla_spans import extract_collective_signals

        for ev in extract_collective_signals(
            self.spans(), ANCHOR_NS, node="host-0"
        ):
            schema.validate(ev, schema.SCHEMA_PROBE_EVENT)

    def test_orphan_collective_outside_modules_skipped(self):
        from tpuslo.otel.xla_spans import extract_collective_signals

        events = extract_collective_signals(self.spans(), ANCHOR_NS)
        # Only two events (per launch); the ts=5000 orphan contributed
        # to neither.
        assert len(events) == 2
        assert sum(e["value"] for e in events) == pytest.approx(0.84)

    def test_multi_device_host_keeps_per_chip_containment(self):
        """Two chips run the same launch concurrently: ops must pair
        with their own device's module span (no double-counting), and
        chips of one host aggregate into one event per launch."""
        from tpuslo.otel.xla_spans import (
            extract_collective_signals,
            parse_trace_events,
        )

        doc = {"traceEvents": []}
        for pid in (3, 4):  # two devices, overlapping in time
            doc["traceEvents"] += [
                {"ph": "M", "pid": pid, "tid": 2, "name": "thread_name",
                 "args": {"name": "XLA Modules"}},
                {"ph": "M", "pid": pid, "tid": 3, "name": "thread_name",
                 "args": {"name": "XLA Ops"}},
                {"ph": "X", "pid": pid, "tid": 2, "ts": 100.0, "dur": 500.0,
                 "name": "jit_step(9)", "args": {"run_id": "0"}},
                {"ph": "X", "pid": pid, "tid": 3, "ts": 150.0, "dur": 100.0,
                 "name": "all-reduce.1",
                 "args": {"hlo_category": "all-reduce"}},
            ]
        spans = parse_trace_events(doc, include_ops=True)
        events = extract_collective_signals(spans, ANCHOR_NS, node="h")
        assert len(events) == 1  # one launch, both chips aggregated
        assert events[0]["value"] == pytest.approx(0.2)  # 100us x 2 chips
        assert events[0]["tpu"]["launch_id"] == 0

    def test_anonymous_launch_ops_sum_into_one_event(self):
        """Modules without run_id still aggregate all their collective
        ops into a single per-launch event."""
        from tpuslo.otel.xla_spans import (
            extract_collective_signals,
            parse_trace_events,
        )

        doc = {"traceEvents": [
            {"ph": "M", "pid": 3, "tid": 2, "name": "thread_name",
             "args": {"name": "XLA Modules"}},
            {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "X", "pid": 3, "tid": 2, "ts": 0.0, "dur": 1000.0,
             "name": "jit_anon(5)", "args": {}},  # no run_id
            {"ph": "X", "pid": 3, "tid": 3, "ts": 10.0, "dur": 5000.0 / 1000,
             "name": "all-reduce.1", "args": {"hlo_category": "all-reduce"}},
            {"ph": "X", "pid": 3, "tid": 3, "ts": 50.0, "dur": 5000.0 / 1000,
             "name": "all-reduce.2", "args": {"hlo_category": "all-reduce"}},
        ]}
        spans = parse_trace_events(doc, include_ops=True)
        events = extract_collective_signals(spans, ANCHOR_NS)
        assert len(events) == 1
        assert events[0]["value"] == pytest.approx(0.01)  # 2 x 5us in ms

    def test_xprof_to_slicecorr_end_to_end(self):
        """Real pipeline shape: per-host xprof traces -> collective
        signals -> SliceJoiner names the straggler host."""
        from tpuslo.correlation.multihost import SliceJoiner
        from tpuslo.otel.xla_spans import extract_collective_signals_by_host

        by_host = {
            "vm-0": self.spans(),
            "vm-1": self.spans(),
            "vm-2": self.spans(straggler=True),  # enters late, waits less
            "vm-3": self.spans(),
        }
        events = extract_collective_signals_by_host(
            by_host, ANCHOR_NS, slice_id="slice-0"
        )
        joiner = SliceJoiner(expected_hosts=4, skew_floor_ms=0.1)
        joiner.add_all(events)
        incidents = joiner.incidents()
        assert len(incidents) == 2  # both launches skewed
        assert all(i.straggler_host == 2 for i in incidents)
        assert all(i.cause == "compute_straggler" for i in incidents)


class TestFiles:
    def write_run(self, tmp_path, run, hosts):
        d = tmp_path / "plugins" / "profile" / run
        d.mkdir(parents=True)
        for host in hosts:
            with gzip.open(d / f"{host}.trace.json.gz", "wt") as fh:
                json.dump(trace_doc(), fh)

    def test_newest_run_first_and_multi_host(self, tmp_path):
        self.write_run(tmp_path, "2026_01_01_00_00_00", ["hostA"])
        self.write_run(tmp_path, "2026_02_02_00_00_00", ["hostA", "hostB"])
        files = find_trace_files(str(tmp_path))
        assert len(files) == 3
        assert "2026_02_02_00_00_00" in files[0]
        spans = load_latest_trace(str(tmp_path))
        # Only the newest run, both host files: 3 module spans each.
        assert len(spans) == 6

    def test_load_single_file(self, tmp_path):
        self.write_run(tmp_path, "r", ["vm"])
        path = find_trace_files(str(tmp_path))[0]
        assert len(load_trace_file(path)) == 3

    def test_empty_dir(self, tmp_path):
        assert find_trace_files(str(tmp_path)) == []
        assert load_latest_trace(str(tmp_path)) == []
        assert load_latest_trace_by_host(str(tmp_path)) == {}

    def test_by_host_grouping_preserves_run_id_scope(self, tmp_path):
        """Per-host grouping: run_id counters are per host file, so the
        exact-identity join must never mix hosts."""
        self.write_run(tmp_path, "r", ["hostA", "hostB"])
        by_host = load_latest_trace_by_host(str(tmp_path))
        assert set(by_host) == {"hostA", "hostB"}
        assert all(len(spans) == 3 for spans in by_host.values())

    def test_dotted_hostnames_stay_distinct(self, tmp_path):
        self.write_run(
            tmp_path,
            "r",
            ["worker.zone-a.internal", "worker.zone-b.internal"],
        )
        by_host = load_latest_trace_by_host(str(tmp_path))
        assert set(by_host) == {
            "worker.zone-a.internal",
            "worker.zone-b.internal",
        }
        assert all(len(spans) == 3 for spans in by_host.values())

    def test_span_refs_by_host_labels_each_host(self, tmp_path):
        self.write_run(tmp_path, "r", ["hostA", "hostB"])
        cap = capture(str(tmp_path))
        cap.anchor_unix_ns = ANCHOR_NS
        cap.spans_by_host = load_latest_trace_by_host(str(tmp_path))
        refs = cap.span_refs_by_host(
            {
                "hostA": {"node": "tpu-vm-0", "host_index": 0},
                "hostB": {"node": "tpu-vm-1", "host_index": 1},
            },
            service="rag",
            slice_id="slice-0",
        )
        assert refs["hostA"][0]["node"] == "tpu-vm-0"
        assert refs["hostB"][0]["host_index"] == 1
        assert refs["hostB"][0]["slice_id"] == "slice-0"

    def test_span_refs_rejects_ambiguous_multi_host_labeling(self, tmp_path):
        self.write_run(tmp_path, "r", ["hostA", "hostB"])
        cap = capture(str(tmp_path))
        cap.spans_by_host = load_latest_trace_by_host(str(tmp_path))
        with pytest.raises(ValueError, match="span_refs_by_host"):
            cap.span_refs(node="tpu-vm-0")


class TestCaptureSmoke:
    def test_capture_profiles_a_jit_region(self, tmp_path):
        """CPU backend emits no device lanes, so this asserts the
        plumbing (anchor recorded, trace written, parse succeeds) —
        module-span recovery is exercised by the fixture tests above
        and on real TPU by the serving benchmark."""
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: (x * 2).sum())
        f(jnp.ones((8,))).block_until_ready()
        with capture(str(tmp_path)) as cap:
            f(jnp.ones((8,))).block_until_ready()
        assert cap.anchor_unix_ns > 0
        assert find_trace_files(str(tmp_path))
        assert isinstance(cap.spans, list)
        assert cap.span_refs(service="s") == [
            r for r in cap.span_refs(service="s")
        ]


class TestLaunchMatchBreakdown:
    """Every unmatched launch span gets an explained reason (the r02
    report's 0.556 join rate was unexplained — VERDICT weak #2)."""

    def _spans(self):
        from tpuslo.otel.xla_spans import parse_trace_events

        return parse_trace_events(trace_doc(), include_ops=True)

    def test_classifies_launches_without_ops(self):
        from tpuslo.otel.xla_spans import launch_match_breakdown

        report = launch_match_breakdown(self._spans())
        # fusion.1 at ts=101 falls inside launch run_id=42 only; the
        # other two launches have no contained ops.
        assert report["launches"] == 3
        assert report["launches_with_ops"] == 1
        assert report["unmatched_count"] == 2
        assert report["reasons"] == {"no_contained_ops": 2}
        # Of the launches WITH ops, all carry exact identity -> the
        # xla_launch tier can serve 100% of its real denominator.
        assert report["substantive_join_rate"] == 1.0
        unmatched_ids = {u["launch_id"] for u in report["unmatched"]}
        assert unmatched_ids == {43, 7}

    def test_no_ops_lane_reason(self):
        from tpuslo.otel.xla_spans import (
            launch_match_breakdown,
            parse_trace_events,
        )

        spans = parse_trace_events(trace_doc(), include_ops=False)
        report = launch_match_breakdown(spans)
        assert report["launches"] == 3
        assert report["launches_with_ops"] == 0
        assert report["reasons"] == {"no_ops_lane": 3}

    def test_empty_trace(self):
        from tpuslo.otel.xla_spans import launch_match_breakdown

        report = launch_match_breakdown([])
        assert report["launches"] == 0
        assert report["substantive_join_rate"] == 0.0
