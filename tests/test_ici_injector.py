"""Real ICI-domain fault injection (VERDICT r02 next-round #3).

The tpu_ici domain was the one fault domain with synthetic-only
evidence.  These tests drive both measured mechanisms end-to-end:
the delayed-host barrier straggler (SliceJoiner must name the delayed
host from real measured waits) and the device-contention collective
degradation (attributor must name tpu_ici from the real signal).
"""

from __future__ import annotations

import subprocess
import sys
import time

import pytest

from tpuslo.chaos import run_straggler_injection


def test_straggler_attributed_from_real_waits():
    report = run_straggler_injection(
        n_hosts=3, launches=5, delay_ms=120.0, delayed_host=1,
        in_process=True,
    )
    assert report["real"] is True
    assert report["events_measured"] == 15
    assert report["correct_attributions"] == 5
    assert report["top_confidence"] >= 0.7
    for incident in report["incidents"]:
        assert incident["straggler_host"] == 1
        assert incident["cause"] == "compute_straggler"
        # Real physics: the delayed host sails through the barrier, the
        # others wait ~delay_ms.
        lat = incident["host_latencies_ms"]
        assert lat["1"] < 20.0
        assert lat["0"] > 100.0 and lat["2"] > 100.0


def test_straggler_different_delayed_host():
    report = run_straggler_injection(
        n_hosts=2, launches=3, delay_ms=100.0, delayed_host=0,
        in_process=True,
    )
    assert report["correct_attributions"] == 3
    assert all(i["straggler_host"] == 0 for i in report["incidents"])


def test_straggler_subprocess_mode():
    """The deployment shape: one OS process per host, events over
    stdout JSONL, joined by the parent.

    Interpreter startup skew between the host processes can exceed
    the injected delay on a loaded machine and flip the first
    launch's attribution — real noise, not a product bug — so poll
    with a deadline (the TestBlackholeProxy pattern) instead of
    asserting a single run instantly.
    """
    deadline = time.monotonic() + 90.0
    report = None
    while report is None or time.monotonic() < deadline:
        report = run_straggler_injection(
            n_hosts=2, launches=2, delay_ms=80.0, delayed_host=1,
            in_process=False,
        )
        if (
            report["correct_attributions"] == 2
            and report["top_confidence"] >= 0.7
        ):
            break
    assert report["correct_attributions"] == 2
    assert report["top_confidence"] >= 0.7


@pytest.mark.slow
def test_contention_degrades_measured_collectives():
    import jax

    if jax.default_backend() != "cpu":  # pragma: no cover - CI is cpu
        pytest.skip("contention smoke runs on the CPU mesh")
    from tpuslo.chaos import contention_injection

    report = contention_injection(reps=5, payload_kb=256, storm_size=512)
    assert report["real"] is True
    assert report["mechanism"] == "device_contention"
    assert report["degradation"] > 1.0
    assert report["events"], "measured probe events must be emitted"
    assert report["attribution"]["predicted_domain"] == "tpu_ici"
    assert report["attribution"]["from_real_signals"] is True


def test_injector_script_help():
    """The CLI wrapper must at least parse (the matrix calls it)."""
    proc = subprocess.run(
        [sys.executable, "scripts/chaos/injectors/ici_contention.py", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    assert "straggler" in proc.stdout


class TestBackendGuard:
    """Chaos injectors must fail fast (not hang in jax.devices()) when
    the tunneled backend's relay is down — the fault matrix wedged
    inside hbm_pressure.py on exactly this before the guard."""

    def test_guard_only_applies_to_tunneled_backend(self, monkeypatch):
        from tpuslo.chaos.backend_guard import tunneled_backend_unreachable

        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        assert tunneled_backend_unreachable() is False
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        assert tunneled_backend_unreachable() is False

    def test_jax_injectors_fail_fast_when_unreachable(self, tmp_path):
        import json as _json
        import os
        import time

        # The force flag makes the guard deterministic regardless of
        # what happens to be listening on the relay ports locally.
        env = {**os.environ, "TPUSLO_FORCE_BACKEND_UNREACHABLE": "1"}
        for script in ("hbm_pressure.py", "xla_recompile_storm.py"):
            report_path = tmp_path / f"{script}.report.json"
            t0 = time.perf_counter()
            proc = subprocess.run(
                [
                    sys.executable, f"scripts/chaos/injectors/{script}",
                    "--report", str(report_path),
                ],
                capture_output=True, text=True, timeout=120, env=env,
            )
            elapsed = time.perf_counter() - t0
            assert proc.returncode == 2, proc.stderr
            report = _json.loads(proc.stdout.strip().splitlines()[-1])
            assert report["real"] is False
            assert "unreachable" in report["reason"]
            # The machine-readable reason survives into the matrix's
            # per-scenario report file too.
            assert _json.loads(report_path.read_text())["real"] is False
            assert elapsed < 60.0  # failed fast, did not hang


def test_recv_exact_reassembles_short_reads():
    """TCP may deliver any prefix per recv(); the barrier protocol must
    reassemble the full 8-byte message (a short read used to make the
    coordinator bail early, wedging every host at the rendezvous)."""
    import socket
    import threading

    from tpuslo.chaos.ici_contention import _MSG, _recv_exact

    a, b = socket.socketpair()
    payload = _MSG.pack(3, 7)

    def dribble():
        for i in range(len(payload)):
            a.sendall(payload[i:i + 1])
        a.close()

    t = threading.Thread(target=dribble)
    t.start()
    raw = _recv_exact(b, _MSG.size)
    assert raw == payload
    assert _MSG.unpack(raw) == (3, 7)
    # EOF mid-message reports None, not a partial buffer.
    assert _recv_exact(b, _MSG.size) is None
    t.join()
    b.close()
