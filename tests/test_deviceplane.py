"""Device-plane truth (ISSUE 14): ledger, roofline, synthetic lane,
dispatch ledger, front-door tracing, and the sweep gate.

The load-bearing invariants:

* the five ledger buckets sum EXACTLY to total device time, on clean,
  preempted, multi-device, and adversarially overlapping timelines;
* every join tier recovers exactly the launches the seeded truth says
  it should (identity = non-split steps, lane_window = split steps,
  compile_event >= warmups, frame catches compile-less helpers), and
  orphan glue is neither hidden nor invented;
* ``launch_match_breakdown`` — now ledger-fed — classifies every
  unmatched-launch reason (anonymous_launch, no-op launches,
  lane-split ops, no_ops_lane) and serves BOTH join rates from one
  source;
* roofline verdicts are schema-legal and land on the correct side of
  the roof for known cost models.
"""

from __future__ import annotations

import json

import pytest

from tpuslo.deviceplane.dispatch import DispatchLedger
from tpuslo.deviceplane.ledger import (
    BUCKET_COMPILE,
    BUCKET_HELPER,
    BUCKET_IDLE_GAP,
    BUCKET_JOINED,
    BUCKET_UNEXPLAINED,
    TIER_COMPILE_EVENT,
    TIER_FRAME,
    TIER_IDENTITY,
    TIER_LANE_WINDOW,
    TIER_NONE,
    build_ledger,
    idle_gap_probe_values,
)
from tpuslo.deviceplane.roofline import (
    VERDICT_COMPUTE_BOUND,
    VERDICT_MEMORY_BOUND,
    attach_roofline,
    decode_step_cost,
    roofline_verdict,
    verdict_from_ledger,
)
from tpuslo.deviceplane.synthetic import (
    STEP_FINGERPRINT,
    synthesize_xprof_trace,
)
from tpuslo.otel.xla_spans import (
    MODULES_LANE,
    OPS_LANE,
    XLASpan,
    launch_match_breakdown,
    parse_trace_events,
)


def make_ledger(seed=1337, compile_events=True, **kw):
    doc, compiles, truth = synthesize_xprof_trace(seed=seed, **kw)
    spans = parse_trace_events(doc, include_ops=True)
    return build_ledger(spans, compiles if compile_events else ()), truth


# ---- ledger bucket accounting ------------------------------------------


class TestLedgerBuckets:
    @pytest.mark.parametrize("seed", [1, 7, 1337])
    def test_buckets_sum_to_total_device_time(self, seed):
        ledger, truth = make_ledger(seed=seed)
        assert ledger.total_us > 0
        assert ledger.bucket_sum_us == pytest.approx(
            ledger.total_us, rel=1e-9
        )
        assert ledger.total_us == pytest.approx(
            truth["window_us"], rel=1e-6
        )

    def test_idle_gap_matches_truth(self):
        ledger, truth = make_ledger(seed=5)
        assert ledger.buckets_us[BUCKET_IDLE_GAP] == pytest.approx(
            truth["idle_us"], rel=1e-6
        )

    def test_preemption_gap_lands_in_idle_bucket(self):
        steady, _ = make_ledger(seed=3)
        preempted, _ = make_ledger(seed=3, preemption_gap_ms=80.0)
        delta = (
            preempted.buckets_us[BUCKET_IDLE_GAP]
            - steady.buckets_us[BUCKET_IDLE_GAP]
        )
        assert delta == pytest.approx(80_000.0, rel=1e-6)
        assert preempted.idle_gap_ms() > 80.0

    def test_multi_device_totals_are_per_device_sums(self):
        one, _ = make_ledger(seed=11, devices=1)
        two, _ = make_ledger(seed=11, devices=2)
        assert len(two.devices) == 2
        assert two.bucket_sum_us == pytest.approx(two.total_us, rel=1e-9)
        assert len(two.launches) == 2 * len(one.launches)

    def test_overlapping_launches_never_double_count(self):
        # Two overlapping module launches on one device: the clip rule
        # must keep the bucket sum equal to the merged window.
        spans = [
            XLASpan(
                name="jit_a(1)", module_name="jit_a", program_id="1",
                launch_id=1, start_us=0.0, duration_us=100.0,
                device_pid=1, lane=MODULES_LANE,
            ),
            XLASpan(
                name="jit_b(2)", module_name="jit_b", program_id="2",
                launch_id=1, start_us=60.0, duration_us=100.0,
                device_pid=1, lane=MODULES_LANE,
            ),
            XLASpan(
                name="op", start_us=10.0, duration_us=5.0,
                device_pid=1, lane=OPS_LANE,
            ),
            XLASpan(
                name="op2", start_us=70.0, duration_us=5.0,
                device_pid=1, lane=OPS_LANE,
            ),
        ]
        ledger = build_ledger(spans)
        assert ledger.total_us == pytest.approx(160.0)
        assert ledger.bucket_sum_us == pytest.approx(160.0)
        # The second launch owns only its non-overlapped 60us.
        owned = {r.module_name: r.owned_us for r in ledger.launches}
        assert owned["jit_a"] == pytest.approx(100.0)
        assert owned["jit_b"] == pytest.approx(60.0)

    def test_empty_spans_gives_empty_ledger(self):
        ledger = build_ledger([])
        assert ledger.total_us == 0.0
        assert ledger.substantive_join_rate == 0.0
        assert ledger.unexplained_share == 0.0

    def test_idle_gap_probe_values(self):
        ledger, truth = make_ledger(seed=2)
        values = idle_gap_probe_values(ledger)
        assert values["device_idle_gap_ms"] == pytest.approx(
            truth["idle_us"] / 1000.0, rel=1e-3
        )


# ---- join tiers ---------------------------------------------------------


class TestJoinTiers:
    def test_tier_counts_match_truth(self):
        ledger, truth = make_ledger(seed=1337)
        tiers = ledger.tier_counts
        assert tiers[TIER_IDENTITY] == (
            truth["steps"] - truth["lane_split_steps"]
        )
        assert tiers[TIER_LANE_WINDOW] == truth["lane_split_steps"]
        # Compile tier: the anonymous warmups plus the name-prefixed
        # helpers (the frame tier is their backstop when compile events
        # are missing).
        assert tiers[TIER_COMPILE_EVENT] >= truth["warmups"]

    def test_substantive_rate_hits_gate_and_raw_stays_honest(self):
        ledger, truth = make_ledger(seed=1337)
        assert ledger.substantive_join_rate >= 0.9
        # Raw exact-identity rate over ALL launches stays low — the
        # 0.556-style number is reported, not gated.
        assert ledger.raw_join_rate < ledger.substantive_join_rate
        assert ledger.unexplained_share <= 0.1

    def test_orphan_helpers_stay_unexplained(self):
        ledger, truth = make_ledger(seed=1337)
        unexplained = [
            r for r in ledger.launches if r.bucket == BUCKET_UNEXPLAINED
        ]
        assert len(unexplained) == truth["orphan_helpers"]
        assert all(r.tier == TIER_NONE for r in unexplained)

    def test_frame_tier_catches_helpers_without_compile_events(self):
        # Without compile events the name-prefix tie is gone: helpers
        # inside a step frame must fall to the frame tier (bucket
        # helper), and the ops-bearing anonymous warmup — with no
        # compilation to own it — must land in unexplained.
        ledger, truth = make_ledger(seed=1337, compile_events=False)
        tiers = ledger.tier_counts
        assert tiers.get(TIER_FRAME, 0) == truth["helpers"]
        warmups = [
            r
            for r in ledger.launches
            if r.launch_id < 0 and r.ops_count > 0
        ]
        assert warmups and all(
            r.bucket == BUCKET_UNEXPLAINED for r in warmups
        )
        assert ledger.bucket_sum_us == pytest.approx(
            ledger.total_us, rel=1e-9
        )

    def test_lane_split_steps_recover_their_ops(self):
        ledger, truth = make_ledger(seed=1337)
        lane = [
            r for r in ledger.launches if r.tier == TIER_LANE_WINDOW
        ]
        assert len(lane) == truth["lane_split_steps"]
        assert all(r.ops_source == "lane" and r.ops_count > 0 for r in lane)
        assert all(r.bucket == BUCKET_JOINED for r in lane)
        assert ledger.orphan_ops_unclaimed == 0

    def test_compile_tier_buckets(self):
        ledger, _ = make_ledger(seed=1337)
        for rec in ledger.launches:
            if rec.tier == TIER_COMPILE_EVENT:
                assert rec.bucket in (BUCKET_COMPILE, BUCKET_HELPER)
                # Ops-bearing anon -> compile; dispatch-only -> helper.
                want = BUCKET_COMPILE if rec.ops_count else BUCKET_HELPER
                assert rec.bucket == want

    def test_synthetic_trace_deterministic(self):
        a = synthesize_xprof_trace(seed=9)
        b = synthesize_xprof_trace(seed=9)
        assert a == b
        c = synthesize_xprof_trace(seed=10)
        assert c != a


# ---- launch_match_breakdown (ledger-fed) --------------------------------


class TestBreakdown:
    def test_reason_classes_cover_the_pathologies(self):
        doc, compiles, truth = synthesize_xprof_trace(seed=1337)
        spans = parse_trace_events(doc, include_ops=True)
        breakdown = launch_match_breakdown(spans, compiles)
        reasons = breakdown["reasons"]
        # Anonymous launches (the warmup) — exact joins can't see them.
        assert reasons.get("anonymous_launch", 0) >= truth["warmups"]
        # No-op (dispatch-only) launches: helpers + orphan glue.
        assert reasons.get("no_contained_ops", 0) == (
            truth["helpers"] + truth["orphan_helpers"]
        )
        # Lane-split launches JOINED via the lane_window tier: not in
        # reasons (they are not unmatched — their recovery counts live
        # in the embedded ledger's tier table).
        assert reasons.get("ops_on_split_lane", 0) == 0
        assert breakdown["ledger"]["tier_counts"]["lane_window"] == (
            truth["lane_split_steps"]
        )
        # Reasons now reconcile with the unmatched population plus the
        # anonymous ops-bearing launches (the historical convention).
        assert sum(reasons.values()) == (
            breakdown["unmatched_count"] + truth["warmups"]
        )

    def test_no_ops_lane_when_capture_has_no_ops(self):
        doc, compiles, truth = synthesize_xprof_trace(
            seed=4, lane_split_every=0, orphan_helpers=0,
            warmup_launches=0, helpers_per_step=0,
        )
        spans = parse_trace_events(doc, include_ops=False)
        breakdown = launch_match_breakdown(spans, compiles)
        assert breakdown["launches_with_ops"] == 0
        assert breakdown["reasons"] == {
            "no_ops_lane": breakdown["launches"]
        }
        assert breakdown["substantive_join_rate"] == 0.0

    def test_single_source_for_both_rates(self):
        doc, compiles, _ = synthesize_xprof_trace(seed=1337)
        spans = parse_trace_events(doc, include_ops=True)
        breakdown = launch_match_breakdown(spans, compiles)
        ledger = build_ledger(spans, compiles)
        assert breakdown["raw_join_rate"] == pytest.approx(
            ledger.raw_join_rate, abs=5e-5
        )
        assert breakdown["ledger_substantive_join_rate"] == pytest.approx(
            ledger.substantive_join_rate, abs=5e-5
        )
        assert breakdown["substantive_join_rate"] == pytest.approx(
            ledger.exact_substantive_join_rate, abs=5e-5
        )
        # The embedded ledger block carries the bucket accounting.
        assert breakdown["ledger"]["bucket_sum_ms"] == pytest.approx(
            breakdown["ledger"]["total_device_time_ms"]
        )

    def test_unmatched_examples_stay_bounded_and_typed(self):
        doc, compiles, _ = synthesize_xprof_trace(seed=1337)
        spans = parse_trace_events(doc, include_ops=True)
        breakdown = launch_match_breakdown(spans, compiles)
        assert len(breakdown["unmatched"]) <= 24
        for entry in breakdown["unmatched"]:
            assert {"module", "reason", "tier", "bucket"} <= set(entry)


# ---- roofline -----------------------------------------------------------


class TestRoofline:
    def test_memory_vs_compute_bound(self):
        # 3.4 GB in 12 ms at tiny FLOPs -> memory bound.
        mem = roofline_verdict(12.0, 3.4e9, 2.5e9 * 8)
        assert mem["verdict"] == VERDICT_MEMORY_BOUND
        assert mem["hbm_bw_pct"] > mem["mfu_pct"]
        # Heavy FLOPs, few bytes -> compute bound.
        comp = roofline_verdict(10.0, 1e8, 1.5e12)
        assert comp["verdict"] == VERDICT_COMPUTE_BOUND
        assert comp["mfu_pct"] > comp["hbm_bw_pct"]

    def test_decode_step_cost_accounting(self):
        step_bytes, step_flops = decode_step_cost(
            1e9, 2e8, batch=8, param_bytes=2.0
        )
        assert step_bytes == pytest.approx(2.2e9)
        assert step_flops == pytest.approx(2.0 * 1e9 * 8)

    def test_verdict_from_ledger_uses_program_mean(self):
        ledger, _ = make_ledger(seed=1337)
        verdict = verdict_from_ledger(
            ledger, 3.4e9, 2.0e10, program_id=STEP_FINGERPRINT
        )
        assert verdict is not None
        assert verdict["launches"] == ledger.tier_counts[TIER_IDENTITY] + (
            ledger.tier_counts[TIER_LANE_WINDOW]
        )
        assert verdict["launch"] == STEP_FINGERPRINT

    def test_verdict_from_ledger_refuses_without_joins(self):
        assert verdict_from_ledger(build_ledger([]), 1e9, 1e9) is None

    def test_attach_roofline_is_schema_legal(self):
        from datetime import datetime, timezone

        from tpuslo.attribution.mapper import build_attribution
        from tpuslo.faultreplay import generate_fault_samples
        from tpuslo.schema import SCHEMA_INCIDENT_ATTRIBUTION, validate

        sample = generate_fault_samples(
            "preemption_eviction", 1,
            datetime(2026, 8, 1, tzinfo=timezone.utc),
        )[0]
        attribution = build_attribution(sample)
        verdict = roofline_verdict(12.0, 3.4e9, 2.0e10)
        attach_roofline(attribution, verdict)
        payload = attribution.to_dict()
        assert payload["roofline"]["verdict"] == VERDICT_MEMORY_BOUND
        validate(payload, SCHEMA_INCIDENT_ATTRIBUTION)

    def test_contract_rejects_malformed_verdict(self):
        from datetime import datetime, timezone

        from tpuslo.attribution.mapper import build_attribution
        from tpuslo.faultreplay import generate_fault_samples
        from tpuslo.schema import SCHEMA_INCIDENT_ATTRIBUTION, validate

        sample = generate_fault_samples(
            "hbm_pressure", 1, datetime(2026, 8, 1, tzinfo=timezone.utc)
        )[0]
        attribution = build_attribution(sample)
        attach_roofline(attribution, {"verdict": "sideways_bound"})
        with pytest.raises(Exception):
            validate(attribution.to_dict(), SCHEMA_INCIDENT_ATTRIBUTION)


# ---- provenance rendering ----------------------------------------------


def test_explain_renders_roofline_block():
    from tpuslo.obs.provenance import ProvenanceRecord, format_chain

    rec = ProvenanceRecord(
        incident_id="inc-1",
        predicted_fault_domain="tpu_preemption",
        confidence=0.93,
        roofline=roofline_verdict(11.0, 3.4e9, 2.0e10),
    )
    text = format_chain(rec)
    assert "roofline: memory_bound" in text
    assert "% of HBM roof" in text
    # Round-trips the JSONL shape.
    rec2 = ProvenanceRecord.from_dict(
        json.loads(json.dumps(rec.to_dict()))
    )
    assert rec2.roofline["verdict"] == VERDICT_MEMORY_BOUND


# ---- new fault domains --------------------------------------------------


class TestNewFaultDomains:
    def test_profiles_encode_the_separators(self):
        from tpuslo.signals.generator import profile_for_fault

        preempt = profile_for_fault("preemption_eviction")
        base = profile_for_fault("baseline")
        assert preempt["device_eviction_events_total"] >= 3  # error line
        assert preempt["device_idle_gap_ms"] >= 100
        # Sub-warning compile creep: separator from a recompile storm.
        assert preempt["xla_compile_ms"] < 500
        noisy = profile_for_fault("noisy_neighbor_cpu")
        assert noisy["cpu_steal_pct"] >= 8
        # The cpu_throttle separator: NO cgroup quota throttling.
        assert noisy["cfs_throttled_ms"] == base["cfs_throttled_ms"]

    def test_clean_profiles_attribute_to_the_new_domains(self):
        from datetime import datetime, timezone

        from tpuslo.attribution.calibrate import calibrated_attributor
        from tpuslo.faultreplay import generate_fault_samples

        attributor = calibrated_attributor()
        start = datetime(2026, 8, 1, tzinfo=timezone.utc)
        for scenario, domain in (
            ("preemption_eviction", "tpu_preemption"),
            ("noisy_neighbor_cpu", "host_noisy_neighbor"),
        ):
            samples = generate_fault_samples(scenario, 4, start)
            for attribution in attributor.attribute_batch(samples):
                assert attribution.predicted_fault_domain == domain

    def test_new_scenarios_in_training_registry(self):
        from tpuslo.attribution.calibrate import (
            TRAIN_SCENARIOS,
            VARIANT_PROFILES,
        )

        for scenario in ("preemption_eviction", "noisy_neighbor_cpu"):
            assert scenario in TRAIN_SCENARIOS
            assert scenario in VARIANT_PROFILES


# ---- dispatch ledger ----------------------------------------------------


class TestDispatchLedger:
    def test_note_accumulates_and_snapshots(self):
        ledger = DispatchLedger()
        ledger.note(1_000_000, 4_000_000, tokens=10, slots=4)
        ledger.note(2_000_000, 6_000_000, tokens=14, slots=3)
        assert ledger.steps == 2
        assert ledger.device_wait_ms_total == pytest.approx(10.0)
        assert ledger.dispatch_ms_total == pytest.approx(3.0)
        last = ledger.last()
        assert last == {
            "dispatch_ms": 2.0,
            "device_wait_ms": 6.0,
            "tokens": 14,
            "slots": 3,
        }
        totals = ledger.totals()
        assert totals["tokens_total"] == 24
        assert totals["device_wait_ms_per_token"] == pytest.approx(
            10.0 / 24, rel=1e-3
        )


# ---- metrics bridge -----------------------------------------------------


def test_deviceplane_observer_publishes_ledger():
    from tpuslo.metrics.registry import AgentMetrics

    metrics = AgentMetrics()
    observer = metrics.deviceplane_observer()
    ledger, _ = make_ledger(seed=6)
    observer.ledger_folded(ledger)
    observer.dispatch_observed(4.2)
    observer.roofline_attached("memory_bound")

    def value(metric, **labels):
        for family in metric.collect():
            for sample in family.samples:
                if all(
                    sample.labels.get(k) == v for k, v in labels.items()
                ) and not sample.name.endswith(("_created", "_bucket")):
                    return sample.value
        return None

    assert value(
        metrics.deviceplane_join_rate, kind="substantive"
    ) == pytest.approx(ledger.substantive_join_rate)
    assert value(
        metrics.deviceplane_device_time_ms, bucket="joined"
    ) == pytest.approx(ledger.buckets_us["joined"] / 1000.0)
    assert value(
        metrics.deviceplane_roofline_verdicts, verdict="memory_bound"
    ) == 1.0


# ---- front-door tracing + per-dispatch ledger ---------------------------


@pytest.fixture(scope="module")
def engines():
    from tpuslo.models.llama import llama_tiny
    from tpuslo.models.serve import ServeEngine

    cfg = llama_tiny(max_seq_len=128)
    target = ServeEngine(cfg=cfg, rng_seed=0)
    draft = ServeEngine(cfg=cfg, rng_seed=0)
    return target, draft


class TestFrontDoorTracing:
    def test_step_emits_root_and_stage_spans_with_ledger_attrs(
        self, engines
    ):
        from tpuslo.models.frontdoor import FrontDoorEngine
        from tpuslo.obs.tracer import SelfTracer, TracerConfig

        exported = []
        tracer = SelfTracer(
            TracerConfig(sample_rate=1.0, metrics_stride=1),
            on_export=exported.append,
        )
        door = FrontDoorEngine(
            engines[0], engines[1], k=3, max_slots=2,
            rounds_per_step=1, self_tracer=tracer,
        )
        door.submit("trace me", max_new_tokens=6, stop_at_eos=False)
        door.run()
        assert exported, "sample_rate 1.0 must export every step cycle"
        roots = [spans[0] for spans in exported]
        assert all(root.name == "frontdoor.step" for root in roots)
        # A dispatching cycle carries the four stage children in order.
        dispatching = next(
            spans for spans in exported if len(spans) == 5
        )
        assert [s.name for s in dispatching[1:]] == [
            "admit", "dispatch", "read", "retire",
        ]
        retire = dispatching[4]
        assert retire.attributes["tokens"] > 0
        assert retire.attributes["device_wait_ms"] >= 0.0
        assert "dispatch_ms" in retire.attributes
        # totals round to 3 decimals, the last-step attr to 4.
        assert retire.attributes["device_wait_ms_total"] >= (
            retire.attributes["device_wait_ms"] - 1e-3
        )

    def test_dispatch_ledger_rides_stats_without_tracer(self, engines):
        from tpuslo.models.frontdoor import FrontDoorEngine

        door = FrontDoorEngine(
            engines[0], engines[1], k=3, max_slots=2, rounds_per_step=1
        )
        door.submit("no tracer", max_new_tokens=6, stop_at_eos=False)
        results = door.run()
        assert all(len(v) == 6 for v in results.values())
        totals = door.stats()["dispatch_ledger"]
        assert totals["steps"] == door.rounds
        # The FIRST token of each request is emitted from the prefill
        # logits at admission — the dispatch ledger counts only
        # dispatch-emitted tokens.
        assert totals["tokens_total"] == sum(
            len(v) for v in results.values()
        ) - len(results)
        assert totals["device_wait_ms_total"] > 0.0


# ---- the sweep gate -----------------------------------------------------


class TestSweep:
    def test_sweep_passes_without_heldout(self):
        from tpuslo.deviceplane.sweep import run_deviceplane_sweep

        report = run_deviceplane_sweep(
            seed=1337, steps=12, skip_heldout=True
        )
        assert report.passed, report.failures
        assert len(report.ledger_runs) == 3
        assert report.roofline["decode"]["verdict"] == (
            VERDICT_MEMORY_BOUND
        )
        assert report.roofline["prefill"]["verdict"] == (
            VERDICT_COMPUTE_BOUND
        )
        attributions = report.roofline["attributions"]
        assert attributions["with_verdict"] == attributions["total"]

    @pytest.mark.slow
    def test_full_sweep_with_heldout_meets_acceptance(self):
        from tpuslo.deviceplane.sweep import (
            MIN_HELDOUT_FULL_DOMAIN_F1,
            run_deviceplane_sweep,
        )

        report = run_deviceplane_sweep(seed=1337)
        assert report.passed, report.failures
        assert report.heldout["full_domain"]["1.0"] >= (
            MIN_HELDOUT_FULL_DOMAIN_F1
        )
        for domain, f1 in report.heldout["new_domain_f1"].items():
            assert f1 >= 0.9, (domain, f1)

    def test_m5gate_cli_round_trip(self, tmp_path):
        from tpuslo.cli.m5gate import main

        out_json = tmp_path / "sweep.json"
        out_md = tmp_path / "sweep.md"
        rc = main(
            [
                "--deviceplane-sweep",
                "--deviceplane-skip-heldout",
                "--deviceplane-steps", "8",
                "--summary-json", str(out_json),
                "--summary-md", str(out_md),
            ]
        )
        assert rc == 0
        payload = json.loads(out_json.read_text())
        assert payload["passed"] is True
        assert "Device-plane truth gate" in out_md.read_text()


# ---- serving bench lane -------------------------------------------------


def test_serving_bench_deviceplane_lane_meets_floors():
    from tpuslo.benchmark.serving_bench import _deviceplane_lane

    lane = _deviceplane_lane(seed=1337)
    assert lane["bucket_sum_matches_total"] is True
    assert lane["substantive_join_rate"] >= 0.9
    assert lane["unexplained_share"] <= 0.1
    assert lane["raw_join_rate"] < lane["substantive_join_rate"]
