"""OTLP exporter + webhook delivery tests against a local HTTP stub.

Reference model: pkg/otel/*_test.go and pkg/webhook/exporter_test.go
(httptest servers with HMAC verification).
"""

import json
import threading
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tpuslo import schema, webhook
from tpuslo.otel.exporters import ExportError, ProbeEventExporter, SLOEventExporter

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)


class StubHandler(BaseHTTPRequestHandler):
    status_code = 202

    def do_POST(self):
        if self.server.hang_s:
            import time

            time.sleep(self.server.hang_s)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        self.server.requests.append(
            {"path": self.path, "headers": dict(self.headers), "body": body}
        )
        code = self.server.status_codes.pop(0) if self.server.status_codes else self.server.default_status
        self.send_response(code)
        self.end_headers()
        self.wfile.write(b"{}")

    def log_message(self, *args):
        pass


@pytest.fixture
def stub_server():
    server = HTTPServer(("127.0.0.1", 0), StubHandler)
    server.requests = []
    server.status_codes = []
    server.default_status = 202
    server.hang_s = 0.0
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield server
    server.shutdown()
    server.server_close()


def make_slo_event():
    return schema.SLOEvent(
        event_id="req-1-ttft_ms",
        timestamp=TS,
        cluster="c",
        namespace="n",
        workload="w",
        service="s",
        request_id="req-1",
        sli_name="ttft_ms",
        sli_value=340.0,
        unit="ms",
        status="breach",
        labels={"fault_label": "dns_latency"},
    )


def make_probe_event():
    return schema.ProbeEventV1(
        ts_unix_nano=int(TS.timestamp() * 1e9),
        signal="hbm_alloc_stall_ms",
        node="tpu-vm-0",
        namespace="llm",
        pod="rag",
        container="rag",
        pid=1,
        tid=1,
        value=60.0,
        unit="ms",
        status="error",
        tpu=schema.TPURef(chip="accel0", slice_id="s0", host_index=0, launch_id=7),
    )


def make_attr():
    return schema.IncidentAttribution(
        incident_id="inc-1",
        timestamp=TS,
        cluster="c",
        service="s",
        predicted_fault_domain="tpu_hbm",
        confidence=0.93,
        evidence=[schema.Evidence("hbm_alloc_stall_ms", 60.0, "libtpu")],
        slo_impact=schema.SLOImpact("ttft_ms", 3.5, 5),
    )


class TestSLOEventExporter:
    def test_export_batch_payload_shape(self, stub_server):
        exporter = SLOEventExporter(
            f"http://127.0.0.1:{stub_server.server_port}/v1/logs"
        )
        exporter.export_batch([make_slo_event()])
        assert len(stub_server.requests) == 1
        payload = json.loads(stub_server.requests[0]["body"])
        record = payload["resourceLogs"][0]["scopeLogs"][0]["logRecords"][0]
        assert record["severityText"] == "ERROR"
        attrs = {a["key"]: a["value"] for a in record["attributes"]}
        assert attrs["sli.name"]["stringValue"] == "ttft_ms"
        assert attrs["sli.value"]["doubleValue"] == 340.0
        assert attrs["label.fault_label"]["stringValue"] == "dns_latency"

    def test_empty_batch_no_post(self, stub_server):
        exporter = SLOEventExporter(
            f"http://127.0.0.1:{stub_server.server_port}/v1/logs"
        )
        exporter.export_batch([])
        assert stub_server.requests == []

    def test_server_error_raises(self, stub_server):
        stub_server.status_codes = [500]
        exporter = SLOEventExporter(
            f"http://127.0.0.1:{stub_server.server_port}/v1/logs"
        )
        with pytest.raises(ExportError):
            exporter.export_batch([make_slo_event()])

    def test_missing_endpoint_raises(self):
        with pytest.raises(ExportError):
            SLOEventExporter("").export_batch([make_slo_event()])

    def test_retryability_classification(self, stub_server):
        exporter = SLOEventExporter(
            f"http://127.0.0.1:{stub_server.server_port}/v1/logs"
        )
        # 429 (rate limiting) is retryable per OTLP/HTTP; 400 is poison.
        for code, retryable in ((429, True), (408, True), (400, False),
                                (500, True)):
            stub_server.status_codes = [code]
            with pytest.raises(ExportError) as err:
                exporter.export_batch([make_slo_event()])
            assert err.value.retryable is retryable, code


class TestProbeEventExporter:
    def test_tpu_attributes_exported(self, stub_server):
        exporter = ProbeEventExporter(
            f"http://127.0.0.1:{stub_server.server_port}/v1/logs"
        )
        exporter.export_batch([make_probe_event()])
        payload = json.loads(stub_server.requests[0]["body"])
        record = payload["resourceLogs"][0]["scopeLogs"][0]["logRecords"][0]
        attrs = {a["key"]: a["value"] for a in record["attributes"]}
        assert attrs["tpu.chip"]["stringValue"] == "accel0"
        assert attrs["tpu.xla.launch_id"]["intValue"] == "7"
        assert attrs["signal"]["stringValue"] == "hbm_alloc_stall_ms"
        assert record["timeUnixNano"] == str(int(TS.timestamp() * 1e9))


class TestWebhook:
    def test_generic_delivery_with_hmac(self, stub_server):
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook",
            secret="s3cret",
        )
        exporter.send(make_attr())
        req = stub_server.requests[0]
        signature = req["headers"]["X-Webhook-Signature"]
        assert signature.startswith("sha256=")
        assert webhook.verify_hmac(req["body"], "s3cret", signature)
        assert not webhook.verify_hmac(req["body"], "wrong", signature)
        body = json.loads(req["body"])
        assert body["predicted_fault_domain"] == "tpu_hbm"

    def test_retry_on_5xx_then_success(self, stub_server):
        stub_server.status_codes = [500, 202]
        sleeps = []
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook",
            sleep=sleeps.append,
            rng=lambda: 1.0,  # pin full jitter to its upper bound
        )
        exporter.send(make_attr())
        assert len(stub_server.requests) == 2
        assert sleeps == [1.0]

    def test_backoff_jitter_and_cap(self, stub_server):
        # 5 attempts with rng pinned high: un-capped exponential would
        # sleep [1, 2, 4, 8]; the default 8s cap must clamp the tail,
        # and jitter must scale the whole delay.
        stub_server.status_codes = [500] * 5
        sleeps = []
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook",
            max_retry=5,
            max_delay_s=4.0,
            sleep=sleeps.append,
            rng=lambda: 0.5,
        )
        with pytest.raises(webhook.WebhookError, match="after 5 attempts"):
            exporter.send(make_attr())
        assert sleeps == [0.5, 1.0, 2.0, 2.0]  # 0.5 * min(4, 2^n)

    def test_timeout_is_retryable(self, stub_server):
        # A hang past the client timeout must classify as an explicitly
        # retryable WebhookError, not an opaque URLError string.
        stub_server.hang_s = 0.5
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook",
            timeout_ms=100,
            max_retry=1,
            sleep=lambda _: None,
        )
        with pytest.raises(webhook.WebhookError, match="timed out"):
            exporter.send(make_attr())

    def test_429_is_retryable(self, stub_server):
        stub_server.status_codes = [429, 202]
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook",
            sleep=lambda _: None,
        )
        exporter.send(make_attr())  # throttled once, then delivered
        assert len(stub_server.requests) == 2

    def test_4xx_not_retried(self, stub_server):
        stub_server.status_codes = [400]
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook", sleep=lambda _: None
        )
        with pytest.raises(webhook.WebhookError) as err:
            exporter.send(make_attr())
        assert not err.value.retryable
        assert len(stub_server.requests) == 1

    def test_exhausted_retries_raise(self, stub_server):
        stub_server.status_codes = [500, 500, 500]
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook", sleep=lambda _: None
        )
        with pytest.raises(webhook.WebhookError, match="after 3 attempts"):
            exporter.send(make_attr())

    def test_pagerduty_payload(self):
        payload = json.loads(webhook.build_pagerduty_payload(make_attr()))
        assert payload["payload"]["severity"] == "critical"  # conf 0.93 >= 0.8
        assert "tpu_hbm" in payload["payload"]["summary"]
        assert payload["payload"]["custom_details"]["burn_rate"] == "3.50"

    def test_opsgenie_priority_p1_on_high_burn(self):
        payload = json.loads(webhook.build_opsgenie_payload(make_attr()))
        assert payload["priority"] == "P1"  # burn 3.5 >= 3.0
        assert payload["entity"] == "s"

    def test_opsgenie_priority_p2_p3(self):
        attr = make_attr()
        attr.slo_impact.burn_rate = 1.0
        assert json.loads(webhook.build_opsgenie_payload(attr))["priority"] == "P2"
        attr.confidence = 0.5
        assert json.loads(webhook.build_opsgenie_payload(attr))["priority"] == "P3"

    def test_severity_escalates_on_fast_burn(self):
        # A low-confidence incident still pages critical/P1 while a
        # fast-burn budget alert is active: budget exhaustion outranks
        # classifier certainty.
        attr = make_attr()
        attr.confidence = 0.5
        attr.slo_impact.burn_rate = 1.0
        attr.slo_burn = {
            "evaluated_at": "2026-07-29T12:00:00Z",
            "max_burn_rate": 25.0,
            "alerting": [
                {
                    "tenant": "gold",
                    "objective": "availability",
                    "state": "fast_burn",
                    "burn_rates": {"1h": 25.0, "5m": 30.0},
                    "budget_remaining": 0.1,
                }
            ],
        }
        pd = json.loads(webhook.build_pagerduty_payload(attr))
        assert pd["payload"]["severity"] == "critical"
        assert pd["payload"]["custom_details"]["burning_budgets"] == [
            "gold/availability=fast_burn"
        ]
        og = json.loads(webhook.build_opsgenie_payload(attr))
        assert og["priority"] == "P1"
        assert "gold/availability=fast_burn" in og["details"]["burning_budgets"]

    def test_slow_burn_alone_does_not_escalate_pagerduty(self):
        attr = make_attr()
        attr.confidence = 0.5
        attr.slo_impact.burn_rate = 1.0
        attr.slo_burn = {
            "alerting": [
                {
                    "tenant": "gold",
                    "objective": "availability",
                    "state": "slow_burn",
                    "burn_rates": {"6h": 8.0, "30m": 8.0},
                    "budget_remaining": 0.6,
                }
            ],
        }
        pd = json.loads(webhook.build_pagerduty_payload(attr))
        assert pd["payload"]["severity"] == "warning"

    def test_slo_burn_rides_generic_payload_and_contract(self):
        from tpuslo.schema import SCHEMA_INCIDENT_ATTRIBUTION, validate

        attr = make_attr()
        attr.slo_burn = {
            "evaluated_at": "2026-07-29T12:00:00Z",
            "max_burn_rate": 25.0,
            "alerting": [
                {
                    "tenant": "gold",
                    "objective": "ttft",
                    "state": "fast_burn",
                    "burn_rates": {"1h": 25.0},
                    "budget_remaining": 0.0,
                }
            ],
        }
        payload = attr.to_dict()
        validate(payload, SCHEMA_INCIDENT_ATTRIBUTION)
        assert payload["slo_burn"]["alerting"][0]["tenant"] == "gold"
        # Absent burn context stays absent (optional field).
        bare = make_attr().to_dict()
        assert "slo_burn" not in bare
        validate(bare, SCHEMA_INCIDENT_ATTRIBUTION)

    def test_pagerduty_format_sent_via_exporter(self, stub_server):
        exporter = webhook.Exporter(
            f"http://127.0.0.1:{stub_server.server_port}/hook",
            format=webhook.FORMAT_PAGERDUTY,
        )
        exporter.send(make_attr())
        body = json.loads(stub_server.requests[0]["body"])
        assert body["event_action"] == "trigger"
