"""Frontend verification of the eBPF probe layer via real clang.

Rounds 2-4 judged the probe layer "code-complete but unverifiable":
no clang driver exists in this image, so the 13 CO-RE programs had no
compile evidence.  The libclang wheel IS the clang-18 frontend;
``tools/ebpf_frontend_check.py`` drives preprocessing + parsing + full
semantic analysis of every program against ``-target bpf``.  These
tests run that check in CI and prove it has teeth (a broken program
fails it).
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)

clang = pytest.importorskip("clang", reason="libclang wheel not present")


def _run_check():
    import ebpf_frontend_check as chk

    return chk.run_check()


def test_all_probe_programs_pass_clang_frontend():
    report = _run_check()
    assert report["programs"] == 13
    failing = [r for r in report["results"] if not r["ok"]]
    assert not failing, failing
    assert "clang version" in report["clang"]


def test_committed_evidence_matches_sources():
    """The committed artifact's sha256 per program must match the
    working tree — stale evidence (edited probe, unrefreshed artifact)
    fails here instead of silently misrepresenting the sources."""
    import json

    import ebpf_frontend_check as chk

    if not os.path.exists(chk.EVIDENCE_PATH):
        pytest.skip("evidence artifact not generated yet")
    committed = {
        r["file"]: r["sha256"]
        for r in json.load(open(chk.EVIDENCE_PATH))["results"]
    }
    live = {r["file"]: r["sha256"] for r in _run_check()["results"]}
    assert committed == live, (
        "docs/evidence/ebpf-frontend-check.json is stale — rerun "
        "`python tools/ebpf_frontend_check.py --write`"
    )


def test_checker_catches_broken_program(tmp_path):
    """Teeth: a program with a type error against the BPF target must
    produce error diagnostics through the same parse path."""
    import ebpf_frontend_check as chk

    cindex = chk._load_cindex()
    bad = tmp_path / "broken.bpf.c"
    bad.write_text(
        '#include "tpuslo_common.bpf.h"\n'
        'SEC("kprobe/x")\n'
        "int broken(struct pt_regs *ctx)\n"
        "{\n"
        "\tstruct tpuslo_inflight *in = 7;  /* int -> ptr */\n"
        "\treturn undeclared_symbol(in);\n"
        "}\n"
    )
    result = chk.check_file(cindex, cindex.Index.create(), str(bad))
    assert result["ok"] is False
    assert any("undeclared" in d["message"] for d in result["diagnostics"])


def test_checker_cli_exit_code():
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "ebpf_frontend_check.py")],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "13 programs" in proc.stdout
