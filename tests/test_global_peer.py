"""Symmetric global peer mesh: gossip reconciliation and root leader
election under WAN chaos.

The invariants that keep the mesh honest get direct coverage:

* **Commit-then-page.**  A leader's closed session parks in the
  outbox with its registry row withdrawn; the page only releases once
  another peer gossips the EXACT row back, so a leader killed at any
  point of the race leaves zero lost and zero duplicate pages.
* **Epoch fencing.**  Every page carries the epoch that stamped it; a
  deposed root's stale announcement is rejected AND counted, and the
  rejection does not seal the window — sealing without a held page
  would suppress the successor's rebuild into a lost incident.
* **Deferred re-stamp.**  Pages dropped at a fence park in
  ``deferred``; retaking leadership re-stamps them at the new epoch
  (Raft's "re-replicate prior-term entries at your own term") unless
  the registry meanwhile covers their window.
* **Replication-fenced acks.**  A region seq is only ackable once a
  second peer's gossiped cursors cover it — acking sooner would let a
  leader that died pre-emission strand the only copy of evidence.
* **Mid-compaction cursor restore.**  A gap-tolerant cursor state
  exported mid-compaction (accepted seqs at or below the watermark)
  must restore without re-accepting a delivered seq.

The live lane drives the same machine over real sockets: a three-node
mesh elects, pages, and confirms through ``LivePeerNode``; a WanProxy
one-way ack-loss partition during an in-flight election forces the
claim to spread while the claimant's own gossip goes unacked, and the
per-sender gossip cursors absorb the replay storm after the heal.
"""

from __future__ import annotations

import json
import socket
import time

import pytest

from tpuslo.chaos.wan import DIR_BACKWARD, WanProxy
from tpuslo.federation.global_tier import GapTolerantCursor, GlobalPeer
from tpuslo.federation.livemesh import LivePeerNode
from tpuslo.federation.sweep import run_peer_sweep
from tpuslo.federation.wire import (
    PEER_WIRE_VERSION,
    PeerWireError,
    decode_peer_envelope,
    encode_global_envelope,
    global_envelope_json_line,
    parse_peer_envelope_line,
    peer_envelope_json_line,
)
from tpuslo.fleet.rollup import FleetIncident
from tpuslo.fleet.simulator import EPOCH_NS
from tpuslo.fleet.wire import WireContractError
from tpuslo.livenet import ReconnectingClient

GAP = 5_000_000_000
#: Short liveness horizon so election tests fit in a few event-clock
#: hops (the default is three simulated minutes).
STALE = 10 * GAP


def _fleet(
    rid: str,
    i: int = 0,
    namespace: str = "tenant-a",
    domain: str = "dcn_degradation",
) -> FleetIncident:
    start = EPOCH_NS + i * 10 * GAP
    return FleetIncident(
        incident_id=f"fleet-{rid}-{i}",
        namespace=namespace,
        domain=domain,
        blast_radius="pod",
        window_start_ns=start,
        window_end_ns=start + 2_000_000_000,
        confidence=0.9,
        nodes=[f"{rid}-node-{i}"],
        slices=[f"{rid}-slice-0"],
        members=[],
        region=rid,
        clusters=[f"{rid}-c0"],
    )


def _env(
    rid: str,
    seq: int,
    incidents: list[FleetIncident] | None = None,
    clock: int = EPOCH_NS + 40 * GAP,
) -> dict:
    return encode_global_envelope(
        region=rid,
        seq=seq,
        incidents=incidents or [],
        watermark_ns=clock,
        head_ns=clock,
    )


def _mesh(n: int = 3, **kwargs) -> dict[str, GlobalPeer]:
    ids = [f"global-{i}" for i in range(n)]
    kwargs.setdefault("peer_stale_after_ns", STALE)
    return {pid: GlobalPeer(pid, ids, **kwargs) for pid in ids}


def _round(
    peers: dict[str, GlobalPeer], now_ns: int, skip: set[str] = frozenset()
) -> None:
    """One synchronous anti-entropy round among the non-skipped peers.

    All envelopes are built before any is delivered — the same
    no-peeking semantics as a real round where everything is in
    flight at once.
    """
    batch = []
    for pid, peer in peers.items():
        if pid in skip:
            continue
        peer.begin_gossip_round()
        for other in peers:
            if other != pid and other not in skip:
                batch.append((other, peer.gossip_out(other, now_ns)))
    for to, envelope in batch:
        peers[to].gossip_in(envelope, now_ns)


def _wait_until(cond, timeout_s: float = 10.0) -> None:
    deadline = time.monotonic() + timeout_s
    while not cond() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert cond(), "condition not reached before deadline"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestGapTolerantCursorRestore:
    def test_mid_compaction_state_cannot_double_accept(self):
        """Accepted seqs at or below the watermark (a mid-compaction
        export, or a state assembled by a peer from gossip) must fold
        away on restore — accept() returning True for a delivered seq
        is the exact duplicate this cursor exists to prevent."""
        cursor = GapTolerantCursor()
        cursor.restore_state({"watermark": 3, "accepted": [1, 2, 4, 5]})
        assert cursor.watermark == 5
        assert cursor.accepted == set()
        assert cursor.accept(4) is False
        assert cursor.accept(5) is False
        assert cursor.accept(6) is True

    def test_contiguous_run_folds_into_watermark(self):
        cursor = GapTolerantCursor()
        cursor.restore_state({"watermark": -1, "accepted": [0, 1, 2, 5]})
        assert cursor.watermark == 2
        assert cursor.accepted == {5}
        assert cursor.accept(0) is False
        assert cursor.accept(5) is False
        assert cursor.accept(3) is True

    def test_export_restore_round_trip_preserves_dedup(self):
        cursor = GapTolerantCursor()
        for seq in (0, 2, 3, 7):
            assert cursor.accept(seq) is True
        restored = GapTolerantCursor()
        restored.restore_state(
            json.loads(json.dumps(cursor.export_state()))
        )
        assert restored.watermark == cursor.watermark
        assert restored.accepted == cursor.accepted
        assert restored.accept(7) is False
        assert restored.accept(1) is True  # fills the gap...
        assert restored.watermark == 3  # ...and compacts through it


class TestCommitThenPage:
    def test_solo_mesh_releases_immediately(self):
        peer = GlobalPeer("global-0", ["global-0"])
        assert peer.is_leader
        assert peer.ingest(_env("region-a", 0, [_fleet("region-a")]))
        stamped = peer.pump(flush=True)
        assert len(stamped) == 1
        assert stamped[0]["epoch"] == 0
        assert stamped[0]["peer"] == "global-0"
        # Nothing to wait for: the solo outbox settles in the same call.
        assert peer.outbox == []
        assert [p["incident_id"] for p in peer.take_released()] == [
            stamped[0]["incident_id"]
        ]

    def test_leader_parks_until_row_gossiped_back(self):
        peers = _mesh(3)
        leader = peers["global-0"]
        follower = peers["global-1"]
        assert leader.ingest(_env("region-a", 0, [_fleet("region-a")]))
        stamped = leader.pump(flush=True)
        assert len(stamped) == 1
        page = stamped[0]
        # Parked, not emitted: the row is withdrawn with it.
        assert leader.take_released() == []
        assert len(leader.outbox) == 1
        assert not leader.agg.rollup.window_registered(
            page["namespace"], page["domain"],
            page["window_start_ns"], page["window_end_ns"],
        )
        now = EPOCH_NS + 50 * GAP
        _round(peers, now)
        # Round 1: the announcement landed — the follower holds the
        # page AND its row (acceptance folds them together).
        assert [p["incident_id"] for p in follower.pages] == [
            page["incident_id"]
        ]
        assert follower.take_released() == []  # held, not re-emitted
        assert leader.take_released() == []  # row not yet echoed
        # Round 2: the row gossips back and the original releases.
        _round(peers, now + GAP)
        released = leader.take_released()
        assert [p["incident_id"] for p in released] == [
            page["incident_id"]
        ]
        assert leader.outbox == []
        assert leader.pages_released == 1
        assert leader.agg.rollup.window_registered(
            page["namespace"], page["domain"],
            page["window_start_ns"], page["window_end_ns"],
        )
        # Union across the mesh: one page, one id, stamped (0, g0).
        ids = [p["incident_id"] for peer in peers.values()
               for p in peer.pages]
        assert ids.count(page["incident_id"]) == len(peers)
        assert all(
            (p["epoch"], p["peer"]) == (0, "global-0")
            for peer in peers.values() for p in peer.pages
        )

    def test_spool_replay_rebuild_suppressed_by_outbox(self):
        """With the row withdrawn, a replayed spool rebuilding the
        same session slips past the rollup's suppression — the parked
        page itself must be the dedup fence until release."""
        peers = _mesh(2)
        leader = peers["global-0"]
        assert leader.ingest(_env("region-a", 0, [_fleet("region-a")]))
        assert len(leader.pump(flush=True)) == 1
        # The region replays the same fault under a fresh seq (its own
        # spool was never acked: the replication fence held it).
        assert leader.ingest(_env("region-a", 1, [_fleet("region-a")]))
        assert leader.pump(flush=True) == []
        assert leader.outbox_suppressed == 1
        assert len(leader.outbox) == 1

    def test_follower_reconcile_trims_provably_paged_pending(self):
        peers = _mesh(2)
        follower = peers["global-1"]
        assert not follower.is_leader
        incident = _fleet("region-b")
        assert follower.ingest(_env("region-b", 0, [incident]))
        # The leader's released row arrives by registry merge...
        follower.agg.rollup.merge_emitted_windows(
            [[incident.namespace, incident.domain,
              incident.window_start_ns, incident.window_end_ns]]
        )
        follower.reconcile()
        # ...and the buffered member is provably paged: drop it.
        assert follower.pending_trimmed >= 1


class TestReplicationFencedAcks:
    def test_ack_fenced_until_a_peer_covers_the_seq(self):
        peers = _mesh(2)
        leader = peers["global-0"]
        assert leader.ingest(_env("region-a", 0, [_fleet("region-a")]))
        assert leader.ingest(_env("region-a", 1, []))
        # Held locally only: acking now could strand the evidence.
        assert leader.ackable_seq("region-a") == -1
        now = EPOCH_NS + 50 * GAP
        _round(peers, now)  # the relay rides gossip out...
        _round(peers, now + GAP)  # ...and the covering cursors return
        assert leader.ackable_seq("region-a") == 1
        # Covered everywhere: the relay spool trims to nothing.
        assert leader.snapshot()["relay_spooled"] == 0

    def test_solo_peer_acks_at_its_own_watermark(self):
        peer = GlobalPeer("global-0", ["global-0"])
        assert peer.ingest(_env("region-a", 0, []))
        assert peer.ackable_seq("region-a") == 0


class TestElectionAndFencing:
    def test_bully_lowest_rank_live_leads_epoch_fenced(self):
        peers = _mesh(3)
        now = EPOCH_NS + 50 * GAP
        _round(peers, now)
        assert all(p.leader_id == "global-0" for p in peers.values())
        assert all(p.epoch == 0 for p in peers.values())
        # The root goes dark for a full liveness horizon.
        later = now + STALE + GAP
        g1, g2 = peers["global-1"], peers["global-2"]
        assert g1.election_tick(later) is True
        assert g1.epoch == 1 and g1.is_leader
        assert g1.elections == 1
        _round(peers, later, skip={"global-0"})
        assert g2.leader_id == "global-1" and g2.epoch == 1
        # The claim seen, g2 never contests: g1 outranks it.
        assert g2.election_tick(later) is False
        assert g1.election_tick(later) is False  # already leading

    def test_equal_epoch_tie_breaks_to_lower_rank(self):
        """Both halves of a split elect at the same epoch; on heal the
        bully rule's pick (the lower rank) wins on every peer."""
        peers = _mesh(3)
        now = EPOCH_NS + 50 * GAP
        _round(peers, now)
        later = now + STALE + GAP
        g1, g2 = peers["global-1"], peers["global-2"]
        # g0 vanished and the g1|g2 link is down too: both elect.
        assert g1.election_tick(later) is True
        assert g2.election_tick(later) is True
        assert g1.epoch == g2.epoch == 1
        # Heal: one gossip exchange converges both on g1.
        g2.gossip_in(g1.gossip_out("global-2", later), later)
        g1.gossip_in(g2.gossip_out("global-1", later), later)
        assert g1.leader_id == g2.leader_id == "global-1"
        assert g1.is_leader and not g2.is_leader

    def test_deposed_root_stale_page_rejected_and_counted(self):
        peers = _mesh(3)
        g0, g1, g2 = peers.values()
        now = EPOCH_NS + 50 * GAP
        _round(peers, now)
        # The root closes a session; its page parks at epoch 0.
        assert g0.ingest(_env("region-a", 0, [_fleet("region-a")]))
        [page] = g0.pump(flush=True)
        assert page["epoch"] == 0
        # Partitioned before the announcement spreads, the survivors
        # elect past it.
        later = now + STALE + GAP
        assert g1.election_tick(later) is True
        _round(peers, later, skip={"global-0"})
        # Heal: the deposed root's announcement arrives at epoch 0
        # against a mesh at epoch 1 — rejected, counted, and the
        # window is NOT sealed (no held page may mean no row).
        before = g1.stale_epoch_rejections
        g1.gossip_in(g0.gossip_out("global-1", later), later)
        assert g1.stale_epoch_rejections == before + 1
        assert page["incident_id"] not in {
            p["incident_id"] for p in g1.pages
        }
        assert not g1.agg.rollup.window_registered(
            page["namespace"], page["domain"],
            page["window_start_ns"], page["window_end_ns"],
        )
        # The return gossip deposes g0: the parked page is dropped to
        # deferred, never released at the stale epoch.
        g0.gossip_in(g1.gossip_out("global-0", later), later)
        assert g0.epoch == 1 and g0.leader_id == "global-1"
        assert g0.stale_pages_dropped == 1
        assert len(g0.deferred) == 1
        assert g0.outbox == []
        assert g0.take_released() == []
        assert g0.pages_released == 0

    def test_retaking_leadership_restamps_deferred_evidence(self):
        """A fenced page may hold the only copy of its evidence (the
        origin's cursors deduped the envelopes away); winning an
        election re-enters it into the outbox at the new epoch."""
        peers = _mesh(3)
        g0, g1, g2 = peers.values()
        now = EPOCH_NS + 50 * GAP
        _round(peers, now)
        assert g0.ingest(_env("region-a", 0, [_fleet("region-a")]))
        [page] = g0.pump(flush=True)
        later = now + STALE + GAP
        assert g1.election_tick(later) is True
        _round(peers, later, skip={"global-0"})
        g0.gossip_in(g1.gossip_out("global-0", later), later)
        assert len(g0.deferred) == 1
        # Now the survivors go dark and g0 is the last peer standing:
        # it retakes at an epoch past everything seen.
        final = later + STALE + GAP
        assert g0.election_tick(final) is True
        assert g0.epoch == 2
        assert g0.pages_restamped == 1
        assert g0.deferred == []
        assert [p["epoch"] for p in g0.outbox] == [2]
        # The restamped announcement is acceptable again: one gossip
        # round-trip with a healed peer confirms and releases it.
        g1.gossip_in(g0.gossip_out("global-1", final), final)
        g0.gossip_in(g1.gossip_out("global-0", final), final)
        released = g0.take_released()
        assert [p["incident_id"] for p in released] == [
            page["incident_id"]
        ]
        assert released[0]["epoch"] == 2
        # Zero lost, zero duplicate across the whole ordeal.
        ids = [p["incident_id"] for peer in peers.values()
               for p in peer.pages]
        assert ids.count(page["incident_id"]) == 2  # g0's + g1's copy

    def test_rank_and_stamps_stable_across_handover(self):
        """Ranks derive from sorted membership, not construction
        order; released pages keep their original (epoch, peer)
        attribution across a handover while new pages carry the new
        leader's stamp."""
        ids = ["global-0", "global-1", "global-2"]
        shuffled = {
            "global-0": ["global-2", "global-0", "global-1"],
            "global-1": ["global-1", "global-2", "global-0"],
            "global-2": ids,
        }
        peers = {
            pid: GlobalPeer(pid, members, peer_stale_after_ns=STALE)
            for pid, members in shuffled.items()
        }
        assert [peers[pid].rank for pid in ids] == [0, 1, 2]
        assert all(p.peer_ids == ids for p in peers.values())
        now = EPOCH_NS + 50 * GAP
        g0, g1 = peers["global-0"], peers["global-1"]
        assert g0.ingest(_env("region-a", 0, [_fleet("region-a", 0)]))
        g0.pump(flush=True)
        _round(peers, now)
        _round(peers, now + GAP)
        [first] = g0.take_released()
        # Handover: g0 dark, g1 takes, and a NEW fault pages under the
        # new authority.
        later = now + STALE + 2 * GAP
        assert g1.election_tick(later) is True
        assert g1.ingest(_env("region-b", 0, [_fleet("region-b", 4)]))
        g1.pump(flush=True)
        _round(peers, later, skip={"global-0"})
        _round(peers, later + GAP, skip={"global-0"})
        [second] = g1.take_released()
        assert (first["epoch"], first["peer"]) == (0, "global-0")
        assert (second["epoch"], second["peer"]) == (1, "global-1")
        # The survivor holds both attributions, unrewritten.
        stamps = {
            p["incident_id"]: (p["epoch"], p["peer"])
            for p in peers["global-2"].pages
        }
        assert stamps[first["incident_id"]] == (0, "global-0")
        assert stamps[second["incident_id"]] == (1, "global-1")


class TestPeerWire:
    def test_envelope_json_round_trip(self):
        peers = _mesh(2)
        leader = peers["global-0"]
        assert leader.ingest(_env("region-a", 0, [_fleet("region-a")]))
        leader.pump(flush=True)
        payload = leader.gossip_out("global-1", EPOCH_NS + 50 * GAP)
        env = parse_peer_envelope_line(peer_envelope_json_line(payload))
        assert env.peer == "global-0"
        assert env.seq == 0
        assert env.epoch == 0
        assert env.leader == "global-0"
        assert "region-a" in env.cursors
        assert len(env.envelopes) == 1  # the relay delta
        assert len(env.pages) == 1  # the parked announcement
        assert env.alive["global-0"] == EPOCH_NS + 50 * GAP

    def test_contract_breaks_are_loud_and_nackable(self):
        with pytest.raises(PeerWireError):
            decode_peer_envelope(
                {"peer_wire_version": PEER_WIRE_VERSION + 1, "peer": "x"}
            )
        with pytest.raises(PeerWireError):
            decode_peer_envelope({"peer_wire_version": PEER_WIRE_VERSION})
        # The live listener nacks WireContractError subclasses — a bad
        # peer frame must ride the same path as a bad shipment.
        assert issubclass(PeerWireError, WireContractError)

    def test_gossip_in_rejects_non_members_and_self(self):
        peers = _mesh(2)
        stranger = GlobalPeer(
            "global-9", ["global-0", "global-1", "global-9"]
        )
        envelope = stranger.gossip_out("global-0", EPOCH_NS)
        with pytest.raises(PeerWireError):
            peers["global-0"].gossip_in(envelope, EPOCH_NS)
        own = peers["global-0"].gossip_out("global-1", EPOCH_NS)
        with pytest.raises(PeerWireError):
            peers["global-0"].gossip_in(own, EPOCH_NS)


def _live_mesh(tmp_path, ids, addressed=None, proxied=None, stale=STALE):
    """Build a live mesh with real listeners on pre-picked ports.

    ``addressed`` limits which peers get nodes (the rest stay
    membership-only: dark, but still ranked); ``proxied`` maps
    ``(from_pid, to_pid)`` to a substitute address.
    """
    addressed = addressed or ids
    proxied = proxied or {}
    ports = {pid: _free_port() for pid in addressed}
    addrs = {pid: f"tcp://127.0.0.1:{ports[pid]}" for pid in addressed}
    nodes = {}
    for pid in addressed:
        peer_addrs = {
            other: proxied.get((pid, other), addrs[other])
            for other in addressed
            if other != pid
        }
        nodes[pid] = LivePeerNode(
            pid,
            peer_addrs,
            tmp_path / pid,
            peer_ids=ids,
            port=ports[pid],
            peer_stale_after_ns=stale,
            client_timeout_s=0.5,
        )
    return nodes


class TestLivePeerMesh:
    def test_three_node_mesh_pages_once_over_sockets(self, tmp_path):
        ids = ["global-0", "global-1", "global-2"]
        nodes = _live_mesh(tmp_path, ids)
        region = ReconnectingClient(
            (nodes["global-0"].listener.host,
             nodes["global-0"].listener.port),
            tmp_path / "region-spool",
            timeout_s=2.0,
        )
        try:
            for i in range(3):
                assert region.send(
                    _env("region-a", i, [_fleet("region-a", i)])
                )
            _wait_until(
                lambda: nodes["global-0"].frames_ingested == 3
            )
            # The region's ack already names the mesh authority.
            assert region.remote_info["peer"] == "global-0"
            assert region.remote_info["leader"] == "global-0"
            released = []
            now = EPOCH_NS + 50 * GAP
            for r in range(6):
                time.sleep(0.1)
                for pid in ids:
                    released += [
                        (pid, p["incident_id"])
                        for p in nodes[pid].tick(
                            now + r * GAP, flush=(r == 0)
                        )
                    ]
            assert len(released) == 3
            assert all(pid == "global-0" for pid, _ in released)
            assert len({iid for _, iid in released}) == 3
            snap = nodes["global-0"].snapshot()
            assert snap["outbox"] == 0
            assert snap["epoch"] == 0
            # Followers hold every page; nobody re-emitted.
            assert nodes["global-1"].snapshot()["pages"] == 3
            assert nodes["global-2"].snapshot()["pages_emitted"] == 0
        finally:
            region.close()
            for node in nodes.values():
                node.close()

    def test_one_way_ack_loss_during_in_flight_election(self, tmp_path):
        """The defining WAN failure mid-election: the new claimant's
        gossip to one survivor arrives but the acks vanish, so the
        claim spreads while the claimant spools and replays the same
        rounds — the per-sender gossip cursor absorbs the storm, the
        mesh converges on one leader, and the fault injected during
        the chaos still pages exactly once."""
        ids = ["global-0", "global-1", "global-2"]
        g2_port = _free_port()
        proxy = WanProxy(("127.0.0.1", g2_port))
        nodes = {}
        try:
            ports = {"global-1": _free_port(), "global-2": g2_port}
            addrs = {
                pid: f"tcp://127.0.0.1:{port}"
                for pid, port in ports.items()
            }
            # global-0 never comes up: membership-only, rank 0, dark.
            nodes["global-1"] = LivePeerNode(
                "global-1",
                {"global-2": f"tcp://{proxy.host}:{proxy.port}"},
                tmp_path / "g1",
                peer_ids=ids,
                port=ports["global-1"],
                peer_stale_after_ns=STALE,
                client_timeout_s=0.5,
            )
            nodes["global-2"] = LivePeerNode(
                "global-2",
                {"global-1": addrs["global-1"]},
                tmp_path / "g2",
                peer_ids=ids,
                port=ports["global-2"],
                peer_stale_after_ns=STALE,
                client_timeout_s=0.5,
            )
            now = EPOCH_NS + 50 * GAP
            for r in range(2):
                for node in nodes.values():
                    node.tick(now + r * GAP)
                time.sleep(0.1)
            # Acks from global-2 back to global-1 vanish; frames still
            # arrive.  The election fires into this.
            proxy.partition(DIR_BACKWARD)
            nodes["global-1"]._handle(
                _env("region-a", 0, [_fleet("region-a")])
            )
            released = []
            later = now + STALE + 2 * GAP
            for r in range(4):
                for node in nodes.values():
                    released += node.tick(
                        later + r * GAP, flush=(r == 0)
                    )
                time.sleep(0.1)
            g1 = nodes["global-1"].snapshot()
            assert g1["is_leader"] and g1["epoch"] >= 1
            # The claim crossed despite the ack loss...
            _wait_until(
                lambda: nodes["global-2"].snapshot()["leader"]
                == "global-1"
            )
            # ...while the unacked rounds piled into the spool.
            assert g1["clients"]["global-2"]["spooled"] > 0
            proxy.heal(DIR_BACKWARD)
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                for node in nodes.values():
                    released += node.tick(later + 10 * GAP)
                snap = nodes["global-1"].snapshot()
                if (
                    snap["outbox"] == 0
                    and snap["pages_released"] == 1
                    and snap["clients"]["global-2"]["spooled"] == 0
                ):
                    break
                time.sleep(0.1)
            g1 = nodes["global-1"].snapshot()
            g2 = nodes["global-2"].snapshot()
            assert g1["pages_released"] == 1
            assert g1["outbox"] == 0
            assert g1["clients"]["global-2"]["spooled"] == 0
            assert g2["leader"] == "global-1"
            assert g2["epoch"] == g1["epoch"]
            # Replayed rounds were absorbed, not re-folded.
            assert g2["peers"]["global-1"]["duplicates"] > 0
            # Exactly one page mesh-wide, stamped by the new leader.
            assert len(released) == 1
            assert released[0]["peer"] == "global-1"
            assert g2["pages"] == 1 and g2["pages_emitted"] == 0
        finally:
            proxy.close()
            for node in nodes.values():
                node.close()


class TestPeerCLI:
    def test_fleetagg_peer_batch_rounds_converge(self, tmp_path, capsys):
        """Iterated ``fleetagg --peer`` runs exchanging gossip files
        ARE the anti-entropy loop: the leader parks and reports its
        outbox honestly, the follower folds page + row together, and
        the next leader run confirms and releases."""
        from tpuslo.cli.fleetagg import main as fleetagg_main

        region_log = tmp_path / "region-a.jsonl"
        region_log.write_text(
            "".join(
                global_envelope_json_line(
                    _env("region-a", i, [_fleet("region-a", i)])
                )
                for i in range(3)
            )
        )
        mesh = "global-0,global-1,global-2"
        state_a = tmp_path / "a-state.json"
        state_b = tmp_path / "b-state.json"
        gossip_a = tmp_path / "a-gossip.jsonl"
        gossip_b = tmp_path / "b-gossip.jsonl"
        assert fleetagg_main([
            "--peer", "--global-id", "global-0", "--peer-ids", mesh,
            "--state-out", str(state_a),
            "--peer-gossip-out", str(gossip_a),
            "--json", str(region_log),
        ]) == 0
        round1 = json.loads(capsys.readouterr().out)
        assert round1["is_leader"] is True
        assert round1["pages_released"] == 0
        assert round1["outbox_unconfirmed"] == 3
        assert fleetagg_main([
            "--peer", "--global-id", "global-1", "--peer-ids", mesh,
            "--state-out", str(state_b),
            "--peer-gossip-out", str(gossip_b),
            "--json", str(gossip_a),
        ]) == 0
        follower = json.loads(capsys.readouterr().out)
        assert follower["is_leader"] is False
        assert follower["pages"] == 3
        incidents_out = tmp_path / "pages.jsonl"
        assert fleetagg_main([
            "--peer", "--global-id", "global-0", "--peer-ids", mesh,
            "--restore-state", str(state_a),
            "--state-out", str(state_a),
            "--incidents-out", str(incidents_out),
            "--json", str(gossip_b),
        ]) == 0
        confirmed = json.loads(capsys.readouterr().out)
        assert confirmed["pages_released"] == 3
        assert confirmed["outbox_unconfirmed"] == 0
        pages = [
            json.loads(line)
            for line in incidents_out.read_text().splitlines()
        ]
        assert len(pages) == 3
        assert all(
            (p["epoch"], p["peer"]) == (0, "global-0") for p in pages
        )

    def test_fleetagg_peer_flag_conflicts(self, capsys):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        rc = fleetagg_main(["--peer", "--global-tier", "x.jsonl"])
        assert rc == 2
        assert "--peer" in capsys.readouterr().err
        rc = fleetagg_main(
            ["--peer", "--peer-upstream", "g1=tcp://h:1", "x.jsonl"]
        )
        assert rc == 2
        assert "live-only" in capsys.readouterr().err
        rc = fleetagg_main(["--peer-ids", "a,b", "x.jsonl"])
        assert rc == 2
        assert "--peer" in capsys.readouterr().err


class TestPeerSweepSmall:
    def test_small_sweep_passes_all_lanes(self):
        report = run_peer_sweep(
            peers=3,
            nodes_per_region=24,
            measure_ingest_lane=False,
        )
        assert report.passed, report.failures
        handover = report.handover
        assert (
            handover["first_successor_round"]
            <= handover["kill_round"] + handover["election_bound_rounds"]
        )
        assert handover["lost"] == [] and handover["duplicated"] == []
        assert report.splitbrain["sides_elected"] == {
            "a": True, "b": True,
        }
        assert len(set(
            report.splitbrain["final_leaders"].values()
        )) == 1
        assert report.deposed["stale_emits"] == []
        assert report.deposed["stale_pages_dropped"] >= 1

    def test_sweep_rejects_degenerate_mesh(self):
        with pytest.raises(ValueError):
            run_peer_sweep(peers=2)
