"""Columnar schema units: dtype contract, pool, adapters, arena."""

from datetime import datetime, timezone

import numpy as np
import pytest

from tpuslo.columnar.schema import (
    COLUMNS_FOR_FIELD,
    PROBE_EVENT_DTYPE,
    STRING_COLUMNS,
    ColumnarBatch,
    StringPool,
    alloc_batch_columns,
    empty_batch,
    from_payloads,
    from_rows,
    to_payloads,
    to_rows,
)
from tpuslo.schema import ConnTuple, ProbeEventV1, TPURef

TS = int(datetime(2026, 1, 1, tzinfo=timezone.utc).timestamp() * 1e9)


def _event(i: int = 0, **overrides) -> ProbeEventV1:
    base = dict(
        ts_unix_nano=TS + i,
        signal="dns_latency_ms",
        node="node-0",
        namespace="llm",
        pod="pod-1",
        container="c",
        pid=3,
        tid=4,
        value=12.5,
        unit="ms",
        status="ok",
    )
    base.update(overrides)
    return ProbeEventV1(**base)


class TestDtypeContract:
    def test_every_dataclass_field_is_mapped(self):
        import dataclasses

        field_names = {f.name for f in dataclasses.fields(ProbeEventV1)}
        assert field_names == set(COLUMNS_FOR_FIELD)

    def test_every_mapped_column_exists_and_none_is_orphaned(self):
        mapped = {c for cols in COLUMNS_FOR_FIELD.values() for c in cols}
        assert mapped == set(PROBE_EVENT_DTYPE.names)

    def test_string_columns_are_dtype_columns(self):
        assert set(STRING_COLUMNS) <= set(PROBE_EVENT_DTYPE.names)


class TestStringPool:
    def test_code_zero_is_empty_string(self):
        pool = StringPool()
        assert pool.get(0) == ""
        assert pool.intern("") == 0

    def test_intern_is_stable_and_append_only(self):
        pool = StringPool()
        a = pool.intern("x")
        b = pool.intern("y")
        assert pool.intern("x") == a
        assert (a, b) == (1, 2)
        assert pool.strings == ["", "x", "y"]

    def test_derived_caches_extend_after_growth(self):
        pool = StringPool()
        pool.intern("x")
        h1 = pool.content_hashes()
        e1 = list(pool.escaped())  # escaped() returns the live cache
        pool.intern('needs "escaping"')
        h2 = pool.content_hashes()
        e2 = pool.escaped()
        assert len(h2) == len(e2) == 3
        assert list(h2[:2]) == list(h1)
        assert e2[:2] == e1
        assert e2[2] == '"needs \\"escaping\\""'


class TestRowAdapters:
    def test_round_trip_plain_event(self):
        events = [_event(i) for i in range(5)]
        assert to_rows(from_rows(events)) == events

    def test_round_trip_full_envelopes(self):
        events = [
            _event(
                0,
                conn_tuple=ConnTuple("1.2.3.4", "5.6.7.8", 1, 2, "tcp"),
                trace_id="t-1",
                span_id="s-1",
                errno=110,
                confidence=0.5,
                tpu=TPURef(
                    chip="accel0",
                    slice_id="sl",
                    host_index=2,
                    ici_link=0,
                    program_id="jit",
                    launch_id=7,
                    module_name="mod",
                ),
            ),
            _event(1, errno=0, confidence=0.0),  # present-but-zero
            _event(2, tpu=TPURef()),  # empty tpu block
        ]
        back = to_rows(from_rows(events))
        assert back == events
        # errno=0 and confidence=0.0 are PRESENT (to_dict emits them).
        assert back[1].errno == 0
        assert back[1].confidence == 0.0

    def test_value_normalizes_to_float(self):
        back = to_rows(from_rows([_event(0, value=12)]))
        assert back[0].value == 12.0
        assert isinstance(back[0].value, float)

    def test_payload_round_trip_matches_to_dict(self):
        events = [
            _event(0, trace_id="t", errno=7),
            _event(
                1,
                conn_tuple=ConnTuple("1.2.3.4", "5.6.7.8", 1, 2, "udp"),
                tpu=TPURef(chip="accel1", launch_id=3),
            ),
        ]
        batch = from_rows(events)
        expected = []
        for e in to_rows(batch):  # float-normalized view
            expected.append(e.to_dict())
        assert to_payloads(batch) == expected

    def test_from_payloads_separates_rejects_with_input_index(self):
        good = _event(0).to_dict()
        bad = {"nope": 1}
        batch, rejects = from_payloads([good, bad, dict(good)])
        assert len(batch) == 2
        assert [i for i, _ in rejects] == [1]
        assert rejects[0][1] is bad

    def test_structured_round_trip(self):
        events = [_event(i, trace_id=f"t{i}") for i in range(4)]
        batch = from_rows(events)
        packed = batch.to_structured()
        assert packed.dtype == PROBE_EVENT_DTYPE
        again = ColumnarBatch.from_structured(packed, batch.pool)
        assert to_rows(again) == events


class TestBatchOps:
    def test_take_and_with_column_share_pool(self):
        events = [_event(i) for i in range(6)]
        batch = from_rows(events)
        sub = batch.take(np.array([1, 3]))
        assert sub.pool is batch.pool
        assert to_rows(sub) == [events[1], events[3]]
        ts = sub.column("ts_unix_nano") + 5
        bumped = sub.with_column("ts_unix_nano", ts)
        assert bumped.column("value") is sub.column("value")
        assert to_rows(bumped)[0].ts_unix_nano == events[1].ts_unix_nano + 5

    def test_empty_batch_defaults(self):
        batch = empty_batch(3)
        assert np.isnan(batch.column("confidence")).all()
        assert (batch.column("tpu_launch_id") == -1).all()
        assert len(empty_batch(0)) == 0

    def test_arena_views_cover_every_dtype_field(self):
        cols = alloc_batch_columns(17)
        assert set(cols) == set(PROBE_EVENT_DTYPE.names)
        for name, fmt in zip(
            PROBE_EVENT_DTYPE.names,
            (PROBE_EVENT_DTYPE[n] for n in PROBE_EVENT_DTYPE.names),
        ):
            assert cols[name].dtype == fmt
            assert len(cols[name]) == 17
        # Views must be writable and disjoint.
        cols["ts_unix_nano"][:] = 7
        cols["pid"][:] = 9
        assert (cols["ts_unix_nano"] == 7).all()
        assert (cols["pid"] == 9).all()


class TestHotpathRegistration:
    def test_columnar_kernels_are_lint_governed(self):
        from tpuslo.analysis.hotpaths import HOT_DATACLASSES, HOT_FUNCTIONS

        functions = {qual for _, qual in HOT_FUNCTIONS}
        assert {
            "columns_from_samples",
            "ColumnarGate.admit_batch",
            "match_columns",
            "log_posterior_batch",
            "serialize_jsonl",
        } <= functions
        classes = {name for _, name in HOT_DATACLASSES}
        assert {"ColumnarBatch", "StringPool", "MatchColumns"} <= classes


@pytest.mark.parametrize("n", [0, 1, 257])
def test_from_rows_sizes(n):
    events = [_event(i, trace_id=f"t{i % 7}") for i in range(n)]
    batch = from_rows(events)
    assert len(batch) == n
    assert to_rows(batch) == events


class TestPosteriorJitAutoTuner:
    """The measured engagement policy (ISSUE 12 satellite): auto mode
    may only engage jit where a timed probe on the call's own inputs
    says jit wins — the ROADMAP #5 regression (always-on jit at 0.63x
    numpy on a 1-CPU host) cannot recur by construction."""

    def _inputs(self, n_rows: int):
        from tpuslo.attribution.calibrate import calibrated_attributor

        attributor = calibrated_attributor()
        mats = attributor._matrices().kernel
        rng = np.random.default_rng(11)
        n_sig = len(attributor.likelihoods)
        values = np.abs(rng.lognormal(2.0, 1.5, (n_rows, n_sig)))
        observed = rng.random((n_rows, n_sig)) < 0.9
        return values, observed, mats, attributor.sharpness

    def test_probe_bucket_rounds_down_to_measured_rows(self):
        """Review regression: the probe slices the call's inputs to
        the bucket, so the bucket must fit INSIDE the row count — an
        upward round would cache a verdict for more rows than it
        timed."""
        from tpuslo.columnar.posterior import (
            JIT_PROBE_MAX_ROWS,
            _row_bucket,
        )

        assert _row_bucket(5000) == 4096
        assert _row_bucket(4096) == 4096
        assert _row_bucket(8191) == 4096
        assert _row_bucket(1) == 1
        assert _row_bucket(10 ** 9) == JIT_PROBE_MAX_ROWS

    def test_below_floor_never_probes(self, monkeypatch):
        from tpuslo.columnar import posterior

        monkeypatch.delenv("TPUSLO_COLUMNAR_JIT", raising=False)
        assert posterior.resolve_use_jax(100, None) is False
        assert posterior.resolve_use_jax(
            posterior.JIT_MIN_BATCH - 1, None
        ) is False

    def test_explicit_and_env_override_skip_probe(self, monkeypatch):
        from tpuslo.columnar import posterior

        assert posterior.resolve_use_jax(10, True) is True
        assert posterior.resolve_use_jax(1 << 20, False) is False
        monkeypatch.setenv("TPUSLO_COLUMNAR_JIT", "0")
        assert posterior.resolve_use_jax(1 << 20, None) is False
        monkeypatch.setenv("TPUSLO_COLUMNAR_JIT", "1")
        assert posterior.resolve_use_jax(1 << 20, None) is True

    def test_min_rows_env_moves_the_floor(self, monkeypatch):
        from tpuslo.columnar import posterior

        monkeypatch.delenv("TPUSLO_COLUMNAR_JIT", raising=False)
        monkeypatch.setenv("TPUSLO_COLUMNAR_JIT_MIN_ROWS", "50000")
        assert posterior.resolve_use_jax(8192, None) is False
        assert posterior.resolve_use_jax(50000, None) is None

    def test_auto_probe_caches_and_reports(self, monkeypatch):
        from tpuslo.columnar import posterior
        from tpuslo.columnar.posterior import log_posterior_batch

        monkeypatch.delenv("TPUSLO_COLUMNAR_JIT", raising=False)
        monkeypatch.delenv("TPUSLO_COLUMNAR_JIT_MIN_ROWS", raising=False)
        monkeypatch.setattr(posterior, "_AUTO_PROBES", {})
        values, observed, mats, sharpness = self._inputs(
            posterior.JIT_MIN_BATCH
        )
        post, _w, _o = log_posterior_batch(
            values, observed, mats, soft=True, sharpness=sharpness,
            use_jax=None,
        )
        report = posterior.auto_report()
        assert len(report["probes"]) == 1
        (probe,) = report["probes"].values()
        assert probe["rows"] == posterior.JIT_MIN_BATCH
        assert probe["speedup"] > 0
        # Whatever the probe decided, the auto result matches the path
        # it chose (parity of the two kernels is asserted elsewhere).
        expected, _w2, _o2 = log_posterior_batch(
            values, observed, mats, soft=True, sharpness=sharpness,
            use_jax=probe["jit_wins"],
        )
        assert np.allclose(post, expected, atol=1e-9)
        # Second call reuses the cached verdict (no new probe entry).
        log_posterior_batch(
            values, observed, mats, soft=True, sharpness=sharpness,
            use_jax=None,
        )
        assert len(posterior.auto_report()["probes"]) == 1
        threshold = posterior.auto_threshold()
        if probe["jit_wins"]:
            assert threshold == posterior.JIT_MIN_BATCH
        else:
            assert threshold is None
