"""Live deployment plane unit tests (ISSUE 17).

Tier-1 coverage for the livenet transport and its supervision: frame
codec edges (torn length prefix, partial recv boundaries, oversized
rejection), the seq journal's transport-switch parity (the satellite
contract: switching file <-> socket mid-life neither replays nor
skips), loopback listener + reconnecting client (ack pressure, spool
replay without duplicating the in-flight shipment), the pressure
sidecar + cadence coarsening, the process supervisor, and the agent
``--fleet-upstream`` regression: the file hop consumes the published
pressure level and measurably coarsens at level >= 1 (the bug this PR
fixes — it used to ship at a fixed cadence no matter the signal).

The multi-process chaos lane lives in ``tests/test_live_procs.py``
(chaos marker, out of tier-1).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import struct
import sys
import time

import pytest

from tpuslo.federation.backpressure import PressureSignal
from tpuslo.fleet.wire import WireContractError, last_recorded_seq
from tpuslo.livenet import (
    FrameDecoder,
    FramingError,
    LiveListener,
    ProcessSpec,
    ProcessSupervisor,
    ReconnectingClient,
    SeqJournal,
    ShipmentCadence,
    encode_frame,
    parse_socket_url,
    pressure_sidecar_path,
    read_pressure_file,
    resolve_resume_seq,
    write_pressure_file,
)
from tpuslo.livenet.framing import FRAME_MAGIC, FRAME_VERSION, HEADER_BYTES
from tpuslo.runtime.supervisor import SupervisorConfig


class TestFraming:
    def test_round_trip_multiple_frames_one_chunk(self):
        frames = [{"seq": i, "payload": "x" * i} for i in range(5)]
        blob = b"".join(encode_frame(f) for f in frames)
        assert FrameDecoder().feed(blob) == frames

    def test_torn_frame_mid_length_prefix(self):
        frame = encode_frame({"seq": 7})
        dec = FrameDecoder()
        # Half the header: not even the length is known yet.
        assert dec.feed(frame[: HEADER_BYTES // 2]) == []
        assert dec.pending_bytes() == HEADER_BYTES // 2
        # The rest arrives: exactly one frame, nothing buffered.
        assert dec.feed(frame[HEADER_BYTES // 2 :]) == [{"seq": 7}]
        assert dec.pending_bytes() == 0

    def test_partial_reads_across_recv_boundaries(self):
        frames = [{"seq": i, "body": "b" * 50} for i in range(3)]
        blob = b"".join(encode_frame(f) for f in frames)
        dec = FrameDecoder()
        out = []
        # Worst-case recv fragmentation: one byte per feed.
        for i in range(len(blob)):
            out.extend(dec.feed(blob[i : i + 1]))
        assert out == frames
        assert dec.pending_bytes() == 0

    def test_torn_trailing_frame_stays_buffered(self):
        good = encode_frame({"seq": 1})
        torn = encode_frame({"seq": 2})[:-3]
        dec = FrameDecoder()
        assert dec.feed(good + torn) == [{"seq": 1}]
        assert dec.pending_bytes() == len(torn)

    def test_oversized_frame_rejected_before_payload(self):
        dec = FrameDecoder(max_frame_bytes=1024)
        header = struct.pack("!HBI", FRAME_MAGIC, FRAME_VERSION, 1 << 30)
        # The header alone must trip the ceiling: no payload byte is
        # ever needed (a corrupt length cannot force an allocation).
        with pytest.raises(FramingError, match="ceiling"):
            dec.feed(header)

    def test_bad_magic_refused(self):
        with pytest.raises(FramingError, match="magic"):
            FrameDecoder().feed(struct.pack("!HBI", 0xDEAD, 1, 4))

    def test_future_version_refused(self):
        header = struct.pack("!HBI", FRAME_MAGIC, FRAME_VERSION + 1, 2)
        with pytest.raises(FramingError, match="version"):
            FrameDecoder().feed(header)

    def test_non_object_payload_refused(self):
        body = b"[1,2]"
        blob = struct.pack(
            "!HBI", FRAME_MAGIC, FRAME_VERSION, len(body)
        ) + body
        with pytest.raises(FramingError, match="JSON object"):
            FrameDecoder().feed(blob)

    def test_garbage_payload_refused(self):
        body = b"\xff\xfe not json"
        blob = struct.pack(
            "!HBI", FRAME_MAGIC, FRAME_VERSION, len(body)
        ) + body
        with pytest.raises(FramingError, match="not valid JSON"):
            FrameDecoder().feed(blob)

    def test_framing_error_is_a_wire_contract_error(self):
        # The listener's nack path catches WireContractError once for
        # both envelope and framing refusals.
        assert issubclass(FramingError, WireContractError)


class TestSocketUrl:
    def test_plain_path_is_not_a_socket(self):
        assert parse_socket_url("/var/run/ship.jsonl") is None
        assert parse_socket_url("relative/ship.jsonl") is None

    def test_tcp_url_parses(self):
        assert parse_socket_url("tcp://10.0.0.1:7001") == ("10.0.0.1", 7001)

    def test_malformed_tcp_urls_refused(self):
        with pytest.raises(ValueError):
            parse_socket_url("tcp://nohost")
        with pytest.raises(ValueError):
            parse_socket_url("tcp://host:notaport")


class TestSeqJournal:
    def test_absent_node_matches_file_scan_absent_value(self, tmp_path):
        journal = SeqJournal(tmp_path / "seq.json")
        log = tmp_path / "empty.jsonl"
        log.write_text("")
        # Both transports use -1 as "never recorded": first shipment
        # is seq 0 either way.
        assert journal.last_recorded_seq("n1") == -1
        assert last_recorded_seq(str(log), "n1") == -1
        assert resolve_resume_seq("n1") == -1

    def test_record_is_monotonic_and_survives_restart(self, tmp_path):
        path = tmp_path / "seq.json"
        journal = SeqJournal(path)
        journal.record("n1", 4)
        journal.record("n1", 2)  # stale: ignored
        journal.record("n2", 0)
        reborn = SeqJournal(path)
        assert reborn.last_recorded_seq("n1") == 4
        assert reborn.last_recorded_seq("n2") == 0

    def test_corrupt_journal_reads_as_absent(self, tmp_path):
        path = tmp_path / "seq.json"
        path.write_text("{torn")
        assert SeqJournal(path).last_recorded_seq("n1") == -1
        path.write_text(json.dumps({"v": 99, "nodes": {"n1": 7}}))
        assert SeqJournal(path).last_recorded_seq("n1") == -1

    def test_transport_switch_file_to_socket_resumes_identically(
        self, tmp_path
    ):
        """The satellite contract: a node that shipped seqs 0..4 over
        the file hop (journal maintained alongside the log) resumes at
        the same place when restarted with a tcp:// upstream — no
        local log to scan, the journal alone carries the cursor."""
        from tpuslo.columnar.schema import from_rows
        from tpuslo.fleet.wire import ShipmentWriter, encode_shipment
        from tpuslo.schema import ProbeEventV1

        log = tmp_path / "ship.jsonl"
        journal = SeqJournal(tmp_path / "seq.json")
        writer = ShipmentWriter(str(log))
        batch = from_rows(
            [
                ProbeEventV1(
                    ts_unix_nano=1_700_000_000_000_000_000,
                    signal="dns_latency_ms",
                    node="n1",
                    namespace="tenant-a",
                    pod="n1-pod-0",
                    container="workload",
                    pid=100,
                    tid=100,
                    value=5.0,
                    unit="ms",
                    status="ok",
                )
            ]
        )
        for seq in range(5):
            writer.send(
                "fleet",
                [encode_shipment(batch, "n1", seq, transport="base64")],
            )
            journal.record("n1", seq)
        writer.close()
        file_resume = resolve_resume_seq(
            "n1", upstream_log=str(log), journal=journal
        )
        socket_resume = resolve_resume_seq("n1", journal=journal)
        assert file_resume == socket_resume == 4

    def test_transport_switch_socket_to_file_resumes_identically(
        self, tmp_path
    ):
        # Socket mode journaled 0..6; the node restarts pointed at a
        # FRESH file log (scans empty).  The shared journal must win:
        # resuming at -1 would re-ship seqs the aggregator's cursor
        # eats as duplicates — silent loss.
        journal = SeqJournal(tmp_path / "seq.json")
        for seq in range(7):
            journal.record("n1", seq)
        fresh_log = tmp_path / "fresh.jsonl"
        fresh_log.write_text("")
        assert (
            resolve_resume_seq(
                "n1", upstream_log=str(fresh_log), journal=journal
            )
            == 6
        )


def _free_port() -> int:
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def _rebind_listener(handler, port: int, timeout_s: float = 5.0):
    """Rebind a listener on a just-vacated port.  The previous
    connection's FIN exchange races the rebind: until the peer's close
    lands, the old accepted socket still holds the address."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            return LiveListener(handler, port=port)
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.05)


def _wait_until(cond, timeout_s: float = 5.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError("condition not met within timeout")


class TestLoopback:
    def test_send_ack_carries_pressure_level(self, tmp_path):
        received = []
        listener = LiveListener(received.append, pressure=lambda: 2)
        client = ReconnectingClient(
            (listener.host, listener.port), tmp_path / "spool"
        )
        try:
            assert client.pressure_level == -1  # never acked yet
            assert client.send({"seq": 0, "hello": "world"}) is True
            assert received == [{"seq": 0, "hello": "world"}]
            assert client.pressure_level == 2
            assert client.sent_frames == 1
            assert client.pending_spooled() == 0
        finally:
            client.close()
            listener.close()

    def test_contract_refusal_nacks_but_counts_delivered(self, tmp_path):
        def handler(payload):
            if payload.get("seq") == 1:
                raise WireContractError("duplicate shipment")

        listener = LiveListener(handler)
        client = ReconnectingClient(
            (listener.host, listener.port), tmp_path / "spool"
        )
        try:
            assert client.send({"seq": 1}) is True
            _wait_until(lambda: listener.frames_rejected == 1)
            # Delivered-and-refused: never spooled, never replayed —
            # a poison frame must not dam the spool forever.
            assert client.nacked_frames == 1
            assert client.pending_spooled() == 0
        finally:
            client.close()
            listener.close()

    def test_spool_replay_resumes_without_duplicating_inflight(
        self, tmp_path
    ):
        port = _free_port()
        client = ReconnectingClient(
            (("127.0.0.1"), port), tmp_path / "spool", timeout_s=0.5
        )
        received = []
        try:
            # Upstream down: both sends spool, the loop never blocks.
            assert client.send({"seq": 0}) is False
            assert client.send({"seq": 1}) is False
            assert client.pending_spooled() == 2
            listener = LiveListener(
                received.append, port=port, pressure=lambda: 0
            )
            try:
                # The live send replays the spool oldest-first, THEN
                # delivers the in-flight payload — each exactly once,
                # in seq order.
                assert client.send({"seq": 2}) is True
                assert received == [{"seq": 0}, {"seq": 1}, {"seq": 2}]
                assert client.replayed_frames == 2
                assert client.pending_spooled() == 0
            finally:
                listener.close()
        finally:
            client.close()

    def test_reconnect_counted_and_logged(self, tmp_path):
        logs = []
        received = []
        listener = LiveListener(received.append)
        port = listener.port
        client = ReconnectingClient(
            (listener.host, port),
            tmp_path / "spool",
            peer="fleet",
            timeout_s=0.5,
            log=logs.append,
        )
        try:
            assert client.send({"seq": 0}) is True
            listener.close()
            assert client.send({"seq": 1}) is False  # spooled
            listener = _rebind_listener(received.append, port)
            _wait_until(lambda: client.send({"seq": 2}) is True)
            assert client.reconnects >= 1
            assert any("reconnected to fleet" in line for line in logs)
            assert [p["seq"] for p in received] == [0, 1, 2]
        finally:
            client.close()
            listener.close()

    def test_listener_drops_peer_on_framing_garbage(self, tmp_path):
        listener = LiveListener(lambda payload: None)
        try:
            raw = socket.create_connection(
                (listener.host, listener.port), timeout=2.0
            )
            raw.sendall(b"GET / HTTP/1.1\r\n\r\n")  # a foreign client
            _wait_until(lambda: listener.frames_rejected == 1)
            # The listener nacks once, then hangs up on us.
            _wait_until(lambda: raw.recv(65536) == b"" or True)
            raw.close()
            _wait_until(lambda: listener.connected_peers == 0)
        finally:
            listener.close()


class TestLiveAggregatorTicks:
    """Regressions for the live ``fleetagg --listen`` tick loop."""

    def test_shared_ingest_lock_excludes_tick_work(self, tmp_path):
        # run_live passes its state lock as the listener's ingest
        # lock: while a tick holds it (window close / pump), a peer
        # frame must wait instead of mutating the same shard/region
        # objects mid-sort.
        import threading

        lock = threading.Lock()
        received = []
        listener = LiveListener(received.append, ingest_lock=lock)
        client = ReconnectingClient(
            (listener.host, listener.port), tmp_path / "spool"
        )
        try:
            lock.acquire()  # the "tick" owns the aggregation state
            sender = threading.Thread(
                target=client.send, args=({"seq": 0},), daemon=True
            )
            sender.start()
            time.sleep(0.2)
            assert received == []  # frame parked behind the tick
            lock.release()
            _wait_until(lambda: received == [{"seq": 0}])
            sender.join(timeout=5.0)
        finally:
            client.close()
            listener.close()

    def test_quiet_cluster_heartbeats_envelope_every_tick(
        self, tmp_path, capsys
    ):
        # A live cluster with zero traffic still ships an (empty)
        # envelope per tick: the region's session-close clock is
        # min(cluster watermarks), so a quiet cluster that stays
        # silent freezes close_up_to for the whole tree.
        from tpuslo.cli.fleetagg import main as fleetagg_main

        upstream = tmp_path / "region.jsonl"
        rc = fleetagg_main(
            [
                "--listen", "127.0.0.1:0",
                "--cluster-id", "c1",
                "--region-upstream", str(upstream),
                "--run-for-s", "0.7",
                "--tick-s", "0.15",
            ]
        )
        capsys.readouterr()
        assert rc == 0
        envelopes = [
            json.loads(line)
            for line in upstream.read_text().splitlines()
            if line.strip()
        ]
        assert len(envelopes) >= 2
        assert all(env["cluster"] == "c1" for env in envelopes)
        assert all(env["incidents"] == [] for env in envelopes)
        seqs = [env["seq"] for env in envelopes]
        assert seqs == sorted(set(seqs))  # strictly increasing

    def test_live_region_writes_pressure_sidecar(
        self, tmp_path, capsys
    ):
        # --pressure-out promises a per-tick sidecar in live mode
        # regardless of role; the region role must publish it too.
        from tpuslo.cli.fleetagg import main as fleetagg_main

        sidecar = tmp_path / "region.pressure"
        rc = fleetagg_main(
            [
                "--region",
                "--listen", "127.0.0.1:0",
                "--region-id", "r-test",
                "--pressure-out", str(sidecar),
                "--run-for-s", "0.4",
                "--tick-s", "0.1",
            ]
        )
        capsys.readouterr()
        assert rc == 0
        sig = read_pressure_file(str(sidecar))
        assert sig is not None
        assert sig.source == "r-test"
        assert sig.level == 0


class TestPressureSidecar:
    def test_round_trip(self, tmp_path):
        path = pressure_sidecar_path(str(tmp_path / "ship.jsonl"))
        assert path.endswith(".pressure")
        write_pressure_file(
            path,
            PressureSignal(
                source="clu-0",
                level=2,
                backlog_events=80,
                capacity_events=100,
            ),
        )
        sig = read_pressure_file(path)
        assert sig is not None
        assert (sig.source, sig.level) == ("clu-0", 2)

    def test_missing_torn_and_foreign_read_as_none(self, tmp_path):
        assert read_pressure_file(str(tmp_path / "absent")) is None
        torn = tmp_path / "torn"
        torn.write_text('{"v": 1, "lev')
        assert read_pressure_file(str(torn)) is None
        foreign = tmp_path / "foreign"
        foreign.write_text(json.dumps({"v": 99, "level": 3}))
        assert read_pressure_file(str(foreign)) is None


class TestShipmentCadence:
    def test_level_zero_ships_every_cycle(self):
        cadence = ShipmentCadence()
        for _ in range(5):
            cadence.observe(0)
            assert cadence.should_flush() is True
        assert cadence.stats() == {
            "cycles": 5,
            "flushes": 5,
            "coarsened_cycles": 0,
            "max_level_seen": 0,
        }

    def test_level_one_ships_every_second_cycle(self):
        cadence = ShipmentCadence()
        flushes = []
        for _ in range(6):
            cadence.observe(1)
            flushes.append(cadence.should_flush())
        assert flushes == [False, True] * 3
        assert cadence.stats()["coarsened_cycles"] == 3

    def test_stride_saturates_at_level_three(self):
        cadence = ShipmentCadence()
        cadence.observe(3)
        assert cadence.stride() == 8
        cadence.observe(7)  # clamped, not 128
        assert cadence.stride() == 8

    def test_level_drop_flushes_held_evidence_immediately(self):
        cadence = ShipmentCadence()
        cadence.observe(3)
        assert cadence.should_flush() is False  # holding
        cadence.observe(0)  # pressure released
        # Held evidence must not age through the recovery.
        assert cadence.should_flush() is True

    def test_none_signal_keeps_current_level(self):
        cadence = ShipmentCadence()
        cadence.observe(2)
        cadence.observe(None)
        assert cadence.level == 2


class _FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


class TestProcessSupervisor:
    def _config(self, **overrides):
        base = dict(
            heartbeat_timeout_s=60.0,
            restart_backoff_base_s=0.0,
            flap_restarts=3,
            flap_window_s=300.0,
        )
        base.update(overrides)
        return SupervisorConfig(**base)

    def test_dead_child_restarted(self):
        sup = ProcessSupervisor(config=self._config())
        proc = sup.start(
            ProcessSpec(
                name="sleeper",
                cmd=[sys.executable, "-c", "import time; time.sleep(60)"],
            )
        )
        try:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            events = sup.evaluate()
            assert [e.action for e in events] == ["restarted"]
            assert sup.restart_count("sleeper") == 1
            reborn = sup.process("sleeper")
            assert reborn.pid != proc.pid
            assert reborn.poll() is None
        finally:
            sup.stop_all(wait_s=5.0)

    def test_clean_exit_is_completion_not_death(self):
        sup = ProcessSupervisor(config=self._config())
        proc = sup.start(
            ProcessSpec(name="oneshot", cmd=[sys.executable, "-c", "pass"])
        )
        try:
            proc.wait(timeout=10)
            assert sup.evaluate() == []
            assert sup.restart_count("oneshot") == 0
        finally:
            sup.stop_all(wait_s=5.0)

    def test_crash_looping_child_flap_shed(self):
        sup = ProcessSupervisor(config=self._config(flap_restarts=2))
        sup.start(
            ProcessSpec(
                name="crasher",
                cmd=[sys.executable, "-c", "raise SystemExit(1)"],
            )
        )
        try:
            _wait_until(lambda: bool(sup.evaluate()) or sup.is_shed("crasher"),
                        timeout_s=10.0)
            deadline = time.monotonic() + 10.0
            while not sup.is_shed("crasher"):
                assert time.monotonic() < deadline
                sup.evaluate()
                time.sleep(0.05)
            assert sup.flap_sheds_total == 1
            # A shed child is never restarted again.
            assert sup.evaluate() == []
        finally:
            sup.stop_all(wait_s=5.0)

    def test_stderr_and_stdout_accumulate_across_incarnations(
        self, tmp_path
    ):
        out_path = tmp_path / "child.out"
        err_path = tmp_path / "child.err"
        sup = ProcessSupervisor(config=self._config())
        spec = ProcessSpec(
            name="talker",
            cmd=[
                sys.executable,
                "-c",
                "import sys; print('born'); "
                "print('complaint', file=sys.stderr)",
            ],
            stdout_path=str(out_path),
            stderr_path=str(err_path),
            restart_on_clean_exit=True,
        )
        proc = sup.start(spec)
        try:
            proc.wait(timeout=10)
            assert sup.evaluate()  # restart the clean exit (opted in)
            sup.process("talker").wait(timeout=10)
        finally:
            sup.stop_all(wait_s=5.0)
        # One append-mode file per stream, reused across incarnations:
        # the chaos auditor greps restart evidence across kills.
        assert (tmp_path / "child.out").read_text().count("born") == 2
        assert err_path.read_text().count("complaint") == 2

    def test_stale_heartbeat_kills_and_restarts(self, tmp_path):
        beat = tmp_path / "beat"
        beat.write_text("x")
        os.utime(beat, (time.time() - 120, time.time() - 120))
        sup = ProcessSupervisor(
            config=self._config(heartbeat_timeout_s=1.0)
        )
        sup.start(
            ProcessSpec(
                name="wedged",
                cmd=[sys.executable, "-c", "import time; time.sleep(60)"],
                heartbeat_path=str(beat),
            )
        )
        try:
            events = sup.evaluate()
            assert [e.action for e in events] == ["restarted"]
            assert sup.restart_count("wedged") == 1
        finally:
            sup.stop_all(wait_s=5.0)


class TestAgentCadenceRegression:
    """Satellite fix: ``agent --fleet-upstream <path>`` must CONSUME
    the published pressure signal — it used to ship at a fixed cadence
    no matter what the aggregator published."""

    def _run_agent(self, log_path, tmp_path, cycles=8):
        from tpuslo.cli.agent import main as agent_main
        from tpuslo.metrics.registry import AgentMetrics

        rc = agent_main(
            [
                "--columnar",
                "--scenario", "hbm_pressure",
                "--columnar-batch", "4",
                "--count", str(cycles),
                "--interval-s", "0",
                "--node", "n-cad",
                "--metrics-port", "0",
                "--fleet-upstream", str(log_path),
                "--spool-dir", str(tmp_path / "spool"),
            ],
            metrics=AgentMetrics(),
        )
        assert rc == 0

    def test_no_signal_ships_every_cycle(self, tmp_path, capsys):
        log = tmp_path / "ship.jsonl"
        self._run_agent(log, tmp_path)
        err = capsys.readouterr().err
        assert "flushes=8" in err and "max_level=0" in err
        assert last_recorded_seq(str(log), "n-cad") == 7

    def test_level_two_coarsens_measurably(self, tmp_path, capsys):
        log = tmp_path / "ship.jsonl"
        write_pressure_file(
            pressure_sidecar_path(str(log)),
            PressureSignal(
                source="clu-0",
                level=2,
                backlog_events=80,
                capacity_events=100,
            ),
        )
        self._run_agent(log, tmp_path)
        err = capsys.readouterr().err
        # 8 cycles at stride 4: two merged shipments, not eight.
        assert "cycles=8 flushes=2 coarsened=6 max_level=2" in err
        assert last_recorded_seq(str(log), "n-cad") == 1
        # Nothing dropped: every gated event still shipped (merged).
        lines = [
            json.loads(line)
            for line in log.read_text().splitlines()
            if line.strip()
        ]
        assert len(lines) == 2

    def test_file_hop_journal_matches_log_scan(self, tmp_path):
        # Seq-resume parity, end to end: after a real file-hop run
        # with a spool dir, the journal and the log scan agree — so a
        # switch to tcp:// (journal only) resumes at the same seq.
        log = tmp_path / "ship.jsonl"
        self._run_agent(log, tmp_path)
        journal = SeqJournal(tmp_path / "spool" / "fleet-seq.json")
        assert journal.last_recorded_seq("n-cad") == last_recorded_seq(
            str(log), "n-cad"
        )
