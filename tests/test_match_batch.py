"""``match_batch`` parity with the pairwise ``match`` across all tiers.

The batched matcher answers each span with per-tier hash indexes and
timestamp-sorted window probes; the pairwise matcher is the semantic
ground truth.  Parity contract: for every span, ``match_batch`` returns
the highest-confidence pairwise decision over all signals, keeping the
first (lowest-index) signal on ties — including the inclusive /
exclusive window edges at 100, 250 and 500 ms and the global window.
"""

import dataclasses
import random
from datetime import datetime, timedelta, timezone

from tpuslo.correlation.matcher import (
    MISSING_TS_CONFIDENCE,
    Decision,
    SignalRef,
    SpanRef,
    match,
    match_batch,
)

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)


def span(**kw) -> SpanRef:
    kw.setdefault("timestamp", TS)
    return SpanRef(**kw)


def sigref(offset_ms=0.0, **kw) -> SignalRef:
    kw.setdefault("signal", "dns_latency_ms")
    kw.setdefault("timestamp", TS + timedelta(milliseconds=offset_ms))
    return SignalRef(**kw)


def best_pairwise(
    s: SpanRef, sigs: list[SignalRef], window_ms: int = 0
) -> tuple[int, Decision]:
    """Reference semantics: first strict-maximum pairwise decision."""
    best, best_i = Decision(), -1
    for i, candidate in enumerate(sigs):
        d = match(s, candidate, window_ms)
        if d.matched and d.confidence > best.confidence:
            best, best_i = d, i
    return best_i, best


def assert_parity(spans, sigs, window_ms=0):
    results = match_batch(spans, sigs, window_ms)
    assert len(results) == len(spans)
    for i, result in enumerate(results):
        expect_i, expect = best_pairwise(spans[i], sigs, window_ms)
        assert result.span_index == i
        assert result.signal_index == expect_i, (
            i, result, expect_i, expect
        )
        assert result.decision == expect, (i, result.decision, expect)


class TestTierParity:
    def test_each_tier_individually(self):
        cases = [
            (span(trace_id="t1"), sigref(trace_id="t1", offset_ms=1500)),
            (
                span(program_id="jit_step", launch_id=42),
                sigref(program_id="jit_step", launch_id=42, offset_ms=200),
            ),
            (span(pod="p", pid=11), sigref(pod="p", pid=11, offset_ms=90)),
            (
                span(pod="p", conn_tuple="tcp:a->b"),
                sigref(pod="p", conn_tuple="tcp:a->b", offset_ms=200),
            ),
            (
                span(slice_id="s0", host_index=1),
                sigref(slice_id="s0", host_index=1, offset_ms=240),
            ),
            (
                span(service="svc", node="n0"),
                sigref(service="svc", node="n0", offset_ms=400),
            ),
        ]
        for sp, sg in cases:
            assert_parity([sp], [sg])
        # All spans against all signals at once.
        assert_parity([c[0] for c in cases], [c[1] for c in cases])

    def test_window_edges_inclusive_and_exclusive(self):
        # Each tier window edge, exactly on it and 1ms past it, on both
        # sides of the span timestamp.
        tier_spans = {
            100: span(pod="p", pid=11),
            250: span(pod="p", conn_tuple="c"),
            500: span(service="svc", node="n0"),
            2000: span(trace_id="t"),
        }
        tier_signal = {
            100: dict(pod="p", pid=11),
            250: dict(pod="p", conn_tuple="c"),
            500: dict(service="svc", node="n0"),
            2000: dict(trace_id="t"),
        }
        for edge, sp in tier_spans.items():
            sigs = [
                sigref(offset_ms=sign * (edge + delta), **tier_signal[edge])
                for sign in (1, -1)
                for delta in (0, 1, -1)
            ]
            assert_parity([sp], sigs)
            for sig in sigs:
                assert_parity([sp], [sig])

    def test_xla_launch_250ms_edge(self):
        sp = span(program_id="jit", launch_id=5)
        sigs = [
            sigref(program_id="jit", launch_id=5, offset_ms=offset)
            for offset in (249, 250, 251, -250, -251)
        ]
        assert_parity([sp], sigs)

    def test_slice_host_250ms_edge(self):
        sp = span(slice_id="s", host_index=0)
        sigs = [
            sigref(slice_id="s", host_index=0, offset_ms=offset)
            for offset in (250, 251, -250, -251)
        ]
        assert_parity([sp], sigs)

    def test_custom_window_truncates_tier_windows(self):
        # A global window below a tier window clips that tier (the
        # pairwise matcher checks the global window first).
        sp = span(pod="p", conn_tuple="c", trace_id="t")
        sigs = [
            sigref(pod="p", conn_tuple="c", offset_ms=200),
            sigref(trace_id="t", offset_ms=180),
            sigref(trace_id="t", offset_ms=120),
        ]
        for window_ms in (50, 150, 190, 250, 2000):
            assert_parity([sp], sigs, window_ms)

    def test_tie_keeps_first_signal(self):
        sp = span(pod="p", pid=3)
        sigs = [
            sigref(pod="p", pid=3, offset_ms=80),
            sigref(pod="p", pid=3, offset_ms=10),  # closer but later index
        ]
        results = match_batch([sp], sigs)
        assert results[0].signal_index == 0
        assert_parity([sp], sigs)

    def test_higher_tier_on_later_signal_wins(self):
        sp = span(pod="p", pid=3, trace_id="t")
        sigs = [
            sigref(pod="p", pid=3, offset_ms=10),
            sigref(trace_id="t", offset_ms=1900),
        ]
        results = match_batch([sp], sigs)
        assert results[0].signal_index == 1
        assert results[0].decision.tier == "trace_id_exact"
        assert_parity([sp], sigs)

    def test_missing_timestamps_and_empty_inputs(self):
        assert match_batch([], []) == []
        no_ts_span = SpanRef(trace_id="t")
        no_ts_sig = SignalRef(trace_id="t")
        assert_parity([no_ts_span], [sigref(trace_id="t")])
        assert_parity([span(trace_id="t")], [no_ts_sig])
        # Trace identity joins across a missing timestamp — at the
        # capped confidence, never the windowed tier's 1.0.
        results = match_batch([span(trace_id="t")], [no_ts_sig])
        assert results[0].signal_index == 0
        assert results[0].decision.confidence == MISSING_TS_CONFIDENCE
        # A span with no timestamp joins the earliest trace-matching
        # signal, also capped.
        results = match_batch(
            [no_ts_span], [sigref(pod="x"), sigref(trace_id="t")]
        )
        assert results[0].signal_index == 1
        assert results[0].decision.confidence == MISSING_TS_CONFIDENCE
        # A windowed lower-tier match beats the capped trace fallback.
        results = match_batch(
            [span(trace_id="t", pod="p", pid=3)],
            [no_ts_sig, sigref(pod="p", pid=3, offset_ms=10)],
        )
        assert results[0].signal_index == 1
        assert results[0].decision.tier == "pod_pid_100ms"
        assert_parity(
            [span(trace_id="t", pod="p", pid=3)],
            [no_ts_sig, sigref(pod="p", pid=3, offset_ms=10)],
        )

    def test_duplicate_signals_keep_parity_and_first_index(self):
        # At-least-once delivery: exact duplicates in the signal batch
        # must not change any span's decision, and ties resolve to the
        # earliest copy, exactly like a pairwise first-maximum scan.
        sp = span(trace_id="t", pod="p", pid=3)
        base = [
            sigref(trace_id="t", offset_ms=5),
            sigref(pod="p", pid=3, offset_ms=10),
        ]
        duplicated = base + [dataclasses.replace(s) for s in base] + base
        results = match_batch([sp], duplicated)
        assert results[0].signal_index == 0
        assert results[0].decision.tier == "trace_id_exact"
        assert_parity([sp], duplicated)

    def test_reordered_signals_keep_parity(self):
        # Arrival order must not matter: shuffles of one signal batch
        # all agree with pairwise match on every span's confidence and
        # tier (the winning index follows the permuted position of the
        # same best candidate set).
        rng = random.Random(42)
        spans = [
            span(
                trace_id=f"t-{i}",
                pod="p",
                pid=i + 1,
                timestamp=TS + timedelta(milliseconds=i * 7),
            )
            for i in range(12)
        ]
        sigs = [
            sigref(trace_id=f"t-{i}", offset_ms=i * 7 + 3)
            for i in range(12)
        ] + [
            sigref(pod="p", pid=i + 1, offset_ms=i * 7 + 60)
            for i in range(12)
        ]
        baseline = {
            r.span_index: r.decision for r in match_batch(spans, sigs)
        }
        for _ in range(5):
            shuffled = list(sigs)
            rng.shuffle(shuffled)
            assert_parity(spans, shuffled)
            for result in match_batch(spans, shuffled):
                assert result.decision == baseline[result.span_index]

    def test_empty_identity_never_joins(self):
        # Empty strings / sentinel ints must not form index keys that
        # join with other empties (pairwise requires truthy span fields).
        assert_parity(
            [span(), span(pod="p"), span(pid=5), span(launch_id=0)],
            [sigref(), sigref(pod="p"), sigref(pid=5), sigref(launch_id=0)],
        )


class TestPropertyParity:
    def test_randomized_corpus(self):
        rng = random.Random(20260803)
        pods = ["", "pod-a", "pod-b"]
        traces = ["", "t1", "t2"]
        programs = ["", "jit_step"]
        services = ["", "rag"]
        nodes = ["", "n0", "n1"]
        conns = ["", "tcp:a->b"]
        slices = ["", "s0"]
        # Offsets clustered on the tier edges where parity is hardest.
        edges = [0, 1, 50, 99, 100, 101, 249, 250, 251, 499, 500, 501,
                 1999, 2000, 2001]

        def random_fields():
            return dict(
                trace_id=rng.choice(traces),
                pod=rng.choice(pods),
                pid=rng.choice([0, 1, 2]),
                conn_tuple=rng.choice(conns),
                slice_id=rng.choice(slices),
                host_index=rng.choice([-1, 0, 1]),
                program_id=rng.choice(programs),
                launch_id=rng.choice([-1, 0, 7]),
                service=rng.choice(services),
                node=rng.choice(nodes),
            )

        spans = [
            span(
                timestamp=TS + timedelta(milliseconds=rng.choice(edges)),
                **random_fields(),
            )
            for _ in range(60)
        ]
        sigs = [
            sigref(
                offset_ms=rng.choice([1, -1]) * rng.choice(edges),
                **random_fields(),
            )
            for _ in range(120)
        ]
        for window_ms in (0, 120, 300, 5000):
            assert_parity(spans, sigs, window_ms)
