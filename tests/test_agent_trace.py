"""End-to-end self-observability: `agent --trace` produces OTLP trace
payloads with >=6 stage spans per cycle, routes them through the
delivery layer, records incident provenance, and `sloctl explain`
prints the full causal chain.  Also covers the agent-wired /readyz."""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tpuslo.cli.agent import main as agent_main
from tpuslo.cli.sloctl import main as sloctl_main
from tpuslo.metrics import AgentMetrics


class _CaptureHandler(BaseHTTPRequestHandler):
    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        self.server.requests.append({"path": self.path, "body": body})
        self.send_response(200)
        self.end_headers()
        self.wfile.write(b"{}")

    def log_message(self, *args):
        pass


@pytest.fixture
def capture_server():
    server = HTTPServer(("127.0.0.1", 0), _CaptureHandler)
    server.requests = []
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield server
    server.shutdown()
    server.server_close()


def trace_spans(server):
    spans = []
    for req in server.requests:
        if req["path"] != "/v1/traces":
            continue
        payload = json.loads(req["body"])
        for rs in payload["resourceSpans"]:
            for scope in rs["scopeSpans"]:
                spans.extend(scope["spans"])
    return spans


class TestAgentTraceE2E:
    def test_every_cycle_ships_a_trace_with_stage_spans(
        self, capture_server, tmp_path
    ):
        endpoint = (
            f"http://127.0.0.1:{capture_server.server_address[1]}/v1/logs"
        )
        rc = agent_main(
            [
                "--scenario", "tpu_mixed", "--count", "4",
                "--interval-s", "0.01", "--event-kind", "both",
                "--output", "otlp", "--otlp-endpoint", endpoint,
                "--metrics-port", "0", "--max-overhead-pct", "1000",
                "--trace", "--trace-sample-rate", "1.0",
                "--spool-dir", str(tmp_path / "spool"),
                "--provenance-path", str(tmp_path / "prov.jsonl"),
            ],
            metrics=AgentMetrics(),
        )
        assert rc == 0
        spans = trace_spans(capture_server)
        roots = [s for s in spans if "parentSpanId" not in s]
        assert len(roots) == 4  # one trace per cycle at sample_rate 1.0
        for root in roots:
            children = [
                s
                for s in spans
                if s.get("parentSpanId") == root["spanId"]
                and s["traceId"] == root["traceId"]
            ]
            assert len(children) >= 6
            names = {c["name"] for c in children}
            assert {
                "generate", "ingest_gate", "validate", "correlate",
                "attribute", "deliver", "snapshot",
            } <= names
            for child in children:
                assert (
                    int(child["endTimeUnixNano"])
                    >= int(child["startTimeUnixNano"])
                )

    def test_slow_and_error_cycles_always_sampled(
        self, capture_server, tmp_path
    ):
        endpoint = (
            f"http://127.0.0.1:{capture_server.server_address[1]}/v1/logs"
        )
        # sample_rate 0 + an absurdly low slow budget: every cycle is a
        # "slow" cycle, so tail sampling must keep all of them.
        rc = agent_main(
            [
                "--scenario", "baseline", "--count", "3",
                "--interval-s", "0.01", "--event-kind", "probe",
                "--output", "otlp", "--otlp-endpoint", endpoint,
                "--metrics-port", "0", "--max-overhead-pct", "1000",
                "--trace", "--trace-sample-rate", "0.0",
                "--trace-slow-ms", "0.0001",
            ],
            metrics=AgentMetrics(),
        )
        assert rc == 0
        roots = [
            s for s in trace_spans(capture_server)
            if "parentSpanId" not in s
        ]
        assert len(roots) == 3
        by_key = {
            a["key"]: a["value"] for a in roots[0]["attributes"]
        }
        assert by_key["sampling"] == {"stringValue": "kept_slow"}

    def test_incident_cycles_always_sampled(self, capture_server, tmp_path):
        port = capture_server.server_address[1]
        # sample_rate 0 + huge slow budget: nothing qualifies for
        # sampling EXCEPT the force-keep on incident cycles, whose
        # provenance records point at these traces.
        rc = agent_main(
            [
                "--scenario", "tpu_mixed", "--count", "3",
                "--interval-s", "0.01", "--event-kind", "both",
                "--output", "otlp",
                "--otlp-endpoint", f"http://127.0.0.1:{port}/v1/logs",
                "--metrics-port", "0", "--max-overhead-pct", "1000",
                "--trace", "--trace-sample-rate", "0.0",
                "--trace-slow-ms", "1000000",
                "--provenance-path", str(tmp_path / "prov.jsonl"),
                "--webhook-url", f"http://127.0.0.1:{port}/hook",
            ],
            metrics=AgentMetrics(),
        )
        assert rc == 0
        roots = {
            s["traceId"]: s
            for s in trace_spans(capture_server)
            if "parentSpanId" not in s
        }
        assert len(roots) == 3  # every tpu_mixed cycle is a fault cycle
        records = [
            json.loads(line)
            for line in (tmp_path / "prov.jsonl").read_text().splitlines()
        ]
        for rec in records:
            assert rec["trace_id"] in roots, (
                "provenance must point at an exported trace"
            )
            assert rec["delivery"]["outcome"] == "ok"

    def test_provenance_chain_recorded_and_explained(
        self, tmp_path, capsys
    ):
        prov = tmp_path / "prov.jsonl"
        rc = agent_main(
            [
                "--scenario", "tpu_mixed", "--count", "4",
                "--interval-s", "0.01", "--event-kind", "both",
                "--output", "jsonl",
                "--jsonl-path", str(tmp_path / "events.jsonl"),
                "--metrics-port", "0", "--max-overhead-pct", "1000",
                "--trace", "--provenance-path", str(prov),
                # A webhook makes fault cycles produce incidents; the
                # dead port exercises the delivery-outcome recording.
                "--webhook-url", "http://127.0.0.1:9/hook",
            ],
            metrics=AgentMetrics(),
        )
        assert rc == 0
        assert prov.exists()
        records = [
            json.loads(line)
            for line in prov.read_text().splitlines()
            if line
        ]
        assert records  # tpu_mixed injects a fault every cycle
        rec = records[0]
        assert rec["trace_id"] and rec["root_span_id"]
        assert rec["predicted_fault_domain"]
        assert rec["events"], "supporting probe events must be recorded"
        assert rec["events"][0]["tier"] == "trace_id_exact"
        assert rec["correlation"]["matched"] >= 1
        assert rec["delivery"]["outcome"] == "error"  # dead webhook port
        # Finalized at cycle end: ALL stages present, including the two
        # most likely to explain a slow incident cycle.
        assert {"deliver", "snapshot"} <= set(rec["stages_ms"])

        # sloctl explain renders the full chain from the same file.
        rc = sloctl_main(
            ["explain", rec["incident_id"], "--provenance", str(prov)]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert f"incident {rec['incident_id']}" in out
        assert "1. probe events" in out
        assert "2. correlation:" in out
        assert "3. fault-domain posterior:" in out
        assert "4. alert delivery: outcome=error" in out

    def test_explain_lists_and_rejects_unknown(self, tmp_path, capsys):
        prov = tmp_path / "prov.jsonl"
        agent_main(
            [
                "--scenario", "tpu_mixed", "--count", "2",
                "--interval-s", "0.01", "--event-kind", "both",
                "--output", "jsonl",
                "--jsonl-path", str(tmp_path / "events.jsonl"),
                "--metrics-port", "0", "--max-overhead-pct", "1000",
                "--trace", "--provenance-path", str(prov),
                "--webhook-url", "http://127.0.0.1:9/hook",
            ],
            metrics=AgentMetrics(),
        )
        rc = sloctl_main(["explain", "--provenance", str(prov)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "agent-inc-0001" in out
        rc = sloctl_main(
            ["explain", "agent-inc-9999", "--provenance", str(prov)]
        )
        err = capsys.readouterr().err
        assert rc == 1
        assert "agent-inc-9999" in err

    def test_explain_missing_log_fails_cleanly(self, tmp_path, capsys):
        rc = sloctl_main(
            [
                "explain", "x",
                "--provenance", str(tmp_path / "absent.jsonl"),
            ]
        )
        assert rc == 1
        assert "no provenance records" in capsys.readouterr().err

    def test_trace_off_by_default_costs_nothing(self, tmp_path):
        metrics = AgentMetrics()
        rc = agent_main(
            [
                "--scenario", "baseline", "--count", "2",
                "--interval-s", "0.01", "--event-kind", "probe",
                "--output", "jsonl",
                "--jsonl-path", str(tmp_path / "events.jsonl"),
                "--metrics-port", "0", "--max-overhead-pct", "1000",
            ],
            metrics=metrics,
        )
        assert rc == 0
        # No trace verdicts recorded: the tracer never engaged.
        samples = [
            s
            for m in metrics.trace_cycles.collect()
            for s in m.samples
            if s.name.endswith("_total")
        ]
        assert sum(s.value for s in samples) == 0


class TestAgentReadyz:
    def test_readyz_reflects_running_agent(self, tmp_path):
        # Pick a free port first (the agent binds 0.0.0.0:port itself).
        import socket

        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            port = sk.getsockname()[1]

        done = threading.Event()
        rcs: list[int] = []

        def run():
            rcs.append(
                agent_main(
                    [
                        "--scenario", "baseline", "--count", "60",
                        "--interval-s", "0.05", "--event-kind", "probe",
                        "--output", "jsonl",
                        "--jsonl-path", str(tmp_path / "e.jsonl"),
                        "--metrics-port", str(port),
                        "--max-overhead-pct", "1000",
                    ],
                    metrics=AgentMetrics(),
                )
            )
            done.set()

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        status = None
        body = b""
        for _ in range(100):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=2
                ) as resp:
                    status, body = resp.status, resp.read()
                break
            except OSError:
                import time

                time.sleep(0.05)
        assert status == 200
        assert body == b"ok\n"
        done.wait(timeout=30)
        assert rcs == [0]
