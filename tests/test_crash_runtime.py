"""Crash chaos: kill -9 the real agent mid-run, restart, audit.

One seeded kill/restart cycle from the ``tpuslo.chaos.crash`` harness
(the full seeds × kill-points sweep runs via ``m5gate --crash-sweep``
/ ``make crash-sweep``).  SIGKILL is the one failure mode no in-process
test can fake: no atexit, no finally, no flush — whatever survives is
exactly what was already durable.

Marked ``chaos`` (run via ``make crash-smoke``) and ``slow`` (kept out
of the tier-1 ``-m 'not slow'`` lane: real subprocesses, real signals,
wall-clock cycles).
"""

from __future__ import annotations

import json

import pytest

from tpuslo.chaos.crash import run_crash_cycle

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


def test_seeded_kill_restart_cycle(tmp_path):
    result = run_crash_cycle(
        tmp_path / "crash", seed=1337, kill_point=0.5, count=14,
        interval_s=0.05,
    )
    assert result.passed, result.failures

    # The three crash-safety contracts, stated explicitly:
    assert result.torn_lines_replayed == 0
    assert result.lost_cycles == 0
    assert result.duplicate_alerts == 0

    # And the warm-restore evidence: the restarted agent resumed from
    # the snapshot with the ingest state intact.
    assert result.resumed_cycle >= 1
    assert "progress" in result.restored_components
    assert "gate" in result.restored_components
    assert "breakers" in result.restored_components
    assert result.restored_watermark_ns > 0

    # At-least-once overlap stays inside the post-snapshot window.
    assert result.duplicate_event_lines <= 11


def test_kill_mid_run_leaves_loadable_snapshot(tmp_path):
    """The snapshot a SIGKILL leaves behind is complete, never torn —
    the mkstemp + fsync + os.replace contract observed from outside."""
    result = run_crash_cycle(
        tmp_path / "crash", seed=7, kill_point=0.3, count=12,
        interval_s=0.05,
    )
    assert result.passed, result.failures
    snapshot_path = tmp_path / "crash" / "state" / "agent-state.json"
    snapshot = json.loads(snapshot_path.read_text())
    assert snapshot["schema_version"] == 1
    assert "progress" in snapshot["components"]
