"""Fast-path validator parity: structural checks == full jsonschema.

The probe spine validates with a hand-rolled structural fast path and
falls back to the precompiled jsonschema validator on anything it cannot
prove valid (tpuslo/schema/fastpath.py).  These tests lock in the
contract on a corpus of valid and malformed events:

* combined fast+fallback result is exactly jsonschema's verdict, and
* the fast path alone never accepts a payload jsonschema rejects
  (false positives would ship contract-breaking events).
"""

from datetime import datetime, timezone

from tpuslo import collector, signals
from tpuslo.schema import (
    SCHEMA_PROBE_EVENT,
    VALIDATION_COUNTERS,
    ConnTuple,
    ProbeEventV1,
    TPURef,
    fast_probe_event_valid,
    fast_probe_payload_valid,
    is_valid,
    validate_probe_event,
    validate_probe_payload,
)


def _event(**overrides) -> ProbeEventV1:
    base = dict(
        ts_unix_nano=1_700_000_000_000_000_000,
        signal="dns_latency_ms",
        node="node-0",
        namespace="llm",
        pod="rag-0",
        container="rag",
        pid=41,
        tid=42,
        value=12.5,
        unit="ms",
        status="ok",
    )
    base.update(overrides)
    return ProbeEventV1(**base)


def _generated_corpus() -> list[ProbeEventV1]:
    """Real generator output across every fault scenario: conn tuples,
    errno-carrying connect signals, and TPU identity blocks included."""
    meta = signals.Metadata(
        node="n0", namespace="llm", pod="p0", container="c0",
        pid=7, tid=8, tpu_chip="accel0", slice_id="slice-0",
        xla_program_id="jit_step",
    )
    gen = signals.Generator(signals.CAPABILITY_TPU_FULL)
    start = datetime(2026, 1, 1, tzinfo=timezone.utc)
    events: list[ProbeEventV1] = []
    for scenario in ("baseline", "tpu_mixed", "network_partition"):
        samples = collector.generate_synthetic_samples(
            scenario, 4, start, collector.SampleMeta()
        )
        events.extend(gen.generate_batch(samples, meta))
    return events


_MALFORMED_EVENTS = [
    _event(status="bogus"),
    _event(status=""),
    _event(ts_unix_nano=-1),
    _event(ts_unix_nano=True),
    _event(pid=-1),
    _event(tid=-2),
    _event(value="12.5"),
    _event(value=None),
    _event(signal=123),
    _event(unit=None),
    _event(errno="ECONNREFUSED"),
    _event(errno=True),
    _event(confidence=1.5),
    _event(confidence=-0.1),
    _event(confidence="high"),
    # Malformed conn_tuple blocks.
    _event(conn_tuple=ConnTuple("a", "b", -1, 443, "tcp")),
    _event(conn_tuple=ConnTuple("a", "b", 70000, 443, "tcp")),
    _event(conn_tuple=ConnTuple("a", "b", 1, 65536, "tcp")),
    _event(conn_tuple=ConnTuple(1, "b", 10, 443, "tcp")),
    _event(conn_tuple=ConnTuple("a", "b", "10", 443, "tcp")),
    _event(conn_tuple=ConnTuple("a", "b", 10, 443, None)),
]

_VALID_EVENTS = [
    _event(),
    _event(trace_id="t" * 32, span_id="s" * 16),
    _event(errno=111, conn_tuple=ConnTuple("10.0.0.1", "10.0.0.2", 1, 65535, "tcp")),
    _event(confidence=0.0),
    _event(confidence=1.0),
    _event(value=0),
    _event(tpu=TPURef()),
    _event(tpu=TPURef(chip="accel0", launch_id=0, host_index=0, ici_link=0)),
    # Negative TPU ints are omitted by to_dict, so they stay valid.
    _event(tpu=TPURef(chip="accel1", launch_id=-1, host_index=-5)),
    # A TPU signal with NO tpu block: the schema keeps the block
    # optional, so both paths must accept it.
    _event(signal="xla_compile_ms"),
]


class TestObjectParity:
    def test_generated_corpus_all_fastpath(self):
        for event in _generated_corpus():
            assert fast_probe_event_valid(event), event
            assert is_valid(event.to_dict(), SCHEMA_PROBE_EVENT), event

    def test_valid_corpus_parity(self):
        for event in _VALID_EVENTS:
            assert validate_probe_event(event) is True, event
            assert is_valid(event.to_dict(), SCHEMA_PROBE_EVENT), event

    def test_malformed_corpus_parity(self):
        for event in _MALFORMED_EVENTS:
            expected = is_valid(event.to_dict(), SCHEMA_PROBE_EVENT)
            assert validate_probe_event(event) is expected, event
            # No false positives: the fast path may only say True when
            # jsonschema agrees.
            if fast_probe_event_valid(event):
                assert expected, event

    def test_malformed_corpus_actually_malformed(self):
        # Guard the corpus itself: every entry must be a jsonschema
        # reject, or the parity assertions above prove nothing.
        for event in _MALFORMED_EVENTS:
            assert not is_valid(event.to_dict(), SCHEMA_PROBE_EVENT), event


class TestPayloadParity:
    def _payloads(self) -> list:
        payloads = [e.to_dict() for e in _generated_corpus() + _VALID_EVENTS]
        base = _event().to_dict()
        # Structural damage jsonschema must catch: missing required
        # keys, unknown keys, and sub-object violations.
        for key in base:
            broken = dict(base)
            del broken[key]
            payloads.append(broken)
        payloads.append({**base, "surprise": 1})
        payloads.append({**base, "conn_tuple": {}})
        payloads.append(
            {**base, "conn_tuple": {"src_ip": "a", "dst_ip": "b"}}
        )
        conn = ConnTuple("a", "b", 1, 2, "tcp").to_dict()
        payloads.append({**base, "conn_tuple": {**conn, "extra": 1}})
        payloads.append({**base, "conn_tuple": {**conn, "src_port": "1"}})
        payloads.append({**base, "tpu": {"chip": 5}})
        payloads.append({**base, "tpu": {"launch_id": -1}})
        payloads.append({**base, "tpu": {"host_index": True}})
        payloads.append({**base, "tpu": {"unknown": "x"}})
        payloads.append({**base, "errno": 1.5})
        payloads.append({**base, "pid": True})
        payloads.append({**base, "value": True})
        payloads.append({**base, "status": "breach"})
        payloads.append({**base, "tpu": {}})  # valid: all keys optional
        return payloads

    def test_payload_corpus_parity(self):
        for payload in self._payloads():
            expected = is_valid(payload, SCHEMA_PROBE_EVENT)
            assert validate_probe_payload(payload) is expected, payload
            if fast_probe_payload_valid(payload):
                assert expected, payload


class TestCounters:
    def test_fastpath_and_fallback_counted(self):
        VALIDATION_COUNTERS.reset()
        assert not VALIDATION_COUNTERS.engaged
        assert validate_probe_event(_event())
        assert VALIDATION_COUNTERS.engaged
        assert VALIDATION_COUNTERS.fastpath_valid == 1

        assert not validate_probe_event(_event(status="bogus"))
        snap = VALIDATION_COUNTERS.snapshot()
        assert snap["fastpath_fallback"] == 1
        assert snap["slowpath_invalid"] == 1

        # A jsonschema-valid shape the fast path cannot prove (float
        # with integral value is a jsonschema "integer").
        assert validate_probe_event(_event(pid=1.0))
        snap = VALIDATION_COUNTERS.snapshot()
        assert snap["fastpath_fallback"] == 2
        assert snap["slowpath_valid"] == 1
