"""Fleet observability plane: wire contract, hash ring, rollup
invariants, aggregator dedup/failover, the seeded simulator, and the
fleetagg / sloctl fleet CLIs.

The cross-node dedup-under-chaos tests (per-host ChaosStream skew /
dup / reorder at intensity 1.0 and 3.0) assert the two structural
rollup invariants the sweep gates on: one injected fleet fault never
splits into multiple incidents, and distinct (tenant, domain) faults
never merge — seeded, so failures replay bit-identically.
"""

from __future__ import annotations

import json

import pytest

from tpuslo.columnar.schema import (
    ColumnarBatch,
    concat_batches,
    empty_batch,
    from_rows,
    to_rows,
)
from tpuslo.fleet.aggregator import AggregatorShard
from tpuslo.fleet.ring import HashRing, node_key
from tpuslo.fleet.rollup import (
    BLAST_FLEET,
    BLAST_NODE,
    BLAST_POD,
    BLAST_SLICE,
    FleetIncident,
    FleetRollup,
    NodeIncident,
    classify_blast_radius,
)
from tpuslo.fleet.simulator import (
    EPOCH_NS,
    FaultInjection,
    FleetSimulator,
    FleetTopology,
    default_injection_plan,
)
from tpuslo.fleet.sweep import run_fleet_sweep, score_incidents
from tpuslo.fleet.wire import (
    FLEET_WIRE_VERSION,
    WIRE_EVENT_COLUMNS,
    ShipmentWriter,
    WireContractError,
    decode_shipment,
    encode_shipment,
    last_recorded_seq,
    load_shipments,
    parse_shipment_line,
    shipment_json_line,
)
from tpuslo.schema.types import ProbeEventV1


def _sample_batch(n: int = 8, node: str = "node-x") -> ColumnarBatch:
    events = [
        ProbeEventV1(
            ts_unix_nano=EPOCH_NS + i * 1_000_000,
            signal="dns_latency_ms",
            node=node,
            namespace="tenant-a",
            pod=f"{node}-pod-0",
            container="workload",
            pid=100 + i,
            tid=100 + i,
            value=float(5 + i),
            unit="ms",
            status="ok",
        )
        for i in range(n)
    ]
    return from_rows(events)


class TestWireContract:
    def test_binary_round_trip(self):
        batch = _sample_batch()
        payload = encode_shipment(batch, "node-x", 7, slice_id="slice-1")
        shipment = decode_shipment(payload)
        assert shipment.node == "node-x"
        assert shipment.seq == 7
        assert shipment.slice_id == "slice-1"
        assert shipment.events == batch.n
        assert shipment.head_ns == int(
            batch.column("ts_unix_nano").max()
        )
        assert to_rows(shipment.batch) == to_rows(batch)

    def test_base64_jsonl_round_trip(self):
        batch = _sample_batch()
        payload = encode_shipment(
            batch, "node-x", 0, transport="base64"
        )
        line = shipment_json_line(payload)
        shipment = parse_shipment_line(line)
        assert to_rows(shipment.batch) == to_rows(batch)

    def test_binary_payload_not_json_safe(self):
        payload = encode_shipment(_sample_batch(), "node-x", 0)
        with pytest.raises(WireContractError):
            shipment_json_line(payload)

    def test_version_mismatch_refused(self):
        payload = encode_shipment(_sample_batch(), "node-x", 0)
        payload["wire_version"] = FLEET_WIRE_VERSION + 1
        with pytest.raises(WireContractError, match="wire version"):
            decode_shipment(payload)

    def test_missing_node_refused(self):
        payload = encode_shipment(_sample_batch(), "node-x", 0)
        payload["node"] = ""
        with pytest.raises(WireContractError, match="node identity"):
            decode_shipment(payload)

    def test_column_drift_refused(self):
        payload = encode_shipment(_sample_batch(), "node-x", 0)
        del payload["columns"]["span_id"]
        with pytest.raises(WireContractError, match="column set drift"):
            decode_shipment(payload)
        payload = encode_shipment(_sample_batch(), "node-x", 0)
        payload["columns"]["extra_col"] = b""
        with pytest.raises(WireContractError, match="column set drift"):
            decode_shipment(payload)

    def test_truncated_buffer_refused(self):
        payload = encode_shipment(_sample_batch(), "node-x", 0)
        payload["columns"]["value"] = payload["columns"]["value"][:-4]
        with pytest.raises(WireContractError, match="bytes"):
            decode_shipment(payload)

    def test_pool_code_out_of_range_refused(self):
        batch = _sample_batch()
        payload = encode_shipment(batch, "node-x", 0)
        bad = batch.columns["signal"].copy()
        bad[0] = len(batch.pool.strings) + 5
        payload["columns"]["signal"] = bad.tobytes()
        with pytest.raises(WireContractError, match="outside"):
            decode_shipment(payload)

    def test_pool_must_start_with_empty_string(self):
        payload = encode_shipment(_sample_batch(), "node-x", 0)
        payload["pool"] = ["not-empty"] + payload["pool"][1:]
        with pytest.raises(WireContractError, match="pool"):
            decode_shipment(payload)

    def test_wire_columns_cover_dtype(self):
        from tpuslo.columnar.schema import PROBE_EVENT_DTYPE

        assert set(WIRE_EVENT_COLUMNS) == set(PROBE_EVENT_DTYPE.names)
        assert len(WIRE_EVENT_COLUMNS) == len(
            set(WIRE_EVENT_COLUMNS)
        )

    def test_bad_transport_refused(self):
        """A corrupted line claiming an unknown transport, or binary
        transport with non-bytes columns, must be a contract break —
        not a TypeError out of np.frombuffer."""
        payload = encode_shipment(
            _sample_batch(), "node-x", 0, transport="base64"
        )
        payload["transport"] = "gzip"
        with pytest.raises(WireContractError, match="transport"):
            decode_shipment(payload)
        payload = json.loads(
            shipment_json_line(
                encode_shipment(
                    empty_batch(0), "node-x", 0, transport="base64"
                )
            )
        )
        payload["transport"] = "binary"  # columns are still str
        with pytest.raises(WireContractError, match="bytes"):
            decode_shipment(payload)

    def test_last_recorded_seq_resumes_across_restart(self, tmp_path):
        """The shipment log appends across agent restarts while the
        aggregator dedups on seq: a restarted writer must resume the
        node's monotonic sequence, not restart at 0."""
        log = tmp_path / "ship.jsonl"
        batch = _sample_batch(2)
        writer = ShipmentWriter(str(log))
        for seq in range(3):
            writer.send(
                "fleet",
                [
                    encode_shipment(
                        batch, "node-x", seq, transport="base64"
                    )
                ],
            )
        writer.close()
        # Another node's seqs and a torn tail must not confuse resume.
        with open(log, "a", encoding="utf-8") as fh:
            fh.write(
                shipment_json_line(
                    encode_shipment(
                        batch, "node-y", 9, transport="base64"
                    )
                )
            )
            fh.write('{"node": "node-x", "seq": ')
        assert last_recorded_seq(str(log), "node-x") == 2
        assert last_recorded_seq(str(log), "node-y") == 9
        assert last_recorded_seq(str(log), "node-z") == -1
        assert last_recorded_seq(str(tmp_path / "absent"), "n") == -1

    def test_writer_repairs_torn_tail_before_append(self, tmp_path):
        """A predecessor killed mid-write leaves a torn half-line at
        the log tail; appending onto it would weld the next shipment
        into one corrupt line, losing both.  The writer must truncate
        the tear on (re)open so every surviving line stays parseable."""
        log = tmp_path / "ship.jsonl"
        batch = _sample_batch(2)
        with open(log, "w", encoding="utf-8") as fh:
            fh.write(
                shipment_json_line(
                    encode_shipment(
                        batch, "node-x", 0, transport="base64"
                    )
                )
            )
            fh.write('{"wire_version": 1, "node": "node-x", "seq"')
        writer = ShipmentWriter(str(log))
        writer.send(
            "fleet",
            [encode_shipment(batch, "node-x", 1, transport="base64")],
        )
        writer.close()
        shipments = load_shipments(str(log))
        assert [s.seq for s in shipments] == [0, 1]


class TestConcatBatches:
    def test_pool_recoding(self):
        a = _sample_batch(3, node="node-a")
        b = _sample_batch(4, node="node-b")
        merged = concat_batches([a, b])
        assert merged.n == 7
        assert to_rows(merged) == to_rows(a) + to_rows(b)

    def test_empty_and_single(self):
        assert concat_batches([]).n == 0
        a = _sample_batch(2)
        assert concat_batches([empty_batch(0), a]) is a


class TestHashRing:
    def test_deterministic_across_instances(self):
        keys = [(f"node-{i}", f"slice-{i % 4}") for i in range(200)]
        a = HashRing(["agg-0", "agg-1", "agg-2"]).assignments(keys)
        b = HashRing(["agg-0", "agg-1", "agg-2"]).assignments(keys)
        assert a == b

    def test_removal_only_rehomes_victims(self):
        keys = [(f"node-{i}", f"slice-{i % 4}") for i in range(300)]
        ring = HashRing(["agg-0", "agg-1", "agg-2"])
        before = ring.assignments(keys)
        ring.remove_shard("agg-1")
        after = ring.assignments(keys)
        for node, owner in before.items():
            if owner != "agg-1":
                assert after[node] == owner
            else:
                assert after[node] in ("agg-0", "agg-2")
        assert ring.rebalances == 1

    def test_spread_is_reasonable(self):
        keys = [(f"node-{i}", f"slice-{i % 16}") for i in range(1000)]
        ring = HashRing([f"agg-{i}" for i in range(4)])
        counts: dict[str, int] = {}
        for node, owner in ring.assignments(keys).items():
            counts[owner] = counts.get(owner, 0) + 1
        assert len(counts) == 4
        assert max(counts.values()) / (1000 / 4) < 1.5

    def test_export_restore_round_trip(self):
        ring = HashRing(["agg-0", "agg-1"], vnodes=32)
        ring.add_shard("agg-2")
        state = ring.export_state()
        other = HashRing([])
        other.restore_state(state)
        keys = [(f"node-{i}", "slice-0") for i in range(100)]
        assert other.assignments(keys) == ring.assignments(keys)
        assert other.rebalances == ring.rebalances

    def test_empty_ring_lookup_raises(self):
        with pytest.raises(LookupError):
            HashRing([]).shard_for(node_key("n", "s"))


def _node_incident(
    node: str,
    pod: str = "pod-0",
    namespace: str = "tenant-a",
    slice_id: str = "slice-0",
    domain: str = "tpu_hbm",
    ts: int = EPOCH_NS,
    confidence: float = 0.9,
) -> NodeIncident:
    return NodeIncident(
        node=node,
        pod=pod,
        namespace=namespace,
        slice_id=slice_id,
        domain=domain,
        confidence=confidence,
        ts_unix_nano=ts,
    )


class TestRollup:
    def test_blast_radius_classification(self):
        one_pod = [_node_incident("n0")]
        assert classify_blast_radius(one_pod) == BLAST_POD
        one_node = [
            _node_incident("n0", pod="pod-0"),
            _node_incident("n0", pod="pod-1"),
        ]
        assert classify_blast_radius(one_node) == BLAST_NODE
        one_slice = [_node_incident("n0"), _node_incident("n1")]
        assert classify_blast_radius(one_slice) == BLAST_SLICE
        fleet = [
            _node_incident("n0", slice_id="slice-0"),
            _node_incident("n1", slice_id="slice-1"),
        ]
        assert classify_blast_radius(fleet) == BLAST_FLEET

    def test_blast_radius_empty_slice_id_is_not_a_slice(self):
        """Agents without --slice-id carry no slice identity: two such
        nodes are slice radius (not fleet), and mixing set/unset must
        not escalate either."""
        no_ids = [
            _node_incident("n0", slice_id=""),
            _node_incident("n1", slice_id=""),
        ]
        assert classify_blast_radius(no_ids) == BLAST_SLICE
        mixed = [
            _node_incident("n0", slice_id="slice-0"),
            _node_incident("n1", slice_id=""),
        ]
        assert classify_blast_radius(mixed) == BLAST_SLICE

    def test_session_window_collapses_to_one_page(self):
        rollup = FleetRollup(gap_ns=5_000_000_000)
        rollup.observe(
            _node_incident(f"n{i}", ts=EPOCH_NS + i * 1_000_000_000)
            for i in range(4)
        )
        incidents = rollup.flush()
        assert len(incidents) == 1
        assert incidents[0].blast_radius == BLAST_SLICE
        assert incidents[0].nodes == [f"n{i}" for i in range(4)]
        assert len(incidents[0].members) == 4

    def test_no_cross_tenant_merge(self):
        rollup = FleetRollup()
        rollup.observe(
            [
                _node_incident("n0", namespace="tenant-a"),
                _node_incident("n1", namespace="tenant-b"),
            ]
        )
        incidents = rollup.flush()
        assert len(incidents) == 2
        assert {i.namespace for i in incidents} == {
            "tenant-a",
            "tenant-b",
        }

    def test_no_cross_domain_merge(self):
        rollup = FleetRollup()
        rollup.observe(
            [
                _node_incident("n0", domain="tpu_hbm"),
                _node_incident("n1", domain="network_dns"),
            ]
        )
        incidents = rollup.flush()
        assert len(incidents) == 2
        assert {i.domain for i in incidents} == {
            "tpu_hbm",
            "network_dns",
        }

    def test_gap_splits_sessions(self):
        rollup = FleetRollup(gap_ns=1_000_000_000)
        rollup.observe([_node_incident("n0", ts=EPOCH_NS)])
        emitted = rollup.observe(
            [_node_incident("n1", ts=EPOCH_NS + 10_000_000_000)]
        )
        assert len(emitted) == 1  # first session closed by the gap
        assert len(rollup.flush()) == 1

    def test_out_of_order_straggler_does_not_merge_backward(self):
        """fleetagg flushes shard 0's whole history before shard 1's:
        a member 600s EARLIER than the open group is a distinct fault
        and must open its own session, not extend the later group's
        window backward into one merged page."""
        rollup = FleetRollup(gap_ns=5_000_000_000)
        rollup.observe(
            [_node_incident("n0", ts=EPOCH_NS + 600_000_000_000)]
        )
        emitted = rollup.observe([_node_incident("n1", ts=EPOCH_NS)])
        assert emitted == []  # the later group stays open
        assert rollup.open_groups() == 2
        incidents = rollup.flush()
        assert len(incidents) == 2
        assert sorted(i.window_start_ns for i in incidents) == [
            EPOCH_NS,
            EPOCH_NS + 600_000_000_000,
        ]
        assert all(len(i.members) == 1 for i in incidents)

    def test_out_of_order_bridging_member_merges_sessions(self):
        """A member landing between two open sessions within gap of
        both bridges them into one fault (one page, all members)."""
        gap = 5_000_000_000
        rollup = FleetRollup(gap_ns=gap)
        # Later member first (shard flush order), then a straggler
        # 1.5 gaps earlier: two open sessions.
        rollup.observe(
            [_node_incident("n1", ts=EPOCH_NS + 15 * gap // 10)]
        )
        rollup.observe([_node_incident("n0", ts=EPOCH_NS)])
        assert rollup.open_groups() == 2
        # A member within gap of BOTH intervals bridges them.
        rollup.observe(
            [_node_incident("n2", ts=EPOCH_NS + 8 * gap // 10)]
        )
        assert rollup.open_groups() == 1
        incidents = rollup.flush()
        assert len(incidents) == 1
        assert incidents[0].nodes == ["n0", "n1", "n2"]

    def test_watermark_close(self):
        rollup = FleetRollup(gap_ns=1_000_000_000)
        rollup.observe([_node_incident("n0", ts=EPOCH_NS)])
        assert rollup.close_up_to(EPOCH_NS) == []
        closed = rollup.close_up_to(EPOCH_NS + 2_000_000_000)
        assert len(closed) == 1
        assert rollup.open_groups() == 0

    def test_duplicate_member_keeps_best_confidence(self):
        rollup = FleetRollup()
        rollup.observe(
            [
                _node_incident("n0", confidence=0.6),
                _node_incident("n0", confidence=0.9),
                _node_incident("n0", confidence=0.7),
            ]
        )
        incidents = rollup.flush()
        assert len(incidents) == 1
        assert len(incidents[0].members) == 1
        assert incidents[0].confidence == pytest.approx(0.9)

    def test_emission_idempotent_across_restore(self):
        """Failover replay: a group already paged must not page again
        after the emitted-id registry restores."""
        rollup = FleetRollup()
        rollup.observe([_node_incident("n0")])
        state_open = rollup.export_state()
        first = rollup.flush()
        assert len(first) == 1
        state_emitted = rollup.export_state()

        # Restore the post-emission state, replay the same member.
        other = FleetRollup()
        other.restore_state(state_emitted)
        other.observe([_node_incident("n0")])
        assert other.flush() == []
        assert other.duplicates_suppressed == 1

        # Restoring the pre-emission state emits exactly once.
        third = FleetRollup()
        third.restore_state(state_open)
        assert len(third.flush()) == 1

    def test_emission_idempotent_under_window_shift(self):
        """A failover-rebuilt group can re-bucket its earliest member
        by one window: the registry must still suppress (gap-tolerant
        window match, not an exact start_ns-derived id)."""
        rollup = FleetRollup(gap_ns=5_000_000_000)
        rollup.observe([_node_incident("n0", ts=EPOCH_NS)])
        assert len(rollup.flush()) == 1
        state = rollup.export_state()

        other = FleetRollup(gap_ns=5_000_000_000)
        other.restore_state(state)
        # Rebuilt member lands one gap later — same fault, shifted id.
        other.observe(
            [_node_incident("n0", ts=EPOCH_NS + 4_000_000_000)]
        )
        assert other.flush() == []
        assert other.duplicates_suppressed == 1

        # A genuinely later fault (past the gap tolerance) still pages.
        later = FleetRollup(gap_ns=5_000_000_000)
        later.restore_state(state)
        later.observe(
            [_node_incident("n0", ts=EPOCH_NS + 20_000_000_000)]
        )
        assert len(later.flush()) == 1

    def test_incident_dict_round_trip(self):
        rollup = FleetRollup()
        rollup.observe([_node_incident("n0")])
        incident = rollup.flush()[0]
        clone = FleetIncident.from_dict(
            json.loads(json.dumps(incident.to_dict()))
        )
        assert clone == incident


class TestAggregatorShard:
    def test_seq_dedup_drops_replays(self):
        shard = AggregatorShard("agg-0")
        batch = _sample_batch()
        p0 = encode_shipment(batch, "node-x", 0)
        assert shard.ingest(p0) is True
        assert shard.ingest(encode_shipment(batch, "node-x", 0)) is False
        assert shard.ingest(encode_shipment(batch, "node-x", 1)) is True
        assert shard.duplicate_shipments == 1
        assert shard.shipments == 2

    def test_fold_is_idempotent_under_duplication(self):
        """Max-folding: re-delivered evidence cannot inflate it."""
        shard_once = AggregatorShard("a", min_confidence=0.0)
        shard_twice = AggregatorShard("b", min_confidence=0.0)
        batch = _sample_batch()
        shard_once.ingest(encode_shipment(batch, "node-x", 0))
        shard_twice.ingest(encode_shipment(batch, "node-x", 0))
        # Same evidence again under a fresh seq (spool re-send after
        # failover lands as a NEW shipment, not a seq duplicate).
        shard_twice.ingest(encode_shipment(batch, "node-x", 1))
        once = shard_once.close_windows(flush=True)
        twice = shard_twice.close_windows(flush=True)
        assert [
            (i.node, i.pod, i.domain, round(i.confidence, 6), i.signals)
            for i in once
        ] == [
            (i.node, i.pod, i.domain, round(i.confidence, 6), i.signals)
            for i in twice
        ]

    def test_watermark_ignores_stale_nodes(self):
        shard = AggregatorShard("agg-0", stale_after_ns=10_000_000_000)
        live = _sample_batch(4, node="node-live")
        shard.ingest(encode_shipment(live, "node-live", 0))
        # A node whose head is far behind the fleet head goes stale
        # and must not freeze the watermark.
        old_events = to_rows(_sample_batch(2, node="node-dead"))
        for ev in old_events:
            object.__setattr__(
                ev, "ts_unix_nano", EPOCH_NS - 60_000_000_000
            )
        stale_batch = from_rows(old_events)
        shard.ingest(encode_shipment(stale_batch, "node-dead", 0))
        reporting, stale = shard.reporting_and_stale()
        assert (reporting, stale) == (1, 1)
        assert shard.watermark_ns() > EPOCH_NS - 60_000_000_000

    def test_export_absorb_rehomes_node_state(self):
        dead = AggregatorShard("dead", min_confidence=0.0)
        batch = _sample_batch()
        dead.ingest(encode_shipment(batch, "node-x", 3, slice_id="s0"))
        state = dead.export_state()
        assert "node-x" in state["nodes"]

        heir = AggregatorShard("heir", min_confidence=0.0)
        heir.absorb_node_state("node-x", state["nodes"]["node-x"])
        assert heir.nodes["node-x"].seq == 3
        assert heir.nodes["node-x"].slice_id == "s0"
        # The replayed shipment is a seq duplicate on the heir.
        assert heir.ingest(encode_shipment(batch, "node-x", 3)) is False
        # The absorbed pending evidence attributes identically.
        dead_incidents = dead.close_windows(flush=True)
        heir_incidents = heir.close_windows(flush=True)
        assert [
            (i.node, i.pod, i.domain, round(i.confidence, 6))
            for i in dead_incidents
        ] == [
            (i.node, i.pod, i.domain, round(i.confidence, 6))
            for i in heir_incidents
        ]


class TestSimulatorCorrectness:
    TOPOLOGY = FleetTopology(nodes=32, nodes_per_slice=8)

    def test_kill_shard_rehomes_late_joining_node_spool(self):
        """A node whose first shipment landed after the last durable
        snapshot has spool entries but no snapshot fragment: failover
        must still re-home it and re-send its whole spool, not drop
        its events because the snapshot never saw the node."""
        topo = FleetTopology(nodes=8, nodes_per_slice=4)
        sim = FleetSimulator(topo, ("agg-0", "agg-1"), seed=7)
        node_i = next(
            i
            for i in range(topo.nodes)
            if sim._assignment[topo.node_name(i)] == "agg-0"
        )
        node = topo.node_name(node_i)
        sim._ship(node_i, sim._events_for_round(node_i, 0, {}))
        spooled = len(sim._node_spool[node])
        assert spooled > 0
        # The dead shard's last durable snapshot predates the node's
        # first shipment — no fragment for it.
        report = sim.kill_shard("agg-0", exported={"nodes": {}})
        assert report["rehomed_nodes"] == 0
        assert report["resent_shipments"] >= spooled
        heir = sim.shards[sim._assignment[node]]
        assert heir.nodes[node].seq == sim._node_seq[node]
        assert heir.ingested_events > 0

    def _run(self, chaos: float, seed: int = 11):
        plan = default_injection_plan(self.TOPOLOGY)
        sim = FleetSimulator(
            self.TOPOLOGY,
            ("agg-0", "agg-1"),
            seed=seed,
            chaos_intensity=chaos,
        )
        result = sim.run(24, plan)
        return plan, result

    def test_one_incident_per_injection_no_chaos(self):
        plan, result = self._run(chaos=0.0)
        matches, precision, recall, macro = score_incidents(
            plan, result.incidents
        )
        assert precision == 1.0 and recall == 1.0 and macro == 1.0
        assert len(result.incidents) == len(plan)
        for match in matches:
            assert match.exact, match.to_dict()

    def test_dedup_under_moderate_chaos(self):
        """Intensity 1.0 (skew<=250ms, 5% dup, 5% reorder, 1% corrupt
        per host): one fault never splits, distinct domains never
        merge — parity with the clean run's incident set."""
        plan, clean = self._run(chaos=0.0)
        _, chaotic = self._run(chaos=1.0)
        _, precision, recall, _ = score_incidents(
            plan, chaotic.incidents
        )
        assert precision == 1.0 and recall == 1.0
        key = lambda i: (i.namespace, i.domain, i.blast_radius)  # noqa: E731
        assert sorted(map(key, chaotic.incidents)) == sorted(
            map(key, clean.incidents)
        )

    @pytest.mark.slow
    def test_dedup_under_heavy_chaos(self):
        """Intensity 3.0 triples skew/dup/reorder/corruption; the
        structural invariants must still hold."""
        plan, result = self._run(chaos=3.0)
        _, precision, recall, _ = score_incidents(plan, result.incidents)
        assert precision == 1.0 and recall == 1.0
        # Cross-tenant / cross-domain probes stay separate pages.
        by_key: dict[tuple[str, str], int] = {}
        for incident in result.incidents:
            k = (incident.namespace, incident.domain)
            by_key[k] = by_key.get(k, 0) + 1
        assert all(count == 1 for count in by_key.values()), by_key

    @pytest.mark.slow
    def test_failover_loses_and_duplicates_nothing(self):
        report = run_fleet_sweep(
            nodes=32,
            shards=2,
            seed=11,
            chaos_intensity=1.0,
            events_per_node=1000,
            rounds=24,
            min_ingest_events_per_sec=1.0,
            max_rollup_latency_ms=60_000.0,
        )
        assert report.passed, report.failures
        assert report.failover.get("rehomed_nodes", 0) > 0
        assert report.failover.get("resent_shipments", 0) > 0
        assert report.failover_lost == []
        assert report.failover_duplicated == []


class TestSimulatorThroughputLane:
    def test_measure_ingest_counts_everything(self):
        topology = FleetTopology(nodes=16, nodes_per_slice=4)
        sim = FleetSimulator(topology, ("agg-0", "agg-1"), seed=3)
        m = sim.measure_ingest(events_per_node=500)
        assert m.nodes == 16
        assert m.total_events > 0
        assert m.admitted_events == m.total_events
        assert m.events_per_sec > 0
        assert set(m.per_shard_events_per_sec) == {"agg-0", "agg-1"}


class TestFleetCLI:
    def _write_shipments(
        self, path, node: str, slice_id: str, n=3, seq_start=0
    ):
        from tpuslo.fleet.wire import ShipmentWriter

        writer = ShipmentWriter(str(path))
        for seq in range(seq_start, seq_start + n):
            events = to_rows(_sample_batch(6, node=node))
            for i, ev in enumerate(events):
                object.__setattr__(
                    ev,
                    "ts_unix_nano",
                    EPOCH_NS + seq * 1_000_000_000 + i * 1_000,
                )
            batch = from_rows(events)
            writer.send(
                "fleet",
                [
                    encode_shipment(
                        batch,
                        node,
                        seq,
                        transport="base64",
                        slice_id=slice_id,
                    )
                ],
            )
        writer.close()

    def test_fleetagg_end_to_end(self, tmp_path, capsys):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        ship_a = tmp_path / "ship-a.jsonl"
        ship_b = tmp_path / "ship-b.jsonl"
        self._write_shipments(ship_a, "node-a", "slice-0")
        self._write_shipments(ship_b, "node-b", "slice-0")
        incidents_out = tmp_path / "incidents.jsonl"
        prov_out = tmp_path / "prov.jsonl"
        state_out = tmp_path / "state.json"
        rc = fleetagg_main(
            [
                str(ship_a),
                str(ship_b),
                "--shards",
                "2",
                "--min-confidence",
                "0.0",
                "--incidents-out",
                str(incidents_out),
                "--provenance-out",
                str(prov_out),
                "--state-out",
                str(state_out),
                "--json",
            ]
        )
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["shipments"] == 6
        assert summary["rejected_shipments"] == 0
        assert summary["nodes"] == 2
        assert summary["ingested_events"] == summary["admitted_events"]
        state = json.loads(state_out.read_text())
        assert set(state["shards"]) == {"agg-0", "agg-1"}
        if summary["incidents"]:
            lines = [
                json.loads(line)
                for line in incidents_out.read_text().splitlines()
            ]
            assert len(lines) == summary["incidents"]
            prov = [
                json.loads(line)
                for line in prov_out.read_text().splitlines()
            ]
            assert all(p["members"] for p in prov)

    def test_fleetagg_restart_does_not_repage(self, tmp_path, capsys):
        """--state-out carries the rollup's emitted-window registry:
        a restarted aggregator replaying the same shipment log with
        --restore-state must not page the same fault twice.  Re-runs
        also rewrite (not append to) the provenance log, keeping it in
        lockstep with --incidents-out."""
        from tpuslo.cli.fleetagg import main as fleetagg_main

        ship = tmp_path / "ship.jsonl"
        self._write_shipments(ship, "node-a", "slice-0")
        incidents_out = tmp_path / "incidents.jsonl"
        prov_out = tmp_path / "prov.jsonl"
        state_out = tmp_path / "state.json"
        common = [
            str(ship),
            "--min-confidence",
            "0.0",
            "--incidents-out",
            str(incidents_out),
            "--provenance-out",
            str(prov_out),
            "--state-out",
            str(state_out),
            "--json",
        ]
        assert fleetagg_main(common) == 0
        first = json.loads(capsys.readouterr().out)
        assert first["incidents"] >= 1
        first_prov = prov_out.read_text().splitlines()
        assert len(first_prov) == first["incidents"]

        # The fault is still ongoing: the shipment log grows while the
        # aggregator restarts.  The replayed shipments seq-dedup; the
        # new ones attribute, but their rollup window overlaps the
        # already-paged one — no second page.
        self._write_shipments(ship, "node-a", "slice-0", seq_start=3)
        assert (
            fleetagg_main(common + ["--restore-state", str(state_out)])
            == 0
        )
        second = json.loads(capsys.readouterr().out)
        assert second["incidents"] == 0
        # Outputs are truncated per run, never accumulated.
        assert incidents_out.read_text() == ""
        assert prov_out.read_text() == ""

    def test_fleetagg_rejects_contract_break_loudly(
        self, tmp_path, capsys
    ):
        from tpuslo.cli.fleetagg import main as fleetagg_main

        ship = tmp_path / "ship.jsonl"
        self._write_shipments(ship, "node-a", "slice-0", n=1)
        with open(ship, "a", encoding="utf-8") as fh:
            fh.write('{"wire_version": 99, "node": "evil"}\n')
        rc = fleetagg_main([str(ship), "--json"])
        assert rc == 0
        captured = capsys.readouterr()
        summary = json.loads(captured.out)
        assert summary["rejected_shipments"] == 1
        assert "rejected" in captured.err

    def test_sloctl_fleet_incidents_and_nodes(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main as sloctl_main

        incident = FleetIncident(
            incident_id="fleet-tenant-a-tpu_hbm-1",
            namespace="tenant-a",
            domain="tpu_hbm",
            blast_radius="slice",
            window_start_ns=EPOCH_NS,
            window_end_ns=EPOCH_NS + 1,
            confidence=0.9,
            nodes=["n0", "n1"],
            slices=["slice-0"],
            members=[{"incident_id": "n0/p@1"}],
        )
        incidents = tmp_path / "incidents.jsonl"
        incidents.write_text(
            json.dumps(incident.to_dict()) + "\n", encoding="utf-8"
        )
        rc = sloctl_main(
            ["fleet", "incidents", "--incidents", str(incidents)]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "fleet-tenant-a-tpu_hbm-1" in out
        assert "slice" in out

        # Radius filter excludes it.
        rc = sloctl_main(
            [
                "fleet",
                "incidents",
                "--incidents",
                str(incidents),
                "--radius",
                "pod",
            ]
        )
        assert rc == 0
        assert "no fleet incidents" in capsys.readouterr().out

        state = {
            "shards": {
                "agg-0": {
                    "nodes": {
                        "node-a": {
                            "head_ns": EPOCH_NS,
                            "seq": 4,
                            "events": 24,
                            "slice_id": "slice-0",
                        }
                    }
                }
            },
            "snapshots": {"agg-0": {"watermark_ns": 0}},
        }
        state_path = tmp_path / "state.json"
        state_path.write_text(json.dumps(state), encoding="utf-8")
        rc = sloctl_main(["fleet", "nodes", "--state", str(state_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "node-a" in out and "agg-0" in out

    def test_explain_renders_members_block(self, tmp_path, capsys):
        from tpuslo.cli.fleetagg import incident_provenance
        from tpuslo.cli.sloctl import main as sloctl_main

        incident = FleetIncident(
            incident_id="fleet-tenant-a-tpu_hbm-1",
            namespace="tenant-a",
            domain="tpu_hbm",
            blast_radius="slice",
            window_start_ns=EPOCH_NS,
            window_end_ns=EPOCH_NS + 1,
            confidence=0.9,
            nodes=["n0", "n1"],
            slices=["slice-0"],
            members=[
                {
                    "incident_id": "n0/p0@1",
                    "node": "n0",
                    "pod": "p0",
                    "slice_id": "slice-0",
                    "tier": "node_window",
                    "confidence": 0.91,
                },
                {
                    "incident_id": "n1/p0@1",
                    "node": "n1",
                    "pod": "p0",
                    "slice_id": "slice-0",
                    "tier": "node_window",
                    "confidence": 0.87,
                },
            ],
        )
        prov = tmp_path / "prov.jsonl"
        prov.write_text(
            json.dumps(incident_provenance(incident)) + "\n",
            encoding="utf-8",
        )
        rc = sloctl_main(
            [
                "explain",
                "--provenance",
                str(prov),
                "fleet-tenant-a-tpu_hbm-1",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "fleet rollup, blast radius: slice" in out
        assert "members (2 contributing node incidents)" in out
        assert "n0/p0@1" in out and "confidence=0.91" in out
        assert "rollup window" in out


class TestFleetMetricsBridge:
    def test_fleet_observer_series(self):
        from prometheus_client import generate_latest

        from tpuslo.metrics import AgentMetrics

        metrics = AgentMetrics()
        observer = metrics.fleet_observer()
        observer.ingested("agg-0", 1000)
        observer.ingested("agg-0", 500)
        observer.rollup_latency_ms(12.5)
        observer.incidents_open("slice", 2)
        observer.nodes(reporting=998, stale=2)
        observer.rebalance()
        text = generate_latest(metrics.registry).decode()
        assert (
            'llm_slo_fleet_ingested_events_total{shard="agg-0"} 1500.0'
            in text
        )
        assert "llm_slo_fleet_rollup_latency_ms_bucket" in text
        assert (
            'llm_slo_fleet_incidents_open{blast_radius="slice"} 2.0'
            in text
        )
        assert "llm_slo_fleet_nodes_reporting 998.0" in text
        assert "llm_slo_fleet_nodes_stale 2.0" in text
        assert "llm_slo_fleet_ring_rebalances_total 1.0" in text

    def test_simulator_drives_observer(self):
        from tpuslo.metrics import AgentMetrics

        metrics = AgentMetrics()
        topology = FleetTopology(nodes=8, nodes_per_slice=4)
        sim = FleetSimulator(
            topology,
            ("agg-0", "agg-1"),
            seed=5,
            observer=metrics.fleet_observer(),
        )
        plan = [
            FaultInjection(
                name="node-mem",
                label="memory_pressure",
                namespace="tenant-b",
                scope="node",
                at_round=2,
                target=1,
            )
        ]
        result = sim.run(10, plan)
        assert len(result.incidents) == 1
        ingested = metrics.fleet_ingested_events.collect()[0]
        total = sum(
            s.value
            for s in ingested.samples
            if s.name.endswith("_total")
        )
        assert total > 0
