"""Mixtral MoE model family: forward, counts, dp x ep sharded training."""

from dataclasses import replace

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuslo.models import mixtral


def test_forward_shape_and_finite():
    cfg = mixtral.mixtral_tiny(max_seq_len=32)
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = mixtral.forward(params, tokens, cfg, remat=False)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_param_count_matches_tree():
    cfg = mixtral.mixtral_tiny()
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg)
    actual = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    assert actual == mixtral.param_count(cfg)


def test_expert_params_accounted():
    # The FFN block must carry exactly n_experts x the dense-FFN params
    # (total params), while only top_k x dense-FFN is active per token —
    # the sparsity ratio the MoE design trades on.
    cfg = mixtral.mixtral_tiny()
    dense_ffn = cfg.n_layers * 3 * cfg.dim * cfg.ffn_dim
    non_ffn = mixtral.param_count(cfg) - cfg.n_experts * dense_ffn
    # Removing one expert everywhere must shrink the count by exactly
    # one dense-FFN's worth; the remainder (attention/router/embeddings)
    # must not depend on n_experts beyond the router column.
    smaller = replace(cfg, n_experts=cfg.n_experts - 1)
    delta = mixtral.param_count(cfg) - mixtral.param_count(smaller)
    assert delta == dense_ffn + cfg.n_layers * cfg.dim  # experts + router col
    assert non_ffn > 0
    assert cfg.top_k < cfg.n_experts  # sparse by construction


def test_moe_train_step_on_dp_ep_mesh():
    cfg = mixtral.mixtral_tiny(max_seq_len=32)
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "ep"))
    step, init = mixtral.build_moe_train_step(mesh, cfg)
    params, opt_state = init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    params, opt_state, loss = step(params, opt_state, tokens, targets)
    loss.block_until_ready()
    assert np.isfinite(float(loss))
    # Second step must reuse the compiled executable and keep improving
    # or at least staying finite.
    params, opt_state, loss2 = step(params, opt_state, tokens, targets)
    assert np.isfinite(float(loss2))
    assert float(loss2) < float(loss) + 1.0


class TestMoEServing:
    def test_prefill_decode_matches_full_forward(self):
        """Incremental KV-cache decode must equal re-running the full
        forward over the growing sequence (tiny configs are drop-free:
        capacity >= every routable token)."""
        from tpuslo.models.llama import init_kv_cache
        from tpuslo.models.mixtral import (
            decode_step,
            forward,
            init_params,
            mixtral_tiny,
            prefill,
        )

        from functools import partial

        cfg = mixtral_tiny(max_seq_len=64)
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 9), 0, cfg.vocab_size)

        logits, cache = prefill(params, prompt, init_kv_cache(cfg.attn_cfg(), 1), cfg)
        step = jax.jit(partial(decode_step, cfg=cfg))
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        toks = [int(tok[0])]
        for _ in range(6):
            logits, cache = step(params, tok, cache)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
            toks.append(int(tok[0]))
        # ONE reference forward over the whole decoded sequence: with a
        # causal model, position p's logits equal the full forward over
        # its prefix, so this checks every step's greedy choice at a
        # single compile (6 growing-length eager forwards made this the
        # suite's #7 cost).
        seq = [int(x) for x in prompt[0]] + toks[:-1]
        ref = forward(
            params, jnp.asarray([seq], jnp.int32), cfg, remat=False
        )[0]
        for i in range(len(toks) - 1):
            assert int(jnp.argmax(ref[8 + i])) == toks[i], (i, toks)

    def test_bucketed_prefill_true_length(self):
        from tpuslo.models.llama import init_kv_cache
        from tpuslo.models.mixtral import init_params, mixtral_tiny, prefill

        cfg = mixtral_tiny(max_seq_len=64)
        params = init_params(jax.random.PRNGKey(0), cfg)
        ids = jax.random.randint(jax.random.PRNGKey(2), (1, 7), 0, cfg.vocab_size)
        padded = jnp.concatenate(
            [ids, jnp.zeros((1, 9), jnp.int32)], axis=1
        )  # bucket 16
        exact_logits, _ = prefill(
            params, ids, init_kv_cache(cfg.attn_cfg(), 1), cfg
        )
        padded_logits, cache = prefill(
            params, padded, init_kv_cache(cfg.attn_cfg(), 1), cfg,
            true_length=jnp.asarray(7, jnp.int32),
        )
        assert int(cache["length"]) == 7
        assert jnp.allclose(exact_logits, padded_logits, atol=1e-4)

    def test_engine_streams_with_ttft(self):
        from tpuslo.models.mixtral import MoEServeEngine, mixtral_tiny

        engine = MoEServeEngine(cfg=mixtral_tiny(max_seq_len=128))
        engine.warmup()
        events = list(
            engine.generate("serve the moe family", max_new_tokens=12,
                            stop_at_eos=False)
        )
        assert len(events) == 12
        assert events[0].ttft_ms is not None and events[0].ttft_ms > 0
        assert all(e.ttft_ms is None for e in events[1:])
        # Deterministic: same prompt, same stream.
        again = [
            e.token_id
            for e in engine.generate("serve the moe family",
                                     max_new_tokens=12, stop_at_eos=False)
        ]
        assert again == [e.token_id for e in events]


def test_mixtral_2b6_sized_for_one_chip_and_drop_free():
    from tpuslo.models.mixtral import mixtral_2b6, param_count

    cfg = mixtral_2b6()
    # Drop-free routing is what makes the serving numbers honest.
    assert cfg.capacity_factor >= cfg.n_experts / cfg.top_k
    n = param_count(cfg)
    assert 2e9 < n < 4e9  # bf16 weights fit 16 GB with headroom
    assert cfg.dim % cfg.n_heads == 0
    assert cfg.n_heads % cfg.n_kv_heads == 0


def test_active_param_count_against_real_leaves():
    """Pin both counts against the actual init_params leaf sizes (an
    independent derivation, not the formula restated)."""
    from tpuslo.models.mixtral import (
        active_param_count,
        init_params,
        mixtral_tiny,
        param_count,
    )

    cfg = mixtral_tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    leaf_total = sum(x.size for x in jax.tree.leaves(params))
    assert param_count(cfg) == leaf_total
    # Active = all leaves minus the unrouted experts' share of the
    # (L, E, ...) expert leaves.
    layers = params["layers"]
    expert_leaves = sum(layers[k].size for k in ("w1", "w3", "w2"))
    unrouted = expert_leaves * (cfg.n_experts - cfg.top_k) // cfg.n_experts
    assert active_param_count(cfg) == leaf_total - unrouted
    assert active_param_count(cfg) < param_count(cfg)


def test_mixtral_8x7b_train_step_compiles_dp_ep():
    """Full-scale MoE sharding, compile-validated without allocation:
    the PRODUCTION 8x7B dp x ep train step (build_moe_train_step, with
    its optimizer-state shardings and donation) lowers AND compiles
    against abstract shapes, so GSPMD accepts the expert/attention
    layout CI-side instead of on a real pod."""
    from functools import partial

    import optax

    from tpuslo.models.mixtral import (
        build_moe_train_step,
        init_params,
        mixtral_8x7b,
    )

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "ep"))
    cfg = mixtral_8x7b()
    assert cfg.n_experts % mesh.shape["ep"] == 0

    abstract = jax.eval_shape(partial(init_params, cfg=cfg), jax.random.PRNGKey(0))
    n_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(abstract))
    assert n_bytes > 80e9  # 8x7B-class: bf16 weights alone need a pod

    optimizer = optax.adamw(1e-4)
    step, _init = build_moe_train_step(mesh, cfg, optimizer=optimizer)
    abstract_opt = jax.eval_shape(optimizer.init, abstract)
    tokens = jax.ShapeDtypeStruct((8, 128), jnp.int32)

    compiled = step.lower(abstract, abstract_opt, tokens, tokens).compile()
    assert compiled is not None

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


class TestMoEContinuousBatching:
    """MoE family gets the full llama scheduler via the mlp_fn hook."""

    def _setup(self, max_slots=2):
        from tpuslo.models.mixtral import (
            MoEContinuousBatchingEngine,
            MoEServeEngine,
            init_params,
            mixtral_tiny,
        )

        cfg = mixtral_tiny(max_seq_len=128)
        params = init_params(jax.random.PRNGKey(0), cfg)
        batched = MoEContinuousBatchingEngine(
            cfg=cfg, params=params, max_slots=max_slots,
            prefill_buckets=(16, 32), decode_chunk_size=4,
        )
        single = MoEServeEngine(
            cfg=cfg, params=params, prefill_buckets=(16, 32),
            decode_chunk_size=4,
        )
        return batched, single

    def _single_stream(self, single, prompt, n):
        return [
            e.token_id
            for e in single.generate(prompt, max_new_tokens=n,
                                     stop_at_eos=False)
        ]

    def test_requests_match_single_request_serving(self):
        batched, single = self._setup()
        prompts = ["moe batch one", "a second longer moe request", "third"]
        ids = [batched.submit(p, max_new_tokens=8, stop_at_eos=False)
               for p in prompts]
        results = batched.run()
        for rid, prompt in zip(ids, prompts):
            assert results[rid] == self._single_stream(single, prompt, 8), (
                prompt
            )

    def test_more_requests_than_slots_queue_and_reuse(self):
        batched, single = self._setup(max_slots=2)
        prompts = [f"moe queued request {i}" for i in range(5)]
        ids = [batched.submit(p, max_new_tokens=6, stop_at_eos=False)
               for p in prompts]
        results = batched.run()
        assert len(results) == 5
        for rid, prompt in zip(ids, prompts):
            assert results[rid] == self._single_stream(single, prompt, 6)

    def test_prefix_rejected_at_submit(self):
        """Rejection happens at submit — an admission-time raise would
        strand every in-flight request in the batch."""
        batched, _single = self._setup()
        ok = batched.submit("fine", max_new_tokens=2, stop_at_eos=False)
        with pytest.raises(ValueError, match="prefix"):
            batched.submit("x", max_new_tokens=2, prefix="sys: ")
        results = batched.run()
        assert ok in results  # the good request was unharmed

    def test_request_timings_present(self):
        batched, _single = self._setup()
        rid = batched.submit("timed moe", max_new_tokens=4,
                             stop_at_eos=False)
        batched.run()
        timing = batched.request_timings()[rid]
        assert timing["e2e_s"] >= timing["queue_delay_s"] >= 0.0


def test_moe_batched_tensor_parallel_matches_single_device():
    """MoE continuous batching composes with the tp mesh: sharded
    batched decode equals the unsharded single-request MoE stream."""
    import numpy as np
    from jax.sharding import Mesh

    from tpuslo.models.mixtral import (
        MoEContinuousBatchingEngine,
        MoEServeEngine,
        init_params,
        mixtral_tiny,
    )

    cfg = mixtral_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    batched = MoEContinuousBatchingEngine(
        cfg=cfg, params=params, max_slots=2,
        prefill_buckets=(16, 32), decode_chunk_size=4, mesh=mesh,
    )
    single = MoEServeEngine(
        cfg=cfg, params=params, prefill_buckets=(16, 32),
        decode_chunk_size=4,
    )
    prompts = ["tp moe batch", "second tp moe request"]
    ids = [batched.submit(p, max_new_tokens=6, stop_at_eos=False)
           for p in prompts]
    results = batched.run()
    for rid, prompt in zip(ids, prompts):
        expect = [
            e.token_id
            for e in single.generate(prompt, max_new_tokens=6,
                                     stop_at_eos=False)
        ]
        assert results[rid] == expect, prompt


def test_moe_batched_int8_kv_matches_single_device():
    """The int8 KV half of the composition claim: batched int8-KV MoE
    equals the single-request int8-KV MoE stream."""
    from tpuslo.models.mixtral import (
        MoEContinuousBatchingEngine,
        MoEServeEngine,
        init_params,
        mixtral_tiny,
    )

    cfg = mixtral_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    batched = MoEContinuousBatchingEngine(
        cfg=cfg, params=params, max_slots=2,
        prefill_buckets=(16, 32), decode_chunk_size=4, kv_dtype="int8",
    )
    single = MoEServeEngine(
        cfg=cfg, params=params, prefill_buckets=(16, 32),
        decode_chunk_size=4, kv_dtype="int8",
    )
    prompts = ["int8 moe batch", "another int8 request"]
    ids = [batched.submit(p, max_new_tokens=6, stop_at_eos=False)
           for p in prompts]
    results = batched.run()
    for rid, prompt in zip(ids, prompts):
        expect = [
            e.token_id
            for e in single.generate(prompt, max_new_tokens=6,
                                     stop_at_eos=False)
        ]
        assert results[rid] == expect, prompt


def test_moe_batched_refuses_droppy_routing():
    from tpuslo.models.mixtral import (
        MoEContinuousBatchingEngine,
        mixtral_tiny,
    )
    from dataclasses import replace

    droppy = replace(mixtral_tiny(max_seq_len=128), capacity_factor=1.0)
    with pytest.raises(ValueError, match="drop-free"):
        MoEContinuousBatchingEngine(cfg=droppy, max_slots=2)


class TestMoEPagedBatching:
    """Paged pool x MoE: the serving matrix's last cell.  Same parity
    contract as the llama paged engine — per-request output equals the
    single-request MoE stream — plus the allocator behaviors the pool
    brings (backpressure, release, capacity win at equal HBM)."""

    def _setup(self, max_slots=2, n_blocks=None, block_size=16,
               kv_dtype="bf16"):
        from tpuslo.models.mixtral import (
            MoEPagedBatchingEngine,
            MoEServeEngine,
            init_params,
            mixtral_tiny,
        )

        cfg = mixtral_tiny(max_seq_len=128)
        params = init_params(jax.random.PRNGKey(0), cfg)
        paged = MoEPagedBatchingEngine(
            cfg=cfg, params=params, max_slots=max_slots,
            n_blocks=n_blocks, block_size=block_size,
            prefill_buckets=(16, 32), decode_chunk_size=4,
            kv_dtype=kv_dtype,
        )
        single = MoEServeEngine(
            cfg=cfg, params=params, prefill_buckets=(16, 32),
            decode_chunk_size=4, kv_dtype=kv_dtype,
        )
        return paged, single

    def _single_stream(self, single, prompt, n):
        return [
            e.token_id
            for e in single.generate(prompt, max_new_tokens=n,
                                     stop_at_eos=False)
        ]

    def test_matches_single_request_moe_serving(self):
        paged, single = self._setup()
        prompts = ["moe paged one", "a second longer moe request", "third"]
        ids = [paged.submit(p, max_new_tokens=8, stop_at_eos=False)
               for p in prompts]
        results = paged.run()
        for rid, prompt in zip(ids, prompts):
            assert results[rid] == self._single_stream(single, prompt, 8), (
                prompt
            )
        assert len(paged._free) == paged.n_blocks - 1  # all returned

    def test_block_backpressure_then_progress(self):
        # 17 ids + 28 new = 45 positions -> 3 blocks of 16; pool of 4
        # fits one request at a time; the second waits, then completes.
        paged, single = self._setup(max_slots=2, n_blocks=5)
        prompts = ["moe pressure one!", "moe pressure two!"]
        ids = [paged.submit(p, max_new_tokens=28, stop_at_eos=False)
               for p in prompts]
        paged.step()
        assert paged.stats()["active_slots"] == 1
        results = paged.run()
        for rid, prompt in zip(ids, prompts):
            assert results[rid] == self._single_stream(single, prompt, 28), (
                prompt
            )

    def test_int8_kv_compose(self):
        paged, single = self._setup(kv_dtype="int8")
        prompts = ["moe paged int8", "second int8 moe"]
        ids = [paged.submit(p, max_new_tokens=6, stop_at_eos=False)
               for p in prompts]
        results = paged.run()
        for rid, prompt in zip(ids, prompts):
            assert results[rid] == self._single_stream(single, prompt, 6), (
                prompt
            )

    def test_prefix_rejected_at_submit(self):
        import pytest

        paged, _ = self._setup()
        with pytest.raises(ValueError, match="prefix"):
            paged.submit("moe", prefix="system: nope")

    def test_droppy_routing_rejected(self):
        import dataclasses

        import pytest

        from tpuslo.models.mixtral import MoEPagedBatchingEngine, mixtral_tiny

        droppy = dataclasses.replace(
            mixtral_tiny(max_seq_len=128), capacity_factor=1.0
        )
        with pytest.raises(ValueError, match="drop-free"):
            MoEPagedBatchingEngine(cfg=droppy)


def test_moe_paged_tp_matches_single_device():
    """MoE paged pool x tensor parallelism: the pool's KV heads shard
    over the tp mesh while the MoE block body and the host-side block
    allocator ride unchanged."""
    import numpy as np
    from jax.sharding import Mesh

    from tpuslo.models.mixtral import (
        MoEPagedBatchingEngine,
        MoEServeEngine,
        init_params,
        mixtral_tiny,
    )

    cfg = mixtral_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    paged = MoEPagedBatchingEngine(
        cfg=cfg, params=params, max_slots=2, block_size=16,
        prefill_buckets=(16, 32), decode_chunk_size=4, mesh=mesh,
    )
    single = MoEServeEngine(
        cfg=cfg, params=params, prefill_buckets=(16, 32),
        decode_chunk_size=4,
    )
    prompts = ["tp moe paged", "second tp moe paged request"]
    ids = [paged.submit(p, max_new_tokens=6, stop_at_eos=False)
           for p in prompts]
    results = paged.run()
    for rid, prompt in zip(ids, prompts):
        expect = [
            e.token_id
            for e in single.generate(prompt, max_new_tokens=6,
                                     stop_at_eos=False)
        ]
        assert results[rid] == expect, prompt


def test_generation_prompt_ids_uses_moe_cap_despite_prefill_ids():
    """Regression: MoEServeEngine now has prefill_ids, so the old
    hasattr-based dense/MoE dispatch in _generation_prompt_ids would
    teacher-force a LONGER context than the MoE engine ever decoded
    from.  The cap must come from the engine's own rule."""
    from tpuslo.models.mixtral import MoEServeEngine, mixtral_tiny
    from tpuslo.models.serve import _generation_prompt_ids

    cfg = mixtral_tiny(max_seq_len=32)
    moe = MoEServeEngine(
        cfg=cfg, prefill_buckets=(32,), decode_chunk_size=4
    )
    assert hasattr(moe, "prefill_ids")
    assert moe.generation_prompt_cap() == 27  # min(32, 32 - 4 - 1)
    ids = _generation_prompt_ids(moe, "x" * 100)
    assert len(ids) == 27
