"""Mixtral MoE model family: forward, counts, dp x ep sharded training."""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuslo.models import mixtral


def test_forward_shape_and_finite():
    cfg = mixtral.mixtral_tiny(max_seq_len=32)
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = mixtral.forward(params, tokens, cfg, remat=False)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_param_count_matches_tree():
    cfg = mixtral.mixtral_tiny()
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg)
    actual = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    assert actual == mixtral.param_count(cfg)


def test_expert_params_accounted():
    # The FFN block must carry exactly n_experts x the dense-FFN params
    # (total params), while only top_k x dense-FFN is active per token —
    # the sparsity ratio the MoE design trades on.
    cfg = mixtral.mixtral_tiny()
    dense_ffn = cfg.n_layers * 3 * cfg.dim * cfg.ffn_dim
    non_ffn = mixtral.param_count(cfg) - cfg.n_experts * dense_ffn
    # Removing one expert everywhere must shrink the count by exactly
    # one dense-FFN's worth; the remainder (attention/router/embeddings)
    # must not depend on n_experts beyond the router column.
    smaller = replace(cfg, n_experts=cfg.n_experts - 1)
    delta = mixtral.param_count(cfg) - mixtral.param_count(smaller)
    assert delta == dense_ffn + cfg.n_layers * cfg.dim  # experts + router col
    assert non_ffn > 0
    assert cfg.top_k < cfg.n_experts  # sparse by construction


def test_moe_train_step_on_dp_ep_mesh():
    cfg = mixtral.mixtral_tiny(max_seq_len=32)
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "ep"))
    step, init = mixtral.build_moe_train_step(mesh, cfg)
    params, opt_state = init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    params, opt_state, loss = step(params, opt_state, tokens, targets)
    loss.block_until_ready()
    assert np.isfinite(float(loss))
    # Second step must reuse the compiled executable and keep improving
    # or at least staying finite.
    params, opt_state, loss2 = step(params, opt_state, tokens, targets)
    assert np.isfinite(float(loss2))
    assert float(loss2) < float(loss) + 1.0
