"""End-to-end agent test of the real-probe (ring) path.

Drives the full chain — userspace ring producer → native consumer →
schema envelope → JSONL writer — through the actual agent CLI loop,
proving the ring path is wired into the agent (the gap the reference
never closed: SURVEY.md §0).
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from tpuslo.collector import native

pytestmark = pytest.mark.skipif(
    not native.runtime_available(), reason="native runtime not buildable"
)


def test_agent_ring_mode_end_to_end(tmp_path):
    from tpuslo.cli import agent
    from tpuslo.collector.ringbuf import RingWriter

    ring_path = str(tmp_path / "agent.buf")
    out_path = str(tmp_path / "probes.jsonl")

    writer = RingWriter(ring_path)

    def produce():
        # Give the agent a moment to attach the ring, then emit a mix of
        # CPU and TPU wire events.
        time.sleep(0.3)
        writer.write_event(
            signal=native.SIG_DNS_LATENCY,
            value=3_000_000,
            ts_ns=time.time_ns(),
            pid=11,
        )
        writer.write_event(
            signal=native.SIG_XLA_COMPILE,
            value=60_000_000,
            ts_ns=time.time_ns(),
            pid=12,
            aux=99,
            flags=native.F_TPU,
        )
        writer.write_event(
            signal=native.SIG_ICI_COLLECTIVE,
            value=4_000_000,
            ts_ns=time.time_ns(),
            pid=12,
            aux=1234,
            flags=native.F_TPU,
        )

    producer = threading.Thread(target=produce)
    producer.start()
    rc = agent.main(
        [
            "--probe-source", "ring",
            "--ring-path", ring_path,
            "--event-kind", "probe",
            "--output", "jsonl",
            "--jsonl-path", out_path,
            "--count", "4",
            "--interval-s", "0.2",
            "--metrics-port", "0",
            "--signal-set", "dns_latency_ms,xla_compile_ms,"
            "ici_collective_latency_ms",
        ]
    )
    producer.join()
    writer.close()
    assert rc == 0

    events = [json.loads(line) for line in open(out_path, encoding="utf-8")]
    by_signal = {e["signal"]: e for e in events}
    assert "dns_latency_ms" in by_signal
    assert by_signal["dns_latency_ms"]["value"] == pytest.approx(3.0)
    assert by_signal["dns_latency_ms"]["pid"] == 11
    assert "xla_compile_ms" in by_signal
    assert by_signal["xla_compile_ms"]["value"] == pytest.approx(60.0)
    assert "ici_collective_latency_ms" in by_signal
    assert (
        by_signal["ici_collective_latency_ms"]["tpu"]["launch_id"] == 1234
    )


def test_agent_ring_mode_runs_ici_prober(tmp_path):
    """Ring mode (the production path) must run the active prober too,
    not just the synthetic loop."""
    import json

    from tpuslo.cli import agent

    out_path = str(tmp_path / "probes.jsonl")
    rc = agent.main(
        [
            "--probe-source", "ring",
            "--ring-path", str(tmp_path / "empty.buf"),
            "--event-kind", "probe",
            "--output", "jsonl",
            "--jsonl-path", out_path,
            "--count", "2",
            "--interval-s", "0.05",
            "--metrics-port", "0",
            "--max-overhead-pct", "1000",
            "--ici-probe-interval-s", "3600",
            "--ici-probe-payload-kb", "16",
        ]
    )
    assert rc == 0
    events = [
        json.loads(l) for l in open(out_path).read().splitlines()
    ]
    ici = [
        e for e in events
        if e.get("tpu", {}).get("program_id") == "icibench"
    ]
    assert len(ici) == 4  # one probe round, four collectives


def test_agent_ring_mode_stamps_multihost_identity(tmp_path):
    """--slice-id/--host-index/--xla-program-id flow into every TPU
    event's TPURef — what slicecorr joins per-host agent streams on
    (the multi-host e2e session's fan-out path)."""
    from tpuslo.cli import agent
    from tpuslo.collector.ringbuf import RingWriter

    ring_path = str(tmp_path / "agent.buf")
    out_path = str(tmp_path / "probes.jsonl")
    writer = RingWriter(ring_path)

    def produce():
        time.sleep(0.3)
        for launch in range(3):
            writer.write_event(
                signal=native.SIG_ICI_COLLECTIVE,
                value=int(25.0 * 1e6),  # 25 ms as ns
                ts_ns=time.time_ns(),
                aux=launch,
                tid=1,
                flags=native.F_TPU,
            )

    producer = threading.Thread(target=produce)
    producer.start()
    rc = agent.main(
        [
            "--probe-source", "ring",
            "--ring-path", ring_path,
            "--count", "8",
            "--interval-s", "0.15",
            "--output", "jsonl",
            "--jsonl-path", out_path,
            "--node", "dist-host-1",
            "--slice-id", "test-slice",
            "--host-index", "1",
            "--xla-program-id", "dist_psum",
            "--signal-set", "ici_collective_latency_ms",
            "--capability-mode", "tpu_full",
            "--metrics-port", "0",
            "--max-overhead-pct", "1000",
        ]
    )
    producer.join()
    assert rc == 0
    events = [
        json.loads(line)
        for line in open(out_path).read().splitlines()
        if line.strip()
    ]
    collectives = [
        e for e in events if e["signal"] == "ici_collective_latency_ms"
    ]
    assert len(collectives) == 3
    launches = set()
    for event in collectives:
        assert event["tpu"]["slice_id"] == "test-slice"
        assert event["tpu"]["host_index"] == 1
        assert event["tpu"]["program_id"] == "dist_psum"
        assert abs(event["value"] - 25.0) < 1e-6
        launches.add(event["tpu"]["launch_id"])
    assert launches == {0, 1, 2}


def test_owned_side_ring_created_securely_and_cleaned_up(
    tmp_path, monkeypatch
):
    """When the agent owns the side ring (no --ring-path), the file is
    created via mkstemp (not the race-prone mktemp) and removed on
    exit."""
    import tempfile

    from tpuslo.cli import agent

    monkeypatch.setattr(tempfile, "tempdir", str(tmp_path))
    rc = agent.main(
        [
            "--probe-source", "ring",
            "--hello",
            "--event-kind", "probe",
            "--output", "jsonl",
            "--jsonl-path", str(tmp_path / "probes.jsonl"),
            "--count", "2",
            "--interval-s", "0.05",
            "--metrics-port", "0",
            "--max-overhead-pct", "1000",
            "--signal-set", "dns_latency_ms",
        ]
    )
    assert rc == 0
    assert not list(tmp_path.glob("tpuslo-ring-*.buf"))


def test_ring_consumer_lifts_launch_id_for_dcn_events():
    """aux -> launch_id must lift for BOTH collective signals: the
    cross-slice joiner keys dcn_transfer groups on (program, launch),
    so a dropped launch id silently disables slice-level verdicts."""
    import tempfile

    from tpuslo.collector import native
    from tpuslo.collector.ringbuf import RingBufConsumer, RingWriter

    path = tempfile.mktemp(suffix=".buf")
    consumer = RingBufConsumer()
    writer = RingWriter(path)
    consumer.add_userspace_ring(path)
    writer.write_event(
        signal=native.SIG_DCN_TRANSFER, value=int(33.0e6), ts_ns=5,
        aux=7, pid=1, tid=0, flags=native.F_TPU,
    )
    samples = list(consumer.poll())
    assert samples and samples[0].signal == "dcn_transfer_latency_ms"
    from tpuslo.collector.ringbuf import to_probe_event
    from tpuslo.signals import Metadata

    meta = Metadata(
        node="n", namespace="llm", pod="p", container="c", pid=1, tid=0,
        tpu_chip="accel0", slice_id="s-0", host_index=0,
        xla_program_id="prog",
    )
    event = to_probe_event(samples[0], meta)
    assert event.tpu.launch_id == 7
