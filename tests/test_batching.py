"""Continuous batching: per-request parity with single-request serving."""

import jax
import pytest

from tpuslo.models.batching import ContinuousBatchingEngine
from tpuslo.models.llama import init_params, llama_tiny
from tpuslo.models.serve import ServeEngine


def _cfg():
    return llama_tiny(max_seq_len=128)


def _plain(params, prompt, n, stop=False):
    engine = ServeEngine(cfg=_cfg(), params=params)
    return [
        e.token_id
        for e in engine.generate(prompt, max_new_tokens=n, stop_at_eos=stop)
    ]


def test_requests_match_single_request_serving():
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)

    prompts = ["alpha", "a much longer prompt with more bytes", "z"]
    ids = [engine.submit(p, max_new_tokens=10, stop_at_eos=False) for p in prompts]
    results = engine.run()

    for rid, prompt in zip(ids, prompts):
        assert results[rid] == _plain(params, prompt, 10), prompt


def test_more_requests_than_slots_queue_and_reuse():
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    ids = [
        engine.submit(f"req {i}", max_new_tokens=4 + i, stop_at_eos=False)
        for i in range(5)
    ]
    results = engine.run()
    assert set(results) == set(ids)
    for i, rid in enumerate(ids):
        assert len(results[rid]) == 4 + i
    # 5 requests through 2 slots: slots were reused.
    assert engine.steps < sum(4 + i for i in range(5))


def test_single_token_requests_complete_without_slots():
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=1)
    rid = engine.submit("one token only", max_new_tokens=1, stop_at_eos=False)
    results = engine.run()
    assert len(results[rid]) == 1
    assert results[rid] == _plain(params, "one token only", 1)


def test_interleaved_admission_does_not_disturb_running_rows():
    """A request admitted mid-flight must not change an in-progress
    row's output (slot injection only touches its own row)."""
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    first = engine.submit("steady request", max_new_tokens=12, stop_at_eos=False)
    # Run a few steps solo, then add a second request mid-stream.
    for _ in range(4):
        engine.step()
    second = engine.submit("late arrival", max_new_tokens=6, stop_at_eos=False)
    results = engine.run()

    assert results[first] == _plain(params, "steady request", 12)
    assert results[second] == _plain(params, "late arrival", 6)


def test_bad_slot_count_rejected():
    with pytest.raises(ValueError, match="max_slots"):
        ContinuousBatchingEngine(cfg=_cfg(), max_slots=0)


def test_budget_capped_near_capacity():
    """Requests near KV capacity are clamped, never writing OOB."""
    cfg = _cfg()  # max_seq_len=128
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ContinuousBatchingEngine(cfg=cfg, params=params, max_slots=1)
    long_prompt = "p" * 120  # 121 ids: 6 free slots
    rid = engine.submit(long_prompt, max_new_tokens=50, stop_at_eos=False)
    results = engine.run()
    assert len(results[rid]) == 128 - 121 - 1  # capped to avail
    # Parity with the single-request engine, which applies the same cap.
    assert results[rid] == _plain(params, long_prompt, 50)


def test_mid_range_budget_matches_single_request_cap():
    """The chunk-rounded budget cap must match ServeEngine exactly."""
    cfg = llama_tiny(max_seq_len=512)
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ContinuousBatchingEngine(cfg=cfg, params=params, max_slots=1)
    rid = engine.submit("x", max_new_tokens=500, stop_at_eos=False)
    results = engine.run()
    plain_engine = ServeEngine(cfg=cfg, params=params)
    plain = [
        e.token_id
        for e in plain_engine.generate("x", max_new_tokens=500, stop_at_eos=False)
    ]
    assert len(results[rid]) == len(plain)
    assert results[rid] == plain


def test_instant_requests_never_dispatch_decode():
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    ids = [engine.submit(f"i{n}", max_new_tokens=1, stop_at_eos=False) for n in range(3)]
    results = engine.run()
    assert engine.steps == 0  # all three completed at admission
    assert set(results) == set(ids)


def test_stats_reflect_lifecycle():
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    assert engine.stats() == {
        "active_slots": 0, "max_slots": 2, "occupancy": 0.0,
        "queued": 0, "steps": 0, "completed": 0,
    }
    engine.submit("a", max_new_tokens=6, stop_at_eos=False)
    engine.submit("b", max_new_tokens=6, stop_at_eos=False)
    engine.step()
    mid = engine.stats()
    assert mid["active_slots"] == 2 and mid["occupancy"] == 1.0
    engine.run()
    done = engine.stats()
    assert done["active_slots"] == 0 and done["completed"] == 2


def test_partial_tokens_streams_per_step():
    """partial_tokens exposes tokens as decode advances (the streaming
    seam the demo backend uses for honest TTFT/tokens-per-sec SLIs)."""
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    rid = engine.submit("stream me", max_new_tokens=6, stop_at_eos=False)
    assert engine.partial_tokens(rid) == []  # queued
    seen = 0
    grew = 0
    while rid not in engine.results:
        engine.step()
        now = len(engine.partial_tokens(rid))
        if now > seen:
            grew += 1
        assert now >= seen
        seen = now
    assert grew >= 2  # tokens appeared incrementally, not in one burst
    assert engine.partial_tokens(rid) == engine.results[rid]
    assert engine.partial_tokens(99999) is None


def test_cancel_releases_queue_slot_and_results():
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=1)
    keep = engine.submit("keep", max_new_tokens=4, stop_at_eos=False)
    drop = engine.submit("drop", max_new_tokens=4, stop_at_eos=False)  # queued
    engine.step()
    engine.cancel(drop)
    assert engine.partial_tokens(drop) is None
    engine.run()
    assert keep in engine.results and drop not in engine.results
    # cancel after completion is idempotent and clears the result
    engine.cancel(keep)
    assert keep not in engine.results


def test_backend_generator_close_cancels_request():
    """A client disconnect (generator close) must not leave a ghost
    request decoding or an unowned entry in results."""
    from demo.rag_service.service import JaxBatchedBackend

    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    backend = JaxBatchedBackend(engine=engine)
    gen = backend.generate("disconnect me", 8, 0, 0)
    next(gen)  # request admitted and producing
    gen.close()  # BrokenPipeError path in server.py
    assert not any(engine._slots), "cancelled request still holds a slot"
    engine.run()
    assert engine.results == {}, "ghost result left behind after disconnect"


def test_prefix_requests_match_single_request_serving():
    """submit(prefix=...) must equal the plain engine on prefix+prompt,
    including when mixed with non-prefix requests mid-flight."""
    params = init_params(jax.random.PRNGKey(0), _cfg())
    engine = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    sys_prompt = "system: terse answers only. "
    a = engine.submit("what is ttft?", max_new_tokens=8,
                      stop_at_eos=False, prefix=sys_prompt)
    b = engine.submit("plain request", max_new_tokens=8, stop_at_eos=False)
    for _ in range(3):
        engine.step()
    c = engine.submit("second prefixed", max_new_tokens=6,
                      stop_at_eos=False, prefix=sys_prompt)
    results = engine.run()

    assert results[a] == _plain(params, sys_prompt + "what is ttft?", 8)
    assert results[b] == _plain(params, "plain request", 8)
    assert results[c] == _plain(params, sys_prompt + "second prefixed", 6)
    # One snapshot serves both prefixed requests.
    assert list(engine._ingest._prefix_cache) == [sys_prompt]


def test_near_capacity_admission_skips_tail_compile():
    """A near-capacity prompt must not compile the single-token tail
    decode fn the batching engine never uses."""
    cfg = llama_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ContinuousBatchingEngine(
        cfg=cfg, params=params, max_slots=1, prefill_buckets=(32, 64)
    )
    rid = engine.submit("w" * 120, max_new_tokens=50, stop_at_eos=False)
    results = engine.run()
    assert engine._ingest._decode_one is None  # tail fn never built
    # Budget equals what streaming serving grants for the same prompt.
    assert len(results[rid]) == engine._ingest.decode_cap_tokens(121)

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


def test_request_timings_record_queue_delay_and_e2e():
    """Lifecycle SLIs: every completed request reports a queue delay
    (submit -> slot admission) and e2e latency; queued-behind requests
    must show strictly later admission than the first wave."""
    params = init_params(jax.random.PRNGKey(0), _cfg())
    eng = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    ids = [
        eng.submit(f"request {i}", max_new_tokens=6, stop_at_eos=False)
        for i in range(5)
    ]
    eng.run()
    timings = eng.request_timings()
    assert sorted(timings) == sorted(ids)
    for t in timings.values():
        assert t["queue_delay_s"] >= 0.0
        assert t["e2e_s"] >= t["queue_delay_s"]
    # With 2 slots, request 4 cannot be admitted before a completion.
    first_wave = min(timings[i]["queue_delay_s"] for i in ids[:2])
    assert timings[ids[4]]["queue_delay_s"] > first_wave


def test_instant_request_timings_complete():
    """max_new_tokens=1 requests finish at admission; their record must
    still carry both SLIs."""
    params = init_params(jax.random.PRNGKey(0), _cfg())
    eng = ContinuousBatchingEngine(cfg=_cfg(), params=params, max_slots=2)
    rid = eng.submit("one token", max_new_tokens=1, stop_at_eos=False)
    eng.run()
    t = eng.request_timings()[rid]
    assert t["e2e_s"] >= 0.0 and t["queue_delay_s"] >= 0.0
