"""Collector tests. Reference model: pkg/collector/{synthetic,pipeline}_test.go."""

from datetime import datetime, timezone

import pytest

from tpuslo import collector, schema

TS = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)
META = collector.SampleMeta(node="tpu-vm-0")


class TestSynthetic:
    def test_supported_scenarios_include_tpu_faults(self):
        scenarios = collector.supported_synthetic_scenarios()
        for name in (
            "baseline",
            "mixed",
            "mixed_multi",
            "ici_drop",
            "hbm_pressure",
            "xla_recompile_storm",
            "host_offload_stall",
            "tpu_mixed",
        ):
            assert name in scenarios

    def test_unknown_scenario_raises(self):
        with pytest.raises(ValueError):
            collector.build_synthetic_sample("warp_core_breach", 0, TS, META)

    def test_deterministic(self):
        a = collector.generate_synthetic_samples("tpu_mixed", 8, TS, META)
        b = collector.generate_synthetic_samples("tpu_mixed", 8, TS, META)
        assert [s.to_dict() for s in a] == [s.to_dict() for s in b]

    def test_mixed_rotates_fault_labels(self):
        samples = collector.generate_synthetic_samples("tpu_mixed", 8, TS, META)
        labels = [s.fault_label for s in samples[:4]]
        assert labels == [
            "ici_drop",
            "hbm_pressure",
            "xla_recompile_storm",
            "host_offload_stall",
        ]
        assert samples[4].fault_label == "ici_drop"

    def test_baseline_has_no_fault_label(self):
        sample = collector.build_synthetic_sample("baseline", 0, TS, META)
        assert sample.fault_label == ""
        assert sample.ttft_ms == 340

    def test_timestamps_advance_per_second(self):
        samples = collector.generate_synthetic_samples("baseline", 3, TS, META)
        deltas = [
            (samples[i + 1].timestamp - samples[i].timestamp).total_seconds()
            for i in range(2)
        ]
        assert deltas == [1.0, 1.0]

    def test_count_must_be_positive(self):
        with pytest.raises(ValueError):
            collector.generate_synthetic_samples("baseline", 0, TS, META)

    def test_raw_sample_round_trip(self):
        sample = collector.build_synthetic_sample("hbm_pressure", 3, TS, META)
        again = collector.RawSample.from_dict(sample.to_dict())
        assert again.to_dict() == sample.to_dict()


class TestNormalize:
    def test_four_events_per_sample(self):
        sample = collector.build_synthetic_sample("baseline", 0, TS, META)
        events = collector.normalize_sample(sample)
        assert [e.sli_name for e in events] == [
            "ttft_ms",
            "request_latency_ms",
            "token_throughput_tps",
            "error_rate",
        ]
        for event in events:
            schema.validate(event.to_dict(), schema.SCHEMA_SLO_EVENT)

    def test_baseline_statuses(self):
        sample = collector.build_synthetic_sample("baseline", 0, TS, META)
        by_sli = {e.sli_name: e.status for e in collector.normalize_sample(sample)}
        # Baseline latency of 720ms sits just above the 700ms warning line,
        # mirroring the reference's synthetic baseline.
        assert by_sli == {
            "ttft_ms": "ok",
            "request_latency_ms": "warning",
            "token_throughput_tps": "ok",
            "error_rate": "ok",
        }

    def test_recompile_storm_breaches_ttft_not_throughput(self):
        sample = collector.build_synthetic_sample("xla_recompile_storm", 0, TS, META)
        by_sli = {e.sli_name: e.status for e in collector.normalize_sample(sample)}
        assert by_sli["ttft_ms"] == "breach"
        assert by_sli["token_throughput_tps"] == "warning"

    def test_ici_drop_breaches_throughput(self):
        sample = collector.build_synthetic_sample("ici_drop", 0, TS, META)
        by_sli = {e.sli_name: e.status for e in collector.normalize_sample(sample)}
        assert by_sli["token_throughput_tps"] == "breach"
        assert by_sli["error_rate"] == "breach"

    def test_threshold_boundaries(self):
        assert collector.threshold_status(499.9, 500, 1000) == "ok"
        assert collector.threshold_status(500, 500, 1000) == "warning"
        assert collector.threshold_status(1000, 500, 1000) == "breach"
        assert collector.inverse_threshold_status(31, 30, 10) == "ok"
        assert collector.inverse_threshold_status(30, 30, 10) == "warning"
        assert collector.inverse_threshold_status(10, 30, 10) == "breach"

    def test_labels_carry_node_and_fault(self):
        sample = collector.build_synthetic_sample("dns_latency", 0, TS, META)
        event = collector.normalize_sample(sample)[0]
        assert event.labels["node"] == "tpu-vm-0"
        assert event.labels["fault_label"] == "dns_latency"


class TestHBMSamplerHangBoundary:
    """A dead TPU tunnel makes jax.devices() HANG (no exception); the
    sampler's live-device probe must time out once, then stay disabled
    instead of parking a worker thread per cycle (the agent ring loop
    wedged on exactly this before the boundary existed)."""

    def test_hung_device_probe_times_out_and_disables(self, monkeypatch):
        import sys
        import threading
        import time as _time
        import types

        from tpuslo.collector import hbm_sampler

        release = threading.Event()
        fake_jax = types.SimpleNamespace(
            devices=lambda: release.wait(30.0) or []
        )
        monkeypatch.setitem(sys.modules, "jax", fake_jax)
        monkeypatch.setenv("TPUSLO_HBM_PROBE_TIMEOUT_S", "0.2")
        monkeypatch.setattr(hbm_sampler, "_DEVICE_PROBE_DEAD", False)

        t0 = _time.perf_counter()
        assert hbm_sampler.read_stats() is None
        first = _time.perf_counter() - t0
        assert first < 5.0  # returned at the join timeout, not the hang
        assert hbm_sampler._DEVICE_PROBE_DEAD

        # Second call: permanent disable, no new worker, instant.
        t0 = _time.perf_counter()
        assert hbm_sampler.read_stats() is None
        assert _time.perf_counter() - t0 < 0.05
        release.set()

    def test_stats_file_path_unaffected(self, tmp_path, monkeypatch):
        from tpuslo.collector import hbm_sampler

        monkeypatch.setattr(hbm_sampler, "_DEVICE_PROBE_DEAD", True)
        stats = tmp_path / "hbm.json"
        stats.write_text('{"bytes_in_use": 8, "bytes_limit": 16}')
        assert hbm_sampler.read_stats(str(stats)) == (8, 16)
