"""Perf smoke for the probe-event spine (``make bench-smoke``).

Runs ``bench_pipeline`` on a small sample count and asserts the spine
is alive end-to-end AND that the structural fast-path validator is the
path actually taken — a silent fall-through to per-event jsonschema
would pass a pure throughput check while giving the speedup back.
The row gate validates every generated payload through the combined
validator, so the counters prove which path admission took.
"""

import pytest

import bench
from tpuslo.schema import VALIDATION_COUNTERS

pytestmark = pytest.mark.slow


def test_bench_pipeline_smoke_engages_fastpath():
    VALIDATION_COUNTERS.reset()
    result = bench.bench_pipeline(sample_count=40, repeats=1)

    assert result["probe_events"] > 0
    assert result["probe_events_per_sec"] > 0
    assert result["matcher_pairs_per_sec"] > 0
    assert result["matcher_matches"] > 0
    assert result["columnar"]["probe_events_per_sec"] > 0

    # The counter (exposed via tpuslo.metrics) proves the fast path ran.
    assert VALIDATION_COUNTERS.engaged
    snap = VALIDATION_COUNTERS.snapshot()
    # Generator output is always contract-valid: every payload the row
    # gate admitted must have taken the fast path, and none may be
    # dropped as invalid.
    assert snap["fastpath_valid"] >= result["probe_events"]
    assert snap["fastpath_fallback"] == 0
    assert snap["slowpath_invalid"] == 0


def test_counters_reachable_via_metrics_package():
    from tpuslo import metrics

    assert metrics.VALIDATION_COUNTERS is VALIDATION_COUNTERS
