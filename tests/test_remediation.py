"""Auto-remediation engine: policy matching (cooldown / rate-limit /
budget edges), every action's apply/rollback round trip, verifier
confirm/rollback/hysteresis, engine state-machine + crash-restart
parity, shed-ownership precedence vs the supervisor hold-down, the
provenance chain, the sloctl surfaces, and the seeded sweep gate.

Style follows tests/test_fleet.py: unit tiers per module, seeded
integration lanes, and regression tests for the review findings (the
flap-shed precedence gap is satellite 2's named regression).
"""

from __future__ import annotations

import json
import os

import pytest

from tpuslo.delivery.breaker import (
    STATE_CLOSED,
    STATE_OPEN,
    CircuitBreaker,
)
from tpuslo.fleet.aggregator import AggregatorShard
from tpuslo.fleet.ring import HashRing
from tpuslo.obs.provenance import (
    ProvenanceLog,
    ProvenanceRecord,
    format_chain,
    load_records,
)
from tpuslo.remediation import (
    ACTION_BREAKER_TRIP,
    ACTION_CORDON_NODE,
    ACTION_DEMOTE_TENANT,
    ACTION_PROBE_SHED,
    ACTION_REHOME_SLICE,
    PHASE_APPLY_FAILED,
    PHASE_APPLYING,
    PHASE_CONFIRMED,
    PHASE_ROLLED_BACK,
    PHASE_VERIFYING,
    ActionBindings,
    ActionRecord,
    AttributionContext,
    BreakerTripAction,
    CordonNodeAction,
    DemoteTenantAction,
    DrainSnapshotAction,
    ProbeShedAction,
    RehomeSliceAction,
    RemediationEngine,
    RemediationPolicy,
    VERDICT_CONFIRMED,
    VERDICT_PENDING,
    VERDICT_ROLLBACK,
    VerifyPolicy,
    VerifyState,
    action_id_for,
    default_rules,
    observe_window,
)
from tpuslo.runtime.supervisor import ProbeSupervisor, SupervisorConfig
from tpuslo.safety.recovery import (
    OWNER_GUARD,
    OWNER_REMEDIATION,
    ShedOwnership,
    ShedRecoveryPolicy,
)
from tpuslo.signals.generator import Generator
from tpuslo.sloengine.engine import (
    DEFAULT_ADMISSION_PRIORITY,
    BurnEngine,
    EngineConfig,
)


def _ctx(
    domain: str = "tpu_hbm",
    confidence: float = 0.95,
    burn_state: str = "fast_burn",
    incident: str = "inc-1",
    tenant: str = "tenant-a",
    **kw,
) -> AttributionContext:
    return AttributionContext(
        incident_id=incident,
        domain=domain,
        confidence=confidence,
        burn_state=burn_state,
        tenant=tenant,
        **kw,
    )


# ---------------------------------------------------------------------------
# policy: matching + dampers
# ---------------------------------------------------------------------------


class TestPolicy:
    def test_matches_high_confidence_fast_burn(self):
        policy = RemediationPolicy()
        decision = policy.decide(_ctx(), now_s=0.0, in_flight=0)
        assert decision is not None
        assert decision.action == ACTION_DEMOTE_TENANT
        assert decision.target == "tenant-a"

    def test_low_confidence_refused(self):
        policy = RemediationPolicy()
        assert policy.decide(
            _ctx(confidence=0.5), now_s=0.0, in_flight=0
        ) is None
        assert policy.refusals.get("low_confidence", 0) == 1

    def test_healthy_burn_state_refused(self):
        policy = RemediationPolicy()
        assert policy.decide(
            _ctx(burn_state="ok"), now_s=0.0, in_flight=0
        ) is None
        assert policy.refusals.get("not_burning", 0) == 1

    def test_unknown_domain_refused(self):
        policy = RemediationPolicy()
        assert policy.decide(
            _ctx(domain="made_up"), now_s=0.0, in_flight=0
        ) is None
        assert policy.refusals.get("no_rule", 0) == 1

    def test_global_budget_refused(self):
        policy = RemediationPolicy(max_concurrent_actions=2)
        assert policy.decide(_ctx(), now_s=0.0, in_flight=2) is None
        assert policy.refusals.get("budget", 0) == 1

    def test_cooldown_blocks_same_target(self):
        policy = RemediationPolicy()
        decision = policy.decide(_ctx(), now_s=0.0, in_flight=0)
        policy.note_applied(decision.action, decision.target, 0.0)
        # Same target inside the cooldown: refused.
        assert policy.decide(
            _ctx(incident="inc-2"), now_s=10.0, in_flight=0
        ) is None
        assert policy.refusals.get("cooldown", 0) == 1
        # Past the cooldown it can act again.
        assert policy.decide(
            _ctx(incident="inc-3"), now_s=301.0, in_flight=0
        ) is not None

    def test_cooldown_does_not_block_other_target(self):
        policy = RemediationPolicy()
        policy.note_applied(ACTION_DEMOTE_TENANT, "tenant-a", 0.0)
        assert policy.decide(
            _ctx(incident="inc-2", tenant="tenant-b"),
            now_s=10.0,
            in_flight=0,
        ) is not None

    def test_rate_limit_per_kind(self):
        policy = RemediationPolicy()
        for i in range(3):
            policy.note_applied(
                ACTION_DEMOTE_TENANT, f"tenant-{i}", float(i)
            )
        assert policy.decide(
            _ctx(incident="inc-x", tenant="tenant-z"),
            now_s=10.0,
            in_flight=0,
        ) is None
        assert policy.refusals.get("rate_limited", 0) == 1
        # The window slides: an hour later the same kind can act.
        assert policy.decide(
            _ctx(incident="inc-y", tenant="tenant-z"),
            now_s=3700.0,
            in_flight=0,
        ) is not None

    def test_disabled_action_refused(self):
        policy = RemediationPolicy(
            disabled_actions=(ACTION_DEMOTE_TENANT,)
        )
        assert policy.decide(_ctx(), now_s=0.0, in_flight=0) is None
        assert policy.refusals.get("disabled", 0) == 1

    def test_node_slice_target_derivation(self):
        policy = RemediationPolicy()
        decision = policy.decide(
            _ctx(domain="tpu_ici", node="n1", slice_id="s1"),
            now_s=0.0,
            in_flight=0,
        )
        assert decision.action == ACTION_CORDON_NODE
        assert decision.target == "n1|s1"

    def test_missing_node_target_refused(self):
        policy = RemediationPolicy()
        assert policy.decide(
            _ctx(domain="tpu_ici"), now_s=0.0, in_flight=0
        ) is None
        assert policy.refusals.get("no_target", 0) == 1

    def test_damper_state_round_trip(self):
        policy = RemediationPolicy()
        policy.note_applied(ACTION_DEMOTE_TENANT, "tenant-a", 100.0)
        policy.decide(_ctx(burn_state="ok"), now_s=0.0, in_flight=0)
        restored = RemediationPolicy()
        restored.restore_state(policy.export_state())
        assert restored.decisions == policy.decisions
        # Cooldown survives the round trip.
        assert restored.decide(
            _ctx(incident="inc-2"), now_s=150.0, in_flight=0
        ) is None
        assert restored.refusals.get("cooldown", 0) == 1
        # The pre-restart refusal counts carried over too.
        assert restored.refusals.get("not_burning", 0) == 1


# ---------------------------------------------------------------------------
# actions: apply/rollback round trips against the real substrate
# ---------------------------------------------------------------------------


class TestActions:
    def test_probe_shed_round_trip(self):
        gen = Generator("tpu_full")
        ownership = ShedOwnership()
        action = ProbeShedAction(
            "syscall_latency_ms", gen, ownership=ownership
        )
        assert action.apply().ok
        assert "syscall_latency_ms" in gen.shed_signals()
        assert ownership.owner_of("syscall_latency_ms") == (
            OWNER_REMEDIATION
        )
        assert action.rollback().ok
        assert "syscall_latency_ms" not in gen.shed_signals()
        assert "syscall_latency_ms" in gen.enabled_signals()
        assert ownership.owner_of("syscall_latency_ms") == ""

    def test_probe_shed_refuses_foreign_shed(self):
        gen = Generator("tpu_full")
        ownership = ShedOwnership()
        ownership.claim("syscall_latency_ms", OWNER_GUARD)
        action = ProbeShedAction(
            "syscall_latency_ms", gen, ownership=ownership
        )
        result = action.apply()
        assert not result.ok
        assert "guard" in result.detail

    def test_probe_shed_refuses_untagged_existing_shed(self):
        gen = Generator("tpu_full")
        gen.import_shed(["syscall_latency_ms"])  # legacy untagged shed
        ownership = ShedOwnership()
        action = ProbeShedAction(
            "syscall_latency_ms", gen, ownership=ownership
        )
        assert not action.apply().ok
        # The refused apply must not leave a dangling claim behind.
        assert ownership.owner_of("syscall_latency_ms") == ""

    def test_probe_shed_rollback_respects_holddown(self):
        gen = Generator("tpu_full")
        ownership = ShedOwnership()
        clock = [0.0]
        supervisor = ProbeSupervisor(
            SupervisorConfig(flap_holddown_s=300.0),
            clock=lambda: clock[0],
        )
        action = ProbeShedAction(
            "syscall_latency_ms",
            gen,
            ownership=ownership,
            supervisor=supervisor,
        )
        assert action.apply().ok
        # The supervisor flap-sheds the same signal while the
        # remediation is in flight.
        supervisor._held["syscall_latency_ms"] = 300.0
        result = action.rollback()
        assert result.ok and "held down" in result.detail
        # The probe stays shed; ownership is released so the
        # supervisor's machinery takes over.
        assert "syscall_latency_ms" in gen.shed_signals()
        assert ownership.owner_of("syscall_latency_ms") == ""

    def test_breaker_trip_round_trip(self):
        breaker = CircuitBreaker()
        action = BreakerTripAction("otlp", breaker)
        assert action.apply().ok
        assert breaker.state == STATE_OPEN
        assert action.rollback().ok
        assert breaker.state == STATE_CLOSED

    def test_breaker_family_trip_covers_every_otlp_channel(self):
        """Review regression: the agent's OTLP path is one channel per
        payload kind (otlp-slo/otlp-probe/otlp-traces) — a trip
        targeting the "otlp" family must take the whole path offline,
        and must not touch unrelated sinks."""
        breakers = {
            name: CircuitBreaker()
            for name in (
                "otlp-slo", "otlp-probe", "otlp-traces", "webhook",
            )
        }
        bindings = ActionBindings(breakers=breakers)
        action = bindings.build(ACTION_BREAKER_TRIP, "otlp")
        assert action is not None
        result = action.apply()
        assert result.ok and "3 breaker(s)" in result.detail
        for name in ("otlp-slo", "otlp-probe", "otlp-traces"):
            assert breakers[name].state == STATE_OPEN, name
        assert breakers["webhook"].state == STATE_CLOSED
        assert action.rollback().ok
        assert all(b.state == STATE_CLOSED for b in breakers.values())
        # An unmatched family is unbound, not a silent no-op.
        assert bindings.build(ACTION_BREAKER_TRIP, "nosuch") is None

    def test_forced_close_clears_failure_streak(self):
        breaker = CircuitBreaker(failure_threshold=2)
        breaker.record_failure()
        breaker.force_open()
        breaker.force_close()
        # A single failure off a stale streak must not re-open.
        breaker.record_failure()
        assert breaker.state == STATE_CLOSED

    def test_cordon_round_trip(self):
        ring = HashRing(["agg-0", "agg-1"], vnodes=8)
        action = CordonNodeAction("n1", "s1", ring)
        assert action.apply().ok
        assert ring.is_cordoned("n1", "s1")
        assert "n1" not in ring.assignments([("n1", "s1"), ("n2", "s1")])
        assert not action.apply().ok  # idempotence guard
        assert action.rollback().ok
        assert not ring.is_cordoned("n1", "s1")
        assert "n1" in ring.assignments([("n1", "s1")])

    def test_cordon_survives_ring_snapshot(self):
        ring = HashRing(["agg-0"], vnodes=8)
        ring.cordon("n1", "s1")
        restored = HashRing(["x"], vnodes=8)
        restored.restore_state(ring.export_state())
        assert restored.is_cordoned("n1", "s1")

    def test_demote_tenant_round_trip(self):
        burn = BurnEngine(EngineConfig())
        action = DemoteTenantAction("tenant-a", burn)
        assert action.apply().ok
        assert burn.admission_priority("tenant-a") < (
            DEFAULT_ADMISSION_PRIORITY
        )
        assert not action.apply().ok  # no stacked demotions
        assert action.rollback().ok
        assert burn.admission_priority("tenant-a") == (
            DEFAULT_ADMISSION_PRIORITY
        )
        # Ensure-undone semantics: a second rollback is a clean no-op.
        second = action.rollback()
        assert second.ok and "nothing to undo" in second.detail

    def test_demotion_survives_burn_snapshot(self):
        burn = BurnEngine(EngineConfig())
        burn.demote_tenant("tenant-a")
        restored = BurnEngine(EngineConfig())
        restored.restore_state(burn.export_state())
        assert restored.demoted_tenants() == ["tenant-a"]
        assert restored.admission_priority("tenant-a") < (
            DEFAULT_ADMISSION_PRIORITY
        )

    def test_rehome_slice_round_trip(self):
        source = AggregatorShard("agg-0")
        target = AggregatorShard("agg-1")
        for node, slice_id in (
            ("n1", "s1"), ("n2", "s1"), ("n3", "s2"),
        ):
            source.absorb_node_state(
                node,
                {"head_ns": 10, "seq": 1, "events": 5,
                 "slice_id": slice_id},
            )
        action = RehomeSliceAction("s1", source, target)
        result = action.apply()
        assert result.ok and "2 node(s)" in result.detail
        assert set(target.nodes) == {"n1", "n2"}
        assert set(source.nodes) == {"n3"}
        assert action.rollback().ok
        assert set(source.nodes) == {"n1", "n2", "n3"}

    def test_rehome_moves_pending_evidence_off_the_source(self):
        """Review regression: popping just the node state left the
        pending window groups in the source accumulator, so both
        shards emitted the re-homed slice's windows — duplicates."""
        source = AggregatorShard("agg-0")
        target = AggregatorShard("agg-1")
        source.absorb_node_state(
            "n1",
            {
                "head_ns": 10,
                "seq": 1,
                "events": 5,
                "slice_id": "s1",
                "pending": [
                    {
                        "bucket": 3,
                        "namespace": "tenant-a",
                        "pod": "pod-0",
                        "signals": {"hbm_alloc_stall_ms": 40.0},
                    }
                ],
            },
        )
        assert RehomeSliceAction("s1", source, target).apply().ok
        # The target owns the evidence; the source forgot it entirely.
        target_pending = target.export_state()["nodes"]["n1"]["pending"]
        assert target_pending and target_pending[0]["bucket"] == 3
        assert "n1" not in source.export_state()["nodes"]
        assert source.export_state() == {"window_ns": source.window_ns,
                                         "nodes": {}}

    def test_drain_snapshot_runs_steps(self, tmp_path):
        from tpuslo.runtime import AgentRuntime, StateStore

        runtime = AgentRuntime(
            StateStore(tmp_path / "state.json", interval_s=0)
        )
        runtime.register("c", lambda: {"x": 1}, lambda s: None)
        ran = []
        action = DrainSnapshotAction(
            "agent",
            runtime,
            drain_steps=[("flush", lambda budget: ran.append(budget))],
            deadline_s=2.0,
        )
        result = action.apply()
        assert result.ok
        assert len(ran) == 1
        assert (tmp_path / "state.json").exists()
        assert action.rollback().ok  # honest no-op

    def test_bindings_build_unbound_kind_is_none(self):
        bindings = ActionBindings()
        assert bindings.build(ACTION_PROBE_SHED, "x") is None
        assert bindings.build(ACTION_BREAKER_TRIP, "otlp") is None
        assert bindings.build(ACTION_REHOME_SLICE, "s1") is None
        assert bindings.build("unknown_kind", "x") is None


# ---------------------------------------------------------------------------
# shed ownership: the flap-shed precedence regression (satellite 2)
# ---------------------------------------------------------------------------


class TestShedOwnership:
    def test_claim_release_owner(self):
        ownership = ShedOwnership()
        assert ownership.claim("sig", OWNER_REMEDIATION)
        assert ownership.claim("sig", OWNER_REMEDIATION)  # re-claim ok
        assert not ownership.claim("sig", OWNER_GUARD)
        assert not ownership.release("sig", OWNER_GUARD)
        assert ownership.release("sig", OWNER_REMEDIATION)
        assert ownership.owner_of("sig") == ""

    def test_guard_cannot_restore_remediation_shed(self):
        """Satellite 2 regression: the overhead-guard recovery streak
        must not restore a probe the remediation engine shed — the two
        policies tugged-of-war before the ownership tag existed."""
        gen = Generator("tpu_full")
        ownership = ShedOwnership()
        ProbeShedAction(
            "syscall_latency_ms", gen, ownership=ownership
        ).apply()
        recovery = ShedRecoveryPolicy(cycles=1)
        # The guard's restore path (agent loop) consults ownership
        # before restore_one: remediation-owned sheds are skipped.
        candidate = gen.shed_signals()[-1]
        assert not ownership.may_restore(candidate, OWNER_GUARD)
        # Untagged and guard-owned sheds remain restorable.
        gen.disable_highest_cost()
        untagged = gen.shed_signals()[-1]
        assert untagged != "syscall_latency_ms"
        assert ownership.may_restore(untagged, OWNER_GUARD)
        del recovery  # streak semantics covered in test_safety

    def test_supervisor_holddown_vetoes_every_owner(self):
        ownership = ShedOwnership()
        clock = [0.0]
        supervisor = ProbeSupervisor(
            SupervisorConfig(flap_holddown_s=100.0),
            clock=lambda: clock[0],
        )
        supervisor._held["sig"] = 100.0
        ownership.claim("sig", OWNER_REMEDIATION)
        assert not ownership.may_restore(
            "sig", OWNER_REMEDIATION, supervisor
        )
        assert not ownership.may_restore("sig", OWNER_GUARD, supervisor)
        clock[0] = 101.0
        assert ownership.may_restore(
            "sig", OWNER_REMEDIATION, supervisor
        )

    def test_ownership_state_round_trip(self):
        ownership = ShedOwnership()
        ownership.claim("a", OWNER_REMEDIATION)
        ownership.claim("b", OWNER_GUARD)
        restored = ShedOwnership()
        restored.restore_state(ownership.export_state())
        assert restored.owner_of("a") == OWNER_REMEDIATION
        assert restored.owner_of("b") == OWNER_GUARD


# ---------------------------------------------------------------------------
# verifier: confirm / rollback / hysteresis
# ---------------------------------------------------------------------------


class TestVerifier:
    def test_confirms_on_sustained_subsidence(self):
        policy = VerifyPolicy(windows=6, subside_streak=2,
                              subside_below=3.0)
        state = VerifyState()
        assert observe_window(policy, state, 20.0) == VERDICT_PENDING
        assert observe_window(policy, state, 1.0) == VERDICT_PENDING
        assert observe_window(policy, state, 0.5) == VERDICT_CONFIRMED

    def test_rolls_back_when_budget_exhausted(self):
        policy = VerifyPolicy(windows=3, subside_streak=2)
        state = VerifyState()
        assert observe_window(policy, state, 20.0) == VERDICT_PENDING
        assert observe_window(policy, state, 20.0) == VERDICT_PENDING
        assert observe_window(policy, state, 20.0) == VERDICT_ROLLBACK

    def test_hysteresis_bounce_resets_streak_without_failing(self):
        policy = VerifyPolicy(windows=6, subside_streak=2,
                              subside_below=3.0)
        state = VerifyState()
        observe_window(policy, state, 1.0)   # streak 1
        observe_window(policy, state, 10.0)  # bounce: streak resets
        assert state.streak == 0
        observe_window(policy, state, 1.0)   # streak 1 again
        assert observe_window(policy, state, 1.0) == VERDICT_CONFIRMED

    def test_last_window_subsidence_still_confirms(self):
        policy = VerifyPolicy(windows=4, subside_streak=2)
        state = VerifyState()
        observe_window(policy, state, 20.0)
        observe_window(policy, state, 20.0)
        observe_window(policy, state, 1.0)
        # Window 4 is both the last budgeted window and the streak's
        # second hit: confirm wins over exhaustion.
        assert observe_window(policy, state, 1.0) == VERDICT_CONFIRMED


# ---------------------------------------------------------------------------
# engine: state machine, restart parity, provenance
# ---------------------------------------------------------------------------


def _engine(tmp_path, **kw) -> tuple[RemediationEngine, BurnEngine]:
    burn = BurnEngine(EngineConfig())
    bindings = ActionBindings(burn_engine=burn)
    engine = RemediationEngine(
        bindings=bindings,
        verify=VerifyPolicy(windows=4, subside_streak=2),
        provenance_log=ProvenanceLog(
            os.fspath(tmp_path / "provenance.jsonl")
        ),
        **kw,
    )
    return engine, burn


class TestEngine:
    def test_consider_applies_and_verifies(self, tmp_path):
        engine, burn = _engine(tmp_path)
        rec = engine.consider(_ctx(), now_s=100.0)
        assert rec is not None and rec.phase == PHASE_VERIFYING
        assert burn.demoted_tenants() == ["tenant-a"]
        assert engine.in_flight() == 1
        resolved = []
        for _ in range(3):
            resolved += engine.tick(200.0, lambda r: 0.0)
        assert [r.phase for r in resolved] == [PHASE_CONFIRMED]
        assert engine.in_flight() == 0
        # Confirmed actions stay applied.
        assert burn.demoted_tenants() == ["tenant-a"]

    def test_failed_verify_rolls_back_and_escalates(self, tmp_path):
        engine, burn = _engine(tmp_path)
        engine.consider(_ctx(), now_s=0.0)
        resolved = []
        for i in range(5):
            resolved += engine.tick(float(i), lambda r: 50.0)
        assert [r.phase for r in resolved] == [PHASE_ROLLED_BACK]
        assert resolved[0].escalated
        assert burn.demoted_tenants() == []

    def test_same_incident_never_acts_twice(self, tmp_path):
        engine, burn = _engine(tmp_path)
        assert engine.consider(_ctx(), now_s=0.0) is not None
        # A re-delivered attribution for the same incident: no-op even
        # after the cooldown would have expired.
        assert engine.consider(_ctx(), now_s=10_000.0) is None
        assert engine.counters.applied == 1

    def test_unbound_substrate_is_apply_failed(self, tmp_path):
        engine = RemediationEngine(
            bindings=ActionBindings(),  # nothing bound
            provenance_log=None,
        )
        rec = engine.consider(_ctx(), now_s=0.0)
        assert rec is not None and rec.phase == PHASE_APPLY_FAILED
        assert engine.in_flight() == 0

    def test_export_restore_parity_with_uninterrupted_run(
        self, tmp_path
    ):
        """The restart run's records must equal the uninterrupted
        run's, transition for transition (the crash-sweep contract)."""

        def drive(engine, burn_seq, kill_at=None):
            engine.consider(_ctx(), now_s=0.0)
            out = []
            for i, burn_rate in enumerate(burn_seq):
                if i == kill_at:
                    state = engine.export_state()
                    burn2 = BurnEngine(EngineConfig())
                    burn2.restore_state(
                        engine.bindings.burn_engine.export_state()
                    )
                    engine = RemediationEngine(
                        bindings=ActionBindings(burn_engine=burn2),
                        verify=engine.verify,
                    )
                    engine.restore_state(state)
                out += engine.tick(float(i + 1), lambda r: burn_rate)
            return engine, out

        burn_seq = [20.0, 2.0, 1.0, 0.5]
        eng_a, resolved_a = drive(_engine(tmp_path)[0], burn_seq)
        eng_b, resolved_b = drive(
            _engine(tmp_path)[0], burn_seq, kill_at=2
        )
        assert [r.to_dict() for r in resolved_a] == [
            r.to_dict() for r in resolved_b
        ]
        assert eng_b.counters.applied == 1
        assert eng_b.counters.interrupted == 0

    def test_interrupted_mid_apply_rolls_back_never_reapplies(
        self, tmp_path
    ):
        """Kill between record registration and apply: the restored
        engine cannot know whether the lever moved, so it rolls back
        and escalates — and the id guard refuses a re-apply."""
        engine, burn = _engine(tmp_path)
        aid = action_id_for("inc-1", ACTION_DEMOTE_TENANT, "tenant-a")
        state = {
            "version": 1,
            "records": [
                ActionRecord(
                    action_id=aid,
                    incident_id="inc-1",
                    kind=ACTION_DEMOTE_TENANT,
                    target="tenant-a",
                    phase=PHASE_APPLYING,
                ).to_dict()
            ],
            "policy": {},
            "counters": {},
        }
        engine.restore_state(state)
        rec = engine._records[aid]
        assert rec.phase == PHASE_ROLLED_BACK
        assert rec.escalated
        assert engine.counters.interrupted == 1
        # The demotion never happened; rollback must not invent one.
        assert burn.demoted_tenants() == []
        # The id guard refuses the same decision forever.
        assert engine.consider(_ctx(), now_s=10_000.0) is None

    def test_provenance_chain_records_full_lifecycle(self, tmp_path):
        engine, _ = _engine(tmp_path)
        base = ProvenanceRecord(
            incident_id="inc-1",
            predicted_fault_domain="tpu_hbm",
            confidence=0.95,
        )
        engine.consider(_ctx(), now_s=0.0, provenance=base)
        for i in range(3):
            engine.tick(float(i + 1), lambda r: 0.0)
        chains = load_records(os.fspath(tmp_path / "provenance.jsonl"))
        rec = chains["inc-1"]
        assert rec.predicted_fault_domain == "tpu_hbm"
        assert len(rec.remediation) == 1
        entry = rec.remediation[0]
        assert entry["kind"] == ACTION_DEMOTE_TENANT
        assert entry["phase"] == PHASE_CONFIRMED
        assert entry["verdict"] == VERDICT_CONFIRMED
        # sloctl explain renders the block.
        text = format_chain(rec)
        assert "remediation" in text
        assert "demote_tenant" in text

    def test_synthesized_provenance_without_base_record(self, tmp_path):
        engine, _ = _engine(tmp_path)
        engine.consider(_ctx(), now_s=0.0)
        chains = load_records(os.fspath(tmp_path / "provenance.jsonl"))
        assert chains["inc-1"].remediation[0]["phase"] == (
            PHASE_VERIFYING
        )

    def test_observer_bridge_counts(self, tmp_path):
        calls = []

        class Obs:
            def applied(self, action):
                calls.append(("applied", action))

            def rolled_back(self, action):
                calls.append(("rolled_back", action))

            def verify_outcome(self, outcome):
                calls.append(("verify", outcome))

            def in_flight(self, count):
                calls.append(("in_flight", count))

            def refused(self, reason):
                calls.append(("refused", reason))

        engine, _ = _engine(tmp_path, observer=Obs())
        engine.consider(_ctx(burn_state="ok"), now_s=0.0)
        engine.consider(_ctx(), now_s=0.0)
        for i in range(5):
            engine.tick(float(i), lambda r: 50.0)
        kinds = [c[0] for c in calls]
        assert "refused" in kinds
        assert ("applied", ACTION_DEMOTE_TENANT) in calls
        assert ("verify", VERDICT_ROLLBACK) in calls
        assert ("rolled_back", ACTION_DEMOTE_TENANT) in calls

    def test_terminal_records_pruned_past_retention(self, tmp_path):
        """Review regression: a long-running agent must not grow its
        per-cycle scans and durable snapshot without bound — settled
        records past the retention depth are pruned, in-flight never."""
        from tpuslo.remediation.engine import MAX_TERMINAL_RECORDS

        burn = BurnEngine(EngineConfig(max_tenants=2048))
        engine = RemediationEngine(
            policy=RemediationPolicy(
                rules=default_rules(
                    cooldown_s=0.0,
                    rate_limit=100_000,
                    rate_window_s=1.0,
                ),
                max_concurrent_actions=1,
            ),
            bindings=ActionBindings(burn_engine=burn),
            verify=VerifyPolicy(windows=2, subside_streak=1),
        )
        total = MAX_TERMINAL_RECORDS + 40
        for i in range(total):
            rec = engine.consider(
                _ctx(incident=f"inc-{i:04d}", tenant=f"t-{i:04d}"),
                now_s=float(i),
            )
            assert rec is not None
            engine.tick(float(i), lambda r: 0.0)  # instant confirm
        assert len(engine.records()) == MAX_TERMINAL_RECORDS
        ids = {r.action_id for r in engine.records()}
        assert action_id_for(
            "inc-0000", ACTION_DEMOTE_TENANT, "t-0000"
        ) not in ids
        assert action_id_for(
            f"inc-{total - 1:04d}", ACTION_DEMOTE_TENANT,
            f"t-{total - 1:04d}",
        ) in ids
        # Counters keep the full history even after pruning.
        assert engine.counters.applied == total

    def test_snapshot_counters(self, tmp_path):
        engine, _ = _engine(tmp_path)
        engine.consider(_ctx(), now_s=0.0)
        snap = engine.snapshot()
        assert snap["applied"] == 1
        assert snap["in_flight"] == 1


# ---------------------------------------------------------------------------
# metrics + config wiring
# ---------------------------------------------------------------------------


class TestWiring:
    def test_prometheus_observer_bridge(self):
        from prometheus_client import generate_latest

        from tpuslo.metrics import AgentMetrics

        metrics = AgentMetrics()
        obs = metrics.remediation_observer()
        obs.applied(ACTION_DEMOTE_TENANT)
        obs.rolled_back(ACTION_DEMOTE_TENANT)
        obs.verify_outcome("rollback")
        obs.in_flight(2)
        obs.refused("low_confidence")
        text = generate_latest(metrics.registry).decode()
        assert (
            'llm_slo_agent_remediation_actions_applied_total'
            '{action="demote_tenant"} 1.0'
        ) in text
        assert (
            "llm_slo_agent_remediation_actions_in_flight 2.0" in text
        )
        assert (
            'llm_slo_agent_remediation_refusals_total'
            '{reason="low_confidence"} 1.0'
        ) in text

    def test_config_presence_implies_on(self, tmp_path):
        from tpuslo.config.toolkitcfg import load_config

        path = tmp_path / "cfg.yaml"
        path.write_text(
            "signal_set: [dns_latency_ms]\n"
            "sampling: {events_per_second_limit: 100}\n"
            "correlation: {window_ms: 1000}\n"
            "otlp: {endpoint: http://x/v1/logs}\n"
            "safety: {max_overhead_pct: 3.0}\n"
            "remediation:\n"
            "  min_confidence: 0.9\n"
            "  disabled_actions: [cordon_node]\n"
        )
        cfg = load_config(os.fspath(path))
        assert cfg.remediation.enabled
        assert cfg.remediation.min_confidence == 0.9
        assert cfg.remediation.disabled_actions == ["cordon_node"]
        # Explicit off still wins.
        path.write_text(
            path.read_text() + "  enabled: false\n"
        )
        assert not load_config(os.fspath(path)).remediation.enabled

    def test_config_rejects_unknown_action_kind(self, tmp_path):
        from tpuslo.config.toolkitcfg import load_config

        path = tmp_path / "cfg.yaml"
        path.write_text(
            "signal_set: [dns_latency_ms]\n"
            "sampling: {events_per_second_limit: 100}\n"
            "correlation: {window_ms: 1000}\n"
            "otlp: {endpoint: http://x/v1/logs}\n"
            "safety: {max_overhead_pct: 3.0}\n"
            "remediation: {disabled_actions: [typo]}\n"
        )
        with pytest.raises(ValueError, match="unknown action kind"):
            load_config(os.fspath(path))

    def test_default_rules_cover_known_domains_only(self):
        from tpuslo.attribution.mapper import map_fault_label

        known = {
            map_fault_label(label)
            for label in (
                "hbm_pressure", "network_partition", "dns_latency",
                "cpu_throttle", "xla_recompile_storm", "ici_drop",
                "host_offload_stall",
            )
        }
        for rule in default_rules():
            assert rule.domain in known

    def test_remediation_evaluate_path_is_lint_clean(self):
        """The evaluate path is registered in the hot-path manifest, so
        TPL120/121 govern it; the repo must self-host clean."""
        from tpuslo.analysis.hotpaths import (
            HOT_DATACLASSES,
            HOT_FUNCTIONS,
        )

        functions = {qn for _, qn in HOT_FUNCTIONS}
        assert "RemediationPolicy.decide" in functions
        assert "RemediationEngine.consider" in functions
        assert "RemediationEngine.tick" in functions
        assert "observe_window" in functions
        dataclasses = {name for _, name in HOT_DATACLASSES}
        assert "ActionRecord" in dataclasses
        assert "AttributionContext" in dataclasses


# ---------------------------------------------------------------------------
# sloctl surfaces
# ---------------------------------------------------------------------------


class TestSloctl:
    def _snapshot_with_actions(self, tmp_path) -> str:
        engine, _ = _engine(tmp_path)
        engine.consider(_ctx(), now_s=100.0)
        snapshot = {
            "schema_version": 1,
            "saved_at": 0.0,
            "components": {"remediation": engine.export_state()},
        }
        path = tmp_path / "agent-state.json"
        path.write_text(json.dumps(snapshot))
        return os.fspath(path)

    def test_remediation_list_table(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main

        state = self._snapshot_with_actions(tmp_path)
        assert main(["remediation", "list", "--state", state]) == 0
        out = capsys.readouterr().out
        assert "demote_tenant" in out
        assert "tenant-a" in out
        assert "verifying" in out

    def test_remediation_list_json(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main

        state = self._snapshot_with_actions(tmp_path)
        assert main(
            ["remediation", "list", "--state", state, "--json"]
        ) == 0
        records = json.loads(capsys.readouterr().out)
        assert len(records) == 1
        assert records[0]["kind"] == ACTION_DEMOTE_TENANT

    def test_remediation_list_missing_section(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main

        path = tmp_path / "agent-state.json"
        path.write_text(json.dumps({"components": {}}))
        assert main(
            ["remediation", "list", "--state", os.fspath(path)]
        ) == 1
        assert "no remediation section" in capsys.readouterr().err

    def test_explain_renders_remediation_block(self, tmp_path, capsys):
        from tpuslo.cli.sloctl import main

        engine, _ = _engine(tmp_path)
        engine.consider(_ctx(), now_s=0.0)
        for i in range(3):
            engine.tick(float(i + 1), lambda r: 0.0)
        prov = os.fspath(tmp_path / "provenance.jsonl")
        assert main(["explain", "inc-1", "--provenance", prov]) == 0
        out = capsys.readouterr().out
        assert "remediation (1 action(s))" in out
        assert "demote_tenant on tenant-a" in out
        assert "verdict=confirmed" in out


# ---------------------------------------------------------------------------
# the seeded sweep gate (fast path of the m5 gate)
# ---------------------------------------------------------------------------


class TestSweep:
    def test_full_sweep_passes(self, tmp_path):
        from tpuslo.remediation.sweep import run_remediation_sweep

        report = run_remediation_sweep(
            seed=1337, provenance_dir=os.fspath(tmp_path)
        )
        assert report.passed, report.failures
        names = {run.name for run in report.runs}
        # The acceptance criterion: >= 7 seeded fault scenarios.
        assert len(names) >= 7
        assert {
            "healthy_quiet",
            "low_confidence_held",
            "false_positive_rollback",
            "storm_rate_limited",
            "restart_mid_verify",
        } <= names

    def test_sweep_precision_evidence(self, tmp_path):
        from tpuslo.remediation.sweep import run_remediation_sweep

        report = run_remediation_sweep(
            seed=7, provenance_dir=os.fspath(tmp_path)
        )
        assert report.passed, report.failures
        by_name = {run.name: run for run in report.runs}
        assert by_name["healthy_quiet"].actions == []
        assert by_name["low_confidence_held"].actions == []
        assert by_name["low_confidence_held"].refusals.get(
            "low_confidence", 0
        ) > 0
        # The storm stayed inside the dampers.
        storm = by_name["storm_rate_limited"]
        assert len(storm.actions) == 3
        assert storm.max_in_flight <= 2

    def test_sweep_mid_kill_no_duplicates(self, tmp_path):
        from tpuslo.remediation.sweep import run_remediation_sweep

        report = run_remediation_sweep(
            seed=42, provenance_dir=os.fspath(tmp_path)
        )
        assert report.passed, report.failures
        restart = next(
            run for run in report.runs
            if run.name == "restart_mid_verify"
        )
        assert len(restart.actions) == 1
        assert restart.actions[0]["phase"] == PHASE_CONFIRMED

    def test_sweep_provenance_chains_on_disk(self, tmp_path):
        from tpuslo.remediation.sweep import run_remediation_sweep

        report = run_remediation_sweep(
            seed=1337, provenance_dir=os.fspath(tmp_path)
        )
        assert report.passed, report.failures
        chains = load_records(
            os.fspath(tmp_path / "demote_fast_burn.jsonl")
        )
        assert chains
        rec = next(iter(chains.values()))
        assert rec.remediation
        assert rec.remediation[0]["verdict"] == VERDICT_CONFIRMED


# ---------------------------------------------------------------------------
# agent e2e: the action loop inside the real synthetic cycle
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestAgentE2E:
    def test_agent_remediates_and_snapshots(self, tmp_path, capsys):
        """An error-heavy synthetic run under --remediate: the burn
        engine pages, the attribution fires, the engine acts, and the
        action history lands in the durable snapshot + provenance."""
        import threading

        from tpuslo.cli import agent as agent_cli

        state_dir = tmp_path / "state"
        out = tmp_path / "events.jsonl"
        argv = [
            "--scenario", "hbm_pressure",
            "--count", "60",
            "--interval-s", "0.01",
            "--metrics-port", "0",
            "--event-kind", "both",
            "--output", "jsonl",
            "--jsonl-path", os.fspath(out),
            "--webhook-url", "http://127.0.0.1:9/webhook",
            "--burn-engine",
            "--remediate",
            "--state-dir", os.fspath(state_dir),
            "--snapshot-interval-s", "0",
            "--trace",
            "--provenance-path",
            os.fspath(tmp_path / "provenance.jsonl"),
            "--max-overhead-pct", "1000",
        ]
        rc = {}
        thread = threading.Thread(
            target=lambda: rc.update(code=agent_cli.main(argv))
        )
        thread.start()
        thread.join(timeout=120)
        assert not thread.is_alive()

        snapshot = json.loads(
            (state_dir / "agent-state.json").read_text()
        )
        section = snapshot["components"].get("remediation")
        assert isinstance(section, dict)
        records = section.get("records") or []
        assert records, "remediation engine never acted"
        assert all(
            r["kind"] == ACTION_DEMOTE_TENANT for r in records
        )
        assert "shed_ownership" in snapshot["components"]
        # Every acted incident's provenance chain carries the block.
        chains = load_records(
            os.fspath(tmp_path / "provenance.jsonl")
        )
        acted = {r["incident_id"] for r in records}
        chained = {
            incident
            for incident, rec in chains.items()
            if rec.remediation
        }
        assert acted <= chained
