"""Prefix caching: shared-prompt KV reuse must be exact.

The cached-prefix path (prefill prefix once, clone the KV snapshot,
suffix-only chunked prefill via ``verify_chunk``) must produce the
same stream as prefilling ``prefix + prompt`` from scratch.
"""

import pytest
import jax

from tpuslo.models.llama import init_params, llama_tiny
from tpuslo.models.serve import ServeEngine

PREFIX = "system: you are a terse tpu slo assistant. answer briefly. "


def _engine(max_seq_len=256):
    cfg = llama_tiny(max_seq_len=max_seq_len)
    return ServeEngine(cfg=cfg, params=init_params(jax.random.PRNGKey(0), cfg))


def _tokens(engine, prompt, **kw):
    return [
        e.token_id
        for e in engine.generate(prompt, max_new_tokens=12, stop_at_eos=False, **kw)
    ]


def test_prefix_path_matches_full_prefill():
    engine = _engine()
    user = "what drives ttft?"
    full = _tokens(engine, PREFIX + user)
    cached = _tokens(engine, user, prefix=PREFIX)
    assert cached == full
    # Second request reuses the snapshot (same result, cache populated).
    assert PREFIX in engine._prefix_cache
    assert _tokens(engine, user, prefix=PREFIX) == full


def test_prefix_snapshot_survives_donation():
    """The suffix-prefill jit donates its cache input; generation must
    clone the snapshot, never consume it."""
    engine = _engine()
    a = _tokens(engine, "first request", prefix=PREFIX)
    b = _tokens(engine, "second request", prefix=PREFIX)
    assert a != b  # different suffixes, sanity
    assert _tokens(engine, "first request", prefix=PREFIX) == a


def test_prefix_with_empty_suffix():
    engine = _engine()
    assert _tokens(engine, "", prefix=PREFIX) == _tokens(engine, PREFIX)


def test_prefix_cache_fifo_eviction():
    engine = _engine()
    engine.prefix_cache_max = 2
    for i in range(3):
        engine.cache_prefix(f"prefix {i} ")
    assert "prefix 0 " not in engine._prefix_cache
    assert "prefix 2 " in engine._prefix_cache
    assert len(engine._prefix_cache) == 2


def test_prefix_respects_decode_budget():
    """Long prefix + oversize suffix must clamp the suffix and the
    token budget instead of overrunning the KV cache."""
    engine = _engine(max_seq_len=128)
    long_prefix = "p" * 100  # 101 ids with BOS
    events = list(
        engine.generate(
            "q" * 50, max_new_tokens=64, stop_at_eos=False, prefix=long_prefix
        )
    )
    entry = engine._prefix_cache[long_prefix]
    # suffix clamps to max_seq_len - 2 - prefix, budget to what's left
    room = engine.cfg.max_seq_len - 2 - len(entry.ids)
    assert room > 0
    assert 1 <= len(events) <= engine.cfg.max_seq_len - len(entry.ids) - room


def test_different_prefixes_do_not_collide():
    engine = _engine()
    p1, p2 = "alpha system prompt. ", "beta system prompt. "
    user = "same user question"
    out1 = _tokens(engine, user, prefix=p1)
    out2 = _tokens(engine, user, prefix=p2)
    assert out1 == _tokens(engine, p1 + user)
    assert out2 == _tokens(engine, p2 + user)


def test_prefix_near_capacity_exact():
    """Reviewer repro: prefix 101 ids in a 128-slot cache, 20-byte
    suffix pads to bucket 32 -> 101+32 > 128.  The write must clamp the
    bucket, not the start; the stream stays exact vs full prefill."""
    engine = _engine(max_seq_len=128)
    prefix = "p" * 100
    user = "q" * 20
    full = _tokens(engine, prefix + user)
    cached = _tokens(engine, user, prefix=prefix)
    assert cached == full


def test_prefix_cache_disabled_retention_still_serves():
    engine = _engine()
    engine.prefix_cache_max = 0
    out = _tokens(engine, "user q", prefix=PREFIX)
    assert engine._prefix_cache == {}
    assert out == _tokens(engine, PREFIX + "user q")


def _engine_with_buckets(buckets, max_seq_len=256):
    from tpuslo.models.serve import ServeEngine

    cfg = llama_tiny(max_seq_len=max_seq_len)
    return ServeEngine(
        cfg=cfg,
        params=init_params(jax.random.PRNGKey(0), cfg),
        prefill_buckets=buckets,
    )


def test_chunked_prefill_matches_single_shot():
    """A prompt longer than the largest bucket ingests chunked and must
    match an engine whose bucket covers it in one shot."""
    small = _engine_with_buckets((32, 64))
    big = _engine_with_buckets((32, 64, 128, 256))
    prompt = "x" * 150  # 151 ids: chunked as 64 + 64 + 32 on `small`
    out_small = [
        e.token_id
        for e in small.generate(prompt, max_new_tokens=10, stop_at_eos=False)
    ]
    out_big = [
        e.token_id
        for e in big.generate(prompt, max_new_tokens=10, stop_at_eos=False)
    ]
    assert out_small == out_big


def test_long_prompt_not_truncated_at_bucket():
    """Streaming ingestion accepts prompts up to KV capacity instead of
    truncating at the largest bucket."""
    engine = _engine_with_buckets((32, 64))
    prompt = "y" * 150  # 151 ids with BOS, largest bucket is 64
    logits, cache, total_len = engine.ingest_prompt(prompt)
    assert total_len == 151
    assert int(cache["length"]) == 151
    assert logits.shape[0] == 1
    # Capacity cap still applies.
    capped = engine.ingest_prompt("y" * 400)[2]
    assert capped == engine.cfg.max_seq_len - 2


def test_long_prefix_chunked_and_long_suffix():
    """Prefixes and suffixes longer than the largest bucket both ride
    the chunked path, exactly."""
    small = _engine_with_buckets((32, 64))
    big = _engine_with_buckets((32, 64, 128, 256))
    long_prefix = "p" * 100
    long_user = "q" * 80
    cached = [e.token_id for e in small.generate(
        long_user, max_new_tokens=8, stop_at_eos=False, prefix=long_prefix)]
    full = [e.token_id for e in big.generate(
        long_prefix + long_user, max_new_tokens=8, stop_at_eos=False)]
    assert cached == full


def test_batching_long_prompt_parity():
    from tpuslo.models.batching import ContinuousBatchingEngine

    cfg = llama_tiny(max_seq_len=256)
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ContinuousBatchingEngine(
        cfg=cfg, params=params, max_slots=2, prefill_buckets=(32, 64)
    )
    rid = engine.submit("z" * 150, max_new_tokens=8, stop_at_eos=False)
    results = engine.run()
    stream = _engine_with_buckets((32, 64))
    stream.params = params
    expect = [e.token_id for e in stream.generate(
        "z" * 150, max_new_tokens=8, stop_at_eos=False)]
    assert results[rid] == expect


def test_compile_telemetry_first_hit_only():
    """Steady-state chunks above the 100ms heuristic must not inflate
    the recompile-storm signal — only a shape's first hit records."""
    engine = _engine_with_buckets((32, 64))
    engine.compile_events.clear()
    engine._seen_shapes.clear()
    engine._record_compile("suffix", 64, 500.0)
    engine._record_compile("suffix", 64, 500.0)
    engine._record_compile("prefill", 64, 500.0)  # distinct program
    engine._record_compile("suffix", 32, 50.0)  # fast first hit: no event
    engine._record_compile("suffix", 32, 500.0)  # already seen
    assert engine.compile_events == [
        {"bucket": 64, "compile_ms": 500.0},
        {"bucket": 64, "compile_ms": 500.0},
    ]


def test_generate_batch_with_prefix_matches_streaming():
    """Batched prefix serving (tiled snapshot + vector-length suffix
    pass) must equal the per-request streaming path row for row."""
    import pytest

    engine = _engine()
    prompts = ["first question", "a second, longer question", "third"]
    batch_out = engine.generate_batch(
        prompts, max_new_tokens=10, stop_at_eos=False, prefix=PREFIX
    )
    for prompt, out in zip(prompts, batch_out):
        single = [
            e.token_id
            for e in engine.generate(
                prompt, max_new_tokens=10, stop_at_eos=False, prefix=PREFIX
            )
        ]
        assert out == single, prompt

    with pytest.raises(ValueError, match="non-empty"):
        engine.generate_batch(["ok", ""], prefix=PREFIX)


def test_generate_batch_long_prompts_chunked():
    """Batched single-shot prompts past the largest bucket ingest via
    lockstep chunked prefill and match the streaming engine row by row
    (generate_batch used to silently truncate at the bucket)."""
    cfg = llama_tiny(max_seq_len=256)
    engine = ServeEngine(
        cfg=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        prefill_buckets=(32, 64),
    )
    cap = engine.prefill_buckets[-1]
    prompts = [
        "a" * (cap + 37),          # crosses one chunk boundary
        "short prompt",            # ends in the head chunk
        "b" * (2 * cap + 5),       # crosses two chunk boundaries
    ]
    rows = engine.generate_batch(prompts, max_new_tokens=6, stop_at_eos=False)
    for prompt, row in zip(prompts, rows):
        single = [
            e.token_id
            for e in engine.generate(prompt, max_new_tokens=6, stop_at_eos=False)
        ]
        assert row == single, prompt[:20]


def test_generate_batch_long_prefix_long_suffix():
    """Prefix path with suffixes past the largest bucket: tiled prefix
    KV + chunked suffix appends must equal streaming prefix serving."""
    cfg = llama_tiny(max_seq_len=512)
    engine = ServeEngine(
        cfg=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        prefill_buckets=(32, 64),
    )
    cap = engine.prefill_buckets[-1]
    prefix = ("p" * (cap + 20))
    users = ["u" * (cap + 9), "v" * 11]
    rows = engine.generate_batch(
        users, max_new_tokens=6, stop_at_eos=False, prefix=prefix
    )
    for user, row in zip(users, rows):
        single = [
            e.token_id
            for e in engine.generate(
                user, max_new_tokens=6, stop_at_eos=False, prefix=prefix
            )
        ]
        assert row == single

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow
