"""Tensor-parallel paged serving: sharded pool matches single-device.

The composition real TPU serving needs: paged KV (concurrency at equal
HBM) x Megatron tensor parallelism (the pool's KV heads sharded over
the tp mesh, page tables host-side).  Parity contract: same tokens as
the unsharded paged engine, near-tie flips excepted — the same
discipline as serve.stream_parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpuslo.models.llama import init_params, llama_tiny
from tpuslo.models.paged_kv import PagedBatchingEngine, paged_pool_shardings
from tpuslo.models.serve import encode_bytes

pytestmark = pytest.mark.slow


def _tp_mesh(tp: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:tp]), ("tp",))


CFG = llama_tiny(max_seq_len=128)
PARAMS = init_params(jax.random.PRNGKey(0), CFG)


def _drive(engine, prompts, n=8):
    ids = [engine.submit(p, max_new_tokens=n, stop_at_eos=False)
           for p in prompts]
    results = engine.run()
    return [results[rid] for rid in ids]


def _assert_stream_close(plain_engine, prompt, got, expect):
    """Token-for-token, with a near-tie escape verified in logit space
    against the plain engine's own prefill (serve.stream_parity's
    rule)."""
    if got == expect:
        return
    for k, (g, e) in enumerate(zip(got, expect)):
        if g == e:
            continue
        forced = encode_bytes(prompt, CFG.max_seq_len - 2) + got[:k]
        logits, _ = plain_engine._ingest.prefill_ids(forced)
        top2 = jnp.sort(logits[0].astype(jnp.float32))[-2:]
        margin = float(top2[1] - top2[0])
        assert margin < 0.15, (prompt, k, g, e, margin)
        return


def test_tp_paged_matches_single_device():
    prompts = ["tp paged one", "a different second request", "third"]
    plain = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    sharded = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16,
        mesh=_tp_mesh(2),
    )
    out_plain = _drive(plain, prompts)
    out_shard = _drive(sharded, prompts)
    for prompt, got, expect in zip(prompts, out_shard, out_plain):
        assert len(got) == len(expect)
        _assert_stream_close(plain, prompt, got, expect)


def test_tp_paged_int8_compose():
    """paged + int8 KV + tensor parallel in one engine."""
    prompts = ["tp paged int8", "second int8 request"]
    plain = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16,
        kv_dtype="int8",
    )
    sharded = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16,
        kv_dtype="int8", mesh=_tp_mesh(2),
    )
    out_plain = _drive(plain, prompts, n=6)
    out_shard = _drive(sharded, prompts, n=6)
    for prompt, got, expect in zip(prompts, out_shard, out_plain):
        assert len(got) == len(expect)
        _assert_stream_close(plain, prompt, got, expect)


def test_tp_pool_is_actually_sharded():
    sharded = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16,
        mesh=_tp_mesh(2),
    )
    spec = sharded._cache["k"].sharding.spec
    assert tuple(spec) == (None, None, None, "tp", None)
    # Page table stays replicated — the free-list allocator is host-side.
    assert all(s is None for s in sharded._cache["page_table"].sharding.spec)


def test_pool_sharding_specs_int8():
    mesh = _tp_mesh(2)
    shardings = paged_pool_shardings(mesh, "int8")
    assert shardings["k"]["q"].spec == (None, None, None, "tp", None)
    assert shardings["k"]["s"].spec == (None, None, None, "tp")


def test_pallas_with_mesh_rejected():
    with pytest.raises(ValueError, match="single-device"):
        PagedBatchingEngine(
            cfg=CFG, params=PARAMS, max_slots=2, block_size=16,
            mesh=_tp_mesh(2), pallas_attention=True,
        )


def test_tp_paged_shared_prefix_parity():
    """Shared prefix blocks x tensor parallelism: the registry and
    page tables are host-side, the pool's KV heads sharded — sharing
    must be transparent to the tp path and keep single-device parity."""
    from tpuslo.models.serve import ServeEngine

    prefix = "system: shared preamble for tp. "  # BOS + 32 bytes: 2 full blocks
    suffixes = ["tp one", "tp two", "tp three"]
    sharded = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16,
        mesh=_tp_mesh(2),
    )
    ids = [
        sharded.submit(s, max_new_tokens=8, stop_at_eos=False, prefix=prefix)
        for s in suffixes
    ]
    results = sharded.run()
    assert sharded.prefix_reuse_hits >= 1
    assert sharded.stats()["shared_prefixes"] == 1
    plain = PagedBatchingEngine(
        cfg=CFG, params=PARAMS, max_slots=2, block_size=16
    )
    single = ServeEngine(cfg=CFG, params=PARAMS)
    for rid, s in zip(ids, suffixes):
        expect = [
            e.token_id
            for e in single.generate(
                s, max_new_tokens=8, stop_at_eos=False, prefix=prefix
            )
        ]
        got = results[rid]
        assert len(got) == len(expect)
        _assert_stream_close(plain, prefix + s, got, expect)
