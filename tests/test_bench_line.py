"""The driver captures ~2 KB of stdout; the bench line must fit.

Round 3 regression (VERDICT r03 weak #1): the single stdout JSON line
embedded the full multi-KB TPU capture, blew the driver's capture
window, and BENCH_r03.json lost the headline macro-F1 entirely.  These
tests lock in the compact-line contract: worst-case serialized line
<= bench.MAX_LINE_BYTES, with the headline metric AND the TPU-evidence
digest still present after the drop ladder runs.
"""

import json

import bench


def _robustness_fixture() -> dict:
    sweep = {"0.1": 0.9876, "0.25": 0.8765, "0.5": 0.7654, "1.0": 0.4567}
    return {
        "noise_macro_f1": dict(sweep),
        "calibrated_noise_macro_f1": dict(sweep),
        "calibrated_noise_micro_accuracy": dict(sweep),
        "calibrated_heldout": {
            "clean": 1.0,
            "lognormal": dict(sweep),
            "gamma": dict(sweep),
            "variant_profiles": dict(sweep),
        },
        "false_alarm_rate": 0.08,
        "abstain_rate": 0.04,
    }


def _attribution_fixture() -> dict:
    return {
        "macro_f1": 1.0,
        "micro_accuracy": 1.0,
        "partial_accuracy": 1.0,
        "coverage_accuracy": 1.0,
        "samples": 120,
        "attributions_per_sec": 812.3456,
    }


def _tpu_capture_fixture() -> dict:
    """A persisted capture at realistic (round-3 artifact) size."""
    capture = {
        "backend": "tpu",
        "device_kind": "TPU v5 lite",
        "platform": "tpu",
        "tpu_gen": "v5e",
        "peak_bf16_flops": 1.97e14,
        "model": "llama32_3b",
        "n_params": 3606752256,
        "flash_attention": True,
        "init_params_s": 62.33,
        "warmup_compile_ms": 4785.8,
        "ttft_ms": 78.43,
        "decode_tokens_per_sec": 84.64,
        "mfu_decode_b1": 0.0031,
        "prefix_cache": {
            "prefix_bytes": 2048,
            "ttft_full_ms": 98.22,
            "ttft_cached_prefix_ms": 78.0,
            "ttft_speedup": 1.26,
        },
        "long_prompt": {
            "prompt_ids": 1022,
            "first_ttft_ms": 4827.59,
            "ttft_ms": 118.57,
            "compile_events": 2,
        },
        "batch8_aggregate_tokens_per_sec": 266.39,
        "batch8_decode_tokens_per_sec": 268.96,
        "mfu_decode_b8": 0.00985,
        "prefill_bucket": 512,
        "prefill_tokens_per_sec": 16896.7,
        "mfu_prefill": 0.6187,
        "kv": {
            "int8_kv": {
                "batch8_decode_tokens_per_sec": 301.2,
                "mfu_decode_b8": 0.011,
                "kv_bytes_vs_bf16": 0.5312,
            },
            "paged": {
                "dense_slots": 4,
                "paged_slots": 8,
                "kv_hbm_bytes": 1073741824,
                "paged_pool_bytes": 1073741824,
                "dense_tokens_per_sec": 120.0,
                "paged_tokens_per_sec": 151.0,
                "throughput_ratio": 1.26,
                "queue_delay_p95_ratio": 2.4,
            },
        },
        "xprof_launch_spans": 18,
        "xprof_programs": 9,
        "device_time_signals": 10,
        "xla_launch_matches": 10,
        "xla_launch_join_rate": 0.5556,
        "xla_launch_join_rate_substantive": 0.9231,
        "xla_launch_unmatched": {
            "count": 8,
            "reasons": {"no_device_ops": 8},
            "examples": [f"helper_program_{i}" for i in range(6)],
        },
        "moe": {
            "model": "mixtral_2b6",
            "ttft_ms": 132.4,
            "decode_tokens_per_sec": 79.1,
        },
        "int8": {
            "model": "llama3_8b",
            "n_params": 8030261248,
            "ttft_ms": 82.55,
            "decode_tokens_per_sec": 69.37,
            "batch8_decode_tokens_per_sec": 202.7,
            "mfu_decode_b1": 0.00566,
            "mfu_decode_b8": 0.01652,
        },
        "elapsed_s": 205.7,
    }
    return {
        "provenance": {
            "captured_at": "2026-07-30T12:34:56+00:00",
            "capture_command": "python -m tpuslo.benchmark.serving_bench "
            "--platform auto",
            "git_sha": "abcdef0",
            "source": "live run (auto-persisted by serving_bench on a "
            "successful TPU capture)" + " padded-provenance" * 8,
            "note": "Last successful real-TPU capture; bench.py embeds "
            "this verbatim as serving_tpu_last_capture when the tunnel "
            "is down at driver capture time.",
        },
        "capture": capture,
    }


def _worst_case_serving() -> dict:
    """cpu_fallback + maximal error strings + full embedded capture —
    the exact shape that broke round 3, made strictly worse."""
    serving = {
        "backend": "cpu_fallback",
        "device_kind": "cpu",
        "model": "llama_tiny",
        "ttft_ms": 123.45,
        "decode_tokens_per_sec": 10.5,
        "batch8_decode_tokens_per_sec": 55.5,
        "mfu_prefill": None,
        "xla_launch_join_rate": 0.4,
        "xla_launch_join_rate_substantive": 0.9,
        "prefix_cache": {"ttft_speedup": 1.31, "prefix_bytes": 2048},
        "long_prompt": {"prompt_ids": 510, "ttft_ms": 99.9},
        "kv": {
            "int8_kv": {"batch8_decode_tokens_per_sec": 60.1},
            "paged": {
                "throughput_ratio": 1.22,
                "queue_delay_p95_ratio": 2.4,
            },
        },
        "int8": {"decode_tokens_per_sec": 40.0},
        "error": "x" * 400,
        "tpu_error": "t" * 300,
        "tpu_retry_error": "r" * 300,
        "chip_holder_candidates": ["python serving_bench " + "a" * 140] * 4,
        "serving_tpu_last_capture": _tpu_capture_fixture(),
    }
    return serving


def _build_compact(serving: dict) -> dict:
    _full, compact = bench.build_result(
        _attribution_fixture(),
        _robustness_fixture(),
        {"agent_cpu_pct_at_1hz": 0.246, "meets_3pct_gate": True},
        {"probe_events": 3600, "probe_events_per_sec": 123456.78},
        serving,
    )
    compact["full_report"] = bench.FULL_REPORT_RELPATH
    return compact


def test_worst_case_line_fits_driver_window():
    line = bench.compact_line(_build_compact(_worst_case_serving()))
    assert len(line.encode()) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    # The headline metric and TPU evidence must survive the drop ladder.
    assert parsed["metric"] == "attribution_macro_f1_tpu_faults"
    assert parsed["value"] == 1.0
    assert parsed["vs_baseline"] > 1.0
    assert parsed["tpu_evidence"]["git_sha"] == "abcdef0"
    assert parsed["tpu_evidence"]["ttft_ms"] == 78.43
    assert parsed["tpu_evidence"]["mfu_prefill"] == 0.6187
    assert parsed["full_report"] == bench.FULL_REPORT_RELPATH


def test_typical_line_keeps_all_digests():
    """Without pathological error strings nothing should be dropped."""
    serving = _worst_case_serving()
    for key in ("error", "tpu_error", "tpu_retry_error",
                "chip_holder_candidates"):
        serving.pop(key)
    line = bench.compact_line(_build_compact(serving))
    assert len(line.encode()) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    robustness = parsed["robustness"]
    assert robustness["calibrated_macro_f1"]["0.5"] == 0.7654
    assert robustness["heldout"]["variants_0.5"] == 0.7654
    assert robustness["false_alarm_rate"] == 0.08
    assert parsed["serving"]["paged_throughput_ratio"] == 1.22
    assert parsed["serving"]["int8_kv_b8_tokens_per_sec"] == 60.1
    assert parsed["overhead"]["meets_3pct_gate"] is True
    # The pipeline digest rounds rates to one decimal.
    assert parsed["pipeline"]["probe_events_per_sec"] == 123456.8


def test_truncation_is_word_boundary_with_marker():
    """BENCH_r05 regression: diagnostics were sliced mid-word
    ("accepts co", "successful TP").  Shortened strings must now end at
    a word boundary and carry a visible truncation marker."""
    diagnostic = (
        "tunnel relay down: no relay port (8082/8092/8102) accepts "
        "connections, so jax.devices() would hang; skipped the "
        "probe/backoff ladder"
    )
    for limit in (60, 120):
        out = bench._truncate_strings({"tpu_error": diagnostic}, limit)[
            "tpu_error"
        ]
        assert out.endswith("…")
        body = out[:-1]
        assert diagnostic.startswith(body)
        # The cut lands on a word boundary: the next source character
        # is the separator the truncation backed up to.
        assert diagnostic[len(body)] == " "
    # Under the limit: untouched, no marker.
    assert bench._truncate_strings({"x": "short"}, 60) == {"x": "short"}


def test_overbudget_line_keeps_diagnostics_whole_words():
    serving = _worst_case_serving()
    original_error = serving.get("tpu_error", "")
    line = bench.compact_line(_build_compact(serving))
    assert len(line.encode()) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    value = (parsed.get("serving") or {}).get("tpu_error")
    if isinstance(value, str) and value != original_error:
        # Shortened: must be a whole-word prefix with the marker.
        assert value.endswith("…")
        body = value[:-1]
        assert original_error.startswith(body)
        assert original_error[len(body)] == " "


def test_live_tpu_line_stamps_live_evidence():
    serving = {
        "backend": "tpu",
        "device_kind": "TPU v5 lite",
        "model": "llama32_3b",
        "ttft_ms": 78.43,
        "decode_tokens_per_sec": 84.64,
        "batch8_decode_tokens_per_sec": 268.96,
        "mfu_prefill": 0.6187,
        "mfu_decode_b8": 0.00985,
        "xla_launch_join_rate": 0.5556,
    }
    line = bench.compact_line(_build_compact(serving))
    assert len(line.encode()) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    assert parsed["tpu_evidence"]["source"] == "live run (this bench invocation)"
    assert parsed["serving"]["backend"] == "tpu"
    assert parsed["serving"]["mfu_prefill"] == 0.6187


def test_drop_ladder_handles_absurd_input():
    """Even a deliberately bloated compact dict must end <= cap with the
    essential keys intact (final-resort branch)."""
    compact = _build_compact(_worst_case_serving())
    compact["robustness"]["bloat"] = {str(i): "y" * 50 for i in range(40)}
    line = bench.compact_line(compact)
    assert len(line.encode()) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    assert parsed["value"] == 1.0
    assert "vs_baseline" in parsed


def test_full_report_roundtrip(tmp_path):
    full, _compact = bench.build_result(
        _attribution_fixture(),
        _robustness_fixture(),
        {"agent_cpu_pct_at_1hz": 0.246, "meets_3pct_gate": True},
        {"probe_events": 3600, "probe_events_per_sec": 123456.78},
        _worst_case_serving(),
    )
    path = tmp_path / "bench_full.json"
    rel = bench.write_full_report(full, path=str(path))
    # The return names the file actually written (a custom path here;
    # the default invocation returns the repo-relative artifact path).
    assert rel == str(path)
    payload = json.loads(path.read_text())
    assert payload["result"]["robustness"]["calibrated_heldout"]["clean"] == 1.0
    assert (
        payload["result"]["serving"]["serving_tpu_last_capture"]["capture"][
            "ttft_ms"
        ]
        == 78.43
    )
    assert payload["git_sha"]


def test_percentile_nearest_rank():
    from tpuslo.benchmark.serving_bench import _percentile

    values = [float(v) for v in range(1, 101)]
    assert _percentile(values, 0.50) == 50.0
    assert _percentile(values, 0.95) == 95.0
    assert _percentile([7.0], 0.95) == 7.0
    assert _percentile([], 0.95) == 0.0
    assert _percentile([3.0, 1.0, 2.0], 0.50) == 2.0


def test_batch_saturation_lane_structure():
    """Curve points carry tokens/s + KV fraction; the Pallas decision
    publishes both arithmetic terms (HBM fraction, attention-vs-weight
    MACs) so the build trigger is checkable."""
    import jax

    from tpuslo.benchmark.serving_bench import _batch_saturation_lane
    from tpuslo.models.llama import init_params, llama_tiny

    cfg = llama_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    out = _batch_saturation_lane(
        cfg, params, batches=(1, 2), block_size=32, timed_steps=2
    )
    assert [p["batch"] for p in out["curve"]] == [1, 2]
    for point in out["curve"]:
        assert point["tokens_per_sec"] > 0
        assert 0 <= point["kv_read_fraction"] <= 1
    assert 0 < out["flagship_kv_read_fraction_b2"] < 1
    assert out["flagship_attn_vs_weight_macs"]["2"] > (
        out["flagship_attn_vs_weight_macs"]["1"]
    )
    assert "decision_arithmetic" in out
    assert "XLA path at batch <= 8" in out["pallas_decode_attention_decision"]


def test_speculative_lane_structure():
    """The lane publishes the three per-round costs plus the derived
    verify speedup / breakeven acceptance / projected speedups, and the
    projection is monotone in acceptance."""
    import jax

    from tpuslo.benchmark.serving_bench import _speculative_lane
    from tpuslo.models.llama import init_params, llama_tiny

    cfg = llama_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    out = _speculative_lane(cfg, params, k=2, timed_steps=2)
    for key in ("t_decode_ms", "t_verify_ms", "t_draft_chunk_ms"):
        assert out[key] > 0
    assert out["verify_speedup"] > 0
    assert out["draft_n_params"] < sum(
        x.size for x in jax.tree.leaves(params)
    )
    speedups = [out["projected_speedup"][a] for a in ("0.6", "0.8", "1.0")]
    assert speedups == sorted(speedups)
    assert "identical" in out["exactness"]


def test_pallas_decision_measured_branches():
    """With measured *_pallas points (a real chip) the decision states
    the measured crossover; without them it keeps the interpret-mode
    status."""
    from tpuslo.benchmark.serving_bench import _pallas_decision

    unmeasured = [{"batch": 8, "tokens_per_sec": 100.0}]
    assert "awaiting a live chip" in _pallas_decision(unmeasured, 512)

    all_failed = [
        {"batch": 8, "tokens_per_sec": 100.0, "pallas_error": "lowering"},
    ]
    decision = _pallas_decision(all_failed, 512)
    assert "FAILED" in decision and "lowering" in decision

    partial_failure = [
        {"batch": 8, "tokens_per_sec": 100.0, "tokens_per_sec_pallas": 90.0},
        {"batch": 32, "tokens_per_sec": 80.0, "pallas_error": "oom"},
    ]
    decision = _pallas_decision(partial_failure, 512)
    assert "MEASURED" in decision
    assert "FAILED at batches [32]" in decision and "oom" in decision

    kernel_wins = [
        {"batch": 8, "tokens_per_sec": 100.0, "tokens_per_sec_pallas": 90.0},
        {"batch": 32, "tokens_per_sec": 80.0, "tokens_per_sec_pallas": 160.0},
    ]
    decision = _pallas_decision(kernel_wins, 512)
    assert "MEASURED" in decision and "[32]" in decision

    xla_wins = [
        {"batch": 8, "tokens_per_sec": 100.0, "tokens_per_sec_pallas": 90.0},
    ]
    decision = _pallas_decision(xla_wins, 512)
    assert "MEASURED" in decision and "XLA masked-pool path wins" in decision
