"""Crash-safe runtime state: StateStore, snapshot hooks, tear repair.

Covers the PR 4 tentpole's durable-state layer at unit level (the
subprocess kill/restart story lives in tests/test_crash_runtime.py):
atomic snapshot write/read with staleness and version guards, the
per-component export/restore registry, the dedup-digest parity
contract for a restarted ingest gate, breaker/limiter/watermark/skew
state portability, and the torn-line repairs for every append-mode
write path.
"""

from __future__ import annotations

import json
import os

from tpuslo.delivery.breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    CircuitBreaker,
)
from tpuslo.delivery.spool import DiskSpool
from tpuslo.ingest import GateConfig, TelemetryGate
from tpuslo.ingest.skew import ClockSkewEstimator
from tpuslo.ingest.watermark import Watermark
from tpuslo.runtime import (
    RESTORE_COLD,
    RESTORE_CORRUPT,
    RESTORE_RESTORED,
    RESTORE_STALE,
    RESTORE_VERSION,
    AgentRuntime,
    StateStore,
    repair_jsonl_tail,
)
from tpuslo.safety import RateLimiter


class FakeClock:
    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


# ---- StateStore --------------------------------------------------------


class TestStateStore:
    def test_save_load_roundtrip(self, tmp_path):
        store = StateStore(tmp_path / "state.json")
        assert store.save({"a": {"x": 1}, "b": [1, 2]})
        outcome, components, age = store.load()
        assert outcome == RESTORE_RESTORED
        assert components == {"a": {"x": 1}, "b": [1, 2]}
        assert age >= 0.0

    def test_missing_snapshot_is_cold(self, tmp_path):
        store = StateStore(tmp_path / "state.json")
        outcome, components, _ = store.load()
        assert outcome == RESTORE_COLD
        assert components == {}

    def test_corrupt_snapshot(self, tmp_path):
        path = tmp_path / "state.json"
        path.write_text('{"schema_version": 1, "saved_at": 12')
        outcome, components, _ = StateStore(path).load()
        assert outcome == RESTORE_CORRUPT
        assert components == {}

    def test_version_mismatch(self, tmp_path):
        path = tmp_path / "state.json"
        path.write_text(
            json.dumps(
                {"schema_version": 999, "saved_at": 1.0, "components": {}}
            )
        )
        outcome, _, _ = StateStore(path).load()
        assert outcome == RESTORE_VERSION

    def test_stale_snapshot(self, tmp_path):
        clock = FakeClock()
        store = StateStore(
            tmp_path / "state.json", max_age_s=60.0, walltime=clock
        )
        store.save({"a": 1})
        clock.advance(61.0)
        outcome, components, age = store.load()
        assert outcome == RESTORE_STALE
        assert components == {}
        assert age > 60.0

    def test_atomic_write_leaves_no_temp_files(self, tmp_path):
        store = StateStore(tmp_path / "state.json")
        for i in range(5):
            store.save({"i": i})
        leftovers = [
            name for name in os.listdir(tmp_path) if name != "state.json"
        ]
        assert leftovers == []

    def test_maybe_save_respects_interval(self, tmp_path):
        clock = FakeClock()
        store = StateStore(
            tmp_path / "state.json", interval_s=10.0, walltime=clock
        )
        assert store.maybe_save(lambda: {"n": 1})
        assert not store.maybe_save(lambda: {"n": 2})
        clock.advance(10.0)
        assert store.maybe_save(lambda: {"n": 3})
        assert store.load()[1] == {"n": 3}

    def test_interval_zero_saves_every_call(self, tmp_path):
        store = StateStore(tmp_path / "state.json", interval_s=0.0)
        assert store.maybe_save(lambda: {"n": 1})
        assert store.maybe_save(lambda: {"n": 2})
        assert store.saves == 2

    def test_unserializable_state_is_counted_not_raised(self, tmp_path):
        store = StateStore(tmp_path / "state.json")
        assert not store.save({"bad": object()})
        assert store.save_errors == 1


# ---- AgentRuntime ------------------------------------------------------


class TestAgentRuntime:
    def test_export_restore_roundtrip(self, tmp_path):
        store = StateStore(tmp_path / "state.json")
        runtime = AgentRuntime(store)
        state = {"value": 7}
        runtime.register(
            "comp", lambda: dict(state), lambda s: state.update(s)
        )
        runtime.snapshot_now()

        state2 = {"value": 0}
        runtime2 = AgentRuntime(StateStore(tmp_path / "state.json"))
        runtime2.register(
            "comp", lambda: dict(state2), lambda s: state2.update(s)
        )
        assert runtime2.restore() == RESTORE_RESTORED
        assert state2 == {"value": 7}
        assert runtime2.restored_components == ["comp"]

    def test_late_registration_applies_pending_state(self, tmp_path):
        store = StateStore(tmp_path / "state.json")
        AgentRuntime(store).store.save({"late": {"v": 3}})

        runtime = AgentRuntime(StateStore(tmp_path / "state.json"))
        assert runtime.restore() == RESTORE_RESTORED
        assert runtime.restored_components == []
        seen = {}
        runtime.register("late", lambda: seen, lambda s: seen.update(s))
        assert seen == {"v": 3}
        assert runtime.restored_components == ["late"]

    def test_restore_isolates_component_failures(self, tmp_path):
        StateStore(tmp_path / "state.json").save(
            {"good": {"v": 1}, "bad": {"v": 2}}
        )
        runtime = AgentRuntime(StateStore(tmp_path / "state.json"))
        good = {}

        def explode(state):
            raise RuntimeError("boom")

        runtime.register("bad", lambda: {}, explode)
        runtime.register("good", lambda: good, lambda s: good.update(s))
        assert runtime.restore() == RESTORE_RESTORED
        assert good == {"v": 1}
        assert runtime.restore_errors == ["bad"]

    def test_cold_start_flag_skips_restore(self, tmp_path):
        StateStore(tmp_path / "state.json").save({"c": {"v": 1}})
        runtime = AgentRuntime(StateStore(tmp_path / "state.json"))
        target = {}
        runtime.register("c", lambda: target, lambda s: target.update(s))
        assert runtime.restore(cold_start=True) == "forced_cold"
        assert target == {}

    def test_disabled_runtime_is_cold(self):
        runtime = AgentRuntime(None)
        assert runtime.restore() == RESTORE_COLD
        assert not runtime.maybe_snapshot()
        assert not runtime.snapshot_now()

    def test_export_isolates_exporter_failures(self, tmp_path):
        runtime = AgentRuntime(StateStore(tmp_path / "state.json"))
        runtime.register("ok", lambda: {"v": 1}, lambda s: None)

        def explode():
            raise RuntimeError("export boom")

        runtime.register("broken", explode, lambda s: None)
        assert runtime.snapshot_now()
        _, components, _ = runtime.store.load()
        assert components == {"ok": {"v": 1}}


# ---- component snapshot hooks -----------------------------------------


class TestRateLimiterState:
    def test_budget_survives_restart(self):
        clock = FakeClock()
        limiter = RateLimiter(10, burst=10, clock=clock)
        for _ in range(7):
            assert limiter.allow()
        exported = limiter.export_state()

        limiter2 = RateLimiter(10, burst=10, clock=clock)
        limiter2.restore_state(exported)
        assert limiter2.tokens == limiter.tokens

    def test_restore_clamps_to_capacity(self):
        limiter = RateLimiter(10, burst=10, clock=FakeClock())
        limiter.restore_state({"tokens": 99999.0})
        assert limiter.tokens == 10.0
        limiter.restore_state({"tokens": -5})
        assert limiter.tokens == 0.0
        limiter.restore_state({"tokens": "junk"})  # ignored, no raise
        assert limiter.tokens == 0.0


class TestBreakerState:
    def test_open_breaker_keeps_remaining_cooldown(self):
        clock = FakeClock()
        breaker = CircuitBreaker(
            failure_threshold=2, open_duration_s=10.0, clock=clock
        )
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.state == STATE_OPEN
        clock.advance(4.0)
        exported = breaker.export_state()
        assert 5.9 <= exported["open_remaining_s"] <= 6.0

        clock2 = FakeClock(5000.0)
        restored = CircuitBreaker(
            failure_threshold=2, open_duration_s=10.0, clock=clock2
        )
        restored.restore_state(exported)
        assert restored.state == STATE_OPEN
        assert not restored.allow()
        clock2.advance(6.1)
        assert restored.state == STATE_HALF_OPEN
        assert restored.allow()  # half-open probe slot

    def test_closed_breaker_restores_closed(self):
        breaker = CircuitBreaker(clock=FakeClock())
        exported = breaker.export_state()
        restored = CircuitBreaker(clock=FakeClock())
        restored.record_failure()
        restored.restore_state(exported)
        assert restored.state == STATE_CLOSED
        assert restored.allow()

    def test_garbage_state_is_ignored(self):
        breaker = CircuitBreaker(clock=FakeClock())
        breaker.restore_state({"state": "bogus"})
        assert breaker.state == STATE_CLOSED


class TestWatermarkState:
    def test_restore_resumes_head(self):
        wm = Watermark(lateness_ns=1000)
        wm.admit(5_000)
        exported = wm.export_state()

        wm2 = Watermark(lateness_ns=1000)
        wm2.restore_state(exported)
        assert wm2.watermark_ns == 4_000
        assert wm2.admit(4_500)
        assert not wm2.admit(100)  # behind the restored watermark: late

    def test_restore_never_moves_backwards(self):
        wm = Watermark(lateness_ns=1000)
        wm.admit(9_000)
        wm.restore_state({"max_ts": 5_000})
        assert wm.watermark_ns == 8_000


class TestSkewState:
    @staticmethod
    def _collective(node: str, host: int, launch: int, ts: int) -> dict:
        return {
            "ts_unix_nano": ts,
            "signal": "ici_collective_latency_ms",
            "node": node,
            "tpu": {
                "slice_id": "slice-a",
                "program_id": "prog",
                "host_index": host,
                "launch_id": launch,
            },
        }

    def test_offsets_survive_restart(self):
        est = ClockSkewEstimator(min_samples=3)
        for launch in range(4):
            base = 1_000_000_000 + launch * 10_000_000
            est.observe(self._collective("node-0", 0, launch, base))
            est.observe(
                self._collective("node-1", 1, launch, base + 250_000)
            )
        assert est.offset_ns("node-1") == 250_000

        est2 = ClockSkewEstimator(min_samples=3)
        est2.restore_state(est.export_state())
        assert est2.offset_ns("node-1") == 250_000
        assert est2.coordinator_node == "node-0"
        # Live evidence keeps accumulating on top of the restored window.
        base = 2_000_000_000
        est2.observe(self._collective("node-0", 0, 99, base))
        est2.observe(self._collective("node-1", 1, 99, base + 250_000))
        assert est2.offset_ns("node-1") == 250_000


# ---- gate dedup-digest parity (satellite: restart vs uninterrupted) ----


def _probe(i: int, ts: int) -> dict:
    return {
        "ts_unix_nano": ts,
        "signal": "dns_latency_ms",
        "node": "node-a",
        "namespace": "llm",
        "pod": f"pod-{i % 3}",
        "container": "svc",
        "pid": 10 + i,
        "tid": 10 + i,
        "value": float(i),
        "unit": "ms",
        "status": "ok",
    }


class TestGateDedupDigestParity:
    def test_restarted_gate_rejects_pre_crash_window(self):
        base = 1_700_000_000_000_000_000
        first = [_probe(i, base + i * 1_000_000) for i in range(40)]
        second = [_probe(i, base + (40 + i) * 1_000_000) for i in range(40)]
        # The replayed tail: exact duplicates of the last pre-crash
        # events (spool replay / exporter retransmit across the crash).
        replayed = [dict(e) for e in first[-10:]]

        # Uninterrupted reference run.
        ref = TelemetryGate(GateConfig(skew_correction=False))
        for event in first + replayed + second:
            ref.admit(event)

        # Crash between `first` and the replay: state crosses via
        # export/restore only.
        gate1 = TelemetryGate(GateConfig(skew_correction=False))
        for event in first:
            gate1.admit(event)
        exported = gate1.export_state()

        gate2 = TelemetryGate(GateConfig(skew_correction=False))
        gate2.restore_state(exported)
        outcomes = [gate2.admit(dict(e))[0] for e in replayed]
        assert outcomes == ["duplicate"] * len(replayed)
        for event in second:
            outcome, _ = gate2.admit(event)
            assert outcome == "admitted"

        # Parity: the split run admits and deduplicates exactly what
        # the uninterrupted run did.
        assert gate1.admitted + gate2.admitted == ref.admitted
        assert gate1.duplicates + gate2.duplicates == ref.duplicates

    def test_restored_watermark_flags_stale_replays_late(self):
        base = 1_700_000_000_000_000_000
        gate1 = TelemetryGate(
            GateConfig(skew_correction=False, watermark_lateness_ms=1)
        )
        for i in range(10):
            gate1.admit(_probe(i, base + i * 50_000_000))
        exported = gate1.export_state()

        gate2 = TelemetryGate(
            GateConfig(skew_correction=False, watermark_lateness_ms=1)
        )
        gate2.restore_state(exported)
        # A *new* event carrying a pre-crash-era timestamp (not an
        # exact duplicate) must be late, not silently in-order.
        stale = _probe(99, base)
        outcome, _ = gate2.admit(stale)
        assert outcome == "late"

    def test_restored_digests_age_out_after_one_window(self):
        """The inherited digest set (and its per-event digest cost)
        drops once a full window of live identities has accumulated —
        matching the bounded-LRU aging an uninterrupted gate applies."""
        base = 1_700_000_000_000_000_000
        gate1 = TelemetryGate(
            GateConfig(skew_correction=False, dedup_window=8)
        )
        for i in range(8):
            gate1.admit(_probe(i, base + i * 1_000_000))
        exported = gate1.export_state()

        gate2 = TelemetryGate(
            GateConfig(skew_correction=False, dedup_window=8)
        )
        gate2.restore_state(exported)
        assert gate2._restored_digests
        for i in range(8):  # one full window of fresh admissions
            gate2.admit(_probe(100 + i, base + (100 + i) * 1_000_000))
        assert not gate2._restored_digests
        # Pre-crash identities older than the window now re-admit,
        # exactly as the LRU would have aged them in one process.
        outcome, _ = gate2.admit(_probe(0, base))
        assert outcome in ("admitted", "late")

    def test_digest_export_is_bounded_by_window(self):
        gate = TelemetryGate(
            GateConfig(skew_correction=False, dedup_window=16)
        )
        base = 1_700_000_000_000_000_000
        for i in range(100):
            gate.admit(_probe(i, base + i * 1_000_000))
        exported = gate.export_state()
        assert len(exported["dedup_digests"]) <= 16


# ---- torn-line repair (satellite: kill-mid-write atomicity audit) ------


class TestTornLineRepair:
    def test_torn_tail_is_truncated_once(self, tmp_path):
        path = tmp_path / "events.jsonl"
        path.write_text('{"a": 1}\n{"b": 2}\n{"torn": ')
        trimmed = repair_jsonl_tail(path)
        assert trimmed == len('{"torn": ')
        assert path.read_text() == '{"a": 1}\n{"b": 2}\n'
        assert repair_jsonl_tail(path) == 0  # idempotent

    def test_clean_missing_and_empty_files(self, tmp_path):
        clean = tmp_path / "clean.jsonl"
        clean.write_text('{"a": 1}\n')
        assert repair_jsonl_tail(clean) == 0
        assert repair_jsonl_tail(tmp_path / "missing.jsonl") == 0
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert repair_jsonl_tail(empty) == 0

    def test_torn_single_line_file_truncates_to_empty(self, tmp_path):
        path = tmp_path / "one.jsonl"
        path.write_text('{"only": ')
        assert repair_jsonl_tail(path) == len('{"only": ')
        assert path.read_text() == ""

    def test_writers_repair_on_append_reopen(self, tmp_path):
        from tpuslo.cli.common import EventWriters

        path = tmp_path / "out.jsonl"
        path.write_text('{"kind": "probe", "ok": true}\n{"kind": "pr')
        writers = EventWriters(output="jsonl", jsonl_path=str(path))
        try:
            assert writers.jsonl_repaired_bytes == len('{"kind": "pr')
        finally:
            writers.close()
        for line in path.read_text().splitlines():
            json.loads(line)  # every surviving line parses


class TestSpoolTornLines:
    """Kill-mid-write on the spool: torn records are skipped exactly once."""

    def _spool_with_tear(self, tmp_path) -> DiskSpool:
        spool = DiskSpool(tmp_path / "spool", segment_max_bytes=1 << 20)
        for i in range(5):
            spool.append({"seq": i})
        spool.seal()
        segment = sorted((tmp_path / "spool").glob("seg-*.jsonl"))[0]
        raw = segment.read_bytes()
        segment.write_bytes(raw[: len(raw) - 9])  # tear the final record
        return spool

    def test_torn_record_never_replayed(self, tmp_path):
        spool = self._spool_with_tear(tmp_path)
        replayed: list[dict] = []
        spool.drain(replayed.append)
        assert [r["seq"] for r in replayed] == [0, 1, 2, 3]

    def test_torn_record_never_seen_twice(self, tmp_path):
        spool = self._spool_with_tear(tmp_path)
        first: list[dict] = []
        spool.drain(first.append)
        second: list[dict] = []
        spool.drain(second.append)
        assert len(first) == 4
        assert second == []  # drained segments are gone, tear included

    def test_reopened_spool_skips_tear_and_appends_cleanly(self, tmp_path):
        self._spool_with_tear(tmp_path).close()
        # Next incarnation adopts the directory; the tear stays isolated
        # in its own (sealed) segment and new appends open a new one.
        spool2 = DiskSpool(tmp_path / "spool", segment_max_bytes=1 << 20)
        spool2.append({"seq": 100})
        spool2.seal()
        replayed: list[dict] = []
        spool2.drain(replayed.append)
        assert [r["seq"] for r in replayed] == [0, 1, 2, 3, 100]
