"""Native runtime tests: ring transport, decode, aggregation, lifecycle.

These exercise the C++ consumer stack through the same userspace-ring
transport the BCC fallback and injectors use — the privilege-free seam
that mirrors the reference's hand-packed ringbuf decode tests
(pkg/collector/ringbuf_test.go), but through the real native code.
"""

from __future__ import annotations

import json

import pytest

from tpuslo.collector import native

pytestmark = pytest.mark.skipif(
    not native.runtime_available(), reason="native runtime not buildable"
)


@pytest.fixture()
def ring(tmp_path):
    from tpuslo.collector.ringbuf import RingBufConsumer, RingWriter

    path = str(tmp_path / "ring.buf")
    writer = RingWriter(path, capacity=1 << 16)
    consumer = RingBufConsumer(steal_window_ms=1000, ncpu=1)
    consumer.add_userspace_ring(path)
    yield writer, consumer
    writer.close()
    consumer.close()


def test_sizes_agree():
    lib = native.load_runtime()
    assert lib.tpuslo_event_size() == 72
    import ctypes

    assert ctypes.sizeof(native.WireEvent) == 72


def test_latency_event_ns_to_ms(ring):
    writer, consumer = ring
    assert writer.write_event(
        signal=native.SIG_DNS_LATENCY,
        value=2_500_000,  # 2.5ms in ns
        ts_ns=1000,
        pid=42,
        tid=43,
        comm=b"resolver",
    )
    samples = consumer.poll()
    assert len(samples) == 1
    s = samples[0]
    assert s.signal == "dns_latency_ms"
    assert s.value == pytest.approx(2.5)
    assert s.unit == "ms"
    assert (s.pid, s.tid) == (42, 43)
    assert s.comm == "resolver"


def test_conn_tuple_formatting(ring):
    writer, consumer = ring
    import socket
    import struct

    saddr = struct.unpack("<I", socket.inet_aton("10.0.0.1"))[0]
    daddr = struct.unpack("<I", socket.inet_aton("10.0.0.53"))[0]
    writer.write_event(
        signal=native.SIG_DNS_LATENCY,
        value=1_000_000,
        saddr4=saddr,
        daddr4=daddr,
        sport=42424,
        dport=53,
        flags=native.F_CONN,
    )
    (s,) = consumer.poll()
    assert s.conn_tuple == "10.0.0.1:42424->10.0.0.53:53"


def test_connect_error_becomes_counter(ring):
    writer, consumer = ring
    writer.write_event(
        signal=native.SIG_CONNECT_LATENCY,
        value=5_000_000,
        err=-111,  # ECONNREFUSED
        flags=native.F_ERROR,
    )
    (s,) = consumer.poll()
    assert s.signal == "connect_errors_total"
    assert s.value == 1.0
    assert s.unit == "count"
    assert s.err == -111


def test_tls_failure_becomes_counter(ring):
    writer, consumer = ring
    writer.write_event(signal=native.SIG_TLS_HANDSHAKE, value=900_000, err=1)
    (s,) = consumer.poll()
    assert s.signal == "tls_handshake_fail_total"
    assert s.value == 1.0


def test_cpu_steal_window_aggregation(ring):
    writer, consumer = ring
    # 100ms of involuntary wait spread over a 1s window on 1 CPU -> 10%.
    base = 1_000_000_000
    for i in range(10):
        writer.write_event(
            signal=native.SIG_CPU_STEAL,
            value=10_000_000,  # 10ms each
            ts_ns=base + i * 100_000_000,
        )
    # Window-closing event (past 1s since first).
    writer.write_event(
        signal=native.SIG_CPU_STEAL, value=0, ts_ns=base + 1_100_000_000
    )
    samples = [s for s in consumer.poll() if s.signal == "cpu_steal_pct"]
    assert len(samples) == 1
    assert samples[0].value == pytest.approx(100.0 / 1100.0 * 100, rel=0.01)
    assert samples[0].unit == "pct"


def test_hbm_utilization_basis_points(ring):
    writer, consumer = ring
    writer.write_event(
        signal=native.SIG_HBM_UTILIZATION, value=8725, flags=native.F_TPU
    )
    (s,) = consumer.poll()
    assert s.signal == "hbm_utilization_pct"
    assert s.value == pytest.approx(87.25)
    assert s.is_tpu


def test_tpu_collective_carries_launch_id(ring):
    writer, consumer = ring
    writer.write_event(
        signal=native.SIG_ICI_COLLECTIVE,
        value=3_000_000,
        aux=777,
        flags=native.F_TPU,
    )
    (s,) = consumer.poll()
    assert s.signal == "ici_collective_latency_ms"
    assert s.aux == 777


def test_ring_wraparound_many_events(ring):
    writer, consumer = ring
    total = 0
    for round_ in range(5):
        for i in range(300):
            assert writer.write_event(
                signal=native.SIG_RUNQ_DELAY, value=1_000_000, ts_ns=i
            )
            total += 1
        drained = 0
        while True:
            batch = consumer.poll()
            if not batch:
                break
            drained += len(batch)
        assert drained == 300
    assert writer.dropped == 0
    assert consumer.decode_errors == 0


def test_ring_backpressure_drops_newest(tmp_path):
    from tpuslo.collector.ringbuf import RingWriter

    writer = RingWriter(str(tmp_path / "tiny.buf"), capacity=4096)
    wrote = 0
    for _ in range(200):
        if writer.write_event(signal=native.SIG_RUNQ_DELAY, value=1):
            wrote += 1
    assert wrote < 200
    assert writer.dropped == 200 - wrote
    writer.close()


def test_unknown_signal_counts_decode_error(ring):
    writer, consumer = ring
    writer.write_event(signal=200, value=1)
    assert consumer.poll() == []
    assert consumer.decode_errors == 1


def test_to_probe_event_bridges_schema(ring):
    from tpuslo.cli.common import validate_probe
    from tpuslo.collector.ringbuf import to_probe_event
    from tpuslo.signals.metadata import Metadata

    writer, consumer = ring
    writer.write_event(
        signal=native.SIG_XLA_COMPILE,
        value=45_000_000,
        ts_ns=1_700_000_000_000_000_000,
        pid=7,
        aux=12345,
        flags=native.F_TPU,
    )
    (s,) = consumer.poll()
    meta = Metadata(
        node="tpu-vm-0", namespace="llm", pod="serve-0", container="serve",
        pid=1, tid=1, tpu_chip="accel0", slice_id="slice-a", host_index=0,
        xla_program_id="prog-1",
    )
    event = to_probe_event(s, meta)
    assert event is not None
    assert event.signal == "xla_compile_ms"
    assert event.value == pytest.approx(45.0)
    assert event.pid == 7  # sample pid wins over template
    assert event.tpu is not None and event.tpu.chip == "accel0"
    assert validate_probe(event)


def test_hello_heartbeat_roundtrip(tmp_path):
    from tpuslo.collector.hello_tracer import HelloTracer
    from tpuslo.collector.ringbuf import RingBufConsumer

    path = str(tmp_path / "hello.buf")
    tracer = HelloTracer(path, interval_s=60.0)
    consumer = RingBufConsumer()
    try:
        assert tracer.beat_once()
        assert tracer.beat_once()
        consumer.add_userspace_ring(path)
        samples = consumer.poll()
        assert [s.value for s in samples] == [1.0, 2.0]
        assert all(s.signal == "hello_heartbeat_total" for s in samples)
    finally:
        tracer.stop()
        consumer.close()


def test_bcc_fallback_forwards_measured_samples(tmp_path):
    from tpuslo.collector.bcc_fallback import BCCFallback
    from tpuslo.collector.ringbuf import RingBufConsumer

    path = str(tmp_path / "bcc.buf")
    fallback = BCCFallback(path)
    consumer = RingBufConsumer()
    consumer.add_userspace_ring(path)
    try:
        # Generous timeout: the default 10s can trip under a fully
        # loaded CI host (subprocess start + the tracer's sampling
        # window), flaking this test without any real defect.
        forwarded = fallback.run_once(timeout_s=60.0)
        assert forwarded == 2  # live dns probe + live tcp tracer
        signals = {s.signal for s in consumer.poll()}
        assert signals == {"dns_latency_ms", "tcp_retransmits_total"}
    finally:
        fallback.close()
        consumer.close()


def _load_tcp_tracer():
    import importlib.util
    from pathlib import Path

    path = (
        Path(__file__).resolve().parent.parent
        / "ebpf"
        / "bcc-fallback"
        / "tcp_retransmits.py"
    )
    spec = importlib.util.spec_from_file_location("tcp_retransmits", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTCPRetransmitTracer:
    """The bcc_degraded TCP tracer measures, it doesn't stub."""

    def test_parses_retrans_segs_from_snmp_fixture(self, tmp_path):
        mod = _load_tcp_tracer()
        snmp = tmp_path / "snmp"
        snmp.write_text(
            "Ip: Forwarding DefaultTTL\nIp: 1 64\n"
            "Tcp: ActiveOpens RetransSegs OutRsts\n"
            "Tcp: 10 37 2\n"
        )
        assert mod.read_retrans_segs(str(snmp)) == 37

    def test_reads_live_kernel_counter(self):
        mod = _load_tcp_tracer()
        value = mod.read_retrans_segs()
        assert value >= 0  # real counter, monotone since boot

    def test_procfs_mode_emits_interval_deltas(self, capsys, monkeypatch):
        mod = _load_tcp_tracer()
        readings = iter([100, 103, 103, 110])
        monkeypatch.setattr(mod, "read_retrans_segs", lambda *a: next(readings))
        monkeypatch.setattr(mod.time, "sleep", lambda s: None)
        assert mod.run_procfs(0.5, 3) == 0
        lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert [s["value"] for s in lines] == [3, 0, 7]
        assert all(s["signal"] == "tcp_retransmits_total" for s in lines)
        assert all(s["source"] == "procfs_delta" for s in lines)

    def test_auto_mode_falls_back_without_bcc(self, tmp_path):
        import subprocess
        import sys
        from pathlib import Path

        script = (
            Path(__file__).resolve().parent.parent
            / "ebpf"
            / "bcc-fallback"
            / "tcp_retransmits.py"
        )
        proc = subprocess.run(
            [sys.executable, str(script), "--interval-s", "0.05"],
            capture_output=True,
            text=True,
            timeout=30,
        )
        assert proc.returncode == 0
        sample = json.loads(proc.stdout.strip().splitlines()[-1])
        # bcc on a BCC host, procfs everywhere else — never the stub.
        assert sample["source"] in ("bcc_tracepoint", "procfs_delta")
        assert sample["value"] >= 0


def test_concurrent_producer_consumer_spsc(tmp_path):
    """True SPSC concurrency: producer and consumer threads race on one
    ring; every event must arrive exactly once, uncorrupted (the
    acquire/release contract in native/ring.cc)."""
    import contextlib
    import threading

    from tpuslo.collector.ringbuf import RingBufConsumer, RingWriter

    path = str(tmp_path / "spsc.buf")
    N = 5000
    produced = []
    stop = threading.Event()
    got = []

    with contextlib.closing(RingWriter(path, capacity=1 << 14)) as writer, \
            contextlib.closing(RingBufConsumer(steal_window_ms=1000, ncpu=1)) as consumer:
        consumer.add_userspace_ring(path)

        def produce():
            for i in range(N):
                # Spin on backpressure: the consumer drains concurrently.
                while not writer.write_event(
                    signal=native.SIG_RUNQ_DELAY, value=1_000_000 + i, ts_ns=i
                ):
                    if stop.is_set():
                        return
                produced.append(i)

        t = threading.Thread(target=produce)
        t.start()
        try:
            while True:
                # Snapshot aliveness BEFORE polling: events written
                # between an empty poll and the thread's exit must get
                # one more drain pass.
                alive = t.is_alive()
                batch = consumer.poll()
                got.extend(batch)
                if not alive and not batch:
                    break
        finally:
            stop.set()
            t.join(timeout=10)

    assert len(produced) == N
    assert len(got) == N
    assert consumer.decode_errors == 0
    # Order and payload preserved (SPSC is FIFO).
    values = [e.value for e in got]
    assert values == sorted(values)


def test_multi_ring_fanin_concurrent(tmp_path):
    """N producers, each with its own SPSC ring, one consumer polling
    all — the BCC-fallback/HBM-sampler/hello-tracer fan-in shape."""
    import contextlib
    import threading

    from tpuslo.collector.ringbuf import RingBufConsumer, RingWriter

    n_rings, per_ring = 4, 1000
    stop = threading.Event()
    got = []

    with contextlib.ExitStack() as stack:
        consumer = stack.enter_context(
            contextlib.closing(RingBufConsumer(steal_window_ms=1000, ncpu=1))
        )
        writers = []
        for r in range(n_rings):
            path = str(tmp_path / f"ring{r}.buf")
            writers.append(
                stack.enter_context(
                    contextlib.closing(RingWriter(path, capacity=1 << 15))
                )
            )
            consumer.add_userspace_ring(path)

        def produce(w, base):
            for i in range(per_ring):
                while not w.write_event(
                    signal=native.SIG_RUNQ_DELAY, value=base + i, ts_ns=i
                ):
                    if stop.is_set():
                        return

        threads = [
            threading.Thread(target=produce, args=(w, 1_000_000 * (r + 1)))
            for r, w in enumerate(writers)
        ]
        for t in threads:
            t.start()
        try:
            while True:
                alive = any(t.is_alive() for t in threads)
                batch = consumer.poll()
                got.extend(batch)
                if not alive and not batch:
                    break
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)

    assert len(got) == n_rings * per_ring
    assert consumer.decode_errors == 0
    # Per-ring FIFO holds even under interleaved fan-in.  The decoder
    # converts latency ns -> ms, so ring r's values land in [r+1, r+2).
    by_ring = {}
    for e in got:
        by_ring.setdefault(int(e.value), []).append(e.value)
    assert sorted(by_ring) == [1, 2, 3, 4]
    for values in by_ring.values():
        assert values == sorted(values)
        assert len(values) == per_ring


def _load_dns_tracer():
    import importlib.util
    from pathlib import Path

    path = (
        Path(__file__).resolve().parent.parent
        / "ebpf"
        / "bcc-fallback"
        / "dns_latency.py"
    )
    spec = importlib.util.spec_from_file_location("dns_latency", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeResolver:
    """Minimal UDP DNS responder on 127.0.0.1: echoes a valid header."""

    def __enter__(self):
        import socket
        import struct
        import threading

        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.sock.settimeout(10.0)

        def serve():
            try:
                while True:
                    data, addr = self.sock.recvfrom(4096)
                    txid = struct.unpack(">H", data[:2])[0]
                    # QR=1 response, RD+RA, zero counts but the query's id.
                    reply = struct.pack(">HHHHHH", txid, 0x8180, 1, 0, 0, 0)
                    self.sock.sendto(reply + data[12:], addr)
            except OSError:
                return

        self.thread = threading.Thread(target=serve, daemon=True)
        self.thread.start()
        return self

    def __exit__(self, *exc):
        self.sock.close()


class TestDNSLatencyTracer:
    """The bcc_degraded DNS tracer measures, it doesn't stub
    (the reference's is a one-static-sample placeholder)."""

    def test_query_builder_wellformed(self):
        mod = _load_dns_tracer()
        q = mod.build_query("tpu.example.com")
        assert q[:2] == b"\x12\x34"  # txid
        assert b"\x03tpu\x07example\x03com\x00" in q
        assert q.endswith(b"\x00\x01\x00\x01")  # A, IN

    def test_default_resolver_parses_resolv_conf(self, tmp_path):
        mod = _load_dns_tracer()
        conf = tmp_path / "resolv.conf"
        conf.write_text("# comment\nsearch local\nnameserver 10.9.8.7\n")
        assert mod.default_resolver(str(conf)) == "10.9.8.7"
        assert mod.default_resolver(str(tmp_path / "absent")) == "127.0.0.53"

    def test_resolver_probe_measures_live_roundtrip(self, capsys):
        mod = _load_dns_tracer()
        with _FakeResolver() as fake:
            rc = mod.run_resolver_probe(
                0.01, 3, "127.0.0.1", "example.com", 5.0, port=fake.port
            )
        assert rc == 0
        lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert len(lines) == 3
        for sample in lines:
            assert sample["signal"] == "dns_latency_ms"
            assert sample["source"] == "resolver_probe"
            assert sample["value_ms"] > 0.0  # live nonzero measurement
            assert sample["resolver"] == "127.0.0.1"

    def test_dead_resolver_never_fabricates_latency(self, capsys):
        """Probe-infrastructure failure must not enter the
        dns_latency_ms stream (it would read as a real 16x-threshold
        DNS incident); it surfaces as a distinct dns_probe_error
        sample the forwarding bridge drops."""
        mod = _load_dns_tracer()
        rc = mod.run_resolver_probe(
            0.01, 1, "127.0.0.1", "example.com", 0.2, port=9
        )
        assert rc == 0
        sample = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert sample["signal"] == "dns_probe_error"
        assert sample["source"] == "resolver_probe_failed"
        assert "value_ms" not in sample

    def test_auto_mode_subprocess_emits_live_sample(self):
        import subprocess
        import sys
        from pathlib import Path

        script = (
            Path(__file__).resolve().parent.parent
            / "ebpf"
            / "bcc-fallback"
            / "dns_latency.py"
        )
        with _FakeResolver() as fake:
            proc = subprocess.run(
                [
                    sys.executable, str(script),
                    "--resolver", "127.0.0.1",
                    "--resolver-port", str(fake.port),
                ],
                capture_output=True, text=True, timeout=60,
            )
        assert proc.returncode == 0
        sample = json.loads(proc.stdout.strip().splitlines()[-1])
        # bcc on a BCC host, the resolver probe everywhere else — never
        # the old stub.
        assert sample["source"] in ("bcc_kprobe", "resolver_probe")
        assert sample["value_ms"] > 0.0
