"""Self-tracing unit tests: span trees, tail sampling, the overhead
gate, OTLP trace payloads, and the incident provenance log."""

import json

import pytest

from tpuslo.obs import (
    CYCLE_STAGES,
    DROPPED,
    KEPT_ERROR,
    KEPT_PROBABILISTIC,
    KEPT_SLOW,
    EvidenceEvent,
    ProvenanceLog,
    ProvenanceRecord,
    SelfTracer,
    SpanExporter,
    TracerConfig,
    format_chain,
    load_records,
    new_span_id,
    new_trace_id,
    probe_event_id,
    span_to_record,
    trace_endpoint_from_logs,
)


def run_cycle(tracer, stages=CYCLE_STAGES, fail_stage=None, **attrs):
    with tracer.cycle("agent.cycle", **attrs) as tr:
        for name in stages:
            with tr.stage(name, stage_attr=name) as sp:
                if name == fail_stage:
                    raise RuntimeError("stage boom")
                sp.set(batch=3)
    return tr


class TestTracerSpans:
    def test_ids_are_hex_and_unique(self):
        tids = {new_trace_id() for _ in range(64)}
        sids = {new_span_id() for _ in range(64)}
        assert len(tids) == 64 and len(sids) == 64
        assert all(len(t) == 32 and int(t, 16) >= 0 for t in tids)
        assert all(len(s) == 16 and int(s, 16) >= 0 for s in sids)

    def test_cycle_builds_root_plus_stage_children(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=1.0),
            on_export=exported.append,
        )
        run_cycle(tracer, cycle=7)
        assert len(exported) == 1
        spans = exported[0]
        root, children = spans[0], spans[1:]
        assert root.name == "agent.cycle"
        assert root.attributes["cycle"] == 7
        assert len(children) == len(CYCLE_STAGES) >= 6
        assert [s.name for s in children] == list(CYCLE_STAGES)
        for child in children:
            assert child.trace_id == root.trace_id
            assert child.parent_span_id == root.span_id
            assert child.span_id and child.span_id != root.span_id
            assert child.end_unix_nano >= child.start_unix_nano
            assert child.attributes["batch"] == 3
        assert root.end_unix_nano >= children[-1].end_unix_nano

    def test_disabled_tracer_records_nothing(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(enabled=False), on_export=exported.append
        )
        tr = run_cycle(tracer)
        assert exported == []
        assert tracer.stats["cycles"] == 0
        assert tr.trace_id == ""  # the shared null cycle

    def test_stage_timings_are_ordered(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=1.0),
            on_export=exported.append,
        )
        run_cycle(tracer)
        spans = exported[0]
        starts = [s.start_unix_nano for s in spans[1:]]
        assert starts == sorted(starts)


class TestTailSampling:
    def test_slow_cycles_always_kept(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=0.0, slow_cycle_ms=0.0),
            on_export=exported.append,
        )
        for _ in range(5):
            run_cycle(tracer)
        assert tracer.stats[KEPT_SLOW] == 5
        assert len(exported) == 5

    def test_error_cycles_always_kept_and_marked(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(
                enabled=True, sample_rate=0.0, slow_cycle_ms=1e9
            ),
            on_export=exported.append,
        )
        with pytest.raises(RuntimeError):
            run_cycle(tracer, fail_stage="validate")
        assert tracer.stats[KEPT_ERROR] == 1
        root = exported[0][0]
        assert root.status == "error"
        failed = [s for s in exported[0][1:] if s.name == "validate"]
        assert failed and failed[0].status == "error"

    def test_probabilistic_sampling_uses_rng(self):
        kept = SelfTracer(
            TracerConfig(enabled=True, sample_rate=0.5, slow_cycle_ms=1e9),
            rng=lambda: 0.4,
        )
        dropped = SelfTracer(
            TracerConfig(enabled=True, sample_rate=0.5, slow_cycle_ms=1e9),
            rng=lambda: 0.6,
        )
        run_cycle(kept)
        run_cycle(dropped)
        assert kept.stats[KEPT_PROBABILISTIC] == 1
        assert dropped.stats[DROPPED] == 1

    def test_dropped_cycles_skip_span_ids_and_export(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=0.0, slow_cycle_ms=1e9),
            on_export=exported.append,
        )
        tr = run_cycle(tracer)
        assert exported == []
        # Dropped cycles keep only the lightweight stage records — no
        # Span materialization, no ids.
        assert all(not getattr(s, "span_id", "") for s in tr.spans)
        assert all(s.duration_ms >= 0 for s in tr.spans)

    def test_export_failure_is_counted_not_raised(self):
        def boom(spans):
            raise OSError("sink down")

        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=1.0), on_export=boom
        )
        run_cycle(tracer)
        assert tracer.stats["export_errors"] == 1


class TestForcedKeep:
    def test_mark_keep_forces_sampling(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=0.0, slow_cycle_ms=1e9),
            on_export=exported.append,
        )
        with tracer.cycle("agent.cycle") as tr:
            with tr.stage("attribute"):
                tr.mark_keep()  # e.g. this cycle produced an incident
        from tpuslo.obs import KEPT_FORCED

        assert tracer.stats[KEPT_FORCED] == 1
        assert len(exported) == 1
        # The forced-kept spans carry real ids: the provenance pointer
        # recorded mid-cycle must resolve to this exported trace.
        assert all(s.span_id for s in exported[0])

    def test_null_cycle_mark_keep_is_noop(self):
        tracer = SelfTracer(TracerConfig(enabled=False))
        with tracer.cycle("agent.cycle") as tr:
            tr.mark_keep()
        assert tracer.stats["cycles"] == 0

    def test_no_export_callback_counts_nothing_exported(self):
        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=1.0)
        )  # kept every cycle, but there is nowhere to ship spans
        run_cycle(tracer)
        assert tracer.stats["spans_exported"] == 0


class TestOverheadGate:
    def _overloaded_tracer(self, **overrides):
        cfg = dict(
            enabled=True,
            sample_rate=0.0,
            slow_cycle_ms=1e9,
            max_overhead_pct=0.000001,
            overhead_grace_cycles=3,
        )
        cfg.update(overrides)
        return SelfTracer(TracerConfig(**cfg))

    def test_sustained_overhead_degrades_to_metrics_only(self):
        tracer = self._overloaded_tracer()
        # Near-empty cycles: bookkeeping dwarfs the body, the EMA
        # breaches the (absurdly low) budget, and the gate trips.
        for _ in range(10):
            run_cycle(tracer)
        assert tracer.degraded
        # Metrics-only, not metrics-off: cycles keep being timed and
        # the observer keeps firing — only span sampling stops.
        assert tracer.enabled
        before = tracer.stats["cycles"]
        run_cycle(tracer)
        assert tracer.stats["cycles"] == before + 1
        assert tracer.stats[DROPPED] >= 1

    def test_degraded_tracer_still_keeps_error_cycles(self):
        exported = []
        tracer = self._overloaded_tracer()
        tracer._on_export = exported.append
        for _ in range(10):
            run_cycle(tracer)
        assert tracer.degraded
        exported.clear()
        with pytest.raises(RuntimeError):
            run_cycle(tracer, fail_stage="deliver")
        assert len(exported) == 1

    def test_degraded_tracer_still_keeps_forced_incident_cycles(self):
        exported = []
        tracer = self._overloaded_tracer()
        tracer._on_export = exported.append
        for _ in range(10):
            run_cycle(tracer)
        assert tracer.degraded
        exported.clear()
        with tracer.cycle("agent.cycle") as tr:
            with tr.stage("attribute"):
                tr.mark_keep()  # incident: the provenance pointer
        assert len(exported) == 1  # must resolve even while degraded

    def test_degradation_heals_when_overhead_recovers(self):
        import time as time_mod

        tracer = self._overloaded_tracer(overhead_grace_cycles=2)
        for _ in range(5):
            run_cycle(tracer)
        assert tracer.degraded
        # Raise the budget and run cycles with a real body: the EMA
        # falls under half the budget and export re-arms.
        tracer.config.max_overhead_pct = 1e9
        for _ in range(10):
            with tracer.cycle("agent.cycle") as tr:
                with tr.stage("generate"):
                    time_mod.sleep(0.001)
        assert not tracer.degraded

    def test_healthy_overhead_does_not_degrade(self):
        tracer = SelfTracer(
            TracerConfig(
                enabled=True, sample_rate=0.0, max_overhead_pct=1e9
            )
        )
        for _ in range(20):
            run_cycle(tracer)
        assert not tracer.degraded
        assert tracer.snapshot()["overhead_pct"] >= 0.0


class TestBackgroundSpanPoster:
    class _Exporter:
        def __init__(self, fail=False):
            self.fail = fail
            self.posted = []

        def post_records(self, records):
            if self.fail:
                raise OSError("endpoint down")
            self.posted.append(records)

    def test_posts_in_background(self):
        import time as time_mod

        from tpuslo.obs import BackgroundSpanPoster

        exporter = self._Exporter()
        poster = BackgroundSpanPoster(exporter)
        poster.submit([{"traceId": "a"}])
        poster.close(timeout_s=5.0)
        assert exporter.posted == [[{"traceId": "a"}]]
        assert poster.stats["posted"] == 1
        _ = time_mod  # imported for parity with other tests

    def test_failures_counted_not_raised(self):
        from tpuslo.obs import BackgroundSpanPoster

        poster = BackgroundSpanPoster(self._Exporter(fail=True))
        poster.submit([{"traceId": "a"}])
        poster.close(timeout_s=5.0)
        assert poster.stats["errors"] == 1

    def test_full_queue_drops_oldest(self):
        from tpuslo.obs import BackgroundSpanPoster

        exporter = self._Exporter()
        poster = BackgroundSpanPoster(exporter, queue_max=2)
        # Freeze the worker so the queue actually fills.
        import threading

        gate = threading.Event()
        orig = exporter.post_records
        exporter.post_records = lambda r: (gate.wait(5), orig(r))
        poster.submit([{"n": 0}])  # worker grabs this and blocks
        import time as time_mod

        time_mod.sleep(0.1)
        for n in (1, 2, 3):
            poster.submit([{"n": n}])
        gate.set()
        poster.close(timeout_s=5.0)
        assert poster.stats["dropped"] >= 1
        posted = [r[0]["n"] for r in exporter.posted]
        assert 3 in posted  # the freshest batch survived


class TestSpanExporter:
    def test_trace_endpoint_derivation(self):
        assert (
            trace_endpoint_from_logs("http://otel:4318/v1/logs")
            == "http://otel:4318/v1/traces"
        )
        assert (
            trace_endpoint_from_logs("http://otel:4318")
            == "http://otel:4318/v1/traces"
        )
        assert trace_endpoint_from_logs("") == ""

    def test_otlp_record_shape(self):
        exported = []
        tracer = SelfTracer(
            TracerConfig(enabled=True, sample_rate=1.0),
            on_export=exported.append,
        )
        run_cycle(tracer, cycle=1)
        spans = exported[0]
        records = [span_to_record(s) for s in spans]
        root = records[0]
        assert root["traceId"] == spans[0].trace_id
        assert root["spanId"] == spans[0].span_id
        assert "parentSpanId" not in root
        assert root["kind"] == 1
        assert int(root["endTimeUnixNano"]) >= int(root["startTimeUnixNano"])
        assert root["status"]["code"] == 1
        child = records[1]
        assert child["parentSpanId"] == spans[0].span_id
        attr_keys = {a["key"] for a in child["attributes"]}
        assert {"stage_attr", "batch"} <= attr_keys
        # Typed attribute values, not stringified everything.
        by_key = {a["key"]: a["value"] for a in child["attributes"]}
        assert by_key["batch"] == {"intValue": "3"}

    def test_envelope_is_resource_spans(self):
        exporter = SpanExporter("http://collector/v1/traces")
        envelope = exporter._envelope([{"traceId": "x"}])
        scope = envelope["resourceSpans"][0]["scopeSpans"][0]
        assert scope["scope"]["name"] == "tpuslo/obs"
        assert scope["spans"] == [{"traceId": "x"}]
        resource = envelope["resourceSpans"][0]["resource"]
        assert resource["attributes"][0]["key"] == "service.name"


def make_record(incident="inc-1") -> ProvenanceRecord:
    return ProvenanceRecord(
        incident_id=incident,
        recorded_at="2026-08-01T00:00:00Z",
        cycle=4,
        trace_id="t" * 32,
        root_span_id="s" * 16,
        fault_label="hbm_pressure",
        predicted_fault_domain="tpu_hbm",
        confidence=0.93,
        posterior={"tpu_hbm": 0.93, "host_offload": 0.05},
        events=[
            EvidenceEvent(
                event_id=probe_event_id("hbm_alloc_stall_ms", 123),
                signal="hbm_alloc_stall_ms",
                value=60.0,
                tier="trace_id_exact",
                confidence=1.0,
            )
        ],
        correlation={
            "window_ms": 2000,
            "total": 16,
            "matched": 14,
            "best_tier": "trace_id_exact",
        },
        delivery={"outcome": "queued", "channel": "delivery_channel"},
        stages_ms={"generate": 0.4, "deliver": 1.2},
    )


class TestProvenance:
    def test_roundtrip_and_last_record_wins(self, tmp_path):
        path = str(tmp_path / "prov.jsonl")
        log = ProvenanceLog(path)
        first = make_record()
        log.record(first)
        second = make_record()
        second.confidence = 0.99
        log.record(second)
        log.record(make_record("inc-2"))
        log.close()
        records = load_records(path)
        assert set(records) == {"inc-1", "inc-2"}
        assert records["inc-1"].confidence == 0.99
        assert records["inc-1"].events[0].tier == "trace_id_exact"
        assert records["inc-1"].stages_ms["deliver"] == 1.2

    def test_torn_tail_is_skipped(self, tmp_path):
        path = tmp_path / "prov.jsonl"
        log = ProvenanceLog(str(path))
        log.record(make_record())
        log.close()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"incident_id": "torn', )
        records = load_records(str(path))
        assert set(records) == {"inc-1"}

    def test_missing_file_is_empty(self, tmp_path):
        assert load_records(str(tmp_path / "nope.jsonl")) == {}

    def test_format_chain_prints_causal_steps(self):
        text = format_chain(make_record())
        assert "incident inc-1" in text
        assert "predicted: tpu_hbm (confidence 0.930)" in text
        assert "hbm_alloc_stall_ms@123" in text
        assert "tier=trace_id_exact" in text
        assert "14/16 events matched within 2000 ms" in text
        assert "tpu_hbm=0.930" in text
        assert "outcome=queued" in text
        assert "generate=0.40ms" in text

    def test_attribution_block_carries_pointers(self):
        rec = make_record()
        block = rec.attribution_block()
        assert block["trace_id"] == rec.trace_id
        assert block["root_span_id"] == rec.root_span_id
        assert block["probe_event_ids"] == ["hbm_alloc_stall_ms@123"]
        json.dumps(block)  # webhook payloads must serialize
