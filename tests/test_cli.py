"""CLI surface tests: each binary smoke-tested end-to-end in-process.

Reference model: the reference CI's no-cluster smoke tests
(fault-inject→collector pipe, replay→benchgen, correlation gate).
"""

import pytest
import json
import urllib.request


from tpuslo.__main__ import BINARIES, main as dispatch
from tpuslo.cli import (
    agent,
    attributor,
    collector,
    correlationeval,
    faultinject,
    faultreplay,
    loadgen,
    m5gate,
    schemavalidate,
)


class TestDispatcher:
    def test_all_binaries_registered(self):
        # 11 reference parity + slicecorr + train + icibench +
        # fleetagg + frontdoor
        assert len(BINARIES) == 16

    def test_unknown_binary_exit_2(self):
        assert dispatch(["warpdrive"]) == 2

    def test_help_exit_0(self):
        assert dispatch(["--help"]) == 0


class TestFaultInjectCollectorPipe:
    def test_pipe(self, tmp_path, capsys):
        raw = tmp_path / "raw.jsonl"
        assert faultinject.main(
            ["--scenario", "tpu_mixed", "--count", "8", "--output", str(raw)]
        ) == 0
        out = tmp_path / "events.jsonl"
        assert collector.main(
            ["--input", str(raw), "--output", "jsonl", "--jsonl-path", str(out)]
        ) == 0
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert len(lines) == 32  # 8 samples x 4 SLIs
        assert {l["kind"] for l in lines} == {"slo"}

    def test_collector_synthetic_stdout(self, capsys):
        assert collector.main(["--scenario", "hbm_pressure", "--count", "2"]) == 0
        out = capsys.readouterr().out
        lines = [json.loads(l) for l in out.strip().splitlines()]
        assert len(lines) == 8

    def test_collector_requires_input(self, capsys):
        assert collector.main([]) == 2


class TestFaultReplayAttributorPipe:
    def test_pipe_with_summary_and_confusion(self, tmp_path):
        samples = tmp_path / "samples.jsonl"
        assert faultreplay.main(
            ["--scenario", "tpu_mixed_multi", "--count", "12", "--output", str(samples)]
        ) == 0
        out = tmp_path / "attributions.jsonl"
        summary = tmp_path / "summary.json"
        confusion = tmp_path / "confusion.csv"
        assert attributor.main(
            [
                "--input", str(samples),
                "--output", str(out),
                "--summary", str(summary),
                "--confusion", str(confusion),
            ]
        ) == 0
        attributions = [json.loads(l) for l in out.read_text().splitlines()]
        assert len(attributions) == 12
        report = json.loads(summary.read_text())
        assert report["partial_accuracy"] == 1.0
        assert confusion.read_text().startswith("actual,predicted,count")

    def test_rule_mode(self, tmp_path):
        samples = tmp_path / "samples.jsonl"
        faultreplay.main(
            ["--scenario", "ici_drop", "--count", "3", "--output", str(samples)]
        )
        out = tmp_path / "attr.jsonl"
        assert attributor.main(
            ["--input", str(samples), "--output", str(out), "--mode", "rule"]
        ) == 0
        rows = [json.loads(l) for l in out.read_text().splitlines()]
        assert all(r["predicted_fault_domain"] == "tpu_ici" for r in rows)


class TestCorrelationEval:
    def test_default_golden_gate_passes(self, tmp_path):
        report = tmp_path / "report.json"
        predictions = tmp_path / "preds.csv"
        assert correlationeval.main(
            ["--report", str(report), "--predictions", str(predictions)]
        ) == 0
        data = json.loads(report.read_text())
        assert data["precision"] == 1.0
        assert predictions.read_text().count("\n") >= 50

    def test_gate_failure_exit_1(self):
        assert correlationeval.main(["--min-precision", "1.01"]) == 1


class TestLoadgen:
    def test_deterministic_trace(self, tmp_path):
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        loadgen.main(["--profile", "context_128k", "--seed", "7", "--output", str(a)])
        loadgen.main(["--profile", "context_128k", "--seed", "7", "--output", str(b)])
        assert a.read_text() == b.read_text()
        first = json.loads(a.read_text().splitlines()[0])
        assert first["prompt_tokens"] == 131072


class TestSchemaValidate:
    def test_all_golden_payloads_valid(self, capsys):
        assert schemavalidate.main([]) == 0
        out = capsys.readouterr().out
        assert "all contracts and golden payloads valid" in out


class TestM5GateCLI:
    def test_end_to_end_with_generated_runs(self, tmp_path):
        import csv as csv_mod

        from tpuslo.cli import faultinject as fi

        candidate = tmp_path / "candidate"
        for run in ("run-1", "run-2", "run-3"):
            run_dir = candidate / "dns_latency" / run
            run_dir.mkdir(parents=True)
            assert fi.main(
                [
                    "--scenario", "dns_latency",
                    "--count", "40",
                    "--output", str(run_dir / "raw_samples.jsonl"),
                    "--start", "2026-07-29T00:00:00Z",
                ]
            ) == 0
            with open(run_dir / "collector_overhead.csv", "w", newline="") as f:
                writer = csv_mod.writer(f)
                writer.writerow(["node", "cpu_pct", "memory_mb"])
                writer.writerow(["tpu-vm-0", "1.8", "105"])
        summary_json = tmp_path / "m5.json"
        summary_md = tmp_path / "m5.md"
        assert m5gate.main(
            [
                "--candidate-root", str(candidate),
                "--scenarios", "dns_latency",
                "--summary-json", str(summary_json),
                "--summary-md", str(summary_md),
            ]
        ) == 0
        data = json.loads(summary_json.read_text())
        assert data["passed"] is True
        assert "# M5 release gate summary" in summary_md.read_text()


class TestAgentCLI:
    def test_bounded_run_emits_events_and_metrics(self, tmp_path):
        out = tmp_path / "agent.jsonl"
        rc = agent.main(
            [
                "--scenario", "tpu_mixed",
                "--count", "4",
                "--interval-s", "0.01",
                "--event-kind", "both",
                "--output", "jsonl",
                "--jsonl-path", str(out),
                "--capability-mode", "tpu_full",
                "--metrics-port", "0",
                "--max-overhead-pct", "1000",
            ]
        )
        assert rc == 0
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        kinds = {l["kind"] for l in lines}
        assert kinds == {"slo", "probe"}
        probes = [l for l in lines if l["kind"] == "probe"]
        # default config signal_set covers 18 of the 23 signals (the
        # three counters are opt-in, mirroring the reference default;
        # the two profiler window signals are emitted only by the
        # continuous profiler, never by the synthetic generator)
        assert len(probes) == 4 * 18
        tpu_probes = [p for p in probes if "tpu" in p]
        assert tpu_probes and tpu_probes[0]["tpu"]["chip"]

    def test_metrics_server_serves(self):
        from tpuslo.metrics import AgentMetrics, start_metrics_server

        metrics = AgentMetrics()
        metrics.up.set(1)
        metrics.observe_probe("hbm_utilization_pct", 97.0)
        server = start_metrics_server(metrics, 0, host="127.0.0.1")
        try:
            port = server.server_address[1]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            assert "llm_slo_agent_up 1.0" in body
            assert "llm_tpu_agent_hbm_utilization_pct 97.0" in body
            health = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            )
            assert health.status == 200
        finally:
            server.shutdown()

    def test_degraded_mode_emits_two_signals(self, tmp_path, capsys):
        out = tmp_path / "agent.jsonl"
        assert agent.main(
            [
                "--scenario", "dns_latency",
                "--count", "1",
                "--event-kind", "probe",
                "--output", "jsonl",
                "--jsonl-path", str(out),
                "--capability-mode", "bcc_degraded",
                "--metrics-port", "0",
            ]
        ) == 0
        probes = [json.loads(l) for l in out.read_text().splitlines()]
        assert {p["signal"] for p in probes} == {
            "dns_latency_ms",
            "tcp_retransmits_total",
        }

    def test_probe_smoke_mode_runs(self, capsys):
        rc = agent.main(["--probe-smoke"])
        out = capsys.readouterr().out
        assert "probe-smoke:" in out
        assert rc in (0, 1)  # depends on host privileges


@pytest.mark.slow
class TestTrain:
    def test_train_cli_steps_and_summary(self, capsys):
        # conftest already forces the 8-device CPU mesh.
        rc = dispatch(
            ["train", "--steps", "2", "--batch", "4", "--seq-len", "32"]
        )
        assert rc == 0
        lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert [l["step"] for l in lines[:-1]] == [1, 2]
        summary = lines[-1]
        assert summary["done"] and summary["last_step"] == 2
        assert summary["mesh"]["dp"] * summary["mesh"]["fsdp"] * summary["mesh"]["tp"] == 8
