"""TPL160-163 trace-discipline rules: fixtures + real-tree anchors.

Mirrors test_analysis_rules.py's pattern: every code gets at least one
fixture that provokes it and one that stays clean, the real JAX plane
must self-host at zero findings (with the committed suppressions), and
in-memory mutation tests anchored to the real ``speculative.py`` /
``serve.py`` prove each rule fires both directions — a mutation that
reintroduces the BENCH_r05 defect class must be caught, and the fixed
tree must not be flagged.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from tpuslo.analysis import FileContext, RepoContext, run_analysis
from tpuslo.analysis.hotpaths import JAX_HOT_LOOPS, JAX_PLANE_PREFIXES
from tpuslo.analysis.rules_jax import TraceDisciplineRule

REPO = Path(__file__).resolve().parent.parent
SPEC_REL = "tpuslo/models/speculative.py"
SERVE_REL = "tpuslo/models/serve.py"
FX_REL = "tpuslo/models/_tpl16x_fixture.py"


def _ctx(rel: str, source: str) -> FileContext:
    return FileContext(REPO / rel, rel, textwrap.dedent(source))


def _plane_repo(*contexts: FileContext) -> RepoContext:
    """RepoContext rooted at the real repo (the manifest exists there)
    holding only the given in-memory plane files."""
    return RepoContext(REPO, list(contexts))


def _findings(rule: TraceDisciplineRule, repo: RepoContext, code: str):
    return [f for f in rule.check_repo(repo) if f.code == code]


def _fixture_rule(**kwargs) -> TraceDisciplineRule:
    """Rule scoped to the fixture file only (no real hot loops), so
    fixture trees never depend on the live manifest entries."""
    kwargs.setdefault("hot_loops", ())
    kwargs.setdefault("plane_prefixes", ("tpuslo/models/_tpl16x",))
    return TraceDisciplineRule(**kwargs)


def _mutated_repo(rel: str, transform) -> RepoContext:
    source = (REPO / rel).read_text(encoding="utf-8")
    return RepoContext(REPO, [FileContext(REPO / rel, rel, transform(source))])


class TestTPL160HostSyncs:
    def _rule(self, qualname: str = "decode_loop") -> TraceDisciplineRule:
        return _fixture_rule(hot_loops=((FX_REL, qualname),))

    def test_item_on_device_value_in_loop_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax
            import jax.numpy as jnp

            def decode_loop(params, cache):
                tok = jnp.zeros((1,))
                out = []
                for _ in range(8):
                    tok = decode(params, tok, cache)
                    out.append(tok.item())
                return out
            """,
        )
        found = _findings(self._rule(), _plane_repo(ctx), "TPL160")
        assert len(found) == 1
        assert ".item()" in found[0].message

    def test_item_on_device_get_result_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax
            import jax.numpy as jnp

            def decode_loop(params, cache):
                tok = jnp.zeros((1,))
                out = []
                for _ in range(8):
                    tok = decode(params, tok, cache)
                    host = jax.device_get(tok)
                    out.append(host.item())
                return out
            """,
        )
        assert not _findings(self._rule(), _plane_repo(ctx), "TPL160")

    def test_scalar_cast_of_device_name_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax.numpy as jnp

            def decode_loop(cache):
                length = jnp.asarray(0, jnp.int32)
                while True:
                    length = step(cache, length)
                    if int(length) > 8:
                        break
            """,
        )
        found = _findings(self._rule(), _plane_repo(ctx), "TPL160")
        assert len(found) == 1
        assert "int()" in found[0].message

    def test_block_until_ready_in_loop_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            def decode_loop(cache):
                for i in range(4):
                    cache = step(cache)
                    jax.block_until_ready(cache)
            """,
        )
        found = _findings(self._rule(), _plane_repo(ctx), "TPL160")
        assert len(found) == 1
        assert "block_until_ready" in found[0].message

    def test_np_asarray_of_device_value_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import numpy as np
            import jax.numpy as jnp

            def decode_loop(cache):
                toks = jnp.zeros((4,))
                for i in range(4):
                    toks = step(cache, toks)
                    host = np.asarray(toks)
            """,
        )
        found = _findings(self._rule(), _plane_repo(ctx), "TPL160")
        assert len(found) == 1
        assert "np.asarray" in found[0].message

    def test_sync_outside_loop_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax
            import jax.numpy as jnp

            def decode_loop(cache):
                toks = jnp.zeros((4,))
                for i in range(4):
                    toks = step(cache, toks)
                return toks.tolist()
            """,
        )
        assert not _findings(self._rule(), _plane_repo(ctx), "TPL160")

    def test_nested_loop_hazard_reported_once(self):
        """A sync inside a for nested in a while is walked by both
        loops' traversals — it must still report exactly one finding
        (one hazard, one suppression)."""
        ctx = _ctx(
            FX_REL,
            """
            import jax
            import jax.numpy as jnp

            def decode_loop(cache):
                length = jnp.zeros(())
                while True:
                    for _ in range(4):
                        length = step(cache, length)
                        if int(length) > 8:
                            return
            """,
        )
        found = _findings(self._rule(), _plane_repo(ctx), "TPL160")
        assert len(found) == 1

    def test_missing_manifest_entry_is_finding(self):
        rule = _fixture_rule(
            hot_loops=((FX_REL, "renamed_away"),),
        )
        ctx = _ctx(FX_REL, "def decode_loop():\n    pass\n")
        found = _findings(rule, _plane_repo(ctx), "TPL160")
        assert len(found) == 1
        assert "not found" in found[0].message

    def test_missing_manifest_file_is_finding(self):
        rule = _fixture_rule(
            hot_loops=(("tpuslo/models/_gone.py", "decode_loop"),),
        )
        found = _findings(rule, _plane_repo(), "TPL160")
        assert len(found) == 1
        assert "missing" in found[0].message


class TestTPL161Retrace:
    def test_jit_inside_loop_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            def serve(chunks):
                for chunk in chunks:
                    fn = jax.jit(lambda x: x[:chunk])
                    fn(chunk)
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")
        assert len(found) == 1
        assert "inside a loop" in found[0].message

    def test_jit_per_call_without_cache_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            def build_step(cfg):
                return jax.jit(lambda p, t: step(p, t, cfg))
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")
        assert len(found) == 1
        assert "recompile for every call" in found[0].message

    def test_lru_cached_builder_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax
            from functools import lru_cache

            @lru_cache(maxsize=32)
            def build_step(cfg):
                return jax.jit(lambda p, t: step(p, t, cfg))
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")

    def test_module_level_jit_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            _step = jax.jit(lambda p, t: p + t)

            @jax.jit
            def other(x):
                return x * 2
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")

    def test_nested_bare_jit_def_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            def outer(cfg):
                @jax.jit
                def inner(x):
                    return x + cfg.bias
                return inner
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")
        assert len(found) == 1
        assert "retraces per enclosing call" in found[0].message

    def test_traced_branching_flagged_and_static_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax
            from functools import partial

            @jax.jit
            def traced(x):
                if x > 0:
                    return x
                return -x

            @partial(jax.jit, static_argnums=(1,))
            def mixed(x, flag):
                if flag:
                    return x * 2
                return x

            @jax.jit
            def shape_based(x):
                if x.ndim == 2:
                    return x.sum(-1)
                return x
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")
        assert len(found) == 1
        assert "'x'" in found[0].message

    def test_optional_arg_none_branch_clean(self):
        """``if mask is None`` keys on pytree structure (part of the
        jit cache key) — the canonical optional-argument idiom must
        not be flagged as value-dependent branching."""
        ctx = _ctx(
            FX_REL,
            """
            import jax

            @jax.jit
            def f(x, mask=None):
                if mask is None:
                    return x
                if mask is not None and x is not None:
                    return x * mask
                return x
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")

    def test_non_literal_static_argnums_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            nums = (1, 2)
            _fn = jax.jit(step, static_argnums=nums)
            _ok = jax.jit(step, static_argnums=(1, 2))
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")
        assert len(found) == 1
        assert "literal" in found[0].message

    def test_non_literal_static_argnums_decorator_form_flagged(self):
        """The decorator idiom must obey the same contract as the
        call-form site (it takes a different AST route)."""
        ctx = _ctx(
            FX_REL,
            """
            import jax
            from functools import partial

            nums = (1,)

            @partial(jax.jit, static_argnums=nums)
            def step(params, n):
                return params
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL161")
        assert len(found) == 1
        assert "literal" in found[0].message


class TestTPL162DtypeDrift:
    def test_asarray_without_dtype_in_loop_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax.numpy as jnp

            def emit(rows):
                for row in rows:
                    yield jnp.asarray(row)
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL162")
        assert len(found) == 1
        assert "dtype" in found[0].message

    def test_asarray_with_dtype_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax.numpy as jnp

            def emit(rows):
                for row in rows:
                    yield jnp.asarray(row, jnp.int32)
                for row in rows:
                    yield jnp.zeros((4,), dtype=jnp.float32)
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL162")

    def test_ctor_outside_loop_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax.numpy as jnp

            def once(rows):
                return jnp.asarray(rows)
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL162")


class TestTPL163DonationMisses:
    def test_undonated_cache_param_flagged(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            def decode_step(params, tok, cache):
                return tok, cache

            _step = jax.jit(decode_step)
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL163")
        assert len(found) == 1
        assert "cache" in found[0].message

    def test_donated_cache_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            def decode_step(params, tok, cache):
                return tok, cache

            _step = jax.jit(decode_step, donate_argnums=(2,))
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL163")

    def test_no_donatable_param_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax

            def score(params, tok):
                return tok

            _score = jax.jit(score)
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL163")

    def test_undonated_cache_bare_decorator_flagged(self):
        """``@jax.jit`` over a cache-threading def is the most common
        jit idiom — the decorator route must not escape TPL163."""
        ctx = _ctx(
            FX_REL,
            """
            import jax

            @jax.jit
            def decode_step(params, tok, kv_cache):
                return tok, kv_cache
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL163")
        assert len(found) == 1
        assert "kv_cache" in found[0].message

    def test_donated_partial_decorator_clean(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnames=("kv_cache",))
            def decode_step(params, tok, kv_cache):
                return tok, kv_cache
            """,
        )
        assert not _findings(_fixture_rule(), _plane_repo(ctx), "TPL163")

    def test_partial_bound_cache_resolved(self):
        ctx = _ctx(
            FX_REL,
            """
            import jax
            from functools import partial

            def decode_step(params, tok, cache, cfg):
                return tok, cache

            _step = jax.jit(partial(decode_step, cfg=None))
            """,
        )
        found = _findings(_fixture_rule(), _plane_repo(ctx), "TPL163")
        assert len(found) == 1


class TestRealTreeAnchors:
    """The committed JAX plane self-hosts; mutations re-fire the rules."""

    def test_real_plane_is_clean_with_suppressions(self):
        result = run_analysis(
            REPO,
            paths=[p.rstrip("/") for p in JAX_PLANE_PREFIXES],
            rules=[TraceDisciplineRule()],
        )
        assert result.findings == []
        # The intentional sites (init-time one-shot jits, first-hit
        # compile timing, dryrun-harness jits) are suppressed per line,
        # not silently invisible.
        assert result.suppressed >= 8

    def test_hot_loop_manifest_points_at_real_functions(self):
        contexts = []
        for rel in sorted({rel for rel, _ in JAX_HOT_LOOPS}):
            source = (REPO / rel).read_text(encoding="utf-8")
            contexts.append(FileContext(REPO / rel, rel, source))
        repo = RepoContext(REPO, contexts)
        stale = [
            f
            for f in TraceDisciplineRule().check_repo(repo)
            if f.path == "tpuslo/analysis/hotpaths.py"
        ]
        assert stale == []

    def test_uncaching_spec_round_builder_fires_tpl161(self):
        """Removing the lru_cache memoization reintroduces the
        BENCH_r05 defect (a fresh jit wrapper per engine): TPL161."""
        repo = _mutated_repo(
            SPEC_REL, lambda s: s.replace("@lru_cache(maxsize=32)\n", "")
        )
        found = [
            f
            for f in TraceDisciplineRule().check_repo(repo)
            if f.code == "TPL161" and f.path == SPEC_REL
        ]
        assert len(found) >= 2  # both memoized builders uncached

    def test_dropping_donation_fires_tpl163(self):
        repo = _mutated_repo(
            SPEC_REL, lambda s: s.replace(", donate_argnums=(3, 4)", "")
        )
        found = [
            f
            for f in TraceDisciplineRule().check_repo(repo)
            if f.code == "TPL163" and f.path == SPEC_REL
        ]
        # The single-stream, batched, and multi-round builders all
        # thread both caches.
        assert len(found) == 3

    def test_host_sync_in_stream_loop_fires_tpl160(self):
        """Reintroducing a per-round scalar pull (the eager-emit-loop
        defect) inside SpeculativeEngine.stream: TPL160."""
        repo = _mutated_repo(
            SPEC_REL,
            lambda s: s.replace(
                "            n = int(n_vec[0])",
                "            n = int(current[0])",
            ),
        )
        found = [
            f
            for f in TraceDisciplineRule().check_repo(repo)
            if f.code == "TPL160" and f.path == SPEC_REL
        ]
        assert len(found) == 1
        assert "int()" in found[0].message

    def test_per_round_asarray_in_stream_fires_tpl162(self):
        """The pre-fix emit loop uploaded a fresh scalar per round via
        jnp.asarray without dtype; planting one back is TPL162."""

        def transform(source: str) -> str:
            return source.replace(
                "            n = int(n_vec[0])",
                "            cur = jnp.asarray(n_vec)\n"
                "            n = int(n_vec[0])",
            )

        repo = _mutated_repo(SPEC_REL, transform)
        found = [
            f
            for f in TraceDisciplineRule().check_repo(repo)
            if f.code == "TPL162" and f.path == SPEC_REL
        ]
        assert len(found) == 1

    def test_serve_steady_sync_fires_tpl160(self):
        """A block_until_ready planted in ServeEngine.generate's chunk
        loop (outside the suppressed first-hit sites): TPL160."""

        def transform(source: str) -> str:
            return source.replace(
                "                chunk_values = jax.device_get(toks[0]).tolist()",
                "                jax.block_until_ready(toks)\n"
                "                chunk_values = jax.device_get(toks[0]).tolist()",
            )

        repo = _mutated_repo(SERVE_REL, transform)
        # check_repo is pre-suppression: filter to the generate loop
        # (the suppressed first-hit _append_ids sites also surface).
        found = [
            f
            for f in TraceDisciplineRule().check_repo(repo)
            if f.code == "TPL160"
            and f.path == SERVE_REL
            and "ServeEngine.generate " in f.message
        ]
        assert len(found) == 1
        assert "block_until_ready" in found[0].message

    def test_manifest_rename_reported_stale(self):
        rule = TraceDisciplineRule(
            hot_loops=((SPEC_REL, "SpeculativeEngine.streamed_away"),),
        )
        source = (REPO / SPEC_REL).read_text(encoding="utf-8")
        repo = RepoContext(
            REPO, [FileContext(REPO / SPEC_REL, SPEC_REL, source)]
        )
        found = [f for f in rule.check_repo(repo) if f.code == "TPL160"]
        assert len(found) == 1
        assert "streamed_away" in found[0].message

    def test_fixture_tree_without_manifest_skipped(self, tmp_path):
        """A repo without the hotpaths manifest (fixture trees) is not
        governed — no spurious findings outside this repo."""
        target = tmp_path / "models"
        target.mkdir()
        (target / "bad.py").write_text(
            "import jax\n\n\ndef f(chunks):\n"
            "    for c in chunks:\n"
            "        jax.jit(lambda x: x)(c)\n",
            encoding="utf-8",
        )
        ctx = FileContext(
            target / "bad.py", "tpuslo/models/bad.py",
            (target / "bad.py").read_text(encoding="utf-8"),
        )
        repo = RepoContext(tmp_path, [ctx])
        assert list(TraceDisciplineRule().check_repo(repo)) == []


class TestChangedRunAnchors:
    def test_plane_prefixes_are_rule_anchors(self):
        """tpulint --changed loads rule anchors; the whole JAX plane +
        the manifest ride along, so touching any models/ops/parallel
        file re-runs the TPL160s (the ISSUE 10 satellite)."""
        anchors = TraceDisciplineRule.repo_anchors
        for prefix in JAX_PLANE_PREFIXES:
            assert prefix in anchors
        assert "tpuslo/analysis/hotpaths.py" in anchors

    def test_changed_scope_still_checks_hot_loops(self):
        """A --changed-style run scoped to ONE plane file still
        resolves every hot-loop manifest entry (anchors loaded)."""
        result = run_analysis(
            REPO,
            rules=[TraceDisciplineRule()],
            files=[REPO / SPEC_REL],
        )
        assert result.findings == []
