"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh).

The kernel is the serving/training hot op (tpuslo/ops/flash_attention);
on real TPU it runs compiled, here every test uses interpret=True via
the TPUSLO_FLASH_ATTENTION=1 override or direct calls.
"""

import pytest
import jax
import jax.numpy as jnp

from tpuslo.models import llama
from tpuslo.ops.flash_attention import flash_attention, flash_eligible
from tpuslo.ops.ring_attention import reference_causal_attention


def _rand_qkv(key, B, S, H, KV, D, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, KV, D), dtype)
    v = jax.random.normal(kv, (B, S, KV, D), dtype)
    return q, k, v


def _ref(q, k, v, n_rep):
    return reference_causal_attention(
        q, jnp.repeat(k, n_rep, axis=2), jnp.repeat(v, n_rep, axis=2)
    )


class TestFlashKernel:
    def test_matches_reference_f32(self):
        q, k, v = _rand_qkv(jax.random.PRNGKey(0), 2, 256, 4, 2, 128)
        out = flash_attention(q, k, v, interpret=True)
        err = jnp.max(jnp.abs(out - _ref(q, k, v, 2)))
        assert float(err) < 2e-5

    def test_matches_reference_bf16(self):
        q, k, v = _rand_qkv(
            jax.random.PRNGKey(1), 1, 256, 4, 4, 128, jnp.bfloat16
        )
        out = flash_attention(q, k, v, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = _ref(q.astype(jnp.float32), k.astype(jnp.float32),
                   v.astype(jnp.float32), 1)
        err = jnp.max(jnp.abs(out.astype(jnp.float32) - ref))
        assert float(err) < 3e-2

    def test_uneven_blocks_cover_sequence(self):
        """block_k != block_q exercises the last-relevant-k epilogue
        bookkeeping (epilogue block differs per q-block)."""
        q, k, v = _rand_qkv(jax.random.PRNGKey(2), 1, 512, 2, 2, 128)
        out = flash_attention(q, k, v, block_q=128, block_k=256, interpret=True)
        err = jnp.max(jnp.abs(out - _ref(q, k, v, 1)))
        assert float(err) < 2e-5

    def test_gqa_head_mapping(self):
        """Each q-head group must attend to ITS kv head: make kv heads
        wildly different and compare with explicit repeat."""
        q, k, v = _rand_qkv(jax.random.PRNGKey(3), 1, 128, 8, 2, 128)
        k = k.at[:, :, 1].mul(10.0)
        v = v.at[:, :, 1].add(5.0)
        out = flash_attention(q, k, v, interpret=True)
        err = jnp.max(jnp.abs(out - _ref(q, k, v, 4)))
        assert float(err) < 2e-4

    def test_causality_strict(self):
        """Changing future k/v rows must not change earlier outputs."""
        q, k, v = _rand_qkv(jax.random.PRNGKey(4), 1, 256, 2, 2, 128)
        out1 = flash_attention(q, k, v, interpret=True)
        k2 = k.at[:, 200:].set(99.0)
        v2 = v.at[:, 200:].set(-99.0)
        out2 = flash_attention(q, k2, v2, interpret=True)
        assert jnp.allclose(out1[:, :200], out2[:, :200], atol=1e-5)
        assert not jnp.allclose(out1[:, 200:], out2[:, 200:], atol=1e-2)

    def test_eligibility_gate(self):
        assert flash_eligible((2, 256, 4, 128), 2)
        assert not flash_eligible((2, 200, 4, 128), 2)  # ragged seq
        assert not flash_eligible((2, 256, 4, 64), 2)  # sub-lane head dim
        assert not flash_eligible((2, 256, 3, 128), 2)  # H % KV != 0


class TestModelIntegration:
    def test_forward_matches_xla_path(self, monkeypatch):
        """Full model forward with the kernel forced on (interpret)
        must match the default XLA attention path."""
        cfg = llama.LlamaConfig(
            vocab_size=256,
            dim=256,
            n_layers=2,
            n_heads=2,
            n_kv_heads=1,
            ffn_dim=512,
            max_seq_len=128,
            dtype=jnp.float32,
        )
        assert cfg.head_dim == 128
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 256)

        monkeypatch.setenv("TPUSLO_FLASH_ATTENTION", "0")
        ref_logits = llama.forward(params, tokens, cfg)
        monkeypatch.setenv("TPUSLO_FLASH_ATTENTION", "1")
        flash_logits = llama.forward(params, tokens, cfg)
        err = jnp.max(jnp.abs(flash_logits - ref_logits))
        assert float(err) < 5e-4

    def test_ineligible_shapes_fall_back(self, monkeypatch):
        """Tiny configs (head_dim 16, seq 31) must keep working with
        the override on — the eligibility gate routes them to XLA."""
        monkeypatch.setenv("TPUSLO_FLASH_ATTENTION", "1")
        cfg = llama.llama_tiny(max_seq_len=64)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((1, 31), jnp.int32)
        logits = llama.forward(params, tokens, cfg)
        assert logits.shape == (1, 31, cfg.vocab_size)

    def test_kernel_gradients_match_xla_path(self, monkeypatch):
        """The custom VJP's recompute backward must produce the same
        gradients as differentiating the plain XLA attention."""
        from tpuslo.ops.flash_attention import flash_attention
        from tpuslo.ops.ring_attention import reference_causal_attention

        q, k, v = _rand_qkv(jax.random.PRNGKey(7), 1, 128, 4, 2, 128)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, interpret=True)
            return jnp.sum(out * jnp.cos(out))

        def loss_ref(q, k, v):
            out = reference_causal_attention(
                q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2)
            )
            return jnp.sum(out * jnp.cos(out))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            assert float(jnp.max(jnp.abs(a - b))) < 5e-4

    def test_gradients_flow_through_kernel(self, monkeypatch):
        """Training uses the same path; loss must differentiate.

        jax.checkpoint remat over a pallas_call exercises the kernel's
        transpose/residual handling in interpret mode.
        """
        monkeypatch.setenv("TPUSLO_FLASH_ATTENTION", "1")
        cfg = llama.LlamaConfig(
            vocab_size=64,
            dim=128,
            n_layers=1,
            n_heads=1,
            n_kv_heads=1,
            ffn_dim=256,
            max_seq_len=128,
            dtype=jnp.float32,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 128), 0, 64)
        targets = jnp.roll(tokens, -1, axis=1)
        loss, grads = jax.value_and_grad(llama.loss_fn)(
            params, tokens, targets, cfg
        )
        assert jnp.isfinite(loss)
        flat = jax.tree_util.tree_leaves(grads)
        assert all(jnp.all(jnp.isfinite(g)) for g in flat)
        assert any(float(jnp.max(jnp.abs(g))) > 0 for g in flat)

# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow
