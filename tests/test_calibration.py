"""Noise-calibrated attribution: soft evidence, fitted likelihoods,
held-out validation (VERDICT r02 next-round #4).

The acceptance bar comes from the reference methodology's single-fault
threshold (macro-F1 >= 0.85,
``/root/reference/docs/benchmarks/llm-slo-attribution-accuracy.md``
Success Thresholds), applied at sigma=0.5 noise on held-out seeds.
"""

from __future__ import annotations

import pytest

from tpuslo import attribution
from tpuslo.attribution import bayesian as B
from tpuslo.attribution import calibrate as C


class TestSoftEvidence:
    def test_weight_half_at_warning_threshold(self):
        assert B.soft_evidence_weight("dns_latency_ms", 40.0) == pytest.approx(0.5)

    def test_weight_grows_with_value(self):
        w_low = B.soft_evidence_weight("dns_latency_ms", 45.0)
        w_err = B.soft_evidence_weight("dns_latency_ms", 120.0)
        w_deep = B.soft_evidence_weight("dns_latency_ms", 500.0)
        assert 0.5 < w_low < w_err < w_deep < 1.0

    def test_weight_zero_for_nonpositive_and_unknown(self):
        assert B.soft_evidence_weight("dns_latency_ms", 0.0) == 0.0
        assert B.soft_evidence_weight("dns_latency_ms", -3.0) == 0.0
        assert B.soft_evidence_weight("not_a_signal", 99.0) == 0.0

    def test_extreme_values_saturate_without_overflow(self):
        import math

        hi = B.soft_evidence_weight("dns_latency_ms", 1e12)
        lo = B.soft_evidence_weight("dns_latency_ms", 1e-12)
        assert math.isfinite(hi) and math.isfinite(lo)
        assert 0.0 <= lo < 0.01 and 0.99 < hi <= 1.0

    def test_hard_mode_unchanged_by_soft_params(self):
        """Default construction is hard mode — reference parity paths
        (elevation thresholds, binary evidence) must be untouched."""
        attributor = B.BayesianAttributor()
        assert attributor.evidence == "hard"

    def test_invalid_evidence_mode_rejected(self):
        with pytest.raises(ValueError):
            B.BayesianAttributor(evidence="fuzzy")

    def test_soft_zero_value_is_unobserved_not_healthy(self):
        """A dropped continuous probe (exact 0.0) must not count as
        negative evidence; a zero counter still counts as healthy."""
        attributor = B.BayesianAttributor(evidence="soft")
        observed, _ = attributor._observed_and_weights(
            {"dns_latency_ms": 0.0, "tcp_retransmits_total": 0.0}
        )
        assert "dns_latency_ms" not in observed
        assert "tcp_retransmits_total" in observed

    def test_soft_batch_matches_scalar(self):
        from datetime import datetime, timezone

        from tpuslo.faultreplay import generate_fault_samples

        start = datetime(2026, 1, 1, tzinfo=timezone.utc)
        samples = []
        for scenario in C.TPU_SCENARIOS:
            samples.extend(generate_fault_samples(scenario, 3, start))
        samples = C.corrupt(samples, 0.5, 11)
        attributor = B.BayesianAttributor(evidence="soft")
        batch = attributor.attribute_batch(samples)
        scalar = [attributor.attribute_sample(s) for s in samples]
        for b, s in zip(batch, scalar):
            assert b.predicted_fault_domain == s.predicted_fault_domain
            assert b.confidence == pytest.approx(s.confidence, abs=1e-9)
            assert [h.domain for h in b.fault_hypotheses] == [
                h.domain for h in s.fault_hypotheses
            ]


class TestCalibration:
    def test_fit_is_deterministic(self):
        t1 = C.fit_likelihoods()
        t2 = C.fit_likelihoods()
        assert t1 == t2

    def test_fit_recalibrates_noisy_healthy_signal(self):
        """hbm_utilization_pct (healthy 62, warning 85) crosses its
        threshold often under noise; the fitted healthy columns must be
        far above the hand-set 0.05 — that miscalibration was the r02
        robustness collapse."""
        table = C.fit_likelihoods()
        hand = B.default_likelihoods()
        assert (
            table["hbm_utilization_pct"][B.DOMAIN_NETWORK_DNS]
            > hand["hbm_utilization_pct"][B.DOMAIN_NETWORK_DNS] + 0.05
        )

    def test_fitted_sharpness_matches_shipped_default(self):
        assert C.fit_sharpness() == B.DEFAULT_EVIDENCE_SHARPNESS

    def test_heldout_noise_beats_bar_at_sigma_05(self):
        """The acceptance bar: >= 0.85 macro-F1 at sigma=0.5 on held-out
        noise — for BOTH the training noise family (held-out seed) and
        the held-out gamma family."""
        report = C.heldout_report()
        assert report.clean >= 0.99
        assert report.lognormal["0.5"] >= 0.85
        assert report.gamma["0.5"] >= 0.85

    def test_heldout_beats_hard_mode_everywhere(self):
        """The calibrated attributor must dominate the hard-threshold
        attributor across the sweep (the point of calibrating)."""
        hard = B.BayesianAttributor()
        soft = C.calibrated_attributor()
        hard_rep = C.heldout_report(hard)
        soft_rep = C.heldout_report(soft)
        for sigma in ("0.25", "0.5", "1.0"):
            assert soft_rep.lognormal[sigma] >= hard_rep.lognormal[sigma]
            assert soft_rep.variant_profiles[sigma] >= (
                hard_rep.variant_profiles[sigma] - 1e-9
            )

    def test_variant_profiles_clean_perfect(self):
        """Profiles the generator never emits (milder magnitudes) must
        attribute perfectly when clean — proof the fit generalizes
        beyond the training magnitudes."""
        attributor = C.calibrated_attributor()
        samples = C.variant_samples(10)
        predictions = attributor.attribute_batch(samples)
        assert attribution.macro_f1(samples, predictions).macro_f1 == 1.0

    def test_cli_calibrated_evidence(self, tmp_path):
        from tpuslo.cli.attributor import main

        from tpuslo.faultreplay import generate_fault_samples
        from datetime import datetime, timezone
        import json

        start = datetime(2026, 1, 1, tzinfo=timezone.utc)
        samples = C.corrupt(
            generate_fault_samples("tpu_mixed", 8, start), 0.5, 5
        )
        inp = tmp_path / "samples.jsonl"
        inp.write_text(
            "\n".join(json.dumps(s.to_dict()) for s in samples) + "\n"
        )
        out = tmp_path / "attr.jsonl"
        summary = tmp_path / "summary.json"
        rc = main(
            [
                "--input", str(inp), "--output", str(out),
                "--summary", str(summary), "--evidence", "calibrated",
            ]
        )
        assert rc == 0
        assert json.loads(summary.read_text())["macro_f1"] >= 0.85


class TestRound4Axes:
    """VERDICT r03 #4/#5: variant generalization + the abstain axis."""

    def test_variant_profiles_beat_bar_at_sigma_05(self):
        report = C.heldout_report()
        assert report.variant_profiles["0.5"] >= 0.85
        # sigma=1.0 published (no bar, but it must exist and be sane)
        assert 0.0 < report.variant_profiles["1.0"] <= 1.0

    def test_variant_set_covers_all_trainable_domains(self):
        """The variant axis must include every trainable domain so a
        stray prediction lands in a class with support instead of
        zeroing 1/N of the macro by luck."""
        from tpuslo.attribution.mapper import map_fault_label

        variant_domains = {map_fault_label(k) for k in C.VARIANT_PROFILES}
        train_domains = {map_fault_label(s) for s in C.TRAIN_SCENARIOS}
        assert variant_domains == train_domains

    def test_false_alarm_below_bar_at_operational_noise(self):
        report = C.heldout_report()
        assert report.false_alarm["0.25"] <= 0.15
        assert report.false_alarm["0.5"] <= 0.15
        assert report.abstain["0.5"] <= 0.15

    def test_clean_baseline_abstains(self):
        """A fully healthy no-burn vector must predict unknown, not a
        fault domain (it used to predict xla_compile at 0.41 because a
        zero compile window dodged the healthy factor)."""
        attributor = C.calibrated_attributor()
        sample = C.baseline_samples(1)[0]
        prediction = attributor.attribute_sample(sample)
        assert prediction.predicted_fault_domain == "unknown"

    def test_zero_compile_window_is_evidence_against_xla(self):
        """xla_compile_ms == 0 must not be silently unobserved: the
        xla domain has to pay the (tempered) healthy factor."""
        attributor = C.calibrated_attributor()
        base = C.baseline_samples(1)[0]
        signals = dict(base.signals)
        post = {p.domain: p.posterior for p in attributor.attribute(signals)}
        signals_no_compile = dict(signals)
        signals_no_compile.pop("xla_compile_ms", None)
        post_missing = {
            p.domain: p.posterior
            for p in attributor.attribute(signals_no_compile)
        }
        # With the signal absent entirely (unobserved) xla gets off
        # easier than with an explicit zero reading.
        assert post["xla_compile"] < post_missing["xla_compile"]

    def test_abstention_is_not_a_stray_macro_class(self):
        """An unknown prediction on a faulted sample costs recall, not
        a zeroed stray class."""
        from tpuslo import attribution as A

        samples = C._base_samples(("ici_drop",), 4)
        predictions = C.calibrated_attributor().attribute_batch(samples)
        # Force one abstention artificially.
        predictions[0].predicted_fault_domain = "unknown"
        report = A.macro_f1(samples, predictions)
        domains = {score.domain for score in report.per_domain}
        assert "unknown" not in domains
        ici = next(
            score for score in report.per_domain if score.domain == "tpu_ici"
        )
        assert report.macro_f1 == pytest.approx(ici.f1)

    def test_incident_burn_keeps_single_signal_sensitivity(self):
        """Burn >= 2 (an incident) must still attribute on one strong
        pathognomonic signal; burn 0 with the same vector abstains."""
        attributor = C.calibrated_attributor()
        sample = C.baseline_samples(1)[0]
        sample.signals["xla_compile_ms"] = 3200.0
        sample.burn_rate = 2.5
        assert (
            attributor.attribute_sample(sample).predicted_fault_domain
            == "xla_compile"
        )
        sample.burn_rate = 0.0
        assert (
            attributor.attribute_sample(sample).predicted_fault_domain
            == "unknown"
        )


def test_full_domain_axis_published_and_strong():
    """The additive full-domain noise axis: with every trainable domain
    supported, strays cost precision instead of zeroing absent classes
    — the number that tracks top-1 accuracy instead of class-support
    luck.  TPU-only axes keep their r01-r03 protocol."""
    report = C.heldout_report()
    assert report.full_domain["0.5"] >= 0.85
    assert report.full_domain["1.0"] >= 0.75
