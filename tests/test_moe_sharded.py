"""Tensor-parallel MoE serving (VERDICT r02 next-round #10): expert
weights sharded over tp, decode matching the single-device engine, and
the 8x7B class compile-validated at tp=8 without materializing weights
(the dense 70B discipline of tests/test_serve_sharded.py).
"""

from functools import partial

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuslo.models.mixtral import (
    MoEServeEngine,
    init_params,
    mixtral_8x7b,
    mixtral_tiny,
    tp_serve_param_shardings,
)


def _tp_mesh(tp: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:tp]), ("tp",))


def _cfg():
    # 4 q heads / 2 kv heads / ffn 128: tp=2 divides all three.
    return mixtral_tiny(max_seq_len=128)


def test_tp_moe_generation_matches_single_device():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plain = MoEServeEngine(cfg=cfg, params=params)
    sharded = MoEServeEngine(cfg=cfg, params=params, mesh=_tp_mesh(2))
    out_plain = [
        e.token_id for e in plain.generate("tp moe", 12, stop_at_eos=False)
    ]
    out_shard = [
        e.token_id for e in sharded.generate("tp moe", 12, stop_at_eos=False)
    ]
    assert len(out_shard) == 12
    # Greedy argmax over near-identical logits (psum reassociation):
    # allow a rare late flip but the prefix must agree.
    assert out_plain[:8] == out_shard[:8]


def test_tp_moe_prefill_logits_match():
    from tpuslo.models.mixtral import prefill

    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plain = MoEServeEngine(cfg=cfg, params=params)
    sharded = MoEServeEngine(cfg=cfg, params=params, mesh=_tp_mesh(2))
    tokens = jnp.zeros((1, 32), jnp.int32).at[0, :4].set(
        jnp.asarray([256, 104, 105, 33])
    )
    tl = jnp.asarray(4, jnp.int32)
    lp, _ = plain._prefill(
        plain.params, tokens, plain._init_cache(1), true_length=tl
    )
    ls, _ = sharded._prefill(
        sharded.params, tokens, sharded._init_cache(1), true_length=tl
    )
    assert float(jnp.max(jnp.abs(lp - ls))) < 5e-2


def test_tp_moe_mesh_init_shards_expert_leaves():
    """params=None + mesh: experts initialize directly into shards."""
    engine = MoEServeEngine(cfg=_cfg(), mesh=_tp_mesh(2))
    w1 = engine.params["layers"]["w1"]
    assert w1.sharding.spec == (None, None, None, "tp")
    events = list(engine.generate("sharded moe", 4, stop_at_eos=False))
    assert len(events) == 4


def test_tp_moe_indivisible_rejected():
    cfg = mixtral_tiny()  # n_kv_heads=2
    with pytest.raises(ValueError, match="must divide"):
        MoEServeEngine(cfg=cfg, mesh=_tp_mesh(4))
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    with pytest.raises(ValueError, match="tp"):
        MoEServeEngine(cfg=cfg, mesh=mesh)


def _mixtral8x7b_abstract_setup():
    from dataclasses import replace

    from tpuslo.models.llama import init_kv_cache

    from tpuslo.models.serve import kv_cache_shardings

    mesh = _tp_mesh(8)
    cfg = replace(mixtral_8x7b(), max_seq_len=256)
    abstract_params = jax.eval_shape(
        partial(init_params, cfg=cfg), jax.random.PRNGKey(0)
    )
    shardings = tp_serve_param_shardings(mesh)
    cache_abstract = jax.eval_shape(
        lambda: init_kv_cache(cfg.attn_cfg(), 1)
    )
    return mesh, cfg, abstract_params, shardings, kv_cache_shardings(mesh), cache_abstract


def test_mixtral_8x7b_tp8_prefill_compiles():
    """The 8x7B-over-v5e-8 serving claim, compile-validated without
    weights: GSPMD partitioning runs at .compile(), which is the step
    that rejects inconsistent expert shardings."""
    from tpuslo.models.mixtral import prefill

    _mesh, cfg, abstract_params, shardings, kv_shard, cache_abstract = (
        _mixtral8x7b_abstract_setup()
    )
    assert cfg.n_heads % 8 == 0 and cfg.n_kv_heads % 8 == 0
    assert cfg.ffn_dim % 8 == 0
    n_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(abstract_params)
    )
    assert n_bytes > 80e9  # ~47B params bf16: needs the full v5e-8

    tokens = jax.ShapeDtypeStruct((1, 64), jnp.int32)

    def prefill_pos(params, toks, cache, true_length):
        return prefill(params, toks, cache, cfg, true_length=true_length)

    compiled = (
        jax.jit(
            prefill_pos,
            in_shardings=(shardings, None, kv_shard, None),
        )
        .lower(
            abstract_params,
            tokens,
            cache_abstract,
            jax.ShapeDtypeStruct((), jnp.int32),
        )
        .compile()
    )
    assert compiled is not None


def test_mixtral_8x7b_tp8_decode_chunk_compiles():
    from tpuslo.models.mixtral import decode_chunk

    _mesh, cfg, abstract_params, shardings, kv_shard, cache_abstract = (
        _mixtral8x7b_abstract_setup()
    )
    token = jax.ShapeDtypeStruct((1,), jnp.int32)

    def decode_pos(params, tok, cache):
        return decode_chunk(params, tok, cache, cfg, num_tokens=4)

    compiled = (
        jax.jit(
            decode_pos,
            in_shardings=(shardings, None, kv_shard),
        )
        .lower(abstract_params, token, cache_abstract)
        .compile()
    )
    assert compiled is not None


# Compile-heavy module: excluded from the sub-2-minute fast gate
# (`make test-fast` / pytest -m "not slow"); the full suite runs it.
pytestmark = pytest.mark.slow


def _ep_mesh(ep: int = 2) -> Mesh:
    return Mesh(np.array(jax.devices()[:ep]), ("ep",))


def test_ep_moe_serving_stream_parity():
    """Expert-parallel serving: experts shard whole over ep, tokens
    never move (one psum per MoE block at the combine einsum); the
    stream must match the single-device engine in logit space."""
    from tpuslo.models.serve import stream_parity

    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plain = MoEServeEngine(cfg=cfg, params=params)
    sharded = MoEServeEngine(cfg=cfg, params=params, mesh=_ep_mesh(2))
    parity = stream_parity(sharded, plain, "ep moe serving")
    assert parity["ok"], parity


def test_ep_moe_mesh_init_shards_expert_leaves_only():
    """params=None + ep mesh: experts initialize sharded on axis 1,
    attention stays replicated, and generation runs."""
    engine = MoEServeEngine(cfg=_cfg(), mesh=_ep_mesh(2))
    w1 = engine.params["layers"]["w1"]
    assert w1.sharding.spec == (None, "ep", None, None)
    wq = engine.params["layers"]["wq"]
    assert all(s is None for s in wq.sharding.spec)
    events = list(engine.generate("ep moe", 4, stop_at_eos=False))
    assert len(events) == 4


def test_ep_moe_indivisible_expert_count_rejected():
    import pytest

    cfg = mixtral_tiny()  # n_experts=4
    with pytest.raises(ValueError, match="divide n_experts"):
        MoEServeEngine(cfg=cfg, mesh=Mesh(
            np.array(jax.devices()[:3]), ("ep",)
        ))


def test_moe_mesh_without_tp_or_ep_rejected():
    import pytest

    with pytest.raises(ValueError, match="'tp' or 'ep'"):
        MoEServeEngine(cfg=_cfg(), mesh=Mesh(
            np.array(jax.devices()[:2]), ("dp",)
        ))


def test_moe_mesh_with_both_tp_and_ep_rejected():
    import pytest

    with pytest.raises(ValueError, match="not both"):
        MoEServeEngine(cfg=_cfg(), mesh=Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2), ("tp", "ep")
        ))


def test_ep_moe_continuous_batching_matches_plain():
    """The whole batched scheduler rides ep unchanged: replicated
    caches, experts sharded, per-request streams identical."""
    from tpuslo.models.mixtral import MoEContinuousBatchingEngine

    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plain = MoEServeEngine(
        cfg=cfg, params=params, prefill_buckets=(16, 32),
        decode_chunk_size=4,
    )
    batched = MoEContinuousBatchingEngine(
        cfg=cfg, params=params, max_slots=2, prefill_buckets=(16, 32),
        decode_chunk_size=4, mesh=_ep_mesh(2),
    )
    prompts = ["ep batch one", "ep batch two"]
    rids = [batched.submit(p, max_new_tokens=5, stop_at_eos=False)
            for p in prompts]
    results = batched.run()
    for rid, prompt in zip(rids, prompts):
        expect = [
            e.token_id
            for e in plain.generate(prompt, max_new_tokens=5,
                                    stop_at_eos=False)
        ]
        assert results[rid] == expect, prompt


def test_ep_moe_paged_engine_matches_plain():
    from tpuslo.models.mixtral import MoEPagedBatchingEngine

    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plain = MoEServeEngine(
        cfg=cfg, params=params, prefill_buckets=(16, 32),
        decode_chunk_size=4,
    )
    paged = MoEPagedBatchingEngine(
        cfg=cfg, params=params, max_slots=2, block_size=16,
        prefill_buckets=(16, 32), decode_chunk_size=4, mesh=_ep_mesh(2),
    )
    rid = paged.submit("ep paged moe", max_new_tokens=5, stop_at_eos=False)
    results = paged.run()
    expect = [
        e.token_id
        for e in plain.generate("ep paged moe", max_new_tokens=5,
                                stop_at_eos=False)
    ]
    assert results[rid] == expect


def test_moe_sp_generate_matches_dense_chain():
    """Long-context MoE: ring prefill + distributed decode with the MoE
    block through the mlp_fn hook matches plain prefill + decode_step
    greedy on the same tokens."""
    from tpuslo.models import mixtral
    from tpuslo.models.llama import init_kv_cache

    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (1, 32), 0, cfg.vocab_size
    )

    cache = init_kv_cache(cfg.attn_cfg(), 1)
    logits, cache = mixtral.prefill(params, tokens, cache, cfg)
    ref = [jnp.argmax(logits, -1).astype(jnp.int32)]
    for _ in range(4):
        logits, cache = mixtral.decode_step(params, ref[-1], cache, cfg)
        ref.append(jnp.argmax(logits, -1).astype(jnp.int32))
    ref_seq = jnp.stack(ref, axis=1)

    out = mixtral.sp_generate(
        params, tokens, cfg, Mesh(np.array(jax.devices()[:4]), ("sp",)),
        max_new_tokens=5,
    )
    assert jnp.array_equal(out, ref_seq), (out, ref_seq)


def test_moe_sp_generate_rejects_droppy_config():
    import pytest

    from tpuslo.models import mixtral

    cfg = mixtral_tiny()
    droppy = cfg.__class__(**{**cfg.__dict__, "capacity_factor": 1.0})
    with pytest.raises(ValueError, match="capacity_factor"):
        mixtral.sp_generate(
            init_params(jax.random.PRNGKey(0), droppy),
            jnp.zeros((1, 32), jnp.int32), droppy,
            Mesh(np.array(jax.devices()[:4]), ("sp",)), max_new_tokens=2,
        )
